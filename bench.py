"""Benchmark: simulated gossip throughput on the current backend.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Headline metric: node-ticks/second of the **bounded partial-view
overlay** at N=65536 with 20% churn — the BASELINE.json intermediate
config the reference cannot represent at all (its merge filter caps at
N<=10, MP1Node.cpp:245, and EmulNet at N<=1000, EmulNet.h:10).  The
run is validated before it is reported: everyone joins, churned peers
rejoin, failed peers are purged from every view, and no live member
stays uncovered past the re-cover bound.

Per-config entries in ``secondary`` report **both** throughput axes:
``node_ticks_per_s`` (work rate) and ``ticks_per_s`` (simulation
rate — BASELINE's north star is >=10,000 ticks/s at 1M peers on a
v4-8), plus a roofline estimate: closed-form HBM bytes per tick for
the path that executed, the achieved fraction of v5e peak HBM
bandwidth, and which resource bounds the config (see _roofline).

Baseline: the reference's measured best case is ~1.4M node-ticks/s
(N=10, one CPU core, BASELINE.md); vs_baseline divides by that.
"""

import json
import multiprocessing
import os
import sys

REFERENCE_NODE_TICKS_PER_S = 1.4e6  # BASELINE.md best case, N=10, 1 CPU core

#: v5e public peak specs (single chip): 819 GB/s HBM BW, 197 bf16
#: TFLOP/s MXU.  Used only for utilization reporting.
V5E_HBM_BYTES_PER_S = 819e9
V5E_MXU_FLOPS = 197e12


def _probe_backend(q):
    try:
        import jax
        q.put(jax.default_backend())
    except Exception:
        q.put("error")


def _backend_or_cpu(timeout_s: float = 180.0) -> str:
    """Bounded accelerator probe.

    This image routes the TPU through a single-grant tunnel that can
    block ``jax.devices()`` indefinitely if a previous client died
    mid-claim; a hung bench is worse than a CPU number, so probe the
    backend in a subprocess with a deadline and fall back to CPU.
    """
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_probe_backend, args=(q,))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.kill()
        p.join()
        return "cpu"
    try:
        backend = q.get_nowait()
    except Exception:
        backend = "cpu"
    return backend if backend not in ("error",) else "cpu"


def _roofline(cfg, ticks_per_s: float, backend: str) -> dict:
    """Closed-form HBM-bytes/tick for the path this config executes,
    and achieved utilization vs v5e peak.

    Three regimes (all byte counts count the (8,128)-tile padded
    layouts the TPU actually stores):

    * ``mega`` (N <= MEGA_N_LIMIT single-device): state lives in VMEM
      across a MEGA_TICKS launch; HBM sees only the (N, 128) plane in
      + out once per launch.  The binding resource is VPU/VMEM
      bandwidth and in-kernel sequencing, NOT HBM — hbm_util is
      reported for completeness and is expected to be tiny.
    * ``fused`` (larger N, fused per-tick kernel): per tick the
      kernel reads the idsaux and packed-payload planes (1+F) times
      each (identity + one XOR-mapped binding per round) and writes
      ids, hb, and the ts+counter planes — each plane (N, 128) i32
      after lane padding.
    * ``dense`` full-view model: per tick the merge reads the
      (N, N) hb/ts planes and recv mask and writes hb/ts/known; the
      MXU level-decomposed merge does ~L boolean (N, N) @ (N, N)
      matmuls (measured L ~= 2-4 data-dependent levels; 3 assumed),
      so mxu_util is also estimated.
    """
    from gossip_protocol_tpu.models.overlay import resolved_dims
    from gossip_protocol_tpu.models.overlay_grid import grid_supported
    from gossip_protocol_tpu.models.overlay_mega import (MEGA_TICKS,
                                                         mega_supported)
    n = cfg.n
    out = {}
    if cfg.model == "overlay":
        k, f = resolved_dims(cfg)
        plane = n * 128 * 4                       # (N, <=128 lanes) i32
        if mega_supported(cfg) and backend == "tpu":
            bytes_per_tick = 2 * plane / MEGA_TICKS
            out["path"] = "mega"
            out["bound"] = "vpu/vmem + in-kernel sequencing"
        elif grid_supported(cfg) and backend == "tpu":
            # grid multi-tick kernel: per tick each row block reads
            # its own packed plane block once plus F XOR-partner
            # blocks and writes once — full PLANE_W=128-lane padded
            # blocks (Mosaic DMA slices are tile-width)
            bytes_per_tick = plane * (2 + f)
            out["path"] = "grid"
            out["bound"] = "hbm + in-kernel vpu"
            # the run executes through the schedule-segment planner
            # (OverlaySimulation pins start_tick=0): one specialized
            # kernel variant per segment, dead phases statically
            # elided (models/segments.py)
            from gossip_protocol_tpu.models.segments import (
                describe_plan, plan_segments)
            from gossip_protocol_tpu.ops.pallas.overlay_grid import \
                GRID_TICKS
            out["segments"] = describe_plan(
                plan_segments(cfg, cfg.total_ticks, 0, GRID_TICKS))
        else:
            bytes_per_tick = plane * ((1 + f) * 2 + 3)
            out["path"] = "fused"
            out["bound"] = "hbm + per-launch dispatch"
    else:
        from gossip_protocol_tpu.core.dense_corner import active_bound
        from gossip_protocol_tpu.core.dense_mega import dense_mega_supported
        from gossip_protocol_tpu.ops.pallas.dense_mega import \
            dense_mega_ticks_for
        # bench mode runs on the static active corner when the
        # schedule never starts peers >= A (core/dense_corner.py) —
        # the roofline must describe the width that actually executes
        a = active_bound(cfg)
        n_eff = a if 0 < a < n else n
        cell = n_eff * n_eff
        flops_per_tick = 3 * 3 * 2 * n_eff ** 3   # 3 reductions x ~3 levels
        corner_tag = "corner-" if n_eff < n else ""
        if dense_mega_supported(cfg.replace(max_nnb=n_eff)) \
                and backend == "tpu":
            # dense megakernel: the four (N, N) planes live in VMEM
            # across an S-tick launch, HBM sees planes in + out once
            # per launch plus the precomputed (S, N, N) drop stack
            # read once
            s = dense_mega_ticks_for(n_eff)
            bytes_per_tick = cell * 4 * (4 * 2 / s + 1)
            out["path"] = corner_tag + "dense-mega"
            out["bound"] = "in-kernel mxu merge + vpu sequencing"
        else:
            # hb/ts i32 + known/gossip i8, read+write once (XLA fuses
            # the elementwise chain); recv mask read
            bytes_per_tick = cell * (4 + 4 + 1 + 1) * 2 + cell
            out["path"] = corner_tag + "dense"
            out["bound"] = "mxu merge + per-tick dispatch"
        out["mxu_util"] = round(flops_per_tick * ticks_per_s
                                / V5E_MXU_FLOPS, 4)
    out["hbm_bytes_per_tick"] = int(bytes_per_tick)
    out["hbm_util"] = round(bytes_per_tick * ticks_per_s
                            / V5E_HBM_BYTES_PER_S, 4)
    return out


#: boundary-walk coverage validation runs below this N (the int8
#: one-hot histogram is O(N*K*(N/256+256)) — fine to 2^17; the 1M
#: config keeps final-snapshot + continuation validation)
WALK_COVERAGE_N_LIMIT = 1 << 17


def _walk_recover(cfg, sched, length):
    """Assert the re-cover bound directly, at every occurrence.

    Replays the (bit-identical, closed-form-scheduled) run in
    GRID_TICKS segments, sampling live coverage at every launch
    boundary with the scatter-free histogram
    (models/overlay.covered_histogram).  Whenever a boundary snapshot
    leaves live members uncovered, the walk drops to tick-by-tick
    stepping and requires every one of them covered again within
    ``SLOT_EPOCH + 1`` ticks (tests/test_overlay.py::test_recover_bound
    — the boosted self-reseed plus the slot re-roll retire any
    contention hole).  This replaces the post-hoc endpoint continuation
    at the scales the overlay exists for: coverage is now *observed*
    during the run, not assumed from a final snapshot.

    Runs outside the timed region; the timed trajectory is identical
    bit-for-bit (same seed, same closed-form schedule)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_protocol_tpu.config import INTRODUCER
    from gossip_protocol_tpu.models.overlay import (SLOT_EPOCH,
                                                    covered_histogram,
                                                    init_overlay_state,
                                                    make_overlay_run)
    from gossip_protocol_tpu.ops.pallas.overlay_grid import GRID_TICKS

    n = cfg.n
    bound = SLOT_EPOCH + 1
    rows = jnp.arange(n, dtype=jnp.int32)

    @jax.jit
    def uncovered_mask(state):
        cov = covered_histogram(state.ids, n)
        t = state.tick
        fail = sched.fail_of(rows)
        rejoin = sched.rejoin_of(rows)
        failed = (t > fail) & (t <= rejoin)
        live = state.in_group & ~failed & (rows != INTRODUCER)
        return live & ~cov

    state = init_overlay_state(cfg)
    # tick-by-tick stepping uses the XLA path: bit-identical to the
    # kernel paths (differential suites) and avoids compiling a
    # 1-tick grid-kernel variant mid-validation
    step1 = make_overlay_run(cfg, 1, use_pallas=False)
    t, pending, holes = 0, None, 0
    while t < length or pending is not None:
        if pending is None:
            seg = min(GRID_TICKS, length - t)
            state, _ = make_overlay_run(cfg, seg)(state, sched)
            t += seg
            unc = np.asarray(uncovered_mask(state))
            if unc.any():
                pending = (unc, t + bound)
                holes += 1
        else:
            if t + 1 > 4094:
                raise RuntimeError(
                    "overlay bench: coverage walk cannot step past the "
                    "4094-tick packed-payload cap")
            state, _ = step1(state, sched)
            t += 1
            # narrow the pending set monotonically: a member that
            # re-covers has satisfied this hole's bound — if it goes
            # uncovered again later that is a NEW hole with a fresh
            # deadline (judged at the next boundary), not a violation
            # of this one
            mask = pending[0] & np.asarray(uncovered_mask(state))
            if not mask.any():
                pending = None
            elif t >= pending[1]:
                raise RuntimeError(
                    f"overlay bench: live members "
                    f"{np.flatnonzero(mask)[:5].tolist()} stayed "
                    f"uncovered past the {bound}-tick re-cover bound "
                    f"(hole observed at the tick-{pending[1] - bound} "
                    "launch boundary)")
            else:
                pending = (mask, pending[1])
    return holes


def _check_recover(cfg, result):
    """No live member may stay uncovered past the re-cover bound.

    A final-snapshot coverage hole can be a benign transient: a
    degree-1 leaf whose self-entry lost one slot contention.  The
    protocol property (tests/test_overlay.py::test_recover_bound)
    is that the direct self-reseed plus the SLOT_EPOCH re-roll
    re-covers any live member within ``SLOT_EPOCH + 1`` ticks — the
    re-roll and the per-tick partner re-draw retire the colliding
    pair, and the next send's freshness-majorized self-entry (maximal
    ts at merge time) wins a slot.  Continue the run — with the
    ORIGINAL schedule pinned, so churn-mode continuations replay the
    exact same fail/rejoin script — for that bound and require every
    snapshot-uncovered member to be covered again.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_protocol_tpu.models.overlay import (SLOT_EPOCH,
                                                    make_overlay_run)
    uncovered, victims_left = result.final_coverage()
    if victims_left:
        raise RuntimeError("overlay bench: victim entries left")
    if not uncovered:
        return 0
    # the guarantee is coverage at ANY tick within the bound (matching
    # test_recover_bound), so accumulate per-tick coverage across the
    # continuation rather than checking only the endpoint snapshot —
    # an unrelated fresh transient at the final tick must not fail a
    # run that satisfied the property
    before = result.uncovered_members()
    bound = SLOT_EPOCH + 1
    n = cfg.n
    # the packed (ts+1) << 12 winner payload caps the absolute clock at
    # 4094 ticks (models/overlay.py); the continuation below runs past
    # cfg.total_ticks, so the bound must still fit under the cap
    t_now = int(np.asarray(result.final_state.tick))
    if t_now + bound > 4094:
        raise RuntimeError(
            f"overlay bench: cannot run the {bound}-tick re-cover "
            f"continuation from tick {t_now} without exceeding the "
            "4094-tick packed-payload cap; shorten total_ticks")
    run1 = make_overlay_run(cfg, 1)

    @jax.jit
    def covered_of(state):
        flat = jnp.clip(state.ids, 0).reshape(-1)
        return jnp.zeros(n, bool).at[flat].max(
            (state.ids >= 0).reshape(-1))

    state = result.final_state
    covered_any = jnp.zeros(n, bool)
    for _ in range(bound):
        state, _ = run1(state, result.sched)
        covered_any = covered_any | covered_of(state)
    still = before[~np.asarray(covered_any)[before]]
    if still.size:
        raise RuntimeError(
            f"overlay bench: coverage hole persisted past the "
            f"{bound}-tick re-cover bound ({still[:5].tolist()}...)")
    return len(before)


def bench_overlay(n: int, ticks: int, mode: str = "churn",
                  topology: str = "uniform"):
    """BASELINE configs: 20% churn (the 65k shape), 10% message drop
    (the 4096 shape), or a scripted failure under the power-law
    topology (the 1M scale-free shape).  Returns the best validated
    OverlayResult."""
    import numpy as np

    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.models.overlay import OverlaySimulation

    if mode == "drop":
        # like the reference's msgdrop scenario, the join ramp finishes
        # before the drop window opens (tick 50), so a dropped JOINREQ
        # can never orphan a peer
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=True, msg_drop_prob=0.1, seed=0,
                        total_ticks=ticks, fail_tick=ticks // 2,
                        step_rate=40.0 / n, topology=topology)
    elif mode == "fail":
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=False, seed=0, total_ticks=ticks,
                        fail_tick=ticks // 2, step_rate=40.0 / n,
                        topology=topology)
    elif mode != "churn":
        raise ValueError(f"unknown bench_overlay mode {mode!r}")
    else:
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                        drop_msg=False, seed=0, total_ticks=ticks,
                        churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n,
                        topology=topology)
    OverlaySimulation(cfg).run()          # compile + warm (seed 0)
    best = None
    for rep in range(2):
        # distinct seeds per rep, never repeating the warmup's: the
        # accelerator relay memoizes identical (executable, args)
        # calls, which would fake the timing (see
        # .claude/skills/verify/SKILL.md)
        res = OverlaySimulation(cfg.replace(seed=rep + 1)).run()
        if best is None or res.wall_seconds < best.wall_seconds:
            best = res
    # validate before reporting: the number only counts if the run is
    # a correct simulation (not assert: must survive -O).  in_group
    # must be exactly n in both modes: churned peers rejoin, and a
    # scripted-failure victim keeps its flag (only the churn wipe
    # clears it) — anything less means an orphaned joiner.
    m = best.metrics
    if int(np.asarray(m.in_group)[-1]) != n:
        raise RuntimeError("overlay bench: join/rejoin incomplete")
    if int(np.asarray(m.victim_slots)[-1]) != 0:
        raise RuntimeError("overlay bench: victims not purged")
    if n <= WALK_COVERAGE_N_LIMIT:
        # direct in-run assertion of the re-cover bound at every
        # launch boundary (the 65k-scale validation; it covers final
        # coverage too — the last boundary IS the final state); above
        # the walk limit fall back to snapshot + endpoint continuation
        _walk_recover(best.cfg, best.sched, best.cfg.total_ticks)
        _, victims_left = best.final_coverage()
        if victims_left:
            raise RuntimeError("overlay bench: victim entries left")
    else:
        _check_recover(best.cfg, best)
    return best


def bench_overlay_fleet(n: int, ticks: int, batch: int = 8):
    """Fleet-batched overlay churn bench: ``batch`` seeds through ONE
    compiled program (core/fleet.py) — the dispatch-amortization
    counterpart of :func:`bench_overlay`'s sequential runs.  Validated
    per lane like the sequential bench: every lane must finish fully
    joined with its victims purged (coverage is host-checkable on lane
    states; the fleet reports the grid/mega kernels' -1 sentinel for
    the per-tick histogram)."""
    import numpy as np

    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.core.fleet import FleetSimulation

    cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                    drop_msg=False, seed=0, total_ticks=ticks,
                    churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)
    fleet = FleetSimulation(cfg)
    fleet.run_bench(seeds=range(101, 101 + batch), warmup=False)  # compile
    best = None
    for rep in range(2):
        # distinct seed sets per rep (relay memoization, see
        # bench_overlay)
        seeds = [1000 * (rep + 1) + i for i in range(batch)]
        res = fleet.run_bench(seeds=seeds, warmup=False)
        if best is None or res.wall_seconds < best.wall_seconds:
            best = res
    for lane in best.lanes:
        m = lane.metrics
        if int(np.asarray(m.in_group)[-1]) != n:
            raise RuntimeError("fleet bench: join/rejoin incomplete")
        if int(np.asarray(m.victim_slots)[-1]) != 0:
            raise RuntimeError("fleet bench: victims not purged")
    return best


def bench_dense(n: int, ticks: int):
    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.core.sim import Simulation

    cfg = SimConfig(max_nnb=n, single_failure=False, drop_msg=True,
                    msg_drop_prob=0.1, seed=0, total_ticks=ticks)
    sim = Simulation(cfg)
    sim.run_bench()                # compiles on the warmup run; its
    best = None                    # timed call repeats the warmup args
    # 5 reps: dense runs are short (~0.3 s) and the relay adds
    # +/-15% jitter at that scale, so best-of-2 under-reports
    for rep in range(5):           # discard warmup (relay memoization)
        r = sim.run_bench(seed=rep + 1, warmup=False)
        if best is None or r.wall_seconds < best.wall_seconds:
            best = r
    return cfg, best.node_ticks_per_second


def _env_provenance() -> dict:
    """The env stamp every serving BENCH entry carries (mesh and load
    numbers are meaningless without the live device count and the XLA
    flags that forced it) — one definition, so the entries cannot
    drift apart in schema."""
    import jax
    return {
        "device_count": jax.device_count(),
        "jax_backend": jax.default_backend(),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }


def _entry(cfg, nps: float, backend: str) -> dict:
    """Per-config bench entry: both throughput axes + roofline."""
    tps = nps / cfg.n
    entry = {"node_ticks_per_s": round(nps, 1),
             "ticks_per_s": round(tps, 1),
             "vs_baseline": round(nps / REFERENCE_NODE_TICKS_PER_S, 3)}
    entry.update(_roofline(cfg, tps, backend))
    return entry


def _overlay_entry(res, backend: str) -> dict:
    return _entry(res.cfg, res.node_ticks_per_second, backend)


def _sv_entry(sv: dict) -> dict:
    """Serving-replay entry schema (shared by the mixed / mesh /
    mesh2d rows — one definition, so the rows cannot drift apart)."""
    return {
        "requests": sv["requests"],
        "devices": sv["devices"],
        # the PR-19 mesh decomposition (1-D meshes report
        # lanes == devices, peers == 1; absent in pre-PR-19
        # jsons; the trajectory renders "-")
        "lanes": sv["lanes"],
        "peers": sv["peers"],
        "pipeline": sv["pipeline"],
        # the PR-17 ring plane: configured in-flight depth and
        # how often a dispatch found its ring full (absent in
        # pre-PR-17 jsons; the trajectory renders "-")
        "pipeline_depth": sv["pipeline_depth"],
        "ring_stalls": sv["ring_stalls"],
        "speedup_vs_sequential": sv["speedup_vs_sequential"],
        "aggregate_node_ticks_per_s":
            sv["aggregate_node_ticks_per_s"],
        "latency_p50_s": sv["latency_p50_s"],
        "latency_p95_s": sv["latency_p95_s"],
        "mean_occupancy": sv["mean_occupancy"],
        # the PR-6 wall decomposition: pack / execute / fetch
        "mean_pack_s": sv["mean_pack_s"],
        "mean_device_wait_s": sv["mean_device_wait_s"],
        "mean_fetch_s": sv["mean_fetch_s"],
        "device_wait_frac": sv["device_wait_frac"],
        "cache_hit_rate": sv["cache_hit_rate"],
        "buckets": sv["buckets"],
        "max_builds_per_bucket": sv["max_builds_per_bucket"],
    }


def _mesh2d_entry(smoke: bool) -> dict:
    """2-D lanes x peers serving (PR 19, docs/SERVING.md "2-D
    capacity"): the acceptance stream PLUS a peer-SHARDABLE dense tier
    (n=16 divides both the 4- and 2-wide peer rungs; the grader's N=10
    and the overlay family stay peer-replicated, so the mixed stream
    proves both routings serve side by side) over the lanes x peers
    factorizations of 8 devices at equal total lane width.  replay()
    enforces per-request bit-parity on every row; the elastic leg
    serves the same stream from the (2,4) mesh with one seeded device
    loss + return, and elastic_replay raises unless the shrink drops a
    PEER shard (zero restarted lanes), checkpointed lanes migrate, and
    the grow restores the full (2,4) decomposition — the rows existing
    IS the gate."""
    import jax
    if jax.device_count() < 8:
        raise RuntimeError(
            f"mesh2d bench needs 8 (virtual) devices; only "
            f"{jax.device_count()} live — force "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8")
    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    from gossip_protocol_tpu.service import (Template, elastic_replay,
                                             grader_templates,
                                             overlay_templates)
    from gossip_protocol_tpu.service import replay as service_replay
    n_sv, t_sv, seeds_sv = (256, 48, 2) if smoke else (512, 96, 8)
    sv_lanes = min(8, 2 * seeds_sv)
    tpls2 = (grader_templates()
             + overlay_templates(n=n_sv, ticks=t_sv)
             + [Template("dense16-drop", SimConfig(
                 max_nnb=16, single_failure=False, drop_msg=True,
                 msg_drop_prob=0.1, seed=0, total_ticks=60,
                 fail_tick=30, rejoin_after=15, drop_open_tick=10,
                 drop_close_tick=50))])
    seq2 = None
    sweep2 = {}
    for lanes2, peers2 in ((2, 4), (4, 2)):
        kw2 = dict(seeds_per_template=seeds_sv,
                   max_batch=sv_lanes // lanes2,
                   mesh=make_lane_peer_mesh(lanes2, peers2))
        if seq2 is None:
            sv2, seq2 = service_replay(tpls2, return_legs=True, **kw2)
        else:
            sv2 = service_replay(tpls2, sequential=seq2, **kw2)
        sweep2[f"{lanes2}x{peers2}"] = _sv_entry(sv2)
    # smoke's 48-tick overlay tier is ONE segment at a 48-tick
    # budget — halve it so every bucket has a resumable leg for the
    # loss/return events to land on
    el2 = elastic_replay(tpls2, seeds_per_template=seeds_sv,
                         max_batch=sv_lanes // 2,
                         mesh=make_lane_peer_mesh(2, 4),
                         checkpoint_every=32 if smoke else 48,
                         fault_seed=20260807, sequential=seq2)
    return {
        "sweep": sweep2,
        "elastic_2x4": {
            "fault_seed": el2["fault_seed"],
            "checkpoint_every": el2["checkpoint_every"],
            "device_loss_at": el2["device_loss_at"],
            "device_return_at": el2["device_return_at"],
            "requests": el2["requests"],
            "completion_rate": el2["completion_rate"],
            "restarted_from_zero": el2["restarted_from_zero"],
            "elastic": el2["elastic"],
            "mean_legs": el2["mean_legs"],
            "cache_rekey_hits": el2["cache_rekey_hits"],
            "devices_start": el2["devices_start"],
            "devices_end": el2["devices_end"],
            "lanes_end": el2["lanes_end"],
            "peers_end": el2["peers_end"],
            "speedup_vs_sequential": el2["speedup_vs_sequential"],
            "schedule_digest": el2["schedule_digest"],
            "outcome_digest": el2["outcome_digest"],
            "parity_checked": el2["parity_checked"],
        },
        "env": _env_provenance(),
    }


def _mesh2d_subprocess(smoke: bool) -> dict:
    """Measure the mesh2d entry in a CHILD process with 8 forced
    virtual devices.  The parent's headline must be measured on the
    unsplit host — forcing virtual devices partitions the XLA host
    threadpool and roughly halves the single-program rate — so the
    2-D row records its OWN env provenance (the child's forced flags)
    instead of inheriting the parent's.  A child failure propagates:
    every in-line serving gate (parity, zero restarts, grow-back)
    still fails the bench run."""
    import subprocess
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    cmd = [sys.executable, os.path.abspath(__file__), "--mesh2d-sub"]
    if smoke:
        cmd.append("--smoke")
    p = subprocess.run(cmd, env=env, capture_output=True, text=True)
    if p.returncode != 0:
        raise RuntimeError(
            f"mesh2d bench subprocess failed (rc={p.returncode}): "
            f"{p.stderr[-800:]}")
    return json.loads(p.stdout.strip().splitlines()[-1])


def main():
    smoke = "--smoke" in sys.argv
    backend = _backend_or_cpu(60.0 if smoke else 180.0)
    if backend == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    # overlay runs need the full churn cycle to finish so the
    # validation can require complete rejoin: lo + span + rejoin + slack
    # = T/4 + T/2 + 40 + 25 <= T  =>  T >= 260
    # overlay tick counts are multiples of GRID_TICKS=16 so the grid
    # path compiles one kernel variant per config (no remainder launch)
    if smoke:
        n_overlay, t_overlay, n_dense, t_dense = 1024, 288, 64, 100
    elif backend == "cpu":
        n_overlay, t_overlay, n_dense, t_dense = 2048, 288, 512, 200
    else:
        # 608 ticks amortizes the relay's fixed per-run costs (~0.2 s
        # of dispatch + warm-path effects) to a few percent; shorter
        # runs under-report the device rate by ~20%
        n_overlay, t_overlay, n_dense, t_dense = 65536, 608, 512, 700

    overlay = bench_overlay(n_overlay, t_overlay)
    n_drop = min(4096, n_overlay)              # BASELINE "4096, 10% drop"
    drop = bench_overlay(n_drop, max(t_overlay, 200), mode="drop")
    dense_cfg, dense = bench_dense(n_dense, t_dense)

    secondary = {}
    if backend == "cpu":
        # fleet-batched serving shape (core/fleet.py): B seeds of the
        # headline config through one compiled program.  CPU-only for
        # now: the TPU fleet rides the batched grid kernel, whose
        # hardware timing recipe lives in docs/PERF.md §8.
        fb = 4 if smoke else 8
        fleet = bench_overlay_fleet(n_overlay, t_overlay, fb)
        agg = fleet.aggregate_node_ticks_per_second
        secondary[f"fleet{fb}_n{n_overlay}_overlay_churn20"] = {
            "batch": fb,
            "aggregate_node_ticks_per_s": round(agg, 1),
            "per_run_node_ticks_per_s": round(
                fleet.node_ticks_per_second_per_run, 1),
            # the dispatch-amortization win: one fleet program vs B
            # sequential runs at the sequential bench's own rate
            "speedup_vs_sequential": round(
                agg / overlay.node_ticks_per_second, 2),
            "vs_baseline": round(agg / REFERENCE_NODE_TICKS_PER_S, 3),
        }
        secondary[f"fleet{fb}_aggregate_node_ticks_per_s_"
                  f"n{n_overlay}_overlay_churn20"] = round(agg, 1)

        # serving-layer replay (gossip_protocol_tpu/service/): a mixed
        # request stream — the three grader scenario kinds x two size
        # tiers — through the continuous-batching scheduler, with
        # per-request bit-parity enforced inside replay().  Emits the
        # serving metrics schema (docs/SERVING.md).
        from gossip_protocol_tpu.service import (grader_templates,
                                                 overlay_templates)
        from gossip_protocol_tpu.service import replay as service_replay

        n_sv, t_sv, seeds_sv = (256, 48, 2) if smoke else (512, 96, 8)
        sv_templates = grader_templates() + overlay_templates(n=n_sv,
                                                              ticks=t_sv)
        # batch width must fit the stream: padding 2-seed smoke
        # buckets to 8 lanes would be 75% filler work
        sv_lanes = min(8, 2 * seeds_sv)
        sv, seq_leg = service_replay(sv_templates,
                                     seeds_per_template=seeds_sv,
                                     max_batch=sv_lanes, return_legs=True)
        secondary["service_replay_mixed"] = _sv_entry(sv)

        # chaos-hardened serving (PR 5, docs/SERVING.md "Failure
        # model"): the same stream under a SEEDED fault schedule
        # (~12% dispatch-boundary faults + one mid-replay device
        # loss).  chaos_replay raises unless 100% of requests complete
        # with per-request parity, so this entry existing IS the gate;
        # the seed + digests + env make the run replayable evidence.
        # When >1 (virtual) device is live the stream is served from a
        # 2-device lane mesh, so the device loss exercises the real
        # degradation ladder (mesh -> single device) instead of being
        # a mere retried transient.
        from gossip_protocol_tpu.service import chaos_replay
        import jax
        chaos_d = 2 if (jax.device_count() > 1 and sv_lanes % 2 == 0) \
            else 1
        chaos_mesh = None
        if chaos_d > 1:
            from gossip_protocol_tpu.parallel.fleet_mesh import \
                make_lane_mesh as _mk_mesh
            chaos_mesh = _mk_mesh(chaos_d)
        ch = chaos_replay(sv_templates, seeds_per_template=seeds_sv,
                          max_batch=sv_lanes // chaos_d,
                          mesh=chaos_mesh, fault_seed=20260804,
                          fault_rate=0.12, sequential=seq_leg)
        secondary["service_replay_chaos"] = {
            "fault_seed": ch["fault_seed"],
            "fault_rate": ch["fault_rate"],
            "device_loss_at": ch["device_loss_at"],
            "requests": ch["requests"],
            "completion_rate": ch["completion_rate"],
            "stranded": ch["stranded"],
            "degraded_requests": ch["degraded_requests"],
            "faults": ch["faults"],
            "retries": ch["failures"]["retries"],
            "backoff_s": ch["failures"]["backoff_s"],
            "device_losses": ch["failures"]["device_losses"],
            "mesh_rebuilds": ch["failures"]["mesh_rebuilds"],
            "devices_start": ch["devices_start"],
            "devices_end": ch["devices_end"],
            "latency_p50_s": ch["latency_p50_s"],
            "latency_p95_s": ch["latency_p95_s"],
            "speedup_vs_sequential": ch["speedup_vs_sequential"],
            "schedule_digest": ch["schedule_digest"],
            "outcome_digest": ch["outcome_digest"],
            "parity_checked": ch["parity_checked"],
            "env": _env_provenance(),
        }
        # elastic serving (PR 8, docs/SERVING.md "Elastic capacity"):
        # the same stream served as RESUMABLE LEGS (segment-boundary
        # checkpoints) under ONE seeded device loss + ONE device
        # return.  elastic_replay raises unless 100% completion, >= 1
        # loss AND >= 1 return injected, ZERO lanes restarted from
        # tick 0 (every interrupted lane resumes from its last
        # checkpoint), per-request parity, and — on a mesh — lane
        # migration across the shrink -> grow rebuild; this entry
        # existing IS the gate.  Served from a 2-device lane mesh when
        # virtual devices are live, so the loss+return exercises the
        # real grow ladder (and the program cache's re-key path).
        from gossip_protocol_tpu.service import elastic_replay
        el_d = 2 if (jax.device_count() > 1 and sv_lanes % 2 == 0) \
            else 1
        el_mesh = None
        if el_d > 1:
            from gossip_protocol_tpu.parallel.fleet_mesh import \
                make_lane_mesh as _mk_mesh_el
            el_mesh = _mk_mesh_el(el_d)
        # smoke's 48-tick overlay tier is ONE segment at a 48-tick
        # budget — halve it so every bucket has a resumable leg for
        # the loss/return events to land on (only reachable with a
        # live mesh, i.e. forced virtual devices)
        el = elastic_replay(sv_templates, seeds_per_template=seeds_sv,
                            max_batch=sv_lanes // el_d, mesh=el_mesh,
                            checkpoint_every=32 if smoke else 48,
                            fault_seed=20260804, sequential=seq_leg)
        secondary["service_replay_elastic"] = {
            "fault_seed": el["fault_seed"],
            "checkpoint_every": el["checkpoint_every"],
            "device_loss_at": el["device_loss_at"],
            "device_return_at": el["device_return_at"],
            "requests": el["requests"],
            "completion_rate": el["completion_rate"],
            "stranded": el["stranded"],
            "restarted_from_zero": el["restarted_from_zero"],
            "degraded_requests": el["degraded_requests"],
            "faults": el["faults"],
            "elastic": el["elastic"],
            "mean_legs": el["mean_legs"],
            "cache_rekey_hits": el["cache_rekey_hits"],
            "retries": el["failures"]["retries"],
            "device_losses": el["failures"]["device_losses"],
            "device_returns": el["failures"]["device_returns"],
            "mesh_rebuilds": el["failures"]["mesh_rebuilds"],
            "devices_start": el["devices_start"],
            "devices_end": el["devices_end"],
            "latency_p50_s": el["latency_p50_s"],
            "latency_p95_s": el["latency_p95_s"],
            "speedup_vs_sequential": el["speedup_vs_sequential"],
            "schedule_digest": el["schedule_digest"],
            "outcome_digest": el["outcome_digest"],
            "parity_checked": el["parity_checked"],
            "env": _env_provenance(),
        }
        # durable serving (PR 12, gossip_protocol_tpu/store/): the
        # kill-and-restart acceptance gate at full scale — the
        # acceptance stream served against a run directory (write-
        # ahead journal + content-addressed checkpoint spill) in a
        # SUBPROCESS that dies via os._exit mid-run, then recovered
        # here.  kill_restart_replay raises unless every request is
        # terminal exactly once across the two processes,
        # restarted_lanes == 0, and every per-request result content
        # digest matches an uninterrupted baseline run — this entry
        # existing IS the gate (at non-smoke scale: 204 requests).
        from gossip_protocol_tpu.store.harness import \
            kill_restart_replay
        seeds_rc = 2 if smoke else 34
        rc, _ = kill_restart_replay(seeds_per_template=seeds_rc,
                                    n_overlay=n_sv, t_overlay=t_sv,
                                    max_batch=8, checkpoint_every=48,
                                    kill_frac=0.5)
        rc.pop("run_dir", None)      # a tmp path, not provenance
        rc["durability"].pop("run_dir", None)
        rc["env"] = _env_provenance()
        secondary["service_recovery"] = rc
        if jax.device_count() > 1:
            # lane-mesh serving (parallel/fleet_mesh.py) at EQUAL total
            # lane width: max_batch is per-device and d must DIVIDE
            # sv_lanes (largest divisor within the live device count),
            # so the mesh replay dispatches exactly the same sv_lanes
            # lanes split over the mesh — on a device count that does
            # not divide the width, a smaller mesh keeps the
            # comparison honest rather than silently changing the
            # width.  The sequential baseline is identical by
            # construction, so the first replay's leg is reused
            # (parity is still verified against it per request).
            # Reachable when the invoker forced virtual devices
            # (XLA_FLAGS=--xla_force_host_platform_device_count=N) —
            # recorded in this json's "env" metadata.
            from gossip_protocol_tpu.parallel.fleet_mesh import \
                make_lane_mesh
            d = max(k for k in range(1, min(jax.device_count(),
                                            sv_lanes) + 1)
                    if sv_lanes % k == 0)
            if d > 1:
                sv_m = service_replay(sv_templates,
                                      seeds_per_template=seeds_sv,
                                      max_batch=sv_lanes // d,
                                      mesh=make_lane_mesh(d),
                                      sequential=seq_leg)
                secondary["service_replay_mixed_mesh"] = _sv_entry(sv_m)

        # 2-D lanes x peers serving (PR 19, docs/SERVING.md "2-D
        # capacity"): measured in THIS process when 8 (virtual)
        # devices are already live, else in a child process with 8
        # forced virtual devices (_mesh2d_subprocess — the headline
        # above must stay on the unsplit host threadpool); either way
        # the entry carries the env that produced it.
        if sv_lanes % 4 == 0:
            if jax.device_count() >= 8:
                secondary["service_replay_mesh2d"] = _mesh2d_entry(smoke)
            else:
                secondary["service_replay_mesh2d"] = \
                    _mesh2d_subprocess(smoke)

        # open-loop traffic plane (PR 7, docs/SERVING.md "Open-loop
        # traffic & SLOs"): seeded Poisson arrivals wall-paced through
        # the pipelined scheduler at a swept ladder of offered loads —
        # p50/p99 per priority class, per-class deadline-miss rates,
        # the measured saturation point, the deadline-aware-early-
        # flush ON/OFF miss-rate comparison on one schedule, and the
        # virtual-clock determinism gate (identical seed -> identical
        # arrival + outcome digests across two runs).  measure_point
        # raises on any stranded handle or non-deadline failure, so
        # this entry existing is itself a completion gate.
        from gossip_protocol_tpu.service.loadbench import \
            load_openloop_bench
        lb = load_openloop_bench(smoke=smoke)
        lb["env"] = _env_provenance()
        secondary["service_load_openloop"] = lb

        # scenario frontier (PR 9 + round 2, docs/SCENARIOS.md): the
        # adversarial failure-world catalog (models/scenarios.py —
        # partitions that heal, asymmetric per-link loss, correlated
        # failure waves, zombie peers, flapping members, Byzantine
        # liars, per-link latency, and the composed storms; both
        # models) x N seeds, graded as ONE FleetService run with
        # every variant's closed-form oracle verdict recorded.
        # scenarios.sweep raises unless 100% of variants reach a
        # terminal state AND every oracle is green (failures print
        # their exact single-variant repro), and the whole sweep is
        # re-run and must reproduce verdict- and outcome-digest-for-
        # digest — so this entry existing IS the scenario replay
        # gate.  Full (non-smoke) runs grade the ISSUE-15 bar: 25
        # families x 40 seeds = 1000 variants.
        from gossip_protocol_tpu.models import scenarios
        sc_seeds = 3 if smoke else 40
        sc = scenarios.sweep(seeds_per_family=sc_seeds)
        sc2 = scenarios.sweep(seeds_per_family=sc_seeds)
        if (sc2["verdict_digest"] != sc["verdict_digest"]
                or sc2["outcome_digest"] != sc["outcome_digest"]):
            raise RuntimeError(
                "scenario sweep replay diverged: "
                f"verdicts {sc['verdict_digest']} -> "
                f"{sc2['verdict_digest']}, outcomes "
                f"{sc['outcome_digest']} -> {sc2['outcome_digest']}")
        secondary["scenario_sweep"] = {
            "variants": sc["variants"],
            "families": sc["families"],
            "worlds": sc["worlds"],
            "seeds_per_family": sc_seeds,
            "oracle_pass_rate": sc["pass_rate"],
            "failed_variants": sc["failed"],
            "per_family": sc["per_family"],
            "terminal_rate": sc["terminal_rate"],
            "verdict_digest": sc["verdict_digest"],
            "outcome_digest": sc["outcome_digest"],
            "replayed_digest_for_digest": True,
            "wall_s": sc["wall_s"],
            "dispatches": sc["dispatches"],
            "buckets": sc["buckets"],
            "mean_occupancy": sc["mean_occupancy"],
            "env": _env_provenance(),
        }

        # compile-surface budget (PR 16, docs/PERF.md §12): the
        # scenario grammar jittered per request (off-rung n, off-grid
        # windows, perturbed world params) through a baseline exact-
        # bucket lap vs cold + warm CANONICAL laps
        # (service/canonical.py).  compile_surface_bench raises unless
        # every request is bit-identical to its exact-bucket result
        # (plus a direct-solo sample), the warm lap builds NOTHING,
        # and (full runs) fresh builds collapse >= 3x — this entry
        # existing IS the compile-surface gate.
        from gossip_protocol_tpu.service.loadbench import \
            compile_surface_bench
        cs = compile_surface_bench(smoke=smoke)
        cs["env"] = _env_provenance()
        secondary["compile_surface"] = cs

    secondary.update({
        f"n{n_drop}_overlay_drop10": _overlay_entry(drop, backend),
        f"n{n_dense}_fullview": _entry(dense_cfg, dense, backend),
        # continuity keys for round-over-round comparison
        f"node_ticks_per_s_n{n_drop}_overlay_drop10":
            round(drop.node_ticks_per_second, 1),
        "overlay_drop10_vs_baseline": round(
            drop.node_ticks_per_second / REFERENCE_NODE_TICKS_PER_S, 3),
        f"node_ticks_per_s_n{n_dense}_fullview": round(dense, 1),
        "fullview_vs_baseline": round(dense / REFERENCE_NODE_TICKS_PER_S, 3),
    })
    if backend == "tpu" and not smoke:
        # the (4096, 65536] envelope: the grid multi-tick kernel's
        # smallest headline size (was the unrecorded fallback gap)
        mid = bench_overlay(8192, t_overlay)
        secondary["n8192_overlay_churn20"] = _overlay_entry(mid, backend)
        secondary["node_ticks_per_s_n8192_overlay_churn20"] = \
            round(mid.node_ticks_per_second, 1)
        # dense full-view at the BASELINE "N=4096, 10% drop" shape
        dense4k_cfg, dense4k = bench_dense(4096, 200)
        secondary["n4096_fullview"] = _entry(dense4k_cfg, dense4k, backend)
        secondary["node_ticks_per_s_n4096_fullview"] = round(dense4k, 1)
        # BASELINE's 1M north-star shape: power-law overlay, validated
        # (join completeness, victim purge, live coverage)
        pl_1m = bench_overlay(1 << 20, 272, mode="fail",
                              topology="powerlaw")
        secondary["n1048576_overlay_powerlaw"] = _overlay_entry(pl_1m,
                                                                backend)
        secondary["node_ticks_per_s_n1048576_overlay_powerlaw"] = \
            round(pl_1m.node_ticks_per_second, 1)
        secondary["overlay_powerlaw_1m_vs_baseline"] = round(
            pl_1m.node_ticks_per_second / REFERENCE_NODE_TICKS_PER_S, 3)

    # provenance: every BENCH json must say what machine shape produced
    # it (_env_provenance; the headline env also samples device names)
    import jax
    nps = overlay.node_ticks_per_second
    payload = {
        "metric": f"node_ticks_per_s_n{n_overlay}_overlay_churn20",
        "value": round(nps, 1),
        "unit": "node-ticks/s",
        "vs_baseline": round(nps / REFERENCE_NODE_TICKS_PER_S, 3),
        "backend": backend,
        "ticks_per_s": round(nps / n_overlay, 1),
        "env": {
            **_env_provenance(),
            "devices": [str(d) for d in jax.devices()[:2]]
            + (["..."] if jax.device_count() > 2 else []),
        },
        "headline": _overlay_entry(overlay, backend),
        "secondary": secondary,
    }
    if "--check" in sys.argv:
        # fold the static-analysis verdict into the payload BEFORE it
        # prints, so the committed BENCH_pr*.json records the lint
        # state of the tree that produced the numbers
        # (bench_trajectory renders the findings/rules columns)
        payload["analysis"] = analysis_summary()
    print(json.dumps(payload))
    if "--check" in sys.argv:
        rc = check_regression(payload)
        rc_compiles = check_steady_state_compiles(
            inject="--inject-recompile" in sys.argv)
        rc_lint = check_static_analysis(payload["analysis"])
        # record the row AFTER the gates ran (so the regression gate
        # compared against the PREVIOUS baseline, not this run) but
        # UNCONDITIONALLY — PR 14 and 15 gated without recording,
        # leaving a two-PR hole in the trajectory.  A write failure is
        # a hard failure: an unrecordable gate run must not pass.
        write_bench_row(payload)
        sys.exit(rc or rc_compiles or rc_lint)


def _pr_number() -> int:
    """The PR number this run records under: ``--pr N`` wins; else one
    past the highest PR mentioned in CHANGES.md (the stacked-PR
    trajectory convention), falling back to the highest existing
    BENCH_pr*.json."""
    import glob
    import re
    for i, a in enumerate(sys.argv):
        if a == "--pr" and i + 1 < len(sys.argv):
            return int(sys.argv[i + 1])
        if a.startswith("--pr="):
            return int(a.split("=", 1)[1])
    root = os.path.dirname(os.path.abspath(__file__))
    prs: list = []
    try:
        with open(os.path.join(root, "CHANGES.md")) as f:
            prs = [int(m) for m in re.findall(r"\bPR (\d+)", f.read())]
    except OSError:
        pass
    if not prs:
        prs = [int(re.search(r"BENCH_pr(\d+)", p).group(1))
               for p in glob.glob(os.path.join(root, "BENCH_pr*.json"))]
    return (max(prs) if prs else 0) + 1


def write_bench_row(payload: dict) -> str:
    """Record this --check run as ``BENCH_pr<N>.json`` — every gate
    run leaves a trajectory row, pass or fail.  Atomic (tmp +
    replace); any write error PROPAGATES — silently losing the row is
    exactly the PR-14/15 hole this exists to close."""
    root = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(root, f"BENCH_pr{_pr_number():02d}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.write("\n")
    os.replace(tmp, path)
    print(f"bench --check: recorded {os.path.basename(path)}",
          file=sys.stderr)
    return path


#: --check fails the run when the fresh headline falls more than this
#: far below the latest recorded BENCH_pr*.json headline
CHECK_REGRESSION_FRAC = 0.15


def check_regression(payload: dict) -> int:
    """Perf-gate mode (``bench.py --check``): compare the fresh run
    against the LATEST recorded ``BENCH_pr*.json`` and return nonzero
    on a >15% headline regression — so a perf-sensitive change can be
    gated in one command instead of by eyeballing two jsons
    (scripts/bench_trajectory.py renders the whole series).

    Only same-metric headlines are compared: a ``--smoke`` run (or a
    different backend's run) measures a different config, and a
    comparison across metrics would gate on noise.
    """
    import glob
    import re
    baselines = sorted(
        glob.glob(os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "BENCH_pr*.json")),
        key=lambda p: int(re.search(r"BENCH_pr(\d+)", p).group(1)))
    if not baselines:
        print("bench --check: no BENCH_pr*.json baseline found",
              file=sys.stderr)
        return 2
    ref = json.load(open(baselines[-1]))
    if ref.get("metric") != payload["metric"]:
        print(f"bench --check: metric mismatch (fresh "
              f"{payload['metric']!r} vs baseline {ref.get('metric')!r} "
              f"in {os.path.basename(baselines[-1])}); run the same "
              "bench shape as the baseline", file=sys.stderr)
        return 2
    old, new = float(ref["value"]), float(payload["value"])
    ratio = new / old if old else float("inf")
    verdict = "OK" if ratio >= 1.0 - CHECK_REGRESSION_FRAC else "FAIL"
    print(f"bench --check vs {os.path.basename(baselines[-1])}: "
          f"{new:,.1f} vs {old:,.1f} nt/s ({(ratio - 1) * 100:+.1f}%) "
          f"-> {verdict} (gate: -{CHECK_REGRESSION_FRAC:.0%})",
          file=sys.stderr)
    return 0 if verdict == "OK" else 1


def check_steady_state_compiles(inject: bool = False) -> int:
    """Compile-count budget gate (``--check``, PR 10): a warmed bench
    lap must trigger ZERO fresh XLA compiles — a steady-state
    recompile means a run-cache key regressed or an input shape leaks
    per call, and on the serving path that is the first-lap cost of
    PERF §11 paid on EVERY dispatch.  Enforced by
    analysis/guards.steady_state_compile_gate; ``--inject-recompile``
    deliberately trips it (the gate's own acceptance fixture — also
    exercised in-process by tests/test_analysis.py)."""
    from gossip_protocol_tpu.analysis.guards import \
        steady_state_compile_gate
    res = steady_state_compile_gate(inject_recompile=inject)
    if res["ok"]:
        print("bench --check compiles: steady-state lap clean "
              "(0 fresh XLA compiles)", file=sys.stderr)
        return 0
    print(f"bench --check compiles: FAIL — {res['compiles']} fresh "
          f"compile(s) in the steady-state lap: "
          f"{res.get('compiled', [])}", file=sys.stderr)
    return 1


def analysis_summary() -> dict:
    """Static-analysis section of the --check payload (PR 14): the
    jaxpr + sharding-flow + AST passes run in-process and their
    verdict rides the committed BENCH json — findings count, rule
    inventory size, and how many registry programs were actually
    traced vs skipped (a bench box without 8 virtual devices skips
    the mesh entries; that must be visible, not read as coverage)."""
    from gossip_protocol_tpu.analysis import RULES, run_all
    from gossip_protocol_tpu.analysis.jaxpr_audit import audit
    findings = run_all(passes=("jaxpr", "sharding", "ast"))
    skipped = sum(1 for p in audit.last_programs if p.jaxpr is None)
    return {
        "findings": len(findings),
        "rules": len(RULES),
        "programs_traced": len(audit.last_programs) - skipped,
        "programs_skipped": skipped,
        "rules_failing": sorted({f.rule for f in findings}),
    }


def check_static_analysis(summary: dict) -> int:
    """Lint gate (``--check``, PR 14): the static passes must be
    clean — a bench number recorded over a tree that fails its own
    invariant analysis is not a number worth recording."""
    if not summary["findings"]:
        print(f"bench --check lint: clean "
              f"({summary['rules']} rule(s), "
              f"{summary['programs_traced']} program(s) traced, "
              f"{summary['programs_skipped']} skipped)",
              file=sys.stderr)
        return 0
    print(f"bench --check lint: FAIL — {summary['findings']} "
          f"finding(s) across rule(s) {summary['rules_failing']}; "
          "run `make lint` for the report", file=sys.stderr)
    return 1


if __name__ == "__main__":
    if "--mesh2d-sub" in sys.argv:
        # child mode for _mesh2d_subprocess: emit the mesh2d entry as
        # the last stdout line (jax warnings may precede it)
        print(json.dumps(_mesh2d_entry("--smoke" in sys.argv)))
        sys.exit(0)
    main()
