"""Benchmark: simulated gossip throughput on the current backend.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric: node-ticks/second of the dense full-view membership
simulation at N=512 (the BASELINE.json intermediate config
"multifailure, N=512"), whole run resident on device via lax.scan.

Baseline: the reference's measured throughput is 3,500-14,000 ticks/s at
N=10 on one CPU core (BASELINE.md) = at best ~1.4e5 node-ticks/s; we use
the best-case 1.4e5 * (10 nodes) => 1.4e6... more precisely BASELINE.md
reports ~0.35-1.4 M node-ticks/s; vs_baseline divides by the top of that
range (1.4e6 node-ticks/s), so vs_baseline > 1 means faster than the
reference has ever measured, on a strictly harder (51x larger) config.
"""

import json
import multiprocessing
import os
import sys
import time

REFERENCE_NODE_TICKS_PER_S = 1.4e6  # BASELINE.md best case, N=10, 1 CPU core


def _probe_backend(q):
    try:
        import jax
        q.put(jax.default_backend())
    except Exception:
        q.put("error")


def _backend_or_cpu(timeout_s: float = 180.0) -> str:
    """Bounded accelerator probe.

    This image routes the TPU through a single-grant tunnel that can
    block ``jax.devices()`` indefinitely if a previous client died
    mid-claim; a hung bench is worse than a CPU number, so probe the
    backend in a subprocess with a deadline and fall back to CPU.
    """
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_probe_backend, args=(q,))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.kill()
        p.join()
        return "cpu"
    try:
        backend = q.get_nowait()
    except Exception:
        backend = "cpu"
    return backend if backend not in ("error",) else "cpu"


def main():
    smoke = "--smoke" in sys.argv
    n = 64 if smoke else 512
    ticks = 100 if smoke else 700

    backend = _backend_or_cpu(60.0 if smoke else 180.0)
    if backend == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.core.sim import Simulation

    cfg = SimConfig(max_nnb=n, single_failure=False, drop_msg=True,
                    msg_drop_prob=0.1, seed=0, total_ticks=ticks)
    sim = Simulation(cfg)
    res = sim.run_bench()          # compiles on the warmup run, times the second
    best = res
    for _ in range(2):             # take the best of 3 timed runs
        r = sim.run_bench(warmup=False)
        if r.wall_seconds < best.wall_seconds:
            best = r

    value = best.node_ticks_per_second
    print(json.dumps({
        "metric": f"node_ticks_per_s_n{n}_fullview",
        "value": round(value, 1),
        "unit": "node-ticks/s",
        "vs_baseline": round(value / REFERENCE_NODE_TICKS_PER_S, 3),
        "backend": backend,
    }))


if __name__ == "__main__":
    main()
