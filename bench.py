"""Benchmark: simulated gossip throughput on the current backend.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Headline metric: node-ticks/second of the **bounded partial-view
overlay** at N=65536 with 20% churn — the BASELINE.json intermediate
config the reference cannot represent at all (its merge filter caps at
N<=10, MP1Node.cpp:245, and EmulNet at N<=1000, EmulNet.h:10).  The
run is validated before it is reported: everyone joins, churned peers
rejoin, failed peers are purged from every view, and the union of
views covers every live member at the end.

Secondary metric (reported in the same line): the dense full-view
model at N=512 (the reference-faithful semantics, "multifailure
N=512" BASELINE config, 10% drop).

Baseline: the reference's measured best case is ~1.4M node-ticks/s
(N=10, one CPU core, BASELINE.md); vs_baseline divides by that.
"""

import json
import multiprocessing
import sys

REFERENCE_NODE_TICKS_PER_S = 1.4e6  # BASELINE.md best case, N=10, 1 CPU core


def _probe_backend(q):
    try:
        import jax
        q.put(jax.default_backend())
    except Exception:
        q.put("error")


def _backend_or_cpu(timeout_s: float = 180.0) -> str:
    """Bounded accelerator probe.

    This image routes the TPU through a single-grant tunnel that can
    block ``jax.devices()`` indefinitely if a previous client died
    mid-claim; a hung bench is worse than a CPU number, so probe the
    backend in a subprocess with a deadline and fall back to CPU.
    """
    ctx = multiprocessing.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_probe_backend, args=(q,))
    p.start()
    p.join(timeout_s)
    if p.is_alive():
        p.kill()
        p.join()
        return "cpu"
    try:
        backend = q.get_nowait()
    except Exception:
        backend = "cpu"
    return backend if backend not in ("error",) else "cpu"


def bench_overlay(n: int, ticks: int, mode: str = "churn",
                  topology: str = "uniform"):
    """BASELINE configs: 20% churn (the 65k shape), 10% message drop
    (the 4096 shape), or a scripted failure under the power-law
    topology (the 1M scale-free shape)."""
    import numpy as np

    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.models.overlay import OverlaySimulation

    if mode == "drop":
        # like the reference's msgdrop scenario, the join ramp finishes
        # before the drop window opens (tick 50), so a dropped JOINREQ
        # can never orphan a peer
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=True, msg_drop_prob=0.1, seed=0,
                        total_ticks=ticks, fail_tick=ticks // 2,
                        step_rate=40.0 / n, topology=topology)
    elif mode == "fail":
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=False, seed=0, total_ticks=ticks,
                        fail_tick=ticks // 2, step_rate=40.0 / n,
                        topology=topology)
    elif mode != "churn":
        raise ValueError(f"unknown bench_overlay mode {mode!r}")
    else:
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                        drop_msg=False, seed=0, total_ticks=ticks,
                        churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n,
                        topology=topology)
    OverlaySimulation(cfg).run()          # compile + warm (seed 0)
    best = None
    for rep in range(2):
        # distinct seeds per rep, never repeating the warmup's: the
        # accelerator relay memoizes identical (executable, args)
        # calls, which would fake the timing (see
        # .claude/skills/verify/SKILL.md)
        res = OverlaySimulation(cfg.replace(seed=rep + 1)).run()
        if best is None or res.wall_seconds < best.wall_seconds:
            best = res
    # validate before reporting: the number only counts if the run is
    # a correct simulation (not assert: must survive -O).  in_group
    # must be exactly n in both modes: churned peers rejoin, and a
    # scripted-failure victim keeps its flag (only the churn wipe
    # clears it) — anything less means an orphaned joiner.
    m = best.metrics
    if int(np.asarray(m.in_group)[-1]) != n:
        raise RuntimeError("overlay bench: join/rejoin incomplete")
    if int(np.asarray(m.victim_slots)[-1]) != 0:
        raise RuntimeError("overlay bench: victims not purged")
    uncovered, victims_left = best.final_coverage()
    if victims_left:
        raise RuntimeError("overlay bench: victim entries left")
    if uncovered:
        # A final-snapshot coverage hole may be a benign transient: a
        # degree-1 leaf whose boosted self-entry lost one slot
        # contention reseeds itself on its next send (observed ~2 per
        # 1M-snapshot under the power-law topology).  A PERSISTENT
        # hole is a violation: run a few more ticks and require every
        # snapshot-uncovered member to be re-covered.
        if uncovered > 8:
            raise RuntimeError(
                f"overlay bench: coverage violated ({uncovered} uncovered)")
        before = set(best.uncovered_members().tolist())
        cfg2 = cfg.replace(total_ticks=cfg.total_ticks + 4)
        cont = OverlaySimulation(cfg2).run(resume_from=best.final_state)
        after = set(cont.uncovered_members().tolist())
        if before & after:
            raise RuntimeError(
                f"overlay bench: persistent coverage hole "
                f"({sorted(before & after)[:5]}...)")
    return best.node_ticks_per_second


def bench_dense(n: int, ticks: int):
    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.core.sim import Simulation

    cfg = SimConfig(max_nnb=n, single_failure=False, drop_msg=True,
                    msg_drop_prob=0.1, seed=0, total_ticks=ticks)
    sim = Simulation(cfg)
    sim.run_bench()                # compiles on the warmup run; its
    best = None                    # timed call repeats the warmup args
    for rep in range(2):           # so discard it (relay memoization)
        r = sim.run_bench(seed=rep + 1, warmup=False)
        if best is None or r.wall_seconds < best.wall_seconds:
            best = r
    return best.node_ticks_per_second


def main():
    smoke = "--smoke" in sys.argv
    backend = _backend_or_cpu(60.0 if smoke else 180.0)
    if backend == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    # overlay runs need the full churn cycle to finish so the
    # validation can require complete rejoin: lo + span + rejoin + slack
    # = T/4 + T/2 + 40 + 25 <= T  =>  T >= 260
    if smoke:
        n_overlay, t_overlay, n_dense, t_dense = 1024, 280, 64, 100
    elif backend == "cpu":
        n_overlay, t_overlay, n_dense, t_dense = 2048, 280, 512, 200
    else:
        n_overlay, t_overlay, n_dense, t_dense = 65536, 300, 512, 700

    overlay = bench_overlay(n_overlay, t_overlay)
    n_drop = min(4096, n_overlay)              # BASELINE "4096, 10% drop"
    overlay_drop = bench_overlay(n_drop, max(t_overlay, 200), mode="drop")
    dense = bench_dense(n_dense, t_dense)

    secondary = {
        f"node_ticks_per_s_n{n_drop}_overlay_drop10": round(overlay_drop, 1),
        "overlay_drop10_vs_baseline": round(
            overlay_drop / REFERENCE_NODE_TICKS_PER_S, 3),
        f"node_ticks_per_s_n{n_dense}_fullview": round(dense, 1),
        "fullview_vs_baseline": round(dense / REFERENCE_NODE_TICKS_PER_S, 3),
    }
    if backend == "tpu" and not smoke:
        # BASELINE's 1M north-star shape: power-law overlay, validated
        # (join completeness, victim purge, live coverage)
        pl_1m = bench_overlay(1 << 20, 260, mode="fail",
                              topology="powerlaw")
        secondary["node_ticks_per_s_n1048576_overlay_powerlaw"] = \
            round(pl_1m, 1)
        secondary["overlay_powerlaw_1m_vs_baseline"] = round(
            pl_1m / REFERENCE_NODE_TICKS_PER_S, 3)

    print(json.dumps({
        "metric": f"node_ticks_per_s_n{n_overlay}_overlay_churn20",
        "value": round(overlay, 1),
        "unit": "node-ticks/s",
        "vs_baseline": round(overlay / REFERENCE_NODE_TICKS_PER_S, 3),
        "backend": backend,
        "secondary": secondary,
    }))


if __name__ == "__main__":
    main()
