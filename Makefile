# Build the native runtime: the `Application` launcher binary (default
# target — the reference's Grader.sh does `make clean && make &&
# ./Application testcases/<x>.conf` and runs unmodified against it) and
# `libgossip_native.so` (the C ABI used by the Python ctypes bindings in
# gossip_protocol_tpu/compat/native.py and by the test suite).

CXX      ?= g++
CXXFLAGS ?= -O2 -std=c++17 -Wall -Wextra -fPIC
PY_INC   := $(shell python3-config --includes)
PY_LD    := $(shell python3-config --ldflags --embed 2>/dev/null || python3-config --ldflags)

NATIVE_SRCS := native/params.cc native/logsink.cc native/bus.cc native/engine.cc
NATIVE_OBJS := $(NATIVE_SRCS:.cc=.o)
HDRS        := native/params.h native/logsink.h native/bus.h native/engine.h native/wire.h

all: Application libgossip_native.so

native/%.o: native/%.cc $(HDRS)
	$(CXX) $(CXXFLAGS) -c $< -o $@

native/gossip_app.o: native/gossip_app.cc $(HDRS)
	$(CXX) $(CXXFLAGS) $(PY_INC) -c $< -o $@

Application: $(NATIVE_OBJS) native/gossip_app.o
	$(CXX) $(CXXFLAGS) -o $@ $^ $(PY_LD)

libgossip_native.so: $(NATIVE_OBJS)
	$(CXX) $(CXXFLAGS) -shared -o $@ $^

clean:
	rm -f $(NATIVE_OBJS) native/gossip_app.o Application libgossip_native.so \
	      dbg.log stats.log msgcount.log

# Static invariant analysis (PR 10/14, docs/ANALYSIS.md): the jaxpr
# audit over the registered hot programs + the sharding-flow per-axis
# collective pass (the 2-D mesh gate) + the AST purity/cache-key
# passes.  Exits nonzero on any finding.  The runtime guard pass is
# enforced by `python bench.py --check` (compile budget) and tier-1
# (transfer guard); `python -m gossip_protocol_tpu.analysis` alone
# runs all four.
lint:
	JAX_PLATFORMS=cpu python -m gossip_protocol_tpu.analysis --pass jaxpr --pass sharding --pass ast

# Same three static passes, one machine-readable JSON document on
# stdout (findings + covered-program roster) for CI and
# scripts/bench_trajectory.py.
lint-json:
	@JAX_PLATFORMS=cpu python -m gossip_protocol_tpu.analysis --pass jaxpr --pass sharding --pass ast --json

.PHONY: all clean lint lint-json
