"""Service smoke: mixed-workload trace replay through the fleet service
(gossip_protocol_tpu/service/) — the serving counterpart of
scripts/fleet_smoke.py.

Modes:

    python scripts/service_smoke.py replay            # the acceptance run
    python scripts/service_smoke.py replay 34 512 96  # seeds/tpl, overlay n, ticks
    python scripts/service_smoke.py quick             # small functional pass
    python scripts/service_smoke.py sweep             # max_batch sweep
    python scripts/service_smoke.py mesh [34]         # replay per device count
    python scripts/service_smoke.py mesh2d [34]       # lanes x peers sweep
    python scripts/service_smoke.py chaos [34] [0.12] # seeded fault sweep
    python scripts/service_smoke.py pipeline [34]     # pipelined vs sync per D
    python scripts/service_smoke.py load [24]         # open-loop 3-seed sweep
    python scripts/service_smoke.py elastic [34] [48] # loss+return legs sweep
    python scripts/service_smoke.py scenarios [40]    # adversarial-world sweep
    python scripts/service_smoke.py scenarios 40 --composed  # round-2 worlds only
    python scripts/service_smoke.py scenario --family F --seed S  # 1 repro
    python scripts/service_smoke.py recover [34] [48] # kill/restart sweep
    python scripts/service_smoke.py inspect RUN_DIR DIGEST  # verify 1 spill

``elastic`` (PR 8) exercises the elasticity ladder end to end
(docs/SERVING.md "Elastic capacity"): for each of three fault seeds
the acceptance stream is served as RESUMABLE LEGS
(``checkpoint_every`` segment budget, second arg) from a 2-device
lane mesh with ONE seeded device loss and ONE device return —
shrink, migrate the checkpointed lanes, grow back, migrate again.
Gates (all enforced inside service.elastic_replay): 100% terminal
handles, >= 1 loss AND >= 1 return actually injected, ZERO lanes
restarted from tick 0 (every interrupted lane resumes from its last
segment-boundary checkpoint), per-request bit-parity against solo
runs, and the first seed re-run digest-for-digest (fault schedule +
per-request status/retries/legs).

``recover`` (PR 12) is the durability acceptance run (docs/SERVING.md
"Durability"): the acceptance stream is served against a run
directory (write-ahead journal + content-addressed checkpoint spill,
gossip_protocol_tpu/store/) in a SUBPROCESS that is killed mid-run
via ``os._exit`` at three different points of the dispatch schedule;
the parent recovers each run directory in a fresh service
(``FleetService.recover``) and drains it.  Gates (enforced inside
store.harness.kill_restart_replay AND re-checked here): every request
terminal exactly once across the two processes, ZERO lanes restarted
from tick 0 (every killed lane resumes from its last spilled cut),
and per-request result content digests identical to one shared
uninterrupted baseline run.  ``inspect`` verifies a single spilled
snapshot (readable -> array sha -> content digest) WITHOUT importing
jax — it is the repro command a CheckpointValidationError prints.

``scenarios`` (PR 9, round 2 in PR 15) is the scenario-frontier
acceptance run (docs/SCENARIOS.md): the full adversarial-world
catalog (models/scenarios.py — partitions that heal, asymmetric
per-link loss, correlated failure waves, zombie peers, flapping
members, Byzantine liars, per-link latency, and composed storms that
stack several planes at once; both models) x ``seeds_per_family``
seeds, graded as ONE FleetService run
with every variant's closed-form oracle verdict recorded.  Gates
(enforced inside scenarios.sweep + here): 100% of variants terminal,
every oracle green, and the whole sweep re-run digest-for-digest
(verdicts AND final-state outcome digests) — identical seeds must
reproduce identical worlds.  A failing variant prints its exact
single-variant repro, which is the ``scenario`` mode:
``scenario --family dense_wave --seed 1007`` re-runs one variant solo
(no service) and prints its verdict + lane digest.  ``--composed``
restricts the catalog to the round-2 frontier (the byz / latency /
composed worlds) for a faster targeted pass with a matching
lower acceptance floor.

``load`` (PR 7) exercises the open-loop traffic plane
(service/traffic.py + service/slo.py + service/loadbench.py): for
each of three traffic seeds it replays a seeded Poisson arrival
schedule at low / knee / saturating offered load (fractions of a
measured closed-loop capacity probe), wall-paced through the
pipelined scheduler with the default SLO classes.  Gates: every
submitted handle reaches a terminal state at every load point (the
harness raises otherwise), and each seed re-driven twice through
VIRTUAL pacing produces the identical arrival AND outcome digests —
load runs are replayable regression tests, like chaos runs.

``pipeline`` (PR 6) replays the acceptance stream at each D in
{1, 2, 4, 8} TWICE — pipelined dispatch (the default) vs the
synchronous beat — after one small untimed warm lap per D, sharing
one sequential baseline, and prints both rows with the
pack/execute/fetch decomposition.  The acceptance gate reads
device-wait frac >= 0.8 from the SYNC row (un-overlapped timing is
the clean serialized measurement) and speedup > the PR-4 5.62x from
the PIPELINED row (the shipped default's wall) — docs/PERF.md §11
has the analysis.

``mesh`` re-runs the acceptance replay served from a lane mesh
(parallel/fleet_mesh.py) at each D in {1, 2, 4, 8} with EQUAL total
lane width (max_batch = 8/D per device) — the PERF §10 serving curve;
8 virtual CPU devices are forced before jax imports, mirroring
tests/conftest.py.

``mesh2d`` (PR 19) sweeps the lanes x peers FACTORIZATIONS of the
same 8 devices — (1,1) solo, (8,1), (4,2), (2,4), (1,8) — with equal
total lane width (max_batch = 8/lanes), over the acceptance stream
plus a peer-SHARDABLE dense tier (n=16 divides both the 4- and 2-wide
peer rungs; the grader's N=10 and the overlay family stay
peer-replicated, so the mixed stream proves both routings serve side
by side bit-identically).  One sequential baseline is shared across
every row.  A peer-shrink elastic leg then serves the stream from the
(2,4) mesh with one seeded device loss + return: the ladder drops a
PEER shard first (lanes keep serving through (2,2)), grows back to
(2,4), and the acceptance gates read zero restarted lanes, full
grow-back, and the first fault seed replayed digest-for-digest —
docs/SERVING.md "2-D capacity".

``chaos`` replays the same acceptance stream under SEEDED fault
schedules (service/faults.py; docs/SERVING.md "Failure model"): for
each fault seed it injects ~``fault_rate`` dispatch-boundary faults
plus one mid-replay device loss (the stream is served from a 2-device
lane mesh when virtual devices allow, so the loss exercises the full
degradation ladder mesh -> single device -> solo), then prints a
completion / degradation / p95 table.  The first seed is replayed
TWICE and its fault-sequence and per-request-outcome digests must
match — chaos runs are regression tests, not flakes.  The sequential
parity baseline is computed once and shared across every row.

``replay`` builds the acceptance stream — the three grader scenario
kinds x two size tiers (the exact dense N=10 course scenarios, plus
their overlay-family analogues at scale) x many seeds, seed-major
interleaved — replays it sequentially and through the service with
all programs pre-warmed, verifies every per-request result
bit-identical to its solo run, and prints the metrics JSON
(speedup vs sequential, p50/p95 latency, mean occupancy, builds per
bucket).  The default 34 seeds/template = 204 requests.  ``sweep``
replays a shorter stream at several ``max_batch`` settings to locate
the serving knee on this backend.

Scripts need PYTHONPATH=/root/repo.  CPU is forced (grading-scale
serving must not dial the accelerator tunnel; the TPU serving recipe
is docs/PERF.md §9).
"""

import json
import os
import sys

if sys.argv[1:2] and sys.argv[1] in ("mesh", "mesh2d", "chaos",
                                     "pipeline", "elastic", "recover"):
    # virtual devices must be forced before jax is first imported
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

if sys.argv[1:2] == ["inspect"]:
    # spill verification is pure numpy + file IO, and the repro this
    # mode backs (CheckpointValidationError) must run on a box with no
    # working accelerator stack — so load store/spill.py by file path,
    # skipping both the package __init__ (which imports jax via
    # .state) and the jax import below
    if len(sys.argv) != 4:
        print("usage: service_smoke.py inspect <run_dir> <digest>")
        raise SystemExit(2)
    import importlib.util
    _p = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "gossip_protocol_tpu", "store", "spill.py")
    _spec = importlib.util.spec_from_file_location("_spill_inspect", _p)
    _mod = importlib.util.module_from_spec(_spec)
    sys.modules[_spec.name] = _mod  # dataclasses resolves __module__
    _spec.loader.exec_module(_mod)
    _v = _mod.inspect_spill(sys.argv[2], sys.argv[3])
    print(json.dumps(_v, indent=1))
    raise SystemExit(0 if _v["ok"] else 1)

import jax

jax.config.update("jax_platforms", "cpu")

from gossip_protocol_tpu.service import (chaos_replay,  # noqa: E402
                                         grader_templates,
                                         overlay_templates, replay)


def _templates(n_overlay: int, t_overlay: int):
    return grader_templates() + overlay_templates(n=n_overlay,
                                                  ticks=t_overlay)


def _replay(seeds: int, n_overlay: int, t_overlay: int,
            max_batch: int = 8) -> dict:
    m = replay(_templates(n_overlay, t_overlay), seeds,
               max_batch=max_batch)
    m["overlay_n"] = n_overlay
    m["overlay_ticks"] = t_overlay
    return m


def main(argv) -> int:
    mode = argv[0] if argv else "replay"
    if mode == "quick":
        seeds = int(argv[1]) if len(argv) > 1 else 4
        # batch width sized to the stream: padding a 2-seed bucket to
        # 8 lanes would be mostly filler work
        m = _replay(seeds, 256, 48, max_batch=min(8, 2 * seeds))
    elif mode == "sweep":
        seeds = int(argv[1]) if len(argv) > 1 else 12
        for b in (2, 4, 8, 16):
            m = _replay(seeds, 512, 96, max_batch=b)
            print(f"max_batch={b:2d}: {m['speedup_vs_sequential']:5.2f}x "
                  f"sequential, occupancy {m['mean_occupancy']:.2f}, "
                  f"p95 {m['latency_p95_s']:.2f}s", flush=True)
        return 0
    elif mode == "mesh":
        from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
        seeds = int(argv[1]) if len(argv) > 1 else 34
        seq = None          # sequential baseline shared across rows
        for d in (1, 2, 4, 8):
            if d > jax.device_count():
                print(f"D={d}: skipped (only {jax.device_count()} "
                      "devices live)", flush=True)
                continue
            mesh = None if d == 1 else make_lane_mesh(d)
            if seq is None:
                m, seq = replay(_templates(512, 96), seeds,
                                max_batch=8 // d, mesh=mesh,
                                return_legs=True)
            else:
                m = replay(_templates(512, 96), seeds, max_batch=8 // d,
                           mesh=mesh, sequential=seq)
            print(f"D={d}: {m['speedup_vs_sequential']:5.2f}x sequential, "
                  f"occupancy {m['mean_occupancy']:.2f}, "
                  f"p95 {m['latency_p95_s']:.2f}s, "
                  f"device-wait frac {m['device_wait_frac']:.2f}",
                  flush=True)
        return 0
    elif mode == "mesh2d":
        from gossip_protocol_tpu.config import SimConfig
        from gossip_protocol_tpu.parallel.fleet_mesh import (
            make_lane_mesh, make_lane_peer_mesh)
        from gossip_protocol_tpu.service import Template, elastic_replay
        seeds = int(argv[1]) if len(argv) > 1 else 34
        if jax.device_count() < 8:
            print(f"mesh2d needs 8 (virtual) devices; only "
                  f"{jax.device_count()} live", flush=True)
            return 2
        # the acceptance stream plus a peer-SHARDABLE dense tier (n=16
        # divides both the 4- and 2-wide peer rungs; the grader's N=10
        # and the overlay family stay peer-replicated, so the mix
        # proves both routings serve side by side)
        tpls = _templates(512, 96) + [
            Template("dense16-drop", SimConfig(
                max_nnb=16, single_failure=False, drop_msg=True,
                msg_drop_prob=0.1, seed=0, total_ticks=60,
                fail_tick=30, rejoin_after=15, drop_open_tick=10,
                drop_close_tick=50))]
        print(f"lanes x peers sweep: {seeds * len(tpls)} requests/row, "
              "equal total lane width (max_batch = 8/lanes)", flush=True)
        seq = None
        rows = {}
        for lanes, peers in ((1, 1), (8, 1), (4, 2), (2, 4), (1, 8)):
            if peers > 1:
                mesh = make_lane_peer_mesh(lanes, peers)
            elif lanes > 1:
                mesh = make_lane_mesh(lanes)
            else:
                mesh = None
            kw = dict(max_batch=8 // lanes, mesh=mesh)
            if seq is None:
                m, seq = replay(tpls, seeds, return_legs=True, **kw)
            else:
                m = replay(tpls, seeds, sequential=seq, **kw)
            rows[(lanes, peers)] = m
            print(f"{lanes}x{peers}: "
                  f"{m['speedup_vs_sequential']:5.2f}x sequential, "
                  f"occupancy {m['mean_occupancy']:.2f}, "
                  f"p95 {m['latency_p95_s']:.2f}s, device-wait frac "
                  f"{m['device_wait_frac']:.2f}", flush=True)
        # ---- the peer-shrink elastic leg -----------------------------
        print("peer-shrink elastic leg ((2,4) -> (2,2) -> grown back):",
              flush=True)
        el_rows = []
        for fseed in (7, 19):
            e = elastic_replay(tpls, seeds_per_template=seeds,
                               max_batch=4,
                               mesh=make_lane_peer_mesh(2, 4),
                               checkpoint_every=48, fault_seed=fseed,
                               sequential=seq)
            el_rows.append(e)
            el = e["elastic"]
            print(f"seed={fseed:3d}: loss@{e['device_loss_at']} "
                  f"return@{e['device_return_at']}, completed "
                  f"{e['completed']}/{e['requests']}, migrated "
                  f"{el['lanes_migrated']}, grows {el['mesh_grows']}, "
                  f"restarted {el['restarted_lanes']}, shape "
                  f"{e['lanes_end']}x{e['peers_end']}, devices "
                  f"{e['devices_start']}->{e['devices_end']}", flush=True)
        e2 = elastic_replay(tpls, seeds_per_template=seeds, max_batch=4,
                            mesh=make_lane_peer_mesh(2, 4),
                            checkpoint_every=48, fault_seed=7,
                            sequential=seq)
        reproduced = (e2["schedule_digest"] == el_rows[0]["schedule_digest"]
                      and e2["outcome_digest"] == el_rows[0]["outcome_digest"])
        zero_restart = all(r["restarted_from_zero"] == 0 for r in el_rows)
        grown = all((r["lanes_end"], r["peers_end"]) == (2, 4)
                    for r in el_rows)
        complete = all(r["completion_rate"] == 1.0 for r in el_rows)
        ok = complete and zero_restart and grown and reproduced
        print(f"acceptance: parity OK (enforced, every row), "
              f"elastic completion=100% "
              f"{'OK' if complete else 'FAIL'}, "
              f"zero restarted-from-zero "
              f"{'OK' if zero_restart else 'FAIL'}, grown back to 2x4 "
              f"{'OK' if grown else 'FAIL'}, seed replay "
              f"{'OK' if reproduced else 'FAIL'} "
              f"(schedule {e2['schedule_digest']}, "
              f"outcomes {e2['outcome_digest']})", flush=True)
        return 0 if ok else 1
    elif mode == "pipeline":
        from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
        seeds = int(argv[1]) if len(argv) > 1 else 34
        tpls = _templates(512, 96)
        seq = None
        rows = {}
        for d in (1, 2, 4, 8):
            if d > jax.device_count():
                print(f"D={d}: skipped (only {jax.device_count()} "
                      "devices live)", flush=True)
                continue
            mesh = None if d == 1 else make_lane_mesh(d)
            # one untimed full-size serving lap per device count first
            # (service leg only — no sequential baseline, no parity):
            # the first lap at a new D pays decaying per-dispatch
            # trace/placement-cache costs that are not steady-state
            # serving behavior — both timed rows below measure warm laps
            from gossip_protocol_tpu.service import FleetService
            from gossip_protocol_tpu.service.replay import (build_trace,
                                                            run_service)
            from gossip_protocol_tpu.service.replay import warm as _warm
            trace_w = build_trace(tpls, seeds)
            svc_w = FleetService(max_batch=8 // d, mesh=mesh)
            _warm(trace_w, svc_w)
            run_service(trace_w, service=svc_w)
            for pipe in (False, True):
                kw = dict(max_batch=8 // d, mesh=mesh, pipeline=pipe)
                if seq is None:
                    m, seq = replay(tpls, seeds, return_legs=True, **kw)
                else:
                    m = replay(tpls, seeds, sequential=seq, **kw)
                rows[(d, pipe)] = m
                tag = "pipelined" if pipe else "sync     "
                print(f"D={d} {tag}: {m['speedup_vs_sequential']:5.2f}x "
                      f"sequential, device-wait frac "
                      f"{m['device_wait_frac']:.2f} "
                      f"(pack {1e3 * m['mean_pack_s']:5.1f}ms / exec "
                      f"{1e3 * m['mean_device_wait_s']:6.1f}ms / fetch "
                      f"{1e3 * m['mean_fetch_s']:5.1f}ms), "
                      f"p95 {m['latency_p95_s']:.2f}s", flush=True)
        d_max = max(d for d, _ in rows)
        # frac gate reads the SYNC row (un-overlapped timing is the
        # clean serialized measurement; the pipelined row measures its
        # hidden host columns at contended values), speedup gate reads
        # the pipelined row (the shipped default's wall)
        frac = rows[(d_max, False)]["device_wait_frac"]
        speedup = rows[(d_max, True)]["speedup_vs_sequential"]
        ok = frac >= 0.8 and speedup > 5.62
        print(f"acceptance (D={d_max}): device-wait frac {frac:.2f} "
              f"{'OK' if frac >= 0.8 else 'FAIL'} (>=0.8, sync row), "
              f"pipelined speedup {speedup:.2f}x "
              f"{'OK' if speedup > 5.62 else 'FAIL'} (>5.62x), "
              f"parity OK (enforced)", flush=True)
        # ---- the ring-depth sweep (PR 17) ----------------------------
        # closed-loop replay at depth 1/2/4 (single device, shared
        # sequential baseline — depth changes resolution order, not
        # results: parity is enforced per row), then the open-loop
        # ladder at the same depths (loadbench.depth_ladder: one
        # capacity anchor, identical schedules per point).  The PR 17
        # gate: depth 2 must hold off open-loop saturation at least as
        # long as depth 1.
        from gossip_protocol_tpu.service.loadbench import (
            default_slo, depth_ladder, effective_saturation,
            load_catalog)
        print("\npipeline_depth sweep (single device, closed-loop "
              "replay):", flush=True)
        for depth in (1, 2, 4):
            m = replay(tpls, seeds, sequential=seq, max_batch=8,
                       pipeline_depth=depth)
            print(f"depth={depth}: {m['speedup_vs_sequential']:5.2f}x "
                  f"sequential, ring stalls {m['ring_stalls']}, "
                  f"p95 {m['latency_p95_s']:.2f}s", flush=True)
        ladder = depth_ladder(load_catalog(n=256, ticks=48),
                              n_probe=16, n_point=24, seed=20260807,
                              slo=default_slo(),
                              fracs=(0.5, 1.0, 1.5, 2.0))
        sat = {}
        for row in ladder["rows"]:
            sat[row["depth"]] = effective_saturation(row)
            s = row["saturation_offered_rps"]
            print(f"depth={row['depth']}: open-loop saturation "
                  f"{'none (absorbed all)' if s is None else f'{s} rps'}"
                  f", max achieved {row['max_achieved_rps']} rps, "
                  f"closed-loop {row['closed_loop_rps']} rps",
                  flush=True)
        depth_ok = sat.get(2, 0.0) >= sat.get(1, 0.0)
        print(f"acceptance (depth sweep): depth-2 saturation >= "
              f"depth-1 {'OK' if depth_ok else 'FAIL'}", flush=True)
        ok = ok and depth_ok
        return 0 if ok else 1
    elif mode == "chaos":
        from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
        seeds = int(argv[1]) if len(argv) > 1 else 34
        rate = float(argv[2]) if len(argv) > 2 else 0.12
        mesh_d = 2 if jax.device_count() >= 2 else 1
        tpls = _templates(512, 96)
        print(f"chaos sweep: {seeds * len(tpls)} requests/seed, "
              f"fault_rate={rate}, mesh D={mesh_d} + one device loss",
              flush=True)
        seq = None
        rows = []
        for i, fseed in enumerate((7, 19, 23)):
            mesh = make_lane_mesh(mesh_d) if mesh_d > 1 else None
            kw = dict(seeds_per_template=seeds, max_batch=8 // mesh_d,
                      mesh=mesh, fault_seed=fseed, fault_rate=rate)
            if seq is None:
                m, seq = chaos_replay(tpls, return_legs=True, **kw)
            else:
                m = chaos_replay(tpls, sequential=seq, **kw)
            rows.append(m)
            fs = m["faults"]
            print(f"seed={fseed:3d}: faults={fs['total']:2d} "
                  f"(c{fs['compile']}/d{fs['dispatch']}/l{fs['latency']}"
                  f"/p{fs['poison']}/D{fs['device_loss']}), "
                  f"completed {m['completed']}/{m['requests']}, "
                  f"degraded {m['degraded_requests']}, "
                  f"retries {m['failures']['retries']}, "
                  f"devices {m['devices_start']}->{m['devices_end']}, "
                  f"p95 {m['latency_p95_s']:.2f}s, "
                  f"{m['speedup_vs_sequential']:.2f}x sequential",
                  flush=True)
        # replayability: the first seed again, digest-for-digest
        mesh = make_lane_mesh(mesh_d) if mesh_d > 1 else None
        m2 = chaos_replay(tpls, seeds_per_template=seeds,
                          max_batch=8 // mesh_d, mesh=mesh, fault_seed=7,
                          fault_rate=rate, sequential=seq)
        reproduced = (m2["schedule_digest"] == rows[0]["schedule_digest"]
                      and m2["outcome_digest"] == rows[0]["outcome_digest"])
        ok = (all(r["completion_rate"] == 1.0 for r in rows)
              and reproduced)
        print(f"acceptance: completion=100% "
              f"{'OK' if all(r['completion_rate'] == 1.0 for r in rows) else 'FAIL'}, "
              f"0 stranded OK (enforced), parity OK (enforced), "
              f"seed replay {'OK' if reproduced else 'FAIL'} "
              f"(schedule {m2['schedule_digest']}, "
              f"outcomes {m2['outcome_digest']})", flush=True)
        return 0 if ok else 1
    elif mode == "elastic":
        from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
        from gossip_protocol_tpu.service import elastic_replay
        seeds = int(argv[1]) if len(argv) > 1 else 34
        every = int(argv[2]) if len(argv) > 2 else 48
        mesh_d = 2 if jax.device_count() >= 2 else 1
        tpls = _templates(512, 96)
        print(f"elastic sweep: {seeds * len(tpls)} requests/seed, "
              f"checkpoint_every={every}, mesh D={mesh_d}, one device "
              "loss + one device return", flush=True)
        seq = None
        rows = []
        for fseed in (7, 19, 23):
            mesh = make_lane_mesh(mesh_d) if mesh_d > 1 else None
            kw = dict(seeds_per_template=seeds, max_batch=8 // mesh_d,
                      mesh=mesh, checkpoint_every=every,
                      fault_seed=fseed)
            if seq is None:
                m, seq = elastic_replay(tpls, return_legs=True, **kw)
            else:
                m = elastic_replay(tpls, sequential=seq, **kw)
            rows.append(m)
            el = m["elastic"]
            print(f"seed={fseed:3d}: loss@{m['device_loss_at']} "
                  f"return@{m['device_return_at']}, completed "
                  f"{m['completed']}/{m['requests']}, mean legs "
                  f"{m['mean_legs']:.2f}, checkpoints "
                  f"{el['checkpoints_taken']}, migrated "
                  f"{el['lanes_migrated']}, grows {el['mesh_grows']}, "
                  f"restarted {el['restarted_lanes']}, rekey hits "
                  f"{m['cache_rekey_hits']}, devices "
                  f"{m['devices_start']}->{m['devices_end']}, "
                  f"{m['speedup_vs_sequential']:.2f}x sequential",
                  flush=True)
        mesh = make_lane_mesh(mesh_d) if mesh_d > 1 else None
        m2 = elastic_replay(tpls, seeds_per_template=seeds,
                            max_batch=8 // mesh_d, mesh=mesh,
                            checkpoint_every=every, fault_seed=7,
                            sequential=seq)
        reproduced = (m2["schedule_digest"] == rows[0]["schedule_digest"]
                      and m2["outcome_digest"] == rows[0]["outcome_digest"])
        zero_restart = all(r["restarted_from_zero"] == 0 for r in rows)
        ok = (all(r["completion_rate"] == 1.0 for r in rows)
              and zero_restart and reproduced)
        print(f"acceptance: completion=100% "
              f"{'OK' if all(r['completion_rate'] == 1.0 for r in rows) else 'FAIL'}, "
              f"zero restarted-from-zero "
              f"{'OK' if zero_restart else 'FAIL'}, loss+return "
              "injected OK (enforced), parity OK (enforced), "
              f"seed replay {'OK' if reproduced else 'FAIL'} "
              f"(schedule {m2['schedule_digest']}, "
              f"outcomes {m2['outcome_digest']})", flush=True)
        return 0 if ok else 1
    elif mode == "recover":
        from gossip_protocol_tpu.store.harness import kill_restart_replay
        seeds = int(argv[1]) if len(argv) > 1 else 34
        every = int(argv[2]) if len(argv) > 2 else 48
        n, t = 512, 96
        print(f"kill/restart sweep: {seeds * 6} requests/run, "
              f"checkpoint_every={every}, subprocess killed at three "
              "points of the dispatch schedule, recovered here",
              flush=True)
        baseline = None
        rows = []
        for frac in (0.25, 0.55, 0.8):
            # raises on ANY gate violation (double service, incomplete
            # set, restarted lanes, digest mismatch) — a printed row
            # already passed; the acceptance line below re-checks
            m, baseline = kill_restart_replay(
                seeds_per_template=seeds, n_overlay=n, t_overlay=t,
                checkpoint_every=every, kill_frac=frac,
                baseline=baseline)
            rows.append(m)
            dur = m["durability"]
            print(f"kill@{frac:.2f} (dispatch "
                  f"{m['kill_after_dispatches']}/"
                  f"{m['baseline_dispatches']}): completed "
                  f"{m['completed']}/{m['requests']} "
                  f"({m['completed_before_kill']} pre-kill + "
                  f"{m['recovered_requests']} recovered), restarted "
                  f"{m['restarted_lanes']}, spills {dur['spills']} "
                  f"({dur['spill_bytes']} B), reloads "
                  f"{dur['reloads']}, outcomes {m['outcome_digest']}",
                  flush=True)
        complete = all(r["completion_rate"] == 1.0 for r in rows)
        zero_restart = all(r["restarted_lanes"] == 0 for r in rows)
        parity = all(r["outcome_digest"] == r["baseline_digest"]
                     for r in rows)
        ok = complete and zero_restart and parity
        print(f"acceptance: completion=100% "
              f"{'OK' if complete else 'FAIL'}, zero restarted-from-"
              f"zero {'OK' if zero_restart else 'FAIL'}, cross-"
              f"process digest parity {'OK' if parity else 'FAIL'} "
              f"(baseline {rows[0]['baseline_digest']})", flush=True)
        return 0 if ok else 1
    elif mode == "scenario":
        from gossip_protocol_tpu.models import scenarios
        opts = dict(zip(argv[1::2], argv[2::2]))
        fam = opts.get("--family")
        if fam not in scenarios.CATALOG:
            print(f"unknown family {fam!r}; one of "
                  f"{sorted(scenarios.CATALOG)}")
            return 2
        seed = int(opts.get("--seed", 1000))
        claim = scenarios.CATALOG[fam].claim
        print(f"{fam}/{seed}: {claim}", flush=True)
        violations, digest = scenarios.run_solo(fam, seed)
        print(f"lane digest {digest}")
        if violations:
            for v in violations:
                print(f"  VIOLATION: {v}")
            return 1
        print("oracle PASS")
        return 0
    elif mode == "scenarios":
        from gossip_protocol_tpu.models import scenarios
        composed = "--composed" in argv[1:]
        rest = [a for a in argv[1:] if a != "--composed"]
        seeds = int(rest[0]) if rest else 40
        fams = sorted(scenarios.CATALOG)
        if composed:
            # the round-2 frontier only: byz / latency planes and the
            # composed storms (worlds.composition)
            fams = [f for f in fams
                    if scenarios.CATALOG[f].world
                    in ("byz", "latency", "composed")]
        n_fam = len(fams)
        floor = 200 if composed else 1000
        print(f"scenario sweep{' (composed frontier)' if composed else ''}: "
              f"{n_fam} families x {seeds} seeds = "
              f"{n_fam * seeds} variants, one FleetService run",
              flush=True)
        r = scenarios.sweep(families=fams, seeds_per_family=seeds)
        for name in sorted(r["per_family"]):
            pf = r["per_family"][name]
            print(f"  {name:26s} pass {pf['pass']:3d} / "
                  f"fail {pf['fail']:3d}   {scenarios.CATALOG[name].claim}",
                  flush=True)
        print(f"{r['variants']} variants in {r['wall_s']:.1f}s, "
              f"{r['dispatches']} dispatches over {r['buckets']} buckets, "
              f"occupancy {r['mean_occupancy']:.2f}", flush=True)
        r2 = scenarios.sweep(families=fams, seeds_per_family=seeds)
        reproduced = (r2["verdict_digest"] == r["verdict_digest"]
                      and r2["outcome_digest"] == r["outcome_digest"])
        ok = (r["pass_rate"] == 1.0 and r["terminal_rate"] == 1.0
              and reproduced)
        print(f"acceptance: {r['variants']} variants "
              f"{'OK' if r['variants'] >= floor else 'FAIL'} "
              f"(>={floor}), "
              f"100% terminal OK (enforced), oracle pass rate "
              f"{r['pass_rate']:.4f} "
              f"{'OK' if r['pass_rate'] == 1.0 else 'FAIL'}, "
              f"seed replay {'OK' if reproduced else 'FAIL'} "
              f"(verdicts {r['verdict_digest']}, "
              f"outcomes {r['outcome_digest']})", flush=True)
        return 0 if ok else 1
    elif mode == "load":
        from gossip_protocol_tpu.service.loadbench import (
            load_catalog, measure_point, probe_capacity_rps,
            replay_check)
        from gossip_protocol_tpu.service.slo import default_slo
        n_req = int(argv[1]) if len(argv) > 1 else 24
        tpls = load_catalog(n=256, ticks=48)
        slo = default_slo()
        cap = probe_capacity_rps(tpls, n_requests=16)
        print(f"open-loop sweep: capacity probe {cap:.2f} rps, "
              f"{n_req} requests/point, classes "
              f"{sorted(slo.classes)}", flush=True)
        ok = True
        for fseed in (7, 19, 23):
            for name, frac in (("low", 0.3), ("knee", 0.75),
                               ("saturating", 1.6)):
                # measure_point raises on any non-terminal handle or
                # non-deadline failure — returning IS the 100%-
                # terminal gate
                r = measure_point(tpls, n_req, rate_rps=cap * frac,
                                  seed=fseed, slo=slo)
                print(f"seed={fseed:3d} {name:10s}: offered "
                      f"{r['offered_rps']:6.2f} rps -> achieved "
                      f"{r['achieved_rps']:6.2f}, p50/p99 "
                      f"{r['latency_p50_s']:.2f}/"
                      f"{r['latency_p99_s']:.2f}s, miss rate "
                      f"{r['deadline_miss_rate']:.2f}, occupancy "
                      f"{r['mean_occupancy']:.2f}, early flushes "
                      f"{r['slo_early_flushes']}, lag "
                      f"{r['max_lag_s']:.2f}s", flush=True)
            rc = replay_check(tpls, n_req, rate_rps=cap * 0.75,
                              seed=fseed, slo=slo)
            ok = ok and rc["deterministic"]
            print(f"seed={fseed:3d} replay: arrival "
                  f"{rc['arrival_digest']}, outcomes "
                  f"{rc['outcome_digest']}, deterministic "
                  f"{'OK' if rc['deterministic'] else 'FAIL'}",
                  flush=True)
        print(f"acceptance: 100% terminal OK (enforced), seed replay "
              f"{'OK' if ok else 'FAIL'} (identical arrival+outcome "
              "digests across two virtual-paced runs/seed)", flush=True)
        return 0 if ok else 1
    elif mode == "replay":
        seeds = int(argv[1]) if len(argv) > 1 else 34
        n = int(argv[2]) if len(argv) > 2 else 512
        t = int(argv[3]) if len(argv) > 3 else 96
        m = _replay(seeds, n, t)
    else:
        print(__doc__)
        return 2
    print(json.dumps(m, indent=1))
    ok = (m["speedup_vs_sequential"] >= 2.0
          and m["mean_occupancy"] >= 0.75
          and m["max_builds_per_bucket"] <= 1)
    print(f"acceptance: speedup>=2x "
          f"{'OK' if m['speedup_vs_sequential'] >= 2.0 else 'FAIL'}, "
          f"occupancy>=0.75 "
          f"{'OK' if m['mean_occupancy'] >= 0.75 else 'FAIL'}, "
          f"<=1 build/bucket "
          f"{'OK' if m['max_builds_per_bucket'] <= 1 else 'FAIL'}, "
          f"parity OK (checked)", flush=True)
    return 0 if (ok or mode == "quick") else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
