"""Time the megakernel run path vs the per-tick paths on the live TPU.

Usage: python scripts/mega_probe.py [N] [ticks]
"""

import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_run,
                                                make_overlay_schedule,
                                                resolved_dims)


def time_run(run, state, sched, reps=3):
    variants = [state.replace(own_hb=state.own_hb + i)
                for i in range(reps + 1)]
    np.asarray(jax.block_until_ready(run(variants[0], sched)[0]).tick)
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.block_until_ready(run(variants[i + 1], sched)[0]).tick)
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    ticks = int(sys.argv[2]) if len(sys.argv) > 2 else 320
    cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                    drop_msg=False, seed=0, total_ticks=ticks,
                    churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)
    print(f"backend={jax.default_backend()} N={n} K,F={resolved_dims(cfg)} "
          f"T={ticks}", flush=True)
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    from gossip_protocol_tpu.models.overlay_mega import mega_supported
    print("mega_supported:", mega_supported(cfg), flush=True)

    for label, up in (("mega", True), ("per-tick", False)):
        if up and not mega_supported(cfg):
            continue
        run = make_overlay_run(cfg, ticks, use_pallas=up)
        dt = time_run(run, state, sched) / ticks
        print(f"{label:9s}: {dt*1e6:9.1f} us/tick -> {1/dt:8.0f} ticks/s "
              f"({n/dt/1e6:9.1f}M node-ticks/s)", flush=True)


if __name__ == "__main__":
    main()
