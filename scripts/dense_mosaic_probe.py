"""Probe Mosaic capabilities the dense megakernel needs (dev tool):
  a. jnp.dot (512, 512) @ (512, 512) f32 inside the kernel (MXU)
  b. the level-descend masked-max merge with its (R, J) loop state in
     scratch REFS and a scalar-only while carry (big vector carries
     fail to legalize: 'scf.yield' with ~750 vreg operands)
  c. 2D transpose of an (N, N) i32 plane
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gossip_protocol_tpu.ops.pallas import tpu_compiler_params

sys.path.insert(0, ".")


def _kernel(n, d_ref, v_ref, out_ref, tr_ref, cur_ref):
    d = d_ref[:].astype(jnp.float32)
    v = v_ref[:]

    # init: out=0, done = false encoded via out sign? keep done in out:
    # use out_ref for m and track done as (m > 0) | (cur == 0) — but m
    # can legitimately stay 0 for receivers with no contribution, so
    # keep an explicit done plane in the spare of tr_ref until the end.
    out_ref[:] = jnp.zeros((n, n), jnp.int32)
    tr_ref[:] = jnp.zeros((n, n), jnp.int32)      # done plane (0/1)
    cur_ref[0:1, :] = v.max(axis=0, keepdims=True)

    def cond(go):
        return go

    def body(go):
        cur = cur_ref[0:1, :]
        w = ((v == cur) & (cur > 0)).astype(jnp.float32)
        hit = jax.lax.dot_general(d, w, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32) > 0
        done = tr_ref[:] > 0
        newly = hit & ~done
        out_ref[:] = jnp.where(newly, jnp.broadcast_to(cur, (n, n)),
                               out_ref[:])
        done = done | newly | jnp.broadcast_to(cur == 0, (n, n))
        tr_ref[:] = done.astype(jnp.int32)
        v_lt = jnp.where(v < cur, v, 0)
        nxt = v_lt.max(axis=0, keepdims=True)
        cur_ref[0:1, :] = nxt
        more = (~done).any() & (nxt > 0).any()
        return more

    jax.lax.while_loop(cond, body, jnp.asarray(True))
    tr_ref[:] = jnp.transpose(d_ref[:])


@functools.partial(jax.jit, static_argnames=("interpret",))
def probe(d, v, *, interpret: bool):
    n = d.shape[0]
    return pl.pallas_call(
        functools.partial(_kernel, n),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((n, n), jnp.int32),
                   jax.ShapeDtypeStruct((n, n), jnp.int32)],
        scratch_shapes=[pltpu.VMEM((8, n), jnp.int32)],
        compiler_params=tpu_compiler_params(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(d, v)


def main():
    n = 512
    rng = np.random.RandomState(0)
    d = (rng.rand(n, n) < 0.7).astype(np.int32)
    v = rng.randint(0, 40, (n, n)).astype(np.int32)
    ref = np.zeros((n, n), np.int32)
    for r in range(n):
        sel = d[r] > 0
        ref[r] = np.where(sel.any(), np.max(np.where(sel[:, None], v, 0), 0),
                          0)
    modes = [True] if jax.default_backend() != "tpu" else [True, False]
    for interpret in modes:
        t0 = time.time()
        out, tr = probe(jnp.asarray(d), jnp.asarray(v), interpret=interpret)
        out, tr = np.asarray(out), np.asarray(tr)
        ok = np.array_equal(out, ref) and np.array_equal(tr, d.T)
        print(f"interpret={interpret}: {'OK' if ok else 'MISMATCH'} "
              f"({time.time()-t0:.1f}s)", flush=True)
        if not ok:
            print("max ok:", np.array_equal(out, ref),
                  "transpose ok:", np.array_equal(tr, d.T))
            sys.exit(1)
    print("dense mosaic probes passed")


if __name__ == "__main__":
    main()
