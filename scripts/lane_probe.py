"""Probe: do 64-lane (B, K) vector ops waste half of every vreg, and
can two exchange rounds share full-width vregs via lane-concat?

The grid kernel's per-round merge chain operates on (B, K=64) i32
operands.  If Mosaic pads the minor dim to the native 128-lane tile,
each such op costs the same vregs as a (B, 128) op — and packing TWO
rounds side by side into (B, 128) would halve the merge-phase op
count, IF the lane-concat of two 64-lane halves is cheap and accepted
(a direct vector bitcast repack was rejected by this Mosaic:
"Invalid vector register cast", docs/PERF.md §3).

Three timed kernels, each running ITERS repetitions of an F-round
merge-like chain (~20 ops/round of the grid kernel's op mix) inside
one launch:
  narrow — per round, ops on (B, 64) operands (the grid kernel today)
  wide   — same op count on (B, 128) operands (cost ceiling check)
  packed — rounds in pairs: concat halves to (B, 128), one chain per
           pair, fold the two halves at the end with a lane roll

Usage: python scripts/lane_probe.py [B] [F] [ITERS]
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")

K = 64


def _chain(x, y, t):
    """~20-op merge-like chain (compares, selects, shifts, a cheap
    hash) on same-shape i32 operands."""
    xu = x.astype(jnp.uint32)
    yu = y.astype(jnp.uint32)
    h = (xu ^ (yu >> 7)) * jnp.uint32(0x9E3779B9)
    h = h ^ (h >> 15)
    valid = (x >= 0) & (y > t) & (x != y)
    key = jnp.where(valid, (yu << 12) | (xu & 0xFFF), jnp.uint32(0))
    pay = jnp.where(valid, y + 1, 0)
    better = key > (h & jnp.uint32(0x00FFFFFF))
    k2 = jnp.where(better, key, h)
    p2 = jnp.where(better, pay, x)
    stale = (k2 < (jnp.uint32(5) << 12)) & (p2 > 0)
    return jnp.where(stale, 0, k2).astype(jnp.int32), \
        jnp.where(stale, -1, p2)


def _kernel(mode: str, f: int, iters: int, x_ref, o_ref, acc_ref):
    b = x_ref.shape[0]

    def body(s, _):
        t = s & 7
        if mode == "narrow":
            ka = x_ref[:, 0:K]
            pa = x_ref[:, K:2 * K]
            for fi in range(f):
                xin = x_ref[:, 0:K] + (s + fi)
                yin = x_ref[:, K:2 * K] ^ fi
                k1, p1 = _chain(xin, yin, t)
                sel = k1 > ka
                ka = jnp.where(sel, k1, ka)
                pa = jnp.where(sel, p1, pa)
            acc_ref[:, 0:K] = ka
            acc_ref[:, K:2 * K] = pa
        elif mode == "wide":
            ka = x_ref[:]
            pa = x_ref[:]
            for fi in range(f):
                xin = x_ref[:] + (s + fi)
                yin = x_ref[:] ^ fi
                k1, p1 = _chain(xin, yin, t)
                sel = k1 > ka
                ka = jnp.where(sel, k1, ka)
                pa = jnp.where(sel, p1, pa)
            acc_ref[:] = ka + pa
        else:                                  # packed
            ka = x_ref[:, 0:K]
            pa = x_ref[:, K:2 * K]
            for fi in range(0, f, 2):
                xin = jnp.concatenate(
                    [x_ref[:, 0:K] + (s + fi), x_ref[:, 0:K] + (s + fi + 1)],
                    axis=1)
                yin = jnp.concatenate(
                    [x_ref[:, K:2 * K] ^ fi, x_ref[:, K:2 * K] ^ (fi + 1)],
                    axis=1)
                k1, p1 = _chain(xin, yin, t)
                # fold the two 64-lane halves: lane-roll by K then lex
                k1r = jnp.concatenate([k1[:, K:], k1[:, :K]], axis=1)
                p1r = jnp.concatenate([p1[:, K:], p1[:, :K]], axis=1)
                sel2 = k1r > k1
                kf = jnp.where(sel2, k1r, k1)[:, 0:K]
                pf = jnp.where(sel2, p1r, p1)[:, 0:K]
                sel = kf > ka
                ka = jnp.where(sel, kf, ka)
                pa = jnp.where(sel, pf, pa)
            acc_ref[:, 0:K] = ka
            acc_ref[:, K:2 * K] = pa
        return ()

    jax.lax.fori_loop(0, iters, body, (), unroll=False)
    o_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("mode", "f", "iters",
                                             "interpret"))
def probe(x, *, mode: str, f: int, iters: int, interpret: bool = False):
    b = x.shape[0]
    return pl.pallas_call(
        functools.partial(_kernel, mode, f, iters),
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 2 * K), jnp.int32),
        scratch_shapes=[pltpu.VMEM((b, 2 * K), jnp.int32)],
        interpret=interpret,
    )(x)


def main():
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    f = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 2000
    assert f % 2 == 0, "packed mode pairs rounds; use an even F"
    print(f"backend={jax.default_backend()} B={b} F={f} iters={iters}",
          flush=True)
    rng = np.random.default_rng(0)
    for mode in ("narrow", "wide", "packed"):
        try:
            xs = [jnp.asarray(rng.integers(-4, 1 << 20, (b, 2 * K)),
                              jnp.int32) for _ in range(4)]
            out = jax.block_until_ready(
                probe(xs[0], mode=mode, f=f, iters=iters))
            np.asarray(out)
            best = 1e9
            for i in (1, 2, 3):
                t0 = time.perf_counter()
                out = jax.block_until_ready(
                    probe(xs[i], mode=mode, f=f, iters=iters))
                np.asarray(out)
                best = min(best, time.perf_counter() - t0)
            per_round = best / iters / f * 1e6
            print(f"{mode:7s}  {best:7.4f}s  {per_round:6.3f} us/round",
                  flush=True)
        except Exception as e:  # noqa: BLE001 — probing compiler limits
            print(f"{mode:7s}  REJECTED: {str(e)[:200]}", flush=True)


if __name__ == "__main__" and not (len(sys.argv) > 1
                                   and sys.argv[1] == "col"):
    main()


def _colchain(x, t):
    """~24-op per-row decision chain (sched_of/drop-hash-like mix)."""
    xu = x.astype(jnp.uint32)
    h = (xu ^ (jnp.uint32(t) + jnp.uint32(0x85EBCA6B))) \
        * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0x27D4EB2F)
    h = h ^ (h >> 16)
    fail = jnp.where(h < jnp.uint32(1 << 29), (x & 1023) + 7, 1 << 30)
    rejoin = jnp.where(fail < (1 << 30), fail + 40, 1 << 30)
    failed = (t > fail) & (t <= rejoin)
    ramp = x * 3
    proc = (ramp < t * 4) & ~failed
    at_start = (ramp >= t * 4) & (ramp < (t + 1) * 4)
    g = (h >> 5) < jnp.uint32(1 << 28)
    out = jnp.where(proc & ~g, x + 1, x)
    return jnp.where(at_start, out + 2, out)


def _colkernel(mode: str, iters: int, x_ref, o_ref):
    b = x_ref.shape[0]

    def body(s, _):
        if mode == "col":
            v = x_ref[:, 0:1] + s
            for _ in range(4):
                v = _colchain(v, s & 15)
            o_ref[:, 0:1] = v
        else:                               # flat (b/128, 128)
            v = x_ref[:].reshape(b // 128, 128) + s
            for _ in range(4):
                v = _colchain(v, s & 15)
            o_ref[:] = v.reshape(b, 1)
        return ()

    jax.lax.fori_loop(0, iters, body, (), unroll=False)


@functools.partial(jax.jit, static_argnames=("mode", "iters", "interpret"))
def colprobe(x, *, mode: str, iters: int, interpret: bool = False):
    b = x.shape[0]
    return pl.pallas_call(
        functools.partial(_colkernel, mode, iters),
        grid=(1,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.int32),
        interpret=interpret,
    )(x)


def colmain():
    b = int(sys.argv[2]) if len(sys.argv) > 2 else 512
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 50000
    print(f"backend={jax.default_backend()} B={b} iters={iters} "
          f"(4 chains of ~24 col ops per iter)", flush=True)
    rng = np.random.default_rng(0)
    for mode in ("col", "flat"):
        try:
            xs = [jnp.asarray(rng.integers(0, 1 << 20, (b, 1)), jnp.int32)
                  for _ in range(4)]
            out = jax.block_until_ready(colprobe(xs[0], mode=mode,
                                                 iters=iters))
            np.asarray(out)
            best = 1e9
            for i in (1, 2, 3):
                t0 = time.perf_counter()
                out = jax.block_until_ready(
                    colprobe(xs[i], mode=mode, iters=iters))
                np.asarray(out)
                best = min(best, time.perf_counter() - t0)
            print(f"{mode:5s}  {best:7.4f}s  "
                  f"{best / iters * 1e6:7.3f} us/iter", flush=True)
        except Exception as e:  # noqa: BLE001 — probing compiler limits
            print(f"{mode:5s}  REJECTED: {str(e)[:200]}", flush=True)


if __name__ == "__main__" and len(sys.argv) > 1 and sys.argv[1] == "col":
    colmain()
