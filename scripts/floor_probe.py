"""Decompose the per-tick wall-clock floor on the live backend.

Times, through identical lax.scan harnesses:
  empty    — a trivial carry bump (the scan-step floor itself)
  kernel   — only the fused Pallas launch per step
  vectors  — only the non-kernel (N,)/(K,N) vector phases
  full     — the whole overlay tick

Development tool for the round-3 "break the 2-3 ms/tick floor" work
(VERDICT.md task 1).  Usage: python scripts/floor_probe.py [N]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_schedule,
                                                make_overlay_tick,
                                                resolved_dims)


def scan_time(step_fn, carry, reps=3, length=200):
    @jax.jit
    def scanned(c):
        return jax.lax.scan(lambda c, _: (step_fn(c), None), c, None,
                            length=length)[0]

    variants = [jax.tree.map(lambda x: x + i if x.dtype != bool else x, carry)
                for i in range(reps + 1)]
    jax.block_until_ready(scanned(variants[0]))
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(scanned(variants[i + 1]))
        best = min(best, time.perf_counter() - t0)
    return best / length


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    print("backend:", jax.default_backend(), flush=True)
    cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                    drop_msg=False, seed=0, total_ticks=300,
                    churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)
    k, f = resolved_dims(cfg)
    print(f"N={n} K={k} F={f}")
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    length = 200 if n <= (1 << 17) else 25

    # 1. empty scan floor
    dt = scan_time(lambda c: c + 1, jnp.int32(0), length=length)
    print(f"empty    step: {dt*1e6:9.1f} us", flush=True)

    # 2. kernel-only scan
    from gossip_protocol_tpu.ops.pallas.overlay_exchange import (
        fused_overlay_tick)
    i32 = jnp.int32
    idsaux = jnp.zeros((n, k + 2 + f), i32)
    pw = jnp.zeros((n, k), i32)
    intro = jnp.zeros((8, k), i32)
    masks = jnp.arange(1, f + 1, dtype=i32)
    scalars = jnp.zeros((8,), i32).at[0].set(5)

    def kstep(c):
        ids2, hb2, ts2, ctr = fused_overlay_tick(
            c["a"], c["p"], intro, masks, scalars, k=k, t_remove=cfg.t_remove,
            churn_lo=cfg.total_ticks // 4,
            churn_span=max(cfg.total_ticks // 2, 1))
        return {"a": c["a"].at[:, :k].max(ids2), "p": jnp.maximum(c["p"], ts2)}

    dt = scan_time(lambda c: kstep(c), {"a": idsaux, "p": pw}, length=length)
    print(f"kernel   step: {dt*1e6:9.1f} us", flush=True)

    # 3. full tick (pallas path)
    tick = make_overlay_tick(cfg, use_pallas=True)

    def fstep(s):
        return tick(s, sched)[0]

    variants = [state.replace(own_hb=state.own_hb + i) for i in range(4)]

    @jax.jit
    def scanned(s):
        return jax.lax.scan(lambda c, _: (tick(c, sched)[0], None), s, None,
                            length=length)[0]

    np.asarray(jax.block_until_ready(scanned(variants[0])).tick)
    best = float("inf")
    for i in range(3):
        t0 = time.perf_counter()
        np.asarray(jax.block_until_ready(scanned(variants[i + 1])).tick)
        best = min(best, time.perf_counter() - t0)
    dt = best / length
    print(f"full     tick: {dt*1e6:9.1f} us -> {1/dt:8.0f} ticks/s",
          flush=True)

    # 4. xla path
    tick_x = make_overlay_tick(cfg, use_pallas=False)

    @jax.jit
    def scanned_x(s):
        return jax.lax.scan(lambda c, _: (tick_x(c, sched)[0], None), s, None,
                            length=length)[0]

    np.asarray(jax.block_until_ready(scanned_x(variants[0])).tick)
    best = float("inf")
    for i in range(3):
        t0 = time.perf_counter()
        np.asarray(jax.block_until_ready(scanned_x(variants[i + 1])).tick)
        best = min(best, time.perf_counter() - t0)
    dt = best / length
    print(f"xla      tick: {dt*1e6:9.1f} us -> {1/dt:8.0f} ticks/s",
          flush=True)


if __name__ == "__main__":
    main()
