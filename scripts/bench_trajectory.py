"""Bench trajectory: one regression table over every BENCH_pr*.json.

The per-PR bench jsons each hold a snapshot; reading the series means
opening five+ files and hunting for the comparable keys.  This script
folds them into one table — headline node-ticks/s, fleet batching
speedup, serving replay speedup (best recorded: mixed / mesh / the
204-request curve's top row), p95 latency, device-wait fraction, the
chaos gate, the open-loop load columns (max achieved rps + measured
saturation point, PR 7+), the scenario-frontier columns (variants
graded + oracle pass rate, PR 9+), the durable-serving columns
(kill/restart completion + spill volume, PR 12+), and the
static-analysis columns (findings + rule-inventory size recorded by
``bench --check``, PR 14+; older jsons without an entry render "-"),
and the compile-surface columns (exact vs canonical bucket
cardinality, fresh-build collapse, warm-lap hit rate, PR 16+),
and the pipeline-depth columns (the best replay row's
pipeline_depth plus the depth-sweep's measured open-loop saturation
at depth 2, PR 17+), and the 2-D mesh columns (the best lanes x peers
serving row plus the peer-shrink elastic gate — restarted lanes and
grow-back shape, PR 19+) — so a regression (or a claimed win) is
visible at a glance, PR over PR.

    PYTHONPATH=. python scripts/bench_trajectory.py          # table
    PYTHONPATH=. python scripts/bench_trajectory.py --json   # rows

Pure host-side JSON reading: no jax import, safe on any machine.
"""

import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get(d: dict, *path, default=None):
    for p in path:
        if not isinstance(d, dict) or p not in d:
            return default
        d = d[p]
    return d


def _best_replay(sec: dict):
    """Best recorded serving-replay row in one json: (speedup, p95,
    device_wait_frac, requests, tag, pipeline_depth).  Older jsons'
    rows predate the pipeline_depth field (PR 17) — it rides as
    None and renders "-"."""
    best = None
    for tag in ("service_replay_mixed", "service_replay_mixed_mesh",
                "service_replay_pipeline_204req"):
        e = sec.get(tag)
        if not isinstance(e, dict):
            continue
        rows = [e]
        # D-curve entries nest rows under d1/d2/... (and the PR-6
        # pipeline sweep nests sync/pipelined one level below that)
        for k, v in e.items():
            if re.fullmatch(r"d\d+", k) and isinstance(v, dict):
                rows.append(v)
                rows += [w for w in v.values() if isinstance(w, dict)]
        for r in rows:
            sp = r.get("speedup_vs_sequential")
            if sp is None:
                continue
            row = (sp, r.get("latency_p95_s"),
                   r.get("device_wait_frac"), r.get("requests"), tag,
                   r.get("pipeline_depth"))
            if best is None or sp > best[0]:
                best = row
    for tag in ("service_replay_mesh_curve_204req",):
        e = sec.get(tag)
        if isinstance(e, dict):
            for k, r in e.items():
                if re.fullmatch(r"d\d+", k) and isinstance(r, dict):
                    sp = r.get("speedup_vs_sequential")
                    if sp is not None and (best is None or sp > best[0]):
                        best = (sp, r.get("latency_p95_s"),
                                r.get("device_wait_frac"), 204, tag,
                                r.get("pipeline_depth"))
    return best


def load_rows():
    rows = []
    for path in sorted(glob.glob(os.path.join(REPO, "BENCH_pr*.json"))):
        pr = re.search(r"BENCH_pr(\d+)", path).group(1)
        try:
            d = json.load(open(path))
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"pr": pr, "error": str(e)})
            continue
        sec = d.get("secondary", {})
        fleet = None
        for k, v in sec.items():
            if k.startswith("fleet") and isinstance(v, dict):
                fleet = v.get("speedup_vs_sequential")
                break
        replay = _best_replay(sec)
        chaos = (_get(sec, "service_replay_chaos_204req")
                 or _get(sec, "service_replay_chaos") or {})
        # elastic serving entry (PR 8+): loss+return replay as
        # resumable legs; absent in earlier PRs' jsons -> "-"
        elastic = sec.get("service_replay_elastic") or {}
        # open-loop load entry (PR 7+): absent in earlier PRs' jsons —
        # every field defaults to None and renders as "-"
        load = sec.get("service_load_openloop") or {}
        load_miss = _get(load, "slo_ab", "miss_rate_on")
        # scenario-frontier entry (PR 9+): the adversarial-world sweep
        # graded as one service run; absent in earlier jsons -> "-"
        scen = sec.get("scenario_sweep") or {}
        # durable-serving entry (PR 12+): the kill-and-restart gate —
        # completion across the death, zero restarts, digest parity,
        # and the spill tier's write volume
        recov = sec.get("service_recovery") or {}
        # static-analysis entry (PR 14+): bench --check runs the
        # jaxpr/sharding/ast passes in-process and records the
        # verdict; older jsons without it render "-"
        lint = d.get("analysis") or {}
        # compile-surface entry (PR 16+): the mixed-schedule bucket
        # canonicalization gate — exact vs canonical bucket
        # cardinality, fresh-build collapse, warm-lap hit rate
        surf = sec.get("compile_surface") or {}
        # depth-sweep entry (PR 17+): the per-bucket in-flight ring
        # ladder under service_load_openloop — one row per
        # pipeline_depth with the measured open-loop saturation; the
        # headline is the depth-2 shift vs depth-1
        ds_rows = _get(load, "depth_sweep", "rows") or []
        ds_sat = {r.get("depth"): r.get("saturation_offered_rps")
                  for r in ds_rows if isinstance(r, dict)}
        # 2-D lanes x peers serving entry (PR 19+): the sweep's best
        # row by speedup plus the peer-shrink elastic gate; absent in
        # earlier jsons -> every column renders "-"
        m2d = sec.get("service_replay_mesh2d") or {}
        m2d_best = None
        for tag2, r2 in (m2d.get("sweep") or {}).items():
            sp2 = r2.get("speedup_vs_sequential") \
                if isinstance(r2, dict) else None
            if sp2 is not None and (m2d_best is None
                                    or sp2 > m2d_best[0]):
                m2d_best = (sp2, tag2)
        m2d_el = m2d.get("elastic_2x4") or {}
        m2d_shape = (f"{m2d_el['lanes_end']}x{m2d_el['peers_end']}"
                     if "lanes_end" in m2d_el else None)
        rows.append({
            "pr": pr,
            "backend": d.get("backend"),
            "devices": _get(d, "env", "device_count"),
            "headline_metric": d.get("metric"),
            "headline_node_ticks_per_s": d.get("value"),
            "fleet_speedup": fleet,
            "replay_speedup": replay[0] if replay else None,
            "replay_p95_s": replay[1] if replay else None,
            "replay_device_wait_frac": replay[2] if replay else None,
            "replay_source": replay[4] if replay else None,
            "replay_pipeline_depth": replay[5] if replay else None,
            "chaos_completion": chaos.get("completion_rate"),
            "chaos_speedup": chaos.get("speedup_vs_sequential"),
            "elastic_completion": elastic.get("completion_rate"),
            "elastic_restarted": elastic.get("restarted_from_zero"),
            "elastic_mean_legs": elastic.get("mean_legs"),
            "load_saturation_rps": load.get("saturation_offered_rps"),
            "load_max_achieved_rps": load.get("max_achieved_rps"),
            "load_miss_rate_slo_on": load_miss,
            "load_deterministic": _get(load, "replay_check",
                                       "deterministic"),
            "depth_sweep_depths": ("/".join(
                str(r["depth"]) for r in ds_rows
                if isinstance(r, dict) and "depth" in r)
                or None),
            "depth1_saturation_rps": ds_sat.get(1),
            "depth2_saturation_rps": ds_sat.get(2),
            "mesh2d_best_speedup": m2d_best[0] if m2d_best else None,
            "mesh2d_best_shape": m2d_best[1] if m2d_best else None,
            "mesh2d_elastic_restarted":
                m2d_el.get("restarted_from_zero"),
            "mesh2d_elastic_shape_end": m2d_shape,
            "scenario_variants": scen.get("variants"),
            "scenario_families": scen.get("families"),
            "scenario_worlds": scen.get("worlds"),
            "scenario_pass_rate": scen.get("oracle_pass_rate"),
            "scenario_replayed": scen.get("replayed_digest_for_digest"),
            "recovery_completion": recov.get("completion_rate"),
            "recovery_restarted": recov.get("restarted_lanes"),
            "recovery_digest_match": recov.get("digest_match"),
            "recovery_spills": _get(recov, "durability", "spills"),
            "recovery_spill_mb": (
                _get(recov, "durability", "spill_bytes") / 1e6
                if _get(recov, "durability", "spill_bytes") is not None
                else None),
            "lint_findings": lint.get("findings"),
            "lint_rules": lint.get("rules"),
            "surface_buckets_exact": surf.get("buckets_exact"),
            "surface_buckets_canonical": surf.get("buckets_canonical"),
            "surface_builds_baseline": surf.get("builds_baseline"),
            "surface_builds_canonical": surf.get("builds_canonical"),
            "surface_build_collapse_x": surf.get("build_collapse_x"),
            "surface_warm_hit_rate": surf.get("warm_hit_rate"),
        })
    return rows


def _fmt(v, spec="{:.2f}"):
    if v is None:
        return "-"
    if isinstance(v, float):
        return spec.format(v)
    return str(v)


def main(argv) -> int:
    rows = load_rows()
    if not rows:
        print("no BENCH_pr*.json found", file=sys.stderr)
        return 1
    if "--json" in argv:
        print(json.dumps(rows, indent=1))
        return 0
    cols = [("PR", "pr", "{}"), ("backend", "backend", "{}"),
            ("dev", "devices", "{}"),
            ("headline nt/s", "headline_node_ticks_per_s", "{:,.0f}"),
            ("fleet x", "fleet_speedup", "{:.2f}"),
            ("replay x", "replay_speedup", "{:.2f}"),
            ("p95 s", "replay_p95_s", "{:.2f}"),
            ("dev-frac", "replay_device_wait_frac", "{:.2f}"),
            ("chaos", "chaos_completion", "{:.0%}"),
            ("elastic", "elastic_completion", "{:.0%}"),
            ("legs", "elastic_mean_legs", "{:.1f}"),
            ("load rps", "load_max_achieved_rps", "{:.1f}"),
            ("sat rps", "load_saturation_rps", "{:.1f}"),
            ("depth", "replay_pipeline_depth", "{}"),
            ("d2 sat", "depth2_saturation_rps", "{:.1f}"),
            ("LxP", "mesh2d_best_shape", "{}"),
            ("LxP x", "mesh2d_best_speedup", "{:.2f}"),
            ("p-shr", "mesh2d_elastic_restarted", "{}"),
            ("p-end", "mesh2d_elastic_shape_end", "{}"),
            ("scen", "scenario_variants", "{}"),
            ("worlds", "scenario_worlds", "{}"),
            ("scen ok", "scenario_pass_rate", "{:.0%}"),
            ("recov", "recovery_completion", "{:.0%}"),
            ("spill MB", "recovery_spill_mb", "{:.1f}"),
            ("lint", "lint_findings", "{}"),
            ("rules", "lint_rules", "{}"),
            ("bkt", "surface_buckets_exact", "{}"),
            ("canon", "surface_buckets_canonical", "{}"),
            ("bld x", "surface_build_collapse_x", "{:.1f}"),
            ("warm", "surface_warm_hit_rate", "{:.0%}")]
    table = [[_fmt(r.get(key), spec) for _, key, spec in cols]
             for r in rows]
    widths = [max(len(h), *(len(t[i]) for t in table))
              for i, (h, _, _) in enumerate(cols)]
    print("  ".join(h.rjust(w) for (h, _, _), w in zip(cols, widths)))
    for t in table:
        print("  ".join(c.rjust(w) for c, w in zip(t, widths)))
    # delta line: latest vs previous headline
    vals = [r.get("headline_node_ticks_per_s") for r in rows
            if r.get("headline_node_ticks_per_s")]
    if len(vals) >= 2:
        print(f"\nheadline: {vals[-1]:,.0f} nt/s "
              f"({(vals[-1] / vals[-2] - 1) * 100:+.1f}% vs prev PR, "
              f"{(vals[-1] / vals[0] - 1) * 100:+.1f}% vs PR {rows[0]['pr']})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
