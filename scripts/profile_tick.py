"""Whole-tick timing of the overlay model on the live backend.

Times the tick through a ``lax.scan`` (single dispatches through this
image's TPU relay cost ~100 ms, so only scans reflect device speed —
see .claude/skills/verify/SKILL.md) for both the XLA and Pallas paths.
Not part of the test suite; a development tool.

Usage: python scripts/profile_tick.py [N]
"""

import sys
import time

import jax

sys.path.insert(0, ".")

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_schedule,
                                                make_overlay_tick,
                                                resolved_dims)


def scan_time(tick, state, sched, reps=3, length=200):
    import numpy as np

    @jax.jit
    def scanned(s):
        def step(c, _):
            return tick(c, sched)[0], None
        return jax.lax.scan(step, s, None, length=length)[0]

    # distinct inputs per call and a readback inside the timed region:
    # the relay memoizes identical (executable, args) pairs and
    # block_until_ready alone can return on dispatch ack (see
    # .claude/skills/verify/SKILL.md)
    variants = [state.replace(own_hb=state.own_hb + i)
                for i in range(reps + 1)]
    np.asarray(jax.block_until_ready(scanned(variants[0])).tick)
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.block_until_ready(scanned(variants[i + 1])).tick)
        best = min(best, time.perf_counter() - t0)
    return best / length


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
    print("backend:", jax.default_backend(), flush=True)
    cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                    drop_msg=False, seed=0, total_ticks=300,
                    churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)
    print(f"N={n} (K, F)={resolved_dims(cfg)}")
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    # long scans amortize the ~100ms relay dispatch cost per call
    length = 200 if n <= (1 << 17) else 25
    for label, up in (("xla", False), ("pallas", True)):
        dt = scan_time(make_overlay_tick(cfg, use_pallas=up), state, sched,
                       length=length)
        print(f"{label:7s} tick: {dt*1e3:8.3f} ms -> "
              f"{n/dt/1e6:8.2f}M node-ticks/s", flush=True)


if __name__ == "__main__":
    main()
