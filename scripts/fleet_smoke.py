"""Fleet timing + parity probes for fleet-batched execution
(core/fleet.py) — the fleet counterpart of scripts/grid_smoke.py.

Modes (positional args are [n] [ticks] [B]):

    python scripts/fleet_smoke.py time 2048 288 8    # fleet vs sequential A/B
    python scripts/fleet_smoke.py sweep 2048 288     # B in {1, 4, 8, 32}
    python scripts/fleet_smoke.py parity 64 64 4     # bit-parity, all paths
    python scripts/fleet_smoke.py mesh 2048 288 8    # D in {1,2,4,8}, B lanes total

``mesh`` sweeps the lane-mesh device count at FIXED total lane width
(parallel/fleet_mesh.py): D=1 is the single-device vmapped fleet, each
D>1 shards the same B lanes over D virtual CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=8 is forced before
jax imports, mirroring tests/conftest.py) — the PERF §10 scaling
curve.

``time`` runs the overlay-churn bench config both ways — B sequential
``OverlaySimulation`` runs, then the same B seeds as one
``FleetSimulation`` — and prints the aggregate node-ticks/s of each
plus the honest wall-clock speedup (the PR's acceptance measurement).
``sweep`` produces the batch-scaling curve for docs/PERF.md §8.
``parity`` replays the fleet test suite's checks at script scale:
per-lane bit-equality for the dense bench fleet, the overlay XLA
fleet, and the batched grid kernel (interpret mode off-TPU).

Scripts need PYTHONPATH=/root/repo.
"""

import sys
import time

import numpy as np


def _cfg(n, ticks):
    from gossip_protocol_tpu.config import SimConfig
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=False, seed=0, total_ticks=ticks,
                     churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)


def _sequential(cfg, seeds):
    """B sequential runs (compile amortized before timing)."""
    import jax

    from gossip_protocol_tpu.models.overlay import OverlaySimulation
    sim = OverlaySimulation(cfg, use_pallas=None)
    sim.run()                                   # compile + warm
    t0 = time.perf_counter()
    for s in seeds:
        OverlaySimulation(cfg.replace(seed=s)).run()
    jax.block_until_ready(jax.numpy.zeros(()))
    return time.perf_counter() - t0


def _fleet(cfg, seeds, warm_seeds):
    from gossip_protocol_tpu.core.fleet import FleetSimulation
    fleet = FleetSimulation(cfg)
    fleet.run_bench(seeds=warm_seeds, warmup=False)   # compile + warm
    t0 = time.perf_counter()
    res = fleet.run_bench(seeds=seeds, warmup=False)
    return time.perf_counter() - t0, res


def _time(n, ticks, batch):
    """Three-way A/B so the speedup decomposes honestly: the fleet
    tick also elides the per-tick coverage histogram (the −1 sentinel
    mode, docs/PERF.md §8), so a B=1 fleet run IS the like-for-like
    sequential baseline — same tick, no batching."""
    import jax

    from gossip_protocol_tpu.core.fleet import FleetSimulation
    cfg = _cfg(n, ticks)
    print(f"backend={jax.default_backend()} n={n} ticks={ticks} "
          f"B={batch}", flush=True)
    seeds = list(range(21, 21 + batch))
    t_seq = _sequential(cfg, seeds)
    agg_seq = batch * n * ticks / t_seq
    print(f"sequential (shipped)   x{batch}: {t_seq:7.3f}s = "
          f"{agg_seq / 1e3:8.1f}k aggregate node-ticks/s", flush=True)
    fleet1 = FleetSimulation(cfg)
    fleet1.run_bench(seeds=[121], warmup=False)       # compile + warm
    t0 = time.perf_counter()
    for s in seeds:
        fleet1.run_bench(seeds=[s], warmup=False)
    t_seq_nc = time.perf_counter() - t0
    print(f"sequential (B=1 fleet) x{batch}: {t_seq_nc:7.3f}s = "
          f"{batch * n * ticks / t_seq_nc / 1e3:8.1f}k aggregate "
          "node-ticks/s", flush=True)
    t_fleet, res = _fleet(cfg, seeds, list(range(121, 121 + batch)))
    agg_fleet = res.total_node_ticks / t_fleet
    print(f"fleet                  x{batch}: {t_fleet:7.3f}s = "
          f"{agg_fleet / 1e3:8.1f}k aggregate node-ticks/s", flush=True)
    print(f"speedup vs shipped sequential: {t_seq / t_fleet:.2f}x "
          f"(= {t_seq / t_seq_nc:.2f}x coverage elision x "
          f"{t_seq_nc / t_fleet:.2f}x batching)", flush=True)
    return t_seq / t_fleet


def _sweep(n, ticks):
    import jax
    cfg = _cfg(n, ticks)
    print(f"backend={jax.default_backend()} n={n} ticks={ticks}",
          flush=True)
    t1 = _sequential(cfg, [21])
    print(f"  B= 1 (sequential): {t1:7.3f}s = "
          f"{n * ticks / t1 / 1e3:8.1f}k nt/s", flush=True)
    for b in (4, 8, 32):
        t_f, res = _fleet(cfg, list(range(21, 21 + b)),
                          list(range(121, 121 + b)))
        agg = res.total_node_ticks / t_f
        print(f"  B={b:2d} (fleet):      {t_f:7.3f}s = "
              f"{agg / 1e3:8.1f}k aggregate nt/s "
              f"({agg / (n * ticks / t1):5.2f}x the B=1 rate)",
              flush=True)


def _mesh(n, ticks, lanes_total):
    """Device-count sweep at fixed total lane width: the shard-parallel
    leg of the PERF §10 decomposition (coverage elision and batching
    are identical across rows — only D moves)."""
    import jax

    from gossip_protocol_tpu.core.fleet import FleetSimulation
    from gossip_protocol_tpu.parallel.fleet_mesh import (
        MeshFleetSimulation, make_lane_mesh)
    cfg = _cfg(n, ticks)
    print(f"backend={jax.default_backend()} devices={jax.device_count()} "
          f"n={n} ticks={ticks} total_lanes={lanes_total}", flush=True)
    if jax.device_count() < 2:
        print("only 1 device live: mesh rows skipped (set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8)", flush=True)
    seeds = list(range(21, 21 + lanes_total))
    warm = list(range(121, 121 + lanes_total))
    t1 = None
    for d in (1, 2, 4, 8):
        if d > jax.device_count() or lanes_total % d:
            continue
        fleet = FleetSimulation(cfg) if d == 1 \
            else MeshFleetSimulation(cfg, make_lane_mesh(d))
        fleet.run_bench(seeds=warm, warmup=False)      # compile + warm
        t0 = time.perf_counter()
        res = fleet.run_bench(seeds=seeds, warmup=False)
        t = time.perf_counter() - t0
        t1 = t if d == 1 else t1
        agg = res.total_node_ticks / t
        rel = f" ({t1 / t:5.2f}x the D=1 fleet)" if t1 and d > 1 else ""
        print(f"  D={d} (B/dev={lanes_total // d}): {t:7.3f}s = "
              f"{agg / 1e3:9.1f}k aggregate nt/s  "
              f"dev {res.device_seconds:6.3f}s{rel}", flush=True)


def _parity(n, ticks, batch):
    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.core.fleet import (FleetSimulation,
                                                _lane_state,
                                                _stack_states, stack_lanes)
    from gossip_protocol_tpu.core.sim import Simulation
    from gossip_protocol_tpu.models.overlay import (OverlaySimulation,
                                                    init_overlay_state,
                                                    make_overlay_schedule)
    from gossip_protocol_tpu.models.overlay_grid import (
        make_grid_fleet_run, make_grid_run)

    bad = 0
    seeds = list(range(1, 1 + batch))

    def check(name, a, b):
        nonlocal bad
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            print(f"MISMATCH {name}")
            bad += 1

    # overlay XLA fleet (parity runs at small n, where the bench
    # config's step_rate would overlap the churn window — use the
    # fast-ramp rate the fleet test suite uses)
    cfg = _cfg(n, ticks).replace(step_rate=8.0 / n)
    fleet = FleetSimulation(cfg).run(seeds=seeds)
    for i, s in enumerate(seeds):
        ref = OverlaySimulation(cfg.replace(seed=s), use_pallas=False).run()
        lane = fleet.lanes[i]
        for f in ("ids", "hb", "ts", "in_group", "send_flags"):
            check(f"overlay lane {i} {f}", getattr(ref.final_state, f),
                  getattr(lane.final_state, f))
        for m in ("sent", "recv", "removals", "victim_slots"):
            check(f"overlay lane {i} metric {m}", getattr(ref.metrics, m),
                  getattr(lane.metrics, m))

    # dense bench fleet
    dcfg = SimConfig(max_nnb=min(n, 64), single_failure=False,
                     drop_msg=True, msg_drop_prob=0.1, seed=0,
                     total_ticks=min(ticks, 100), fail_tick=30,
                     rejoin_after=20)
    dfleet = FleetSimulation(dcfg).run_bench(seeds=seeds)
    dsim = Simulation(dcfg)
    for i, s in enumerate(seeds):
        ref = dsim.run_bench(seed=s)
        lane = dfleet.lanes[i]
        check(f"dense lane {i} known", ref.final_state.known,
              lane.final_state.known)
        check(f"dense lane {i} sent", ref.sent, lane.sent)

    # batched grid kernel (interpret off-TPU)
    gcfgs = [cfg.replace(seed=s) for s in seeds[:2]]
    scheds = [make_overlay_schedule(c) for c in gcfgs]
    states = _stack_states([init_overlay_state(c) for c in gcfgs])
    gt = min(ticks, 20)
    run_f = make_grid_fleet_run(cfg, gt, 2, block_rows=min(n, 32),
                                start_tick=0)
    ff, mf = run_f(states, stack_lanes(scheds))
    for i, c in enumerate(gcfgs):
        f1, m1 = make_grid_run(c, gt, block_rows=min(n, 32),
                               start_tick=0)(init_overlay_state(c),
                                             scheds[i])
        check(f"grid lane {i} ids", f1.ids, _lane_state(ff, i).ids)
        check(f"grid lane {i} sent", m1.sent, np.asarray(mf.sent)[i])

    print("PARITY OK" if not bad else f"PARITY FAILED ({bad} checks)")
    sys.exit(1 if bad else 0)


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "time"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    ticks = int(sys.argv[3]) if len(sys.argv) > 3 else 288
    batch = int(sys.argv[4]) if len(sys.argv) > 4 else 8

    if mode == "mesh":
        # must land before jax is first imported (same rule as
        # tests/conftest.py): the virtual-device flag is read at
        # backend init
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    if mode in ("parity", "mesh"):
        import jax
        jax.config.update("jax_platforms", "cpu")

    if mode == "time":
        _time(n, ticks, batch)
    elif mode == "sweep":
        _sweep(n, ticks)
    elif mode == "parity":
        _parity(n, ticks, batch)
    elif mode == "mesh":
        _mesh(n, ticks, batch)
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
