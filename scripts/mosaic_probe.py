"""Probe Mosaic capabilities the multi-tick megakernel needs.

Verifies on the live backend (and in interpret mode):
  a. jax.lax.fori_loop mutating a whole-array VMEM ref across ticks
  b. dynamic indexing of the scalar-prefetch ref (sp_ref[15 + s*F + fi])
  c. full (N, K) -> (1, 1) reduction stored at a dynamic metrics row
  d. pl.when predicated on a traced scalar inside the loop
  e. static sublane rolls of the whole block

Development tool (VERDICT round-3 task 1).
"""

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")


def _roll_rows(x, shift: int):
    s = shift % x.shape[0]
    if s == 0:
        return x
    return jnp.concatenate([x[-s:], x[:-s]], axis=0)


def _kernel(n, s_ticks, sp_ref, x_ref, out_ref, met_ref, w_ref):
    out_ref[:] = x_ref[:]

    def tick(s, _):
        t = sp_ref[0] + s
        m = sp_ref[2 + s]                       # dynamic sp index
        w_ref[:] = out_ref[:]

        for j in range(n.bit_length() - 1):
            @pl.when(((m >> j) & 1) == 1)
            def _swap(j=j):
                rbits = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
                sel = ((rbits >> j) & 1) == 0
                cur = w_ref[:]
                w_ref[:] = jnp.where(sel, _roll_rows(cur, -(1 << j)),
                                     _roll_rows(cur, 1 << j))

        out_ref[:] = out_ref[:] + w_ref[:] + t

        @pl.when(t % 4 == 3)
        def _boundary():
            out_ref[:] = out_ref[:] * 2

        total = out_ref[:].sum(axis=1, keepdims=True).sum(
            axis=0, keepdims=True)                       # (1, 1)
        met_ref[pl.ds(s, 1), pl.ds(0, 1)] = total
        met_ref[pl.ds(s, 1), pl.ds(1, 1)] = jnp.zeros((1, 1), jnp.int32) + t
        return ()

    jax.lax.fori_loop(0, s_ticks, tick, ())


@functools.partial(jax.jit, static_argnames=("s_ticks", "interpret"))
def mega_probe(x, sp, *, s_ticks: int, interpret: bool):
    n, w = x.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(1,),
        in_specs=[pl.BlockSpec((n, w), lambda i, sp: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((n, w), lambda i, sp: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((s_ticks, 128), lambda i, sp: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        scratch_shapes=[pltpu.VMEM((n, w), jnp.int32)],
    )
    return pl.pallas_call(
        functools.partial(_kernel, n, s_ticks),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((n, w), jnp.int32),
                   jax.ShapeDtypeStruct((s_ticks, 128), jnp.int32)],
        interpret=interpret,
    )(sp, x)


def reference(x, sp, s_ticks):
    n = x.shape[0]
    out = np.asarray(x).copy()
    mets = np.zeros((s_ticks, 128), np.int32)
    for s in range(s_ticks):
        t = int(sp[0]) + s
        m = int(sp[2 + s])
        w = out[np.arange(n) ^ m]
        out = out + w + t
        if t % 4 == 3:
            out = out * 2
        tot = int(out.astype(np.int64).sum()) & 0xFFFFFFFF
        mets[s, 0] = tot - (1 << 32) if tot >= (1 << 31) else tot
        mets[s, 1] = t
    return out, mets


def main():
    n, w, s_ticks = 512, 128, 8
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randint(0, 100, (n, w)), jnp.int32)
    sp = jnp.asarray([5, 0] + [int(rng.randint(1, n)) for _ in range(s_ticks)],
                     jnp.int32)
    ref_out, ref_met = reference(x, sp, s_ticks)

    for interpret in ([True] if jax.default_backend() != "tpu"
                      else [True, False]):
        out, met = mega_probe(x, sp, s_ticks=s_ticks, interpret=interpret)
        mode = "interpret" if interpret else "compiled "
        ok_out = np.array_equal(np.asarray(out), ref_out)
        ok_met = np.array_equal(np.asarray(met)[:, :2], ref_met[:, :2])
        print(f"{mode}: out={'OK' if ok_out else 'MISMATCH'} "
              f"met={'OK' if ok_met else 'MISMATCH'}", flush=True)
        if not (ok_out and ok_met):
            print("first out rows:", np.asarray(out)[:2, :4], ref_out[:2, :4])
            print("met:", np.asarray(met)[:, :2].T, ref_met[:, :2].T)
            sys.exit(1)
    print("all probes passed")


if __name__ == "__main__":
    main()
