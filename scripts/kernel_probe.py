"""Isolate the fused overlay kernel's cost components on TPU.

Times kernel-only scans while varying:
  * block_rows (grid step count vs butterfly depth),
  * mask low bits (masks divisible by b skip every butterfly stage via
    pl.when predication — isolates butterfly cost from DMA/launch).

Development tool (VERDICT round-3 task 1).  Usage:
  python scripts/kernel_probe.py [N]
"""

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import resolved_dims
from gossip_protocol_tpu.ops.pallas.overlay_exchange import fused_overlay_tick


def scan_time(step_fn, carry, reps=3, length=200):
    @jax.jit
    def scanned(c):
        return jax.lax.scan(lambda c, _: (step_fn(c), None), c, None,
                            length=length)[0]

    variants = [jax.tree.map(lambda x: x + i, carry)
                for i in range(reps + 1)]
    jax.block_until_ready(scanned(variants[0]))
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(scanned(variants[i + 1]))
        best = min(best, time.perf_counter() - t0)
    return best / length


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                    drop_msg=False, seed=0, total_ticks=300,
                    churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)
    k, f = resolved_dims(cfg)
    print(f"backend={jax.default_backend()} N={n} K={k} F={f}", flush=True)
    i32 = jnp.int32
    idsaux = jnp.zeros((n, k + 2 + f), i32)
    pw = jnp.zeros((n, k), i32)
    intro = jnp.zeros((8, k), i32)
    scalars = jnp.zeros((8,), i32).at[0].set(5)
    length = 200 if n <= (1 << 16) else 50

    for br in (256, 512, 1024, 2048):
        if br > n:
            continue
        for lowbits in (True, False):
            b_eff = min(br if f <= 4 else br // 2, n)
            masks = (jnp.arange(1, f + 1, dtype=i32) * (1 if lowbits else b_eff)) % n
            masks = jnp.where(masks == 0, b_eff % n, masks)

            def kstep(c, br=br, masks=masks):
                ids2, hb2, ts2, ctr = fused_overlay_tick(
                    c["a"], c["p"], intro, masks, scalars, k=k,
                    t_remove=cfg.t_remove, churn_lo=cfg.total_ticks // 4,
                    churn_span=max(cfg.total_ticks // 2, 1), block_rows=br)
                return {"a": c["a"].at[:, :k].max(ids2),
                        "p": jnp.maximum(c["p"], ts2)}

            dt = scan_time(kstep, {"a": idsaux, "p": pw}, length=length)
            print(f"block_rows={br:5d} butterfly={'on ' if lowbits else 'off'}"
                  f" : {dt*1e6:9.1f} us", flush=True)


if __name__ == "__main__":
    main()
