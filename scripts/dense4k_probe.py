"""Component timing of the dense full-view tick at large N (dev tool).

Decomposes the per-tick wall time of the BASELINE "N=4096, 10% drop"
dense config into: whole tick, drop-mask draw, MXU merge, fused
epilogue — all timed as whole-``lax.scan`` runs on the live backend
(single dispatches through this image's TPU relay cost ~100 ms; see
.claude/skills/verify/SKILL.md).  The residual is the XLA glue the
next dense kernel iteration must fuse.

Usage: python scripts/dense4k_probe.py [N] [ticks-to-steady-state]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.tick import make_tick
from gossip_protocol_tpu.ops.drop import tick_drop_masks
from gossip_protocol_tpu.ops.merge import gossip_reductions_mxu
from gossip_protocol_tpu.ops.pallas.tickfused import fused_tick_update
from gossip_protocol_tpu.state import init_state, make_schedule


def timed(fn, variants, reps=3):
    """Best wall time of fn over distinct inputs with a readback."""
    out = jax.block_until_ready(fn(variants[0]))        # compile
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn(variants[i + 1]))
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    warm = int(sys.argv[2]) if len(sys.argv) > 2 else 120
    length = 32
    reps = 3

    cfg = SimConfig(max_nnb=n, single_failure=False, drop_msg=True,
                    msg_drop_prob=0.1, seed=0, total_ticks=200)
    sched = make_schedule(cfg)
    state0 = init_state(cfg)
    tick = make_tick(cfg, with_events=False)

    @jax.jit
    def advance(s):
        def step(c, _):
            return tick(c, sched)[0], None
        return jax.lax.scan(step, s, None, length=warm)[0]

    print(f"backend={jax.default_backend()} n={n}", flush=True)
    state = jax.block_until_ready(advance(state0))
    print(f"steady state at t={int(state.tick)} "
          f"known_rows={int(state.known.sum(1).max())}", flush=True)

    # ---- whole tick ------------------------------------------------
    @jax.jit
    def full(s):
        def step(c, _):
            return tick(c, sched)[0], None
        return jax.lax.scan(step, s, None, length=length)[0]

    variants = [state.replace(own_hb=state.own_hb + i)
                for i in range(reps + 1)]
    t_full = timed(lambda s: full(s).hb, variants, reps) / length
    print(f"full tick          {t_full * 1e3:8.3f} ms", flush=True)

    # ---- drop draw -------------------------------------------------
    @jax.jit
    def drops(s):
        def step(c, i):
            g, q, p = tick_drop_masks(s.rng, s.tick + i, n,
                                      jnp.asarray(True), sched.drop_prob)
            return c ^ g[0, 0] ^ q[0] ^ p[0], None
        return jax.lax.scan(step, jnp.asarray(False),
                            jnp.arange(length))[0]

    t_drop = timed(drops, variants, reps) / length
    print(f"drop-mask draw     {t_drop * 1e3:8.3f} ms", flush=True)

    # ---- MXU merge -------------------------------------------------
    deliver = state.gossip
    recv_from = jnp.transpose(deliver)

    @jax.jit
    def merge(s):
        def step(c, i):
            m_a, m_f, m_t, anyf = gossip_reductions_mxu(
                recv_from, s.known, s.hb + c, s.ts, s.tick + i,
                t_remove=cfg.t_remove)
            return c + (m_a[0, 0] & 1), None
        return jax.lax.scan(step, jnp.int32(0), jnp.arange(length))[0]

    t_merge = timed(merge, variants, reps) / length
    print(f"mxu merge          {t_merge * 1e3:8.3f} ms", flush=True)

    # ---- fused epilogue -------------------------------------------
    m_a, m_f, m_t, _ = jax.jit(
        lambda s: gossip_reductions_mxu(recv_from, s.known, s.hb, s.ts,
                                        s.tick, t_remove=cfg.t_remove)
    )(state)
    g0, q0, p0 = tick_drop_masks(state.rng, state.tick, n,
                                 jnp.asarray(True), sched.drop_prob)
    ops = jnp.ones((n,), bool)
    zeros = jnp.zeros((n,), bool)

    @jax.jit
    def epi(s):
        def step(c, i):
            out = fused_tick_update(
                m_a, m_f, m_t, recv_from, s.known, s.hb + c, s.ts,
                s.gossip, g0, ops, zeros, zeros, zeros, s.tick + i,
                t_remove=cfg.t_remove, with_events=False)
            return c + (out[1][0, 0] & 1), None
        return jax.lax.scan(step, jnp.int32(0), jnp.arange(length))[0]

    t_epi = timed(epi, variants, reps) / length
    print(f"fused epilogue     {t_epi * 1e3:8.3f} ms", flush=True)

    resid = t_full - t_drop - t_merge - t_epi
    print(f"residual glue      {resid * 1e3:8.3f} ms", flush=True)
    print(f"ticks/s (full)     {1.0 / t_full:8.1f}", flush=True)


if __name__ == "__main__":
    main()
