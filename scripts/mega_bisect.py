"""Runtime bisect of the megakernel's per-tick cost on TPU (dev tool)."""
import sys, time
import jax, jax.numpy as jnp
sys.path.insert(0, ".")
from gossip_protocol_tpu.ops.pallas.overlay_mega import (mega_overlay_ticks,
                                                         _SP_NSCALARS)

n, k, f, s = 4096, 48, 3, 16
w = 2*k+16
st0 = jnp.zeros((n, w), jnp.int32).at[:, 0:k].set(-1)
kw = dict(n=n, k=k, f_rounds=f, s_ticks=s, t_remove=20, churn_lo=75,
          churn_span=150, can_rejoin=True, powerlaw=False)
reps, chain = 3, 12

for dbg in ((), ('nofly',), ('nochunk',), ('nomet',), ('noreslot',),
            ('nofly', 'nochunk', 'noreslot')):
    @jax.jit
    def many(st, dbg=dbg):
        def step(c, _):
            sp = jnp.zeros((_SP_NSCALARS + s*f,), jnp.int32) \
                .at[_SP_NSCALARS:].set(jnp.arange(s*f) % (n-1) + 1) \
                .at[0].set(c[1])
            st2, met = mega_overlay_ticks(c[0], sp, dbg=dbg, **kw)
            return (st2, c[1] + s), met[:, :1]
        return jax.lax.scan(step, (st, jnp.int32(16)), None, length=chain)
    variants = [st0 + i for i in range(reps + 1)]
    jax.block_until_ready(many(variants[0]))
    best = float('inf')
    for i in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(many(variants[i + 1]))
        best = min(best, time.perf_counter() - t0)
    per_tick = best / (chain * s)
    print(f"dbg={dbg}: {per_tick*1e6:8.1f} us/tick", flush=True)
