"""Hardware smoke + parity + segment-timing probes for the grid-scale
multi-tick kernel.

Modes (run as separate processes — the TPU relay latches the backend
per process); positional args are [n] [ticks] [block] [fanout] [scen]:

    python scripts/grid_smoke.py run 8192 96        # default backend
    python scripts/grid_smoke.py check 8192 96      # CPU, XLA path
    python scripts/grid_smoke.py seg 65536 608      # per-segment timing
    python scripts/grid_smoke.py sweep 65536 192    # block x grid_ticks

``run`` executes the grid kernel (compiled on TPU when available,
routed through the segment planner) and dumps the final state +
metrics to /tmp/grid_smoke_<n>.npz; ``check`` replays the same config
through the per-tick XLA formulation on CPU and compares bit-for-bit
— the on-hardware counterpart of tests/test_overlay_grid.py and
tests/test_segments.py (which run interpret mode only).

``seg`` prints the schedule-segment plan (models/segments.py) and
times each segment's kernel variant separately — the per-segment
op-savings breakdown for docs/PERF.md.  ``sweep`` times the segmented
run over a block-rows x GRID_TICKS grid so the win is measured per
config rather than assumed from the default launch shape.
"""

import sys
import time

import numpy as np

STATE_FIELDS = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                "send_flags", "joinreq", "joinrep")
METRIC_FIELDS = ("in_group", "view_slots", "adds", "removals",
                 "false_removals", "victim_slots", "sent", "recv")


def _cfg(n, ticks, fanout=0, mode="churn"):
    from gossip_protocol_tpu.config import SimConfig
    if mode == "fail":
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=False, seed=11, total_ticks=ticks,
                         fail_tick=ticks // 2, fanout=fanout,
                         step_rate=(ticks / 6.0) / n)
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=False, seed=11, total_ticks=ticks,
                     churn_rate=0.2, rejoin_after=40, fanout=fanout,
                     step_rate=(ticks / 6.0) / n)


def _seg_probe(cfg, sched, state, ticks, block):
    """Time each schedule segment's specialized kernel variant.

    Warmup compiles every variant and collects the (seed-11) state at
    each segment boundary; timed reps replay each segment from its
    boundary state under fresh seeds (the relay memoizes identical
    (executable, args) calls) with an in-timing readback."""
    import jax

    from gossip_protocol_tpu.models.overlay import make_overlay_schedule
    from gossip_protocol_tpu.models.overlay_grid import make_grid_run
    from gossip_protocol_tpu.models.segments import (describe_plan,
                                                     plan_segments)
    from gossip_protocol_tpu.ops.pallas.overlay_grid import GRID_TICKS

    plan = plan_segments(cfg, ticks, 0, GRID_TICKS)
    print(f"backend={jax.default_backend()} n={cfg.n} ticks={ticks} "
          f"block={block}\nplan: {describe_plan(plan)}", flush=True)
    runs, states = [], []
    st = state
    for seg in plan:                     # compile + boundary states
        run = make_grid_run(cfg, seg.ticks, block_rows=block,
                            start_tick=seg.start)
        states.append(st)
        runs.append(run)
        st, _ = run(st, sched)
        jax.block_until_ready(st.ids)
    for rep in (1, 2):
        sched_r = make_overlay_schedule(cfg.replace(seed=cfg.seed + rep))
        print(f"-- rep {rep}", flush=True)
        for seg, run, st0 in zip(plan, runs, states):
            t0 = time.perf_counter()
            fin, _ = run(st0, sched_r)
            readback = int(np.asarray(fin.ids[:1, :1])[0, 0])
            wall = time.perf_counter() - t0
            print(f"  {seg.flags.tag:>20} [{seg.start:4d},"
                  f"{seg.start + seg.ticks:4d}): {wall:7.3f}s = "
                  f"{seg.ticks / wall:8.1f} t/s "
                  f"({cfg.n * seg.ticks / wall / 1e6:8.2f}M nt/s) "
                  f"[readback {readback}]", flush=True)


def _sweep(cfg, sched, state, ticks):
    """Whole-run timing over a block-rows x grid_ticks grid."""
    import jax

    from gossip_protocol_tpu.models.overlay import make_overlay_schedule
    from gossip_protocol_tpu.models.overlay_grid import make_grid_run

    blocks = [b for b in (256, 512, 1024) if b <= cfg.n] or [cfg.n]
    gts = [8, 16, 32]
    print(f"backend={jax.default_backend()} n={cfg.n} ticks={ticks}",
          flush=True)
    for b in blocks:
        for g in gts:
            run = make_grid_run(cfg, ticks, block_rows=b, start_tick=0,
                                grid_ticks=g)
            fin, _ = run(state, sched)              # compile + warm
            jax.block_until_ready(fin.ids)
            best = float("inf")
            for rep in (1, 2):
                sched_r = make_overlay_schedule(
                    cfg.replace(seed=cfg.seed + rep))
                t0 = time.perf_counter()
                fin, _ = run(state, sched_r)
                int(np.asarray(fin.ids[:1, :1])[0, 0])   # readback
                best = min(best, time.perf_counter() - t0)
            print(f"  block={b:5d} grid_ticks={g:3d}: "
                  f"{ticks / best:8.1f} t/s "
                  f"({cfg.n * ticks / best / 1e6:8.2f}M nt/s)",
                  flush=True)


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "run"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 8192
    ticks = int(sys.argv[3]) if len(sys.argv) > 3 else 48
    block = int(sys.argv[4]) if len(sys.argv) > 4 else 512
    fanout = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    scen = sys.argv[6] if len(sys.argv) > 6 else "churn"
    path = f"/tmp/grid_smoke_{n}_{ticks}.npz"

    if mode == "check":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                    make_overlay_run,
                                                    make_overlay_schedule)
    cfg = _cfg(n, ticks, fanout, scen)
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)

    if mode == "seg":
        _seg_probe(cfg, sched, state, ticks, block)
        return
    if mode == "sweep":
        _sweep(cfg, sched, state, ticks)
        return

    if mode == "run":
        from gossip_protocol_tpu.models.overlay_grid import make_grid_run
        print(f"backend={jax.default_backend()} n={n} ticks={ticks} "
              f"block={block}", flush=True)
        run = make_grid_run(cfg, ticks, block_rows=block, start_tick=0)
        t0 = time.perf_counter()
        final, met = run(state, sched)
        jax.block_until_ready(final)
        print(f"compile+first run: {time.perf_counter() - t0:.1f}s",
              flush=True)
        # timed runs use fresh seeds: the relay memoizes identical
        # (executable, args) calls (see .claude/skills/verify/SKILL.md),
        # and the in-timing readback defeats early dispatch acks
        for rep in (1, 2):
            sched_r = make_overlay_schedule(cfg.replace(seed=11 + rep))
            t0 = time.perf_counter()
            final_r, _ = run(state, sched_r)
            readback = int(np.asarray(final_r.ids[:1, :1])[0, 0])
            wall = time.perf_counter() - t0
            print(f"timed rep {rep}: {wall:.3f}s = {ticks / wall:.1f} "
                  f"ticks/s ({n * ticks / wall / 1e6:.2f}M node-ticks/s) "
                  f"[readback {readback}]", flush=True)
        out = {f"s_{f}": np.asarray(getattr(final, f)) for f in STATE_FIELDS}
        out.update({f"m_{f}": np.asarray(getattr(met, f))
                    for f in METRIC_FIELDS})
        np.savez(path, **out)
        print(f"wrote {path}", flush=True)
        return

    assert mode == "check", mode
    run = make_overlay_run(cfg, ticks, use_pallas=False)
    final, met = run(state, sched)
    ref = np.load(path)
    bad = 0
    for f in STATE_FIELDS:
        a, b = np.asarray(getattr(final, f)), ref[f"s_{f}"]
        if not np.array_equal(a, b):
            print(f"STATE MISMATCH {f}: {np.argwhere(a != b)[:4]}")
            bad += 1
    for f in METRIC_FIELDS:
        a, b = np.asarray(getattr(met, f)), ref[f"m_{f}"]
        if not np.array_equal(a, b):
            print(f"METRIC MISMATCH {f}: ticks {np.flatnonzero(a != b)[:6]}")
            bad += 1
    print("PARITY OK" if not bad else f"PARITY FAILED ({bad} fields)")
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
