"""Hardware smoke + parity check for the grid-scale multi-tick kernel.

Two phases, run as separate processes (the TPU relay latches the
backend per process):

    python scripts/grid_smoke.py run [n] [ticks]    # default backend
    python scripts/grid_smoke.py check [n] [ticks]  # CPU, XLA path

``run`` executes the grid kernel (compiled on TPU when available) and
dumps the final state + metrics to /tmp/grid_smoke_<n>.npz; ``check``
replays the same config through the per-tick XLA formulation on CPU
and compares bit-for-bit.  This is the on-hardware counterpart of
tests/test_overlay_grid.py (which runs interpret mode only).
"""

import sys
import time

import numpy as np

STATE_FIELDS = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                "send_flags", "joinreq", "joinrep")
METRIC_FIELDS = ("in_group", "view_slots", "adds", "removals",
                 "false_removals", "victim_slots", "sent", "recv")


def _cfg(n, ticks, fanout=0, mode="churn"):
    from gossip_protocol_tpu.config import SimConfig
    if mode == "fail":
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=False, seed=11, total_ticks=ticks,
                         fail_tick=ticks // 2, fanout=fanout,
                         step_rate=(ticks / 6.0) / n)
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=False, seed=11, total_ticks=ticks,
                     churn_rate=0.2, rejoin_after=40, fanout=fanout,
                     step_rate=(ticks / 6.0) / n)


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "run"
    n = int(sys.argv[2]) if len(sys.argv) > 2 else 8192
    ticks = int(sys.argv[3]) if len(sys.argv) > 3 else 48
    block = int(sys.argv[4]) if len(sys.argv) > 4 else 512
    fanout = int(sys.argv[5]) if len(sys.argv) > 5 else 0
    scen = sys.argv[6] if len(sys.argv) > 6 else "churn"
    path = f"/tmp/grid_smoke_{n}_{ticks}.npz"

    if mode == "check":
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax

    from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                    make_overlay_run,
                                                    make_overlay_schedule)
    cfg = _cfg(n, ticks, fanout, scen)
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)

    if mode == "run":
        from gossip_protocol_tpu.models.overlay_grid import make_grid_run
        print(f"backend={jax.default_backend()} n={n} ticks={ticks} "
              f"block={block}", flush=True)
        run = make_grid_run(cfg, ticks, block_rows=block)
        t0 = time.perf_counter()
        final, met = run(state, sched)
        jax.block_until_ready(final)
        print(f"compile+first run: {time.perf_counter() - t0:.1f}s",
              flush=True)
        # timed runs use fresh seeds: the relay memoizes identical
        # (executable, args) calls (see .claude/skills/verify/SKILL.md),
        # and the in-timing readback defeats early dispatch acks
        for rep in (1, 2):
            sched_r = make_overlay_schedule(cfg.replace(seed=11 + rep))
            t0 = time.perf_counter()
            final_r, _ = run(state, sched_r)
            readback = int(np.asarray(final_r.ids[:1, :1])[0, 0])
            wall = time.perf_counter() - t0
            print(f"timed rep {rep}: {wall:.3f}s = {ticks / wall:.1f} "
                  f"ticks/s ({n * ticks / wall / 1e6:.2f}M node-ticks/s) "
                  f"[readback {readback}]", flush=True)
        out = {f"s_{f}": np.asarray(getattr(final, f)) for f in STATE_FIELDS}
        out.update({f"m_{f}": np.asarray(getattr(met, f))
                    for f in METRIC_FIELDS})
        np.savez(path, **out)
        print(f"wrote {path}", flush=True)
        return

    assert mode == "check", mode
    run = make_overlay_run(cfg, ticks, use_pallas=False)
    final, met = run(state, sched)
    ref = np.load(path)
    bad = 0
    for f in STATE_FIELDS:
        a, b = np.asarray(getattr(final, f)), ref[f"s_{f}"]
        if not np.array_equal(a, b):
            print(f"STATE MISMATCH {f}: {np.argwhere(a != b)[:4]}")
            bad += 1
    for f in METRIC_FIELDS:
        a, b = np.asarray(getattr(met, f)), ref[f"m_{f}"]
        if not np.array_equal(a, b):
            print(f"METRIC MISMATCH {f}: ticks {np.flatnonzero(a != b)[:6]}")
            bad += 1
    print("PARITY OK" if not bad else f"PARITY FAILED ({bad} fields)")
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
