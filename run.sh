#!/bin/bash
# One-command harness: build, run all three shipped scenarios, collect
# per-scenario dbg logs.  Equivalent of the reference's run.sh:14-26
# (minus the dead Coursera download/submission plumbing).
#
#   ./run.sh                # native C++ engine (fastest)
#   GOSSIP_BACKEND=jax ./run.sh   # embedded-CPython JAX engine
#
# Produces dbg.0.log (singlefailure), dbg.1.log (multifailure),
# dbg.2.log (msgdropsinglefailure) in the repo root, then prints the
# grader's verdict for each.
set -euo pipefail
cd "$(dirname "$0")"

make

i=0
kinds=(single multi drop)
for conf in testcases/singlefailure.conf \
            testcases/multifailure.conf \
            testcases/msgdropsinglefailure.conf; do
  GOSSIP_BACKEND="${GOSSIP_BACKEND:-native}" ./Application "$conf" >/dev/null
  mv dbg.log "dbg.$i.log"
  i=$((i + 1))
done

echo "wrote dbg.0.log dbg.1.log dbg.2.log"

rc=0
for i in 0 1 2; do
  python3 -m gossip_protocol_tpu.grader --log "dbg.$i.log" \
      --kind "${kinds[$i]}" || rc=1
done
exit $rc
