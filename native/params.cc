#include "params.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>

namespace gossip {

bool Params::LoadConf(const std::string& path) {
  std::ifstream in(path);
  if (!in) return false;
  bool saw_max_nnb = false;
  std::string line;
  while (std::getline(in, line)) {
    auto colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = line.substr(0, colon);
    std::string val = line.substr(colon + 1);
    auto strip = [](std::string* s) {
      size_t a = s->find_first_not_of(" \t\r\n");
      size_t b = s->find_last_not_of(" \t\r\n");
      *s = (a == std::string::npos) ? "" : s->substr(a, b - a + 1);
    };
    strip(&key);
    strip(&val);
    if (val.empty()) continue;
    if (key == "MAX_NNB") {
      max_nnb = std::atoi(val.c_str());
      saw_max_nnb = true;
    } else if (key == "SINGLE_FAILURE") {
      single_failure = std::atoi(val.c_str()) != 0;
    } else if (key == "DROP_MSG") {
      drop_msg = std::atoi(val.c_str()) != 0;
    } else if (key == "MSG_DROP_PROB") {
      msg_drop_prob = std::atof(val.c_str());
    }
  }
  // A readable file that never mentions MAX_NNB is a malformed or
  // mis-pathed conf (the reference's fscanf would have read garbage,
  // Params.cpp:22-25); refuse it instead of silently simulating the
  // 10-peer defaults.
  if (!saw_max_nnb) {
    std::fprintf(stderr, "Params: no MAX_NNB key in %s\n", path.c_str());
    return false;
  }
  return true;
}

}  // namespace gossip
