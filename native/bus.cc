#include "bus.h"

#include <cstring>

#include "logsink.h"

namespace gossip {

double HashUniform(uint64_t seed, uint64_t a, uint64_t b, uint64_t c,
                   uint64_t d) {
  // Mix the key material with distinct odd constants, then apply the
  // splitmix64 finalizer.  Counter-based: no sequential state, so any
  // (tick, from, to, salt) decision can be recomputed independently.
  uint64_t x = seed;
  x += 0x9E3779B97F4A7C15ULL * (a + 1);
  x += 0xBF58476D1CE4E5B9ULL * (b + 1);
  x += 0x94D049BB133111EBULL * (c + 1);
  x += 0xD6E8FEB86659FD93ULL * (d + 1);
  x ^= x >> 30;
  x *= 0xBF58476D1CE4E5B9ULL;
  x ^= x >> 27;
  x *= 0x94D049BB133111EBULL;
  x ^= x >> 31;
  // 53-bit mantissa -> [0, 1)
  return static_cast<double>(x >> 11) * (1.0 / 9007199254740992.0);
}

Bus::Bus(int max_nodes, int total_ticks, Limits limits, double drop_prob,
         uint64_t seed)
    : max_nodes_(max_nodes),
      total_ticks_(total_ticks),
      limits_(limits),
      drop_prob_(drop_prob),
      seed_(seed),
      inbox_(max_nodes),
      sent_(static_cast<size_t>(max_nodes) * total_ticks, 0),
      recv_(static_cast<size_t>(max_nodes) * total_ticks, 0) {}

int Bus::Init() {
  if (next_id_ >= max_nodes_) return -1;
  return next_id_++;
}

bool Bus::Send(int from, int to, const uint8_t* data, size_t size, int tick,
               bool drop_active, int channel) {
  if (to < 0 || to >= next_id_ || from < 0 || from >= next_id_) return false;
  // The three silent-drop conditions (EmulNet.cpp:92-94): full buffer,
  // oversize payload, Bernoulli drop inside the window.
  if (inflight_ >= limits_.max_inflight) return false;
  if (size > static_cast<size_t>(limits_.max_msg_size)) return false;
  if (drop_active) {
    bool drop = drop_hook_
                    ? drop_hook_(from, to, tick, channel)
                    : HashUniform(seed_, tick, from, to, channel) < drop_prob_;
    if (drop) return false;
  }
  inbox_[to].emplace_back(data, data + size);
  ++inflight_;
  if (tick >= 0 && tick < total_ticks_) {
    ++sent_[static_cast<size_t>(from) * total_ticks_ + tick];
  }
  return true;
}

int Bus::Recv(int me, int tick,
              const std::function<void(const uint8_t*, size_t)>& cb) {
  if (me < 0 || me >= next_id_) return 0;
  int delivered = 0;
  auto& q = inbox_[me];
  while (!q.empty()) {
    std::vector<uint8_t> msg = std::move(q.front());
    q.pop_front();
    --inflight_;
    ++delivered;
    if (tick >= 0 && tick < total_ticks_) {
      ++recv_[static_cast<size_t>(me) * total_ticks_ + tick];
    }
    cb(msg.data(), msg.size());
  }
  return delivered;
}

int Bus::Purge(int me) {
  if (me < 0 || me >= next_id_) return 0;
  int purged = static_cast<int>(inbox_[me].size());
  inflight_ -= purged;
  inbox_[me].clear();
  return purged;
}

int Bus::RecvBounded(int me, int tick, uint8_t* out, size_t out_cap,
                     int* sizes, int sizes_cap, bool* more) {
  if (more != nullptr) *more = false;
  if (me < 0 || me >= next_id_) return 0;
  auto& q = inbox_[me];
  size_t used = 0;
  int count = 0;
  while (!q.empty()) {
    const auto& front = q.front();
    if (count >= sizes_cap || used + front.size() > out_cap) {
      if (more != nullptr) *more = true;
      break;
    }
    std::memcpy(out + used, front.data(), front.size());
    used += front.size();
    sizes[count++] = static_cast<int>(front.size());
    q.pop_front();
    --inflight_;
    if (tick >= 0 && tick < total_ticks_) {
      ++recv_[static_cast<size_t>(me) * total_ticks_ + tick];
    }
  }
  return count;
}

bool Bus::Cleanup(const std::string& outdir) const {
  return WriteMsgCount(outdir, sent_.data(), recv_.data(), next_id_,
                       total_ticks_);
}

}  // namespace gossip

// ---- C ABI -----------------------------------------------------------

struct gp_bus {
  gossip::Bus impl;
};

extern "C" {

gp_bus* gp_bus_create(int max_nodes, int total_ticks, int max_inflight,
                      int max_msg_size, double drop_prob, uint64_t seed) {
  gossip::Bus::Limits lim;
  if (max_inflight > 0) lim.max_inflight = max_inflight;
  if (max_msg_size > 0) lim.max_msg_size = max_msg_size;
  return new gp_bus{gossip::Bus(max_nodes, total_ticks, lim, drop_prob, seed)};
}

void gp_bus_destroy(gp_bus* bus) { delete bus; }

int gp_bus_init(gp_bus* bus) { return bus->impl.Init(); }

int gp_bus_send(gp_bus* bus, int from, int to, const void* data, int size,
                int tick, int drop_active, int channel) {
  return bus->impl.Send(from, to, static_cast<const uint8_t*>(data),
                        static_cast<size_t>(size), tick, drop_active != 0,
                        channel)
             ? 1
             : 0;
}

int gp_bus_recv(gp_bus* bus, int me, int tick, void* out, int out_cap,
                int* sizes, int sizes_cap, int* more) {
  bool m = false;
  int count = bus->impl.RecvBounded(me, tick, static_cast<uint8_t*>(out),
                                    static_cast<size_t>(out_cap), sizes,
                                    sizes_cap, &m);
  if (more != nullptr) *more = m ? 1 : 0;
  return count;
}

int gp_bus_inflight(const gp_bus* bus) { return bus->impl.inflight(); }

int gp_bus_cleanup(const gp_bus* bus, const char* outdir) {
  return bus->impl.Cleanup(outdir) ? 1 : 0;
}

void gp_bus_counters(const gp_bus* bus, uint32_t* sent, uint32_t* recv) {
  const auto& s = bus->impl.sent_matrix();
  const auto& r = bus->impl.recv_matrix();
  std::memcpy(sent, s.data(), s.size() * sizeof(uint32_t));
  std::memcpy(recv, r.data(), r.size() * sizeof(uint32_t));
}

double gp_hash_uniform(uint64_t seed, uint64_t a, uint64_t b, uint64_t c,
                       uint64_t d) {
  return gossip::HashUniform(seed, a, b, c, d);
}

}  // extern "C"
