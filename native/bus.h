// EmulNet-shaped message bus: the native communication backend.
//
// Same plugin boundary as the reference's EmulNet (ENinit / ENsend /
// ENrecv / ENcleanup, reference EmulNet.h:92-96) with the same
// unreliable-datagram semantics — silent drop on buffer-full, oversize,
// or Bernoulli probability inside the drop window (EmulNet.cpp:92-94);
// store-and-forward delivery at the receiver's next recv pass; per-node/
// per-tick send/recv accounting dumped as msgcount.log (EmulNet.cpp:184-220).
//
// Designed fresh rather than translated:
//  * messages are real serialized bytes (wire.h), not aliased pointers;
//  * per-destination queues replace the reference's single flat array
//    scanned O(buffer) by every node every tick (EmulNet.cpp:151-174) —
//    recv is O(inbox), and delivery preserves send order (the reference's
//    swap-pop shuffles order; the protocol tolerates both);
//  * the drop decision is a pure hash of (seed, tick, from, to, salt) —
//    a counter-based splitmix64 PRNG — so runs are reproducible and the
//    exact same decisions can be replayed from Python for differential
//    tests (the reference's rand()-after-srand(time(NULL)) is neither,
//    Application.cpp:50, EmulNet.cpp:90);
//  * a test hook can override the drop decision per message.
//
// The C ABI at the bottom exposes the bus to ctypes for the Python-side
// plugin tests.
#pragma once

#include <cstdint>
#include <deque>
#include <functional>
#include <string>
#include <vector>

namespace gossip {

// Counter-based uniform in [0, 1): splitmix64 finalizer over a key mix.
// Public-domain bit-mixing constants (Stafford/Steele); no stream state.
double HashUniform(uint64_t seed, uint64_t a, uint64_t b, uint64_t c,
                   uint64_t d);

class Bus {
 public:
  struct Limits {
    int max_inflight = 30000;  // ENBUFFSIZE (EmulNet.h:12)
    int max_msg_size = 4000;   // MAX_MSG_SIZE (Params.cpp:31)
  };

  // drop_hook(from, to, tick, channel) -> true to drop; installed by
  // tests to replay externally-computed (e.g. device-PRNG) drop patterns.
  using DropHook = std::function<bool(int, int, int, int)>;

  Bus(int max_nodes, int total_ticks, Limits limits, double drop_prob,
      uint64_t seed);

  // ENinit (EmulNet.cpp:72-77): registers the next peer; returns its
  // 0-based index (the reference returns a 1-based id; the off-by-one
  // lives only at the logging boundary, addressing.py).
  int Init();

  // ENsend (EmulNet.cpp:87-111).  Returns true iff enqueued.
  // `drop_active` is the caller's dropmsg-window flag (Params.h);
  // `channel` salts the drop decision so distinct message classes draw
  // independent Bernoulli trials (as the device engine's split keys do,
  // core/tick.py).
  bool Send(int from, int to, const uint8_t* data, size_t size, int tick,
            bool drop_active, int channel = 0);

  // ENrecv (EmulNet.cpp:144-177): deliver every queued message for `me`
  // to the callback, in send order.  Returns messages delivered.
  int Recv(int me, int tick,
           const std::function<void(const uint8_t*, size_t)>& cb);

  // Bounded variant for the C ABI: consumes messages only while they fit
  // the caller's buffers, leaving the rest queued (retryable — unlike a
  // drain-then-discard, nothing is lost on a short buffer).  Writes
  // payloads back-to-back into out and per-message sizes into sizes;
  // returns the count consumed; *more is set if messages remain.
  int RecvBounded(int me, int tick, uint8_t* out, size_t out_cap, int* sizes,
                  int sizes_cap, bool* more);

  // Drop every queued message for `me`, silently (no accounting): the
  // in-flight traffic of a failed peer.  The framework drops such
  // traffic (the reference lets it rot in the shared buffer forever,
  // EmulNet.cpp:151); with the churn extension a rejoined peer must
  // come back to an empty inbox.  Returns the number purged.
  int Purge(int me);

  // ENcleanup (EmulNet.cpp:184-220): dump msgcount.log.
  bool Cleanup(const std::string& outdir) const;

  void set_drop_hook(DropHook hook) { drop_hook_ = std::move(hook); }

  int inflight() const { return inflight_; }
  const std::vector<uint32_t>& sent_matrix() const { return sent_; }
  const std::vector<uint32_t>& recv_matrix() const { return recv_; }
  int n_nodes() const { return next_id_; }

 private:
  int max_nodes_;
  int total_ticks_;
  Limits limits_;
  double drop_prob_;
  uint64_t seed_;
  int next_id_ = 0;
  int inflight_ = 0;
  DropHook drop_hook_;
  std::vector<std::deque<std::vector<uint8_t>>> inbox_;  // per-destination
  std::vector<uint32_t> sent_;  // [node][tick], row-major
  std::vector<uint32_t> recv_;
};

}  // namespace gossip

// ---- C ABI (ctypes surface) -----------------------------------------
extern "C" {
typedef struct gp_bus gp_bus;

gp_bus* gp_bus_create(int max_nodes, int total_ticks, int max_inflight,
                      int max_msg_size, double drop_prob, uint64_t seed);
void gp_bus_destroy(gp_bus* bus);
int gp_bus_init(gp_bus* bus);  // -> new 0-based peer index, or -1
int gp_bus_send(gp_bus* bus, int from, int to, const void* data, int size,
                int tick, int drop_active,
                int channel);  // -> 1 sent / 0 dropped
// Consume messages for `me` into out (concatenated) while they fit,
// writing each message's size into sizes.  Messages that don't fit stay
// queued (*more != 0) — call again with fresh buffers.  Returns the
// count consumed.
int gp_bus_recv(gp_bus* bus, int me, int tick, void* out, int out_cap,
                int* sizes, int sizes_cap, int* more);
int gp_bus_inflight(const gp_bus* bus);
int gp_bus_cleanup(const gp_bus* bus, const char* outdir);
// Copy the (n, t_total) accounting matrices into caller buffers.
void gp_bus_counters(const gp_bus* bus, uint32_t* sent, uint32_t* recv);
double gp_hash_uniform(uint64_t seed, uint64_t a, uint64_t b, uint64_t c,
                       uint64_t d);
}
