#include "engine.h"

#include <climits>
#include <cstdio>
#include <cstring>

#include "wire.h"

namespace gossip {

namespace {
constexpr int kIntroducer = 0;  // join address id=1 -> index 0
                                // (Application.cpp:209-217, EmulNet.cpp:74)
}

Engine::Engine(const Params& par, std::vector<int32_t> fail_ticks,
               std::vector<int32_t> rejoin_ticks)
    : par_(par),
      n_(par.n()),
      bus_(par.n(), par.total_ticks,
           Bus::Limits{par.en_buff_size, par.max_msg_size}, par.msg_drop_prob,
           par.seed),
      start_at_(n_),
      fail_at_(std::move(fail_ticks)),
      rejoin_at_(std::move(rejoin_ticks)),
      failed_(n_, 0),
      in_group_(n_, 0),
      own_hb_(n_, 0),
      known_(static_cast<size_t>(n_) * n_, 0),
      hb_(static_cast<size_t>(n_) * n_, 0),
      ts_(static_cast<size_t>(n_) * n_, 0),
      inbox_(n_) {
  for (int i = 0; i < n_; ++i) {
    start_at_[i] = par_.start_tick(i);
    bus_.Init();
  }
  if (fail_at_.empty()) {
    // Scenario schedule (Application::fail semantics, Application.cpp:181-196)
    // with the framework's seeded counter PRNG in place of rand().
    fail_at_.assign(n_, INT32_MAX);
    double u = HashUniform(par_.seed, 0, 0, 0, /*salt=*/7);
    if (par_.single_failure) {
      int victim = static_cast<int>(u * n_) % n_;
      fail_at_[victim] = par_.fail_tick;
    } else {
      int r = (static_cast<int>(u * n_) % n_) / 2;
      for (int i = r; i < r + n_ / 2 && i < n_; ++i) {
        fail_at_[i] = par_.fail_tick;
      }
    }
  }
  fail_at_.resize(n_, INT32_MAX);
  rejoin_at_.resize(n_, INT32_MAX);
}

void Engine::WipeNode(int i) {
  // initThisNode semantics (MP1Node.cpp:95-113) for a churn rejoin:
  // empty member list, heartbeat 0, out of group, empty inbox — and
  // the peer's in-flight backlog is dropped (Bus::Purge), matching the
  // device engine's traffic-to-failed-receivers rule.
  for (int j = 0; j < n_; ++j) {
    known_[cell(i, j)] = 0;
    hb_[cell(i, j)] = 0;
    ts_[cell(i, j)] = 0;
  }
  in_group_[i] = 0;
  own_hb_[i] = 0;
  inbox_[i].clear();
  bus_.Purge(i);
}

bool Engine::Run(const std::string& outdir, bool quiet) {
  LogSink log(outdir, /*bug_compat=*/true);
  if (!log.ok()) return false;

  // Construction-time output: one stdout line and one "APP" dbg.log line
  // per node, forward order (Application.cpp:59-69,146).
  for (int i = 0; i < n_; ++i) {
    if (!quiet) {
      printf("%d-th introduced node is assigned with the address: %d:0\n", i,
             i + 1);
    }
    log.Event(i, 0, "APP");
  }

  for (int t = 0; t < par_.total_ticks; ++t) {
    // Churn wipe, before any traffic moves this tick: the rejoining
    // peer is re-initialized and its backlog dropped, but it is still
    // failed while processing tick t (the flag clears after the
    // injection pass below, mirroring failed_at's fail < t <= rejoin
    // window in state.py) — messages sent to it *during* tick t are
    // legitimately delivered at t+1.
    for (int i = 0; i < n_; ++i) {
      if (rejoin_at_[i] == t) WipeNode(i);
    }

    // Phase A — every started, live node drains its inbox
    // (forward order, Application.cpp:125-135).  Messages are staged and
    // handled in phase B, preserving the reference's recv-then-step split.
    for (int i = 0; i < n_; ++i) {
      if (failed_[i] || t <= start_at_[i]) continue;
      bus_.Recv(i, t, [&](const uint8_t* data, size_t size) {
        inbox_[i].emplace_back(data, data + size);
      });
    }

    // Phase B — reverse order (Application.cpp:138-163): introduction at
    // the start tick, else message handling + periodic ops.  The
    // introduction branch is NOT gated on bFailed (Application.cpp:142-147
    // checks it only for the nodeLoop else-branch), so a peer whose start
    // tick falls after its fail tick still sends its JOINREQ and is
    // admitted — then removed TREMOVE ticks later, never having gossiped.
    for (int i = n_ - 1; i >= 0; --i) {
      if (t == start_at_[i] || t == rejoin_at_[i]) {
        NodeStart(log, i, t);
      } else if (failed_[i]) {
        continue;
      } else if (t > start_at_[i]) {
        CheckMessages(log, i, t);
        if (in_group_[i]) NodeLoopOps(log, i, t);
        if (i == 0 && t % 500 == 0) {
          char text[32];
          snprintf(text, sizeof(text), "@@time=%d", t);
          log.Event(0, t, text);  // Application.cpp:156-160
        }
      }
    }

    // Fault injection, after the protocol phases (Application.cpp:99-104).
    // Note the single- and multi-failure log formats differ by spaces
    // around '=' (Application.cpp:184,192).
    for (int i = 0; i < n_; ++i) {
      if (fail_at_[i] == t) {
        char text[48];
        snprintf(text, sizeof(text),
                 par_.single_failure ? "Node failed at time=%d"
                                     : "Node failed at time = %d",
                 t);
        log.Event(i, t, text);
        failed_[i] = 1;
      } else if (rejoin_at_[i] == t) {
        failed_[i] = 0;   // alive again from tick t+1 on
      }
    }
  }

  return bus_.Cleanup(outdir);
}

void Engine::NodeStart(LogSink& log, int i, int t) {
  // introduceSelfToGroup (MP1Node.cpp:120-154): the introducer starts the
  // group; everyone else sends a JOINREQ with its (empty) member list.
  if (i == kIntroducer) {
    log.Event(i, t, "Starting up group...");
    in_group_[i] = 1;
  } else {
    log.Event(i, t, "Trying to join...");
    // JOINREQ carries the joiner's (empty) member list (MP1Node.cpp:135-149).
    std::vector<uint8_t> req;
    wire_encode(&req, kJoinReq, i + 1, nullptr, 0);
    bus_.Send(i, kIntroducer, req.data(), req.size(), t, par_.drop_active(t),
              /*channel=*/1);
  }
}

void Engine::CheckMessages(LogSink& log, int i, int t) {
  // Process in ascending-sender order.  The bus queues phase-B sends in
  // reverse node order (the driver steps nodes n-1..0), and the reference
  // effectively delivers its buffer newest-first (reverse scan with
  // swap-pop, EmulNet.cpp:151-160) — i.e. ascending sender id.  The
  // order matters for exact heartbeat convergence: adopting the leader's
  // piggybacked maximum *before* a later sender's direct increment is
  // what makes every observer's value for a subject identical in steady
  // state, which in turn makes failure-removal ticks uniform
  // (all survivors at fail + TREMOVE + 1; BASELINE.md).
  for (auto it = inbox_[i].rbegin(); it != inbox_[i].rend(); ++it) {
    const auto& msg = *it;
    WireHeader h;
    const WireEntry* entries = nullptr;
    if (!wire_decode(msg.data(), msg.size(), &h, &entries)) continue;
    int s = h.sender - 1;
    if (s < 0 || s >= n_ || s == i) continue;
    switch (h.type) {
      case kGossip:
        HandleGossip(log, i, s, entries, h.count, t);
        break;
      case kJoinReq: {
        // Introducer adds the requester (dedup'd) with heartbeat 1 and
        // replies with its full member list (MP1Node.cpp:221-230).
        if (!known_[cell(i, s)]) {
          known_[cell(i, s)] = 1;
          hb_[cell(i, s)] = 1;
          ts_[cell(i, s)] = t;
          log.NodeAdd(i, t, s);
        }
        std::vector<WireEntry> list;
        for (int j = 0; j < n_; ++j) {
          if (known_[cell(i, j)]) {
            list.push_back({j + 1, hb_[cell(i, j)], ts_[cell(i, j)]});
          }
        }
        std::vector<uint8_t> rep;
        wire_encode(&rep, kJoinRep, i + 1, list.data(),
                    static_cast<int32_t>(list.size()));
        bus_.Send(i, s, rep.data(), rep.size(), t, par_.drop_active(t),
                  /*channel=*/2);
        break;
      }
      case kJoinRep:
        // Joiner adds the sender (the introducer) and enters the group;
        // the piggybacked list is ignored (MP1Node.cpp:231-233 — the
        // joiner learns the rest of the group via subsequent gossip).
        if (!known_[cell(i, s)]) {
          known_[cell(i, s)] = 1;
          hb_[cell(i, s)] = 1;
          ts_[cell(i, s)] = t;
          log.NodeAdd(i, t, s);
        }
        in_group_[i] = 1;
        break;
      default:
        break;
    }
  }
  inbox_[i].clear();
}

void Engine::HandleGossip(LogSink& log, int i, int s, const WireEntry* entries,
                          int count, int t) {
  // Direct-sender handling (MP1Node.cpp:236-242): a known sender's
  // heartbeat is *incremented* locally (not adopted) and its timestamp
  // refreshed; an unknown sender is added with heartbeat 1.
  if (known_[cell(i, s)]) {
    ++hb_[cell(i, s)];
    ts_[cell(i, s)] = t;
  } else {
    known_[cell(i, s)] = 1;
    hb_[cell(i, s)] = 1;
    ts_[cell(i, s)] = t;
    log.NodeAdd(i, t, s);
  }
  // Piggyback merge (MP1Node.cpp:244-256): adopt strictly larger
  // heartbeats (stamping the local clock); add unknown entries whose
  // timestamp is still fresh, copying the entry verbatim
  // (addMember, MP1Node.cpp:282-301).  Any valid id merges — the
  // reference's id<10 cap (MP1Node.cpp:245) is a bug, not a feature.
  for (int k = 0; k < count; ++k) {
    int j = entries[k].id - 1;
    if (j < 0 || j >= n_ || j == i) continue;
    if (known_[cell(i, j)]) {
      if (entries[k].hb > hb_[cell(i, j)]) {
        hb_[cell(i, j)] = entries[k].hb;
        ts_[cell(i, j)] = t;
      }
    } else if (t - entries[k].ts < par_.t_remove) {
      known_[cell(i, j)] = 1;
      hb_[cell(i, j)] = entries[k].hb;
      ts_[cell(i, j)] = entries[k].ts;
      log.NodeAdd(i, t, j);
    }
  }
}

void Engine::NodeLoopOps(LogSink& log, int i, int t) {
  // Own heartbeat (MP1Node.cpp:337), staleness sweep in reverse subject
  // order (MP1Node.cpp:339-348), then full-list gossip to every member
  // (MP1Node.cpp:350-361).
  ++own_hb_[i];
  for (int j = n_ - 1; j >= 0; --j) {
    if (known_[cell(i, j)] && t - ts_[cell(i, j)] >= par_.t_remove) {
      known_[cell(i, j)] = 0;
      hb_[cell(i, j)] = 0;
      ts_[cell(i, j)] = 0;
      log.NodeRemove(i, t, j);
    }
  }
  std::vector<WireEntry> list;
  list.reserve(n_);
  for (int j = 0; j < n_; ++j) {
    if (known_[cell(i, j)]) {
      list.push_back({j + 1, hb_[cell(i, j)], ts_[cell(i, j)]});
    }
  }
  if (list.empty()) return;
  std::vector<uint8_t> msg;
  wire_encode(&msg, kGossip, i + 1, list.data(),
              static_cast<int32_t>(list.size()));
  bool window = par_.drop_active(t);
  for (const auto& e : list) {
    bus_.Send(i, e.id - 1, msg.data(), msg.size(), t, window, /*channel=*/0);
  }
}

}  // namespace gossip

// ---- C ABI -----------------------------------------------------------

extern "C" {

int gp_run_scenario(int n, int single_failure, int drop_msg, double drop_prob,
                    int total_ticks, uint64_t seed, const int32_t* fail_ticks,
                    const char* outdir) {
  return gp_run_scenario_churn(n, single_failure, drop_msg, drop_prob,
                               total_ticks, seed, fail_ticks,
                               /*rejoin_ticks=*/nullptr, outdir);
}

int gp_run_scenario_churn(int n, int single_failure, int drop_msg,
                          double drop_prob, int total_ticks, uint64_t seed,
                          const int32_t* fail_ticks,
                          const int32_t* rejoin_ticks, const char* outdir) {
  gossip::Params par;
  par.max_nnb = n;
  par.single_failure = single_failure != 0;
  par.drop_msg = drop_msg != 0;
  par.msg_drop_prob = drop_prob;
  par.total_ticks = total_ticks;
  par.seed = seed;
  std::vector<int32_t> ft, rt;
  if (fail_ticks != nullptr) ft.assign(fail_ticks, fail_ticks + n);
  if (rejoin_ticks != nullptr) rt.assign(rejoin_ticks, rejoin_ticks + n);
  gossip::Engine engine(par, std::move(ft), std::move(rt));
  return engine.Run(outdir != nullptr ? outdir : ".") ? 0 : 1;
}

int gp_run_conf(const char* conf_path, uint64_t seed, const char* outdir) {
  gossip::Params par;
  if (!par.LoadConf(conf_path != nullptr ? conf_path : "")) return 2;
  par.seed = seed;
  gossip::Engine engine(par);
  return engine.Run(outdir != nullptr ? outdir : ".") ? 0 : 1;
}

}  // extern "C"
