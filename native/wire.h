// Dense wire format for protocol messages.
//
// The reference has no real serialization: it memcpys a C++ struct whose
// fields are a raw Address* into the *sender's* heap and a std::vector
// header aliasing sender-owned storage (reference MP1Node.cpp:136-147,
// EmulNet.cpp:96-101) — receivers dereference foreign pointers, which only
// works because all emulated peers share one address space.  This framework
// fixes that quirk (SURVEY.md §2.2 #1) with a trivially-copyable,
// position-independent, fixed-width layout: a message is a header followed
// by `count` packed entries.  The same bytes are valid across processes,
// over ctypes into Python, and as rows of a device tensor.
#pragma once

#include <cstdint>
#include <cstring>
#include <vector>

namespace gossip {

enum MsgType : int32_t {
  // Same protocol vocabulary as the reference (MP1Node.h:31-36); the
  // DUMMYLASTMSGTYPE sentinel is dropped.
  kJoinReq = 0,
  kJoinRep = 1,
  kGossip = 2,
};

#pragma pack(push, 1)
struct WireHeader {
  int32_t type;    // MsgType
  int32_t sender;  // peer id (1-based, EmulNet.cpp:74 numbering)
  int32_t count;   // number of WireEntry records that follow
};

struct WireEntry {
  // One membership-table cell (MemberListEntry, reference Member.h:62-81).
  int32_t id;  // peer id (1-based)
  int64_t hb;  // heartbeat
  int64_t ts;  // local-clock timestamp at the sender
};
#pragma pack(pop)

inline size_t wire_size(int32_t count) {
  return sizeof(WireHeader) + static_cast<size_t>(count) * sizeof(WireEntry);
}

// Serialize into `out` (resized to fit).  Entries are appended verbatim.
inline void wire_encode(std::vector<uint8_t>* out, int32_t type, int32_t sender,
                        const WireEntry* entries, int32_t count) {
  out->resize(wire_size(count));
  WireHeader h{type, sender, count};
  std::memcpy(out->data(), &h, sizeof(h));
  if (count > 0) {
    std::memcpy(out->data() + sizeof(h), entries,
                static_cast<size_t>(count) * sizeof(WireEntry));
  }
}

// Validate and view a received buffer.  Returns false on malformed input
// (short buffer / negative count) — a real check the reference cannot do.
inline bool wire_decode(const uint8_t* data, size_t size, WireHeader* h,
                        const WireEntry** entries) {
  if (size < sizeof(WireHeader)) return false;
  std::memcpy(h, data, sizeof(WireHeader));
  if (h->count < 0 || wire_size(h->count) > size) return false;
  *entries = reinterpret_cast<const WireEntry*>(data + sizeof(WireHeader));
  return true;
}

}  // namespace gossip
