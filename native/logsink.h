// Observability sink: the reference-grammar log files, written natively.
//
// Native twin of gossip_protocol_tpu/logging_compat.py (the single source
// of truth for the grammar).  Three files:
//
//  * dbg.log    — the grep-able event log Grader.sh asserts on.  First
//    line is the hex char-sum of "CS425" (= "131", reference Log.cpp:79-88);
//    each event renders as "\n <addr> [tick] <text>" (Log.cpp:97-99).
//    Under bug_compat the very first event's address is blank, matching
//    the reference's uninitialized static buffer on the first LOG call
//    (Log.cpp:56-73).
//  * stats.log  — created empty (no #STATSLOG# producers, Log.cpp:90-95).
//  * msgcount.log — per-node/per-tick (sent, recv) matrix in ENcleanup's
//    format (EmulNet.cpp:184-220), including the 10-per-line wrapping and
//    the node-67 "special" rows.
#pragma once

#include <cstdint>
#include <cstdio>
#include <string>

namespace gossip {

// Dotted log form of peer index (0-based): little-endian bytes of
// id = index + 1, then ":port" (Log.cpp:73).  Writes into buf, returns buf.
const char* AddrStr(int index, char* buf, size_t bufsz, int port = 0);

class LogSink {
 public:
  // Opens outdir/dbg.log (writing the magic first line) and creates an
  // empty outdir/stats.log alongside it (Log.cpp:66-67).
  LogSink(const std::string& outdir, bool bug_compat = true);
  ~LogSink();

  // One event line.  observer < 0 renders a blank address
  // unconditionally; otherwise the first call renders blank iff
  // bug_compat (the Log.cpp:56-73 quirk).
  void Event(int observer, int tick, const char* text);

  // printf-style convenience for the standard event texts.
  void NodeAdd(int observer, int tick, int subject);     // Log.cpp:116-120
  void NodeRemove(int observer, int tick, int subject);  // Log.cpp:127-131

  bool ok() const { return dbg_ != nullptr; }

 private:
  FILE* dbg_ = nullptr;
  bool first_ = true;
  bool bug_compat_;
};

// Write outdir/msgcount.log from (n, t_total) row-major counters.
// Node ids print 1-based; see EmulNet.cpp:195-216 for the format quirks.
bool WriteMsgCount(const std::string& outdir, const uint32_t* sent,
                   const uint32_t* recv, int n, int t_total);

}  // namespace gossip
