// Native protocol engine: a vectorized struct-of-arrays implementation of
// the gossip membership protocol over the EmulNet-shaped Bus.
//
// This is the framework's native CPU backend and differential oracle for
// the JAX/TPU engine (gossip_protocol_tpu/core/tick.py).  It implements
// the same protocol semantics the reference defines — join handshake
// (JOINREQ/JOINREP, MP1Node.cpp:120-154,221-233), full-list heartbeat
// gossip with max-merge (MP1Node.cpp:234-257,350-361), and TREMOVE
// staleness removal (MP1Node.cpp:335-348) — but with a fresh design:
// state is four dense arrays (known/hb/ts tables + per-node flags)
// instead of N heap objects with vector<MemberListEntry> lists, messages
// are really serialized (wire.h) instead of aliased pointers, and the
// PRNG is counter-based and seedable instead of srand(time(NULL)).
// The N<=10 merge cap (MP1Node.cpp:245, SURVEY.md §2.2 #2) is
// deliberately NOT reproduced: any valid peer id merges.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "bus.h"
#include "logsink.h"
#include "params.h"
#include "wire.h"

namespace gossip {

class Engine {
 public:
  // fail_ticks: per-node failure tick (INT32_MAX = never), or empty to
  // derive the scenario schedule from params (single: one uniform victim;
  // multi: contiguous half-block — Application.cpp:181-196 semantics,
  // seeded PRNG instead of wall-clock rand()).
  // rejoin_ticks: churn extension (absent in the reference, SURVEY.md
  // §5): a failed peer is wiped at this tick and re-introduced through
  // the normal JOINREQ path (must be > its fail tick; INT32_MAX =
  // stays dead).  Twin of Schedule.rejoin_tick (state.py).
  Engine(const Params& par, std::vector<int32_t> fail_ticks = {},
         std::vector<int32_t> rejoin_ticks = {});

  // Run the full scenario, writing dbg.log / stats.log / msgcount.log
  // into outdir.  Returns false if the logs could not be opened.
  bool Run(const std::string& outdir, bool quiet = true);

  const std::vector<int32_t>& fail_ticks() const { return fail_at_; }
  const std::vector<int32_t>& start_ticks() const { return start_at_; }

 private:
  void WipeNode(int i);
  void NodeStart(LogSink& log, int i, int t);
  void CheckMessages(LogSink& log, int i, int t);
  void NodeLoopOps(LogSink& log, int i, int t);
  void HandleGossip(LogSink& log, int i, int sender, const WireEntry* entries,
                    int count, int t);

  // membership-table accessors (row-major [observer][subject])
  size_t cell(int i, int j) const {
    return static_cast<size_t>(i) * n_ + j;
  }

  Params par_;
  int n_;
  Bus bus_;
  std::vector<int32_t> start_at_;  // introduction tick per node
  std::vector<int32_t> fail_at_;   // failure tick per node (INT32_MAX = never)
  std::vector<int32_t> rejoin_at_;  // churn rejoin tick (INT32_MAX = never)

  // SoA world state — the native mirror of state.py's WorldState.
  std::vector<uint8_t> failed_;    // [N]
  std::vector<uint8_t> in_group_;  // [N]
  std::vector<int64_t> own_hb_;    // [N]
  std::vector<uint8_t> known_;     // [N*N]
  std::vector<int64_t> hb_;        // [N*N]
  std::vector<int64_t> ts_;        // [N*N]
  std::vector<std::vector<std::vector<uint8_t>>> inbox_;  // staged per tick
};

}  // namespace gossip

// ---- C ABI (ctypes surface) -----------------------------------------
extern "C" {
// Run one scenario natively.  fail_ticks may be NULL (derive from the
// scenario parameters).  Returns 0 on success.
int gp_run_scenario(int n, int single_failure, int drop_msg, double drop_prob,
                    int total_ticks, uint64_t seed, const int32_t* fail_ticks,
                    const char* outdir);
// Churn variant: rejoin_ticks (may be NULL) wipes each failed peer at
// its rejoin tick and re-introduces it through the JOINREQ path.
int gp_run_scenario_churn(int n, int single_failure, int drop_msg,
                          double drop_prob, int total_ticks, uint64_t seed,
                          const int32_t* fail_ticks,
                          const int32_t* rejoin_ticks, const char* outdir);
// Same, parsing a reference-format .conf file. Returns 0 on success.
int gp_run_conf(const char* conf_path, uint64_t seed, const char* outdir);
}
