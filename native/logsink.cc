#include "logsink.h"

#include <cstring>

namespace gossip {
namespace {

constexpr const char kMagic[] = "CS425";  // Log.h:19

int MagicSum() {
  int s = 0;
  for (const char* p = kMagic; *p; ++p) s += static_cast<unsigned char>(*p);
  return s;
}

std::string Join(const std::string& dir, const char* name) {
  if (dir.empty() || dir == ".") return name;
  return dir + "/" + name;
}

}  // namespace

const char* AddrStr(int index, char* buf, size_t bufsz, int port) {
  uint32_t id = static_cast<uint32_t>(index + 1);
  snprintf(buf, bufsz, "%u.%u.%u.%u:%d", id & 0xFF, (id >> 8) & 0xFF,
           (id >> 16) & 0xFF, (id >> 24) & 0xFF, port);
  return buf;
}

LogSink::LogSink(const std::string& outdir, bool bug_compat)
    : bug_compat_(bug_compat) {
  dbg_ = fopen(Join(outdir, "dbg.log").c_str(), "w");
  if (dbg_ != nullptr) {
    fprintf(dbg_, "%x\n", MagicSum());  // Log.cpp:80-88
  }
  FILE* stats = fopen(Join(outdir, "stats.log").c_str(), "w");
  if (stats != nullptr) fclose(stats);
}

LogSink::~LogSink() {
  if (dbg_ != nullptr) fclose(dbg_);
}

void LogSink::Event(int observer, int tick, const char* text) {
  if (dbg_ == nullptr) return;
  char addr[32];
  bool blank = observer < 0 || (first_ && bug_compat_);
  first_ = false;
  if (blank) {
    fprintf(dbg_, "\n [%d] %s", tick, text);
  } else {
    fprintf(dbg_, "\n %s [%d] %s", AddrStr(observer, addr, sizeof(addr), 0),
            tick, text);
  }
}

void LogSink::NodeAdd(int observer, int tick, int subject) {
  char addr[32], text[64];
  snprintf(text, sizeof(text), "Node %s joined at time %d",
           AddrStr(subject, addr, sizeof(addr), 0), tick);
  Event(observer, tick, text);
}

void LogSink::NodeRemove(int observer, int tick, int subject) {
  char addr[32], text[64];
  snprintf(text, sizeof(text), "Node %s removed at time %d",
           AddrStr(subject, addr, sizeof(addr), 0), tick);
  Event(observer, tick, text);
}

bool WriteMsgCount(const std::string& outdir, const uint32_t* sent,
                   const uint32_t* recv, int n, int t_total) {
  FILE* f = fopen(Join(outdir, "msgcount.log").c_str(), "w");
  if (f == nullptr) return false;
  for (int i = 0; i < n; ++i) {
    int node_id = i + 1;
    fprintf(f, "node %3d ", node_id);
    uint64_t stot = 0, rtot = 0;
    for (int j = 0; j < t_total; ++j) {
      uint32_t s = sent[static_cast<size_t>(i) * t_total + j];
      uint32_t r = recv[static_cast<size_t>(i) * t_total + j];
      stot += s;
      rtot += r;
      if (node_id != 67) {  // the EmulNet.cpp:204 oddity, kept verbatim
        fprintf(f, " (%4u, %4u)", s, r);
        if (j % 10 == 9) fprintf(f, "\n         ");
      } else {
        fprintf(f, "special %4d %4u %4u\n", j, s, r);
      }
    }
    fprintf(f, "\nnode %3d sent_total %6llu  recv_total %6llu\n\n", node_id,
            static_cast<unsigned long long>(stot),
            static_cast<unsigned long long>(rtot));
  }
  fclose(f);
  return true;
}

}  // namespace gossip
