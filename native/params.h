// Scenario configuration: native twin of gossip_protocol_tpu/config.py.
//
// Parses the reference's 4-line `KEY: value` .conf grammar (reference
// Params.cpp:22-25) and carries the same derived constants the reference
// hardwires at compile time (TOTAL_RUNNING_TIME Application.h:27, TREMOVE
// MP1Node.h:21, buffer limits EmulNet.h:10-12, STEP_RATE/MAX_MSG_SIZE
// Params.cpp:30-31).  Unlike the reference's positional fscanf, keys may
// appear in any order and unknown keys are ignored.
#pragma once

#include <cstdint>
#include <string>

namespace gossip {

struct Params {
  // .conf fields (Params.cpp:22-25)
  int max_nnb = 10;            // MAX_NNB; EN_GPSZ = MAX_NNB (Params.cpp:29)
  bool single_failure = true;  // SINGLE_FAILURE
  bool drop_msg = false;       // DROP_MSG
  double msg_drop_prob = 0.1;  // MSG_DROP_PROB

  // reference compile-time constants, same defaults
  int total_ticks = 700;    // TOTAL_RUNNING_TIME (Application.h:27)
  double step_rate = 0.25;  // STEP_RATE (Params.cpp:30)
  int t_remove = 20;        // TREMOVE (MP1Node.h:21)
  int fail_tick = 100;      // failure injection time (Application.cpp:181,188)
  int drop_open_tick = 50;  // drop window opens after this tick (Application.cpp:177)
  int drop_close_tick = 300;  // ...and closes after this one (Application.cpp:198)
  int max_msg_size = 4000;  // MAX_MSG_SIZE (Params.cpp:31)
  int en_buff_size = 30000;  // ENBUFFSIZE (EmulNet.h:12)

  // framework knob (the reference seeds srand(time(NULL)), Application.cpp:50)
  uint64_t seed = 0;

  int n() const { return max_nnb; }
  // Node i is introduced at tick int(step_rate * i) — C float-to-int
  // truncation (Application.cpp:143).
  int start_tick(int i) const { return static_cast<int>(step_rate * i); }
  // The dropmsg window is open for sends during ticks (open, close]
  // (flag set after tick 50, cleared after tick 300,
  // Application.cpp:177-179,198-200).
  bool drop_active(int t) const {
    return drop_msg && t > drop_open_tick && t <= drop_close_tick;
  }

  // Parse a .conf file; returns false if the file cannot be read.
  bool LoadConf(const std::string& path);
};

}  // namespace gossip
