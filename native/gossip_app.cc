// Application: the native launcher binary.
//
// Drop-in replacement for the reference's `./Application <testcase.conf>`
// entry point (Application.cpp:27-42): same argv contract, same output
// files (dbg.log / stats.log / msgcount.log in the working directory),
// so the reference's Grader.sh and testcases/*.conf run unmodified
// against this framework.
//
// Two backends:
//   * jax (default)  — embeds CPython and delegates the whole run to the
//     TPU-native engine (gossip_protocol_tpu.cli.main): the simulation is
//     a jitted lax.scan over batched device tensors.  The launcher sets
//     conservative env defaults (platform, compilation cache) before the
//     interpreter boots.
//   * native         — the in-process C++ engine (engine.cc): no Python,
//     sub-second at N=10; also the differential oracle.
//
// Select with `--backend={jax,native}` or GOSSIP_BACKEND=... (flag wins).
// Extra args after the conf file are forwarded to the Python CLI.

#include <Python.h>

#include <climits>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "engine.h"
#include "params.h"

namespace {

void SetDefaultEnv(const char* key, const char* value) {
  if (getenv(key) == nullptr) setenv(key, value, 0);
}

std::string DirName(const std::string& path) {
  auto slash = path.find_last_of('/');
  return slash == std::string::npos ? std::string(".") : path.substr(0, slash);
}

bool Exists(const std::string& path) {
  FILE* f = fopen(path.c_str(), "r");
  if (f == nullptr) return false;
  fclose(f);
  return true;
}

// The interpreter the user's environment would run as `python3` — venvs
// included.  Embedding with this as config.executable makes the path
// machinery honor pyvenv.cfg, so site-packages (jax et al.) resolve.
std::string FindPython() {
  const char* explicit_py = getenv("GOSSIP_PYTHON");
  if (explicit_py != nullptr && Exists(explicit_py)) return explicit_py;
  const char* venv = getenv("VIRTUAL_ENV");
  if (venv != nullptr) {
    std::string p = std::string(venv) + "/bin/python3";
    if (Exists(p)) return p;
  }
  const char* path = getenv("PATH");
  if (path != nullptr) {
    std::string paths = path;
    size_t start = 0;
    while (start <= paths.size()) {
      size_t end = paths.find(':', start);
      if (end == std::string::npos) end = paths.size();
      std::string p = paths.substr(start, end - start) + "/python3";
      if (Exists(p)) return p;
      start = end + 1;
    }
  }
  return "";
}

int RunNative(const std::string& conf, uint64_t seed) {
  gossip::Params par;
  if (!par.LoadConf(conf)) {
    fprintf(stderr, "Application: cannot read config %s\n", conf.c_str());
    return 2;
  }
  par.seed = seed;
  gossip::Engine engine(par);
  return engine.Run(".", /*quiet=*/false) ? 0 : 1;
}

// Embed CPython and call gossip_protocol_tpu.cli.main(argv_tail).
int RunJax(const std::string& self_path,
           const std::vector<std::string>& cli_args) {
  // The TPU in this image sits behind a single-grant tunnel that can
  // stall unrelated processes; the N<=1000 compat path is CPU-bound
  // anyway.  Opt into an accelerator explicitly with GOSSIP_TPU_PLATFORM.
  const char* plat = getenv("GOSSIP_TPU_PLATFORM");
  SetDefaultEnv("JAX_PLATFORMS", plat != nullptr ? plat : "cpu");
  // Persistent compilation cache: repeat grader invocations of the same
  // scenario shape skip XLA recompilation.
  SetDefaultEnv("JAX_COMPILATION_CACHE_DIR", "/tmp/gossip_tpu_xla_cache");

  PyConfig config;
  PyConfig_InitPythonConfig(&config);
  PyStatus status = PyConfig_SetBytesString(&config, &config.program_name,
                                            self_path.c_str());
  if (PyStatus_Exception(status)) return 3;
  std::string py = FindPython();
  if (!py.empty()) {
    status = PyConfig_SetBytesString(&config, &config.executable, py.c_str());
    if (PyStatus_Exception(status)) return 3;
  }
  status = Py_InitializeFromConfig(&config);
  PyConfig_Clear(&config);
  if (PyStatus_Exception(status)) return 3;

  int rc = 3;
  // The package lives next to the binary (repo root).
  std::string repo_root = DirName(self_path);
  PyObject* sys_path = PySys_GetObject("path");  // borrowed
  PyObject* root = PyUnicode_FromString(repo_root.c_str());
  if (sys_path != nullptr && root != nullptr) {
    PyList_Insert(sys_path, 0, root);
  }
  Py_XDECREF(root);

  PyObject* mod = PyImport_ImportModule("gossip_protocol_tpu.cli");
  if (mod != nullptr) {
    PyObject* argv = PyList_New(0);
    for (const auto& a : cli_args) {
      PyObject* s = PyUnicode_FromString(a.c_str());
      PyList_Append(argv, s);
      Py_XDECREF(s);
    }
    PyObject* result = PyObject_CallMethod(mod, "main", "(O)", argv);
    if (result != nullptr) {
      rc = static_cast<int>(PyLong_AsLong(result));
      Py_DECREF(result);
    }
    Py_DECREF(argv);
    Py_DECREF(mod);
  }
  if (PyErr_Occurred()) {
    PyErr_Print();
    rc = 3;
  }
  Py_Finalize();
  return rc;
}

}  // namespace

int main(int argc, char** argv) {
  std::string conf;
  std::string backend = getenv("GOSSIP_BACKEND") != nullptr
                            ? getenv("GOSSIP_BACKEND")
                            : "jax";
  uint64_t seed = 0;
  std::vector<std::string> passthrough;

  for (int i = 1; i < argc; ++i) {
    std::string arg = argv[i];
    if (arg.rfind("--backend=", 0) == 0) {
      backend = arg.substr(strlen("--backend="));
    } else if (arg.rfind("--seed=", 0) == 0) {
      seed = strtoull(arg.c_str() + strlen("--seed="), nullptr, 10);
      passthrough.push_back("--seed");
      passthrough.push_back(arg.substr(strlen("--seed=")));
    } else if (conf.empty() && arg[0] != '-') {
      conf = arg;
    } else {
      passthrough.push_back(arg);
    }
  }
  if (conf.empty()) {
    // Same usage contract as the reference (Application.cpp:34-38).
    fprintf(stderr, "Configuration (i.e., *.conf) file is required\n");
    fprintf(stderr,
            "usage: %s <conf> [--backend=jax|native] [--seed=N] "
            "[python-cli args...]\n",
            argc > 0 ? argv[0] : "Application");
    return 2;
  }

  if (backend == "native") return RunNative(conf, seed);

  std::vector<std::string> cli_args;
  cli_args.push_back(conf);
  for (const auto& a : passthrough) cli_args.push_back(a);
  return RunJax(argv[0], cli_args);
}
