"""Scale-proof coverage validation: the scatter-free presence
histogram and the bench's launch-boundary re-cover walk."""

import numpy as np

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (covered_histogram,
                                                init_overlay_state,
                                                make_overlay_run,
                                                make_overlay_schedule)


def test_covered_histogram_matches_numpy():
    rng = np.random.default_rng(0)
    n, k = 1024, 40
    ids = rng.integers(-1, n, size=(n, k), dtype=np.int32)
    got = np.asarray(covered_histogram(ids, n))
    want = np.zeros(n, bool)
    want[ids[ids >= 0]] = True
    assert np.array_equal(got, want)


def test_covered_histogram_empty_and_full():
    n = 512
    empty = np.full((n, 16), -1, np.int32)
    assert not np.asarray(covered_histogram(empty, n)).any()
    full = np.arange(n, dtype=np.int32).reshape(n, 1)
    assert np.asarray(covered_histogram(full, n)).all()


def test_walk_recover_passes_on_healthy_run():
    """The bench's boundary walk accepts a correct churn run (and
    exercises segment + tick-by-tick stepping end to end)."""
    import bench

    n = 1024
    cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                    drop_msg=False, seed=1, total_ticks=288,
                    churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)
    sched = make_overlay_schedule(cfg)
    holes = bench._walk_recover(cfg, sched, 96)
    assert holes >= 0          # completed without violating the bound


def test_walk_recover_flags_a_planted_hole(monkeypatch):
    """A member that never re-covers must trip the walk."""
    import bench

    n = 1024
    cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                    drop_msg=False, seed=1, total_ticks=288,
                    churn_rate=0.2, rejoin_after=40, step_rate=64.0 / n)
    sched = make_overlay_schedule(cfg)

    from gossip_protocol_tpu.models import overlay as overlay_mod
    real = overlay_mod.covered_histogram

    def sabotaged(ids, n_, **kw):
        cov = real(ids, n_, **kw)
        return cov & (np.arange(n_) != 777)       # 777 never covered

    monkeypatch.setattr(overlay_mod, "covered_histogram", sabotaged)
    try:
        # long enough that peer 777 (start tick 48 on this ramp) has
        # joined and is live at a sampled boundary
        bench._walk_recover(cfg, sched, 96)
    except RuntimeError as e:
        assert "re-cover bound" in str(e)
    else:
        raise AssertionError("planted hole not flagged")
