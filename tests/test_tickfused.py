"""Differential tests for the fused tick path (MXU merge +
ops/pallas/tickfused.py epilogue kernel): the update+detect+send pass
must be bit-identical to the composable-op tick — states, events, and
accounting — across scenario shapes (interpret mode on CPU; the same
comparison passes on real TPU hardware against the Mosaic-compiled
kernel)."""

import dataclasses

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.tick import make_tick
from gossip_protocol_tpu.parallel.comm import LocalComm
from gossip_protocol_tpu.state import init_state, make_schedule
from tests.conftest import scenario_cfg


@pytest.mark.parametrize("name,kw", [
    ("drop", dict(max_nnb=24, seed=7, total_ticks=160)),
    ("churn", dict(max_nnb=16, seed=2, fail_tick=30, rejoin_after=25,
                   total_ticks=120)),
    ("start_after_fail", dict(max_nnb=24, seed=0, fail_tick=3,
                              single_failure=False, total_ticks=80)),
])
def test_fused_tick_bit_parity(name, kw):
    scen = "msgdropsinglefailure" if name == "drop" else "singlefailure"
    cfg = scenario_cfg(scen, **kw)
    tick_ref = jax.jit(make_tick(cfg, comm=LocalComm(use_pallas=False)))
    tick_fus = jax.jit(make_tick(cfg, use_pallas=True))
    sched = make_schedule(cfg)
    s1 = s2 = init_state(cfg)
    for t in range(cfg.total_ticks):
        s1, e1 = tick_ref(s1, sched)
        s2, e2 = tick_fus(s2, sched)
        for f in dataclasses.fields(type(s1)):
            a = np.asarray(getattr(s1, f.name))
            b = np.asarray(getattr(s2, f.name))
            assert np.array_equal(a, b), (name, t, f.name)
        for f in dataclasses.fields(type(e1)):
            a = np.asarray(getattr(e1, f.name))
            b = np.asarray(getattr(e2, f.name))
            assert np.array_equal(a, b), (name, t, "ev." + f.name)


def test_fused_gate_falls_back_on_odd_n():
    """N not divisible by the kernel tiling uses the composable ops
    (still under use_pallas: the merge kernel pads internally)."""
    cfg = scenario_cfg("singlefailure", max_nnb=10, total_ticks=30, seed=1)
    tick_ref = jax.jit(make_tick(cfg, comm=LocalComm(use_pallas=False)))
    tick_pal = jax.jit(make_tick(cfg, use_pallas=True))
    sched = make_schedule(cfg)
    s1 = s2 = init_state(cfg)
    for _ in range(cfg.total_ticks):
        s1, _ = tick_ref(s1, sched)
        s2, _ = tick_pal(s2, sched)
    for f in dataclasses.fields(type(s1)):
        assert np.array_equal(np.asarray(getattr(s1, f.name)),
                              np.asarray(getattr(s2, f.name))), f.name


@pytest.mark.slow
def test_fused_multi_tile_grid_parity():
    """Exercise the epilogue kernel's real tiling: at N=256 the grid
    has 4 row tiles, so the per-tile global-index math (is_row0, the
    self-diagonal, JOINREP col 0) runs on non-first tiles (at tiny N
    everything degenerates to a single program).  Covers both event
    modes."""
    cfg = SimConfig(max_nnb=256, single_failure=False, drop_msg=True,
                    msg_drop_prob=0.1, seed=5, total_ticks=40,
                    fail_tick=15)
    tick_ref = jax.jit(make_tick(cfg, comm=LocalComm(use_pallas=False)))
    tick_fus = jax.jit(make_tick(cfg, use_pallas=True))
    tick_fus_bench = jax.jit(make_tick(cfg, use_pallas=True,
                                       with_events=False))
    sched = make_schedule(cfg)
    s1 = s2 = s3 = init_state(cfg)
    for t in range(cfg.total_ticks):
        s1, e1 = tick_ref(s1, sched)
        s2, e2 = tick_fus(s2, sched)
        s3, e3 = tick_fus_bench(s3, sched)
        for f in dataclasses.fields(type(s1)):
            a = np.asarray(getattr(s1, f.name))
            assert np.array_equal(a, np.asarray(getattr(s2, f.name))), (t, f.name)
            assert np.array_equal(a, np.asarray(getattr(s3, f.name))), (t, f.name)
        for f in dataclasses.fields(type(e1)):
            assert np.array_equal(np.asarray(getattr(e1, f.name)),
                                  np.asarray(getattr(e2, f.name))), (t, f.name)
        assert np.array_equal(np.asarray(e1.sent), np.asarray(e3.sent))
        assert np.array_equal(np.asarray(e1.recv), np.asarray(e3.recv))
