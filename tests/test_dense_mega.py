"""Differential tests: dense megakernel vs the per-tick XLA bench run.

The dense megakernel (ops/pallas/dense_mega.py + core/dense_mega.py)
must replay the per-tick path's exact trajectory — final WorldState
bit-identical, per-tick sent/recv counters identical — across join
ramp, single/multi failure, the drop window, and churn.  On CPU the
kernel runs in interpret mode; compiled TPU runs are exercised by
bench.py's validated dense configs.
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.dense_mega import (dense_mega_supported,
                                                 make_dense_mega_run)
from gossip_protocol_tpu.core.tick import make_run
from gossip_protocol_tpu.state import init_state, make_schedule

STATE_FIELDS = ("tick", "in_group", "own_hb", "known", "hb", "ts",
                "gossip", "joinreq", "joinrep")


def _cfg(scenario, n=64):
    if scenario == "single":
        return SimConfig(max_nnb=n, single_failure=True, drop_msg=False,
                         seed=3, total_ticks=120, fail_tick=40)
    if scenario == "multi":
        return SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                         seed=5, total_ticks=120, fail_tick=50)
    if scenario == "drop":
        return SimConfig(max_nnb=n, single_failure=True, drop_msg=True,
                         msg_drop_prob=0.25, seed=7, total_ticks=120,
                         fail_tick=60, drop_open_tick=10,
                         drop_close_tick=100)
    if scenario == "churn":
        return SimConfig(max_nnb=n, single_failure=True, drop_msg=False,
                         seed=9, total_ticks=120, fail_tick=30,
                         rejoin_after=25)
    if scenario == "wave":
        # the one adversarial world inside the mega envelope: pure
        # schedule data (worlds.wave_fail_ticks rewrites fail_tick)
        return SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                         seed=11, total_ticks=120, wave_size=6,
                         wave_tick=40, wave_speed=2)
    raise ValueError(scenario)


@pytest.mark.parametrize("scenario", ["single", "multi", "drop", "churn",
                                      "wave"])
def test_dense_megakernel_bitwise_equals_xla(scenario):
    cfg = _cfg(scenario)
    sched = make_schedule(cfg)
    state = init_state(cfg)
    run_x = make_run(cfg, with_events=False, use_pallas=False)
    run_m = make_dense_mega_run(cfg)
    fx, ex = run_x(state, sched)
    fm, em = run_m(state, sched)
    for name in STATE_FIELDS:
        a, b = np.asarray(getattr(fx, name)), np.asarray(getattr(fm, name))
        assert np.array_equal(a, b), f"state field {name} diverged"
    for name in ("sent", "recv"):
        a, b = np.asarray(getattr(ex, name)), np.asarray(getattr(em, name))
        assert np.array_equal(a, b), \
            f"{name} diverged at ticks {np.flatnonzero((a != b).any(1))[:5]}"


def test_dense_megakernel_odd_length_chunks():
    """total_ticks not a multiple of DENSE_MEGA_TICKS exercises the
    remainder launch."""
    cfg = _cfg("single").replace(total_ticks=39)
    sched = make_schedule(cfg)
    state = init_state(cfg)
    fx, ex = make_run(cfg, with_events=False, use_pallas=False)(state, sched)
    fm, em = make_dense_mega_run(cfg)(state, sched)
    for name in STATE_FIELDS:
        assert np.array_equal(np.asarray(getattr(fx, name)),
                              np.asarray(getattr(fm, name))), name
    assert np.array_equal(np.asarray(ex.sent), np.asarray(em.sent))


@pytest.mark.parametrize("scenario", ["single", "multi", "drop", "churn",
                                      "wave"])
def test_dense_megakernel_events_equal_xla(scenario):
    """Trace mode: the kernel-emitted added/removed masks match the
    per-tick XLA path's TickEvents exactly (the graded dbg.log path
    rides the megakernel — VERDICT round-4 task 4)."""
    cfg = _cfg(scenario).replace(total_ticks=57)   # remainder launch too
    sched = make_schedule(cfg)
    state = init_state(cfg)
    fx, ex = make_run(cfg, with_events=True, use_pallas=False)(state, sched)
    fm, em = make_dense_mega_run(cfg, with_events=True)(state, sched)
    for name in STATE_FIELDS:
        assert np.array_equal(np.asarray(getattr(fx, name)),
                              np.asarray(getattr(fm, name))), name
    for name in ("added", "removed", "sent", "recv"):
        a, b = np.asarray(getattr(ex, name)), np.asarray(getattr(em, name))
        assert np.array_equal(a, b), \
            f"{name} diverged at ticks " \
            f"{np.flatnonzero((a != b).reshape(a.shape[0], -1).any(1))[:5]}"


def test_dense_mega_envelope():
    assert dense_mega_supported(_cfg("single", 64))
    # wave-only configs keep the fast path (schedule data); every
    # other world falls back to the XLA per-tick path
    assert dense_mega_supported(_cfg("wave", 64))
    assert not dense_mega_supported(_cfg("single", 64).replace(zombie=True))
    assert not dense_mega_supported(_cfg("wave", 64).replace(zombie=True))
    assert dense_mega_supported(_cfg("single", 512))
    big = SimConfig(max_nnb=1024, single_failure=True, drop_msg=False,
                    total_ticks=50)
    # bench mode reaches 1024 (the 4096-config active corner is 896);
    # trace mode's two extra (S, N, N) event planes keep it at 512
    assert dense_mega_supported(big)
    assert not dense_mega_supported(big, with_events=True)
    assert not dense_mega_supported(big.replace(max_nnb=2048))


@pytest.mark.slow
def test_dense_mega_reduced_ticks_above_512():
    """The S=8 launch shape (N > 512) replays the per-tick path too.

    Slow tier: two n=576 compiles (~50 s on a 1-core container) —
    the S<8 mega parity stays tier-1 via the scenario matrix above.
    """
    import jax

    from gossip_protocol_tpu.core.tick import make_tick
    cfg = SimConfig(max_nnb=576, single_failure=True, drop_msg=True,
                    msg_drop_prob=0.2, seed=13, total_ticks=44,
                    fail_tick=20, drop_open_tick=8, drop_close_tick=36)
    sched = make_schedule(cfg)
    state = init_state(cfg)
    # full-width per-tick scan, NOT make_run: this config's active
    # bound (256) would route make_run to the corner path, whose drop
    # stream is drawn at width A while the megakernel draws at N
    tick = make_tick(cfg, use_pallas=False, with_events=False)

    @jax.jit
    def run_x(s, sc):
        def step(c, _):
            c, ev = tick(c, sc)
            return c, (ev.sent, ev.recv)
        return jax.lax.scan(step, s, None, length=cfg.total_ticks)

    fx, (sent_x, recv_x) = run_x(state, sched)
    ex = type("E", (), {"sent": sent_x, "recv": recv_x})
    fm, em = make_dense_mega_run(cfg)(state, sched)
    for name in STATE_FIELDS:
        a, b = np.asarray(getattr(fx, name)), np.asarray(getattr(fm, name))
        assert np.array_equal(a, b), f"state field {name} diverged"
    for name in ("sent", "recv"):
        a, b = np.asarray(getattr(ex, name)), np.asarray(getattr(em, name))
        assert np.array_equal(a, b)
