"""Test harness configuration.

Tests run on CPU with 8 virtual devices so the multi-chip sharding paths
compile and execute without TPU hardware (the driver's dryrun does the
same).  These env vars must be set before jax is first imported.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# This image's sitecustomize registers a tunneled TPU PJRT plugin in every
# interpreter and latches JAX_PLATFORMS before conftest runs; its backend
# grabs the (single-grant) device on first use, serializing all jax
# processes machine-wide.  Tests are CPU-only by design — force the
# platform list through the live config so the tunnel is never dialed.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# NOTE: do NOT enable jax's persistent compilation cache here.  It was
# tried (round 3) and a cache entry corrupted by a killed process made
# deserialization abort() the whole pytest run with no Python-level
# error — a silent suite-killer worth far more than the compile time
# it saves.

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from gossip_protocol_tpu.config import SimConfig  # noqa: E402

TESTCASES = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "testcases")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: bench-scale validation runs (deselect with "
        "-m 'not slow' while iterating)")
    config.addinivalue_line(
        "markers", "service: serving-layer tests (select the fast "
        "service path with -m service; the full mixed-trace replay is "
        "additionally marked slow and runs outside tier-1)")
    config.addinivalue_line(
        "markers", "resilience: serving failure-model tests (fault "
        "injection, retry/deadline/breaker, mesh degradation; the "
        "full 204-request chaos replay is additionally marked slow)")
    config.addinivalue_line(
        "markers", "traffic: open-loop traffic/SLO plane tests "
        "(seeded arrival schedules, deadline-aware early flush, "
        "tenant quotas, virtual-clock load replay)")


@pytest.fixture(scope="session")
def testcases_dir():
    return TESTCASES


def scenario_cfg(name: str, **kw) -> SimConfig:
    return SimConfig.from_conf(os.path.join(TESTCASES, f"{name}.conf"), **kw)


@pytest.fixture(params=["singlefailure", "multifailure", "msgdropsinglefailure"])
def scenario(request):
    return request.param
