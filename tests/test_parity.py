"""Differential parity: the vectorized TPU tick vs the scalar oracle.

The oracle (testing/oracle.py) re-implements the reference's
message-by-message semantics including EmulNet buffer ordering; both
sides consume identical drop decisions.  Everything grader-visible must
match exactly: membership tables, timestamps, event sets, removal
times, and per-tick send/recv accounting.  Heartbeat counters may
diverge by at most 1 in entries created during the join transient (the
documented canonical-order effect, core/tick.py docstring).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.state import make_schedule
from gossip_protocol_tpu.testing.dropsync import make_drop_masks
from gossip_protocol_tpu.testing.oracle import ReferenceOracle
from tests.conftest import scenario_cfg


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_full_parity(scenario, seed):
    cfg = scenario_cfg(scenario, seed=seed)
    res = Simulation(cfg).run()
    sched = make_schedule(cfg)
    drops = make_drop_masks(cfg, sched) if cfg.drop_msg else (None, None, None)
    o = ReferenceOracle(cfg, res.start_tick, res.fail_tick, *drops).run()

    gv = res.grader_view()
    # event sets
    assert {(i, j) for (_, i, j) in o.events.added} == gv["joins"]
    oracle_removals = {}
    for (t, i, j) in o.events.removed:
        oracle_removals.setdefault((i, j), t)
    if not cfg.drop_msg:
        assert oracle_removals == gv["removal_ticks"]
    else:
        # Under message drop, heartbeat values diverge by the documented
        # +/-1 join-transient (core/tick.py), which can shift a
        # drop-starved straggler's merge-refresh — and so its removal —
        # by a tick.  The removal *set* must still match exactly.
        assert set(oracle_removals) == set(gv["removal_ticks"])
        for k, t_o in oracle_removals.items():
            assert abs(t_o - gv["removal_ticks"][k]) <= 2, (k, t_o)

    # final tables
    km = o.known_matrix()
    assert np.array_equal(km, np.asarray(res.final_state.known))
    ts_diff = o.table("ts") - np.asarray(res.final_state.ts) * km
    if not cfg.drop_msg:
        assert not ts_diff.any()
    else:
        # Failed nodes freeze their table at the fail tick; the +/-1
        # heartbeat transient can shift one last merge-refresh by a tick
        # under drop, and the frozen row preserves it.  Live rows still
        # converge exactly.
        frozen = (np.asarray(res.fail_tick) <= cfg.total_ticks)[:, None]
        assert not (ts_diff * ~frozen).any()
        assert np.abs(ts_diff).max() <= 1
    # Heartbeat counters seeded during the join transient carry a
    # persistent canonical-order offset; two independently-seeded
    # offsets can stack along a gossip path under drop (core/tick.py
    # docstring), so the bound is 1 without drop and 2 with.
    hb_diff = o.table("hb") - np.asarray(res.final_state.hb) * km
    assert np.abs(hb_diff).max() <= (2 if cfg.drop_msg else 1)

    # accounting parity (drives msgcount.log, EmulNet.cpp:184-220)
    if not cfg.drop_msg:
        assert np.array_equal(o.sent, res.sent)
        assert np.array_equal(o.recv, res.recv)
    else:
        # a one-tick straggler shift means one extra/fewer gossip send
        # around the removal tick; totals must stay within a few messages
        assert np.abs(o.sent - res.sent).sum() <= 6
        assert np.abs(o.recv - res.recv).sum() <= 6


def test_detection_latency_exact(scenario):
    """Failure at t=100 is removed by every survivor at exactly
    t = 100 + TREMOVE + 1 = 121 in the no-drop scenarios (BASELINE.md);
    under 10% drop stragglers may take a few ticks longer."""
    cfg = scenario_cfg(scenario, seed=3)
    res = Simulation(cfg).run()
    gv = res.grader_view()
    failed = gv["failed"]
    survivors = set(range(cfg.n)) - failed
    for f in failed:
        observers = {obs for (obs, subj) in gv["removal_ticks"] if subj == f}
        assert observers == survivors
    ticks = list(gv["removal_ticks"].values())
    if cfg.drop_msg:
        assert all(121 <= t <= 126 for t in ticks)
    else:
        assert all(t == 121 for t in ticks)


def test_join_completeness(scenario):
    """Every peer observes every other peer join (Grader.sh:40-60)."""
    cfg = scenario_cfg(scenario, seed=4)
    gv = Simulation(cfg).run().grader_view()
    assert gv["joins"] == {(i, j) for i in range(cfg.n)
                           for j in range(cfg.n) if i != j}


def test_no_false_positives_no_drop():
    for scen in ("singlefailure", "multifailure"):
        cfg = scenario_cfg(scen, seed=5)
        gv = Simulation(cfg).run().grader_view()
        assert all(subj in gv["failed"] for (_, subj) in gv["removal_ticks"])


def test_determinism_and_seed_sensitivity():
    cfg = scenario_cfg("msgdropsinglefailure", seed=11)
    r1 = Simulation(cfg).run()
    r2 = Simulation(cfg).run()
    assert np.array_equal(r1.added, r2.added)
    assert np.array_equal(r1.sent, r2.sent)
    r3 = Simulation(scenario_cfg("msgdropsinglefailure", seed=12)).run()
    assert not np.array_equal(r1.sent, r3.sent)


@pytest.mark.parametrize("single", [True, False])
def test_start_after_fail_parity(single):
    """A peer whose start tick falls after its fail tick still sends its
    JOINREQ — the driver's introduction branch does not check bFailed
    (Application.cpp:142-147; only recvLoop/nodeLoop do).  The
    introducer admits the silent peer and everyone removes it TREMOVE
    ticks later.  Exercised here with an early fail tick so
    start_tick > fail_tick is reachable at small N; full exact parity
    against the message-level oracle."""
    seed = 1 if single else 0   # victim 19 (start 4) / block [5, 17)
    cfg = scenario_cfg("singlefailure" if single else "multifailure",
                       max_nnb=24, fail_tick=3, total_ticks=80, seed=seed)
    res = Simulation(cfg).run()
    start = res.start_tick
    fail = res.fail_tick
    late = (start > fail) & (fail <= cfg.total_ticks)
    assert late.any(), "schedule must exercise start_tick > fail_tick"

    o = ReferenceOracle(cfg, start, fail).run()
    gv = res.grader_view()
    assert {(i, j) for (_, i, j) in o.events.added} == gv["joins"]
    oracle_removals = {}
    for (t, i, j) in o.events.removed:
        oracle_removals.setdefault((i, j), t)
    assert oracle_removals == gv["removal_ticks"]
    assert np.array_equal(o.known_matrix(), np.asarray(res.final_state.known))
    assert np.array_equal(o.sent, res.sent)
    assert np.array_equal(o.recv, res.recv)
    # the late-started victims were admitted (introducer logged a join)
    # and then removed TREMOVE+1 ticks after their start
    for j in np.flatnonzero(late):
        assert (0, j) in gv["joins"]
        assert gv["removal_ticks"][(0, j)] == start[j] + cfg.t_remove + 1


@pytest.mark.slow
def test_bench_scale_invariants():
    """Grader-style validation of the benchmarked N=512 configuration
    (multifailure block covering late starters; no drop so the checks
    are exact).  The reference cannot run this shape at all (N<=10
    merge cap MP1Node.cpp:245, 30k-message buffer EmulNet.h:12)."""
    cfg = SimConfig(max_nnb=512, single_failure=False, drop_msg=False,
                    seed=1, total_ticks=160)
    res = Simulation(cfg).run()
    gv = res.grader_view()
    start = res.start_tick
    failed = gv["failed"]
    assert len(failed) == 256
    late_victims = {j for j in failed if start[j] > cfg.fail_tick}
    assert late_victims, "seed must place the failure block over late starters"

    # no false positives: every removal names a failed peer
    assert {subj for (_, subj) in gv["removal_ticks"]} <= failed

    # early-started live observers see every other peer join, including
    # the late-started victims the introducer admits posthumously
    early_live = [i for i in range(cfg.n)
                  if i not in failed and start[i] <= 79]
    for i in early_live[:: max(1, len(early_live) // 16)]:
        assert {j for (obs, j) in gv["joins"] if obs == i} \
            == set(range(cfg.n)) - {i}

    removals_by_subject = {}
    for (obs, subj), t in gv["removal_ticks"].items():
        removals_by_subject.setdefault(subj, {})[obs] = t
    t_det = cfg.fail_tick + cfg.t_remove + 1
    for j in failed:
        by_obs = removals_by_subject[j]
        if j in late_victims:
            # silent posthumous member: entry ts is pinned at its
            # introduction tick, so every observer removes at
            # start + TREMOVE + 1 exactly
            assert set(by_obs.values()) == {start[j] + cfg.t_remove + 1}, j
        elif start[j] <= cfg.fail_tick - 4:
            # fully-active victim: joined, learned the full membership,
            # and gossiped to everyone through the fail tick.  Observers
            # started before the failure refresh its timestamp from its
            # final gossip and detect at exactly fail + TREMOVE + 1 =
            # 121; observers that joined after the failure hold a
            # one-tick-older piggybacked copy.
            for obs, t in by_obs.items():
                if start[obs] <= cfg.fail_tick:
                    assert t == t_det, (obs, j, t)
                else:
                    assert t_det - 1 <= t <= t_det + 1, (obs, j, t)
        else:
            # boundary victim (started within the JOINREQ/JOINREP
            # round-trip of the fail tick): it may have gossiped zero or
            # a few times before failing, so per-observer timestamps
            # span its introduction tick through its last relayed
            # refresh — detection lands within a small window
            for t in by_obs.values():
                assert start[j] + cfg.t_remove <= t <= t_det + 2, (j, t)
        # every early-started live observer detects every victim
        assert set(early_live) <= set(by_obs), j


def test_scales_past_reference_cap():
    """The reference hard-caps at N=10 (MP1Node.cpp:245 merge filter);
    the framework must not.  N=64 joins completely and detects exactly."""
    cfg = scenario_cfg("singlefailure", max_nnb=64, seed=0)
    res = Simulation(cfg).run()
    gv = res.grader_view()
    assert len(gv["joins"]) == 64 * 63
    failed = gv["failed"]
    assert len(failed) == 1
    assert all(t == 121 for t in gv["removal_ticks"].values())
    assert {obs for (obs, _) in gv["removal_ticks"]} == set(range(64)) - failed
