"""Differential parity: the vectorized TPU tick vs the scalar oracle.

The oracle (testing/oracle.py) re-implements the reference's
message-by-message semantics including EmulNet buffer ordering; both
sides consume identical drop decisions.  Everything grader-visible must
match exactly: membership tables, timestamps, event sets, removal
times, and per-tick send/recv accounting.  Heartbeat counters may
diverge by at most 1 in entries created during the join transient (the
documented canonical-order effect, core/tick.py docstring).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.state import make_schedule
from gossip_protocol_tpu.testing.dropsync import make_drop_masks
from gossip_protocol_tpu.testing.oracle import ReferenceOracle
from tests.conftest import scenario_cfg


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_full_parity(scenario, seed):
    cfg = scenario_cfg(scenario, seed=seed)
    res = Simulation(cfg).run()
    sched = make_schedule(cfg)
    drops = make_drop_masks(cfg, sched) if cfg.drop_msg else (None, None, None)
    o = ReferenceOracle(cfg, res.start_tick, res.fail_tick, *drops).run()

    gv = res.grader_view()
    # event sets
    assert {(i, j) for (_, i, j) in o.events.added} == gv["joins"]
    oracle_removals = {}
    for (t, i, j) in o.events.removed:
        oracle_removals.setdefault((i, j), t)
    if not cfg.drop_msg:
        assert oracle_removals == gv["removal_ticks"]
    else:
        # Under message drop, heartbeat values diverge by the documented
        # +/-1 join-transient (core/tick.py), which can shift a
        # drop-starved straggler's merge-refresh — and so its removal —
        # by a tick.  The removal *set* must still match exactly.
        assert set(oracle_removals) == set(gv["removal_ticks"])
        for k, t_o in oracle_removals.items():
            assert abs(t_o - gv["removal_ticks"][k]) <= 2, (k, t_o)

    # final tables
    km = o.known_matrix()
    assert np.array_equal(km, np.asarray(res.final_state.known))
    ts_diff = o.table("ts") - np.asarray(res.final_state.ts) * km
    if not cfg.drop_msg:
        assert not ts_diff.any()
    else:
        # Failed nodes freeze their table at the fail tick; the +/-1
        # heartbeat transient can shift one last merge-refresh by a tick
        # under drop, and the frozen row preserves it.  Live rows still
        # converge exactly.
        frozen = (np.asarray(res.fail_tick) <= cfg.total_ticks)[:, None]
        assert not (ts_diff * ~frozen).any()
        assert np.abs(ts_diff).max() <= 1
    hb_diff = o.table("hb") - np.asarray(res.final_state.hb) * km
    assert np.abs(hb_diff).max() <= 1

    # accounting parity (drives msgcount.log, EmulNet.cpp:184-220)
    if not cfg.drop_msg:
        assert np.array_equal(o.sent, res.sent)
        assert np.array_equal(o.recv, res.recv)
    else:
        # a one-tick straggler shift means one extra/fewer gossip send
        # around the removal tick; totals must stay within a few messages
        assert np.abs(o.sent - res.sent).sum() <= 6
        assert np.abs(o.recv - res.recv).sum() <= 6


def test_detection_latency_exact(scenario):
    """Failure at t=100 is removed by every survivor at exactly
    t = 100 + TREMOVE + 1 = 121 in the no-drop scenarios (BASELINE.md);
    under 10% drop stragglers may take a few ticks longer."""
    cfg = scenario_cfg(scenario, seed=3)
    res = Simulation(cfg).run()
    gv = res.grader_view()
    failed = gv["failed"]
    survivors = set(range(cfg.n)) - failed
    for f in failed:
        observers = {obs for (obs, subj) in gv["removal_ticks"] if subj == f}
        assert observers == survivors
    ticks = list(gv["removal_ticks"].values())
    if cfg.drop_msg:
        assert all(121 <= t <= 126 for t in ticks)
    else:
        assert all(t == 121 for t in ticks)


def test_join_completeness(scenario):
    """Every peer observes every other peer join (Grader.sh:40-60)."""
    cfg = scenario_cfg(scenario, seed=4)
    gv = Simulation(cfg).run().grader_view()
    assert gv["joins"] == {(i, j) for i in range(cfg.n)
                           for j in range(cfg.n) if i != j}


def test_no_false_positives_no_drop():
    for scen in ("singlefailure", "multifailure"):
        cfg = scenario_cfg(scen, seed=5)
        gv = Simulation(cfg).run().grader_view()
        assert all(subj in gv["failed"] for (_, subj) in gv["removal_ticks"])


def test_determinism_and_seed_sensitivity():
    cfg = scenario_cfg("msgdropsinglefailure", seed=11)
    r1 = Simulation(cfg).run()
    r2 = Simulation(cfg).run()
    assert np.array_equal(r1.added, r2.added)
    assert np.array_equal(r1.sent, r2.sent)
    r3 = Simulation(scenario_cfg("msgdropsinglefailure", seed=12)).run()
    assert not np.array_equal(r1.sent, r3.sent)


def test_scales_past_reference_cap():
    """The reference hard-caps at N=10 (MP1Node.cpp:245 merge filter);
    the framework must not.  N=64 joins completely and detects exactly."""
    cfg = scenario_cfg("singlefailure", max_nnb=64, seed=0)
    res = Simulation(cfg).run()
    gv = res.grader_view()
    assert len(gv["joins"]) == 64 * 63
    failed = gv["failed"]
    assert len(failed) == 1
    assert all(t == 121 for t in gv["removal_ticks"].values())
    assert {obs for (obs, _) in gv["removal_ticks"]} == set(range(64)) - failed
