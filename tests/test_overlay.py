"""Overlay model: differential parity against the numpy oracle at
small N (bit-exact state trajectories — all randomness and schedules
are shared counter hashing), plus convergence/detection/accuracy
invariants at medium N.

Accuracy semantics: in a bounded partial view, per-holder staleness
removals are expected background churn (an entry's refresh is
arrival-limited); the guarantees asserted here are the global ones —
every live member stays covered by the group's union of views, failed
peers are purged everywhere within the detection horizon, and the
group re-covers rejoining peers (models/overlay.py docstring).
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (OverlaySimulation,
                                                init_overlay_state,
                                                make_overlay_schedule,
                                                make_overlay_tick)
from gossip_protocol_tpu.state import NEVER
from gossip_protocol_tpu.testing.overlay_oracle import OverlayOracle


def _overlay_cfg(**kw):
    base = dict(model="overlay", single_failure=True, drop_msg=False,
                seed=0, max_nnb=32, total_ticks=80, fail_tick=30)
    base.update(kw)
    return SimConfig(**base)


@pytest.mark.parametrize("name,kw", [
    ("plain", {}),
    ("drop", dict(drop_msg=True, msg_drop_prob=0.15, drop_open_tick=10,
                  drop_close_tick=60)),
    ("churn_single", dict(rejoin_after=25, total_ticks=100)),
    ("churn_rate", dict(single_failure=False, churn_rate=0.3,
                        rejoin_after=20, total_ticks=120, seed=5)),
    ("wide", dict(max_nnb=64, seed=3, overlay_view=16, overlay_sample=4,
                  fanout=4)),
    ("powerlaw", dict(max_nnb=64, seed=6, topology="powerlaw",
                      total_ticks=100, drop_msg=True, msg_drop_prob=0.1,
                      drop_open_tick=20, drop_close_tick=80)),
    # the adversarial failure worlds (worlds.py, PR 9) — every draw is
    # the same counter-hash discipline, so the oracle replays them
    # bit-exactly too
    ("partition", dict(partition_groups=2, partition_open_tick=20,
                       partition_close_tick=55, seed=4)),
    ("asym_drop", dict(drop_msg=True, msg_drop_prob=0.15, asym_drop=True,
                       drop_open_tick=10, drop_close_tick=60, seed=2)),
    ("wave", dict(single_failure=False, wave_size=8, wave_tick=35,
                  wave_speed=2, seed=7)),
    ("zombie", dict(zombie=True, seed=8)),
    ("zombie_rejoin", dict(zombie=True, rejoin_after=25,
                           total_ticks=100, seed=9)),
    ("flapping", dict(flap_rate=0.4, flap_period=24, flap_down=6,
                      fail_tick=10_000, total_ticks=100, seed=10)),
    ("part_asym_flap", dict(partition_groups=3, partition_open_tick=25,
                            partition_close_tick=50, drop_msg=True,
                            msg_drop_prob=0.1, asym_drop=True,
                            flap_rate=0.25, flap_period=20, flap_down=5,
                            total_ticks=100, seed=11)),
])
def test_overlay_oracle_parity(name, kw):
    """Bit-exact state trajectory vs the scalar oracle."""
    cfg = _overlay_cfg(**kw)
    sched = make_overlay_schedule(cfg)
    tick = jax.jit(make_overlay_tick(cfg))
    state = init_overlay_state(cfg)
    oracle = OverlayOracle(cfg)
    for t in range(cfg.total_ticks):
        state, m = tick(state, sched)
        counters = oracle.step()
        for field in ("ids", "hb", "ts", "send_flags"):
            got = np.asarray(getattr(state, field))
            want = getattr(oracle, field)
            assert np.array_equal(got, want), (name, t, field)
        assert np.array_equal(np.asarray(state.in_group), oracle.in_group), (name, t)
        assert np.array_equal(np.asarray(state.own_hb), oracle.own_hb), (name, t)
        assert np.array_equal(np.asarray(state.joinreq), oracle.joinreq), (name, t)
        assert np.array_equal(np.asarray(state.joinrep), oracle.joinrep), (name, t)
        assert int(m.sent) == counters["sent"], (name, t)
        assert int(m.recv) == counters["recv"], (name, t)
        assert int(m.removals) == counters["removals"], (name, t)


def _assert_coverage_holes_transient(unc, n, bound=None, budget=0.001):
    """The coverage contract on a live_uncovered series: holes are
    transient (re-covered within SLOT_EPOCH + 1 ticks — the bound
    test_recover_bound establishes; under the freshness-majorized key
    a member re-covers via its next direct reseed, typically 1 tick)
    and rare (a tiny fraction of member-ticks)."""
    from gossip_protocol_tpu.models.overlay import SLOT_EPOCH
    unc = np.asarray(unc)
    # a -1 means live_uncovered was not tracked (kernel-path sentinel):
    # this helper would then pass vacuously, so fail loudly instead
    assert (unc >= 0).all(), "live_uncovered not tracked on this path"
    bound = SLOT_EPOCH + 1 if bound is None else bound
    run = 0
    for t, v in enumerate(unc):
        run = run + 1 if v > 0 else 0
        assert run <= bound, f"coverage hole persisted {run} ticks at {t}"
    assert unc.sum() <= max(3, budget * n * unc.size), \
        f"coverage holes too frequent ({unc.sum()} member-ticks)"


def test_overlay_converges_and_detects():
    """N=512: everyone joins, the union of views covers every live
    member (holes only transient, within the re-cover bound) after the
    join phase, and the victim is purged from all views within the
    detection horizon."""
    cfg = SimConfig(max_nnb=512, model="overlay", single_failure=True,
                    drop_msg=False, seed=1, total_ticks=220, fail_tick=120)
    res = OverlaySimulation(cfg).run()
    m = res.metrics
    n = cfg.n
    joined = np.flatnonzero(np.asarray(m.in_group) == n)
    last_start = int(cfg.step_rate * (n - 1))
    assert joined.size and joined[0] <= last_start + 4, "join phase too slow"
    # global coverage of live members holds once the last joiner's
    # first gossip lands — transient single-tick holes within the
    # re-cover bound are the documented contention background
    _assert_coverage_holes_transient(
        np.asarray(m.live_uncovered)[joined[0] + 3:], n)
    # victim purged from every view within TREMOVE + sampling slack
    vs = np.asarray(m.victim_slots)
    horizon = cfg.fail_tick + cfg.t_remove + 10
    assert (vs[horizon:] == 0).all()
    assert vs[cfg.fail_tick - 5: cfg.fail_tick].sum() == 0
    # background per-holder staleness churn stays marginal
    total_entry_ticks = np.asarray(m.view_slots)[joined[0]:].sum()
    assert np.asarray(m.false_removals).sum() < 0.001 * total_entry_ticks
    # live views stay near capacity (resolved K, not the 0=auto config
    # knob).  The fail-stopped victim's frozen table is dead state and
    # decays through the SLOT_EPOCH re-rolls (birthday collisions with
    # no refill), so only live nodes are held to the capacity bar.
    from gossip_protocol_tpu.models.overlay import resolved_dims
    k_resolved = resolved_dims(cfg)[0]
    ids = np.asarray(res.final_state.ids)
    import jax.numpy as jnp
    sched = res.sched
    i = jnp.arange(cfg.n)
    t_end = int(np.asarray(res.final_state.tick))
    failed = np.asarray((t_end > sched.fail_of(i))
                        & (t_end <= sched.rejoin_of(i)))
    assert (ids >= 0).sum(1)[~failed].min() >= k_resolved - 8
    # host-side final coverage agrees
    uncovered, victim_left = res.final_coverage()
    assert uncovered == 0 and victim_left == 0


def test_overlay_churn_recovers():
    """20%-churn shape (the BASELINE 65k scenario, scaled down): churned
    peers leave, are purged, rejoin, and the group re-covers them."""
    cfg = SimConfig(max_nnb=512, model="overlay", single_failure=False,
                    drop_msg=False, seed=2, total_ticks=300,
                    churn_rate=0.2, rejoin_after=40, step_rate=0.05)
    sched = make_overlay_schedule(cfg)
    import jax.numpy as jnp
    fail = np.asarray(sched.fail_of(jnp.arange(cfg.n)))
    churned = fail != NEVER
    assert 0.1 < churned.mean() < 0.3
    res = OverlaySimulation(cfg).run()
    m = res.metrics
    # everyone is back in the group at the end (rejoins completed)
    assert int(np.asarray(m.in_group)[-1]) == cfg.n
    # every live member covered at the end, and no victim entries linger
    assert int(np.asarray(m.live_uncovered)[-1]) == 0
    assert int(np.asarray(m.victim_slots)[-1]) == 0
    uncovered, victim_left = res.final_coverage()
    assert uncovered == 0 and victim_left == 0
    # churn window saw real departures (membership dipped mid-run)
    assert int(np.asarray(m.in_group).min()) < cfg.n
    # and their view entries were purged (evicted by fresh rivals or
    # staleness-removed — victim_slots reaching 0 covers both paths)
    assert int(np.asarray(m.victim_slots).max()) > 0


def test_overlay_powerlaw_topology():
    """Scale-free out-degrees (BASELINE's 1M shape): degrees follow the
    bounded Pareto tail, and the global guarantees still hold — every
    live member covered, victim purged within the (slower, low-mean-
    degree) horizon."""
    from gossip_protocol_tpu.models.overlay import (_SALT_DEGREE,
                                                    degree_thresholds,
                                                    resolved_dims)
    from gossip_protocol_tpu.utils.hash32 import mix32

    cfg = SimConfig(max_nnb=512, model="overlay", single_failure=True,
                    drop_msg=False, seed=1, total_ticks=260, fail_tick=140,
                    topology="powerlaw")
    k, f = resolved_dims(cfg)
    assert f == 8
    # the seeded degree distribution matches the bounded Pareto tail
    thr = degree_thresholds(cfg, f)
    du = np.asarray([int(mix32(np.uint32(cfg.seed), np.uint32(i),
                               np.uint32(_SALT_DEGREE)))
                     for i in range(cfg.n)], np.int64)
    deg = 1 + (du[:, None] < thr[None, :].astype(np.int64)).sum(1)
    assert deg.min() == 1 and deg.max() == f
    assert 1.4 < deg.mean() < 2.6          # ~1.9 expected at alpha=2.5
    res = OverlaySimulation(cfg).run()
    m = res.metrics
    joined = np.flatnonzero(np.asarray(m.in_group) == cfg.n)
    assert joined.size
    # coverage: direct self-entries re-seed it even for degree-1 leaves
    # (holes only transient, within the re-cover bound)
    _assert_coverage_holes_transient(
        np.asarray(m.live_uncovered)[joined[0] + 3:], cfg.n)
    # victim purged (low supply -> allow extra sampling slack)
    vs = np.asarray(m.victim_slots)
    assert (vs[cfg.fail_tick + cfg.t_remove + 20:] == 0).all()
    uncovered, victim_left = res.final_coverage()
    assert uncovered == 0 and victim_left == 0


def test_overlay_staleness_removal_fires():
    """With K >> N every slot class is a near-singleton, so a failed
    peer's entries have no contending rival, survive the SLOT_EPOCH
    re-rolls slot-alone, and MUST age out through the TREMOVE
    staleness path (MP1Node.cpp:339-348 analog) — the detection
    machinery is exercised, not just eviction-purge."""
    cfg = SimConfig(max_nnb=64, model="overlay", single_failure=True,
                    drop_msg=False, seed=4, total_ticks=160, fail_tick=80,
                    overlay_view=1024, step_rate=0.5)
    res = OverlaySimulation(cfg).run()
    m = res.metrics
    removals = np.asarray(m.removals)
    horizon = cfg.fail_tick + cfg.t_remove + 11
    # every survivor staleness-removes the victim inside the horizon
    assert removals[cfg.fail_tick:horizon].sum() == cfg.n - 1
    assert (np.asarray(m.victim_slots)[horizon:] == 0).all()
    assert int(np.asarray(m.false_removals).sum()) == 0


def test_overlay_deterministic_and_seed_sensitive():
    cfg = _overlay_cfg(max_nnb=64, total_ticks=60)
    r1 = OverlaySimulation(cfg).run()
    r2 = OverlaySimulation(cfg).run()
    assert np.array_equal(np.asarray(r1.final_state.ids),
                          np.asarray(r2.final_state.ids))
    assert np.array_equal(np.asarray(r1.metrics.sent), np.asarray(r2.metrics.sent))
    r3 = OverlaySimulation(cfg.replace(seed=9)).run()
    assert not np.array_equal(np.asarray(r1.final_state.ids),
                              np.asarray(r3.final_state.ids))


def test_overlay_memory_is_bounded():
    """State is O(N*K), not O(N^2): the tables have the configured
    widths regardless of N."""
    cfg = _overlay_cfg(max_nnb=256, overlay_view=32, overlay_sample=8,
                       fanout=6)
    s = init_overlay_state(cfg)
    assert s.ids.shape == (256, 32)
    assert s.send_flags.shape == (256, 6)


def test_overlay_requires_power_of_two():
    """The power-of-two-n restriction fires EARLY, at config
    construction, with the reason and the nearest valid n — a bad n
    used to fail deep in the XOR exchange (PR 9 satellite)."""
    with pytest.raises(ValueError, match="power of two") as ei:
        _overlay_cfg(max_nnb=48)
    # 48 sits exactly between 32 and 64; the tie goes low
    assert "nearest valid n is 32" in str(ei.value)
    with pytest.raises(ValueError, match="nearest valid n is 4"):
        _overlay_cfg(max_nnb=3)
    # the dense model keeps arbitrary n
    SimConfig(max_nnb=48)


def test_overlay_checkpoint_resume_bit_identical(tmp_path):
    """40+40 stitched run == uninterrupted 80-tick run, through a file
    round trip (the schedule is closed-form in the absolute clock)."""
    import dataclasses

    from gossip_protocol_tpu.models.overlay import (
        OverlayMetrics, load_overlay_checkpoint, overlay_state_from_host,
        overlay_state_to_host, save_overlay_checkpoint)

    cfg = _overlay_cfg(max_nnb=64, total_ticks=80, drop_msg=True,
                       msg_drop_prob=0.1, drop_open_tick=10,
                       drop_close_tick=70)
    sim = OverlaySimulation(cfg)
    full = sim.run()

    first = sim.run(ticks=40)
    p = tmp_path / "ov.ckpt"
    save_overlay_checkpoint(first.final_state, str(p))
    second = sim.run(resume_from=load_overlay_checkpoint(str(p)))

    for f in dataclasses.fields(type(full.final_state)):
        assert np.array_equal(np.asarray(getattr(full.final_state, f.name)),
                              np.asarray(getattr(second.final_state, f.name))), f.name
    for f in dataclasses.fields(OverlayMetrics):
        a = np.asarray(getattr(full.metrics, f.name))
        b = np.concatenate([np.asarray(getattr(first.metrics, f.name)),
                            np.asarray(getattr(second.metrics, f.name))])
        assert np.array_equal(a, b), f.name

    # schema validation
    d = overlay_state_to_host(first.final_state)
    d.pop("hb")
    with pytest.raises(ValueError, match="missing"):
        overlay_state_from_host(d)


def test_recover_bound():
    """The stated coverage guarantee (bench.py gates on it): a live
    member uncovered in a snapshot is re-covered within
    ``SLOT_EPOCH + 1`` ticks.

    Why the bound holds: a live member's self-entry is reseeded at F
    fresh (per-tick re-randomized) partners every tick, and under the
    freshness-majorized key (models/overlay.py _pack_key) its tick-
    (t-1) timestamp outranks every relayed table rival — it can only
    keep losing to *equal-ts rivals* (other direct entries, or a
    relayed copy of a JOINREQ entry) colliding in the same global
    slot with a larger id, and both the per-tick partner re-draw and
    the SLOT_EPOCH re-roll retire any such collision, so the gap
    cannot outlive the current epoch plus the one tick the next send
    needs to land.  Provoked here with a deliberately tiny view
    (K=8 at N=512: 64x slot contention vs auto-K) so snapshot holes
    actually occur.
    """
    from gossip_protocol_tpu.config import INTRODUCER
    from gossip_protocol_tpu.models.overlay import (
        SLOT_EPOCH, init_overlay_state, make_overlay_schedule,
        make_overlay_tick)

    # single failure scheduled past the observation window, so every
    # non-introducer member is live throughout it
    cfg = SimConfig(max_nnb=512, model="overlay", single_failure=True,
                    drop_msg=False, seed=5, total_ticks=400,
                    fail_tick=398, overlay_view=8, step_rate=0.5)
    n = cfg.n
    sched = make_overlay_schedule(cfg)
    tick = jax.jit(make_overlay_tick(cfg, use_pallas=False))
    state = init_overlay_state(cfg)
    warm = int(cfg.step_rate * (n - 1)) + 20       # past the join ramp
    for _ in range(warm):
        state, _ = tick(state, sched)

    window = 3 * SLOT_EPOCH
    bound = SLOT_EPOCH + 1
    covered = np.zeros((window, n), bool)
    for t in range(window):
        ids = np.asarray(state.ids)
        cov = np.zeros(n, bool)
        cov[ids[ids >= 0]] = True
        covered[t] = cov
        state, _ = tick(state, sched)

    member = np.ones(n, bool)
    member[INTRODUCER] = False                     # never holds itself only
    holes = 0
    for t in range(window - bound):
        uncov = member & ~covered[t]
        holes += int(uncov.sum())
        recovered = covered[t + 1:t + 1 + bound].any(0)
        stuck = np.flatnonzero(uncov & ~recovered)
        assert stuck.size == 0, \
            f"members {stuck[:5]} uncovered at +{bound} ticks (t={t})"
    # the config must actually provoke contention holes, or the bound
    # was never exercised
    assert holes > 0, "contention config produced no snapshot holes"
