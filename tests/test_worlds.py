"""Adversarial failure worlds (worlds.py, PR 9): dense-model semantics
vs the message-level reference oracle, and fleet/solo bit-parity for
every world on both models.

The worlds are pure ``(seed, tick, node)`` counter/PRNG draws layered
on the existing schedule machinery, so the differential discipline is
the same as the course worlds': the oracle consumes the byte-identical
drop decisions (testing/dropsync.py now folds the asym per-link
thresholds and the partition's deterministic cross-group mask into the
masks exactly as the tick does), wave schedules ride the fail-tick
array, and zombie/flap semantics are implemented on both sides.
Overlay-side bit-exactness lives in
tests/test_overlay.py::test_overlay_oracle_parity (world cases added
there); this file owns the dense model and the cross-model fleet
parity sweep.
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu import worlds
from gossip_protocol_tpu.config import INTRODUCER, SimConfig
from gossip_protocol_tpu.core.fleet import FleetSimulation
from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.models.overlay import OverlaySimulation
from gossip_protocol_tpu.state import NEVER, make_schedule
from gossip_protocol_tpu.testing.dropsync import make_drop_masks
from gossip_protocol_tpu.testing.oracle import ReferenceOracle

DENSE_STATE = ("tick", "in_group", "own_hb", "known", "hb", "ts",
               "gossip", "joinreq", "joinrep")
OV_STATE = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
            "send_flags", "joinreq", "joinrep")


def _dense(**kw):
    base = dict(max_nnb=16, single_failure=True, drop_msg=False, seed=2,
                total_ticks=120, fail_tick=40)
    base.update(kw)
    return SimConfig(**base)


def _overlay(**kw):
    base = dict(model="overlay", max_nnb=64, single_failure=True,
                drop_msg=False, seed=2, total_ticks=96, fail_tick=40,
                step_rate=8.0 / 64)
    base.update(kw)
    return SimConfig(**base)


DENSE_WORLDS = {
    "partition": dict(partition_groups=2, partition_open_tick=30,
                      partition_close_tick=70),
    "asym_drop": dict(drop_msg=True, msg_drop_prob=0.12, asym_drop=True,
                      drop_open_tick=10, drop_close_tick=90),
    "wave": dict(single_failure=False, wave_size=6, wave_tick=40,
                 wave_speed=2),
    "zombie": dict(zombie=True),
    "flapping": dict(flap_rate=0.4, flap_period=24, flap_down=6,
                     fail_tick=10_000),
}

OVERLAY_WORLDS = {
    "partition": dict(partition_groups=2, partition_open_tick=30,
                      partition_close_tick=60),
    "asym_drop": dict(drop_msg=True, msg_drop_prob=0.1, asym_drop=True,
                      drop_open_tick=10, drop_close_tick=80),
    "wave": dict(single_failure=False, wave_size=6, wave_tick=40,
                 wave_speed=2),
    "zombie": dict(zombie=True),
    "flapping": dict(flap_rate=0.3, flap_period=24, flap_down=6,
                     fail_tick=10_000),
}


def _oracle_for(cfg, res):
    sched = make_schedule(cfg)
    inject = cfg.drop_msg or cfg.partition_groups >= 2
    drops = make_drop_masks(cfg, sched) if inject else (None, None, None)
    flap = worlds.make_flap_state(cfg) if cfg.flap_rate > 0 else None
    return ReferenceOracle(cfg, res.start_tick, res.fail_tick, *drops,
                           rejoin_tick=res.rejoin_tick,
                           flap_state=flap).run()


@pytest.mark.parametrize("name", sorted(DENSE_WORLDS))
def test_dense_world_oracle_parity(name):
    """Every world's dense tick vs the message-level oracle: event
    sets, final membership, and (for PRNG-free worlds) exact removal
    ticks and accounting."""
    cfg = _dense(**DENSE_WORLDS[name])
    res = Simulation(cfg).run()
    o = _oracle_for(cfg, res)

    gv = res.grader_view()
    assert {(i, j) for (_, i, j) in o.events.added} == gv["joins"], name
    oracle_removals = {}
    for (t, i, j) in o.events.removed:
        oracle_removals.setdefault((i, j), t)
    # message-lossy worlds (drop window, partition) admit the
    # documented +/-1 canonical-order heartbeat transient, which can
    # shift a starved straggler's removal by a tick or two; loss-free
    # worlds must match exactly, accounting included
    lossy = cfg.drop_msg or cfg.partition_groups >= 2
    if lossy:
        assert set(oracle_removals) == set(gv["removal_ticks"]), name
        for k2, t_o in oracle_removals.items():
            assert abs(t_o - gv["removal_ticks"][k2]) <= 2, (name, k2)
    else:
        assert oracle_removals == gv["removal_ticks"], name
        assert np.array_equal(o.sent, res.sent), name
        assert np.array_equal(o.recv, res.recv), name
    assert np.array_equal(o.known_matrix(),
                          np.asarray(res.final_state.known)), name


def test_dense_zombie_detected_despite_gossip():
    """The zombie keeps sending its frozen table after the fail tick
    (observable traffic), yet is still removed from every live view
    within the horizon, and its stale table resurrects nobody."""
    cfg = _dense(zombie=True)
    res = Simulation(cfg).run()
    silent = Simulation(cfg.replace(zombie=False)).run()
    # the zombie world strictly adds traffic after the fail tick
    assert res.sent[:, cfg.fail_tick + 1:].sum() \
        > silent.sent[:, cfg.fail_tick + 1:].sum()
    victim = int(np.flatnonzero(res.fail_tick != NEVER)[0])
    known = np.asarray(res.final_state.known)
    live = np.ones(cfg.n, bool)
    live[victim] = False
    assert not known[live, victim].any(), "zombie never removed"
    # no resurrection: after an observer removes the victim, it never
    # re-adds it (the stale table's entries age out of the fresh gate)
    rem_t = {}
    for t, i, j in zip(*np.nonzero(res.removed)):
        if j == victim:
            rem_t.setdefault(i, t)
    assert rem_t, "victim was never removed by anyone"
    for t, i, j in zip(*np.nonzero(res.added)):
        if j == victim and i in rem_t:
            assert t <= rem_t[i], f"observer {i} resurrected the zombie"


def test_dense_partition_semantics():
    """The dense full-view protocol's honest partition behavior, both
    regimes.  A partition LONGER than t_remove causes mutual
    cross-group removal, and because the reference protocol gossips
    only to KNOWN members there is no discovery path back: the split
    is permanent (same-group liveness untouched).  A partition SHORTER
    than t_remove ends before any entry crosses the staleness horizon:
    zero removals, full membership at the end.  (The overlay model
    re-converges after a long partition because its XOR exchange
    delivers by index, not by membership — pinned by the partition
    scenario oracle in models/scenarios.py.)"""
    # long partition (40 > t_remove=20): permanent split
    cfg = _dense(partition_groups=2, partition_open_tick=30,
                 partition_close_tick=70, total_ticks=160,
                 fail_tick=10_000)
    g = worlds.partition_groups_host(cfg)
    res = Simulation(cfg).run()
    known = np.asarray(res.final_state.known)
    n = cfg.n
    same = g[:, None] == g[None, :]
    off = ~np.eye(n, dtype=bool)
    assert (known | ~(same & off)).all(), "same-group entries lost"
    assert not known[~same].any(), \
        "cross-group entries survived a partition longer than t_remove"
    cross_rm = [(t, i, j) for t, i, j in zip(*np.nonzero(res.removed))
                if g[i] != g[j]]
    assert cross_rm, "no cross-group removals during the partition"
    same_rm = [(t, i, j) for t, i, j in zip(*np.nonzero(res.removed))
               if g[i] == g[j]]
    assert not same_rm, "partition must not disturb same-group liveness"
    # short partition (12 < t_remove=20): heals with zero removals
    cfg2 = _dense(partition_groups=2, partition_open_tick=30,
                  partition_close_tick=42, total_ticks=120,
                  fail_tick=10_000)
    res2 = Simulation(cfg2).run()
    assert not np.asarray(res2.removed).any(), \
        "sub-horizon partition caused removals"
    assert (np.asarray(res2.final_state.known) | ~off).all(), \
        "sub-horizon partition did not heal"


def test_overlay_partition_reconverges_after_heal():
    """The overlay's partition tolerance: the XOR exchange delivers by
    INDEX, so after the window closes cross-group freshness flows
    again and every live member is re-covered — even though the
    partition (60 > t_remove) starved every cross-group entry in
    between."""
    cfg = _overlay(partition_groups=2, partition_open_tick=30,
                   partition_close_tick=90, total_ticks=160,
                   fail_tick=10_000)
    res = OverlaySimulation(cfg).run()
    unc, victim_left = res.final_coverage()
    assert unc == 0 and victim_left == 0


def test_dense_flapping_no_false_removals():
    """flap_down < t_remove: a flapper's silences are shorter than the
    staleness horizon, so no observer ever removes anyone."""
    cfg = _dense(flap_rate=0.4, flap_period=24, flap_down=6,
                 fail_tick=10_000, total_ticks=140)
    assert worlds.flap_mask_host(cfg).sum() >= 2, "world never engaged"
    res = Simulation(cfg).run()
    assert not np.asarray(res.removed).any(), \
        "flapping below the horizon caused removals"


def test_wave_fail_ticks_shape():
    """Closed-form wave properties: contiguous ring block from the
    seeded epicenter, one radius step per wave_speed ticks, introducer
    exempt, seeds move the epicenter but never the window."""
    cfg = _dense(single_failure=False, wave_size=6, wave_tick=40,
                 wave_speed=2)
    ft = worlds.wave_fail_ticks(cfg)
    vic = np.flatnonzero(ft != NEVER)
    assert INTRODUCER not in vic
    assert len(vic) in (5, 6)     # 6, minus the introducer if covered
    assert ft[vic].min() == 40
    assert ft[vic].max() <= 40 + (cfg.wave_size - 1) // cfg.wave_speed
    assert worlds.wave_last_fail(cfg) == 40 + 5 // 2
    # seed moves WHICH nodes, never the window
    c2 = cfg.replace(seed=99)
    ft2 = worlds.wave_fail_ticks(c2)
    assert worlds.wave_start(c2) == worlds.wave_start(cfg)
    assert ft2[ft2 != NEVER].min() == 40


@pytest.mark.parametrize("name", sorted(DENSE_WORLDS))
def test_fleet_dense_world_parity(name):
    """B=3 dense trace fleet == 3 solo runs, per world, bit-exact."""
    cfg = _dense(**DENSE_WORLDS[name])
    seeds = [1, 2, 3]
    fleet = FleetSimulation(cfg).run(seeds=seeds)
    sim = Simulation(cfg)
    for i, s in enumerate(seeds):
        ref = sim.run(seed=s)
        lane = fleet.lanes[i]
        assert np.array_equal(ref.added, lane.added), (name, s)
        assert np.array_equal(ref.removed, lane.removed), (name, s)
        assert np.array_equal(ref.sent, lane.sent), (name, s)
        assert np.array_equal(ref.recv, lane.recv), (name, s)
        for f in DENSE_STATE:
            assert np.array_equal(
                np.asarray(getattr(ref.final_state, f)),
                np.asarray(getattr(lane.final_state, f))), (name, s, f)


@pytest.mark.parametrize("name", sorted(OVERLAY_WORLDS))
def test_fleet_overlay_world_parity(name):
    """B=3 overlay fleet == 3 solo runs, per world, bit-exact."""
    cfg = _overlay(**OVERLAY_WORLDS[name])
    seeds = [1, 2, 3]
    fleet = FleetSimulation(cfg).run(seeds=seeds)
    for i, s in enumerate(seeds):
        ref = OverlaySimulation(cfg.replace(seed=s),
                                use_pallas=False).run()
        lane = fleet.lanes[i]
        for f in OV_STATE:
            assert np.array_equal(
                np.asarray(getattr(ref.final_state, f)),
                np.asarray(getattr(lane.final_state, f))), (name, s, f)


@pytest.mark.parametrize("name", sorted(DENSE_WORLDS))
def test_mesh_dense_world_parity(name):
    """D=1 and D=2 virtual-device lane meshes == solo runs, per world,
    bit-exact (the acceptance-criterion mesh sweep: the world draws are
    pure lane arithmetic, so sharding the lane axis moves nothing)."""
    from gossip_protocol_tpu.parallel.fleet_mesh import (
        MeshFleetSimulation, make_lane_mesh)
    cfg = _dense(**DENSE_WORLDS[name])
    seeds = [1, 2]
    sim = Simulation(cfg)
    refs = [sim.run(seed=s) for s in seeds]
    for d in (1, 2):
        if jax.device_count() < d:
            pytest.skip(f"needs {d} (virtual) devices")
        fleet = MeshFleetSimulation(cfg, make_lane_mesh(d)).run(seeds=seeds)
        for i, ref in enumerate(refs):
            lane = fleet.lanes[i]
            assert np.array_equal(ref.added, lane.added), (name, d, i)
            assert np.array_equal(ref.removed, lane.removed), (name, d, i)
            for f in DENSE_STATE:
                assert np.array_equal(
                    np.asarray(getattr(ref.final_state, f)),
                    np.asarray(getattr(lane.final_state, f))), (name, d, f)


@pytest.mark.parametrize("name", sorted(OVERLAY_WORLDS))
def test_mesh_overlay_world_parity(name):
    """Overlay twin of the dense mesh sweep: D=1 and D=2 lane meshes
    replay every world's solo run bit-for-bit."""
    from gossip_protocol_tpu.parallel.fleet_mesh import (
        MeshFleetSimulation, make_lane_mesh)
    cfg = _overlay(**OVERLAY_WORLDS[name])
    seeds = [1, 2]
    refs = [OverlaySimulation(cfg.replace(seed=s), use_pallas=False).run()
            for s in seeds]
    for d in (1, 2):
        if jax.device_count() < d:
            pytest.skip(f"needs {d} (virtual) devices")
        fleet = MeshFleetSimulation(cfg, make_lane_mesh(d)).run(seeds=seeds)
        for i, ref in enumerate(refs):
            lane = fleet.lanes[i]
            for f in OV_STATE:
                assert np.array_equal(
                    np.asarray(getattr(ref.final_state, f)),
                    np.asarray(getattr(lane.final_state, f))), (name, d, f)


def test_world_configs_validated():
    """Config-construction guards: bad world knobs fail early and
    typed."""
    with pytest.raises(ValueError, match="partition_groups"):
        _dense(partition_groups=1)
    with pytest.raises(ValueError, match="empty"):
        _dense(partition_groups=2, partition_open_tick=50,
               partition_close_tick=50)
    with pytest.raises(ValueError, match="drop_msg"):
        _dense(asym_drop=True)
    with pytest.raises(ValueError, match="msg_drop_prob"):
        _dense(asym_drop=True, drop_msg=True, msg_drop_prob=0.6)
    with pytest.raises(ValueError, match="churn_rate"):
        _overlay(wave_size=4, single_failure=False, churn_rate=0.2)
    with pytest.raises(ValueError, match="flap_down"):
        _dense(flap_rate=0.2, flap_period=8, flap_down=8)
    # an inverted/too-short flap window would silently never engage
    with pytest.raises(ValueError, match="flap window"):
        _dense(flap_rate=0.2, flap_period=8, flap_down=4,
               flap_open_tick=100, flap_close_tick=50)
    with pytest.raises(ValueError, match="flap window"):
        _dense(flap_rate=0.2, flap_period=8, flap_down=4,
               flap_open_tick=100, flap_close_tick=103)
    with pytest.raises(ValueError, match="wave_speed"):
        _dense(wave_size=4, wave_speed=0)
    # windows entirely past the run end silently never engage
    with pytest.raises(ValueError, match="never engage"):
        _dense(partition_groups=2, partition_open_tick=200,
               partition_close_tick=300, total_ticks=120)
    with pytest.raises(ValueError, match="never engage"):
        _dense(wave_size=4, wave_tick=200, total_ticks=120,
               single_failure=False)
    # ... but a close past the end is legal: "never heals"
    _dense(partition_groups=2, partition_open_tick=30,
           partition_close_tick=10_000, fail_tick=10_000)


def test_worlds_key_is_program_identity():
    """Two configs differing only in a world knob never share a
    compiled run or a fleet bucket (the zombie/partition/asym/flap
    branches are static)."""
    a = _dense(zombie=True)
    b = a.replace(zombie=False)
    assert a.worlds_key() != b.worlds_key()
    from gossip_protocol_tpu.core.fleet import fleet_shape_key
    assert fleet_shape_key(a) != fleet_shape_key(b)
    c = _overlay(partition_groups=2, partition_open_tick=10,
                 partition_close_tick=20)
    d = c.replace(partition_close_tick=30)
    assert c.worlds_key() != d.worlds_key()
    # seeds move which nodes are hit, never the key
    assert a.worlds_key() == a.replace(seed=7).worlds_key()


@pytest.mark.slow
def test_partition_heal_scenario_through_elastic_service():
    """Scenario x elasticity composition (PR 9 satellite): the
    partition-heal scenario served as resumable legs on a D=2 lane
    mesh with a device loss mid-sequence — the loss costs no work
    (restarted_lanes == 0), every lane stays bit-identical to its solo
    run, and the scenario ORACLE still passes on the served results
    (checkpoint cuts and mesh shrink must not perturb the world)."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 (virtual) devices")
    from gossip_protocol_tpu.models import scenarios
    from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
    from gossip_protocol_tpu.service import (FaultInjector, FleetService,
                                             RetryPolicy)
    from gossip_protocol_tpu.service.resilience import solo_execute
    fam = scenarios.CATALOG["overlay_partition_heal"]
    seeds = (1000, 1001)
    svc = FleetService(
        max_batch=2, mesh=make_lane_mesh(2), checkpoint_every=48,
        injector=FaultInjector(device_loss_at=2),
        retry=RetryPolicy(max_retries=3, backoff_base_s=1e-4))
    svc.warm(fam.build(seeds[0]), "trace")
    hs = [svc.submit(fam.build(s), mode="trace") for s in seeds]
    svc.drain()
    assert [h.status for h in hs] == ["completed", "completed"]
    st = svc.stats()
    assert st["failures"]["device_losses"] == 1
    assert st["elastic"]["restarted_lanes"] == 0
    assert st["elastic"]["checkpoints_taken"] >= 1
    for s, h in zip(seeds, hs):
        cfg = fam.build(s)
        ref = solo_execute(cfg, "trace")
        got = h.result()
        for f in OV_STATE:
            assert np.array_equal(
                np.asarray(getattr(ref.final_state, f)),
                np.asarray(getattr(got.final_state, f))), (s, f)
        assert scenarios.grade(fam, s, got) == [], s


# ---- round 2: Byzantine forgery + per-link latency planes ----


def test_byz_latency_configs_validated():
    """Round-2 knob guards: rates in range, a boost that actually
    forges, and a worst-case delay strictly under the staleness
    horizon (a clean link must never read as a failure)."""
    with pytest.raises(ValueError, match="byz_rate"):
        _dense(byz_rate=1.5)
    with pytest.raises(ValueError, match="byz_boost"):
        _dense(byz_rate=0.2, byz_boost=0)
    with pytest.raises(ValueError, match="link_latency"):
        _dense(link_latency=-1)
    with pytest.raises(ValueError, match="link_latency"):
        _dense(link_latency=24)
    with pytest.raises(ValueError, match="t_remove"):
        _dense(link_latency=19)  # worst case 20 >= t_remove=20
    _dense(link_latency=18)      # worst case 19 < 20: legal


def test_byz_latency_host_draws_pure_in_seed():
    """The liar mask, ghost-target matrix, and per-link delay matrix
    are pure functions of (seed, index, salt): replayable, introducer
    exempt, honest rows inert, delays in [1, L + 1] — and the traced
    twin computes the identical matrix entry for entry."""
    cfg = _dense(max_nnb=32, byz_rate=0.25, byz_boost=8, link_latency=4,
                 seed=1000)
    m = worlds.byz_mask_host(cfg)
    assert np.array_equal(m, worlds.byz_mask_host(cfg))
    assert not m[INTRODUCER]
    assert m.sum() >= 1, "world never engaged at this seed"
    tgt = worlds.byz_target_host(cfg)
    assert tgt.shape == (cfg.n, cfg.n)
    assert not tgt[~m].any(), "honest rows must forge nothing"
    assert not tgt.diagonal().any()
    lat = worlds.link_latency_host(cfg)
    assert np.array_equal(lat, worlds.link_latency_host(cfg))
    assert lat.min() >= 1 and lat.max() <= cfg.link_latency + 1
    ii = np.arange(cfg.n, dtype=np.uint32)
    twin = np.asarray(worlds.link_latency_of(
        np.uint32(cfg.seed & 0xFFFFFFFF), ii[:, None], ii[None, :],
        cfg.n, cfg.link_latency))
    assert np.array_equal(twin, lat)
    # a different seed redraws the plane; the off-plane placeholders
    # keep the tick branches static
    assert not np.array_equal(lat, worlds.link_latency_host(
        cfg.replace(seed=7)))
    assert worlds.byz_target_host(_dense()).shape == (0, 0)
    assert worlds.link_latency_host(_dense()).shape == (0, 0)


@pytest.mark.slow
def test_dense_byz_first_removal_is_exact():
    """Liars relay boosted heartbeats for the corpse, but the
    direct-sender-credit defense refuses forged counters a timestamp
    refresh: every live observer's FIRST removal of the victim lands
    on the exact honest horizon fail + t_remove + 1, and forgery
    alone removes nobody else."""
    cfg = _dense(max_nnb=32, byz_rate=0.2, byz_boost=8, seed=1000)
    assert worlds.byz_mask_host(cfg).sum() >= 1, "no liars at this seed"
    res = Simulation(cfg).run()
    victim = int(np.flatnonzero(res.fail_tick != NEVER)[0])
    horizon = int(res.fail_tick[victim]) + cfg.t_remove + 1
    first = {}
    for t, i, j in zip(*np.nonzero(res.removed)):
        first.setdefault((int(i), int(j)), int(t))
    assert all(j == victim for (_, j) in first), "false removal"
    for i in range(cfg.n):
        if i != victim:
            assert first.get((i, victim)) == horizon, i


@pytest.mark.slow
def test_dense_latency_loose_vs_byz_tight_window():
    """Pure per-link delay stretches detection by at most 3L past the
    loss-free horizon — the per-link TIGHT window does NOT hold,
    because honest relays refresh adoption timestamps.  Composing the
    byz plane switches on the direct-sender-credit defense, which
    removes exactly that relay refresh: each observer's removal then
    lands inside its own link's window (base, base + lat[victim,
    observer]]."""
    cfg = _dense(link_latency=4, seed=1000)
    res = Simulation(cfg).run()
    victim = int(np.flatnonzero(res.fail_tick != NEVER)[0])
    base = int(res.fail_tick[victim]) + cfg.t_remove
    first = {}
    for t, i, j in zip(*np.nonzero(res.removed)):
        first.setdefault((int(i), int(j)), int(t))
    assert all(j == victim for (_, j) in first), "false removal"
    for i in range(cfg.n):
        if i != victim:
            t_rm = first.get((i, victim))
            assert t_rm is not None \
                and 1 <= t_rm - base <= 3 * cfg.link_latency, (i, t_rm)
    cfg2 = _dense(max_nnb=32, byz_rate=0.2, byz_boost=8, link_latency=4,
                  total_ticks=140, seed=1000)
    res2 = Simulation(cfg2).run()
    lat = worlds.link_latency_host(cfg2)
    victim2 = int(np.flatnonzero(res2.fail_tick != NEVER)[0])
    base2 = int(res2.fail_tick[victim2]) + cfg2.t_remove
    first2 = {}
    for t, i, j in zip(*np.nonzero(res2.removed)):
        first2.setdefault((int(i), int(j)), int(t))
    for i in range(cfg2.n):
        if i != victim2:
            t_rm = first2.get((i, victim2))
            assert t_rm is not None \
                and 1 <= t_rm - base2 <= int(lat[victim2, i]), (i, t_rm)


@pytest.mark.slow
def test_overlay_byz_latency_deterministic():
    """The overlay's byz + latency planes ride the same pure counter-
    hash draws as everything else: two runs of a composed world are
    bit-identical, final state field for field."""
    cfg = _overlay(byz_rate=0.15, byz_boost=4, link_latency=3,
                   total_ticks=120)
    a = OverlaySimulation(cfg).run()
    b = OverlaySimulation(cfg).run()
    for f in ("ids", "hb", "ts", "in_group", "own_hb"):
        assert np.array_equal(np.asarray(getattr(a.final_state, f)),
                              np.asarray(getattr(b.final_state, f))), f


def test_composition_grammar_names_the_world():
    """worlds.composition: one failure script plus any subset of the
    orthogonal planes, in PLANES order — and each round-2 plane flips
    the program identity exactly like the round-1 planes."""
    cfg = _dense(max_nnb=32, byz_rate=0.2, byz_boost=8, link_latency=4)
    assert worlds.composition(cfg) == ("scripted", ("byz", "latency"))
    storm = _dense(single_failure=False, wave_size=6, wave_tick=40,
                   wave_speed=2, flap_rate=0.2, flap_period=24,
                   flap_down=6, partition_groups=2,
                   partition_open_tick=57, partition_close_tick=63)
    assert worlds.composition(storm) == \
        ("wave", ("partition", "flapping"))
    base = _dense()
    assert worlds.composition(base) == ("scripted", ())
    kb = base.replace(byz_rate=0.2).worlds_key()
    kl = base.replace(link_latency=4).worlds_key()
    assert len({base.worlds_key(), kb, kl}) == 3
    # the boost and the delay bound are part of the key (they change
    # the compiled constants), the seed never is
    assert kb != base.replace(byz_rate=0.2, byz_boost=16).worlds_key()
    assert kl == base.replace(link_latency=4, seed=9).worlds_key()


@pytest.mark.slow
def test_overlay_coverage_spells_are_transient():
    """Union coverage in the bounded-view overlay is an equilibrium
    property with a re-advert tail: a live, quiet member can fall out
    of every view for a tick or two between an eviction and its next
    advert.  The honest claim (scenarios._overlay_coverage) bounds the
    SPELLS in the live_uncovered series instead of point-sampling the
    end tick — graded on the solo path, where the series exists
    (fleet lanes report the -1 not-tracked sentinel).  The two seeds
    that forced the refinement: 1026 lands the END tick on a blip,
    and zombie/1034's blip transiently crossed the horizon in one
    view (two false-removal events, healed by the next advert)."""
    from gossip_protocol_tpu.models import scenarios
    for fam, seed in (("overlay_partition_heal", 1026),
                      ("overlay_zombie", 1034)):
        violations, _ = scenarios.run_solo(fam, seed)
        assert violations == [], (fam, seed, violations)
