"""Elastic serving (PR 8): segment-boundary checkpointing, mesh grow,
and in-flight lane migration.

The contracts under test:

* **snapshot exactness** — a fleet run split at ANY segment cut
  (core/fleet.py ``launch_leg``: stacked carry snapshotted to host
  numpy, re-entered from the cut) is bit-identical to the
  uninterrupted run — dense and overlay, single-device and lane-mesh;
* **snapshot discipline** — the PR-1 planner's cuts are the ONLY
  legal leg boundaries (phase elision stays static across a resume);
* **never restart from tick 0** — a device loss (or any dispatch
  failure) mid-sequence retries a checkpointed batch from its LAST
  snapshot, and even the solo-degrade bottom rung resumes
  (``solo_resume``); the scheduler's ``restarted_lanes`` counter
  stays 0;
* **the grow ladder** — a deterministic fault-plane device return
  grows the mesh back (``grow_mesh``), the program cache RE-KEYS to
  the restored mesh's warm programs (zero rebuilds), and queued +
  checkpointed lanes migrate across the rebuild;
* **replayability** — a shrink -> grow -> shrink chaos seed
  reproduces its fault schedule and per-request outcomes (status,
  retries, legs) digest-for-digest.
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.fleet import FleetSimulation
from gossip_protocol_tpu.models.segments import (CHECKPOINT_GRID_TICKS,
                                                 checkpoint_ticks,
                                                 cut_for_budget)
from gossip_protocol_tpu.service import (BreakerPolicy, FaultInjector,
                                         FleetService, RetryPolicy)
from gossip_protocol_tpu.service.resilience import solo_execute

pytestmark = [pytest.mark.service, pytest.mark.resilience]


def _overlay_churn_drop(n=64, ticks=96):
    """Overlay churn + drop10: every protocol phase (ramp, churn,
    join, drop) crosses at least one segment cut."""
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=True, msg_drop_prob=0.1, seed=0,
                     total_ticks=ticks, churn_rate=0.2, rejoin_after=30,
                     step_rate=12 / n, drop_open_tick=ticks // 3,
                     drop_close_tick=2 * ticks // 3)


def _dense_churn_drop(n=16, ticks=60):
    return SimConfig(max_nnb=n, single_failure=False, drop_msg=True,
                     msg_drop_prob=0.1, seed=0, total_ticks=ticks,
                     fail_tick=30, rejoin_after=15, drop_open_tick=10,
                     drop_close_tick=50)


def _fast_retry(**kw):
    kw.setdefault("max_retries", 3)
    kw.setdefault("backoff_base_s", 1e-4)
    return RetryPolicy(**kw)


def _assert_overlay_equal(ref, got, tag=""):
    for f in ("tick", "ids", "hb", "ts", "in_group", "own_hb",
              "send_flags", "joinreq", "joinrep"):
        assert np.array_equal(np.asarray(getattr(ref.final_state, f)),
                              np.asarray(getattr(got.final_state, f))), \
            f"{tag} final_state.{f}"
    for f in ("in_group", "view_slots", "adds", "removals",
              "false_removals", "victim_slots", "sent", "recv"):
        assert np.array_equal(np.asarray(getattr(ref.metrics, f)),
                              np.asarray(getattr(got.metrics, f))), \
            f"{tag} metrics.{f}"


def _assert_dense_equal(ref, got, tag=""):
    for f in ("added", "removed", "sent", "recv"):
        assert np.array_equal(getattr(ref, f), getattr(got, f)), \
            f"{tag} {f}"
    for f in ("tick", "in_group", "own_hb", "known", "hb", "ts",
              "gossip", "joinreq", "joinrep"):
        assert np.array_equal(np.asarray(getattr(ref.final_state, f)),
                              np.asarray(getattr(got.final_state, f))), \
            f"{tag} final_state.{f}"


# ---- the snapshot planner --------------------------------------------
def test_checkpoint_grid_quantum_matches_kernel():
    """The planner's launch quantum and the grid kernel's GRID_TICKS
    are the same constant (segments.py cannot import the Pallas stack,
    so the sync is pinned here)."""
    from gossip_protocol_tpu.ops.pallas.overlay_grid import GRID_TICKS
    assert CHECKPOINT_GRID_TICKS == GRID_TICKS


def test_cut_for_budget_rules():
    cfg = _dense_churn_drop()                    # cuts (16, 48) of 60
    assert checkpoint_ticks(cfg) == (16, 48)
    assert cut_for_budget(cfg, 0, 100) == 60     # fits: finish
    assert cut_for_budget(cfg, 0, 20) == 16      # largest cut in budget
    assert cut_for_budget(cfg, 0, 50) == 48
    assert cut_for_budget(cfg, 16, 8) == 48      # none in budget: next
    assert cut_for_budget(cfg, 48, 8) == 60      # no cuts left: finish
    with pytest.raises(ValueError, match="outside"):
        cut_for_budget(cfg, 60, 8)


def test_leg_boundaries_enforced():
    """Only segment cuts (or the run's end) are legal leg boundaries,
    and resumed lanes must agree on the shared scan clock."""
    cfg = _dense_churn_drop()
    sim = FleetSimulation(cfg)
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    with pytest.raises(ValueError, match="segment cut"):
        sim.run_leg(configs=cfgs, ticks=20)      # 20 is mid-segment
    leg16 = sim.run_leg(configs=cfgs, ticks=16)
    leg48 = sim.run_leg(resume=leg16.checkpoints, ticks=32)
    with pytest.raises(ValueError, match="clock"):
        sim.run_leg(resume=[leg16.checkpoints[0], leg48.checkpoints[1]])


# ---- checkpoint/resume bit-parity ------------------------------------
def test_overlay_resume_bit_identical_at_every_cut():
    """Satellite gate: resuming at EVERY segment boundary of a
    churn+drop10 overlay config reproduces the uninterrupted fleet run
    bit-for-bit — including through a padded (filler-lane) leg."""
    cfg = _overlay_churn_drop()
    cuts = checkpoint_ticks(cfg)
    assert len(cuts) >= 2, cuts
    cfgs = [cfg.replace(seed=s) for s in (1, 2, 3)]
    sim = FleetSimulation(cfg)
    full = sim.run(configs=cfgs, warmup=False)
    for cut in cuts:
        leg = sim.run_leg(configs=cfgs + [cfg.replace(seed=9)],
                          n_real=3, ticks=cut)
        assert leg.checkpoints[0].tick == cut
        leg = sim.run_leg(resume=leg.checkpoints, width=4)
        assert leg.done
        res = leg.results()
        for ref, got in zip(full.lanes, res.lanes):
            _assert_overlay_equal(ref, got, tag=f"cut={cut}")


def test_dense_resume_bit_identical_at_every_cut():
    cfg = _dense_churn_drop()
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    sim = FleetSimulation(cfg)
    full = sim.run(configs=cfgs, warmup=False)
    for cut in checkpoint_ticks(cfg):
        leg = sim.run_leg(configs=cfgs, ticks=cut)
        leg = sim.run_leg(resume=leg.checkpoints)
        assert leg.done
        res = leg.results()
        for ref, got in zip(full.lanes, res.lanes):
            _assert_dense_equal(ref, got, tag=f"cut={cut}")


@pytest.mark.skipif(__import__("jax").device_count() < 2,
                    reason="needs 2 (virtual) devices")
def test_mesh_leg_resume_and_cross_mesh_migration():
    """A checkpoint is mesh-independent: a leg run on a D=2 mesh can
    be resumed on a single device (and vice versa), bit-identical to
    the uninterrupted single-device fleet."""
    from gossip_protocol_tpu.parallel.fleet_mesh import (
        MeshFleetSimulation, make_lane_mesh)
    cfg = _overlay_churn_drop()
    cut = checkpoint_ticks(cfg)[0]
    cfgs = [cfg.replace(seed=s) for s in (1, 2, 3, 4)]
    full = FleetSimulation(cfg).run(configs=cfgs, warmup=False)
    msim = MeshFleetSimulation(cfg, make_lane_mesh(2))
    leg = msim.run_leg(configs=cfgs, ticks=cut)        # D=2 leg
    leg = FleetSimulation(cfg).run_leg(resume=leg.checkpoints)  # D=1
    res = leg.results()
    for ref, got in zip(full.lanes, res.lanes):
        _assert_overlay_equal(ref, got, tag="mesh->solo")
    # and the other direction: solo leg, mesh resume
    leg = FleetSimulation(cfg).run_leg(configs=cfgs, ticks=cut)
    leg = msim.run_leg(resume=leg.checkpoints)
    for ref, got in zip(full.lanes, leg.results().lanes):
        _assert_overlay_equal(ref, got, tag="solo->mesh")


# ---- checkpointed serving --------------------------------------------
def test_service_checkpointed_serving_parity_and_counters():
    """FleetService(checkpoint_every=) serves long dispatches as
    resumable legs: results stay bit-identical to solo runs, handles
    report the leg count, and the elasticity counters move."""
    ov = _overlay_churn_drop()
    dn = _dense_churn_drop()
    svc = FleetService(max_batch=3, checkpoint_every=16)
    hs = [svc.submit(ov, seed=s) for s in (1, 2)] \
        + [svc.submit(dn, seed=s) for s in (1, 2)]
    svc.drain()
    assert all(h.status == "completed" for h in hs)
    assert all(h.metrics.legs >= 2 for h in hs), \
        [h.metrics.legs for h in hs]
    st = svc.stats()
    assert st["elastic"]["checkpoints_taken"] >= 2
    assert st["elastic"]["resume_dispatches"] >= 2
    assert st["elastic"]["restarted_lanes"] == 0
    assert st["checkpoint_every"] == 16
    for h in hs:
        ref = solo_execute(h.request.cfg, h.request.mode)
        if h.request.cfg.model == "overlay":
            _assert_overlay_equal(ref, h.result())
        else:
            _assert_dense_equal(ref, h.result())
    # result() on a checkpointed request flushes leg by leg
    svc2 = FleetService(max_batch=2, checkpoint_every=16)
    h = svc2.submit(ov, seed=5)
    ref = solo_execute(ov.replace(seed=5), "trace")
    _assert_overlay_equal(ref, h.result())
    assert h.metrics.legs >= 2


def test_result_advances_pipelined_checkpointed_leg():
    """Review regression: under the default pipelined beat, a full
    bucket dispatched by ``submit``'s pump leaves leg 1 IN FLIGHT;
    ``result()`` must then walk the whole leg chain — the first flush
    dispatches nothing (the queue is empty) but resolving the
    in-flight leg checkpoints and re-queues the batch, which is
    progress, not an interrupted flush."""
    ov = _overlay_churn_drop()
    svc = FleetService(max_batch=2, checkpoint_every=16)
    hs = [svc.submit(ov, seed=s) for s in (1, 2)]
    assert svc.in_flight == 2            # leg 1 launched, unresolved
    _assert_overlay_equal(solo_execute(ov.replace(seed=1), "trace"),
                          hs[0].result())
    _assert_overlay_equal(solo_execute(ov.replace(seed=2), "trace"),
                          hs[1].result())
    assert all(h.metrics.legs >= 2 for h in hs)


def test_device_loss_mid_sequence_resumes_from_checkpoint():
    """A device loss hitting a RESUME dispatch retries from the last
    checkpoint — never from tick 0 — and the batch completes
    bit-identically."""
    ov = _overlay_churn_drop()
    svc = FleetService(max_batch=2, checkpoint_every=16,
                       injector=FaultInjector(device_loss_at=2),
                       retry=_fast_retry())
    hs = [svc.submit(ov, seed=s) for s in (1, 2)]
    svc.drain()
    assert [h.status for h in hs] == ["completed", "completed"]
    st = svc.stats()
    assert st["failures"]["device_losses"] == 1
    assert st["failures"]["retries"] >= 1
    assert st["elastic"]["restarted_lanes"] == 0
    for s, h in zip((1, 2), hs):
        _assert_overlay_equal(solo_execute(ov.replace(seed=s), "trace"),
                              h.result())


def test_solo_degrade_resumes_from_checkpoint():
    """Even the ladder's bottom rung preserves checkpointed work: a
    resumed leg that exhausts its retries is served by solo_resume
    (continuation from the snapshot), not a tick-0 re-run — and the
    stitched result is still bit-identical to an uninterrupted solo
    run."""
    ov = _overlay_churn_drop()
    svc = FleetService(
        max_batch=2, checkpoint_every=16,
        injector=FaultInjector(schedule={2: "dispatch", 3: "dispatch"}),
        retry=_fast_retry(max_retries=1),
        breaker=BreakerPolicy(failure_threshold=10))
    hs = [svc.submit(ov, seed=s) for s in (1, 2)]
    svc.drain()
    assert [h.status for h in hs] == ["degraded", "degraded"]
    assert all(h.metrics.legs >= 2 for h in hs)
    assert svc.stats()["elastic"]["restarted_lanes"] == 0
    for s, h in zip((1, 2), hs):
        _assert_overlay_equal(solo_execute(ov.replace(seed=s), "trace"),
                              h.result())


# ---- the grow ladder -------------------------------------------------
@pytest.mark.skipif(__import__("jax").device_count() < 2,
                    reason="needs 2 (virtual) devices")
def test_device_return_grows_mesh_migrates_lanes_and_rekeys():
    """The elastic round trip: loss shrinks D=2 -> single device
    (checkpointed lanes migrate down), a fault-plane device return
    grows it back (lanes migrate up), the program cache RE-KEYS to the
    restored mesh's warm programs, and every result stays
    bit-identical."""
    from gossip_protocol_tpu.core.tick import run_build_count
    from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
    ov = _overlay_churn_drop()
    svc = FleetService(max_batch=2, mesh=make_lane_mesh(2),
                       checkpoint_every=16,
                       injector=FaultInjector(device_loss_at=2,
                                              device_return_at=4),
                       retry=_fast_retry(),
                       breaker=BreakerPolicy(reset_after_s=float("inf")))
    hs = [svc.submit(ov, seed=s) for s in (1, 2, 3, 4)]
    # the first leg dispatched on D=2; warm the count AFTER it exists
    svc.pump()
    svc.drain()
    assert all(h.status == "completed" for h in hs)
    st = svc.stats()
    assert st["failures"]["device_losses"] == 1
    assert st["failures"]["device_returns"] == 1
    assert st["elastic"]["mesh_grows"] == 1
    assert st["elastic"]["lanes_migrated"] >= 8   # down AND back up
    assert st["elastic"]["restarted_lanes"] == 0
    assert st["devices"] == 2 and svc.n_devices == 2
    assert st["cache"]["rekey_hits"] >= 1
    for s, h in zip((1, 2, 3, 4), hs):
        _assert_overlay_equal(solo_execute(ov.replace(seed=s), "trace"),
                              h.result())
    # the grow re-keyed to the original D=2 programs: a fresh dispatch
    # on the restored mesh builds NOTHING new
    built = run_build_count()
    h2 = [svc.submit(ov, seed=s) for s in (5, 6, 7, 8)]
    svc.drain()
    assert run_build_count() == built, \
        "the restored mesh recompiled instead of re-keying"
    assert all(h.status == "completed" for h in h2)


def test_grow_mesh_ladder():
    import jax
    from gossip_protocol_tpu.parallel.fleet_mesh import (grow_mesh,
                                                         make_lane_mesh,
                                                         mesh_descriptor,
                                                         shrink_mesh)
    assert grow_mesh(None, None) is None         # never had a mesh
    if jax.device_count() < 4:
        pytest.skip("needs 4 (virtual) devices")
    m4 = make_lane_mesh(4)
    full = tuple(m4.devices.flat)
    m3 = shrink_mesh(m4)
    assert mesh_descriptor(grow_mesh(m3, full)) == mesh_descriptor(m4)
    m2 = shrink_mesh(m3)
    none = shrink_mesh(m2)
    assert none is None
    g2 = grow_mesh(none, full)                   # None -> 2-device mesh
    assert mesh_descriptor(g2) == mesh_descriptor(m2)
    assert grow_mesh(m4, full) is m4             # already full


@pytest.mark.skipif(__import__("jax").device_count() < 2,
                    reason="needs 2 (virtual) devices")
def test_shrink_grow_shrink_chaos_seed_replays_digest_for_digest():
    """Satellite gate: a shrink -> grow -> shrink chaos sequence
    reproduces its fault schedule AND per-request outcomes (status,
    retries, legs) across two runs, with zero restarted-from-zero
    lanes in both."""
    from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
    ov = _overlay_churn_drop()

    def run_once():
        inj = FaultInjector(seed=11, schedule={2: "device_loss",
                                               4: "device_return",
                                               6: "device_loss"})
        svc = FleetService(max_batch=2, mesh=make_lane_mesh(2),
                           checkpoint_every=16, injector=inj,
                           retry=_fast_retry(),
                           breaker=BreakerPolicy(
                               reset_after_s=float("inf")))
        hs = [svc.submit(ov, seed=s) for s in (1, 2, 3, 4)]
        svc.drain()
        st = svc.stats()
        assert st["elastic"]["restarted_lanes"] == 0
        return (inj.schedule_digest(), st["devices"],
                tuple((h.request.rid, h.status, h.metrics.retries,
                       h.metrics.legs) for h in hs))

    a, b = run_once(), run_once()
    assert a == b
    digest, devices, outcomes = a
    assert devices == 1            # the second loss is never reclaimed
    assert all(o[1] == "completed" for o in outcomes)


# ---- SLO class dispatch ordering (PR 7 follow-on) --------------------
def test_pump_pops_tight_deadline_class_first():
    """Classes now shape DISPATCH ORDER, not just deadlines: with
    class_ordering (the default) a pump pass serves the bucket holding
    the tightest queued deadline first; with it off, FIFO over bucket
    creation order — the pre-PR-8 behavior."""
    from gossip_protocol_tpu.service import (ClassPolicy, SLOPolicy,
                                             VirtualClock)
    dn = _dense_churn_drop(n=12, ticks=20)
    ov = _overlay_churn_drop(n=64, ticks=48)
    slo = SLOPolicy(classes={"interactive": ClassPolicy(deadline_s=30.0),
                             "batch": ClassPolicy(deadline_s=None)},
                    default_class="batch",
                    assumed_dispatch_wall_s=0.01)

    def dispatch_order(ordering: bool):
        import dataclasses
        vc = VirtualClock()
        svc = FleetService(
            max_batch=2, max_wait_s=5.0, clock=vc, sleep=vc.sleep,
            slo=dataclasses.replace(slo, class_ordering=ordering),
            pump_harvest=False)
        # the deadline-less bucket enqueues FIRST, then the
        # tight-deadline one; neither flushes at t=0 (margins are
        # ample).  At t=6 BOTH are past max_wait: the pump pass's
        # bucket order is the decision under test.
        svc.submit(ov, seed=1, priority="batch")
        svc.submit(dn, seed=1, priority="interactive")
        vc.t = 6.0
        svc.pump()
        svc.drain()
        order = [d["bucket"][1][0] for d in svc._dispatches]
        return order

    assert dispatch_order(True)[0] == "full_view"    # tight class first
    assert dispatch_order(False)[0] == "overlay"     # FIFO


# ---- the acceptance harness ------------------------------------------
def test_elastic_replay_small():
    """The in-line gates of elastic_replay on a small stream: 100%
    completion, >=1 loss + >=1 return, zero restarted lanes, lane
    migration across the rebuild, digest-for-digest replay."""
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs 2 (virtual) devices")
    from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
    from gossip_protocol_tpu.service import (Template, elastic_replay,
                                             overlay_templates)
    tpls = [Template("churn-drop", _overlay_churn_drop())] \
        + overlay_templates(n=64, ticks=96)[:1]
    m, seq = elastic_replay(tpls, seeds_per_template=2, max_batch=2,
                            mesh=make_lane_mesh(2), checkpoint_every=32,
                            fault_seed=7, return_legs=True)
    assert m["completion_rate"] == 1.0
    assert m["faults"]["device_loss"] >= 1
    assert m["faults"]["device_return"] >= 1
    assert m["restarted_from_zero"] == 0
    assert m["elastic"]["lanes_migrated"] >= 1
    assert m["devices_end"] == m["devices_start"] == 2
    assert m["mean_legs"] > 1.0
    m2 = elastic_replay(tpls, seeds_per_template=2, max_batch=2,
                        mesh=make_lane_mesh(2), checkpoint_every=32,
                        fault_seed=7, sequential=seq)
    assert m2["schedule_digest"] == m["schedule_digest"]
    assert m2["outcome_digest"] == m["outcome_digest"]


@pytest.mark.slow
def test_elastic_acceptance():
    """The full elastic chaos gate (the BENCH_pr08 entry's shape): the
    204-request mixed replay as resumable legs on a D=2 mesh with one
    device loss AND one device return — 204/204, zero restarts, parity,
    digest-replayable (all enforced inside elastic_replay)."""
    import jax
    if jax.device_count() < 2:
        pytest.skip("needs 2 (virtual) devices")
    from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
    from gossip_protocol_tpu.service import (elastic_replay,
                                             grader_templates,
                                             overlay_templates)
    tpls = grader_templates() + overlay_templates(n=512, ticks=96)
    m = elastic_replay(tpls, seeds_per_template=34, max_batch=4,
                       mesh=make_lane_mesh(2), checkpoint_every=48,
                       fault_seed=20260804)
    assert m["requests"] == 204 and m["completed"] == 204
    assert m["restarted_from_zero"] == 0


# ---- peer-axis elasticity (PR 19) ------------------------------------
def test_shrink_grow_mesh_ladder_2d():
    """The 2-D ladder and its inverse: shrink halves the PEER axis
    first (lanes untouched) down to a 1-D lane mesh; grow restores
    lanes first, then doubles peers back — every rung's descriptor
    equal on the way down and on the way up (the warm-rekey
    invariant: service/cache.py finds the retained programs)."""
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    from gossip_protocol_tpu.parallel.fleet_mesh import (
        grow_mesh, make_lane_peer_mesh, mesh_axis_sizes,
        mesh_descriptor, shrink_mesh)
    m24 = make_lane_peer_mesh(2, 4)
    assert mesh_axis_sizes(m24) == (2, 4, "peers")
    full = tuple(m24.devices.flat)
    down, m = [], m24
    while m is not None:
        down.append(mesh_descriptor(m))
        m = shrink_mesh(m)
    # (2,4) -> (2,2) -> 1-D (2,) -> None
    assert [d[0] for d in down] == [("lanes", "peers"),
                                    ("lanes", "peers"), ("lanes",)]
    assert [d[2] for d in down] == [(2, 4), (2, 2), (2,)]
    up, g = [], None
    for _ in range(6):
        g2 = grow_mesh(g, full, full_shape=(2, 4),
                       full_axes=m24.axis_names)
        if g2 is g:
            break
        g = g2
        up.append(mesh_descriptor(g))
    assert up == list(reversed(down))


def test_peer_shard_loss_mid_sequence_zero_restarts():
    """A device loss on the 2-D mesh drops a PEER shard — the lane
    axis keeps serving, checkpointed lanes migrate across the
    re-shard (host-numpy snapshots are mesh-independent), the device
    return doubles the peer axis back — and nothing restarts from
    tick 0, every result bit-identical to its solo run."""
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    cfg = _dense_churn_drop()   # n=16: peer-sharded at 4 AND 2 peers
    svc = FleetService(max_batch=2, mesh=make_lane_peer_mesh(2, 4),
                       checkpoint_every=16,
                       injector=FaultInjector(device_loss_at=2,
                                              device_return_at=4),
                       retry=_fast_retry(),
                       breaker=BreakerPolicy(reset_after_s=float("inf")))
    hs = [svc.submit(cfg, seed=s) for s in (1, 2, 3, 4)]
    svc.drain()
    assert all(h.status == "completed" for h in hs)
    st = svc.stats()
    assert st["failures"]["device_losses"] == 1
    assert st["failures"]["device_returns"] == 1
    assert st["elastic"]["mesh_grows"] == 1
    assert st["elastic"]["restarted_lanes"] == 0
    assert st["elastic"]["lanes_migrated"] >= 1
    assert (st["lanes"], st["peers"]) == (2, 4)   # grown back whole
    assert st["devices"] == 8 and svc.n_peers == 4
    for s, h in zip((1, 2, 3, 4), hs):
        _assert_dense_equal(solo_execute(cfg.replace(seed=s), "trace"),
                            h.result(), tag=f"seed {s}")


def test_mesh2d_shrink_grow_digest_equals_baseline():
    """The PR-19 acceptance gate: digest replay of a (2,4) -> (2,2)
    -> (2,4) peer-shard shrink/grow cycle equal to the UNINTERRUPTED
    baseline, with zero restarted lanes."""
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    from gossip_protocol_tpu.service.replay import result_digest
    cfg = _dense_churn_drop()
    seeds = (1, 2, 3, 4)

    def run_once(injector):
        svc = FleetService(max_batch=2,
                           mesh=make_lane_peer_mesh(2, 4),
                           checkpoint_every=16, injector=injector,
                           retry=_fast_retry(),
                           breaker=BreakerPolicy(
                               reset_after_s=float("inf")))
        hs = [svc.submit(cfg, seed=s) for s in seeds]
        svc.drain()
        assert all(h.done and not h.failed for h in hs)
        return [result_digest(h.result()) for h in hs], svc.stats()

    base, bst = run_once(None)
    faulted, fst = run_once(FaultInjector(device_loss_at=2,
                                          device_return_at=4))
    assert faulted == base, "shrink/grow cycle changed results"
    assert fst["elastic"]["restarted_lanes"] == 0
    assert fst["elastic"]["mesh_grows"] >= 1
    assert (fst["lanes"], fst["peers"]) == \
        (bst["lanes"], bst["peers"]) == (2, 4)


def test_elastic_replay_mesh2d_small():
    """elastic_replay over the 2-D mesh: the in-line gates (100%
    completion, zero restarts, lane migration, grow-back) plus the
    2-D shape fields — the shrink drops the peer axis, the grow
    restores the full (2,4) decomposition."""
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    from gossip_protocol_tpu.service import Template, elastic_replay
    tpls = [Template("churn-drop", _overlay_churn_drop()),
            Template("dense-drop", _dense_churn_drop())]
    m = elastic_replay(tpls, seeds_per_template=2, max_batch=2,
                       mesh=make_lane_peer_mesh(2, 4),
                       checkpoint_every=32, fault_seed=7)
    assert m["completion_rate"] == 1.0
    assert m["restarted_from_zero"] == 0
    assert m["devices_end"] == m["devices_start"] == 8
    assert (m["lanes_end"], m["peers_end"]) == (2, 4)


# ---- wall-clock-triggered checkpoints (PR 9 satellite) ---------------
class _StepClock:
    """A fake service clock that advances a fixed step per reading:
    every clock DELTA the scheduler measures is a pure function of how
    many times it looked, so the seconds->ticks budget conversion is
    bit-deterministic run to run."""

    def __init__(self, step=0.05):
        self.t = 0.0
        self.step = float(step)

    def __call__(self):
        self.t += self.step
        return self.t

    def sleep(self, dt):
        self.t += max(float(dt), 0.0)


def test_checkpoint_budget_knobs_validated():
    with pytest.raises(ValueError, match="two spellings"):
        FleetService(checkpoint_every=16, checkpoint_every_s=1.0)
    with pytest.raises(ValueError, match="> 0"):
        FleetService(checkpoint_every_s=0.0)


def test_checkpoint_every_s_converts_budget_and_stays_deterministic():
    """FleetService(checkpoint_every_s=): the seconds budget becomes a
    tick budget via the per-bucket wall-per-tick EWMA (seeded by warm,
    measured from CLOCK deltas) and cut_for_budget — under a fake
    stepping clock the whole leg structure is deterministic, results
    stay bit-identical to solo runs, and nothing restarts from 0."""
    ov = _overlay_churn_drop()

    def run_once():
        from gossip_protocol_tpu.core.tick import run_build_count
        clk = _StepClock(0.05)
        svc = FleetService(max_batch=2, checkpoint_every_s=1e-3,
                          clock=clk, sleep=clk.sleep)
        svc.warm(ov, "trace")
        b0 = run_build_count()
        hs = [svc.submit(ov, seed=s) for s in (1, 2)]
        svc.drain()
        return hs, svc.stats(), run_build_count() - b0

    hs, st, live_builds = run_once()
    # warm() pre-built the leg chain the seconds budget resolves to,
    # so the live dispatches compile nothing in-band
    assert live_builds == 0
    assert st["checkpoint_every_s"] == 1e-3
    assert st["checkpoint_every"] is None
    # the tiny seconds budget forces interior cuts: real legs ran
    assert st["elastic"]["checkpoints_taken"] >= 1
    assert st["elastic"]["resume_dispatches"] >= 1
    assert st["elastic"]["restarted_lanes"] == 0
    assert all(h.status == "completed" for h in hs)
    assert all(h.metrics.legs >= 2 for h in hs)
    for s, h in zip((1, 2), hs):
        _assert_overlay_equal(solo_execute(ov.replace(seed=s), "trace"),
                              h.result(), tag=f"seed{s}")
    # budget determinism: an identical fake-clock run reproduces the
    # exact leg structure, dispatch for dispatch
    hs2, st2, _ = run_once()
    for k in ("checkpoints_taken", "resume_dispatches",
              "restarted_lanes"):
        assert st2["elastic"][k] == st["elastic"][k], k
    assert st2["dispatches"] == st["dispatches"]
    assert [h.metrics.legs for h in hs2] == [h.metrics.legs for h in hs]


def test_checkpoint_every_s_unwarmed_runs_monolithic():
    """No wall-per-tick estimate yet (no warm, frozen virtual clock):
    the first dispatch must run monolithic rather than guess a
    budget — and still complete with solo parity."""
    from gossip_protocol_tpu.service import VirtualClock
    ov = _overlay_churn_drop()
    vc = VirtualClock()
    svc = FleetService(max_batch=2, checkpoint_every_s=1e-3, clock=vc,
                       sleep=vc.sleep)
    hs = [svc.submit(ov, seed=s) for s in (1, 2)]
    svc.drain()
    assert all(h.status == "completed" for h in hs)
    assert all(h.metrics.legs == 1 for h in hs)
    assert svc.stats()["elastic"]["checkpoints_taken"] == 0
    for s, h in zip((1, 2), hs):
        _assert_overlay_equal(solo_execute(ov.replace(seed=s), "trace"),
                              h.result(), tag=f"seed{s}")
