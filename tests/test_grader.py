"""End-to-end acceptance: the Grader.sh checks (reimplemented in
gossip_protocol_tpu.grader) must award the maximum attainable 90/100
against this framework's output, as they do against the reference
(BASELINE.md)."""

import os

import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.sim import run_scenario
from gossip_protocol_tpu.grader import (grade_all, grade_multi, grade_single)


def _runner(conf, workdir):
    run_scenario(SimConfig.from_conf(conf, seed=0), outdir=workdir)


def test_full_grade(tmp_path, testcases_dir):
    results = grade_all(_runner, testcases_dir, str(tmp_path))
    assert results["singlefailure"].points == 30
    assert results["multifailure"].points == 30
    assert results["msgdropsinglefailure"].points == 30
    assert results["total"] == 90


@pytest.mark.parametrize("seed", [6, 7, 8])
def test_grade_robust_to_seed(tmp_path, testcases_dir, seed):
    """The grade must not depend on which node the fault injector picks
    (the reference is time-seeded; we sweep seeds instead)."""
    def runner(conf, workdir):
        run_scenario(SimConfig.from_conf(conf, seed=seed), outdir=workdir)
    results = grade_all(runner, testcases_dir, str(tmp_path))
    assert results["total"] == 90


def test_grader_rejects_bad_logs(tmp_path):
    """Sanity: the grader actually fails on broken output."""
    dbg = tmp_path / "dbg.log"
    dbg.write_text("131\n\n 1.0.0.0:0 [0] APP")
    assert grade_single(str(dbg)).points == 0
    assert grade_multi(str(dbg)).points == 0
