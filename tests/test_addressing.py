"""Address model: 0-based peer index <-> reference id/byte forms
(Member.h:29-55, EmulNet.cpp:72-77, Log.cpp:73)."""

from gossip_protocol_tpu.addressing import (addr_str, display_addr,
                                            parse_addr, peer_id, peer_index)


def test_sequential_ids():
    assert peer_id(0) == 1  # introducer (Application.cpp:209-217)
    assert peer_index(peer_id(41)) == 41


def test_addr_str_little_endian_bytes():
    assert addr_str(0) == "1.0.0.0:0"
    assert addr_str(9) == "10.0.0.0:0"
    assert addr_str(255) == "0.1.0.0:0"       # id 256 -> bytes 0,1,0,0
    assert addr_str(256 + 255) == "0.2.0.0:0"  # id 512


def test_roundtrip():
    for i in (0, 9, 99, 65535, 1_000_000 - 1):
        assert parse_addr(addr_str(i)) == i


def test_display_addr():
    # Address::getAddress form used on stdout (Member.h:46-52)
    assert display_addr(0) == "1:0"
    assert display_addr(9) == "10:0"
