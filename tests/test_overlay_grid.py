"""Differential tests: grid-scale multi-tick kernel vs the XLA path.

The grid kernel (ops/pallas/overlay_grid.py + models/overlay_grid.py)
must replay the exact trajectory of the per-tick XLA formulation —
final state bit-identical, per-tick metrics identical except
``live_uncovered`` (the grid path reports the -1 "not tracked"
sentinel).  Tests force a small row-block so multiple grid blocks and
the cross-block XOR partner DMA are exercised; on CPU the kernel runs
in interpret mode, and the same contract holds compiled on TPU
(exercised by bench.py).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_run,
                                                make_overlay_schedule)
from gossip_protocol_tpu.models.overlay_grid import (grid_supported,
                                                     make_grid_run,
                                                     pack_grid_plane,
                                                     unpack_grid_plane)

STATE_FIELDS = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                "send_flags", "joinreq", "joinrep")
METRIC_FIELDS = ("in_group", "view_slots", "adds", "removals",
                 "false_removals", "victim_slots", "sent", "recv")

#: small row block so n=64 runs as multiple grid blocks (the real
#: default is 512; the kernel is shape-generic in the block height)
BLOCK = 32


def _cfg(scenario, n):
    if scenario == "ramp_fail":
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=False, seed=3, total_ticks=120,
                         fail_tick=40, step_rate=0.5)
    if scenario == "drop":
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=True, msg_drop_prob=0.3, seed=5,
                         total_ticks=120, fail_tick=60, step_rate=0.25,
                         drop_open_tick=10, drop_close_tick=100)
    if scenario == "churn":
        return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                         drop_msg=False, seed=7, total_ticks=200,
                         churn_rate=0.25, rejoin_after=30,
                         step_rate=40.0 / n)
    if scenario == "even_fanout":
        # F=4: two exchange-round pairs, no leftover round — covers
        # the doubled-lane merge's even case
        return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                         drop_msg=False, seed=17, total_ticks=200,
                         churn_rate=0.25, rejoin_after=30, fanout=4,
                         step_rate=40.0 / n)
    if scenario == "aged":
        # tiny TREMOVE + a long drop window: entries routinely age to
        # exactly t_remove in a partner's table, exercising the packed
        # freshness floor's boundary (t - ts < t_remove must exclude
        # age == t_remove — the XLA path is the arbiter)
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=True, msg_drop_prob=0.6, seed=13,
                         total_ticks=120, fail_tick=60, t_remove=3,
                         step_rate=1.0, drop_open_tick=2,
                         drop_close_tick=118)
    if scenario == "powerlaw":
        # fanout capped at 5: interpret-mode execution degrades
        # pathologically at exactly 8 unrolled exchange rounds (see
        # overlay_mega.mega_supported); the capped power-law still
        # exercises the in-kernel out-degree gating
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=False, seed=9, total_ticks=120,
                         fail_tick=50, step_rate=0.5, topology="powerlaw",
                         fanout=5)
    raise ValueError(scenario)


def _compare(cfg, length):
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    run_x = make_overlay_run(cfg, length, use_pallas=False)
    run_g = make_grid_run(cfg, length, block_rows=BLOCK)
    fx, mx = run_x(state, sched)
    fg, mg = run_g(state, sched)
    for name in STATE_FIELDS:
        a, b = np.asarray(getattr(fx, name)), np.asarray(getattr(fg, name))
        assert np.array_equal(a, b), f"state field {name} diverged"
    for name in METRIC_FIELDS:
        a, b = np.asarray(getattr(mx, name)), np.asarray(getattr(mg, name))
        assert np.array_equal(a, b), \
            f"metric {name} diverged at ticks {np.flatnonzero(a != b)[:5]}"
    assert np.all(np.asarray(mg.live_uncovered) == -1)
    return fg


# ramp_fail/drop/churn stay tier-1; the remaining topology scenarios
# ride the slow lap (each is ~10-15 s of grid-kernel compiles, and
# tier-1 must fit its 870 s wrapper on 1-core containers)
@pytest.mark.parametrize("scenario,n", [
    ("ramp_fail", 64),
    ("drop", 128),
    ("churn", 64),
    pytest.param("powerlaw", 64, marks=pytest.mark.slow),
    pytest.param("aged", 64, marks=pytest.mark.slow),
    pytest.param("even_fanout", 64, marks=pytest.mark.slow),
])
def test_grid_kernel_bitwise_equals_xla(scenario, n):
    cfg = _cfg(scenario, n)
    # 44 = 2 full GRID_TICKS chunks + a 12-tick remainder launch,
    # crossing two SLOT_EPOCH re-slot boundaries
    _compare(cfg, 44)


def test_grid_kernel_full_run_with_churn_cycle():
    """A whole churn run: ramp, churn fails, rejoins, steady state."""
    cfg = _cfg("churn", 64)
    final = _compare(cfg, cfg.total_ticks)
    assert int(np.asarray(final.in_group).sum()) == cfg.n


def test_grid_kernel_resume_bit_identical():
    """Stopping after 17 ticks and resuming matches one uninterrupted
    run (the clock lives in the state; chunk boundaries are free)."""
    cfg = _cfg("ramp_fail", 64)
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    mid, _ = make_grid_run(cfg, 17, block_rows=BLOCK)(state, sched)
    final_split, _ = make_grid_run(cfg, 23, block_rows=BLOCK)(mid, sched)
    final_once, _ = make_grid_run(cfg, 40, block_rows=BLOCK)(state, sched)
    for name in STATE_FIELDS:
        assert np.array_equal(np.asarray(getattr(final_split, name)),
                              np.asarray(getattr(final_once, name))), name


def test_grid_plane_roundtrip():
    """pack -> unpack is the identity on a mid-run state."""
    cfg = _cfg("churn", 64)
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    mid, _ = make_overlay_run(cfg, 30, use_pallas=False)(state, sched)
    back = unpack_grid_plane(cfg, pack_grid_plane(cfg, mid), mid.tick)
    for name in STATE_FIELDS:
        assert np.array_equal(np.asarray(getattr(mid, name)),
                              np.asarray(getattr(back, name))), name


def test_grid_supported_envelope():
    assert grid_supported(_cfg("churn", 64))
    # the grid path covers the sizes the VMEM megakernel cannot
    big = SimConfig(max_nnb=1 << 14, model="overlay",
                    single_failure=True, drop_msg=False,
                    total_ticks=100, step_rate=40.0 / (1 << 14))
    assert grid_supported(big)
    # a user-set view width that overflows the 128-lane packed plane
    wide = SimConfig(max_nnb=64, model="overlay", single_failure=True,
                     drop_msg=False, total_ticks=100, step_rate=0.5,
                     overlay_view=65)
    assert not grid_supported(wide)
