"""Sparse device->host event staging (core/sim.py _pack_sparse /
_masks_to_host): round-trip exactness, the dense-fallback overflow
path, and degenerate shapes."""

import jax.numpy as jnp
import numpy as np

from gossip_protocol_tpu.core.sim import _masks_to_host


def _roundtrip(added, removed, cap):
    a, r = _masks_to_host(jnp.asarray(added), jnp.asarray(removed), cap)
    assert np.array_equal(np.asarray(a), added)
    assert np.array_equal(np.asarray(r), removed)


def test_sparse_roundtrip_exact():
    rng = np.random.default_rng(0)
    c, n = 7, 100                      # n not a multiple of 32 (padding)
    added = rng.random((c, n, n)) < 0.01
    removed = rng.random((c, n, n)) < 0.002
    _roundtrip(added, removed, cap=1 << 14)


def test_sparse_dense_fallback_on_overflow():
    """Masks denser than the word cap must fall back to the dense
    transfer and still round-trip exactly."""
    rng = np.random.default_rng(1)
    c, n = 3, 64
    added = rng.random((c, n, n)) < 0.9          # nearly every word set
    removed = rng.random((c, n, n)) < 0.9
    _roundtrip(added, removed, cap=8)            # cap << nonzero words


def test_sparse_empty_and_full():
    c, n = 2, 64
    _roundtrip(np.zeros((c, n, n), bool), np.zeros((c, n, n), bool),
               cap=1 << 10)
    _roundtrip(np.ones((c, n, n), bool), np.ones((c, n, n), bool),
               cap=2 * c * n * ((n + 31) // 32))  # exactly at the cap


def test_sparse_degenerate_shapes():
    # zero-length chunk and tiny n take the direct np.asarray path
    a, r = _masks_to_host(jnp.zeros((0, 8, 8), bool),
                          jnp.zeros((0, 8, 8), bool), cap=16)
    assert a.shape == (0, 8, 8) and r.shape == (0, 8, 8)
    one = jnp.ones((2, 1, 1), bool)
    a, r = _masks_to_host(one, one, cap=16)
    assert a.all() and r.all()
