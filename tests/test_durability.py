"""Durable serving (PR 12): checkpoint spill tier, write-ahead
journal, and crash-restart recovery (gossip_protocol_tpu/store/).

The contracts under test:

* **spill exactness** — a LaneCheckpoint flattened to npz and
  rebuilt is bit-identical (state, chunks, clock) and DIGEST-stable,
  for both chunk families; the pure-numpy
  ``checkpoint_digest_from_arrays`` (the jax-free inspect path) is
  pinned byte-for-byte to the live ``LaneCheckpoint.digest``;
* **the address covers the config** — same-state lanes of different
  scenario variants never share a content address (they resume into
  different futures; regression for the grader-template collision);
* **atomic, validated spills** — a save leaves no tmp droppings, a
  corrupted file raises :class:`CheckpointValidationError` carrying
  the single-command ``service_smoke.py inspect`` repro;
* **spill-before-evict** — the RAM LRU never drops a snapshot
  without a bit-identical copy on disk first (both policies);
* **journal discipline** — append-order round trip, a torn FINAL
  line is tolerated (the append the death interrupted), a torn
  interior line raises;
* **kill-at-every-cut** — a service killed after EVERY dispatch
  boundary of a multi-leg run recovers in a fresh service object
  with ``restarted_lanes == 0`` and results bit-identical to solo;
* **degraded recovery** — a corrupt newest cut falls back to the
  next-older one (still zero restarts); every cut corrupt restarts
  the lane from tick 0, counted, still bit-correct;
* the slow tier runs the genuine cross-process 204-request
  kill-and-restart acceptance gate (store/harness.py).
"""

import json
import os
import shutil

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.fleet import (FleetSimulation,
                                            checkpoint_arrays,
                                            checkpoint_from_arrays)
from gossip_protocol_tpu.models.segments import checkpoint_ticks
from gossip_protocol_tpu.service import FleetService
from gossip_protocol_tpu.service.replay import result_digest
from gossip_protocol_tpu.service.resilience import solo_execute
from gossip_protocol_tpu.store import RunStore
from gossip_protocol_tpu.store.harness import _drive
from gossip_protocol_tpu.store.journal import Journal, read_journal
from gossip_protocol_tpu.store.spill import (CheckpointStore,
                                             CheckpointValidationError,
                                             SpilledCheckpoint,
                                             checkpoint_digest_from_arrays,
                                             inspect_spill, read_spill,
                                             save_spill)

pytestmark = [pytest.mark.service, pytest.mark.resilience]


def _overlay_churn_drop(n=32, ticks=96):
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=True, msg_drop_prob=0.1, seed=0,
                     total_ticks=ticks, churn_rate=0.2, rejoin_after=30,
                     step_rate=12 / n, drop_open_tick=ticks // 3,
                     drop_close_tick=2 * ticks // 3)


def _dense_churn_drop(n=12, ticks=60):
    return SimConfig(max_nnb=n, single_failure=False, drop_msg=True,
                     msg_drop_prob=0.1, seed=0, total_ticks=ticks,
                     fail_tick=30, rejoin_after=15, drop_open_tick=10,
                     drop_close_tick=50)


def _one_checkpoint(cfg, seeds=(1, 2), legs=1):
    """Mid-run LaneCheckpoints: run ``legs`` legs of a fleet and
    return the cut's snapshots (one per seed)."""
    sim = FleetSimulation(cfg)
    cuts = checkpoint_ticks(cfg)
    assert len(cuts) >= legs
    cfgs = [cfg.replace(seed=s) for s in seeds]
    leg = sim.run_leg(configs=cfgs, ticks=cuts[0])
    for cut in cuts[1:legs]:
        leg = sim.run_leg(resume=leg.checkpoints,
                          ticks=cut - leg.checkpoints[0].tick)
    return leg.checkpoints


def _assert_ck_equal(a, b, tag=""):
    assert a.cfg == b.cfg and a.mode == b.mode, tag
    assert int(a.tick) == int(b.tick) and int(a.legs) == int(b.legs)
    assert sorted(a.state) == sorted(b.state), tag
    for k in a.state:
        assert np.array_equal(np.asarray(a.state[k]),
                              np.asarray(b.state[k])), f"{tag} state.{k}"
    assert len(a.chunks) == len(b.chunks), tag
    for j, (ca, cb) in enumerate(zip(a.chunks, b.chunks)):
        if isinstance(ca, tuple):
            for f, (xa, xb) in enumerate(zip(ca, cb)):
                assert np.array_equal(np.asarray(xa), np.asarray(xb)), \
                    f"{tag} chunk[{j}][{f}]"
        else:
            import dataclasses
            for fld in dataclasses.fields(ca):
                assert np.array_equal(
                    np.asarray(getattr(ca, fld.name)),
                    np.asarray(getattr(cb, fld.name))), \
                    f"{tag} chunk[{j}].{fld.name}"


# ---- spill round trip ------------------------------------------------
@pytest.mark.parametrize("family", ["overlay", "dense"])
def test_spill_roundtrip_bit_identical_and_digest_stable(tmp_path,
                                                         family):
    cfg = (_overlay_churn_drop() if family == "overlay"
           else _dense_churn_drop())
    for ck in _one_checkpoint(cfg):
        meta, arrays = checkpoint_arrays(ck)
        # the pure-numpy digest (the jax-free inspect path) is pinned
        # to the live one — across the JSON round trip the spill
        # header actually takes
        meta_rt = json.loads(json.dumps(meta, sort_keys=True))
        assert checkpoint_digest_from_arrays(meta_rt, arrays) \
            == ck.digest()
        path = str(tmp_path / f"{ck.digest()}.npz")
        save_spill(path, meta, arrays)
        meta2, arrays2 = read_spill(path)
        back = checkpoint_from_arrays(meta2, arrays2)
        _assert_ck_equal(ck, back, tag=family)
        assert back.digest() == ck.digest()
        assert back.mesh_desc is None  # deliberately not serialized


def test_digest_folds_full_config():
    """Regression: the grader templates share seed + mode and carry
    bit-identical state before their failures fire — their snapshots
    must STILL get distinct content addresses (they resume into
    different futures)."""
    import dataclasses
    ck = _one_checkpoint(_dense_churn_drop(), seeds=(1,))[0]
    twin = dataclasses.replace(
        ck, cfg=ck.cfg.replace(msg_drop_prob=0.2))
    assert twin.state is ck.state  # same carry bytes by construction
    assert twin.digest() != ck.digest()


def test_save_spill_is_atomic_and_validated(tmp_path):
    ck = _one_checkpoint(_dense_churn_drop(), seeds=(1,))[0]
    store = CheckpointStore(str(tmp_path / "spill"))
    proxy = store.ref(ck)
    assert isinstance(proxy, SpilledCheckpoint)
    assert not proxy.done and int(proxy.tick) == int(ck.tick)
    # eager policy: write-through at put, no tmp droppings
    assert store.spills == 1 and store.spill_bytes > 0
    assert sorted(os.listdir(store.spill_dir)) \
        == [f"{ck.digest()}.npz"]
    # corrupt the file mid-body, drop the RAM copy, reload
    path = store._path(ck.digest())
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        f.write(b"\xff" * 64)
    cold = CheckpointStore(str(tmp_path / "spill"))
    with pytest.raises(CheckpointValidationError,
                       match="service_smoke.py inspect"):
        cold.fetch(ck.digest())
    assert cold.validation_failures == 1
    verdict = inspect_spill(str(tmp_path), ck.digest())
    assert verdict["ok"] is False and verdict["why"]


def test_fetch_unspilled_address_raises_file_not_found(tmp_path):
    store = CheckpointStore(str(tmp_path / "spill"))
    with pytest.raises(FileNotFoundError, match="never|no spilled"):
        store.fetch("0123456789abcdef")


@pytest.mark.parametrize("policy", ["eager", "lazy"])
def test_lru_spills_before_evicting(tmp_path, policy):
    """No snapshot is ever dropped from RAM without a bit-identical
    copy on disk first — under BOTH policies; every evicted address
    stays fetchable."""
    cfg = _dense_churn_drop()
    cks = _one_checkpoint(cfg, seeds=(1, 2, 3, 4, 5))
    store = CheckpointStore(str(tmp_path / "spill"),
                            max_ram_snapshots=2, policy=policy)
    proxies = [store.ref(ck) for ck in cks]
    st = store.stats()
    assert st["evicted_snapshots"] == 3 and st["ram_snapshots"] == 2
    # eager spills at put; lazy only at eviction — but the evicted
    # ones are ALWAYS on disk
    assert st["spills"] == (5 if policy == "eager" else 3)
    on_disk = set(os.listdir(store.spill_dir))
    for ck in cks[:3]:
        assert f"{ck.digest()}.npz" in on_disk
    # newest-first so the two RAM residents hit before reloads start
    # churning the LRU
    for ck, proxy in zip(reversed(cks), reversed(proxies)):
        _assert_ck_equal(ck, store.fetch(proxy.digest))
    assert store.stats()["ram_hits"] == 2
    assert store.stats()["reloads"] == 3


# ---- journal ---------------------------------------------------------
def test_journal_roundtrip_and_torn_tail(tmp_path):
    run_dir = str(tmp_path)
    j = Journal(run_dir)
    j.meta({"max_batch": 4})
    cfg = _dense_churn_drop()
    from types import SimpleNamespace
    j.submit(SimpleNamespace(rid=0, cfg=cfg, mode="trace",
                             priority="default", tenant=None))
    j.cut(0, 16, 1, "deadbeefdeadbeef")
    j.fault(3, "device_loss")
    j.outcome(0, "completed")
    j.recover_mark(1, 1, warmed_buckets=1)
    j.close()
    recs = read_journal(run_dir)
    assert [r["rec"] for r in recs] \
        == ["meta", "submit", "cut", "fault", "outcome", "recover"]
    assert recs[1]["cfg"] == cfg.to_dict()
    assert SimConfig.from_dict(recs[1]["cfg"]) == cfg
    # a torn FINAL line is the append the death interrupted: tolerated
    path = os.path.join(run_dir, Journal.FILENAME)
    with open(path, "a") as f:
        f.write('{"rec": "outcome", "rid": 1, "sta')
    assert len(read_journal(run_dir)) == 6
    # a torn INTERIOR line is corruption: raises
    lines = open(path).read().splitlines()
    lines[2] = lines[2][:10]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    with pytest.raises(ValueError, match="corrupt journal record"):
        read_journal(run_dir)


# ---- crash-restart recovery ------------------------------------------
_RECOVERY_CFG = _overlay_churn_drop()
#: one bucket, max_batch >= n_seeds => one dispatch per leg, so the
#: dispatch count IS the leg count: killing after dispatch k abandons
#: the run with k-1 journaled cuts (the k-th leg died unresolved) —
#: k=1 exercises never-checkpointed re-admission from tick 0
_RECOVERY_LEGS = len(checkpoint_ticks(_RECOVERY_CFG)) + 1


def _killed_run(run_dir, kill_after, cfg=_RECOVERY_CFG,
                seeds=(1, 2, 3), checkpoint_every=16):
    """Serve ``seeds`` against ``run_dir`` and abandon the service
    object after ``kill_after`` dispatches (the in-process crash
    model); returns False as _drive does on a kill."""
    svc = FleetService(max_batch=len(seeds) + 1,
                       checkpoint_every=checkpoint_every,
                       run_dir=run_dir)
    svc.warm(cfg, "trace")
    for s in seeds:
        svc.submit(cfg, seed=s)
    return _drive(svc, kill_after=kill_after)


@pytest.mark.parametrize("kill_after", range(1, _RECOVERY_LEGS))
def test_kill_at_every_cut_recovers_bit_identical(tmp_path,
                                                  kill_after):
    """The satellite gate: tear the service down after EVERY dispatch
    boundary of a multi-leg run; recovery must resume from the last
    spilled cut (never tick 0) and finish bit-identical to solo."""
    run_dir = str(tmp_path)
    seeds = (1, 2, 3)
    assert _killed_run(run_dir, kill_after) is False
    svc, handles = FleetService.recover(run_dir)
    assert sorted(handles) == [0, 1, 2]
    assert _drive(svc)
    st = svc.stats()
    assert st["elastic"]["restarted_lanes"] == 0
    dur = st["durability"]
    assert dur["recoveries"] == 1 and dur["recovered_requests"] == 3
    if kill_after > 1:     # cuts existed: recovery reloaded from disk
        assert dur["reloads"] >= 1
    for rid, s in enumerate(seeds):
        ref = solo_execute(_RECOVERY_CFG.replace(seed=s), "trace")
        assert result_digest(handles[rid].result()) \
            == result_digest(ref)
        assert handles[rid].status == "completed"


def test_recover_completed_run_readmits_nothing(tmp_path):
    """Killing DURING the final leg still journals every outcome (the
    leg resolves before the trip) — recovering such a run dir finds
    everything terminal and re-admits nothing."""
    run_dir = str(tmp_path)
    assert _killed_run(run_dir, _RECOVERY_LEGS) is False
    svc, handles = FleetService.recover(run_dir)
    assert handles == {}
    assert svc.stats()["elastic"]["restarted_lanes"] == 0
    assert svc.stats()["durability"]["recovered_requests"] == 0


def test_recovery_survives_corrupt_newest_cut(tmp_path):
    """A corrupt latest spill falls back to the next-older cut (still
    zero restarts); every cut corrupt restarts the lane from tick 0 —
    counted, and STILL bit-correct."""
    run_dir = str(tmp_path / "run")
    # kill late enough that every lane has >= 2 journaled cuts
    assert _RECOVERY_LEGS >= 4
    assert _killed_run(run_dir, _RECOVERY_LEGS - 1) is False
    by_rid = {}
    for r in read_journal(run_dir):
        if r.get("rec") == "cut":
            by_rid.setdefault(r["rid"], []).append(r)
    assert all(len(cuts) >= 2 for cuts in by_rid.values())
    partial = str(tmp_path / "partial")
    total = str(tmp_path / "total")
    shutil.copytree(run_dir, partial)
    shutil.copytree(run_dir, total)

    def _corrupt(path):
        with open(path, "r+b") as f:
            f.seek(os.path.getsize(path) // 2)
            f.write(b"\xff" * 64)

    # variant A: newest cut of every lane corrupted -> older cut wins
    for cuts in by_rid.values():
        _corrupt(os.path.join(partial, "spill",
                              f"{cuts[-1]['digest']}.npz"))
    svc, handles = FleetService.recover(partial)
    assert _drive(svc)
    st = svc.stats()
    assert st["elastic"]["restarted_lanes"] == 0
    assert st["durability"]["validation_failures"] >= 1
    for rid, s in enumerate((1, 2, 3)):
        assert result_digest(handles[rid].result()) == result_digest(
            solo_execute(_RECOVERY_CFG.replace(seed=s), "trace"))

    # variant B: EVERY spill corrupted -> genuine tick-0 restarts
    for name in os.listdir(os.path.join(total, "spill")):
        _corrupt(os.path.join(total, "spill", name))
    svc, handles = FleetService.recover(total)
    assert svc.stats()["elastic"]["restarted_lanes"] == len(handles)
    assert _drive(svc)
    for rid, s in enumerate((1, 2, 3)):
        assert result_digest(handles[rid].result()) == result_digest(
            solo_execute(_RECOVERY_CFG.replace(seed=s), "trace"))


def test_journal_outcomes_bridge_the_kill(tmp_path):
    """Pre-kill completions are proven by their journal outcome
    digests — the cross-process half of the parity gate."""
    run_dir = str(tmp_path)
    svc = FleetService(max_batch=2, checkpoint_every=16,
                       run_dir=run_dir)
    cfg = _RECOVERY_CFG
    svc.warm(cfg, "trace")
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    assert _drive(svc)
    outcomes = {r["rid"]: r for r in read_journal(run_dir)
                if r.get("rec") == "outcome"}
    assert sorted(outcomes) == [0, 1]
    for rid, h in enumerate(hs):
        assert outcomes[rid]["status"] == "completed"
        assert outcomes[rid]["digest"] == result_digest(h.result())
    dur = svc.stats()["durability"]
    assert dur["journal_records"] == svc.store.journal.records_appended
    assert dur["spills"] >= 1 and dur["spill_bytes"] > 0


def test_stats_durability_counters(tmp_path):
    svc = FleetService(max_batch=2)
    assert svc.stats()["durability"] is None  # store-less: explicit
    svc = FleetService(max_batch=2, run_dir=str(tmp_path))
    dur = svc.stats()["durability"]
    for key in ("spills", "spill_bytes", "journal_records",
                "recoveries", "recovered_requests",
                "evicted_snapshots", "validation_failures", "policy"):
        assert key in dur, key
    assert dur["journal_records"] == 1  # the meta record
    assert isinstance(svc.store, RunStore)


def test_run_store_bounds_ram_via_proxies(tmp_path):
    """The scheduler parks SpilledCheckpoint proxies on req.resume —
    the RAM bound is real because queued requests never pin full
    snapshots."""
    run_dir = str(tmp_path)
    svc = FleetService(max_batch=4, checkpoint_every=16,
                       run_dir=run_dir)
    cfg = _RECOVERY_CFG
    svc.warm(cfg, "trace")
    for s in (1, 2, 3):
        svc.submit(cfg, seed=s)
    svc.flush(next(iter(svc._queues)))  # leg 1 only (flush() drains)
    svc.resolve_inflight()  # leg 1 checkpointed, batch re-queued
    queued = [r for q in svc._queues.values() for r in q]
    assert queued and all(
        isinstance(r.resume, SpilledCheckpoint) for r in queued)
    assert _drive(svc)
    assert all(h.status == "completed"
               for h in svc._handles.values())


# ---- the acceptance gate (slow tier) ---------------------------------
@pytest.mark.slow
def test_kill_restart_204_requests_cross_process():
    """The PR 12 gate at bench scale: the 204-request mixed replay
    killed mid-run in a SUBPROCESS recovers here with 204/204
    completed, restarted_lanes == 0, and outcome digests identical to
    the uninterrupted baseline (all raised on violation inside
    kill_restart_replay)."""
    from gossip_protocol_tpu.store.harness import kill_restart_replay
    m, _ = kill_restart_replay(seeds_per_template=34, n_overlay=512,
                               t_overlay=96, checkpoint_every=48,
                               kill_frac=0.5, child=True)
    assert m["requests"] == 204 and m["completed"] == 204
    assert m["restarted_lanes"] == 0 and m["digest_match"]
    assert m["cross_process"] and m["completed_before_kill"] > 0
    assert m["outcome_digest"] == m["baseline_digest"]
