"""Differential tests: fused overlay exchange+merge kernel vs XLA path.

The Pallas kernel (ops/pallas/overlay_exchange.py) must be
bit-identical to the composable XLA phases in models/overlay.py —
state trajectories and metrics — across join ramp, scripted failure,
drop window, and churn scenarios.  On CPU the kernel runs in
interpret mode; the same contract holds compiled on TPU (exercised by
bench.py and the profile harness there).
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (
    init_overlay_state, make_overlay_schedule, make_overlay_tick)


def _run_both(cfg, ticks):
    sched = make_overlay_schedule(cfg)
    tick_x = jax.jit(make_overlay_tick(cfg, use_pallas=False))
    tick_p = jax.jit(make_overlay_tick(cfg, use_pallas=True))
    sx = sp = init_overlay_state(cfg)
    for _ in range(ticks):
        sx, mx = tick_x(sx, sched)
        sp, mp = tick_p(sp, sched)
        yield sx, mx, sp, mp


def _assert_state_equal(sx, sp, t):
    for name in ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                 "send_flags", "joinreq", "joinrep"):
        a = np.asarray(getattr(sx, name))
        b = np.asarray(getattr(sp, name))
        assert np.array_equal(a, b), \
            f"state field {name} diverged at tick {t}"


def _assert_metrics_equal(mx, mp, t):
    for name in ("in_group", "view_slots", "adds", "removals",
                 "false_removals", "victim_slots", "live_uncovered",
                 "sent", "recv"):
        a = int(np.asarray(getattr(mx, name)))
        b = int(np.asarray(getattr(mp, name)))
        assert a == b, f"metric {name} diverged at tick {t}: {a} != {b}"


@pytest.mark.parametrize("n,scenario", [
    (64, "ramp_fail"),
    (128, "drop"),
    (64, "churn"),
])
def test_kernel_bitwise_equals_xla(n, scenario):
    if scenario == "ramp_fail":
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=False, seed=3, total_ticks=120,
                        fail_tick=40, step_rate=0.5)
        ticks = 80
    elif scenario == "drop":
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=True, msg_drop_prob=0.3, seed=5,
                        total_ticks=120, fail_tick=60, step_rate=0.25,
                        drop_open_tick=10, drop_close_tick=100)
        ticks = 80
    else:
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                        drop_msg=False, seed=7, total_ticks=200,
                        churn_rate=0.25, rejoin_after=30,
                        step_rate=40.0 / n)
        ticks = 160
    for t, (sx, mx, sp, mp) in enumerate(_run_both(cfg, ticks)):
        _assert_state_equal(sx, sp, t)
        _assert_metrics_equal(mx, mp, t)


def test_kernel_small_block_sizes():
    """N smaller than the default block: one block, pure butterfly."""
    cfg = SimConfig(max_nnb=32, model="overlay", single_failure=True,
                    drop_msg=False, seed=11, total_ticks=80,
                    fail_tick=30, step_rate=0.5)
    for t, (sx, mx, sp, mp) in enumerate(_run_both(cfg, 60)):
        _assert_state_equal(sx, sp, t)
        _assert_metrics_equal(mx, mp, t)
