"""Differential tests: fused overlay exchange+merge kernel vs XLA path.

The Pallas kernel (ops/pallas/overlay_exchange.py) must be
bit-identical to the composable XLA phases in models/overlay.py —
state trajectories and metrics — across join ramp, scripted failure,
drop window, and churn scenarios.  On CPU the kernel runs in
interpret mode; the same contract holds compiled on TPU (exercised by
bench.py and the profile harness there).
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (
    init_overlay_state, make_overlay_schedule, make_overlay_tick)


def _run_both(cfg, ticks):
    sched = make_overlay_schedule(cfg)
    tick_x = jax.jit(make_overlay_tick(cfg, use_pallas=False))
    tick_p = jax.jit(make_overlay_tick(cfg, use_pallas=True))
    sx = sp = init_overlay_state(cfg)
    for _ in range(ticks):
        sx, mx = tick_x(sx, sched)
        sp, mp = tick_p(sp, sched)
        yield sx, mx, sp, mp


def _assert_state_equal(sx, sp, t):
    for name in ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                 "send_flags", "joinreq", "joinrep"):
        a = np.asarray(getattr(sx, name))
        b = np.asarray(getattr(sp, name))
        assert np.array_equal(a, b), \
            f"state field {name} diverged at tick {t}"


def _assert_metrics_equal(mx, mp, t):
    for name in ("in_group", "view_slots", "adds", "removals",
                 "false_removals", "victim_slots", "live_uncovered",
                 "sent", "recv"):
        a = int(np.asarray(getattr(mx, name)))
        b = int(np.asarray(getattr(mp, name)))
        assert a == b, f"metric {name} diverged at tick {t}: {a} != {b}"


@pytest.mark.parametrize("n,scenario", [
    (64, "ramp_fail"),
    (128, "drop"),
    (64, "churn"),
])
def test_kernel_bitwise_equals_xla(n, scenario):
    if scenario == "ramp_fail":
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=False, seed=3, total_ticks=120,
                        fail_tick=40, step_rate=0.5)
        ticks = 80
    elif scenario == "drop":
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=True,
                        drop_msg=True, msg_drop_prob=0.3, seed=5,
                        total_ticks=120, fail_tick=60, step_rate=0.25,
                        drop_open_tick=10, drop_close_tick=100)
        ticks = 80
    else:
        cfg = SimConfig(max_nnb=n, model="overlay", single_failure=False,
                        drop_msg=False, seed=7, total_ticks=200,
                        churn_rate=0.25, rejoin_after=30,
                        step_rate=40.0 / n)
        ticks = 160
    for t, (sx, mx, sp, mp) in enumerate(_run_both(cfg, ticks)):
        _assert_state_equal(sx, sp, t)
        _assert_metrics_equal(mx, mp, t)


def test_kernel_small_block_sizes():
    """N smaller than the default block: one block, pure butterfly."""
    cfg = SimConfig(max_nnb=32, model="overlay", single_failure=True,
                    drop_msg=False, seed=11, total_ticks=80,
                    fail_tick=30, step_rate=0.5)
    for t, (sx, mx, sp, mp) in enumerate(_run_both(cfg, 60)):
        _assert_state_equal(sx, sp, t)
        _assert_metrics_equal(mx, mp, t)


def test_kernel_multiblock_xor_dma_path():
    """A small block_rows forces nb > 1, exercising the
    scalar-prefetch block-index-map XOR DMA path (block i sources
    block ``i ^ (m // b)``) that the default 512-row block never hits
    at test sizes; the powerlaw case also covers the F > 4
    block-halving branch (fused_overlay_tick's VMEM gate)."""
    import jax.numpy as jnp

    from gossip_protocol_tpu.models.overlay import (
        exchange_mask, make_overlay_schedule, resolved_dims)
    from gossip_protocol_tpu.ops.pallas.overlay_exchange import (
        fused_overlay_tick)

    for topology in ("uniform", "powerlaw"):
        # fanout capped at 7 for the powerlaw case: still > 4 (the
        # block-halving branch), avoiding the documented 8-round
        # XLA:CPU interpret pathology (ops/pallas/overlay_mega.py)
        cfg = SimConfig(max_nnb=64, model="overlay", single_failure=True,
                        drop_msg=False, seed=13, total_ticks=80,
                        fail_tick=30, step_rate=0.5, topology=topology,
                        fanout=0 if topology == "uniform" else 7)
        n = cfg.n
        k, f = resolved_dims(cfg)
        sched = make_overlay_schedule(cfg)
        tick_x = jax.jit(make_overlay_tick(cfg, use_pallas=False))
        state = init_overlay_state(cfg)
        # run the XLA path to a mid-run state with live traffic
        for _ in range(24):
            state, _ = tick_x(state, sched)
        t = state.tick
        i32 = jnp.int32
        ids0, hb0, ts0 = state.ids, state.hb, state.ts
        p0 = jnp.where(ids0 >= 0, ((ts0 + 1) << 12) | (hb0 + 1), 0)
        proc = jnp.ones((n,), bool)
        ops = proc & state.in_group
        bits = (proc.astype(i32) | (ops.astype(i32) << 1))
        idsaux = jnp.concatenate([
            ids0, state.own_hb[:, None], bits[:, None],
            state.send_flags.astype(i32)], 1)
        intro = jnp.zeros((8, k), i32) \
            .at[0].set(ids0[0]).at[1].set(p0[0]) \
            .at[2, 0].set(state.own_hb[0])
        masks = jnp.stack([exchange_mask(sched.seed, t - 1, fi, n)
                           for fi in range(f)])
        scalars = jnp.stack([t, sched.seed.astype(i32), sched.victim_lo,
                             sched.victim_hi, sched.fail_tick,
                             sched.rejoin_after,
                             sched.churn_thr.astype(i32),
                             sched.churn_after])
        kw = dict(k=k, t_remove=cfg.t_remove,
                  churn_lo=cfg.total_ticks // 4,
                  churn_span=max(cfg.total_ticks // 2, 1))
        ref = fused_overlay_tick(idsaux, p0, intro, masks, scalars, **kw)
        multi = fused_overlay_tick(idsaux, p0, intro, masks, scalars,
                                   block_rows=16, **kw)
        for name, r, m in zip(("ids", "hb", "ts", "ctr"), ref, multi):
            assert np.array_equal(np.asarray(r), np.asarray(m)), \
                f"{topology}: {name} diverged between nb=1 and nb>1"


def test_tiny_view_falls_back_to_xla():
    """overlay_view < N_COUNTERS must not trip kernel asserts: the
    use_kernel gate routes such shapes to the XLA phases (round-2
    advisor finding)."""
    cfg = SimConfig(max_nnb=16, model="overlay", single_failure=True,
                    drop_msg=False, seed=3, total_ticks=60, fail_tick=30,
                    step_rate=0.5, overlay_view=4)
    sched = make_overlay_schedule(cfg)
    tick = jax.jit(make_overlay_tick(cfg, use_pallas=True))
    state = init_overlay_state(cfg)
    for _ in range(10):
        state, _ = tick(state, sched)
    assert int(np.asarray(state.in_group).sum()) > 0
