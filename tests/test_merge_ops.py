"""Unit/property tests for the gossip merge reduction (ops/merge.py) —
the kernel the reference lacks unit tests for (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from gossip_protocol_tpu.ops.merge import FILL, gossip_reductions


def brute_force(recv_from, known, hb, ts, now, t_remove):
    r_dim, s_dim = recv_from.shape
    j_dim = known.shape[1]
    m_all = np.full((r_dim, j_dim), -1, np.int32)
    m_fr = np.full((r_dim, j_dim), -1, np.int32)
    t_fr = np.full((r_dim, j_dim), -1, np.int32)
    anyf = np.zeros((r_dim, j_dim), bool)
    for r in range(r_dim):
        for s in range(s_dim):
            if not recv_from[r, s]:
                continue
            for j in range(j_dim):
                if not known[s, j]:
                    continue
                m_all[r, j] = max(m_all[r, j], hb[s, j])
                if now - ts[s, j] < t_remove:
                    m_fr[r, j] = max(m_fr[r, j], hb[s, j])
                    t_fr[r, j] = max(t_fr[r, j], ts[s, j])
                    anyf[r, j] = True
    return m_all, m_fr, t_fr, anyf


@pytest.mark.parametrize("n,block", [(7, 128), (16, 4), (33, 8), (64, 64)])
def test_matches_brute_force(n, block):
    rng = np.random.RandomState(n)
    recv_from = rng.rand(n, n) < 0.4
    known = rng.rand(n, n) < 0.6
    hb = rng.randint(1, 100, (n, n)).astype(np.int32)
    ts = rng.randint(0, 50, (n, n)).astype(np.int32)
    now = 45
    got = gossip_reductions(jnp.asarray(recv_from), jnp.asarray(known),
                            jnp.asarray(hb), jnp.asarray(ts), jnp.int32(now),
                            t_remove=20, block_size=block)
    want = brute_force(recv_from, known, hb, ts, now, 20)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), w)


def test_block_size_invariance():
    """The reduction must not depend on the blocking (padding included)."""
    rng = np.random.RandomState(0)
    n = 30
    args = (jnp.asarray(rng.rand(n, n) < 0.5), jnp.asarray(rng.rand(n, n) < 0.5),
            jnp.asarray(rng.randint(1, 9, (n, n)), jnp.int32),
            jnp.asarray(rng.randint(0, 40, (n, n)), jnp.int32), jnp.int32(35))
    ref = gossip_reductions(*args, t_remove=20, block_size=n)
    for b in (1, 3, 7, 16, 128):
        got = gossip_reductions(*args, t_remove=20, block_size=b)
        for g, w in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_merge_is_max_semiring():
    """Gossip merge is a (max, and) semiring reduction — commutative in
    senders and idempotent: merging the same payload twice changes
    nothing.  This is the property that makes the batched formulation
    (and the sharded ring version) equivalent to any sequential
    message order."""
    rng = np.random.RandomState(1)
    n = 12
    recv = rng.rand(n, n) < 0.5
    known = rng.rand(n, n) < 0.5
    hb = rng.randint(1, 50, (n, n)).astype(np.int32)
    ts = rng.randint(0, 30, (n, n)).astype(np.int32)
    base = gossip_reductions(jnp.asarray(recv), jnp.asarray(known),
                             jnp.asarray(hb), jnp.asarray(ts), jnp.int32(25),
                             t_remove=20)
    # sender permutation invariance
    perm = rng.permutation(n)
    permd = gossip_reductions(jnp.asarray(recv[:, perm]), jnp.asarray(known[perm]),
                              jnp.asarray(hb[perm]), jnp.asarray(ts[perm]),
                              jnp.int32(25), t_remove=20)
    for g, w in zip(base, permd):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    # idempotence: duplicating every sender leaves the maxima unchanged
    dup = gossip_reductions(jnp.asarray(np.concatenate([recv, recv], 1)),
                            jnp.asarray(np.concatenate([known, known], 0)),
                            jnp.asarray(np.concatenate([hb, hb], 0)),
                            jnp.asarray(np.concatenate([ts, ts], 0)),
                            jnp.int32(25), t_remove=20)
    for g, w in zip(base, dup):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_no_contribution_is_fill():
    got = gossip_reductions(jnp.zeros((3, 3), bool), jnp.ones((3, 3), bool),
                            jnp.ones((3, 3), jnp.int32), jnp.zeros((3, 3), jnp.int32),
                            jnp.int32(5), t_remove=20)
    assert (np.asarray(got[0]) == int(FILL)).all()
    assert not np.asarray(got[3]).any()
