"""Message-loss injection: window semantics and statistics
(EmulNet.cpp:90-94, Application.cpp:177-200)."""

import numpy as np

from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.state import make_schedule
from tests.conftest import scenario_cfg


def test_window_exact():
    """dropmsg is flipped *after* ticks 50 and 300 (fail() runs after
    mp1Run, Application.cpp:99-104), so sends are droppable exactly for
    ticks 51..300 inclusive."""
    cfg = scenario_cfg("msgdropsinglefailure")
    sched = make_schedule(cfg)
    active = np.asarray(sched.drop_active)
    assert not active[:51].any()
    assert active[51:301].all()
    assert not active[301:].any()


def test_no_drops_outside_window():
    cfg = scenario_cfg("msgdropsinglefailure", seed=0)
    res = Simulation(cfg).run()
    # outside the window every live in-group sender emits exactly
    # len(member list) gossips; with N=10 steady state that is 9/tick.
    steady_pre = res.sent[:, 40:50]
    assert (steady_pre == 9).all()
    post = res.sent[:, 320:330]
    failed = set(np.nonzero(res.fail_tick < 2**31 - 1)[0])
    for i in range(cfg.n):
        expect = 0 if i in failed else 9 - len(failed)
        assert (post[i] == expect).all()


def test_drop_rate_statistics():
    """Inside the window the observed drop rate must be ~MSG_DROP_PROB."""
    cfg = scenario_cfg("msgdropsinglefailure", seed=1)
    res = Simulation(cfg).run()
    window = res.sent[:, 60:95]  # before the failure, all 10 alive
    total = window.sum()
    expected = 10 * 9 * 35  # attempts
    rate = 1 - total / expected
    assert 0.05 < rate < 0.15  # p=0.1, ~3150 attempts


def test_drop_only_affects_delivery_not_state():
    """A dropped gossip must not update the receiver (no phantom
    refreshes): with 100% drop inside the window, every survivor's
    entries go stale and get removed TREMOVE after the window opens."""
    cfg = scenario_cfg("msgdropsinglefailure", seed=2, msg_drop_prob=1.0)
    res = Simulation(cfg).run()
    gv = res.grader_view()
    # last refresh at t=51 (sends of tick 50 delivered), removal when
    # t - 51 >= 20 -> tick 71, for *all* peers' entries
    early = {t for (obs, subj), t in gv["removal_ticks"].items()}
    assert 71 in early
    assert min(early) == 71
