"""The invariant analyzer (gossip_protocol_tpu/analysis/) — PR 10.

Two-sided contract, both directions tested:

* the CLEAN TREE passes every pass (jaxpr audit over the registered
  hot programs, AST purity lint + allowlist hygiene, cache-key
  completeness, runtime guards);
* every rule FIRES on a synthetic violation — a batched-clock fleet,
  a batched drop plane, a psum in the tick body, a device_put/
  callback in the scanned body, a dropped donation, a jnp-using
  staging fn, an unseeded rng, an in-place write on a host view, an
  unkeyed builder field, an injected steady-state recompile, an
  implicit transfer.  A rule that cannot fire protects nothing.

conftest forces 8 virtual CPU devices, so the mesh audit entries run
here exactly as they do under ``python -m gossip_protocol_tpu
.analysis`` (which re-execs itself to force the same flags).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_protocol_tpu.analysis import (RULES, Finding, jaxpr_audit,
                                          purity_lint, rule_names,
                                          run_all)
from gossip_protocol_tpu.analysis import cache_keys, guards
from gossip_protocol_tpu.config import SimConfig


def needs_devices(d):
    return pytest.mark.skipif(
        jax.device_count() < d, reason=f"needs {d} (virtual) devices")


# ---- the catalog itself ----------------------------------------------
def test_rule_catalog_names_at_least_eight_rules():
    """Acceptance: >= 8 named rules across the jaxpr/AST/guard passes,
    each with a motivating origin."""
    names = rule_names()
    assert len(names) >= 8
    assert len(set(names)) == len(names)
    for r in RULES:
        assert r.pass_name in ("jaxpr", "sharding", "ast", "guard")
        assert r.protects and r.origin
    # the PR-14 sharding pass ships all four per-axis rules
    sharding = {r.name for r in RULES if r.pass_name == "sharding"}
    assert sharding == {"lanes-axis-zero-collectives",
                        "peers-axis-collective-budget",
                        "replicated-plane-stays-replicated",
                        "spec-derivation-consistent"}
    assert "journal-before-mutation" in names


# ---- clean tree ------------------------------------------------------
def test_clean_tree_passes_ast_rules():
    assert purity_lint.lint() == []
    assert cache_keys.check() == []


def test_allowlist_entries_are_justified():
    """Satellite: lint_allow.toml is empty or every entry carries a
    why — and every entry actually MASKS a live finding (a stale
    entry is clutter that hides nothing)."""
    entries, findings = purity_lint.load_allowlist()
    assert findings == []
    for e in entries:
        raw = purity_lint.raw_findings(e.rule, e.file)
        assert any(e.match in f.path for f in raw), (
            f"allowlist entry {e.match!r} masks nothing in {e.file} — "
            "drop the stale entry")


def test_clean_tree_passes_jaxpr_audit():
    """The registered hot programs (solo dense/overlay, fleet pair,
    leg resume, grid kernel, and — with devices — the D=2 mesh pair)
    carry their conds, zero collectives, live donations, and no
    transfers.  This is the tier-1 twin of the CLI's jaxpr pass."""
    findings = jaxpr_audit.audit()
    assert findings == [], "\n".join(str(f) for f in findings)
    names = [p.name for p in jaxpr_audit.audit.last_programs]
    for expected in ("solo-dense-trace", "solo-overlay",
                     "fleet-dense-bench", "fleet-overlay",
                     "fleet-overlay-leg", "grid-kernel"):
        assert expected in names
    if jax.device_count() >= 2:
        assert "mesh-dense-bench-d2" in names
        assert "mesh-overlay-d2" in names
    if jax.device_count() >= 8:
        assert "mesh2d-lanes-peers" in names


# ---- jaxpr rule fixtures ---------------------------------------------
def _overlay_fixture_cfg():
    return SimConfig(model="overlay", max_nnb=16, total_ticks=32,
                     seed=5, step_rate=4.0 / 16)


def _batched_clock_jaxpr():
    """The PR-2 regression in miniature: vmap the overlay tick with
    the CLOCK batched (tick=0 instead of the shared None scalar) —
    the SLOT_EPOCH re-slot cond degrades to a both-branches select."""
    from gossip_protocol_tpu.models.overlay import (
        OVERLAY_FLEET_STATE_AXES, init_overlay_state,
        make_overlay_schedule, make_overlay_tick)
    cfg = _overlay_fixture_cfg()
    tick = make_overlay_tick(cfg, use_pallas=False, with_coverage=False)
    bad_axes = OVERLAY_FLEET_STATE_AXES.replace(tick=0)
    vtick = jax.vmap(tick, in_axes=(bad_axes, 0),
                     out_axes=(bad_axes, 0))

    @jax.jit
    def run(states, scheds):
        def step(carry, _):
            return vtick(carry, scheds)
        return jax.lax.scan(step, states, None, length=cfg.total_ticks)

    from gossip_protocol_tpu.core.fleet import stack_lanes
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    states = stack_lanes([init_overlay_state(c) for c in cfgs])
    # batched clock: every lane carries its own tick scalar
    scheds = stack_lanes([make_overlay_schedule(c) for c in cfgs])
    return jax.make_jaxpr(run)(states, scheds)


def test_batched_clock_fleet_is_caught():
    jx = _batched_clock_jaxpr()
    prog = jaxpr_audit.AuditedProgram(
        name="fixture-batched-clock", provenance="test_analysis",
        jaxpr=jx, min_cond=1, rules=("cond-stays-cond",))
    findings = jaxpr_audit.audit_program(prog)
    assert findings and findings[0].rule == "cond-stays-cond"
    # sanity: the SHARED-clock build of the same program is clean
    from gossip_protocol_tpu.models.overlay import (
        init_overlay_state, make_overlay_fleet_run,
        make_overlay_schedule)
    from gossip_protocol_tpu.core.fleet import stack_lanes
    cfg = _overlay_fixture_cfg()
    run = make_overlay_fleet_run(cfg, 2, use_pallas=False)
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    states = stack_lanes([init_overlay_state(c) for c in cfgs])
    states = states.replace(tick=init_overlay_state(cfgs[0]).tick)
    scheds = stack_lanes([make_overlay_schedule(c) for c in cfgs])
    good = jaxpr_audit.AuditedProgram(
        name="fixture-shared-clock", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(run)(states, scheds), min_cond=1,
        rules=("cond-stays-cond",))
    assert jaxpr_audit.audit_program(good) == []


@needs_devices(2)
def test_collective_in_tick_body_is_caught():
    from jax.sharding import Mesh, PartitionSpec as P

    from gossip_protocol_tpu.compat.jaxapi import shard_map
    mesh = Mesh(np.array(jax.devices()[:2]), ("lanes",))

    def body(x):
        return x + jax.lax.psum(x.sum(), "lanes")

    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("lanes"),),
                          out_specs=P("lanes")))
    jx = jax.make_jaxpr(f)(jnp.ones((2, 4)))
    prog = jaxpr_audit.AuditedProgram(
        name="fixture-psum", provenance="test_analysis", jaxpr=jx,
        rules=("zero-collectives-per-tick",))
    findings = jaxpr_audit.audit_program(prog)
    assert findings and findings[0].rule == "zero-collectives-per-tick"
    assert "psum" in findings[0].detail


def test_transfer_and_callback_in_scan_are_caught():
    def step_put(c, _):
        return jax.device_put(c) + 1, None

    def step_dbg(c, _):
        jax.debug.print("tick {}", c[0])
        return c + 1, None

    for step, prim in ((step_put, "device_put"),
                       (step_dbg, "debug_callback")):
        f = jax.jit(lambda x, _s=step: jax.lax.scan(_s, x, None,
                                                    length=3))
        jx = jax.make_jaxpr(f)(jnp.ones(3))
        prog = jaxpr_audit.AuditedProgram(
            name=f"fixture-{prim}", provenance="test_analysis",
            jaxpr=jx, rules=("no-transfer-in-scan",))
        findings = jaxpr_audit.audit_program(prog)
        assert findings and findings[0].rule == "no-transfer-in-scan"
        assert prim in findings[0].detail


def test_dropped_donation_is_caught():
    """A program registered as donating whose jit does NOT donate:
    neither the MLIR marker nor a compiled alias exists — the rule
    must flag it (and pass the genuinely-donating twin)."""
    f_no = jax.jit(lambda x: x * 2.0)
    f_do = jax.jit(lambda x: x * 2.0, donate_argnums=(0,))
    x = jnp.ones((8,))
    bad = jaxpr_audit.AuditedProgram(
        name="fixture-no-donate", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(f_no)(x), lowered=f_no.lower(x),
        rules=("donation-taken",))
    findings = jaxpr_audit.audit_program(bad)
    assert findings and findings[0].rule == "donation-taken"
    good = jaxpr_audit.AuditedProgram(
        name="fixture-donate", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(f_do)(x), lowered=f_do.lower(x),
        rules=("donation-taken",))
    assert jaxpr_audit.audit_program(good) == []


def test_walker_reaches_nested_and_pallas_jaxprs():
    """The recursive eqn walk must see through pjit/scan/cond nesting
    — the grid-kernel registry entry additionally proves pallas_call
    kernel jaxprs are walked (its conds live INSIDE the kernel)."""
    @jax.jit
    def f(x):
        def step(c, _):
            c = jax.lax.cond(c[0] > 0, lambda v: v + 1,
                             lambda v: v - 1, c)
            return c, None
        return jax.lax.scan(step, x, None, length=2)

    jx = jax.make_jaxpr(f)(jnp.ones(3))
    counts = jaxpr_audit.prim_counts(jx)
    assert counts.get("cond", 0) >= 1
    hits = jaxpr_audit.find_prims(jx, {"cond"})
    assert any("scan" in p for p, _ in hits), hits


# ---- sharding-flow pass (PR 14) --------------------------------------
from gossip_protocol_tpu.analysis import sharding_flow
from gossip_protocol_tpu.analysis.sharding_flow import ShardingContract

#: every registry entry by name, with the device floor that gates it
#: (mesh entries SKIP — never silently pass — below their floor,
#: the same discipline as the CLI roster)
_REGISTRY_ROSTER = {
    "solo-dense-trace": 1, "solo-overlay": 1, "fleet-dense-bench": 1,
    "fleet-overlay": 1, "fleet-overlay-leg": 1, "grid-kernel": 1,
    "mesh-dense-bench-d2": 2, "mesh-overlay-d2": 2,
    "mesh2d-lanes-peers": 8,
}


@pytest.fixture(scope="module")
def registered_programs():
    progs = jaxpr_audit.audit.last_programs \
        or jaxpr_audit.build_programs()
    return {p.name: p for p in progs}


@pytest.mark.parametrize("name", sorted(_REGISTRY_ROSTER))
def test_sharding_flow_clean_per_program(registered_programs, name):
    """Acceptance: the sharding-flow pass reports ZERO findings on
    every registered program of the clean tree — including the 2-D
    lanes×peers prototype, whose peer collectives must pass under
    the axis-aware rules that replaced the blanket collective ban."""
    need = _REGISTRY_ROSTER[name]
    if jax.device_count() < need:
        pytest.skip(f"needs {need} (virtual) devices")
    prog = registered_programs[name]
    assert prog.jaxpr is not None
    if name.startswith("mesh"):
        # every mesh entry carries a contract — a mesh program
        # outside the sharding gate would be an unguarded program
        assert prog.contract is not None
        assert prog.contract.expected_in_names
    findings = sharding_flow.check_program(prog)
    assert findings == [], "\n".join(str(f) for f in findings)


@needs_devices(8)
def test_mesh2d_contract_shape(registered_programs):
    """The flagship entry: 2-D axes, zero-collective lanes, a
    declared peer budget, and the replicated plane derived as exactly
    the unbatched leaves (clock + shared drop plane)."""
    c = registered_programs["mesh2d-lanes-peers"].contract
    assert c.mesh_axes == ("lanes", "peers")
    assert c.zero_collective_axes == ("lanes",)
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        LANE_PEER_TICK_COLLECTIVE_BUDGET
    assert c.budgets == {"peers": LANE_PEER_TICK_COLLECTIVE_BUDGET}
    assert "state.tick" in c.replicated_plane
    assert "sched.drop_active" in c.replicated_plane


def _mesh1d(axis):
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:2]), (axis,))


@needs_devices(2)
def test_lane_axis_collective_is_caught():
    """Fixture: a collective smuggled onto the lanes axis fires
    lanes-axis-zero-collectives with the eqn path."""
    from jax.sharding import PartitionSpec as P

    from gossip_protocol_tpu.compat.jaxapi import shard_map

    def body(x):
        return jax.lax.psum(x, "lanes")

    f = jax.jit(shard_map(body, mesh=_mesh1d("lanes"),
                          in_specs=(P("lanes"),), out_specs=P()))
    prog = jaxpr_audit.AuditedProgram(
        name="fixture-lane-psum", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(f)(jnp.ones((2, 4))), rules=(),
        contract=ShardingContract(
            mesh_axes=("lanes",), zero_collective_axes=("lanes",),
            expected_in_names=(("x", {0: ("lanes",)}),)))
    findings = sharding_flow.check_program(prog)
    assert any(f.rule == "lanes-axis-zero-collectives"
               for f in findings), findings
    hit = [f for f in findings
           if f.rule == "lanes-axis-zero-collectives"][0]
    assert "psum" in hit.detail and "shard_map" in hit.path


@needs_devices(2)
def test_over_budget_peer_exchange_is_caught():
    """Fixture: 3 static ppermutes inside the scanned body bust a
    per-tick budget of 2 and pass a budget of 3 — the rule counts
    STATIC eqns in the scan body, not dynamic trips."""
    from jax.sharding import PartitionSpec as P

    from gossip_protocol_tpu.compat.jaxapi import shard_map
    perm = [(0, 1), (1, 0)]

    def body(x):
        def step(c, _):
            for _ in range(3):
                c = jax.lax.ppermute(c, "peers", perm)
            return c, None
        y, _ = jax.lax.scan(step, x, None, length=4)
        return y

    f = jax.jit(shard_map(body, mesh=_mesh1d("peers"),
                          in_specs=(P("peers"),),
                          out_specs=P("peers")))
    jx = jax.make_jaxpr(f)(jnp.ones((2, 4)))

    def prog(budget):
        return jaxpr_audit.AuditedProgram(
            name="fixture-peer-budget", provenance="test_analysis",
            jaxpr=jx, rules=(),
            contract=ShardingContract(
                mesh_axes=("peers",), zero_collective_axes=(),
                budgets={"peers": budget},
                expected_in_names=(("x", {0: ("peers",)}),)))

    busted = sharding_flow.check_program(prog(2))
    assert any(f.rule == "peers-axis-collective-budget"
               for f in busted), busted
    assert "3" in busted[0].detail and "budget of 2" in busted[0].detail
    assert sharding_flow.check_program(prog(3)) == []


@needs_devices(2)
def test_undeclared_axis_collective_is_caught():
    """Fixture: a collective over an axis with NO declared budget
    fires unconditionally (outside the scan too)."""
    from jax.sharding import PartitionSpec as P

    from gossip_protocol_tpu.compat.jaxapi import shard_map

    def body(x):
        return jax.lax.psum(x, "peers")

    f = jax.jit(shard_map(body, mesh=_mesh1d("peers"),
                          in_specs=(P("peers"),), out_specs=P()))
    prog = jaxpr_audit.AuditedProgram(
        name="fixture-undeclared-axis", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(f)(jnp.ones((2, 4))), rules=(),
        contract=ShardingContract(
            mesh_axes=("peers",), zero_collective_axes=(),
            budgets={},
            expected_in_names=(("x", {0: ("peers",)}),)))
    findings = sharding_flow.check_program(prog)
    assert any(f.rule == "peers-axis-collective-budget"
               and "no declared per-tick budget" in f.detail
               for f in findings), findings


@needs_devices(2)
def test_batched_drop_plane_is_caught_by_sharding_flow():
    """Fixture: the batched-drop-plane bug, mesh edition — a plane
    leaf entering the shard_map SHARDED fires both the boundary check
    (replicated-plane + spec-derivation, with the leaf path) and the
    dataflow check (the cond predicate becomes device-varying)."""
    from jax.sharding import PartitionSpec as P

    from gossip_protocol_tpu.compat.jaxapi import shard_map

    def body(flag, x):
        return jax.lax.cond(flag[0] > 0, lambda v: v + 1.0,
                            lambda v: v - 1.0, x)

    contract = ShardingContract(
        mesh_axes=("lanes",), zero_collective_axes=("lanes",),
        replicated_plane=("sched.drop_active",),
        expected_in_names=(("sched.drop_active", {}),
                           ("state.x", {0: ("lanes",)})))

    bad = jax.jit(shard_map(body, mesh=_mesh1d("lanes"),
                            in_specs=(P("lanes"), P("lanes")),
                            out_specs=P("lanes")))
    prog = jaxpr_audit.AuditedProgram(
        name="fixture-batched-plane", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(bad)(jnp.ones((2,), jnp.int32),
                                  jnp.ones((2, 4))),
        rules=(), contract=contract)
    findings = sharding_flow.check_program(prog)
    rules_hit = {f.rule for f in findings}
    assert "replicated-plane-stays-replicated" in rules_hit, findings
    assert "spec-derivation-consistent" in rules_hit, findings
    # the spec mismatch names the offending leaf path
    assert any("sched.drop_active" in f.detail for f in findings
               if f.rule == "spec-derivation-consistent")
    # the dataflow side: the cond predicate went device-varying
    assert any("predicate" in f.detail for f in findings
               if f.rule == "replicated-plane-stays-replicated")

    # the replicated build of the SAME program is clean
    good = jax.jit(shard_map(body, mesh=_mesh1d("lanes"),
                             in_specs=(P(), P("lanes")),
                             out_specs=P("lanes")))
    gprog = jaxpr_audit.AuditedProgram(
        name="fixture-shared-plane", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(good)(jnp.ones((2,), jnp.int32),
                                   jnp.ones((2, 4))),
        rules=(), contract=contract)
    assert sharding_flow.check_program(gprog) == []


def test_spec_derivation_helpers_mirror_the_builders():
    """axes_tree_dims derives the SAME dims the builders' spec
    composition produces — the independent derivation the rule
    cross-checks; and the replicated plane falls out as exactly the
    unbatched leaves."""
    from gossip_protocol_tpu.core.fleet import (SCHED_AXES_SHARED_DROP,
                                                WORLD_AXES)
    from gossip_protocol_tpu.parallel.sharded import peer_spec_trees
    peer_state, peer_sched = peer_spec_trees()
    dims = (sharding_flow.axes_tree_dims(
                "state", WORLD_AXES, peer_specs=peer_state)
            + sharding_flow.axes_tree_dims(
                "sched", SCHED_AXES_SHARED_DROP,
                peer_specs=peer_sched))
    by_name = dict(dims)
    # lane-batched + peer-row-sharded table: both axes, shifted
    assert by_name["state.known"] == {0: ("lanes",), 1: ("peers",)}
    # lane-batched, peer-replicated vector: lanes only
    assert by_name["state.in_group"] == {0: ("lanes",)}
    # the clock and the shared drop plane: no axis at all
    assert by_name["state.tick"] == {}
    assert by_name["sched.drop_active"] == {}


# ---- donation-taken on the sharded path (PR-14 satellite) ------------
@needs_devices(2)
def test_donation_checked_on_sharded_path():
    """The hardened donation rule reads the compiled executable's
    input_output_alias as PRIMARY evidence — which is the only record
    the shard_map path has (no MLIR marker).  Donating sharded
    program passes; non-donating twin fires."""
    from jax.sharding import PartitionSpec as P

    from gossip_protocol_tpu.compat.jaxapi import shard_map

    def body(x):
        return x * 2.0

    shm = shard_map(body, mesh=_mesh1d("lanes"),
                    in_specs=(P("lanes"),), out_specs=P("lanes"))
    x = jnp.ones((2, 4))
    f_do = jax.jit(shm, donate_argnums=(0,))
    f_no = jax.jit(shm)
    good = jaxpr_audit.AuditedProgram(
        name="fixture-sharded-donate", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(f_do)(x), lowered=f_do.lower(x),
        rules=("donation-taken",))
    assert jaxpr_audit.audit_program(good) == []
    bad = jaxpr_audit.AuditedProgram(
        name="fixture-sharded-no-donate", provenance="test_analysis",
        jaxpr=jax.make_jaxpr(f_no)(x), lowered=f_no.lower(x),
        rules=("donation-taken",))
    findings = jaxpr_audit.audit_program(bad)
    assert findings and findings[0].rule == "donation-taken"


# ---- AST rule fixtures -----------------------------------------------
def test_unseeded_rng_and_wall_clock_are_caught():
    src = """
import time
import time as clk
from time import perf_counter
import numpy as np
from numpy.random import default_rng

def bad_draw(seed, idx):
    rng = np.random.default_rng()           # unseeded
    r2 = np.random.default_rng(seed)        # non-tuple key
    r3 = default_rng()                      # bare import, unseeded
    u = np.random.random()                  # mutable global RNG
    t = time.perf_counter()                 # wall clock call
    t2 = perf_counter()                     # from-import escape
    t3 = clk.monotonic()                    # module-alias escape
    return rng, r2, r3, u, t, t2, t3

def good_draw(seed, idx, now=time.perf_counter):
    rng = np.random.default_rng((seed, idx))
    return rng.random()
"""
    findings = purity_lint.lint_source(
        src, rule="no-wall-clock-in-pure-paths")
    assert len(findings) == 7, [str(f) for f in findings]
    # the injectable-clock DEFAULT and the tuple-keyed draw are clean
    assert not any("good_draw" in f.path for f in findings)


def test_wall_clock_in_ring_harvest_order_is_caught():
    """The PR 17 ring coverage: the no-wall-clock rule SCOPED to the
    harvest-ordering functions (purity_lint.RING_ORDER_FUNCS form)
    fires on a ``time.*`` call or an RNG tiebreak inside them — the
    exact bug class that would make in-flight resolution order (and
    hence every chaos/elastic digest) depend on host timing — while
    wall clock elsewhere in the same module stays out of scope."""
    src = """
import time
import numpy as np

class Svc:
    def _harvest_ready(self):
        # ordering by arrival wall time: the violation
        heads = sorted(self._rings, key=lambda k: time.monotonic())
        return heads

    def _pop_oldest_inflight(self):
        if np.random.random() < 0.5:        # RNG tiebreak: violation
            return None
        for rkey in list(self._rings):
            return self._rings[rkey].popleft()

    def _deadline_slack(self, req):
        # wall clock OUTSIDE the harvest path: legitimately allowed
        return req.deadline - time.monotonic()
"""
    scoped = ("_harvest_ready", "_pop_oldest_inflight")
    findings = purity_lint.lint_source(
        src, rule="no-wall-clock-in-pure-paths", pure_funcs=scoped)
    assert len(findings) == 2, [str(f) for f in findings]
    assert all(f.rule == "no-wall-clock-in-pure-paths"
               for f in findings)
    assert {f.where.split(":")[-1] for f in findings} == {"8", "12"}
    # the deadline helper's time.monotonic() is NOT flagged: scoping
    # is what lets the rule cover scheduler.py at all
    assert not any("_deadline_slack" in (f.path or "")
                   for f in findings)
    # and the shipped scheduler's ring functions are covered + clean
    rel = "gossip_protocol_tpu/service/scheduler.py"
    assert rel in purity_lint.RING_ORDER_FUNCS
    assert purity_lint.raw_findings(
        "no-wall-clock-in-pure-paths", rel) == []


def test_jnp_in_staging_function_is_caught():
    src = """
import jax
import jax.numpy as jnp
import numpy as np

def stage_lanes_host(trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)

def stage_lanes_host_np(trees):
    return jax.tree.map(lambda *xs: np.stack(xs), *trees)
"""
    findings = purity_lint.lint_source(
        src, rule="host-staging-is-numpy",
        staging_funcs=("stage_lanes_host", "stage_lanes_host_np"))
    assert len(findings) == 1
    assert findings[0].rule == "host-staging-is-numpy"
    assert findings[0].path == "stage_lanes_host"


def test_inplace_write_on_host_view_is_caught():
    src = """
import numpy as np

def poison_direct(lane):
    lane.metrics.sent[:] = -1               # the PR-5 bug, verbatim

def poison_via_alias(lane):
    sent = np.asarray(lane.metrics.sent)
    sent[...] = -1                          # aliased view write

def poison_via_method(lane):
    m = lane.metrics.sent.reshape(2, -1)    # method-form alias
    m[:] = 0
    m2 = np.asarray(lane.metrics.recv)
    m3 = m2.view()                          # alias-of-alias
    m3[...] = 1

def fine(lane, key, y):
    out = np.zeros(8)
    out[:4] = 1                             # fresh local: fine
    lane.chunks[-1] = y                     # list slot swap: fine
    table = {}
    table[key] = y                          # dict write: fine
    safe = np.array(lane.metrics.sent)      # np.array COPIES
    safe[:] = 0
    v = out.reshape(2, 4)                   # safe-local reshape: fine
    v[:] = 1
"""
    findings = purity_lint.lint_source(
        src, rule="no-inplace-on-host-views")
    assert len(findings) == 4, [str(f) for f in findings]
    assert {f.where.split(":")[-1] for f in findings} == \
        {"5", "9", "13", "16"}


def test_mutation_before_journal_is_caught():
    """journal-before-mutation: a terminal setter (``._complete`` /
    ``._fail``) with no ``journal.outcome(...)`` append textually
    above it in the same function is the crash window the recovery
    replay cannot close — the rule fires with the function's name."""
    src = """
class Scheduler:
    def finish_ok(self, req, out):
        self.journal.outcome(req.request_id, "completed")
        req._complete(out)

    def finish_bad(self, req, err):
        req._fail(err)
        self.journal.outcome(req.request_id, "failed", detail=err)

    def finish_nested_is_skipped(self, req):
        def later():
            req._complete(None)
        return later
"""
    findings = purity_lint.lint_source(
        src, rule="journal-before-mutation")
    assert len(findings) == 1, [str(f) for f in findings]
    assert findings[0].rule == "journal-before-mutation"
    assert findings[0].path == "finish_bad"
    assert "_fail" in findings[0].detail


def test_journal_order_clean_on_tree():
    """The shipped scheduler/recovery modules journal before every
    terminal setter — the rule's clean-tree half."""
    findings = [f for f in purity_lint.lint()
                if f.rule == "journal-before-mutation"]
    assert findings == [], "\n".join(str(f) for f in findings)


# ---- cache-key completeness ------------------------------------------
def test_cache_key_scan_sees_builder_reads():
    """The AST scan actually collects the known builder reads —
    including the ``cfg.n`` property alias of max_nnb — and the
    covered set contains them (the clean-tree assertion is
    test_clean_tree_passes_ast_rules)."""
    builders = cache_keys.builder_fields()
    for fld in ("max_nnb", "total_ticks", "t_remove", "model",
                "zombie", "flap_rate"):
        assert fld in builders, f"builder scan lost {fld}"
    covered = cache_keys.covered_fields()
    assert set(builders) <= covered
    assert cache_keys.overlay_bakes_whole_config()


def test_unkeyed_builder_field_fails_with_its_name():
    """Satellite: the diff FAILS naming the missing field.  A fixture
    builder reads a real field; with that field stripped from the
    covered set the check reports it (builder locations included)."""
    fixture = cache_keys.fields_read_source("""
def make_fixture_run(cfg):
    horizon = cfg.total_ticks
    window = cfg.drop_open_tick
    return horizon + window
""", funcs=("make_fixture_run",))
    assert set(fixture) == {"total_ticks", "drop_open_tick"}
    missing = cache_keys.missing_fields(
        builders=fixture,
        covered=cache_keys.covered_fields() - {"drop_open_tick"})
    assert set(missing) == {"drop_open_tick"}
    assert missing["drop_open_tick"] == fixture["drop_open_tick"]


def test_round2_world_fields_are_covered_by_name():
    """The round-2 planes' knobs (byz_rate / byz_boost / link_latency)
    are key-folded (worlds_key appends them only when active —
    config.py), so a composed-world config can never be served a
    cached honest/delay-free program.  byz_rate and link_latency are
    also read directly by builders — the pin: strip one from the
    covered set and the diff must fail naming it.  byz_boost reaches
    the tick only THROUGH the Schedule arrays (sched.byz_boost), so
    it legitimately has no builder read — its coverage is the
    key+data side alone.  A silent pass here would mean the scanner
    stopped seeing the reads and the gate went blind to the planes."""
    builders = cache_keys.builder_fields()
    covered = cache_keys.covered_fields()
    for fld in ("byz_rate", "byz_boost", "link_latency"):
        assert fld in covered, f"{fld} not key-folded"
    for fld in ("byz_rate", "link_latency"):
        assert fld in builders, f"builder scan lost {fld}"
        missing = cache_keys.missing_fields(
            builders=builders, covered=covered - {fld})
        assert fld in missing, f"diff went blind to {fld}"
        assert missing[fld], f"no builder locations reported for {fld}"
    assert "byz_boost" not in builders, \
        "byz_boost grew a direct builder read: add it to the diff pin"


def test_canonical_key_fields_are_covered_by_name():
    """PR 16 satellite: the canonical-key completeness diff covers the
    three new key ingredients by name — the pad-ladder rung over ``n``
    (max_nnb), the quantized phase windows, and the operand-vs-static
    world split.  The split's pin is structural: fields that moved to
    runtime operands (msg_drop_prob, byz_boost) must have NO direct
    canonical-builder read at all — a read appearing there means a
    world knob got re-baked into the shared program and the
    equivalence class just went stale-capable."""
    builders = cache_keys.canonical_builder_fields()
    covered = cache_keys.canonical_covered_fields()
    # ladder rung + quantized windows are key-folded
    for fld in ("max_nnb", "drop_open_tick", "partition_open_tick",
                "total_ticks"):
        assert fld in covered, f"{fld} fell out of the canonical key"
    # static shape discriminators still read by the shared builders
    for fld in ("max_nnb", "t_remove", "partition_groups"):
        assert fld in builders, f"builder scan lost {fld}"
        missing = cache_keys.canonical_missing_fields(
            builders=builders, covered=covered - {fld})
        assert fld in missing, f"canonical diff went blind to {fld}"
        assert missing[fld], f"no builder locations for {fld}"
    # operand side of the split: these ride as traced operands /
    # schedule data, never as canonical-builder bakes
    for fld in ("msg_drop_prob", "byz_boost"):
        assert fld not in builders, (
            f"{fld} grew a direct canonical-builder read — a runtime "
            "world operand got re-baked into the shared program")


def test_unkeyed_canonical_field_fails_naming_builder_line():
    """Satellite pin: a canonical-path builder read with no canonical
    key coverage FAILS, and the finding names the builder line."""
    fixture = cache_keys.fields_read_source("""
def make_tick(cfg):
    return cfg.wave_size + cfg.flap_rate
""", funcs=("make_tick",), relfile="fixture_tick.py")
    missing = cache_keys.canonical_missing_fields(
        builders=fixture,
        covered=cache_keys.canonical_covered_fields() - {"wave_size"})
    assert set(missing) == {"wave_size"}
    assert missing["wave_size"] == ["fixture_tick.py:3"]


def test_clean_tree_passes_canonical_key_rule():
    """The real tree has no canonical coverage gap, and check() would
    report any under the ``canon-key-complete`` rule name."""
    assert cache_keys.canonical_missing_fields() == {}
    assert [f for f in cache_keys.check()
            if f.rule == "canon-key-complete"] == []


# ---- runtime guards --------------------------------------------------
def test_compile_counter_counts_and_budget_trips():
    f = jax.jit(lambda x: x * 5 + 2)
    f(jnp.ones(11))                          # warm
    with guards.count_compiles() as c:
        f(jnp.ones(11))
    assert c.count == 0
    with guards.count_compiles() as c:
        f(jnp.ones(13))                      # fresh shape
    assert c.count >= 1
    with pytest.raises(guards.RecompileBudget, match="budget"):
        with guards.compile_budget(0):
            f(jnp.ones(17))


def test_steady_state_compile_gate_clean_and_injected():
    """The bench.py --check gate: a warmed bench lap stays at zero
    compiles; an injected recompile trips it (acceptance: bench.py
    --check fails on an injected steady-state recompile and passes
    clean — bench exposes the injection as --inject-recompile)."""
    clean = guards.steady_state_compile_gate()
    assert clean["ok"], clean
    assert clean["compiles"] == 0
    tripped = guards.steady_state_compile_gate(inject_recompile=True)
    assert not tripped["ok"]
    assert tripped["compiles"] >= 1


def test_fleet_resolve_is_free_of_implicit_transfers():
    """A small replay's device-resident segment under
    ``transfer_guard("disallow")``: the launched fleet's wait +
    resolve must perform only EXPLICIT transfers (device_get) — an
    eager jnp op on host data or a numpy arg sliding into a jitted
    helper would raise here (PERF §11 serializer class)."""
    from gossip_protocol_tpu.core.fleet import FleetSimulation
    cfg = _overlay_fixture_cfg()
    fleet = FleetSimulation(cfg)
    pending = fleet.launch(seeds=[1, 2], warmup=True)
    with guards.no_implicit_transfers():
        pending.wait()
        result = pending.resolve()
    assert len(result.lanes) == 2
    # the guard itself must BITE on this backend: an implicit
    # numpy->jit transfer raises under the same guard
    g = jax.jit(lambda x: x + 1)
    g(jnp.ones(3))
    with pytest.raises(Exception, match="[Dd]isallow"):
        with guards.no_implicit_transfers():
            g(np.ones(3))


def test_guard_self_check_is_clean():
    assert guards.self_check() == []


# ---- the whole front door --------------------------------------------
def test_run_all_static_passes_clean():
    findings = run_all(passes=("ast",))
    assert findings == [], "\n".join(str(f) for f in findings)


# ---- the CLI front door (PR-14 satellite) ----------------------------
def test_cli_preserves_flags_and_json_through_reexec():
    """The module CLI re-execs itself to force virtual devices; the
    full flag set (--pass/--rule/--json) must ride through the execv
    — a re-exec that dropped argv would run the DEFAULT passes and
    print the human report, so the assertions below pin both."""
    import json
    import os as _os
    import subprocess
    import sys as _sys
    env = {k: v for k, v in _os.environ.items()
           if k not in ("XLA_FLAGS", "_GOSSIP_ANALYSIS_REEXEC")}
    env["JAX_PLATFORMS"] = "cpu"
    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    proc = subprocess.run(
        [_sys.executable, "-m", "gossip_protocol_tpu.analysis",
         "--pass", "ast", "--rule", "journal-before-mutation",
         "--json"],
        capture_output=True, text=True, env=env, cwd=repo, timeout=300)
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["ok"] is True
    assert payload["passes"] == ["ast"]
    assert payload["rules"] == ["journal-before-mutation"]
    assert payload["programs"] == []
    assert payload["count"] == 0


def test_reexec_failure_exits_nonzero(monkeypatch):
    """An execv that fails must exit 2, not fall through to an
    in-process run with the mesh entries silently skipped (which
    would read as a pass to the caller)."""
    import gossip_protocol_tpu.analysis.__main__ as cli
    monkeypatch.setenv("_GOSSIP_ANALYSIS_REEXEC", "0")
    monkeypatch.setenv("XLA_FLAGS", "")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    def refuse(*_a):
        raise OSError("exec refused")

    monkeypatch.setattr(cli.os, "execv", refuse)
    with pytest.raises(SystemExit) as e:
        cli._force_virtual_devices()
    assert e.value.code == 2


# ---- bench --check trajectory row (PR 16 satellite) -----------------

def test_bench_check_row_is_always_written(tmp_path, monkeypatch):
    """bench --check must leave a BENCH_pr*.json row for EVERY gate
    run (PR 14 and 15 gated without recording — a two-PR hole in the
    trajectory), and a write failure must propagate, not pass."""
    import bench
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    monkeypatch.setattr(bench.sys, "argv", ["bench.py", "--check"])
    (tmp_path / "CHANGES.md").write_text(
        "- PR 7 (perf_opt): something\n- PR 9 (robustness): more\n")
    assert bench._pr_number() == 10
    path = bench.write_bench_row({"metric": "m", "value": 1.0})
    assert os.path.basename(path) == "BENCH_pr10.json"
    with open(path) as f:
        assert json.load(f) == {"metric": "m", "value": 1.0}
    assert not os.path.exists(path + ".tmp")

    # --pr override wins over CHANGES.md
    monkeypatch.setattr(bench.sys, "argv", ["bench.py", "--check",
                                            "--pr", "99"])
    assert bench._pr_number() == 99

    # no CHANGES.md: fall back to the highest existing BENCH_pr*.json
    monkeypatch.setattr(bench.sys, "argv", ["bench.py", "--check"])
    (tmp_path / "CHANGES.md").unlink()
    assert bench._pr_number() == 11  # BENCH_pr10.json from above + 1

    # an unwritable row is a HARD failure — never a silent pass
    def boom(*a, **kw):
        raise OSError("disk full")
    monkeypatch.setattr(bench.os, "replace", boom)
    with pytest.raises(OSError, match="disk full"):
        bench.write_bench_row({"metric": "m", "value": 2.0})
