"""Randomized differential sweep over the dense path-selection matrix.

make_run can route a dense config four ways — per-tick XLA, per-tick
fused (Pallas), whole-run megakernel, active-corner (which itself may
ride the megakernel) — and the choice depends on n, total_ticks,
with_events, use_pallas, backend, and the schedule.  The scenario
tests pin specific configs; this sweep draws random small configs and
asserts the paths that are defined to share a drop stream stay
bitwise identical, so a routing or envelope change that silently
shifts one path's semantics trips here rather than in a bench run.

Streams: the interpret-mode fused/mega paths and the per-tick XLA
path all draw at full width; the corner path draws at width A and is
compared against the full path pinned to the same width
(``make_tick(n_active=A)``) — the equivalence dense_corner.py
documents.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.dense_corner import (active_bound,
                                                   make_corner_run)
from gossip_protocol_tpu.core.dense_mega import (dense_mega_supported,
                                                 make_dense_mega_run)
from gossip_protocol_tpu.core.tick import make_tick
from gossip_protocol_tpu.state import init_state, make_schedule

STATE_FIELDS = ("tick", "in_group", "own_hb", "known", "hb", "ts",
                "gossip", "joinreq", "joinrep")


def _random_cfg(rng: np.random.Generator) -> SimConfig:
    n = int(rng.choice([16, 24, 32, 48, 64]))
    total = int(rng.integers(20, 90))
    drop = bool(rng.integers(0, 2))
    churn = bool(rng.integers(0, 3) == 0)
    kw = dict(max_nnb=n, total_ticks=total,
              single_failure=bool(rng.integers(0, 2)),
              fail_tick=int(rng.integers(5, max(6, total - 5))),
              seed=int(rng.integers(0, 1 << 16)))
    if drop:
        lo = int(rng.integers(0, total // 2))
        kw.update(drop_msg=True,
                  msg_drop_prob=float(rng.uniform(0.05, 0.4)),
                  drop_open_tick=lo,
                  drop_close_tick=int(rng.integers(lo + 5, total + 50)))
    else:
        kw["drop_msg"] = False
    if churn:
        kw["rejoin_after"] = int(rng.integers(5, 40))
    return SimConfig(**kw)


def _scan_run(tick, total):
    @jax.jit
    def run(state, sched):
        def step(c, _):
            c, ev = tick(c, sched)
            return c, (ev.sent, ev.recv)
        return jax.lax.scan(step, state, None, length=total)
    return run


def _assert_states(fa, fb, tag, cfg):
    for name in STATE_FIELDS:
        x, y = np.asarray(getattr(fa, name)), np.asarray(getattr(fb, name))
        assert np.array_equal(x, y), \
            f"{tag}: field {name} diverged for {cfg}"


@pytest.mark.parametrize("trial", range(12))
def test_random_config_paths_agree(trial):
    rng = np.random.default_rng(1000 + trial)
    cfg = _random_cfg(rng)
    sched, state = make_schedule(cfg), init_state(cfg)
    total = cfg.total_ticks

    # reference trajectory: per-tick composable XLA
    run_x = _scan_run(make_tick(cfg, use_pallas=False, with_events=False),
                      total)
    fx, (sx, rx) = run_x(state, sched)

    # per-tick fused (interpret-mode Pallas kernels)
    run_f = _scan_run(make_tick(cfg, use_pallas=True, with_events=False),
                      total)
    ff, (sf, rf) = run_f(state, sched)
    _assert_states(fx, ff, "fused", cfg)
    np.testing.assert_array_equal(np.asarray(sx), np.asarray(sf))

    # whole-run megakernel (same full-width stream)
    if dense_mega_supported(cfg):
        fm, em = make_dense_mega_run(cfg)(state, sched)
        _assert_states(fx, fm, "mega", cfg)
        np.testing.assert_array_equal(np.asarray(sx), np.asarray(em.sent))
        np.testing.assert_array_equal(np.asarray(rx), np.asarray(em.recv))

    # corner (width-A stream) vs full path pinned to the same stream
    a = active_bound(cfg)
    if 0 < a < cfg.n:
        run_a = _scan_run(
            make_tick(cfg, use_pallas=False, with_events=False, n_active=a),
            total)
        fa, (sa, ra) = run_a(state, sched)
        fc, ec = make_corner_run(cfg, a, use_pallas=False)(state, sched)
        _assert_states(fa, fc, "corner", cfg)
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(ec.sent))
        np.testing.assert_array_equal(np.asarray(ra), np.asarray(ec.recv))


@pytest.mark.parametrize("drop", [False, True])
def test_corner_riding_megakernel_interpret(drop):
    """Corner+mega differential: the path the N=4096 bench actually
    takes on TPU (make_corner_run routing launches through the dense
    megakernel), forced in interpret mode at small N so a kernel
    change that breaks corner+mega parity trips in CI rather than
    only on hardware (ADVICE round 5, item 4).  Both sides draw the
    width-A drop stream (tick_drop_masks at the corner width)."""
    kw = dict(max_nnb=256, total_ticks=30, single_failure=True,
              fail_tick=15, seed=21, drop_msg=False)
    if drop:
        kw.update(drop_msg=True, msg_drop_prob=0.25, drop_open_tick=4,
                  drop_close_tick=26)
    cfg = SimConfig(**kw)
    a = active_bound(cfg)
    assert 0 < a < cfg.n and dense_mega_supported(cfg.replace(max_nnb=a))
    sched, state = make_schedule(cfg), init_state(cfg)
    run_a = _scan_run(
        make_tick(cfg, use_pallas=False, with_events=False, n_active=a),
        cfg.total_ticks)
    fa, (sa, ra) = run_a(state, sched)
    fc, ec = make_corner_run(cfg, a, use_pallas=True,
                             force_mega=True)(state, sched)
    _assert_states(fa, fc, "corner+mega", cfg)
    np.testing.assert_array_equal(np.asarray(sa), np.asarray(ec.sent))
    np.testing.assert_array_equal(np.asarray(ra), np.asarray(ec.recv))


def test_corner_run_rejects_nonzero_start_tick():
    """active_bound spans the whole-run horizon, so the corner run
    refuses a resumed (tick != 0) state (ADVICE round 5, item 1)."""
    cfg = SimConfig(max_nnb=256, total_ticks=30, single_failure=True,
                    fail_tick=15, seed=3, drop_msg=False)
    a = active_bound(cfg)
    sched, state = make_schedule(cfg), init_state(cfg)
    run = make_corner_run(cfg, a, use_pallas=False)
    mid, _ = run(state, sched)      # tick-0 start: fine
    with pytest.raises(ValueError, match="tick-0"):
        run(mid, sched)


def test_active_bound_negative_step_rate_falls_back_full_width():
    """A pathological negative step_rate breaks the bisection's
    monotonicity precondition; the bound must fall back to N instead
    of miscomputing a corner (ADVICE round 5, item 2)."""
    cfg = SimConfig(max_nnb=256, total_ticks=30, single_failure=True,
                    fail_tick=15, seed=3, drop_msg=False)
    assert 0 < active_bound(cfg) < cfg.n
    assert active_bound(cfg.replace(step_rate=-0.25)) == cfg.n
