"""Differential tests: active-corner dense run vs the full-width path.

The corner reduction (core/dense_corner.py) must replay the full
(N, N) path's exact trajectory whenever both consume the same drop
stream — and the invariant it rests on (no state ever appears outside
the active prefix) must hold on the full-width path with its native
stream too.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.dense_corner import (active_bound,
                                                   make_corner_run)
from gossip_protocol_tpu.core.tick import make_run, make_tick
from gossip_protocol_tpu.state import init_state, make_schedule

STATE_FIELDS = ("tick", "in_group", "own_hb", "known", "hb", "ts",
                "gossip", "joinreq", "joinrep")


def _cfg(drop: bool, n=256, total=30, **kw):
    kw.setdefault("fail_tick", 20)
    kw.setdefault("single_failure", False)
    kw.setdefault("seed", 11)
    if drop:
        kw.update(drop_msg=True, msg_drop_prob=0.25,
                  drop_open_tick=5, drop_close_tick=25)
    else:
        kw.setdefault("drop_msg", False)
    return SimConfig(max_nnb=n, total_ticks=total, **kw)


def _full_run(cfg, n_active=None):
    tick = make_tick(cfg, use_pallas=False, with_events=False,
                     n_active=n_active)

    @jax.jit
    def run(state, sched):
        def step(c, _):
            c, ev = tick(c, sched)
            return c, (ev.sent, ev.recv)
        return jax.lax.scan(step, state, None, length=cfg.total_ticks)

    return run


def _assert_same(fa, ea, fb, eb):
    for name in STATE_FIELDS:
        x, y = np.asarray(getattr(fa, name)), np.asarray(getattr(fb, name))
        assert np.array_equal(x, y), f"state field {name} diverged"
    np.testing.assert_array_equal(np.asarray(ea[0]), np.asarray(eb.sent))
    np.testing.assert_array_equal(np.asarray(ea[1]), np.asarray(eb.recv))


def test_active_bound_matches_bruteforce():
    cfgs = [SimConfig(max_nnb=n, total_ticks=t)
            for n, t in [(256, 30), (256, 1000), (64, 5), (512, 127),
                         (4096, 200)]]
    cfgs += [SimConfig(max_nnb=256, total_ticks=30, rejoin_after=8,
                       fail_tick=12, single_failure=sf, seed=s)
             for sf in (True, False) for s in (0, 3, 11)]
    for cfg in cfgs:
        a = active_bound(cfg)
        sched = make_schedule(cfg)
        start = np.asarray(sched.start_tick)
        rejoin = np.asarray(sched.rejoin_tick)
        active = (start < cfg.total_ticks) | (rejoin < cfg.total_ticks)
        a_raw = int(np.flatnonzero(active).max()) + 1 if active.any() else 0
        assert a_raw <= a <= cfg.n
        if a < cfg.n:
            assert a % 128 == 0 and a - a_raw < 128


def test_corner_matches_full_without_drops():
    cfg = _cfg(drop=False)
    a = active_bound(cfg)
    assert a < cfg.n
    sched, state = make_schedule(cfg), init_state(cfg)
    fa, ea = _full_run(cfg)(state, sched)
    fb, eb = make_corner_run(cfg, a, use_pallas=False)(state, sched)
    _assert_same(fa, ea, fb, eb)


def test_corner_matches_full_same_drop_stream():
    cfg = _cfg(drop=True)
    a = active_bound(cfg)
    assert a < cfg.n
    sched, state = make_schedule(cfg), init_state(cfg)
    fa, ea = _full_run(cfg, n_active=a)(state, sched)
    fb, eb = make_corner_run(cfg, a, use_pallas=False)(state, sched)
    _assert_same(fa, ea, fb, eb)


def test_make_run_picks_corner_and_matches():
    cfg = _cfg(drop=True, total=25)
    a = active_bound(cfg)
    assert a < cfg.n
    sched, state = make_schedule(cfg), init_state(cfg)
    run = make_run(cfg, with_events=False, use_pallas=False)
    fb, eb = run(state, sched)
    fa, ea = _full_run(cfg, n_active=a)(state, sched)
    _assert_same(fa, ea, fb, eb)
    assert int(fb.tick) == cfg.total_ticks


def test_nothing_exists_outside_corner_on_full_path():
    # full-width path with its native stream: the invariant the corner
    # rests on must hold regardless of which stream is drawn
    cfg = _cfg(drop=True)
    a = active_bound(cfg)
    sched, state = make_schedule(cfg), init_state(cfg)
    fa, _ = _full_run(cfg)(state, sched)
    for name in ("known", "hb", "ts", "gossip"):
        p = np.asarray(getattr(fa, name))
        assert not p[a:, :].any(), f"{name} rows >= A nonzero"
        assert not p[:, a:].any(), f"{name} cols >= A nonzero"
    for name in ("in_group", "own_hb", "joinreq", "joinrep"):
        v = np.asarray(getattr(fa, name))
        assert not v[a:].any(), f"{name} >= A nonzero"


def test_churn_gets_no_corner():
    # victims are seed-drawn and the compiled run must stay reusable
    # across reseeds (core/sim.py caches it), so a config whose rejoin
    # can fire inside the run must report the full width
    cfg = _cfg(drop=False, rejoin_after=8, single_failure=True,
               fail_tick=12)
    assert active_bound(cfg) == cfg.n
    # ... but with the rejoin outside the run the start bound applies
    assert active_bound(cfg.replace(rejoin_after=1000)) < cfg.n


def test_corner_run_handles_churn_when_victim_covered():
    # make_corner_run itself is churn-correct whenever the caller's
    # bound covers the victim — exercised here with a bound derived
    # from the realized schedule
    cfg = sched = a = None
    for seed in range(64):
        c = _cfg(drop=False, rejoin_after=8, single_failure=True,
                 fail_tick=12, seed=seed)
        s = make_schedule(c)
        start = np.asarray(s.start_tick)
        rejoin = np.asarray(s.rejoin_tick)
        active = (start < c.total_ticks) | (rejoin < c.total_ticks)
        a_raw = int(np.flatnonzero(active).max()) + 1
        pad = min(c.n, -(-a_raw // 128) * 128)
        if pad < c.n:
            cfg, sched, a = c, s, pad
            break
    assert cfg is not None, "no seed with an in-corner victim found"
    state = init_state(cfg)
    fa, ea = _full_run(cfg)(state, sched)
    fb, eb = make_corner_run(cfg, a, use_pallas=False)(state, sched)
    _assert_same(fa, ea, fb, eb)


def test_zero_tick_bound_is_zero():
    # a == 0 must not be treated as a corner (make_run guards 0 < a);
    # the zero-length run itself goes down the pre-existing full path
    cfg = _cfg(drop=False, total=0)
    assert active_bound(cfg) == 0
