"""Config system: legacy .conf ingestion and derived semantics
(reference: Params.cpp:19-50, Application.cpp:143)."""

import pytest

from gossip_protocol_tpu.config import SimConfig
from tests.conftest import scenario_cfg


def test_parse_singlefailure():
    cfg = scenario_cfg("singlefailure")
    assert cfg.max_nnb == 10 and cfg.n == 10
    assert cfg.single_failure and not cfg.drop_msg
    assert cfg.msg_drop_prob == pytest.approx(0.1)


def test_parse_multifailure():
    cfg = scenario_cfg("multifailure")
    assert not cfg.single_failure and not cfg.drop_msg


def test_parse_msgdrop():
    cfg = scenario_cfg("msgdropsinglefailure")
    assert cfg.single_failure and cfg.drop_msg
    assert cfg.msg_drop_prob == pytest.approx(0.1)


def test_reference_constants():
    cfg = SimConfig()
    # Params.cpp:29-31, Application.h:27, MP1Node.h:21-22, EmulNet.h:12
    assert cfg.total_ticks == 700
    assert cfg.step_rate == 0.25
    assert cfg.t_remove == 20
    assert cfg.t_fail == 5
    assert cfg.max_msg_size == 4000
    assert cfg.en_buff_size == 30000
    assert cfg.portnum == 8001


def test_start_tick_truncation():
    """Node i starts at C-truncated int(0.25*i) (Application.cpp:143)."""
    cfg = SimConfig()
    assert [cfg.start_tick(i) for i in range(10)] == [0, 0, 0, 0, 1, 1, 1, 1, 2, 2]


def test_overrides():
    cfg = scenario_cfg("singlefailure", max_nnb=512, seed=7)
    assert cfg.n == 512 and cfg.seed == 7


def test_malformed_conf_rejected(tmp_path):
    """A readable conf with no MAX_NNB key must be refused, not
    silently simulated with defaults (native/params.cc agrees)."""
    import pytest

    p = tmp_path / "junk.conf"
    p.write_text("SOMETHING: 5\n")
    with pytest.raises(ValueError, match="MAX_NNB"):
        SimConfig.from_conf(str(p))
    # an explicit override supplies the peer count, so the file is fine
    assert SimConfig.from_conf(str(p), max_nnb=16).n == 16
