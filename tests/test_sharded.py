"""Multi-device sharding: the ring-merge sharded run must be
bit-equivalent to the single-device run (8 virtual CPU devices,
conftest sets --xla_force_host_platform_device_count=8)."""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.parallel.sharded import (make_mesh, make_sharded_run,
                                                  shard_state)
from gossip_protocol_tpu.state import init_state, make_schedule
from tests.conftest import scenario_cfg

needs_devices = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs 8 (virtual) devices")


@needs_devices
@pytest.mark.parametrize("scen", ["singlefailure", "msgdropsinglefailure"])
def test_sharded_equals_local(scen):
    cfg = scenario_cfg(scen, max_nnb=16, seed=0, total_ticks=200)
    sched = make_schedule(cfg)

    local = Simulation(cfg).run()

    mesh = make_mesh(8)
    run = make_sharded_run(cfg, mesh)
    state = shard_state(init_state(cfg), mesh)
    final, ev = run(state, sched)

    # identical event masks
    np.testing.assert_array_equal(np.asarray(ev.added), local.added)
    np.testing.assert_array_equal(np.asarray(ev.removed), local.removed)
    # identical accounting (drop decisions are row-keyed, so the drop
    # pattern must be bit-identical across paths)
    np.testing.assert_array_equal(np.asarray(ev.sent).T, local.sent)
    np.testing.assert_array_equal(np.asarray(ev.recv).T, local.recv)
    # identical final tables
    for f in ("known", "hb", "ts", "in_group", "own_hb", "gossip"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final, f)),
            np.asarray(getattr(local.final_state, f)), err_msg=f)


@needs_devices
def test_sharded_mxu_merge_equals_local():
    """RingComm with use_pallas=True (the TPU default resolution)
    routes the ring merge through the MXU level decomposition; it must
    trace under shard_map (input-derived while_loop carry inits — a
    constant init trips the varying-axes typing) and match the local
    run bit-for-bit."""
    cfg = scenario_cfg("msgdropsinglefailure", max_nnb=16, seed=3,
                       total_ticks=150)
    sched = make_schedule(cfg)
    local = Simulation(cfg).run()
    mesh = make_mesh(4)
    run = make_sharded_run(cfg, mesh, use_pallas=True)
    final, ev = run(shard_state(init_state(cfg), mesh), sched)
    np.testing.assert_array_equal(np.asarray(ev.added), local.added)
    np.testing.assert_array_equal(np.asarray(ev.removed), local.removed)
    for f in ("known", "hb", "ts", "in_group", "own_hb", "gossip"):
        np.testing.assert_array_equal(
            np.asarray(getattr(final, f)),
            np.asarray(getattr(local.final_state, f)), err_msg=f)


@needs_devices
def test_sharded_mesh_sizes():
    """The ring must be correct for any axis size dividing N."""
    cfg = scenario_cfg("singlefailure", max_nnb=12, seed=1, total_ticks=60)
    sched = make_schedule(cfg)
    base = None
    for p in (1, 2, 4):
        mesh = make_mesh(p)
        run = make_sharded_run(cfg, mesh)
        final, ev = run(shard_state(init_state(cfg), mesh), sched)
        added = np.asarray(ev.added)
        if base is None:
            base = added
        else:
            np.testing.assert_array_equal(added, base)
