"""Differential tests: multi-tick megakernel vs the XLA overlay path.

The megakernel (ops/pallas/overlay_mega.py + models/overlay_mega.py)
must replay the exact trajectory of the per-tick XLA formulation —
final state bit-identical, per-tick metrics identical except
``live_uncovered`` (the megakernel reports the -1 "not tracked"
sentinel).  On CPU the kernel runs in interpret mode; the same
contract holds compiled on TPU (exercised by bench.py).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_run,
                                                make_overlay_schedule)
from gossip_protocol_tpu.models.overlay_mega import (make_mega_run,
                                                     mega_supported)

STATE_FIELDS = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                "send_flags", "joinreq", "joinrep")
METRIC_FIELDS = ("in_group", "view_slots", "adds", "removals",
                 "false_removals", "victim_slots", "sent", "recv")


def _cfg(scenario, n):
    if scenario == "ramp_fail":
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=False, seed=3, total_ticks=120,
                         fail_tick=40, step_rate=0.5)
    if scenario == "drop":
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=True, msg_drop_prob=0.3, seed=5,
                         total_ticks=120, fail_tick=60, step_rate=0.25,
                         drop_open_tick=10, drop_close_tick=100)
    if scenario == "churn":
        return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                         drop_msg=False, seed=7, total_ticks=200,
                         churn_rate=0.25, rejoin_after=30,
                         step_rate=40.0 / n)
    if scenario == "powerlaw":
        # fanout capped at 5: the mega path rejects the default F=8
        # hub cap (see mega_supported), and a capped power-law still
        # exercises the out-degree gating
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=False, seed=9, total_ticks=120,
                         fail_tick=50, step_rate=0.5, topology="powerlaw",
                         fanout=5)
    raise ValueError(scenario)


def _compare(cfg, length):
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    run_x = make_overlay_run(cfg, length, use_pallas=False)
    run_m = make_mega_run(cfg, length)
    fx, mx = run_x(state, sched)
    fm, mm = run_m(state, sched)
    for name in STATE_FIELDS:
        a, b = np.asarray(getattr(fx, name)), np.asarray(getattr(fm, name))
        assert np.array_equal(a, b), f"state field {name} diverged"
    for name in METRIC_FIELDS:
        a, b = np.asarray(getattr(mx, name)), np.asarray(getattr(mm, name))
        assert np.array_equal(a, b), \
            f"metric {name} diverged at ticks {np.flatnonzero(a != b)[:5]}"
    assert np.all(np.asarray(mm.live_uncovered) == -1)
    return fm


@pytest.mark.parametrize("scenario,n", [
    ("ramp_fail", 64),
    ("drop", 128),
    ("churn", 64),
    ("powerlaw", 64),
])
def test_megakernel_bitwise_equals_xla(scenario, n):
    cfg = _cfg(scenario, n)
    # 44 = 2 full MEGA_TICKS chunks + a 12-tick remainder launch
    _compare(cfg, 44)


def test_megakernel_full_run_with_churn_cycle():
    """A whole churn run: ramp, churn fails, rejoins, steady state."""
    cfg = _cfg("churn", 64)
    final = _compare(cfg, cfg.total_ticks)
    assert int(np.asarray(final.in_group).sum()) == cfg.n


def test_megakernel_resume_bit_identical():
    """Stopping after 17 ticks and resuming matches one uninterrupted
    run (the clock lives in the state; chunk boundaries are free)."""
    cfg = _cfg("ramp_fail", 64)
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    mid, _ = make_mega_run(cfg, 17)(state, sched)
    final_split, _ = make_mega_run(cfg, 23)(mid, sched)
    final_once, _ = make_mega_run(cfg, 40)(state, sched)
    for name in STATE_FIELDS:
        assert np.array_equal(np.asarray(getattr(final_split, name)),
                              np.asarray(getattr(final_once, name))), name


def test_mega_supported_envelope():
    ok = _cfg("churn", 64)
    assert mega_supported(ok)
    too_big = SimConfig(max_nnb=1 << 14, model="overlay",
                        single_failure=True, drop_msg=False,
                        total_ticks=100, step_rate=40.0 / (1 << 14))
    assert not mega_supported(too_big)
    # a user-set view width that overflows the 128-lane plane
    wide = SimConfig(max_nnb=64, model="overlay", single_failure=True,
                     drop_msg=False, total_ticks=100, step_rate=0.5,
                     overlay_view=64)
    assert not mega_supported(wide)
