"""Differential tests: the MXU level-decomposition merge vs the
blockwise VPU XLA op.

Both implement the same contract (ops/merge.py docstring): exact
masked maxima over the sender axis.  The MXU form resolves one
distinct column value per iteration with a boolean matmul, so the
tests include value distributions from degenerate (all equal — one
level) to adversarial (all distinct — N levels), plus shapes that are
not tile multiples and a full end-to-end run through the whole
simulation.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from gossip_protocol_tpu.ops.merge import (gossip_reductions,
                                           gossip_reductions_mxu)


def _random_inputs(rng, r, s, j, t_now=50, t_remove=20):
    recv = rng.random((r, s)) < 0.4
    known = rng.random((s, j)) < 0.6
    hb = rng.integers(1, t_now + 2, size=(s, j)).astype(np.int32)
    ts = rng.integers(0, t_now + 1, size=(s, j)).astype(np.int32)
    return (jnp.asarray(recv), jnp.asarray(known),
            jnp.asarray(hb * known), jnp.asarray(ts * known))


@pytest.mark.parametrize("r,s,j", [
    (8, 8, 128),        # exactly one tile
    (16, 24, 128),      # sender axis not a receiver multiple
    (10, 10, 10),       # tiny odd shape (reference N=10)
    (64, 64, 200),      # j not a lane multiple
    (130, 64, 130),     # r and j cross tile boundaries
])
@pytest.mark.parametrize("seed", [0, 1])
def test_mxu_reductions_match(r, s, j, seed):
    rng = np.random.default_rng(seed)
    recv, known, hb, ts = _random_inputs(rng, r, s, j)
    now = jnp.int32(50)
    ref = gossip_reductions(recv, known, hb, ts, now,
                            t_remove=20, block_size=16)
    got = gossip_reductions_mxu(recv, known, hb, ts, now, t_remove=20)
    for a, b, name in zip(ref, got, ["m_all", "m_fr", "t_fr", "anyf"]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


@pytest.mark.parametrize("spread", ["one_level", "adversarial"])
def test_mxu_reductions_value_spread(spread):
    """Degenerate (single distinct value -> 1 iteration) and
    adversarial (every sender distinct -> S iterations) columns."""
    rng = np.random.default_rng(3)
    s = j = 48
    recv = jnp.asarray(rng.random((s, s)) < 0.5)
    known = jnp.asarray(rng.random((s, j)) < 0.7)
    if spread == "one_level":
        hb = jnp.full((s, j), 17, jnp.int32) * known
    else:
        hb = jnp.asarray((np.arange(s)[:, None] + np.arange(j)[None, :] + 1)
                         .astype(np.int32)) * known
    ts = jnp.asarray(rng.integers(30, 50, size=(s, j)).astype(np.int32)) * known
    now = jnp.int32(50)
    ref = gossip_reductions(recv, known, hb, ts, now,
                            t_remove=20, block_size=16)
    got = gossip_reductions_mxu(recv, known, hb, ts, now, t_remove=20)
    for a, b, name in zip(ref, got, ["m_all", "m_fr", "t_fr", "anyf"]):
        assert np.array_equal(np.asarray(a), np.asarray(b)), name


def test_mxu_no_contributions():
    """All-empty delivery must yield FILL everywhere and anyf False."""
    n = 16
    z = jnp.zeros((n, n), bool)
    zi = jnp.zeros((n, n), jnp.int32)
    m_all, m_fr, t_fr, anyf = gossip_reductions_mxu(
        z, z, zi, zi, jnp.int32(5), t_remove=20)
    assert (np.asarray(m_all) == -1).all()
    assert (np.asarray(m_fr) == -1).all()
    assert (np.asarray(t_fr) == -1).all()
    assert not np.asarray(anyf).any()


def test_end_to_end_mxu_matches_xla():
    """A full scenario run must produce identical events and final
    state with either merge implementation."""
    from gossip_protocol_tpu.core.sim import Simulation
    from tests.conftest import scenario_cfg

    cfg = scenario_cfg("msgdropsinglefailure", max_nnb=24, seed=7,
                       total_ticks=200)
    r_xla = Simulation(cfg, use_pallas=False).run()
    r_pal = Simulation(cfg, use_pallas=True).run()
    assert np.array_equal(r_xla.added, r_pal.added)
    assert np.array_equal(r_xla.removed, r_pal.removed)
    assert np.array_equal(r_xla.sent, r_pal.sent)
    assert np.array_equal(np.asarray(r_xla.final_state.hb),
                          np.asarray(r_pal.final_state.hb))
    assert np.array_equal(np.asarray(r_xla.final_state.ts),
                          np.asarray(r_pal.final_state.ts))
