"""Schedule-segmented grid kernel: planner + differential tests.

The segment planner (models/segments.py) splits a run at the
closed-form schedule boundaries and compiles a specialized grid-kernel
variant per segment (static ``ramp_live``/``churn_live``/``join_live``
/``drop_live`` elision in ops/pallas/overlay_grid.py).  The parity bar
is absolute: the segmented run must replay the exact trajectory of the
per-tick XLA formulation — final state bit-identical, per-tick metrics
identical except ``live_uncovered`` (the grid path's -1 sentinel).
Interpret mode on CPU; the same contract holds compiled on TPU
(bench.py routes its grid configs through the planner).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_run,
                                                make_overlay_schedule)
from gossip_protocol_tpu.models.overlay_grid import make_grid_run
from gossip_protocol_tpu.models.segments import (ALL_LIVE, PhaseFlags,
                                                 describe_plan, flags_at,
                                                 phase_windows,
                                                 plan_segments)
from gossip_protocol_tpu.ops.pallas.overlay_grid import GRID_TICKS

STATE_FIELDS = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                "send_flags", "joinreq", "joinrep")
METRIC_FIELDS = ("in_group", "view_slots", "adds", "removals",
                 "false_removals", "victim_slots", "sent", "recv")

#: small row block so n=64 runs as multiple grid blocks
BLOCK = 32


def _cfg(scenario, n=64):
    if scenario == "churn":
        return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                         drop_msg=False, seed=7, total_ticks=200,
                         churn_rate=0.25, rejoin_after=30,
                         step_rate=40.0 / n)
    if scenario == "fail_rejoin":
        return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                         drop_msg=False, seed=3, total_ticks=180,
                         fail_tick=70, rejoin_after=25, step_rate=0.5)
    if scenario == "drop10":
        # the BASELINE 10%-drop shape in miniature: ramp finishes, the
        # window opens at 20 and closes at 90, a scripted failure with
        # no rejoin keeps churn_live on for the run's tail
        return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                         drop_msg=True, msg_drop_prob=0.1, seed=5,
                         total_ticks=160, fail_tick=60, step_rate=0.25,
                         drop_open_tick=20, drop_close_tick=90)
    raise ValueError(scenario)


def _compare(cfg, length, start_tick=0, state=None):
    sched = make_overlay_schedule(cfg)
    if state is None:
        state = init_overlay_state(cfg)
    run_x = make_overlay_run(cfg, length, use_pallas=False)
    run_g = make_grid_run(cfg, length, block_rows=BLOCK,
                          start_tick=start_tick)
    fx, mx = run_x(state, sched)
    fg, mg = run_g(state, sched)
    for name in STATE_FIELDS:
        a, b = np.asarray(getattr(fx, name)), np.asarray(getattr(fg, name))
        assert np.array_equal(a, b), f"state field {name} diverged"
    for name in METRIC_FIELDS:
        a, b = np.asarray(getattr(mx, name)), np.asarray(getattr(mg, name))
        assert np.array_equal(a, b), \
            f"metric {name} diverged at ticks {np.flatnonzero(a != b)[:5]}"
    return fg


# churn is the tier-1 representative (most distinct segment flags);
# the other scenarios move to the slow lap to keep tier-1 inside its
# 870 s wrapper on 1-core containers (~20-25 s of compiles each)
@pytest.mark.parametrize("scenario", [
    "churn",
    pytest.param("fail_rejoin", marks=pytest.mark.slow),
    pytest.param("drop10", marks=pytest.mark.slow),
])
def test_segmented_run_bitwise_equals_xla(scenario):
    cfg = _cfg(scenario)
    plan = plan_segments(cfg, cfg.total_ticks, 0, GRID_TICKS)
    # the plan must actually specialize (several variants), or the
    # test would only re-prove the all-live kernel
    assert len(plan) >= 2, describe_plan(plan)
    assert len({s.flags for s in plan}) >= 2, describe_plan(plan)
    _compare(cfg, cfg.total_ticks)


def test_segmented_steady_state_elides_everything():
    """A churn run's tail is the fully-dead steady-state variant."""
    cfg = _cfg("churn")
    plan = plan_segments(cfg, cfg.total_ticks, 0, GRID_TICKS)
    assert plan[-1].flags == PhaseFlags(False, False, False, False), \
        describe_plan(plan)


def test_segmented_resume_from_pinned_tick():
    """A segmented continuation pinned to tick 48 (the post-ramp
    clock) replays the uninterrupted trajectory bit-identically."""
    cfg = _cfg("churn")
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    mid, _ = make_overlay_run(cfg, 48, use_pallas=False)(state, sched)
    final = _compare(cfg, cfg.total_ticks - 48, start_tick=48, state=mid)
    assert int(np.asarray(final.tick)) == cfg.total_ticks


def test_segmented_run_rejects_mismatched_clock():
    cfg = _cfg("churn")
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    mid, _ = make_overlay_run(cfg, 16, use_pallas=False)(state, sched)
    run = make_grid_run(cfg, 32, block_rows=BLOCK, start_tick=0)
    with pytest.raises(ValueError, match="start tick"):
        run(mid, sched)


def test_planner_windows_and_flags():
    cfg = _cfg("drop10")                    # n=64, step 1/4, no rejoin
    win = phase_windows(cfg)
    assert win.last_start == 63 // 4 == 15
    assert win.join_dead_from == 15 + 3     # no rejoin: ramp-only joins
    assert win.drop_lo == 21 and win.drop_hi == 90
    assert flags_at(win, 15).ramp_live and not flags_at(win, 16).ramp_live
    assert not flags_at(win, 20).drop_live
    assert flags_at(win, 21).drop_live and flags_at(win, 90).drop_live
    assert not flags_at(win, 91).drop_live
    # scripted failure without rejoin: victims stay failed forever
    # (the window is conservative by one tick at the fail boundary)
    assert not flags_at(win, 59).churn_live
    assert flags_at(win, 61).churn_live and flags_at(win, 10_000).churn_live

    churn = _cfg("churn")                   # total=200 -> fails [50,149]
    cwin = phase_windows(churn)
    assert cwin.fail_lo == 50 and cwin.rejoin_hi == 149 + 30
    assert cwin.join_dead_from == 179 + 3
    assert not flags_at(cwin, 182).join_live
    assert flags_at(cwin, 185) == PhaseFlags(False, False, False, False)


def test_planner_launch_alignment_and_coverage():
    cfg = _cfg("churn")
    for length, t0 in ((200, 0), (152, 48), (44, 0), (17, 100)):
        plan = plan_segments(cfg, length, t0, GRID_TICKS)
        assert sum(s.ticks for s in plan) == length
        t = t0
        for j, seg in enumerate(plan):
            assert seg.start == t
            t += seg.ticks
            if j < len(plan) - 1:           # only the tail may be ragged
                assert seg.ticks % GRID_TICKS == 0
        # consecutive segments always change flags (maximal merging)
        for a, b in zip(plan, plan[1:]):
            assert a.flags != b.flags or a.ticks % GRID_TICKS != 0


def test_planner_unpinned_clock_degenerates_to_all_live():
    cfg = _cfg("churn")
    plan = plan_segments(cfg, 200, None, GRID_TICKS)
    assert len(plan) == 1 and plan[0].flags == ALL_LIVE
    assert plan_segments(cfg, 0, 0, GRID_TICKS) == []


def test_planner_is_seed_independent():
    cfg = _cfg("churn")
    plans = {describe_plan(plan_segments(cfg.replace(seed=s), 200, 0,
                                         GRID_TICKS))
             for s in (0, 1, 2, 99)}
    assert len(plans) == 1


# ---- adversarial-world windows (worlds.py, PR 9) ----------------------

def _world_cfg(**kw):
    base = dict(max_nnb=64, model="overlay", single_failure=True,
                drop_msg=False, seed=5, total_ticks=160, fail_tick=60,
                step_rate=0.25)
    base.update(kw)
    return SimConfig(**base)


def test_partition_window_rides_the_drop_plane():
    """The partition window unions into drop_lo/drop_hi (it blocks
    sends exactly like the drop window), drop world on or off."""
    cfg = _world_cfg(partition_groups=2, partition_open_tick=30,
                     partition_close_tick=80)
    win = phase_windows(cfg)
    assert win.drop_lo == 31 and win.drop_hi == 80
    assert not flags_at(win, 30).drop_live
    assert flags_at(win, 31).drop_live and flags_at(win, 80).drop_live
    assert not flags_at(win, 81).drop_live
    both = _world_cfg(partition_groups=2, partition_open_tick=30,
                      partition_close_tick=80, drop_msg=True,
                      msg_drop_prob=0.1, drop_open_tick=50,
                      drop_close_tick=100)
    bwin = phase_windows(both)
    assert bwin.drop_lo == 31 and bwin.drop_hi == 100


def test_wave_window_replaces_the_scripted_fail_tick():
    """The wave's radius ramp sets the churn window: [wave_start,
    wave_last_fail] (+ rejoin), never the seed-moved victim set."""
    cfg = _world_cfg(single_failure=False, wave_size=9, wave_tick=70,
                     wave_speed=2)
    win = phase_windows(cfg)
    assert win.fail_lo == 70
    # conservative by one tick at the fail boundary, like the
    # scripted window (test_planner_windows_and_flags)
    assert not flags_at(win, 69).churn_live
    assert flags_at(win, 71).churn_live
    assert flags_at(win, 10_000).churn_live      # no rejoin: permanent
    rj = phase_windows(cfg.replace(rejoin_after=20))
    # last victim fails at 70 + 8//2 = 74; rejoined by 94
    assert rj.rejoin_hi == 94
    assert not flags_at(rj, 95).churn_live
    assert rj.join_dead_from == 94 + 3           # rejoins re-JOINREQ


def test_flap_window_widens_churn_and_join():
    """Flapping members keep churn_live AND join_live on through the
    flap window (every up-edge re-enters via JOINREQ)."""
    cfg = _world_cfg(flap_rate=0.3, flap_period=24, flap_down=6,
                     flap_open_tick=50, flap_close_tick=120,
                     fail_tick=10_000)
    win = phase_windows(cfg)
    assert win.fail_lo == 51 and win.rejoin_hi >= 120
    assert flags_at(win, 100).churn_live and flags_at(win, 100).join_live
    assert win.join_dead_from == 123
    assert not flags_at(win, 123).join_live
    # the -1 knobs default to the churn machinery's quarter points
    dflt = phase_windows(_world_cfg(flap_rate=0.3, fail_tick=10_000))
    assert dflt.fail_lo == 160 // 4 + 1
    assert dflt.rejoin_hi >= (3 * 160) // 4


def test_world_plan_signatures_are_distinct_and_seedless():
    """A world-knob edit always re-buckets; a seed edit never does —
    and the zombie/asym worlds (no window of their own) still change
    plan identity."""
    from gossip_protocol_tpu.models.segments import plan_signature
    base = _world_cfg()
    zomb = _world_cfg(zombie=True)
    asym = _world_cfg(drop_msg=True, msg_drop_prob=0.1, asym_drop=True)
    uni = _world_cfg(drop_msg=True, msg_drop_prob=0.1)
    part = _world_cfg(partition_groups=2, partition_open_tick=30,
                      partition_close_tick=80)
    part2 = _world_cfg(partition_groups=3, partition_open_tick=30,
                       partition_close_tick=80)
    sigs = [plan_signature(c) for c in (base, zomb, asym, uni, part,
                                        part2)]
    assert len(set(sigs)) == len(sigs)
    assert plan_signature(part) == plan_signature(part.replace(seed=9))


def test_world_checkpoint_cuts_are_seed_shared():
    """checkpoint_ticks for a partition scenario cuts at the window
    boundaries and is identical across seeds (lanes of a fleet agree
    on the legal snapshot points by construction)."""
    from gossip_protocol_tpu.models.segments import checkpoint_ticks
    cfg = _world_cfg(partition_groups=2, partition_open_tick=48,
                     partition_close_tick=96, fail_tick=10_000)
    cuts = checkpoint_ticks(cfg)
    assert cuts, "partition plan offered no interior cuts"
    assert cuts == checkpoint_ticks(cfg.replace(seed=123))
    # the window opening lands on a launch-aligned cut (48 is a
    # multiple of the 16-tick quantum); the close tick 96 is the last
    # LIVE tick, so its segment runs through the covering launch and
    # the post-partition steady segment starts at 112
    assert 48 in cuts and 112 in cuts


# ---- composed worlds (worlds.composition, round 2) --------------------

def _composed_cfg(**kw):
    """"Partition DURING failure wave WHILE flappers flap" as ONE
    config — the composition-grammar sentence from docs/SCENARIOS.md."""
    base = dict(single_failure=False, wave_size=9, wave_tick=70,
                wave_speed=2, rejoin_after=20,
                flap_rate=0.3, flap_period=24, flap_down=6,
                flap_open_tick=50, flap_close_tick=120,
                partition_groups=2, partition_open_tick=30,
                partition_close_tick=80)
    base.update(kw)
    return _world_cfg(**base)


def test_composed_windows_are_the_union_of_the_planes():
    """Each plane folds onto its own window axis and overlapping
    windows ∪-fold: the composed config's windows are exactly the
    pointwise union of the single-plane runs."""
    from gossip_protocol_tpu.models.segments import checkpoint_ticks
    cfg = _composed_cfg()
    win = phase_windows(cfg)
    wave = phase_windows(_world_cfg(single_failure=False, wave_size=9,
                                    wave_tick=70, wave_speed=2,
                                    rejoin_after=20))
    flap = phase_windows(_world_cfg(flap_rate=0.3, flap_period=24,
                                    flap_down=6, flap_open_tick=50,
                                    flap_close_tick=120,
                                    fail_tick=10_000))
    part = phase_windows(_world_cfg(partition_groups=2,
                                    partition_open_tick=30,
                                    partition_close_tick=80))
    # churn = wave ∪ flap: the flap opens first (50 + 1 = 51), the
    # flap closes last (120 > wave's 74 + 20 = 94).  The flap-only
    # baseline can't anchor the rejoin axis — its out-of-horizon
    # scripted failure is permanent, so it reports an infinite
    # rejoin_hi — but composing with the wave (finite rejoin) folds
    # the flap close tick in exactly.
    assert win.fail_lo == min(wave.fail_lo, flap.fail_lo) == 51
    assert win.rejoin_hi == 120 and wave.rejoin_hi == 94
    assert win.join_dead_from == flap.join_dead_from == 123
    # drop = the partition alone (the drop world is off)
    assert (win.drop_lo, win.drop_hi) == (part.drop_lo, part.drop_hi) \
        == (31, 80)
    # all three phases are simultaneously live mid-storm
    f = flags_at(win, 72)
    assert f.churn_live and f.drop_live and f.join_live


def test_windowless_planes_rebucket_without_moving_windows():
    """BYZ and LATENCY have no window of their own — they must leave
    phase elision untouched while still changing plan identity (via
    worlds_key), so a liar config can never be served a kernel plan
    compiled for the honest one."""
    from gossip_protocol_tpu.models.segments import plan_signature
    cfg = _composed_cfg()
    byz = cfg.replace(byz_rate=0.25)
    lat = cfg.replace(link_latency=4)
    assert phase_windows(byz) == phase_windows(cfg)
    assert phase_windows(lat) == phase_windows(cfg)
    sigs = [plan_signature(c) for c in
            (cfg, byz, lat, byz.replace(byz_boost=16),
             lat.replace(link_latency=6), byz.replace(link_latency=4))]
    assert len(set(sigs)) == len(sigs)
    assert plan_signature(byz) == plan_signature(byz.replace(seed=77))


def test_composed_checkpoint_cuts_resume_to_the_plan_tail():
    """Cuts of the composed plan are seed-shared, launch-aligned, sit
    only where the live-phase mix actually changes (never inside an
    elided steady phase), and resuming at any cut replays the original
    plan's tail exactly — the static-elision invariant checkpointing
    relies on."""
    from gossip_protocol_tpu.models.segments import checkpoint_ticks
    cfg = _composed_cfg(link_latency=4, byz_rate=0.1)
    cuts = checkpoint_ticks(cfg)
    assert cuts, "composed plan offered no interior cuts"
    assert cuts == checkpoint_ticks(cfg.replace(seed=123))
    assert all(c % 16 == 0 for c in cuts)
    full = plan_segments(cfg, cfg.total_ticks, 0, 16)
    for a, b in zip(full, full[1:]):
        assert a.flags != b.flags      # a cut always changes the mix
    for c in cuts:
        tail = [s for s in full if s.start >= c]
        assert plan_segments(cfg, cfg.total_ticks - c, c, 16) == tail
