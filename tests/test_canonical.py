"""Bucket canonicalization: pad-ladder / quantization bit-parity.

PR 16 collapses near-identical dense trace requests into canonical
equivalence classes (service/canonical.py): peer counts pad to
power-of-two ladder rungs with inert filler peers, phase windows
quantize to the checkpoint grid with exact windows riding as Schedule
data, and world parameters become runtime operands.  The whole scheme
is only sound if a canonical run is BIT-IDENTICAL to its exact
(unpadded, unquantized) solo run — these tests pin that per tick, for
the grader's non-power-of-two N=10 padded to rung 16, for mixed-n
drop-off classes, and for composed-world classes with operand jitter.
Filler peers must never be unstacked into results (the peer-axis twin
of the fleet's filler-lane invariant).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.fleet import CanonicalFleetSimulation
from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.core.tick import run_build_count
from gossip_protocol_tpu.models.segments import (CHECKPOINT_GRID_TICKS,
                                                 quantize_tick,
                                                 quantized_plan_signature)
from gossip_protocol_tpu.service.canonical import (canonical_bucket_key,
                                                   canonical_drop_active,
                                                   canonical_supported,
                                                   ladder_rung)

STATE_FIELDS = ("tick", "in_group", "own_hb", "known", "hb", "ts",
                "gossip", "gossip_age", "joinreq", "joinrep")


def _drop10(seed=1, prob=0.1, open_t=13, close_t=41):
    """Grader-style dense config: N=10 (non-power-of-two), windowed
    drop — pads to rung 16."""
    return SimConfig(max_nnb=10, single_failure=True, drop_msg=True,
                     msg_drop_prob=prob, seed=seed, total_ticks=60,
                     fail_tick=20, drop_open_tick=open_t,
                     drop_close_tick=close_t)


def _nodrop(n, seed=1):
    return SimConfig(max_nnb=n, single_failure=True, drop_msg=False,
                     seed=seed, total_ticks=60, fail_tick=20)


def _assert_lane_bitidentical(ref, lane, ctx):
    """Per-tick event equality (stronger than every-cut equality) plus
    counters and the full final state."""
    assert lane.added.shape == ref.added.shape, ctx
    assert np.array_equal(ref.added, lane.added), \
        f"{ctx}: added events diverged"
    assert np.array_equal(ref.removed, lane.removed), \
        f"{ctx}: removed events diverged"
    assert np.array_equal(ref.sent, lane.sent), f"{ctx}: sent"
    assert np.array_equal(ref.recv, lane.recv), f"{ctx}: recv"
    for f in STATE_FIELDS:
        a = np.asarray(getattr(ref.final_state, f))
        b = np.asarray(getattr(lane.final_state, f))
        assert np.array_equal(a, b), f"{ctx}: state field {f} diverged"


# ---- key algebra ----------------------------------------------------

def test_ladder_rung():
    assert [ladder_rung(n) for n in (1, 4, 5, 8, 10, 16, 17, 33)] \
        == [4, 4, 8, 8, 16, 16, 32, 64]


def test_quantize_tick_superset():
    g = CHECKPOINT_GRID_TICKS
    for lo, hi in [(13, 41), (0, 16), (15, 17), (16, 16)]:
        ql, qh = quantize_tick(lo, g), quantize_tick(hi, g, up=True)
        assert ql <= lo and qh >= hi
        assert ql % g == 0 and qh % g == 0
    # sentinels pass through
    assert quantize_tick(-1, g) == -1
    assert quantize_tick(1 << 30, g, up=True) == 1 << 30


def test_canonical_key_collapses_operands_and_jitter():
    base = _drop10(seed=1, prob=0.1, open_t=13, close_t=41)
    key = canonical_bucket_key(base, "trace")
    assert key[0] == "canon"
    # drop probability is a runtime operand; window jitter within the
    # grid and seeds collapse too
    for c in (_drop10(seed=2, prob=0.25, open_t=13, close_t=41),
              _drop10(seed=3, prob=0.1, open_t=14, close_t=44)):
        assert canonical_bucket_key(c, "trace") == key
    # ... but t_remove (baked), a window crossing a grid line, and a
    # different static plane set split classes
    assert canonical_bucket_key(
        base.replace(t_remove=12), "trace") != key
    assert canonical_bucket_key(
        _drop10(open_t=31, close_t=41), "trace") != key
    assert canonical_bucket_key(
        base.replace(zombie=True), "trace") != key


def test_canonical_key_collapses_n_for_dropless():
    """Drop-off configs share a rung-wide program across REAL n; a
    drop-on config pins its real n (stream width) in the key."""
    k10 = canonical_bucket_key(_nodrop(10), "trace")
    assert canonical_bucket_key(_nodrop(13), "trace") == k10
    assert canonical_bucket_key(_nodrop(16), "trace") == k10
    assert canonical_bucket_key(_nodrop(17), "trace") != k10  # rung 32
    d10 = canonical_bucket_key(_drop10(), "trace")
    d11 = canonical_bucket_key(
        _drop10().replace(max_nnb=11), "trace")
    assert d10 != d11


def test_canonical_fallback_and_support():
    ov = SimConfig(max_nnb=64, model="overlay", single_failure=True,
                   drop_msg=False, seed=0, total_ticks=64,
                   fail_tick=30, step_rate=8.0 / 64)
    assert not canonical_supported(ov, "trace")
    assert not canonical_supported(_drop10(), "bench")
    assert canonical_bucket_key(ov, "trace")[0] != "canon"
    assert canonical_bucket_key(_drop10(), "bench")[0] != "canon"


def test_canonical_drop_active_superset():
    cfg = _drop10(open_t=13, close_t=41)
    t = np.arange(cfg.total_ticks)
    exact = (t > 13) & (t <= 41)
    canon = canonical_drop_active(cfg)
    assert canon.shape == exact.shape
    assert np.all(canon[exact]), "quantized window must cover exact"
    assert not canonical_drop_active(_nodrop(10)).any()


# ---- satellite 4: pad-ladder parity ---------------------------------

def test_pad_ladder_parity_n10_rung16():
    """The grader's N=10 padded to rung 16: three class members with
    jittered windows and drop probabilities, every lane bit-identical
    to its exact unpadded solo run at EVERY tick; filler peer rows
    never surface in results."""
    members = [_drop10(seed=1, prob=0.1, open_t=13, close_t=41),
               _drop10(seed=2, prob=0.25, open_t=14, close_t=44),
               _drop10(seed=3, prob=0.1, open_t=13, close_t=41)]
    fleet = CanonicalFleetSimulation(members[0])
    assert fleet.rung == 16
    res = fleet.run(configs=members)
    assert res.batch == len(members)
    for i, c in enumerate(members):
        ref = Simulation(c).run()
        lane = res.lanes[i]
        # filler peers are never unstacked: results are REAL width
        assert lane.added.shape == (c.total_ticks, 10, 10)
        assert np.asarray(lane.final_state.known).shape == (10, 10)
        assert lane.sent.shape[0] == 10
        _assert_lane_bitidentical(ref, lane, f"lane {i}")


@pytest.mark.slow
def test_pad_ladder_parity_mixed_n_dropless():
    """One rung-16 drop-off class serving REAL n of 10, 13, and 16 in
    a single program — per-lane results bit-identical to solo runs at
    each lane's own width."""
    members = [_nodrop(10, seed=5), _nodrop(13, seed=6),
               _nodrop(16, seed=7)]
    keys = {canonical_bucket_key(c, "trace") for c in members}
    assert len(keys) == 1
    fleet = CanonicalFleetSimulation(members[0])
    res = fleet.run(configs=members)
    for i, c in enumerate(members):
        ref = Simulation(c).run()
        lane = res.lanes[i]
        assert lane.added.shape == (c.total_ticks, c.n, c.n)
        _assert_lane_bitidentical(ref, lane, f"lane n={c.n}")


@pytest.mark.slow
def test_pad_ladder_parity_composed_worlds():
    """Composed-world class (partition + drop): the partition group
    COUNT and window scalars ride as operands/data, so members with
    different group counts share one program and still match their
    solo runs bit-for-bit."""
    def member(seed, groups, prob):
        return SimConfig(max_nnb=12, single_failure=True, drop_msg=True,
                         msg_drop_prob=prob, seed=seed, total_ticks=64,
                         fail_tick=20, drop_open_tick=13,
                         drop_close_tick=41, partition_groups=groups,
                         partition_open_tick=16,
                         partition_close_tick=32)
    members = [member(1, 2, 0.1), member(2, 3, 0.2)]
    assert len({canonical_bucket_key(c, "trace")
                for c in members}) == 1
    fleet = CanonicalFleetSimulation(members[0])
    res = fleet.run(configs=members)
    for i, c in enumerate(members):
        _assert_lane_bitidentical(Simulation(c).run(), res.lanes[i],
                                  f"groups={c.partition_groups}")


@pytest.mark.slow
def test_pad_ladder_parity_latency_plane():
    """Latency plane: the per-link delay matrix pads with an inert
    filler value; real-corner delivery ages match solo exactly."""
    def member(seed):
        return SimConfig(max_nnb=11, single_failure=True,
                         drop_msg=False, seed=seed, total_ticks=64,
                         fail_tick=20, link_latency=3)
    members = [member(1), member(2)]
    fleet = CanonicalFleetSimulation(members[0])
    res = fleet.run(configs=members)
    for i, c in enumerate(members):
        _assert_lane_bitidentical(Simulation(c).run(), res.lanes[i],
                                  f"lat lane {i}")


@pytest.mark.slow
def test_canonical_program_reuse_across_members():
    """Two launches with different members of one class share the
    compiled program: zero fresh builds on the second dispatch."""
    a = _drop10(seed=11, prob=0.11)
    b = _drop10(seed=12, prob=0.33, open_t=14, close_t=44)
    fleet = CanonicalFleetSimulation(a)
    fleet.run(configs=[a])
    before = run_build_count()
    fleet.run(configs=[b])
    assert run_build_count() == before, \
        "second member dispatch must not rebuild the canonical program"


def test_canonical_rejects_non_members():
    fleet = CanonicalFleetSimulation(_drop10())
    with pytest.raises(ValueError, match="equivalence class"):
        fleet.run(configs=[_drop10().replace(t_remove=12)])
    with pytest.raises(NotImplementedError):
        fleet.run_bench(seeds=[1])
    with pytest.raises(NotImplementedError):
        fleet.launch_leg(seeds=[1])


def test_quantized_signature_from_real_config():
    """The quantized plan signature must derive from the REAL config's
    phase windows (last_start depends on n), not the rung
    representative's — members of a mixed-n class agree by
    quantization, not by accident of width."""
    s10 = quantized_plan_signature(_nodrop(10))
    s13 = quantized_plan_signature(_nodrop(13))
    assert s10 == s13
    assert s10[0] == "segplan-q"


# ---- the serving layer (FleetService(canonicalize=True)) ------------

def _svc():
    from gossip_protocol_tpu.service import FleetService
    return FleetService(max_batch=4, max_wait_s=1e9,
                        canonicalize=True)


def test_service_canonical_class_serves_jittered_members_exactly():
    """Three drop requests that jitter probability and window edges
    within one quantization cell land in ONE canonical class, build
    ONE program, and each comes back bit-identical to its exact solo
    run.  The class map records every absorbed exact bucket key."""
    from gossip_protocol_tpu.service import bucket_key
    cfgs = [_drop10(seed=3, prob=0.08, open_t=13, close_t=41),
            _drop10(seed=4, prob=0.12, open_t=9, close_t=44),
            _drop10(seed=5, prob=0.10, open_t=12, close_t=47)]
    assert len({bucket_key(c, "trace") for c in cfgs}) == 3
    assert len({canonical_bucket_key(c, "trace") for c in cfgs}) == 1
    svc = _svc()
    b0 = run_build_count()
    handles = [svc.submit(c) for c in cfgs]
    svc.drain()
    assert run_build_count() - b0 == 1
    for c, h in zip(cfgs, handles):
        ref = Simulation(c).run()
        _assert_lane_bitidentical(ref, h.result(), f"seed={c.seed}")
    classes = svc.cache.class_map()
    assert len(classes) == 1
    (cls,) = classes.values()
    assert cls["members"] == {bucket_key(c, "trace") for c in cfgs}
    assert cls["hits"] >= 1
    st = svc.stats()
    assert st["canonicalize"] is True
    assert st["cache"]["class_member_buckets"] == 3


def test_service_canonical_warm_registers_class_member():
    """warm() on a canonical service records the warmed config's exact
    bucket key as a class member and leaves the bucket build-free on
    the next dispatch."""
    from gossip_protocol_tpu.service import bucket_key
    cfg = _drop10(seed=7)
    svc = _svc()
    svc.warm(cfg)
    classes = svc.cache.class_map()
    assert bucket_key(cfg, "trace") in next(iter(classes.values()))["members"]
    b0 = run_build_count()
    h = svc.submit(_drop10(seed=8, prob=0.11))
    svc.drain()
    assert run_build_count() - b0 == 0
    _assert_lane_bitidentical(Simulation(_drop10(seed=8, prob=0.11)).run(),
                              h.result(), "warmed member")


def test_service_canonical_falls_back_to_exact_for_overlay():
    """Unsupported shapes (overlay) keep EXACT buckets even on a
    canonical service — the scheduler's bucket routing hands them the
    plain ``bucket_key`` and no class entry appears.  (The exact
    dispatch path itself is exercised by the overlay service tests;
    this pins only the ROUTING so no overlay program compiles here.)"""
    from gossip_protocol_tpu.service import bucket_key
    ocfg = SimConfig(max_nnb=64, model="overlay", single_failure=False,
                     drop_msg=False, seed=2, total_ticks=48,
                     churn_rate=0.25, rejoin_after=16, step_rate=8.0 / 64)
    svc = _svc()
    assert not canonical_supported(ocfg, "trace")
    key = svc._bucket(ocfg, "trace")
    assert key == bucket_key(ocfg, "trace")
    assert key[0] != "canon"
    assert svc.cache.class_map() == {}
    svc.drain()


def test_service_canonicalize_checkpoint_and_mesh_gates():
    """The composition matrix (PR 19): canonicalize + checkpoint legs
    stays a TYPED construction-time error (legs validate resume cuts
    against the exact segment plan canonical buckets quantize away);
    canonicalize + a non-power-of-two peer axis is rejected (the pad
    ladder doubles, so only pow2 peer counts divide every rung); and
    canonicalize + a pow2 2-D mesh is ACCEPTED and bit-identical."""
    import jax
    from gossip_protocol_tpu.service import FleetService
    from gossip_protocol_tpu.service.canonical import \
        CanonicalLegUnsupported
    with pytest.raises(ValueError, match="checkpoint"):
        FleetService(canonicalize=True, checkpoint_every=16)
    with pytest.raises(CanonicalLegUnsupported):
        FleetService(canonicalize=True, checkpoint_every=16)
    if jax.device_count() < 8:
        pytest.skip("mesh legs need 8 (virtual) devices")
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    with pytest.raises(ValueError, match="power-of-two peer axis"):
        FleetService(canonicalize=True, mesh=make_lane_peer_mesh(2, 3))
    svc = FleetService(max_batch=2, canonicalize=True,
                       mesh=make_lane_peer_mesh(2, 4))
    assert (svc.n_lanes, svc.n_peers) == (2, 4)
    key = svc._bucket(_drop10(), "trace")
    assert key[0] == "canon"
    h = svc.submit(_drop10(seed=5), mode="trace")
    svc.drain()
    assert h.status == "completed"
    _assert_lane_bitidentical(Simulation(_drop10(seed=5)).run(),
                              h.result(), "canon over (2,4)")
