"""Checkpoint/restore round trip.

The reference always runs 0..700 with no way to stop or resume
(Application.cpp:99).  Here the whole world — clock, tables, in-flight
traffic, PRNG key — is one pytree, so a mid-run checkpoint plus resume
must reproduce an uninterrupted run bit-for-bit, including under
message drop (the per-tick drop key is folded from the carried rng and
the carried clock).
"""

import numpy as np

from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.state import (load_checkpoint, save_checkpoint,
                                       state_from_host, state_to_host)
from tests.conftest import scenario_cfg


def _events_key(evs):
    return [(e.observer, e.tick, e.text) for e in evs]


def test_resume_is_bit_identical(tmp_path):
    cfg = scenario_cfg("msgdropsinglefailure", seed=3)
    sim = Simulation(cfg)

    full = sim.run()

    first = sim.run(ticks=350)
    assert int(np.asarray(first.final_state.tick)) == 350

    ckpt = tmp_path / "mid.npz"
    save_checkpoint(first.final_state, str(ckpt))
    restored = load_checkpoint(str(ckpt))
    second = sim.run(resume_from=restored)
    assert second.first_tick == 350
    assert int(np.asarray(second.final_state.tick)) == cfg.total_ticks

    # events of the stitched run match the uninterrupted one exactly
    assert _events_key(first.events()) + _events_key(second.events()) \
        == _events_key(full.events())
    # final state bit-identical
    a, b = state_to_host(full.final_state), state_to_host(second.final_state)
    for k in a:
        assert np.array_equal(a[k], b[k]), k
    # per-tick accounting stitches exactly
    assert np.array_equal(np.concatenate([first.sent, second.sent], 1),
                          full.sent)
    assert np.array_equal(np.concatenate([first.recv, second.recv], 1),
                          full.recv)


def test_state_host_round_trip():
    cfg = scenario_cfg("singlefailure", seed=1)
    sim = Simulation(cfg)
    res = sim.run(ticks=123)
    d = state_to_host(res.final_state)
    back = state_to_host(state_from_host(d))
    for k in d:
        assert np.array_equal(d[k], back[k]), k
        assert d[k].dtype == back[k].dtype, k


def test_checkpoint_missing_field_rejected(tmp_path):
    import pytest

    cfg = scenario_cfg("singlefailure", seed=0)
    res = Simulation(cfg).run(ticks=10)
    d = state_to_host(res.final_state)
    d.pop("hb")
    with pytest.raises(ValueError, match="missing"):
        state_from_host(d)


def test_checkpoint_path_used_verbatim(tmp_path):
    """No silent .npz suffixing: save/load round-trips any path."""
    cfg = scenario_cfg("singlefailure", seed=0)
    res = Simulation(cfg).run(ticks=5)
    p = tmp_path / "ckpt_no_extension"
    save_checkpoint(res.final_state, str(p))
    assert p.exists()
    back = state_to_host(load_checkpoint(str(p)))
    want = state_to_host(res.final_state)
    for k in want:
        assert np.array_equal(want[k], back[k]), k


def test_profile_hook_writes_trace(tmp_path):
    """run(profile_dir=...) wraps the run in jax.profiler.trace and
    produces a TensorBoard-loadable profile (SURVEY.md §5)."""
    import os

    cfg = scenario_cfg("singlefailure", seed=0)
    res = Simulation(cfg).run(ticks=10, profile_dir=str(tmp_path))
    assert int(np.asarray(res.final_state.tick)) == 10
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert any(f.endswith(".xplane.pb") for f in found), found
