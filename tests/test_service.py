"""Fleet service: continuous-batching scheduler, bucketing, padding
parity, and compiled-program cache behavior
(gossip_protocol_tpu/service/).

The two contracts the serving layer must never bend:

* **exactness** — a request served in a padded batch is bit-identical
  to the same config run alone (filler lanes are masked out
  device-side and vmap lanes are data-independent; core/fleet.py
  ``n_real``);
* **one build per bucket** — a mixed request stream compiles at most
  one fleet program per distinct bucket key (shape key + segment-plan
  signature + mode), pinned as a ``core.tick.run_build_count`` delta.

The fast tests here run inside tier-1 (select just them with
``-m service``); the full >=200-request acceptance replay is
additionally marked ``slow`` (scripts/service_smoke.py runs the same
harness standalone).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.fleet import FleetSimulation, stack_lanes
from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.core.tick import run_build_count
from gossip_protocol_tpu.service import FleetService, bucket_key

pytestmark = pytest.mark.service


def _dense_churn(n=32, ticks=60):
    return SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                     seed=0, total_ticks=ticks, fail_tick=20,
                     rejoin_after=15)


def _dense_drop(n=24, ticks=80):
    return SimConfig(max_nnb=n, single_failure=True, drop_msg=True,
                     msg_drop_prob=0.1, seed=0, total_ticks=ticks,
                     fail_tick=30)


def _overlay_churn(n=64, ticks=64):
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=False, seed=0, total_ticks=ticks,
                     churn_rate=0.25, rejoin_after=16, step_rate=8.0 / n)


class _Clock:
    """Deterministic service clock for flush-policy tests."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---- padding parity (satellite) -------------------------------------
@pytest.mark.parametrize("make_cfg", [_dense_churn, _dense_drop],
                         ids=["churn", "drop10"])
def test_padding_parity(make_cfg):
    """B=3 real + 5 filler lanes: every real lane bit-identical to a
    direct single-simulation run (events, counters, final state)."""
    cfg = make_cfg()
    svc = FleetService(max_batch=8, pad_policy="full")
    handles = [svc.submit(cfg, seed=s) for s in (1, 2, 3)]
    svc.drain()
    sim = Simulation(cfg)
    for s, h in zip((1, 2, 3), handles):
        ref = sim.run(seed=s)
        lane = h.result()
        assert np.array_equal(ref.added, lane.added), s
        assert np.array_equal(ref.removed, lane.removed), s
        assert np.array_equal(ref.sent, lane.sent), s
        assert np.array_equal(ref.recv, lane.recv), s
        for f in ("tick", "in_group", "own_hb", "known", "hb", "ts",
                  "gossip", "joinreq", "joinrep"):
            assert np.array_equal(
                np.asarray(getattr(ref.final_state, f)),
                np.asarray(getattr(lane.final_state, f))), (s, f)
        m = h.metrics
        assert m.batch == 3 and m.padded_batch == 8
        assert m.occupancy == pytest.approx(3 / 8)


def test_padding_parity_overlay():
    """Overlay padded batch: per-lane state and metrics bit-equal to a
    solo run (live_uncovered excepted — the fleet's -1 sentinel)."""
    from gossip_protocol_tpu.models.overlay import OverlaySimulation
    cfg = _overlay_churn()
    svc = FleetService(max_batch=4, pad_policy="full")
    handles = [svc.submit(cfg, seed=s) for s in (1, 2)]
    svc.drain()
    for s, h in zip((1, 2), handles):
        ref = OverlaySimulation(cfg.replace(seed=s), use_pallas=False).run()
        lane = h.result()
        for f in ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                  "send_flags", "joinreq", "joinrep"):
            assert np.array_equal(
                np.asarray(getattr(ref.final_state, f)),
                np.asarray(getattr(lane.final_state, f))), (s, f)
        for f in ("in_group", "view_slots", "adds", "removals",
                  "false_removals", "victim_slots", "sent", "recv"):
            assert np.array_equal(np.asarray(getattr(ref.metrics, f)),
                                  np.asarray(getattr(lane.metrics, f))), \
                (s, f)
        assert h.metrics.occupancy == pytest.approx(0.5)


def test_bench_mode_parity():
    cfg = SimConfig(max_nnb=16, single_failure=True, drop_msg=True,
                    msg_drop_prob=0.1, seed=0, total_ticks=30,
                    fail_tick=10)
    svc = FleetService(max_batch=4)
    handles = [svc.submit(cfg, seed=s, mode="bench") for s in (5, 6)]
    svc.drain()
    sim = Simulation(cfg)
    for s, h in zip((5, 6), handles):
        ref = sim.run_bench(seed=s)
        lane = h.result()
        assert np.array_equal(ref.sent, lane.sent), s
        assert np.array_equal(ref.recv, lane.recv), s
        assert lane.counter_stream_width == ref.counter_stream_width


# ---- compiled-program cache (satellite) ------------------------------
def test_mixed_trace_builds_once_per_bucket():
    """A 20-request mixed trace compiles at most one fleet program per
    distinct bucket key (run_build_count regression)."""
    shapes = [_dense_churn(n=20, ticks=26),
              _dense_churn(n=20, ticks=26).replace(fail_tick=21,
                                                   rejoin_after=3),
              _dense_drop(n=20, ticks=26),
              _dense_churn(n=12, ticks=34)]
    svc = FleetService(max_batch=4, pad_policy="full")
    built0 = run_build_count()
    handles = [svc.submit(shapes[i % len(shapes)], seed=i)
               for i in range(20)]
    svc.drain()
    [h.result() for h in handles]
    stats = svc.stats()
    keys = {bucket_key(c, "trace") for c in shapes}
    assert stats["cache"]["buckets"] == len(keys)
    assert run_build_count() - built0 <= len(keys)
    for b in stats["buckets"].values():
        assert b["builds"] <= 1, stats["buckets"]
    # every dispatch after the bucket's first was a program-cache hit
    assert stats["dispatches"] >= len(keys)
    assert stats["mean_occupancy"] > 0


def test_warmed_bucket_never_builds_on_dispatch():
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=4)
    svc.warm(cfg)
    built = run_build_count()
    handles = [svc.submit(cfg, seed=s) for s in range(6)]
    svc.drain()
    assert run_build_count() == built
    assert all(h.metrics.cache_hit for h in handles)


# ---- flush policies --------------------------------------------------
def test_flush_on_max_batch():
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=4, pipeline=True)
    handles = [svc.submit(cfg, seed=s) for s in range(4)]
    # the 4th submit fills the bucket: LAUNCHED inside submit(); under
    # pipelined dispatch (PR 6) the batch rides in flight — its device
    # program executing — until the next launch or a flush resolves it
    assert svc.pending == 0
    assert svc.in_flight == 4
    assert all(h.status == "in_flight" for h in handles)
    svc.drain()
    assert svc.in_flight == 0
    assert all(h.done for h in handles)
    assert handles[0].metrics.occupancy == 1.0


def test_flush_on_max_wait():
    cfg = _dense_churn(n=16, ticks=22)
    clock = _Clock()
    svc = FleetService(max_batch=8, max_wait_s=5.0, clock=clock,
                       pipeline=True)
    h = svc.submit(cfg, seed=1)
    assert not h.done and svc.pending == 1
    clock.t = 3.0
    svc.pump()
    assert not h.done, "flushed before max_wait elapsed"
    clock.t = 6.0
    assert svc.pump() == 1
    assert h.status == "in_flight"    # launched by the max-wait flush
    svc.flush()
    assert h.done
    assert h.metrics.batch == 1 and h.metrics.padded_batch == 8


def test_result_forces_flush():
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=8)
    h = svc.submit(cfg, seed=9)
    assert not h.done
    ref = Simulation(cfg).run(seed=9)
    assert np.array_equal(h.result().sent, ref.sent)


def test_context_manager_drains():
    cfg = _dense_churn(n=16, ticks=22)
    with FleetService(max_batch=8) as svc:
        h = svc.submit(cfg, seed=2)
    assert h.done


# ---- bucketing -------------------------------------------------------
def test_bucket_key_separates_phase_boundaries():
    """A config edit that only moves a phase boundary lands in a new
    bucket (segment-plan signature); a seed edit does not."""
    cfg = _dense_drop()
    assert bucket_key(cfg, "trace") == bucket_key(cfg.replace(seed=7),
                                                  "trace")
    assert bucket_key(cfg, "trace") != \
        bucket_key(cfg.replace(drop_open_tick=60), "trace")
    assert bucket_key(cfg, "trace") != \
        bucket_key(cfg.replace(fail_tick=31), "trace")
    assert bucket_key(cfg, "trace") != bucket_key(cfg, "bench")
    # same window, different probability: one bucket must share the
    # WHOLE drop plan (the fleet rides it unbatched) — a mixed-prob
    # bucket would degrade to the batched-drop program and build twice
    assert bucket_key(cfg, "trace") != \
        bucket_key(cfg.replace(msg_drop_prob=0.2), "trace")


def test_run_bench_cache_key_includes_plan_signature():
    """Satellite regression: moving a phase boundary must compile a
    fresh run — never serve the old boundaries' program — while
    reseeding stays build-free."""
    cfg = SimConfig(max_nnb=14, single_failure=True, drop_msg=True,
                    msg_drop_prob=0.1, seed=0, total_ticks=28,
                    fail_tick=9, drop_open_tick=5, drop_close_tick=20)
    Simulation(cfg).run_bench(seed=1)
    built = run_build_count()
    Simulation(cfg).run_bench(seed=2)          # reseed: cached
    assert run_build_count() == built
    moved = cfg.replace(drop_open_tick=11)     # phase boundary moved
    Simulation(moved).run_bench(seed=1)
    assert run_build_count() == built + 1, \
        "phase-boundary edit was served a stale compiled run"


# ---- actionable shape errors (satellite) -----------------------------
def test_mismatched_lane_error_names_lane_and_field():
    cfg = _dense_churn()
    bad = cfg.replace(total_ticks=cfg.total_ticks + 1)
    with pytest.raises(ValueError, match=r"lane 1.*total_ticks=61"):
        FleetSimulation(cfg).run(configs=[cfg, bad])
    smaller = cfg.replace(max_nnb=16)
    with pytest.raises(ValueError, match=r"lane 2.*max_nnb=16"):
        FleetSimulation(cfg).run_bench(configs=[cfg, cfg, smaller])
    with pytest.raises(ValueError, match="model"):
        FleetSimulation(cfg).run(configs=[cfg, _overlay_churn()])


def test_stack_lanes_error_names_lane_and_field():
    from gossip_protocol_tpu.state import init_state
    good = init_state(_dense_churn(n=16, ticks=22))
    bad = init_state(_dense_churn(n=32, ticks=22))
    with pytest.raises(ValueError, match=r"lane 1 field \.\w+ has shape"):
        stack_lanes([good, bad])


def test_n_real_bounds():
    cfg = _dense_churn(n=16, ticks=22)
    with pytest.raises(ValueError, match="n_real"):
        FleetSimulation(cfg).run(seeds=[1, 2], n_real=3)
    with pytest.raises(ValueError, match="n_real"):
        FleetSimulation(cfg).run(seeds=[1, 2], n_real=0)


# ---- failure handling is atomic (PR 5 satellite) ---------------------
def test_failed_dispatch_is_atomic_regression():
    """Regression for the pre-PR-5 failure path (re-queue + re-raise
    out of the caller's flush, leaking in-flight state): a failing
    dispatch must terminally resolve EVERY popped request — none left
    ``pending``, nothing re-queued into limbo — and the bucket must
    keep serving afterwards."""
    from gossip_protocol_tpu.service import (DispatchFailed,
                                             FaultInjector, RetryPolicy)
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(
        max_batch=2, degrade_to_solo=False,
        injector=FaultInjector(schedule={1: "dispatch", 2: "compile"}),
        retry=RetryPolicy(max_retries=0))
    handles = [svc.submit(cfg, seed=s) for s in (1, 2)]
    # the flush returned normally; the failure lives on the handles
    assert svc.pending == 0
    assert all(h.done and h.status == "failed" for h in handles)
    assert not svc._handles, "handle stranded in pending"
    with pytest.raises(DispatchFailed):
        handles[0].result()
    # the NEXT batch fails independently (attempt 2) ... and the one
    # after that succeeds: the bucket was never poisoned
    bad = [svc.submit(cfg, seed=s) for s in (3, 4)]
    assert all(h.status == "failed" for h in bad)
    good = [svc.submit(cfg, seed=s) for s in (5, 6)]
    svc.drain()                 # resolve the pipelined clean batch
    assert all(h.status == "completed" for h in good)
    ref = Simulation(cfg).run(seed=5)
    assert np.array_equal(good[0].result().sent, ref.sent)
    st = svc.stats()
    assert st["failed"] == 4 and st["completed"] == 2
    assert st["failures"]["failed_requests"] == 4


def test_stats_failure_domain_counters_clean_path():
    """stats() carries the PR-5 failure-domain counters (satellite):
    present and zero on a clean stream."""
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=2)
    [svc.submit(cfg, seed=s) for s in (1, 2)]
    st = svc.stats()
    f = st["failures"]
    for k in ("retries", "backoff_s", "deadline_misses", "shed",
              "breaker_opens", "degraded_dispatches",
              "degraded_requests", "failed_requests", "device_losses",
              "mesh_rebuilds", "faults_injected", "poisoned_lanes"):
        assert f[k] == 0, (k, f)
    assert st["breaker_open_buckets"] == 0
    assert st["failed"] == 0
    # the windowed per-dispatch view carries the retry count
    assert all(d["retries"] == 0 for d in svc._dispatches)


def test_filler_safety_bench_mode_under_fault():
    """Satellite: a bench-mode dispatch that dies mid-bucket must
    never unstack filler lanes into real handles — the retried partial
    batch returns exactly its real lanes, counters bit-identical."""
    from gossip_protocol_tpu.service import FaultInjector, RetryPolicy
    cfg = SimConfig(max_nnb=16, single_failure=True, drop_msg=True,
                    msg_drop_prob=0.1, seed=0, total_ticks=30,
                    fail_tick=10)
    svc = FleetService(max_batch=8, pad_policy="full",
                       injector=FaultInjector(schedule={1: "dispatch"}),
                       retry=RetryPolicy(max_retries=2,
                                         backoff_base_s=1e-4))
    handles = [svc.submit(cfg, seed=s, mode="bench") for s in (5, 6)]
    svc.drain()
    sim = Simulation(cfg)
    for s, h in zip((5, 6), handles):
        assert h.status == "completed"
        m = h.metrics
        assert m.batch == 2 and m.padded_batch == 8 and m.retries == 1
        assert np.array_equal(sim.run_bench(seed=s).sent, h.result().sent)
    assert not svc._handles


# ---- grader through the service --------------------------------------
def test_grade_all_service_full_marks(testcases_dir, tmp_path):
    """The grader — the service's first real client — still scores
    90/90 when grade_all routes through FleetService."""
    from gossip_protocol_tpu.grader import grade_all
    results = grade_all(None, testcases_dir, str(tmp_path))
    assert results["total"] == 90, {
        k: (v.points if hasattr(v, "points") else v)
        for k, v in results.items()}


# ---- replay harness --------------------------------------------------
def test_smoke_replay_fast():
    """A small mixed replay end-to-end: parity enforced inside
    replay(), at most one build per bucket, every request completed.
    (Throughput is asserted only in the slow full replay — wall-clock
    ratios are too noisy at this size for CI.)"""
    from gossip_protocol_tpu.service import (grader_templates,
                                             overlay_templates, replay)
    m = replay(grader_templates() + overlay_templates(n=128, ticks=48),
               seeds_per_template=3, max_batch=4)
    assert m["requests"] == 18
    assert m["parity_checked"]
    assert m["max_builds_per_bucket"] <= 1
    assert m["mean_occupancy"] > 0.5


# ---- mesh-aware program cache (satellite) ----------------------------
@pytest.mark.skipif(__import__("jax").device_count() < 2,
                    reason="needs 2 (virtual) devices")
def test_mesh_device_count_misses_program_cache():
    """A device-count change can never be served a stale program: the
    same bucket served by services over different lane meshes (and
    over none) compiles fresh each time — both the service-level
    ProgramCache and the process-wide fleet-program cache key on the
    mesh descriptor — while results stay bit-identical."""
    from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
    cfg = _dense_churn(n=16, ticks=24)
    ref = Simulation(cfg).run(seed=1)

    svc1 = FleetService(max_batch=2)                       # no mesh
    h1 = [svc1.submit(cfg, seed=s) for s in (1, 2)]
    svc1.drain()
    built = run_build_count()
    svc2 = FleetService(max_batch=1, mesh=make_lane_mesh(2))
    h2 = [svc2.submit(cfg, seed=s) for s in (1, 2)]
    svc2.drain()
    assert run_build_count() > built, \
        "the 2-device mesh dispatch reused the single-device program"
    assert svc2.cache.stats()["misses"] >= 1
    built = run_build_count()
    if __import__("jax").device_count() >= 4:
        svc4 = FleetService(max_batch=1, mesh=make_lane_mesh(4))
        h4 = [svc4.submit(cfg, seed=s) for s in (1, 2, 3, 4)]
        svc4.drain()
        assert run_build_count() > built, \
            "the 4-device mesh dispatch reused the 2-device program"
        assert np.array_equal(h4[0].result().sent, ref.sent)
    # same bucket, same results, regardless of mesh
    assert np.array_equal(h1[0].result().sent, ref.sent)
    assert np.array_equal(h2[0].result().sent, ref.sent)


def test_program_cache_lru_eviction_counts():
    """Satellite: the ProgramCache is bounded — inserting past
    max_entries evicts LRU (and its compiled programs) and counts it
    in stats()."""
    from gossip_protocol_tpu.service.cache import ProgramCache
    shapes = [_dense_churn(n=12, ticks=20 + i) for i in range(3)]
    pc = ProgramCache(max_entries=2)
    sims = [pc.get(bucket_key(c, "trace"), c) for c in shapes]
    st = pc.stats()
    assert st["buckets"] == 2 and st["evictions"] == 1, st
    # the survivor handles are still served as hits
    assert pc.get(bucket_key(shapes[2], "trace"), shapes[2]) is sims[2]
    assert pc.stats()["hits"] == 1
    # re-asking for the evicted shape is a miss (rebuilt handle)
    assert pc.get(bucket_key(shapes[0], "trace"), shapes[0]) is not sims[0]
    with pytest.raises(ValueError, match="max_entries"):
        ProgramCache(max_entries=0)


def test_lru_eviction_spares_sibling_bucket_programs():
    """Eviction is exact: dropping one bucket removes only the
    programs THAT bucket's handle touched — a sibling bucket sharing
    the config shape (other mode) keeps its compiled programs."""
    from gossip_protocol_tpu.service.cache import ProgramCache
    cfg = _dense_churn(n=12, ticks=18)
    pc = ProgramCache(max_entries=1)
    trace_sim = pc.get(bucket_key(cfg, "trace"), cfg)
    trace_sim.run(seeds=[1])                     # trace program built
    FleetSimulation(cfg).run_bench(seeds=[1])    # sibling bench program
    built = run_build_count()
    # inserting the bench bucket evicts the trace bucket + its programs
    pc.get(bucket_key(cfg, "bench"), cfg)
    assert pc.stats()["evictions"] == 1
    FleetSimulation(cfg).run_bench(seeds=[2])    # bench program survived
    assert run_build_count() == built, \
        "evicting the trace bucket also evicted the bench program"
    FleetSimulation(cfg).run(seeds=[2])          # trace program is gone
    assert run_build_count() == built + 1


def test_stats_device_host_split():
    """Satellite: stats() splits the per-dispatch wall into
    pack / execute (device wait) / fetch, with host = pack + fetch —
    so the pipelined numbers decompose honestly instead of burying
    the blocking result fetch inside device wait."""
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=2)
    [svc.submit(cfg, seed=s) for s in (1, 2)]
    svc.drain()
    st = svc.stats()
    assert st["mean_device_wait_s"] > 0.0
    assert st["mean_pack_s"] >= 0.0 and st["mean_fetch_s"] >= 0.0
    assert st["mean_host_s"] == pytest.approx(
        st["mean_pack_s"] + st["mean_fetch_s"], abs=1e-6)
    assert 0.0 < st["device_wait_frac"] <= 1.0
    assert st["devices"] == 1 and st["capacity"] == 2
    for d in svc._dispatches:
        assert d["host_s"] == pytest.approx(d["pack_s"] + d["fetch_s"])
        assert d["wall_s"] == pytest.approx(
            d["pack_s"] + d["device_wait_s"] + d["fetch_s"], rel=1e-6)


# ---- pipelined dispatch (PR 6 tentpole) ------------------------------
def test_pipelined_replay_parity_and_stats():
    """A mixed replay with pipelining forced ON: per-request
    bit-parity is enforced inside replay(), nothing is left in
    flight, and the metrics carry the pipeline flag + decomposition."""
    from gossip_protocol_tpu.service import (grader_templates,
                                             overlay_templates, replay)
    m = replay(grader_templates() + overlay_templates(n=128, ticks=48),
               seeds_per_template=3, max_batch=4, pipeline=True)
    assert m["pipeline"] is True
    assert m["parity_checked"]
    assert m["max_builds_per_bucket"] <= 1
    assert m["mean_pack_s"] >= 0.0 and m["mean_fetch_s"] >= 0.0
    assert m["device_wait_frac"] > 0.0


def test_pipeline_modes_serve_identical_results():
    """The same stream served pipelined and synchronous returns
    bit-identical lanes (the overlap must be invisible to results)."""
    cfg = _dense_churn(n=16, ticks=22)
    lanes = {}
    for pipe in (True, False):
        svc = FleetService(max_batch=2, pipeline=pipe)
        hs = [svc.submit(cfg, seed=s) for s in (1, 2, 3)]
        svc.drain()
        assert all(h.status == "completed" for h in hs)
        lanes[pipe] = [h.result() for h in hs]
    for a, b in zip(lanes[True], lanes[False]):
        assert np.array_equal(a.sent, b.sent)
        assert np.array_equal(a.recv, b.recv)
        assert np.array_equal(np.asarray(a.final_state.known),
                              np.asarray(b.final_state.known))


def test_multichunk_trace_falls_back_to_sync_beat():
    """A launch the engine cannot defer (multi-chunk dense trace
    executes eagerly inside launch()) must be served on the
    synchronous beat — previous batch resolved first, this batch
    completed before the dispatch returns, never left pretending to
    be in flight."""
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=2, pipeline=True, chunk_ticks=8)
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    assert all(h.status == "completed" for h in hs)
    assert svc.in_flight == 0
    ref = Simulation(cfg).run(seed=1)
    assert np.array_equal(hs[0].result().sent, ref.sent)


def test_pump_harvests_finished_inflight():
    """A poll-driven caller must see completions without forcing a
    flush: a pump that makes no dispatch harvests the in-flight batch
    once its program is ready (non-blocking readiness check)."""
    import time as _time
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=2, pipeline=True)
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    assert svc.in_flight == 2
    for _ in range(500):
        if all(h.done for h in hs):
            break
        svc.pump()
        _time.sleep(0.01)
    assert all(h.status == "completed" for h in hs)
    assert svc.in_flight == 0


def test_inflight_resolves_via_result_and_stats_nonblocking():
    """result() on an in-flight handle resolves it (flush of its
    bucket); stats() must NOT resolve anything (non-blocking metric
    capture)."""
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=2, pipeline=True)
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    assert svc.in_flight == 2
    st = svc.stats()                      # must not resolve
    assert st["in_flight"] == 2 and st["pipeline"] is True
    assert svc.in_flight == 2
    ref = Simulation(cfg).run(seed=1)
    assert np.array_equal(hs[0].result().sent, ref.sent)
    assert svc.in_flight == 0 and hs[1].done


@pytest.mark.slow
def test_full_replay_acceptance():
    """The acceptance criterion, as a test: >= 200 mixed requests,
    >= 2x sequential throughput, occupancy >= 75%, <= 1 build per
    bucket, per-request bit-parity (raised inside replay())."""
    from gossip_protocol_tpu.service import (grader_templates,
                                             overlay_templates, replay)
    m = replay(grader_templates() + overlay_templates(n=512, ticks=96),
               seeds_per_template=34)
    assert m["requests"] >= 200
    assert m["speedup_vs_sequential"] >= 2.0, m
    assert m["mean_occupancy"] >= 0.75, m
    assert m["max_builds_per_bucket"] <= 1, m
