"""Fleet-batched execution: per-lane bit-parity with sequential runs.

One compiled program serves B simulations (core/fleet.py); these tests
pin the contract that batching is EXACT: every lane of a fleet must be
bit-identical to the same seed run alone — dense bench and trace
modes, the overlay XLA path, and the batched grid kernel (interpret
mode on CPU; the same leading-batch-grid-dimension kernel compiles on
TPU).  Plus the satellite regressions: ``SimResult.ticks_per_second``
degenerate-segment guard and the bench-path compile-cache keying.
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.fleet import (FleetSimulation, _lane_state,
                                            _stack_states, stack_lanes)
from gossip_protocol_tpu.core.sim import SimResult, Simulation

STATE_FIELDS = ("tick", "in_group", "own_hb", "known", "hb", "ts",
                "gossip", "joinreq", "joinrep")
OV_STATE_FIELDS = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                   "send_flags", "joinreq", "joinrep")
OV_METRIC_FIELDS = ("in_group", "view_slots", "adds", "removals",
                    "false_removals", "victim_slots", "sent", "recv")

SEEDS = [1, 2, 3, 4]


def _dense_churn(n=32, ticks=60):
    return SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                     seed=0, total_ticks=ticks, fail_tick=20,
                     rejoin_after=15)


def _dense_drop(n=24, ticks=80):
    return SimConfig(max_nnb=n, single_failure=True, drop_msg=True,
                     msg_drop_prob=0.1, seed=0, total_ticks=ticks,
                     fail_tick=30)


def _overlay_churn(n=64, ticks=64):
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=False, seed=0, total_ticks=ticks,
                     churn_rate=0.25, rejoin_after=16, step_rate=8.0 / n)


def _overlay_drop(n=64, ticks=64):
    return SimConfig(max_nnb=n, model="overlay", single_failure=True,
                     drop_msg=True, msg_drop_prob=0.1, seed=0,
                     total_ticks=ticks, fail_tick=30, step_rate=8.0 / n,
                     drop_open_tick=10, drop_close_tick=50)


def _assert_state_equal(ref_state, lane_state, fields, ctx):
    for f in fields:
        a = np.asarray(getattr(ref_state, f))
        b = np.asarray(getattr(lane_state, f))
        assert np.array_equal(a, b), f"{ctx}: state field {f} diverged"


def test_fleet_dense_bench_parity_churn():
    """B=4 churn seeds as a fleet == 4 sequential run_bench calls."""
    cfg = _dense_churn()
    fleet = FleetSimulation(cfg).run_bench(seeds=SEEDS)
    sim = Simulation(cfg)
    assert fleet.batch == len(SEEDS)
    for i, s in enumerate(SEEDS):
        ref = sim.run_bench(seed=s)
        lane = fleet.lanes[i]
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"lane {i}")
        assert np.array_equal(ref.sent, lane.sent), i
        assert np.array_equal(ref.recv, lane.recv), i
        assert lane.counter_stream_width == ref.counter_stream_width


def test_fleet_dense_trace_parity_drop10():
    """Trace-mode fleet: events (and so grades) match sequential."""
    cfg = _dense_drop()
    fleet = FleetSimulation(cfg).run(seeds=SEEDS)
    sim = Simulation(cfg)
    for i, s in enumerate(SEEDS):
        ref = sim.run(seed=s)
        lane = fleet.lanes[i]
        assert np.array_equal(ref.added, lane.added), i
        assert np.array_equal(ref.removed, lane.removed), i
        assert np.array_equal(ref.sent, lane.sent), i
        assert np.array_equal(ref.recv, lane.recv), i
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"lane {i}")


def test_fleet_dense_trace_chunked_matches_unchunked():
    """Tick-chunking is a staging detail: same events either way."""
    cfg = _dense_drop(ticks=50)
    whole = FleetSimulation(cfg).run(seeds=[7, 8])
    parts = FleetSimulation(cfg, chunk_ticks=16).run(seeds=[7, 8])
    for w, p in zip(whole.lanes, parts.lanes):
        assert np.array_equal(w.added, p.added)
        assert np.array_equal(w.sent, p.sent)
        _assert_state_equal(w.final_state, p.final_state, STATE_FIELDS,
                            "chunked")


@pytest.mark.parametrize("make_cfg", [_overlay_churn, _overlay_drop],
                         ids=["churn", "drop10"])
def test_fleet_overlay_parity(make_cfg):
    """Overlay fleet (vmapped XLA tick, shared clock): per-lane states
    and metrics bit-equal to sequential; live_uncovered reports the
    same -1 sentinel the mega/grid kernels use."""
    from gossip_protocol_tpu.models.overlay import OverlaySimulation
    cfg = make_cfg()
    fleet = FleetSimulation(cfg).run(seeds=SEEDS)
    for i, s in enumerate(SEEDS):
        ref = OverlaySimulation(cfg.replace(seed=s), use_pallas=False).run()
        lane = fleet.lanes[i]
        _assert_state_equal(ref.final_state, lane.final_state,
                            OV_STATE_FIELDS, f"lane {i}")
        for m in OV_METRIC_FIELDS:
            a = np.asarray(getattr(ref.metrics, m))
            b = np.asarray(getattr(lane.metrics, m))
            assert np.array_equal(a, b), f"lane {i}: metric {m}"
        assert np.all(np.asarray(lane.metrics.live_uncovered) == -1)
        # host-side coverage validation still works on lane states
        lane.final_coverage()


@pytest.mark.parametrize(
    "make_cfg",
    [pytest.param(_overlay_churn, marks=pytest.mark.slow),
     _overlay_drop],
    ids=["churn", "drop10"])
def test_grid_fleet_kernel_parity(make_cfg):
    """The batched grid kernel (leading batch grid dimension) replays
    each lane of the single-lane grid run bit-for-bit — and therefore
    the XLA tick too (tests/test_overlay_grid.py closes that leg)."""
    from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                    make_overlay_schedule)
    from gossip_protocol_tpu.models.overlay_grid import (
        make_grid_fleet_run, make_grid_run)
    cfg = make_cfg()
    ticks = 20          # one full GRID_TICKS launch + a remainder
    cfgs = [cfg.replace(seed=s) for s in (5, 6)]
    scheds = [make_overlay_schedule(c) for c in cfgs]
    states = _stack_states([init_overlay_state(c) for c in cfgs])
    run_f = make_grid_fleet_run(cfg, ticks, 2, block_rows=32,
                                start_tick=0)
    ff, mf = run_f(states, stack_lanes(scheds))
    for i, c in enumerate(cfgs):
        run_1 = make_grid_run(c, ticks, block_rows=32, start_tick=0)
        f1, m1 = run_1(init_overlay_state(c), scheds[i])
        _assert_state_equal(f1, _lane_state(ff, i), OV_STATE_FIELDS,
                            f"lane {i}")
        for m in OV_METRIC_FIELDS:
            a = np.asarray(getattr(m1, m))
            b = np.asarray(getattr(mf, m))[i]
            assert np.array_equal(a, b), f"lane {i}: metric {m}"
        assert np.all(np.asarray(mf.live_uncovered) == -1)


def test_grid_fleet_rejects_wrong_clock():
    from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                    make_overlay_schedule)
    from gossip_protocol_tpu.models.overlay_grid import make_grid_fleet_run
    cfg = _overlay_churn()
    states = _stack_states([init_overlay_state(cfg)] * 2)
    states = states.replace(tick=states.tick + 3)
    run = make_grid_fleet_run(cfg, 16, 2, block_rows=32, start_tick=0)
    with pytest.raises(ValueError, match="start tick"):
        run(states, stack_lanes([make_overlay_schedule(cfg)] * 2))


def test_fleet_rejects_mixed_shapes():
    cfg = _dense_churn()
    other = cfg.replace(total_ticks=cfg.total_ticks + 1)
    with pytest.raises(ValueError, match="shape"):
        FleetSimulation(cfg).run_bench(configs=[cfg, other])
    with pytest.raises(ValueError, match="exactly one"):
        FleetSimulation(cfg).run_bench()


def test_fleet_grader_full_marks(testcases_dir, tmp_path):
    """The three course scenarios as ONE B=3 fleet grade 90/90 —
    same totals as the sequential grade_all path."""
    from gossip_protocol_tpu.grader import grade_all_fleet
    results = grade_all_fleet(testcases_dir, str(tmp_path))
    assert results["total"] == 90, {
        k: (v.points if hasattr(v, "points") else v)
        for k, v in results.items()}


def test_ticks_per_second_zero_length_segment():
    """Satellite regression: a zero-length resumed segment must not
    raise ZeroDivisionError from the throughput properties."""
    cfg = SimConfig(max_nnb=8, total_ticks=10)
    sim = Simulation(cfg)
    full = sim.run()
    # resuming at/after the end tick runs 0 ticks in ~0 wall seconds
    empty = sim.run(resume_from=full.final_state)
    assert empty.ticks_run == 0
    assert empty.ticks_per_second == 0.0
    assert empty.node_ticks_per_second == 0.0
    # explicit degenerate wall clock (sub-resolution timer)
    degen = SimResult(
        cfg=cfg, start_tick=full.start_tick, fail_tick=full.fail_tick,
        rejoin_tick=full.rejoin_tick, added=None, removed=None,
        sent=np.zeros((8, 5), np.int32), recv=np.zeros((8, 5), np.int32),
        final_state=full.final_state, wall_seconds=0.0)
    assert degen.ticks_per_second == 0.0


def test_run_bench_no_rebuild():
    """Satellite regression: a second ``run_bench(seed=...)`` reuses
    the cached bench run — no new whole-run build (the cache key is
    config shape, seeds flow through the Schedule arrays)."""
    from gossip_protocol_tpu.core.tick import run_build_count
    cfg = SimConfig(max_nnb=16, single_failure=True, total_ticks=30)
    sim = Simulation(cfg)
    sim.run_bench(seed=1)
    built = run_build_count()
    fn = sim._bench_run
    sim.run_bench(seed=2)
    sim.run_bench(seed=3, warmup=False)
    assert run_build_count() == built, \
        "reseeded run_bench rebuilt its compiled run"
    assert sim._bench_run is fn
    # a second Simulation of the same shape shares the process cache
    Simulation(cfg).run_bench(seed=4)
    assert run_build_count() == built


# ---- launch/resolve split (PR 6) -------------------------------------
def test_launch_defer_start_resolve_parity():
    """The pipelined engine protocol — launch(defer=True) stages
    without dispatching, start() dispatches, resolve() fetches — and
    the result is bit-identical to run(), with the wall decomposed as
    pack + execute + fetch."""
    cfg = _overlay_churn()
    sim = FleetSimulation(cfg)
    ref = sim.run(seeds=[7, 8])
    pending = sim.launch(seeds=[7, 8], warmup=False, defer=True)
    pending.start()
    res = pending.resolve()
    assert pending.resolve() is res              # idempotent
    for i in range(2):
        _assert_state_equal(ref.lanes[i].final_state,
                            res.lanes[i].final_state,
                            OV_STATE_FIELDS, f"lane {i}")
        for f in OV_METRIC_FIELDS:
            assert np.array_equal(np.asarray(getattr(ref.lanes[i].metrics, f)),
                                  np.asarray(getattr(res.lanes[i].metrics, f)))
    assert res.pack_seconds >= 0.0 and res.fetch_seconds >= 0.0
    assert res.device_seconds > 0.0
    assert res.wall_seconds == pytest.approx(
        res.pack_seconds + res.device_seconds + res.fetch_seconds,
        rel=1e-6)


def test_stack_lanes_variants_agree():
    """The three lane-stacking paths (eager jnp, one jitted program,
    host numpy) produce identical stacked trees — the launch paths
    mix them by leaf origin, so they must never drift."""
    from gossip_protocol_tpu.models.overlay import make_overlay_schedule
    from gossip_protocol_tpu.core.fleet import (stack_lanes_host,
                                                stack_lanes_jit)
    scheds = [make_overlay_schedule(_overlay_churn().replace(seed=s))
              for s in (1, 2, 3)]
    eager = stack_lanes(scheds)
    jitted = stack_lanes_jit(scheds)
    host = stack_lanes_host(scheds)
    import jax
    for a, b, c in zip(jax.tree.leaves(eager), jax.tree.leaves(jitted),
                       jax.tree.leaves(host)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(a), np.asarray(c))
        assert np.asarray(a).dtype == np.asarray(c).dtype


def test_launch_resolve_without_explicit_start():
    """resolve() on a deferred launch auto-starts (a sync fallback
    path must never deadlock on a never-dispatched program)."""
    cfg = _dense_drop(n=16, ticks=30)
    sim = FleetSimulation(cfg)
    ref = Simulation(cfg).run_bench(seed=5)
    res = sim.launch_bench(seeds=[5, 6], warmup=False,
                           defer=True).resolve()
    assert np.array_equal(ref.sent, res.lanes[0].sent)
