"""Sharded overlay: bit parity with the single-device path.

The tick body is the same code parameterized by comm; over the
8-virtual-device CPU mesh (tests/conftest.py) a full run must produce
exactly the single-device trajectory — tables, vectors, and metrics.
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_run,
                                                make_overlay_schedule)
from gossip_protocol_tpu.models.overlay_sharded import (
    make_overlay_mesh, make_sharded_overlay_run, shard_overlay_state)


def _run_both(cfg, n_devices, use_pallas=None):
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)

    run_local = make_overlay_run(cfg)
    final_l, metrics_l = run_local(state, sched)

    mesh = make_overlay_mesh(n_devices)
    run_sharded = make_sharded_overlay_run(cfg, mesh, use_pallas=use_pallas)
    final_s, metrics_s = run_sharded(shard_overlay_state(state, mesh), sched)
    return (final_l, metrics_l), (final_s, metrics_s)


STATE_FIELDS = ("ids", "hb", "ts", "send_flags", "in_group", "own_hb",
                "joinreq", "joinrep", "tick")


def _assert_equal(fl, ml, fs, ms):
    import dataclasses
    for field in STATE_FIELDS:
        a = np.asarray(getattr(fl, field))
        b = np.asarray(getattr(fs, field))
        assert np.array_equal(a, b), field
    for f in dataclasses.fields(type(ml)):
        a = np.asarray(getattr(ml, f.name))
        b = np.asarray(getattr(ms, f.name))
        assert np.array_equal(a, b), f.name


@pytest.mark.parametrize("n_devices", [2, 8])
@pytest.mark.parametrize("scenario", ["plain", "drop", "churn"])
def test_sharded_bit_parity(n_devices, scenario):
    kw = dict(model="overlay", max_nnb=64, seed=3, total_ticks=90,
              single_failure=True, drop_msg=False, fail_tick=30)
    if scenario == "drop":
        kw.update(drop_msg=True, msg_drop_prob=0.15, drop_open_tick=10,
                  drop_close_tick=70)
    elif scenario == "churn":
        kw.update(single_failure=False, churn_rate=0.3, rejoin_after=20,
                  total_ticks=120)
    cfg = SimConfig(**kw)
    (fl, ml), (fs, ms) = _run_both(cfg, n_devices)
    _assert_equal(fl, ml, fs, ms)


def test_sharded_rejects_non_power_of_two_mesh():
    from gossip_protocol_tpu.models.overlay_sharded import RingOverlayComm
    with pytest.raises(AssertionError, match="power of two"):
        RingOverlayComm("peers", 3)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_sharded_kernel_bit_parity(n_devices):
    """The fused Pallas kernel under shard_map (interpret mode on the
    virtual mesh): comm ppermutes the exchange's shard bits, the
    kernel applies the local bits — bit-identical to the XLA
    single-device trajectory (round-2 verdict task 2)."""
    cfg = SimConfig(model="overlay", max_nnb=128, seed=7, total_ticks=90,
                    single_failure=True, drop_msg=True, msg_drop_prob=0.1,
                    fail_tick=40, drop_open_tick=10, drop_close_tick=80,
                    step_rate=0.5)
    (fl, ml), (fs, ms) = _run_both(cfg, n_devices, use_pallas=True)
    _assert_equal(fl, ml, fs, ms)


@pytest.mark.slow
def test_sharded_kernel_parity_n1024():
    """Non-toy sharded kernel shapes: Nl = 128 spans multiple 8-row
    sublane tiles and multi-block index maps (round-2 verdict task 5:
    block-geometry interactions only appear past toy N)."""
    cfg = SimConfig(model="overlay", max_nnb=1024, seed=9, total_ticks=60,
                    single_failure=True, drop_msg=False, fail_tick=30,
                    step_rate=4.0)
    (fl, ml), (fs, ms) = _run_both(cfg, 8, use_pallas=True)
    _assert_equal(fl, ml, fs, ms)


@pytest.mark.slow
def test_sharded_invariants_n4096():
    """8-device sharded overlay at N=4096 (~60 ticks): join
    completeness over the ramp prefix, victim purge by the horizon,
    and union coverage of live members on the final state — the
    invariant gates bench.py applies, at a shard geometry where
    _xor_factors splits and ring-merge block sizes actually vary
    (round-2 verdict task 5)."""
    n = 4096
    cfg = SimConfig(model="overlay", max_nnb=n, seed=2, total_ticks=64,
                    single_failure=True, drop_msg=False, fail_tick=20,
                    step_rate=8.0 / n)   # everyone starts by tick 8
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)
    mesh = make_overlay_mesh(8)
    run = make_sharded_overlay_run(cfg, mesh)
    final, metrics = run(shard_overlay_state(state, mesh), sched)
    in_group = np.asarray(metrics.in_group)
    assert in_group[-1] == n, "join incomplete on the sharded mesh"
    assert np.asarray(metrics.victim_slots)[-1] == 0, "victim not purged"
    # final-state union coverage of live members
    from gossip_protocol_tpu.models.overlay import OverlayResult
    res = OverlayResult(cfg=cfg, sched=sched, final_state=final,
                        metrics=jax.tree.map(np.asarray, metrics),
                        wall_seconds=0.0)
    uncovered, victims_left = res.final_coverage()
    assert victims_left == 0
    assert uncovered == 0, f"{uncovered} live members uncovered"
