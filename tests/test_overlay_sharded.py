"""Sharded overlay: bit parity with the single-device path.

The tick body is the same code parameterized by comm; over the
8-virtual-device CPU mesh (tests/conftest.py) a full run must produce
exactly the single-device trajectory — tables, vectors, and metrics.
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                make_overlay_run,
                                                make_overlay_schedule)
from gossip_protocol_tpu.models.overlay_sharded import (
    make_overlay_mesh, make_sharded_overlay_run, shard_overlay_state)


def _run_both(cfg, n_devices):
    sched = make_overlay_schedule(cfg)
    state = init_overlay_state(cfg)

    run_local = make_overlay_run(cfg)
    final_l, metrics_l = run_local(state, sched)

    mesh = make_overlay_mesh(n_devices)
    run_sharded = make_sharded_overlay_run(cfg, mesh)
    final_s, metrics_s = run_sharded(shard_overlay_state(state, mesh), sched)
    return (final_l, metrics_l), (final_s, metrics_s)


@pytest.mark.parametrize("n_devices", [2, 8])
@pytest.mark.parametrize("scenario", ["plain", "drop", "churn"])
def test_sharded_bit_parity(n_devices, scenario):
    kw = dict(model="overlay", max_nnb=64, seed=3, total_ticks=90,
              single_failure=True, drop_msg=False, fail_tick=30)
    if scenario == "drop":
        kw.update(drop_msg=True, msg_drop_prob=0.15, drop_open_tick=10,
                  drop_close_tick=70)
    elif scenario == "churn":
        kw.update(single_failure=False, churn_rate=0.3, rejoin_after=20,
                  total_ticks=120)
    cfg = SimConfig(**kw)
    (fl, ml), (fs, ms) = _run_both(cfg, n_devices)

    for field in ("ids", "hb", "ts", "send_flags", "in_group", "own_hb",
                  "joinreq", "joinrep", "tick"):
        a = np.asarray(getattr(fl, field))
        b = np.asarray(getattr(fs, field))
        assert np.array_equal(a, b), field
    import dataclasses
    for f in dataclasses.fields(type(ml)):
        a = np.asarray(getattr(ml, f.name))
        b = np.asarray(getattr(ms, f.name))
        assert np.array_equal(a, b), f.name


def test_sharded_rejects_non_power_of_two_mesh():
    from gossip_protocol_tpu.models.overlay_sharded import RingOverlayComm
    with pytest.raises(AssertionError, match="power of two"):
        RingOverlayComm("peers", 3)
