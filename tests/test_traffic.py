"""Open-loop traffic plane (PR 7): seeded arrival schedules
(service/traffic.py), SLO-aware scheduling (service/slo.py), and the
load harness's determinism gate (service/loadbench.py).

The contracts under test:

* **arrival purity** — every arrival is a pure function of
  ``(seed, index)``: the same seed reproduces the identical schedule,
  a longer schedule extends (never rewrites) a shorter one's prefix,
  and the closed kind degenerates to the PR-3 replay trace exactly;
* **deadline-aware early flush** — a partial bucket with a tight
  deadline dispatches BEFORE ``max_wait`` when the SLO scheduler is
  on, the identical run with it off misses the deadline, and the
  early-flushed batch stays bit-identical to solo runs;
* **determinism under load** — a virtual-clock traffic run (harvest
  pinned off, wall estimate pinned) replays outcome-digest-for-digest,
  INCLUDING with a chaos injector driving faults under the arrivals;
* **quotas** — per-tenant admission sheds typed, never drops queued
  work, and is invisible to other tenants.
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.service import (ClassPolicy, DeadlineExceeded,
                                         FaultInjector, FleetService,
                                         RetryPolicy, SLOPolicy,
                                         Template, TenantQuotaExceeded,
                                         TrafficPattern, VirtualClock,
                                         build_trace, closed_schedule,
                                         make_schedule, outcome_digest,
                                         run_schedule)

pytestmark = [pytest.mark.service, pytest.mark.traffic]


def _dense_churn(n=16, ticks=22):
    return SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                     seed=0, total_ticks=ticks, fail_tick=20,
                     rejoin_after=15)


def _dense_drop(n=16, ticks=26):
    return SimConfig(max_nnb=n, single_failure=True, drop_msg=True,
                     msg_drop_prob=0.1, seed=0, total_ticks=ticks,
                     fail_tick=10)


def _catalog():
    return [Template("dense-churn", _dense_churn()),
            Template("dense-drop", _dense_drop())]


def _slo(deadline=4.0, wall=0.3, **kw):
    kw.setdefault("assumed_dispatch_wall_s", wall)
    kw.setdefault("safety_factor", 1.0)
    return SLOPolicy(classes={"interactive": ClassPolicy(deadline_s=deadline,
                                                         weight=1.0)},
                     default_class="interactive", **kw)


# ---- arrival schedules are pure functions of (seed, index) ----------
def test_schedule_pure_function_of_seed():
    tpls = _catalog()
    kw = dict(pattern=TrafficPattern(kind="poisson", rate_rps=6.0),
              class_mix={"a": 0.5, "b": 0.5})
    s1 = make_schedule(tpls, 40, seed=5, **kw)
    s2 = make_schedule(tpls, 40, seed=5, **kw)
    assert s1.digest() == s2.digest()
    assert [a.t_s for a in s1.arrivals] == [a.t_s for a in s2.arrivals]
    # a different seed draws a different schedule
    assert make_schedule(tpls, 40, seed=6, **kw).digest() != s1.digest()
    # the per-index draw makes a longer schedule EXTEND a shorter one:
    # arrival i never depends on how many arrivals were asked for
    s_short = make_schedule(tpls, 15, seed=5, **kw)
    assert [(a.t_s, a.template.name, a.lane_seed, a.priority, a.tenant)
            for a in s_short.arrivals] == \
        [(a.t_s, a.template.name, a.lane_seed, a.priority, a.tenant)
         for a in s1.arrivals[:15]]
    # arrival times are strictly ordered and the mean gap tracks the
    # offered rate (loosely: 40 exponential draws)
    ts = np.asarray([a.t_s for a in s1.arrivals])
    assert (np.diff(ts) > 0).all()
    assert 0.4 * (40 / 6.0) < ts[-1] < 2.5 * (40 / 6.0)


def test_arrival_kinds():
    tpls = _catalog()
    # burst: the on-phase of each period is denser than the off-phase
    pat = TrafficPattern(kind="burst", rate_rps=8.0, burst_factor=3.0,
                         duty_cycle=0.25, period_s=4.0)
    s = make_schedule(tpls, 240, pattern=pat, seed=1)
    ts = np.asarray([a.t_s for a in s.arrivals])
    phase = (ts % 4.0) / 4.0
    on = int((phase < 0.25).sum())
    assert on > 0.45 * len(ts), (on, len(ts))   # ~0.75 expected at f=3
    # diurnal: the middle of the period is denser than the edges, and
    # the explicit period keeps the prefix invariant length-free
    pat = TrafficPattern(kind="diurnal", rate_rps=8.0,
                         diurnal_amplitude=0.75, diurnal_period_s=30.0)
    s = make_schedule(tpls, 240, pattern=pat, seed=1)
    ts = np.asarray([a.t_s for a in s.arrivals])
    span = ts[-1]
    mid = int(((ts > 0.25 * span) & (ts < 0.75 * span)).sum())
    assert mid > 0.55 * len(ts), mid
    s_short = make_schedule(tpls, 60, pattern=pat, seed=1)
    assert [a.t_s for a in s_short.arrivals] == \
        [a.t_s for a in s.arrivals[:60]]
    # closed: every arrival at t=0
    s = make_schedule(tpls, 10,
                      pattern=TrafficPattern(kind="closed"), seed=1)
    assert all(a.t_s == 0.0 for a in s.arrivals)
    with pytest.raises(ValueError, match="unknown arrival kind"):
        TrafficPattern(kind="pareto")
    with pytest.raises(ValueError, match="burst_factor"):
        TrafficPattern(kind="burst", burst_factor=10.0, duty_cycle=0.25)
    with pytest.raises(ValueError, match="diurnal_period_s"):
        TrafficPattern(kind="diurnal")   # length-derived default: no


def test_closed_schedule_is_the_replay_trace():
    """The closed-loop replay is the degenerate arrival schedule: the
    exact (template, seed) sequence build_trace produces, at t=0."""
    tpls = _catalog()
    sched = closed_schedule(tpls, seeds_per_template=4)
    trace = build_trace(tpls, 4)
    assert [(a.template.name, a.lane_seed) for a in sched.arrivals] == \
        [(t.name, s) for t, s in trace]
    assert sched.span_s == 0.0


# ---- deadline-aware early flush (satellite) --------------------------
def test_early_flush_dispatches_before_max_wait_with_parity():
    """A partial bucket holding a tight-deadline request dispatches
    EARLY — before max_wait, when deadline margin <= the estimated
    dispatch wall — and the early-flushed batch is bit-identical to
    solo runs."""
    cfg = _dense_churn()
    vc = VirtualClock()
    svc = FleetService(max_batch=8, max_wait_s=100.0, clock=vc,
                       sleep=vc.sleep, slo=_slo(deadline=1.0, wall=0.2),
                       pump_harvest=False)
    hs = [svc.submit(cfg, seed=s, priority="interactive")
          for s in (1, 2)]
    # at submit (t=0) the margin (1.0) exceeds the estimate (0.2):
    # NOT flushed — early flush is deadline-driven, not eager
    assert svc.pending == 2 and svc.stats()["slo_early_flushes"] == 0
    vc.t = 0.5
    assert svc.pump() == 0, "flushed while the deadline still had slack"
    vc.t = 0.85                      # margin 0.15 <= est 0.2: must go
    assert svc.pump() == 1
    assert svc.stats()["slo_early_flushes"] == 1
    svc.drain()
    sim = Simulation(cfg)
    for s, h in zip((1, 2), hs):
        assert h.status == "completed"
        assert not h.metrics.deadline_missed
        assert h.metrics.batch == 2 and h.metrics.padded_batch == 8
        assert np.array_equal(sim.run(seed=s).sent, h.result().sent), s


def test_without_slo_scheduling_the_same_run_misses():
    """The identical sequence with early flush OFF: the partial bucket
    sits past its deadline (max_wait is far away) and the requests
    expire — the miss the SLO scheduler exists to prevent."""
    cfg = _dense_churn()
    vc = VirtualClock()
    svc = FleetService(max_batch=8, max_wait_s=100.0, clock=vc,
                       sleep=vc.sleep,
                       slo=_slo(deadline=1.0, wall=0.2,
                                early_flush=False),
                       pump_harvest=False)
    hs = [svc.submit(cfg, seed=s, priority="interactive")
          for s in (1, 2)]
    vc.t = 0.85
    assert svc.pump() == 0, "early flush fired with early_flush=False"
    vc.t = 1.1                       # past the deadline: queue expiry
    svc.pump()
    assert [h.status for h in hs] == ["failed", "failed"]
    with pytest.raises(DeadlineExceeded):
        hs[0].result()
    st = svc.stats()
    assert st["failures"]["deadline_misses"] == 2
    assert st["classes"]["interactive"]["deadline_misses"] == 2
    assert st["slo_early_flushes"] == 0


def test_priority_class_resolution_and_default_deadline():
    cfg = _dense_churn()
    vc = VirtualClock()
    slo = SLOPolicy(classes={"fast": ClassPolicy(deadline_s=5.0),
                             "bulk": ClassPolicy(deadline_s=None)},
                    default_class="bulk")
    svc = FleetService(max_batch=8, clock=vc, sleep=vc.sleep, slo=slo,
                       pump_harvest=False)
    h_fast = svc.submit(cfg, seed=1, priority="fast")
    h_bulk = svc.submit(cfg, seed=2)          # defaults to bulk
    assert h_fast.request.deadline_s == 5.0
    assert h_bulk.request.deadline_s is None
    assert h_bulk.request.priority == "bulk"
    # the policy OWNS deadlines: a deadline-less class stays
    # deadline-less even when the service carries a global default
    svc_dflt = FleetService(max_batch=8, clock=vc, sleep=vc.sleep,
                            slo=slo, default_deadline_s=5.0,
                            pump_harvest=False)
    assert svc_dflt.submit(cfg, seed=9).request.deadline_s is None
    svc_dflt.drain()
    with pytest.raises(ValueError, match="unknown priority class"):
        svc.submit(cfg, seed=3, priority="warp")
    # an explicit deadline overrides the class default
    h = svc.submit(cfg, seed=4, priority="fast", deadline_s=1.5)
    assert h.request.deadline_s == pytest.approx(vc.t + 1.5)
    svc.drain()


# ---- per-class stats windows (satellite) -----------------------------
def test_stats_split_per_priority_class():
    """stats() reports p50/p99 per priority class from per-class
    windows, without changing the existing aggregate fields."""
    cfg = _dense_churn()
    svc = FleetService(max_batch=2)
    [svc.submit(cfg, seed=s, priority="gold") for s in (1, 2)]
    [svc.submit(cfg, seed=s, priority="dirt") for s in (3, 4)]
    svc.drain()
    st = svc.stats()
    assert set(st["classes"]) == {"gold", "dirt"}
    for name in ("gold", "dirt"):
        c = st["classes"][name]
        assert c["completed"] == 2 and c["window"] == 2
        assert c["latency_p50_s"] > 0.0
        assert c["latency_p99_s"] >= c["latency_p50_s"]
        assert c["deadline_miss_rate"] == 0.0
    # the aggregate fields are still there, untouched in meaning
    for k in ("latency_p50_s", "latency_p95_s", "mean_occupancy",
              "program_hit_rate", "device_wait_frac"):
        assert k in st
    assert st["latency_p99_s"] >= st["latency_p50_s"]


# ---- tenant quotas (tentpole) ----------------------------------------
def test_tenant_quota_sheds_typed_and_isolated():
    cfg = _dense_churn()
    svc = FleetService(max_batch=8, tenant_quota=2)
    h1 = svc.submit(cfg, seed=1, tenant="acme")
    h2 = svc.submit(cfg, seed=2, tenant="acme")
    with pytest.raises(TenantQuotaExceeded, match="tenant 'acme'"):
        svc.submit(cfg, seed=3, tenant="acme")
    # another tenant (and untenanted traffic) is unaffected
    h3 = svc.submit(cfg, seed=4, tenant="globex")
    h4 = svc.submit(cfg, seed=5)
    st = svc.stats()
    assert st["failures"]["shed"] == 1
    assert st["tenant_shed"] == {"acme": 1}
    svc.drain()                   # nothing queued was dropped
    assert all(h.status == "completed" for h in (h1, h2, h3, h4))
    assert h1.metrics.tenant == "acme"
    assert svc._tenant_queued == {}, "queued-count drifted after drain"
    # room again after the drain
    assert svc.submit(cfg, seed=6, tenant="acme").request.tenant == "acme"
    svc.drain()
    with pytest.raises(ValueError, match="tenant_quota"):
        FleetService(tenant_quota=0)


# ---- deterministic virtual-clock load runs ---------------------------
def _virtual_run(sched, injector_seed=None, fault_rate=0.0):
    vc = VirtualClock()
    inj = FaultInjector(seed=injector_seed, fault_rate=fault_rate) \
        if injector_seed is not None else None
    svc = FleetService(
        max_batch=4, max_wait_s=2.0, clock=vc, sleep=vc.sleep,
        slo=_slo(deadline=6.0, wall=0.25), pump_harvest=False,
        injector=inj,
        retry=RetryPolicy(max_retries=2, backoff_base_s=1e-3))
    handles, rec = run_schedule(svc, sched, pace="virtual", clock=vc)
    dig = outcome_digest(sched, handles, rec["sheds"])
    fault_dig = inj.schedule_digest() if inj is not None else None
    return handles, dig, fault_dig


def test_virtual_load_run_replays_digest_for_digest():
    tpls = _catalog()
    sched = make_schedule(tpls, 14,
                          TrafficPattern(kind="burst", rate_rps=6.0),
                          seed=9, class_mix={"interactive": 1.0})
    h1, d1, _ = _virtual_run(sched)
    h2, d2, _ = _virtual_run(sched)
    assert d1 == d2
    assert all(h.done for h in h1)
    # and the served lanes are bit-identical to solo runs
    a = sched.arrivals[0]
    ref = Simulation(a.template.cfg).run(seed=a.lane_seed)
    assert np.array_equal(h1[0].result().sent, ref.sent)


def test_chaos_seed_replays_under_load_generator():
    """Satellite regression: a chaos seed stays digest-for-digest
    replayable while the load generator drives arrivals — the idle
    harvest is off (injector active AND pump_harvest=False), the
    traffic clock advances purely per the schedule, and fault draws
    sit at fixed points of the submit/flush sequence."""
    tpls = _catalog()
    sched = make_schedule(tpls, 14,
                          TrafficPattern(kind="poisson", rate_rps=6.0),
                          seed=9, class_mix={"interactive": 1.0})
    h1, d1, f1 = _virtual_run(sched, injector_seed=11, fault_rate=0.3)
    h2, d2, f2 = _virtual_run(sched, injector_seed=11, fault_rate=0.3)
    assert f1 == f2, "fault schedule diverged under the load generator"
    assert d1 == d2, "outcomes diverged under the load generator"
    assert all(h.done for h in h1)
    # the schedule must actually have injected something for the test
    # to mean anything
    assert f1 is not None
    # a different chaos seed still terminates everything (validity is
    # seed-independent; only the schedule changes)
    h3, _, f3 = _virtual_run(sched, injector_seed=12, fault_rate=0.3)
    assert all(h.done for h in h3)
    assert all(h.done for h in h2)


def test_virtual_pacing_guards():
    """Virtual pacing refuses wall-dependent setups loudly: a service
    on a real clock, or one whose idle harvest is still enabled."""
    tpls = _catalog()
    sched = make_schedule(tpls, 3, seed=1)
    svc = FleetService(max_batch=4)
    with pytest.raises(ValueError, match="VirtualClock"):
        run_schedule(svc, sched, pace="virtual")
    vc = VirtualClock()
    svc = FleetService(max_batch=4, clock=vc, sleep=vc.sleep)
    with pytest.raises(ValueError, match="pump_harvest"):
        run_schedule(svc, sched, pace="virtual", clock=vc)
    with pytest.raises(ValueError, match="unknown pace"):
        run_schedule(svc, sched, pace="warp")
    svc.drain()
    # an UNPINNED early-flush wall estimate is wall-dependent too:
    # virtual pacing refuses it unless the policy pins the estimate
    # (or early flush is off)
    vc = VirtualClock()
    svc = FleetService(max_batch=4, clock=vc, sleep=vc.sleep,
                       pump_harvest=False,
                       slo=_slo(deadline=5.0, wall=None))
    with pytest.raises(ValueError, match="assumed_dispatch_wall_s"):
        run_schedule(svc, sched, pace="virtual", clock=vc)
    svc.drain()


def test_pump_harvest_false_pins_idle_harvest_off():
    """pump_harvest=False: an idle pump never resolves the in-flight
    batch (the wall-dependent readiness poll is off); flush still
    does."""
    import time as _time
    cfg = _dense_churn()
    svc = FleetService(max_batch=2, pipeline=True, pump_harvest=False)
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    assert svc.in_flight == 2
    deadline = _time.perf_counter() + 2.0
    while _time.perf_counter() < deadline and \
            not all(i.pending.is_ready()
                    for i in svc._inflight_batches()):
        _time.sleep(0.01)
    assert svc.pump() == 0
    assert svc.in_flight == 2, "idle pump harvested with harvest off"
    svc.flush()
    assert all(h.status == "completed" for h in hs)


# ---- wall pacing + the loadbench determinism gate --------------------
def test_wall_paced_open_loop_run_terminates():
    tpls = _catalog()
    sched = make_schedule(tpls, 6,
                          TrafficPattern(kind="poisson", rate_rps=50.0),
                          seed=2)
    svc = FleetService(max_batch=4)
    handles, rec = run_schedule(svc, sched, pace="wall")
    assert all(h is not None and h.done for h in handles)
    assert rec["wall_s"] > 0.0 and rec["sheds"] == []
    assert rec["max_lag_s"] >= 0.0


def test_loadbench_replay_check_deterministic():
    from gossip_protocol_tpu.service.loadbench import replay_check
    rc = replay_check(_catalog(), n_requests=8, rate_rps=6.0, seed=4,
                      slo=_slo(deadline=6.0, wall=0.25))
    assert rc["deterministic"], rc
    assert rc["runs"] == 2 and len(rc["arrival_digest"]) == 16


# ---- weighted fair queuing between SLO classes (PR 9 satellite) ------
def _wfq_slo(**weights):
    from gossip_protocol_tpu.service.slo import default_slo
    return default_slo(assumed_dispatch_wall_s=0.3).with_weights(
        weights or None)


def test_wfq_weights_validated():
    """Bad weight knobs fail at policy construction, typed."""
    with pytest.raises(ValueError, match="unknown classes"):
        _wfq_slo(nosuch=2.0)
    with pytest.raises(ValueError, match="> 0"):
        _wfq_slo(interactive=0.0)
    slo = _wfq_slo(interactive=8.0)
    assert slo.weight_of("interactive") == 8.0
    # classes absent from the mapping inherit their ClassPolicy weight
    assert slo.weight_of("standard") == slo.classes["standard"].weight
    # with_weights(None) restores tightest-deadline-first ordering
    assert slo.with_weights(None).weights is None


def test_wfq_orders_buckets_by_normalized_deficit():
    """With ``slo.weights`` set, pump order is least-served-per-weight
    first: after a dispatch is charged to the standard class, the
    heavy interactive bucket jumps ahead of the earlier-created
    standard one; without weights the earlier bucket keeps its
    tightest-deadline/FIFO place."""
    vc = VirtualClock()
    slo = _wfq_slo(interactive=8.0, standard=1.0)
    svc = FleetService(max_batch=4, max_wait_s=100.0, clock=vc,
                       sleep=vc.sleep, slo=slo, pump_harvest=False)
    # bucket A (standard) created first, bucket B (interactive) second
    svc.submit(_dense_churn(), seed=1, priority="standard")
    svc.submit(_dense_drop(), seed=1, priority="interactive")
    order0 = svc._pump_order()
    assert len(order0) == 2
    # zero service everywhere: deficit ties, creation order breaks it
    assert svc._dominant_class(svc._queues[order0[0]]) == "standard"
    # charge the standard class one dispatched lane; the interactive
    # bucket (deficit 0) must now order first despite its later birth
    svc._wfq_served["standard"] = 1.0
    order1 = svc._pump_order()
    assert svc._dominant_class(svc._queues[order1[0]]) == "interactive"
    # the normalization: 8 lanes of interactive service / weight 8
    # equals 1 lane of standard / weight 1 — back to creation order
    svc._wfq_served["interactive"] = 8.0
    order2 = svc._pump_order()
    assert svc._dominant_class(svc._queues[order2[0]]) == "standard"
    svc.drain()


def test_wfq_run_serves_all_and_reports_shares():
    """An end-to-end WFQ run: every handle terminal, per-class service
    counters reported, results bit-identical to solo runs."""
    slo = _wfq_slo(interactive=8.0)
    svc = FleetService(max_batch=2, slo=slo)
    hs = [svc.submit(_dense_churn(), seed=s, priority=p)
          for s in (1, 2) for p in ("interactive", "batch")]
    svc.drain()
    assert all(h.status == "completed" for h in hs)
    st = svc.stats()
    assert st["wfq_served"]["interactive"] == 2.0
    assert st["wfq_served"]["batch"] == 2.0
    for h in hs:
        ref = Simulation(h.request.cfg).run()
        got = h.result()
        assert np.array_equal(ref.added, got.added)
        assert np.array_equal(ref.removed, got.removed)


def test_wfq_virtual_load_replays_digest_for_digest():
    """WFQ ordering is deterministic on a virtual clock: the same
    seeded arrival schedule re-driven under weights replays
    outcome-digest-for-digest (the loadbench wfq A/B's gate)."""
    tpls = _catalog()
    sched = make_schedule(tpls, 10,
                          TrafficPattern(kind="poisson", rate_rps=8.0),
                          seed=6, class_mix={"interactive": 0.5,
                                             "standard": 0.5})
    digs = []
    for _ in range(2):
        vc = VirtualClock()
        svc = FleetService(max_batch=4, clock=vc, sleep=vc.sleep,
                           slo=_wfq_slo(interactive=8.0),
                           pump_harvest=False)
        handles, rec = run_schedule(svc, sched, pace="virtual")
        assert all(h is not None and h.done for h in handles)
        digs.append(outcome_digest(sched, handles, rec["sheds"]))
    assert digs[0] == digs[1]
