"""Native runtime tests: bus semantics, engine grading, and differential
parity between the C++ engine and the JAX engine.

The native layer is built via make (skipped gracefully if no toolchain).
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from gossip_protocol_tpu.compat import native
from gossip_protocol_tpu.grader import grade_multi, grade_single

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ toolchain")


@pytest.fixture(scope="session")
def lib():
    lib = native.load(auto_build=True)
    if lib is None:
        pytest.skip("native library failed to build")
    return lib


# ---- bus -------------------------------------------------------------

def test_bus_store_and_forward_order(lib):
    with native.NativeBus(4, 10) as bus:
        ids = [bus.init() for _ in range(4)]
        assert ids == [0, 1, 2, 3]
        assert bus.send(0, 1, b"first", tick=0)
        assert bus.send(2, 1, b"second", tick=0)
        assert bus.send(0, 3, b"other", tick=0)
        assert bus.inflight == 3
        msgs = bus.recv(1, tick=1)
        assert msgs == [b"first", b"second"]  # send order preserved
        assert bus.recv(1, tick=1) == []      # drained
        assert bus.inflight == 1


def test_bus_silent_drop_conditions(lib):
    # oversize (EmulNet.cpp:93 analogue)
    with native.NativeBus(2, 4, max_msg_size=8) as bus:
        bus.init(), bus.init()
        assert not bus.send(0, 1, b"x" * 9, tick=0)
        assert bus.send(0, 1, b"x" * 8, tick=0)
    # buffer full (EmulNet.cpp:92 analogue)
    with native.NativeBus(2, 4, max_inflight=2) as bus:
        bus.init(), bus.init()
        assert bus.send(0, 1, b"a", tick=0)
        assert bus.send(0, 1, b"b", tick=0)
        assert not bus.send(0, 1, b"c", tick=0)
    # invalid destination
    with native.NativeBus(2, 4) as bus:
        bus.init(), bus.init()
        assert not bus.send(0, 5, b"a", tick=0)


def test_bus_drop_probability_and_determinism(lib):
    kw = dict(max_nodes=2, total_ticks=1000, drop_prob=0.3, seed=42)
    sent = []
    for _ in range(2):
        with native.NativeBus(**kw) as bus:
            bus.init(), bus.init()
            ok = [bus.send(0, 1, b"m", tick=t % 1000, drop_active=True)
                  for t in range(2000)]
            sent.append(ok)
    assert sent[0] == sent[1]  # seeded => reproducible
    rate = 1 - np.mean(sent[0])
    assert 0.25 < rate < 0.35  # Bernoulli(0.3)
    # outside the window nothing drops
    with native.NativeBus(**kw) as bus:
        bus.init(), bus.init()
        assert all(bus.send(0, 1, b"m", tick=0, drop_active=False)
                   for _ in range(100))


def test_bus_accounting_matches_python_formatter(lib, tmp_path):
    """msgcount.log written by the native bus must match the Python
    formatter byte-for-byte on the same counter matrices."""
    from gossip_protocol_tpu.logging_compat import format_msgcount
    with native.NativeBus(3, 5) as bus:
        for _ in range(3):
            bus.init()
        bus.send(0, 1, b"a", tick=0)
        bus.send(0, 2, b"b", tick=1)
        bus.send(1, 0, b"c", tick=1)
        bus.recv(1, tick=1)
        bus.recv(0, tick=2)
        bus.recv(2, tick=2)
        assert bus.cleanup(str(tmp_path))
        sent, recv = bus.counters()
    native_text = (tmp_path / "msgcount.log").read_text()
    assert native_text == format_msgcount(sent, recv)
    assert sent[0].sum() == 2 and recv[0].sum() == 1


# ---- engine: grading -------------------------------------------------

@pytest.mark.parametrize("conf,kind", [
    ("singlefailure", "single"),
    ("multifailure", "multi"),
    ("msgdropsinglefailure", "drop"),
])
def test_native_engine_grades_full_marks(lib, tmp_path, testcases_dir,
                                         conf, kind):
    rc = native.run_conf(os.path.join(testcases_dir, f"{conf}.conf"),
                         seed=3, outdir=str(tmp_path))
    assert rc == 0
    dbg = str(tmp_path / "dbg.log")
    if kind == "single":
        g = grade_single(dbg)
        assert g.points == 30, g.detail
    elif kind == "multi":
        g = grade_multi(dbg)
        assert g.points == 30, g.detail
    else:
        g = grade_single(dbg, join_pts=15, comp_pts=15, acc_pts=None)
        assert g.points == 30, g.detail
    # the msgcount/stats files exist alongside
    assert (tmp_path / "msgcount.log").exists()
    assert (tmp_path / "stats.log").exists()


def test_native_engine_detection_latency(lib, tmp_path):
    """Failure at t=100 must be removed by every survivor at exactly
    t = 100 + TREMOVE + 1 = 121 in the drop-free scenario (BASELINE.md)."""
    fail = np.full(10, np.iinfo(np.int32).max, np.int32)
    fail[4] = 100
    rc = native.run_scenario(10, True, False, 0.0, 700, seed=0,
                             fail_ticks=fail, outdir=str(tmp_path))
    assert rc == 0
    lines = [ln for ln in (tmp_path / "dbg.log").read_text().splitlines()
             if "removed" in ln]
    assert len(lines) == 9
    assert all("[121] Node 5.0.0.0:0 removed at time 121" in ln
               for ln in lines)


# ---- engine vs JAX engine: differential parity -----------------------

def _jax_events(cfg, fail_ticks, rejoin_ticks=None):
    import jax.numpy as jnp

    from gossip_protocol_tpu.core.sim import Simulation
    from gossip_protocol_tpu.state import make_schedule

    sim = Simulation(cfg)
    sched = make_schedule(cfg)
    sched = sched.replace(fail_tick=jnp.asarray(fail_ticks))
    if rejoin_ticks is not None:
        sched = sched.replace(rejoin_tick=jnp.asarray(rejoin_ticks))
    # re-run with the pinned schedule
    from gossip_protocol_tpu.state import init_state
    state = init_state(cfg)
    run = sim._trace_run_fn(cfg.total_ticks)
    state, ev = run(state, sched)
    return np.asarray(ev.added), np.asarray(ev.removed)


def _parse_native_events(dbg_path):
    """dbg.log -> ({(observer, subject, tick)} joins, {...} removals)."""
    import re
    adds, rems = set(), set()
    for ln in dbg_path.read_text().splitlines():
        m = re.match(r" (\d+)\.0\.0\.0:0 \[(\d+)\] Node (\d+)\.0\.0\.0:0 "
                     r"(joined|removed)", ln)
        if m:
            obs, t, subj, kind = (int(m.group(1)) - 1, int(m.group(2)),
                                  int(m.group(3)) - 1, m.group(4))
            (adds if kind == "joined" else rems).add((obs, subj, t))
    return adds, rems


@pytest.mark.parametrize("single", [True, False])
def test_native_vs_jax_event_parity(lib, tmp_path, single):
    """With an identical (pinned) failure schedule and no message drops,
    the native message-level engine and the batched JAX engine must
    produce the identical set of (observer, subject, tick) join and
    removal events."""
    from gossip_protocol_tpu.config import SimConfig

    n, t_total = 10, 200
    cfg = SimConfig(max_nnb=n, single_failure=single, drop_msg=False,
                    seed=0, total_ticks=t_total)
    fail = np.full(n, np.iinfo(np.int32).max, np.int32)
    if single:
        fail[6] = 100
    else:
        fail[2:7] = 100

    rc = native.run_scenario(n, single, False, 0.0, t_total, seed=0,
                             fail_ticks=fail, outdir=str(tmp_path))
    assert rc == 0
    adds_native, rems_native = _parse_native_events(tmp_path / "dbg.log")

    # the JAX event masks are (t, observer, subject)
    added, removed = _jax_events(cfg, fail)
    adds_jax = {(int(i), int(j), int(t)) for t, i, j in zip(*np.nonzero(added))}
    rems_jax = {(int(i), int(j), int(t)) for t, i, j in zip(*np.nonzero(removed))}

    assert adds_native == adds_jax
    assert rems_native == rems_jax


def test_native_vs_jax_start_after_fail_parity(lib, tmp_path):
    """Peers whose start tick falls after their (pinned, early) fail tick
    are still introduced — the reference's introduction branch does not
    check bFailed (Application.cpp:142-147) — and both engines must emit
    the identical posthumous join/removal events for them."""
    from gossip_protocol_tpu.config import SimConfig

    n, t_total = 24, 80
    cfg = SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                    seed=0, total_ticks=t_total)
    fail = np.full(n, np.iinfo(np.int32).max, np.int32)
    fail[16:24] = 3            # starts are int(0.25*i) in [4, 5] > 3

    rc = native.run_scenario(n, False, False, 0.0, t_total, seed=0,
                             fail_ticks=fail, outdir=str(tmp_path))
    assert rc == 0
    adds_native, rems_native = _parse_native_events(tmp_path / "dbg.log")

    added, removed = _jax_events(cfg, fail)
    adds_jax = {(int(i), int(j), int(t)) for t, i, j in zip(*np.nonzero(added))}
    rems_jax = {(int(i), int(j), int(t)) for t, i, j in zip(*np.nonzero(removed))}
    assert adds_native == adds_jax
    assert rems_native == rems_jax
    # the posthumous members were admitted by the introducer and removed
    # TREMOVE + 1 ticks after their start by every live peer
    for j in range(16, 24):
        s = int(0.25 * j)
        assert (0, j, s + 1) in adds_native
        assert (0, j, s + cfg.t_remove + 1) in rems_native


def test_hash_uniform_python_native_parity(lib):
    """utils/prng.py must be the bit-exact twin of gossip::HashUniform."""
    from gossip_protocol_tpu.utils.prng import hash_uniform
    for seed, a, b, c, d in [(0, 0, 0, 0, 7), (42, 1, 2, 3, 4),
                             (2**63, 699, 999, 1023, 0),
                             (123456789, 0, 9, 0, 2)]:
        assert hash_uniform(seed, a, b, c, d) == native.hash_uniform(
            seed, a, b, c, d)


def test_same_seed_same_failure_schedule(lib, tmp_path):
    """The same seed must pick the same failure victims on both backends
    (the native engine is the differential oracle; schedules must line
    up without pinning)."""
    from gossip_protocol_tpu.config import SimConfig
    from gossip_protocol_tpu.state import make_schedule

    for seed, single in [(3, True), (3, False), (11, True), (11, False)]:
        cfg = SimConfig(max_nnb=10, single_failure=single, seed=seed)
        expect = np.asarray(make_schedule(cfg).fail_tick)
        rc = native.run_scenario(10, single, False, 0.0, 150, seed=seed,
                                 outdir=str(tmp_path))
        assert rc == 0
        failed = sorted(
            int(ln.split(".")[0]) - 1
            for ln in (tmp_path / "dbg.log").read_text().splitlines()
            if "Node failed at time" in ln)
        assert failed == sorted(np.nonzero(expect == cfg.fail_tick)[0])


# ---- Application binary + reference grading harness ------------------

@pytest.fixture(scope="session")
def app_binary():
    res = subprocess.run(["make", "Application"], cwd=REPO,
                         capture_output=True, timeout=300)
    if res.returncode != 0:
        pytest.skip(f"Application build failed: {res.stderr.decode()[-500:]}")
    return os.path.join(REPO, "Application")


def test_application_native_backend(app_binary, tmp_path, testcases_dir):
    res = subprocess.run(
        [app_binary, os.path.join(testcases_dir, "singlefailure.conf"),
         "--backend=native"],
        cwd=tmp_path, capture_output=True, timeout=60)
    assert res.returncode == 0, res.stderr.decode()[-500:]
    assert b"0-th introduced node" in res.stdout
    g = grade_single(str(tmp_path / "dbg.log"))
    assert g.points == 30


def test_reference_grader_sh_passes(app_binary, tmp_path, testcases_dir):
    """The reference's own Grader.sh (run unmodified from its read-only
    mount) must award the maximum 90 against this framework's binary."""
    grader = "/root/reference/Grader.sh"
    if not os.path.exists(grader):
        pytest.skip("reference Grader.sh not mounted")
    env = dict(os.environ, GOSSIP_BACKEND="native")
    res = subprocess.run(["bash", grader], cwd=REPO, env=env,
                         capture_output=True, timeout=600)
    out = res.stdout.decode()
    assert "Final grade 90" in out, out[-2000:]


def test_application_jax_backend_smoke(app_binary, tmp_path, testcases_dir):
    """The embedded-interpreter path: ./Application delegating the run to
    the JAX engine must produce a grader-clean dbg.log."""
    env = dict(os.environ)
    env.pop("GOSSIP_BACKEND", None)
    env["GOSSIP_TPU_PLATFORM"] = "cpu"   # keep the test off the TPU tunnel
    res = subprocess.run(
        [app_binary, os.path.join(testcases_dir, "singlefailure.conf"),
         "--quiet"],
        cwd=tmp_path, env=env, capture_output=True, timeout=300)
    assert res.returncode == 0, res.stderr.decode()[-1000:]
    g = grade_single(str(tmp_path / "dbg.log"))
    assert g.points == 30, (tmp_path / "dbg.log").read_text()[:500]


@pytest.mark.parametrize("rejoin_after", [40, 10])
def test_native_vs_jax_churn_parity(lib, tmp_path, rejoin_after):
    """The churn extension on both engines: a pinned fail+rejoin
    schedule must produce the identical (observer, subject, tick) join
    and removal event sets — covering both the late rejoin (peer was
    removed, re-admitted with fresh join events) and the quick rejoin
    (old entries refreshed in place, no removals at all)."""
    from gossip_protocol_tpu.config import SimConfig

    n, t_total = 16, 160
    cfg = SimConfig(max_nnb=n, single_failure=True, drop_msg=False,
                    seed=2, total_ticks=t_total, fail_tick=30,
                    rejoin_after=rejoin_after)
    fail = np.full(n, np.iinfo(np.int32).max, np.int32)
    rejoin = np.full(n, np.iinfo(np.int32).max, np.int32)
    fail[5] = 30
    rejoin[5] = 30 + rejoin_after

    rc = native.run_scenario_churn(n, True, False, 0.0, t_total, seed=2,
                                   fail_ticks=fail, rejoin_ticks=rejoin,
                                   outdir=str(tmp_path))
    assert rc == 0
    adds_native, rems_native = _parse_native_events(tmp_path / "dbg.log")

    added, removed = _jax_events(cfg, fail, rejoin)
    adds_jax = {(int(i), int(j), int(t)) for t, i, j in zip(*np.nonzero(added))}
    rems_jax = {(int(i), int(j), int(t)) for t, i, j in zip(*np.nonzero(removed))}
    assert adds_native == adds_jax
    assert rems_native == rems_jax
    if rejoin_after == 40:
        # late rejoin: everyone removed the victim once and re-admitted it
        assert any(subj == 5 and t > 70 for (_, subj, t) in adds_native)
        assert {(obs, t) for (obs, subj, t) in rems_native if subj == 5}
    else:
        # quick rejoin inside TREMOVE: no removals at all
        assert not rems_native


def test_native_churn_rejects_collapsed_window(lib, tmp_path):
    """rejoin <= fail is invalid (same rule as make_schedule)."""
    fail = np.full(8, np.iinfo(np.int32).max, np.int32)
    rejoin = np.full(8, np.iinfo(np.int32).max, np.int32)
    fail[3] = 20
    rejoin[3] = 20
    with pytest.raises(ValueError, match="rejoin_ticks"):
        native.run_scenario_churn(8, True, False, 0.0, 60, seed=0,
                                  fail_ticks=fail, rejoin_ticks=rejoin,
                                  outdir=str(tmp_path))
