"""Mesh-parallel fleet: lane-axis sharding must be invisible in the
results (parallel/fleet_mesh.py).

The contract under test: a ``MeshFleetSimulation`` over D virtual CPU
devices replays every lane bit-for-bit against the single-device
fleet AND against solo runs — dense bench, dense trace, overlay XLA,
and (interpret mode) the grid-kernel path — because lanes are
embarrassingly parallel: the only shared carriers are the unbatched
clock and, within a bucket, the drop plane, both REPLICATED across
the mesh.  Plus the regressions that keep it fast and honest:

* the replicated drop plane keeps the drop ``lax.cond`` a real cond
  (a sharded/batched ``drop_active`` degrades it to a both-branches
  select — pinned by jaxpr op-count, not wall clock);
* a batch that does not divide the mesh is rejected with an
  actionable error, and the serving layer pads to shard-divisible
  widths (bit-parity through the padded mesh dispatch);
* mesh programs carry their own cache identity (the device-count
  cache-miss regression lives in tests/test_service.py).

conftest forces 8 virtual CPU devices
(``--xla_force_host_platform_device_count=8``); the guards below skip
cleanly when fewer are live, mirroring tests/test_sharded.py.
"""

import jax
import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.fleet import (FleetSimulation, _stack_scheds,
                                            _stack_states, stack_lanes)
from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.parallel.fleet_mesh import (MeshFleetSimulation,
                                                     make_lane_mesh)
from gossip_protocol_tpu.state import init_state, make_schedule


def needs_devices(d):
    return pytest.mark.skipif(
        jax.device_count() < d, reason=f"needs {d} (virtual) devices")


STATE_FIELDS = ("tick", "in_group", "own_hb", "known", "hb", "ts",
                "gossip", "joinreq", "joinrep")
OV_STATE_FIELDS = ("tick", "ids", "hb", "ts", "in_group", "own_hb",
                   "send_flags", "joinreq", "joinrep")
OV_METRIC_FIELDS = ("in_group", "view_slots", "adds", "removals",
                    "false_removals", "victim_slots", "sent", "recv")

SEEDS = [1, 2, 3, 4]


def _dense_churn(n=32, ticks=60):
    return SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                     seed=0, total_ticks=ticks, fail_tick=20,
                     rejoin_after=15)


def _dense_drop(n=24, ticks=40):
    return SimConfig(max_nnb=n, single_failure=True, drop_msg=True,
                     msg_drop_prob=0.1, seed=0, total_ticks=ticks,
                     fail_tick=15)


def _overlay_churn(n=64, ticks=64):
    return SimConfig(max_nnb=n, model="overlay", single_failure=False,
                     drop_msg=False, seed=0, total_ticks=ticks,
                     churn_rate=0.25, rejoin_after=16, step_rate=8.0 / n)


def _assert_state_equal(ref_state, lane_state, fields, ctx):
    for f in fields:
        a = np.asarray(getattr(ref_state, f))
        b = np.asarray(getattr(lane_state, f))
        assert np.array_equal(a, b), f"{ctx}: state field {f} diverged"


# ---- per-lane bit-parity across device counts ------------------------
@needs_devices(2)
@pytest.mark.parametrize("d", [2, 4])
def test_mesh_dense_bench_parity(d):
    """D-device mesh bench fleet == solo run_bench, per lane."""
    if jax.device_count() < d:
        pytest.skip(f"needs {d} devices")
    cfg = _dense_drop()
    mesh = MeshFleetSimulation(cfg, make_lane_mesh(d)).run_bench(seeds=SEEDS)
    sim = Simulation(cfg)
    assert mesh.batch == len(SEEDS)
    assert 0.0 < mesh.device_seconds <= mesh.wall_seconds
    for i, s in enumerate(SEEDS):
        ref = sim.run_bench(seed=s)
        lane = mesh.lanes[i]
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"D={d} lane {i}")
        assert np.array_equal(ref.sent, lane.sent), i
        assert np.array_equal(ref.recv, lane.recv), i


@needs_devices(2)
def test_mesh_dense_trace_parity():
    """Trace-mode mesh fleet: events (and so grades) match solo runs,
    whole and tick-chunked (chunking is a staging detail)."""
    cfg = _dense_drop()
    d = 2
    whole = MeshFleetSimulation(cfg, make_lane_mesh(d)).run(seeds=SEEDS)
    parts = MeshFleetSimulation(cfg, make_lane_mesh(d),
                                chunk_ticks=16).run(seeds=SEEDS)
    sim = Simulation(cfg)
    for i, s in enumerate(SEEDS):
        ref = sim.run(seed=s)
        for tag, lane in (("whole", whole.lanes[i]), ("chunk", parts.lanes[i])):
            assert np.array_equal(ref.added, lane.added), (tag, i)
            assert np.array_equal(ref.removed, lane.removed), (tag, i)
            assert np.array_equal(ref.sent, lane.sent), (tag, i)
            assert np.array_equal(ref.recv, lane.recv), (tag, i)
            _assert_state_equal(ref.final_state, lane.final_state,
                                STATE_FIELDS, f"{tag} lane {i}")


@needs_devices(2)
@pytest.mark.parametrize("d", [2, 4, 8])
def test_mesh_overlay_parity(d):
    """Overlay mesh fleet across device counts: states and metrics
    bit-equal to solo runs and to the single-device fleet (which
    tests/test_fleet.py pins against solo already)."""
    if jax.device_count() < d:
        pytest.skip(f"needs {d} devices")
    from gossip_protocol_tpu.models.overlay import OverlaySimulation
    cfg = _overlay_churn()
    seeds = list(range(1, 9))            # B=8 divides every tested D
    fleet = MeshFleetSimulation(cfg, make_lane_mesh(d)).run(seeds=seeds)
    for i, s in enumerate(seeds):
        ref = OverlaySimulation(cfg.replace(seed=s), use_pallas=False).run()
        lane = fleet.lanes[i]
        _assert_state_equal(ref.final_state, lane.final_state,
                            OV_STATE_FIELDS, f"D={d} lane {i}")
        for m in OV_METRIC_FIELDS:
            a = np.asarray(getattr(ref.metrics, m))
            b = np.asarray(getattr(lane.metrics, m))
            assert np.array_equal(a, b), f"D={d} lane {i}: metric {m}"
        # the fleet tick elides the coverage histogram, like mega/grid
        assert np.all(np.asarray(lane.metrics.live_uncovered) == -1)


@needs_devices(2)
def test_mesh_matches_grid_fleet_interpret():
    """The mesh fleet replays the batched grid kernel (interpret mode
    on CPU — the same kernel compiles on TPU) bit-for-bit per lane:
    the lane mesh and the leading-batch-grid-dimension kernel are two
    executions of one trajectory."""
    from gossip_protocol_tpu.models.overlay import (init_overlay_state,
                                                    make_overlay_schedule)
    from gossip_protocol_tpu.models.overlay_grid import make_grid_fleet_run
    cfg = _overlay_churn(ticks=32)       # two GRID_TICKS launches
    cfgs = [cfg.replace(seed=s) for s in (5, 6)]
    mesh = MeshFleetSimulation(cfg, make_lane_mesh(2)).run(
        configs=cfgs)
    scheds = [make_overlay_schedule(c) for c in cfgs]
    states = _stack_states([init_overlay_state(c) for c in cfgs])
    grid = make_grid_fleet_run(cfg, cfg.total_ticks, 2, block_rows=32,
                               start_tick=0)
    gf, gm = grid(states, stack_lanes(scheds))
    for i in range(2):
        lane = mesh.lanes[i]
        for f in OV_STATE_FIELDS:
            a = np.asarray(getattr(lane.final_state, f))
            b = np.asarray(getattr(gf, f)) if f == "tick" \
                else np.asarray(getattr(gf, f))[i]
            assert np.array_equal(a, b), f"lane {i}: state {f}"
        for m in OV_METRIC_FIELDS:
            a = np.asarray(getattr(lane.metrics, m))
            b = np.asarray(getattr(gm, m))[i]
            assert np.array_equal(a, b), f"lane {i}: metric {m}"


# ---- replicated drop plane (regression) ------------------------------
@needs_devices(2)
def test_mesh_shared_drop_plane_keeps_cond():
    """The SCHED_AXES_SHARED_DROP rule must survive sharding: with the
    drop plane replicated, the drop draw stays a real ``lax.cond`` in
    the mesh program's jaxpr; batching the plane per lane erases the
    cond (both branches inlined under a select) — the 2.6x regression
    PERF §9 measured.  Pinned by op-count, not wall clock.

    Since PR 10 the pin is enforced by the jaxpr auditor's
    ``cond-stays-cond`` rule (gossip_protocol_tpu/analysis/
    jaxpr_audit.py) over the registered ``mesh-dense-bench-d2``
    program; this wrapper keeps the original test name — and the
    string-grep history it carries — findable while delegating the
    actual check (recursive eqn walk instead of the old ``"cond["``
    substring count) to the rule engine."""
    from gossip_protocol_tpu.analysis import jaxpr_audit
    cfg = _dense_drop(n=16, ticks=30)
    sim = MeshFleetSimulation(cfg, make_lane_mesh(2))
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    scheds = [make_schedule(c) for c in cfgs]

    shared = sim._dense_bench_fn(2, cfg.n, True)
    jx_shared = jax.make_jaxpr(shared.jitted)(
        _stack_states([init_state(c) for c in cfgs]),
        _stack_scheds(scheds, True))
    batched = sim._dense_bench_fn(2, cfg.n, False)
    jx_batched = jax.make_jaxpr(batched.jitted)(
        _stack_states([init_state(c) for c in cfgs]),
        _stack_scheds(scheds, False))
    prog = jaxpr_audit.AuditedProgram(
        name="mesh-dense-bench-d2", provenance="tests/test_fleet_mesh",
        jaxpr=jx_shared, twin=jx_batched, min_cond=1,
        rules=("cond-stays-cond",))
    assert jaxpr_audit.audit_program(prog) == [], (
        "replicated drop plane no longer lowers to a real cond — the "
        "drop draw is running every tick as a both-branches select")
    # and the rule itself must BITE: a program whose plane batched
    # (the twin standing in for both builds) is a violation
    broken = jaxpr_audit.AuditedProgram(
        name="mesh-dense-bench-d2-batched",
        provenance="tests/test_fleet_mesh",
        jaxpr=jx_batched, twin=jx_batched, min_cond=1,
        rules=("cond-stays-cond",))
    assert jaxpr_audit.audit_program(broken), (
        "cond-stays-cond did not fire on a batched-plane program")


# ---- batch/mesh geometry ---------------------------------------------
@needs_devices(2)
def test_mesh_rejects_indivisible_batch():
    cfg = _overlay_churn()
    sim = MeshFleetSimulation(cfg, make_lane_mesh(2))
    with pytest.raises(ValueError, match="divide.*lanes"):
        sim.run(seeds=[1, 2, 3])
    with pytest.raises(ValueError, match="devices are available"):
        make_lane_mesh(jax.device_count() + 1)
    # foreign axis names are rejected once, at construction — only the
    # 1-D ("lanes",) and 2-D ("lanes", "peers") shapes serve (PR 19)
    from jax.sharding import Mesh
    with pytest.raises(ValueError, match="serving meshes are 1-D"):
        MeshFleetSimulation(cfg, Mesh(
            np.array(jax.devices()[:2]).reshape(2, 1), ("a", "b")))
    if jax.device_count() >= 4:
        from gossip_protocol_tpu.parallel.fleet_mesh import \
            make_lane_peer_mesh
        m2 = MeshFleetSimulation(cfg, make_lane_peer_mesh(2, 2))
        assert (m2.n_lanes, m2.n_peers) == (2, 2)
        with pytest.raises(ValueError, match="divide.*lanes"):
            m2.run(seeds=[1, 2, 3])


@needs_devices(2)
def test_mesh_service_shard_divisible_padding_parity():
    """A partial batch through a mesh service pads to a
    shard-divisible width and every real lane stays bit-identical to
    its solo run — the serving layer's mesh contract."""
    from gossip_protocol_tpu.service import FleetService
    cfg = _dense_churn(n=16, ticks=22)
    svc = FleetService(max_batch=2, mesh=make_lane_mesh(2))
    assert svc.capacity == 4
    handles = [svc.submit(cfg, seed=s) for s in (1, 2, 3)]
    svc.drain()
    sim = Simulation(cfg)
    for s, h in zip((1, 2, 3), handles):
        ref = sim.run(seed=s)
        lane = h.result()
        assert np.array_equal(ref.added, lane.added), s
        assert np.array_equal(ref.sent, lane.sent), s
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"seed {s}")
        m = h.metrics
        assert m.batch == 3 and m.padded_batch == 4
        assert m.padded_batch % 2 == 0


# ---- 2-D lanes x peers prototype (PR 14) -----------------------------
@needs_devices(8)
def test_lane_peer_mesh_parity_with_fleet():
    """The 2-D ``Mesh((lanes, peers))`` prototype — the fleet's
    vmapped dense tick with the RingComm peer exchange inside,
    composed via ``compose_lane_peer_specs`` — replays the 1-D lane
    fleet bit-for-bit: final states AND per-tick sent/recv counters.
    This is the program the static analyzer registers as
    ``mesh2d-lanes-peers`` and holds to the per-axis collective
    contract (analysis/sharding_flow.py)."""
    import dataclasses

    from gossip_protocol_tpu.parallel.fleet_mesh import (
        make_lane_peer_bench_fn, make_lane_peer_mesh)
    from gossip_protocol_tpu.state import WorldState

    cfg = SimConfig(max_nnb=16, total_ticks=30, drop_msg=True,
                    msg_drop_prob=0.1, single_failure=True)
    cfgs = [cfg.replace(seed=s) for s in (1, 2)]
    scheds = [make_schedule(c) for c in cfgs]

    def args():
        return (_stack_states([init_state(c) for c in cfgs]),
                _stack_scheds(scheds, True))

    mesh2 = make_lane_peer_mesh(2, 4)
    jitted = make_lane_peer_bench_fn(cfg, mesh2)
    out_states, (sent, recv) = jitted(*args())

    ref_fn = FleetSimulation(cfg)._dense_bench_fn(2, cfg.n, True)
    ref_states, (ref_sent, ref_recv) = ref_fn(*args())
    assert np.array_equal(np.asarray(sent), np.asarray(ref_sent))
    assert np.array_equal(np.asarray(recv), np.asarray(ref_recv))
    for f in dataclasses.fields(WorldState):
        assert np.array_equal(
            np.asarray(getattr(out_states, f.name)),
            np.asarray(getattr(ref_states, f.name))), \
            f"2-D state field {f.name} diverged"


@needs_devices(2)
def test_lane_peer_mesh_rejects_bad_shapes():
    """Actionable errors: too many devices asked for, a non-2-D mesh
    handed to the builder, a world that does not divide the peer
    axis."""
    from gossip_protocol_tpu.parallel.fleet_mesh import (
        make_lane_peer_bench_fn, make_lane_peer_mesh)
    with pytest.raises(ValueError, match="devices are available"):
        make_lane_peer_mesh(64, 64)
    cfg = _dense_drop(n=24)
    with pytest.raises(ValueError, match="2-D"):
        make_lane_peer_bench_fn(cfg, make_lane_mesh(2))
    if jax.device_count() >= 4:
        with pytest.raises(ValueError, match="does not divide"):
            # n=25 over 2 peers
            make_lane_peer_bench_fn(cfg.replace(max_nnb=25),
                                    make_lane_peer_mesh(2, 2))


# ---- 2-D production serving (PR 19) ----------------------------------
@needs_devices(8)
def test_mesh2d_dense_trace_and_bench_parity():
    """The production path: ``MeshFleetSimulation`` over a 2-D
    ``Mesh((lanes, peers))`` runs the peer-SHARDED dense program when
    the world width divides the peer axis (``_peer_comm``), and every
    lane — events, counters, final state — is bit-identical to its
    solo run and to the 1-D lane fleet."""
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    cfg = SimConfig(max_nnb=16, total_ticks=30, drop_msg=True,
                    msg_drop_prob=0.1, single_failure=True)
    mesh2 = make_lane_peer_mesh(2, 4)
    m2 = MeshFleetSimulation(cfg, mesh2)
    assert (m2.n_lanes, m2.n_peers) == (2, 4)
    assert m2._peer_comm(cfg.n) is not None      # n=16 % 4 == 0
    sim = Simulation(cfg)
    tr = m2.run(seeds=SEEDS)
    for i, s in enumerate(SEEDS):
        ref = sim.run(seed=s)
        lane = tr.lanes[i]
        assert np.array_equal(ref.added, lane.added), i
        assert np.array_equal(ref.removed, lane.removed), i
        assert np.array_equal(ref.sent, lane.sent), i
        assert np.array_equal(ref.recv, lane.recv), i
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"2-D trace lane {i}")
    bench = m2.run_bench(seeds=SEEDS)
    for i, s in enumerate(SEEDS):
        ref = sim.run_bench(seed=s)
        lane = bench.lanes[i]
        assert np.array_equal(ref.sent, lane.sent), i
        assert np.array_equal(ref.recv, lane.recv), i
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"2-D bench lane {i}")


@needs_devices(8)
def test_mesh2d_replicated_fallback_parity():
    """Worlds that do NOT divide the peer axis (and the overlay
    model) serve peer-REPLICATED — every peer shard runs the same
    deterministic program, so lanes still replay solo runs
    bit-for-bit."""
    from gossip_protocol_tpu.models.overlay import OverlaySimulation
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    mesh2 = make_lane_peer_mesh(2, 4)
    # dense n=10 (the grader width): 10 % 4 != 0 -> replicated
    cfg = SimConfig(max_nnb=10, total_ticks=30, drop_msg=True,
                    msg_drop_prob=0.1, single_failure=True)
    m2 = MeshFleetSimulation(cfg, mesh2)
    assert m2._peer_comm(cfg.n) is None
    sim = Simulation(cfg)
    tr = m2.run(seeds=SEEDS)
    for i, s in enumerate(SEEDS):
        ref = sim.run(seed=s)
        lane = tr.lanes[i]
        assert np.array_equal(ref.added, lane.added), i
        assert np.array_equal(ref.sent, lane.sent), i
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"replicated lane {i}")
    # overlay: no peer decomposition by construction
    ocfg = _overlay_churn()
    ov = MeshFleetSimulation(ocfg, mesh2).run(seeds=SEEDS[:2])
    for i, s in enumerate(SEEDS[:2]):
        ref = OverlaySimulation(ocfg.replace(seed=s),
                                use_pallas=False).run()
        lane = ov.lanes[i]
        _assert_state_equal(ref.final_state, lane.final_state,
                            OV_STATE_FIELDS, f"overlay 2-D lane {i}")
        for f in OV_METRIC_FIELDS:
            assert np.array_equal(np.asarray(getattr(ref.metrics, f)),
                                  np.asarray(getattr(lane.metrics, f))), f


@needs_devices(8)
def test_mesh2d_service_mixed_replay_parity():
    """FleetService over the 2-D mesh: a mixed dense stream
    (peer-sharded and peer-replicated buckets side by side) with
    every request bit-identical to its solo run; capacity follows the
    LANE axis only, and stats speak the 2-D shape."""
    from gossip_protocol_tpu.parallel.fleet_mesh import \
        make_lane_peer_mesh
    from gossip_protocol_tpu.service import FleetService
    mesh2 = make_lane_peer_mesh(2, 4)
    sharded = SimConfig(max_nnb=16, total_ticks=24, drop_msg=True,
                        msg_drop_prob=0.1, single_failure=True)
    replicated = _dense_churn(n=10, ticks=24)
    svc = FleetService(max_batch=2, mesh=mesh2)
    assert svc.capacity == 4            # 2 lanes x max_batch, not 8
    assert (svc.n_lanes, svc.n_peers) == (2, 4)
    handles = [(c, s, svc.submit(c, seed=s))
               for c in (sharded, replicated) for s in (1, 2, 3)]
    svc.drain()
    for c, s, h in handles:
        ref = Simulation(c).run(seed=s)
        lane = h.result()
        assert np.array_equal(ref.added, lane.added), (c.n, s)
        assert np.array_equal(ref.sent, lane.sent), (c.n, s)
        _assert_state_equal(ref.final_state, lane.final_state,
                            STATE_FIELDS, f"n={c.n} seed {s}")
    st = svc.stats()
    assert st["devices"] == 8 and st["lanes"] == 2 and st["peers"] == 4
    assert st["failed"] == 0 and st["failures"]["degraded_requests"] == 0
