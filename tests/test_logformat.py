"""Log grammar: dbg.log / stats.log / msgcount.log byte-level formats
(Log.cpp:44-131, EmulNet.cpp:184-220).  These files are the external
API that Grader.sh and the course harness grep."""

import os
import re

import numpy as np
import pytest

from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.events import LogEvent
from gossip_protocol_tpu.logging_compat import (format_events, format_msgcount,
                                                magic_line, write_dbg_log,
                                                write_msgcount_log)
from tests.conftest import scenario_cfg


def test_magic_line():
    # hex char-sum of "CS425" = 0x131 (Log.cpp:80-86)
    assert magic_line() == "131"


def test_event_line_grammar():
    evs = [LogEvent(0, 0, "APP"), LogEvent(1, 3, "Node 1.0.0.0:0 joined at time 3")]
    text = format_events(evs, bug_compat=False)
    lines = text.split("\n")
    assert lines[0] == "131"
    assert lines[1] == ""          # first event starts with its own \n
    assert lines[2] == " 1.0.0.0:0 [0] APP"
    assert lines[3] == " 2.0.0.0:0 [3] Node 1.0.0.0:0 joined at time 3"


def test_first_line_address_quirk():
    """The reference's first LOG call skips the address sprintf
    (Log.cpp:56-73), leaving the address blank — reproduced under
    bug_compat (see the committed reference dbg.log: ' [0] APP')."""
    evs = [LogEvent(0, 0, "APP"), LogEvent(1, 0, "APP")]
    lines = format_events(evs, bug_compat=True).split("\n")
    assert lines[2] == " [0] APP"
    assert lines[3] == " 2.0.0.0:0 [0] APP"


def test_end_to_end_dbg_log(tmp_path):
    cfg = scenario_cfg("singlefailure", seed=0)
    res = Simulation(cfg).run()
    res.write_logs(str(tmp_path))
    text = (tmp_path / "dbg.log").read_text()
    lines = text.split("\n")
    assert lines[0] == "131"
    # every event line matches the reference grammar
    pat = re.compile(r"^ (\d+\.\d+\.\d+\.\d+:\d+ )?\[\d+\] .+$")
    for ln in lines[2:]:
        assert pat.match(ln), repr(ln)
    # boot lines: one APP per node, forward order (Application.cpp:59-69)
    app = [ln for ln in lines if ln.endswith("APP")]
    assert len(app) == 10
    assert app[0] == " [0] APP"                 # quirk line
    assert app[1] == " 2.0.0.0:0 [0] APP"
    # the periodic driver heartbeat line (Application.cpp:156-160)
    assert any("@@time=500" in ln for ln in lines)
    # stats.log exists and is empty (Log.cpp:66-67, no #STATSLOG# producers)
    assert (tmp_path / "stats.log").read_text() == ""


def test_failed_line_formats(tmp_path):
    """'time=%d' for single failure vs 'time = %d' for multi
    (Application.cpp:184 vs :192)."""
    for scen, needle in [("singlefailure", "Node failed at time=100"),
                         ("multifailure", "Node failed at time = 100")]:
        res = Simulation(scenario_cfg(scen, seed=0)).run()
        res.write_logs(str(tmp_path))
        assert needle in (tmp_path / "dbg.log").read_text()


def test_msgcount_format():
    sent = np.zeros((2, 25), np.int32)
    recv = np.zeros((2, 25), np.int32)
    sent[0, 1], recv[0, 1] = 6, 3
    text = format_msgcount(sent, recv)
    lines = text.split("\n")
    assert lines[0].startswith("node   1  (   0,    0) (   6,    3)")
    # wraps after 10 entries with a 9-space hanging indent (EmulNet.cpp:206-208)
    assert lines[1].startswith("         ")
    assert "node   1 sent_total      6  recv_total      3" in text
    assert text.endswith("\n\n")


def test_msgcount_against_reference_shape(tmp_path):
    """Our msgcount.log for N=10/700 ticks must be line-structurally
    identical to the committed reference artifact."""
    ref_path = "/root/reference/msgcount.log"
    if not os.path.exists(ref_path):
        pytest.skip("reference C++ run artifact not present in this "
                    "image (external to the repo)")
    cfg = scenario_cfg("singlefailure", seed=0)
    res = Simulation(cfg).run()
    write_msgcount_log(res.sent, res.recv, str(tmp_path))
    ours = (tmp_path / "msgcount.log").read_text().split("\n")
    ref = open(ref_path).read().split("\n")
    assert len(ours) == len(ref)
    for a, b in zip(ours, ref):
        # same structure: collapse each padded number, compare skeletons
        norm = lambda s: re.sub(r"\s*\d+", " #", s)
        assert norm(a) == norm(b)
