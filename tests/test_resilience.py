"""Serving failure model (PR 5): deterministic fault injection
(service/faults.py), the resilience machinery that survives it
(service/resilience.py), and graceful mesh degradation
(parallel/fleet_mesh.py ``shrink_mesh``, service/cache.py
``rebind_mesh``).

The contracts under test:

* **atomicity** — a request popped for a dispatch always reaches a
  terminal state (completed / degraded / failed-with-typed-error);
  no handle is ever stranded ``pending``, whatever the dispatch did;
* **determinism** — the fault schedule is a pure function of
  ``(seed, attempt index)``: the same seed reproduces the identical
  fault sequence AND identical per-request outcomes across runs;
* **exactness under chaos** — retried, mesh-degraded, and
  solo-degraded requests still return results bit-identical to solo
  runs (the solo fallback IS the parity reference);
* **filler safety** — a dispatch that dies mid-bucket can never
  unstack filler lanes into real handles.

The fast tests here run inside tier-1 (``-m resilience``); the full
204-request chaos acceptance replay is additionally marked ``slow``
(scripts/service_smoke.py ``chaos`` runs the same harness standalone).
"""

import numpy as np
import pytest

from gossip_protocol_tpu.config import SimConfig
from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.service import (BreakerPolicy, DeadlineExceeded,
                                         DispatchFailed, FaultInjector,
                                         FleetService, RetryPolicy,
                                         ShedRejection, chaos_replay,
                                         overlay_templates, Template)

pytestmark = [pytest.mark.service, pytest.mark.resilience]


def _dense_churn(n=16, ticks=22):
    return SimConfig(max_nnb=n, single_failure=False, drop_msg=False,
                     seed=0, total_ticks=ticks, fail_tick=20,
                     rejoin_after=15)


def _fast_retry(**kw):
    kw.setdefault("max_retries", 2)
    kw.setdefault("backoff_base_s", 1e-4)
    return RetryPolicy(**kw)


class _Clock:
    """Deterministic service clock; ``sleep`` advances it (so backoff
    and breaker cooldowns run on fake time in these tests)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


# ---- the injector is deterministic -----------------------------------
def test_injector_schedule_deterministic():
    a = FaultInjector(seed=42, fault_rate=0.3)
    b = FaultInjector(seed=42, fault_rate=0.3)
    plan_a = [a.plan(i) for i in range(1, 200)]
    plan_b = [b.plan(i) for i in range(1, 200)]
    assert plan_a == plan_b
    assert a.events == b.events and a.schedule_digest() == b.schedule_digest()
    assert any(k is not None for k in plan_a)
    # the draw is per-index, not per-call-order: asking only for the
    # odd indices reproduces exactly the odd subsequence
    c = FaultInjector(seed=42, fault_rate=0.3)
    assert [c.plan(i) for i in range(1, 200, 2)] == plan_a[::2]
    # a different seed gives a different schedule
    d = FaultInjector(seed=43, fault_rate=0.3)
    assert [d.plan(i) for i in range(1, 200)] != plan_a


def test_injector_device_loss_wins_at_its_index():
    inj = FaultInjector(seed=1, fault_rate=0.0, device_loss_at=5)
    assert [inj.plan(i) for i in (3, 4, 5, 6)] == \
        [None, None, "device_loss", None]
    assert inj.summary()["device_loss"] == 1


def test_retry_policy_backoff_deterministic_and_bounded():
    p = RetryPolicy(max_retries=5, backoff_base_s=0.1,
                    backoff_factor=2.0, max_backoff_s=0.5,
                    jitter_frac=0.25, seed=3)
    seq = [p.backoff_s(a) for a in (1, 2, 3, 4, 5)]
    assert seq == [p.backoff_s(a) for a in (1, 2, 3, 4, 5)]
    for a, b in enumerate(seq, start=1):
        nominal = min(0.5, 0.1 * 2.0 ** (a - 1))
        assert 0.75 * nominal <= b <= 1.25 * nominal, (a, b)
    assert RetryPolicy(jitter_frac=0.0).backoff_s(1) == \
        RetryPolicy(jitter_frac=0.0).backoff_base_s


# ---- retry recovers transients, terminal failures are typed ----------
def test_transient_fault_recovered_with_parity():
    cfg = _dense_churn()
    ref = Simulation(cfg).run(seed=1)
    for kind in ("compile", "dispatch", "poison"):
        svc = FleetService(max_batch=2,
                           injector=FaultInjector(schedule={1: kind}),
                           retry=_fast_retry())
        hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
        svc.drain()     # resolve-side faults (poison) surface here
        assert [h.status for h in hs] == ["completed", "completed"], kind
        assert all(h.metrics.retries == 1 for h in hs), kind
        assert np.array_equal(hs[0].result().sent, ref.sent), kind
        st = svc.stats()["failures"]
        assert st["retries"] == 1 and st["faults_injected"] == 1, kind
        if kind == "poison":
            assert st["poisoned_lanes"] == 1


def test_poison_overlay_lane_detected():
    """Overlay fleet metrics cross to host as READ-ONLY numpy views;
    poisoning must replace the lane's array (not write into it) so
    validate_lane — not a ValueError — is what catches it."""
    from gossip_protocol_tpu.models.overlay import OverlaySimulation
    cfg = SimConfig(max_nnb=64, model="overlay", single_failure=False,
                    drop_msg=False, seed=0, total_ticks=48,
                    churn_rate=0.25, rejoin_after=16, step_rate=8.0 / 64)
    svc = FleetService(max_batch=2,
                       injector=FaultInjector(schedule={1: "poison"}),
                       retry=_fast_retry())
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    svc.drain()         # poison is applied (and caught) at resolve
    assert [h.status for h in hs] == ["completed", "completed"]
    st = svc.stats()["failures"]
    assert st["poisoned_lanes"] == 1 and st["retries"] == 1
    ref = OverlaySimulation(cfg.replace(seed=1), use_pallas=False).run()
    lane = hs[0].result()
    assert np.array_equal(np.asarray(ref.metrics.sent),
                          np.asarray(lane.metrics.sent))


def test_injector_rejects_unknown_kinds():
    with pytest.raises(ValueError, match="unknown fault kinds"):
        FaultInjector(kinds=("dispatch", "segfault"))
    with pytest.raises(ValueError, match="schedule"):
        FaultInjector(schedule={1: "device-loss"})    # typo'd kind
    FaultInjector(schedule={1: "device_loss"})        # explicit loss OK


def test_clean_replay_raises_on_hidden_degradation(monkeypatch):
    """The fault-free replay() harness must stay LOUD about engine
    failures: the resilient scheduler degrades a broken fleet path to
    solo runs that pass parity trivially, so replay() asserts zero
    degraded/failed requests instead of reporting a bogus speedup."""
    from gossip_protocol_tpu.core.fleet import FleetSimulation
    from gossip_protocol_tpu.service import replay

    real_launch = FleetSimulation.launch

    def broken_launch(self, *a, **kw):
        if kw.get("n_real") == 1:      # keep the warm pass alive
            return real_launch(self, *a, **kw)
        raise RuntimeError("engine regression")

    monkeypatch.setattr(FleetSimulation, "launch", broken_launch)
    with pytest.raises(RuntimeError,
                       match="degraded|dispatch path is broken"):
        replay(overlay_templates(n=128, ticks=48), seeds_per_template=2,
               max_batch=4)


def test_injected_latency_counts_without_failing():
    cfg = _dense_churn()
    svc = FleetService(max_batch=2,
                       injector=FaultInjector(schedule={1: "latency"},
                                              latency_s=1e-3),
                       retry=_fast_retry())
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    svc.drain()         # the latency stall happens at resolve
    assert all(h.status == "completed" and h.metrics.retries == 0
               for h in hs)
    assert svc.stats()["failures"]["injected_latency_s"] > 0.0


def test_exhausted_retries_degrade_to_solo_with_parity():
    cfg = _dense_churn()
    ref = Simulation(cfg).run(seed=5)
    svc = FleetService(
        max_batch=2,
        injector=FaultInjector(schedule={i: "dispatch"
                                         for i in range(1, 40)}),
        retry=_fast_retry(max_retries=1))
    hs = [svc.submit(cfg, seed=s) for s in (5, 6)]
    assert [h.status for h in hs] == ["degraded", "degraded"]
    assert np.array_equal(hs[0].result().sent, ref.sent)
    st = svc.stats()["failures"]
    assert st["degraded_dispatches"] == 1 and st["degraded_requests"] == 2


def test_exhausted_retries_without_fallback_fail_typed():
    cfg = _dense_churn()
    svc = FleetService(
        max_batch=2, degrade_to_solo=False,
        injector=FaultInjector(schedule={i: "dispatch"
                                         for i in range(1, 40)}),
        retry=_fast_retry(max_retries=1))
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    assert all(h.status == "failed" for h in hs)
    assert svc.pending == 0, "failed batch must not re-queue"
    with pytest.raises(DispatchFailed, match="request 0 failed"):
        hs[0].result()
    assert isinstance(hs[0].exception().__cause__, Exception)
    assert svc.stats()["failures"]["failed_requests"] == 2


# ---- deadlines -------------------------------------------------------
def test_deadline_expires_queued_request():
    cfg = _dense_churn()
    clock = _Clock()
    svc = FleetService(max_batch=8, clock=clock, sleep=clock.sleep)
    h = svc.submit(cfg, seed=1, deadline_s=2.0)
    h2 = svc.submit(cfg, seed=2)          # no deadline: survives
    clock.t = 3.0
    svc.pump()
    assert h.status == "failed" and not h2.done
    with pytest.raises(DeadlineExceeded, match="deadline"):
        h.result()
    assert svc.stats()["failures"]["deadline_misses"] == 1
    svc.drain()
    assert h2.status == "completed"


def test_deadline_missed_accounting_on_late_completion():
    """A request that is DISPATCHED past its deadline inside a flush
    still gets its result, flagged ``deadline_missed`` (accounting,
    not a drop) — only queue-side expiry fails a handle."""
    cfg = _dense_churn()
    clock = _Clock()
    svc = FleetService(max_batch=1, clock=clock, sleep=clock.sleep,
                       default_deadline_s=5.0)
    # max_batch=1: the submit itself dispatches (pipelined: launches);
    # the flush resolves it at the fake clock's frozen "now" == submit
    # time -> not missed
    h = svc.submit(cfg, seed=1)
    svc.drain()
    assert h.status == "completed" and not h.metrics.deadline_missed


def test_retry_loop_respects_deadline_budget():
    """Backoff never sleeps past the batch's tightest deadline: with a
    budget smaller than the first backoff, a faulted batch goes
    straight to the fallback instead of sleeping through it."""
    cfg = _dense_churn()
    clock = _Clock()
    svc = FleetService(
        max_batch=2, clock=clock, sleep=clock.sleep,
        injector=FaultInjector(schedule={1: "dispatch"}),
        retry=RetryPolicy(max_retries=5, backoff_base_s=10.0,
                          jitter_frac=0.0))
    hs = [svc.submit(cfg, seed=s, deadline_s=1.0) for s in (1, 2)]
    assert all(h.status == "degraded" for h in hs)
    assert svc.stats()["failures"]["retries"] == 0, \
        "slept into a deadline instead of degrading"


# ---- admission control -----------------------------------------------
def test_admission_sheds_typed_never_drops():
    cfg = _dense_churn()
    svc = FleetService(max_batch=8, max_queue_depth=2)
    h1 = svc.submit(cfg, seed=1)
    h2 = svc.submit(cfg, seed=2)
    with pytest.raises(ShedRejection, match="max_queue_depth=2"):
        svc.submit(cfg, seed=3)
    assert svc.stats()["failures"]["shed"] == 1
    svc.drain()                    # the queued two were never dropped
    assert h1.status == h2.status == "completed"
    assert svc.submit(cfg, seed=4).status == "pending"  # room again
    svc.drain()
    with pytest.raises(ValueError, match="max_queue_depth"):
        FleetService(max_queue_depth=0)


# ---- circuit breaker -------------------------------------------------
def test_breaker_opens_quarantines_and_recovers():
    cfg = _dense_churn()
    ref = Simulation(cfg).run(seed=1)
    clock = _Clock()
    # faults on the first two attempts only; threshold 2, cooldown 10s
    svc = FleetService(
        max_batch=2, clock=clock, sleep=clock.sleep,
        injector=FaultInjector(schedule={1: "dispatch", 2: "dispatch"}),
        retry=_fast_retry(max_retries=0),
        breaker=BreakerPolicy(failure_threshold=2, reset_after_s=10.0))
    h1 = [svc.submit(cfg, seed=s) for s in (1, 2)]   # attempt 1 fails
    h2 = [svc.submit(cfg, seed=s) for s in (3, 4)]   # attempt 2 opens
    st = svc.stats()
    assert st["failures"]["breaker_opens"] == 1
    assert st["breaker_open_buckets"] == 1
    assert all(h.status == "degraded" for h in h1 + h2)
    # while open: quarantined straight to solo, no attempt consumed
    attempts0 = svc._attempts
    h3 = [svc.submit(cfg, seed=s) for s in (5, 6)]
    assert all(h.status == "degraded" for h in h3)
    assert svc._attempts == attempts0, "open breaker must not dispatch"
    assert np.array_equal(h3[0].result().sent, ref.sent)
    # after the cooldown: one probe dispatch, success closes it
    clock.t += 11.0
    h4 = [svc.submit(cfg, seed=s) for s in (1, 7)]
    svc.drain()          # the pipelined probe resolves here
    assert all(h.status == "completed" for h in h4)
    assert svc.stats()["breaker_open_buckets"] == 0
    assert np.array_equal(h4[0].result().sent, ref.sent)


# ---- filler-lane safety under faults ---------------------------------
def test_filler_lanes_survive_faulted_partial_batches():
    """A PARTIAL batch (3 real + 5 filler) whose first attempt dies
    must, on the retried attempt, still unstack exactly the 3 real
    lanes — bit-identical to solo runs, filler never leaked."""
    cfg = _dense_churn()
    sim = Simulation(cfg)
    svc = FleetService(max_batch=8, pad_policy="full",
                       injector=FaultInjector(schedule={1: "dispatch"}),
                       retry=_fast_retry())
    hs = [svc.submit(cfg, seed=s) for s in (1, 2, 3)]
    svc.drain()
    assert [h.status for h in hs] == ["completed"] * 3
    for s, h in zip((1, 2, 3), hs):
        m = h.metrics
        assert m.batch == 3 and m.padded_batch == 8 and m.retries == 1
        assert np.array_equal(sim.run(seed=s).sent, h.result().sent), s
    assert not svc._handles, "stranded handles after a faulted batch"


def test_unstack_miscount_is_caught_not_mispaired():
    """If a fleet ever unstacked the wrong lane count (filler leaked,
    or a lane lost), the scheduler must catch it as a dispatch
    failure — never zip mismatched lanes onto handles.  Pinned by
    wrapping the bucket's fleet handle to return one extra lane."""
    from gossip_protocol_tpu.service import bucket_key
    cfg = _dense_churn()
    ref = Simulation(cfg).run(seed=1)
    svc = FleetService(max_batch=2, retry=_fast_retry(max_retries=0))
    key = bucket_key(cfg, "trace")
    fleet_sim = svc.cache.get(key, cfg)
    real_launch = fleet_sim.launch

    def leaky_launch(*a, **kw):
        pending = real_launch(*a, **kw)
        real_resolve = pending.resolve

        def leaky_resolve():
            fleet = real_resolve()
            fleet.lanes.append(fleet.lanes[-1])  # a filler lane "leaks"
            return fleet

        pending.resolve = leaky_resolve
        return pending

    fleet_sim.launch = leaky_launch
    hs = [svc.submit(cfg, seed=s) for s in (1, 2)]
    svc.drain()          # the miscount is detected at resolve
    # the leak is detected, the batch degrades to solo -> right results
    assert [h.status for h in hs] == ["degraded", "degraded"]
    assert np.array_equal(hs[0].result().sent, ref.sent)
    assert not svc._handles


def test_fleet_unstack_invariant_direct():
    from gossip_protocol_tpu.core.fleet import _check_unstacked
    _check_unstacked([1, 2, 3], 3)
    with pytest.raises(RuntimeError, match="never be unstacked"):
        _check_unstacked([1, 2, 3, 4], 3)


def test_pending_fleet_failed_resolution_reraises():
    """A FAILED resolution must re-raise on every later resolve()
    call (the step is retained), never silently return None."""
    from gossip_protocol_tpu.core.fleet import PendingFleet
    calls = []

    def bad():
        calls.append(1)
        raise RuntimeError("boom")

    p = PendingFleet(bad, 0.0)
    with pytest.raises(RuntimeError, match="boom"):
        p.resolve()
    with pytest.raises(RuntimeError, match="boom"):
        p.resolve()
    assert len(calls) == 2


def test_interrupted_pipelined_dispatch_requeues_exactly_once():
    """A non-Exception escape (KeyboardInterrupt) out of a pipelined
    dispatch re-queues the popped requests EXACTLY once — the inner
    handlers and _dispatch's deduped backstop must not stack
    duplicate queue entries — and the next flush serves them."""
    from gossip_protocol_tpu.service import bucket_key
    cfg = _dense_churn()
    ref = Simulation(cfg).run(seed=1)
    svc = FleetService(max_batch=2, pipeline=True)
    key = bucket_key(cfg, "trace")
    sim = svc.cache.get(key, cfg)
    real_launch = sim.launch
    boom = {"armed": True}

    def interrupted_launch(*a, **kw):
        if boom.pop("armed", False):
            raise KeyboardInterrupt
        return real_launch(*a, **kw)

    sim.launch = interrupted_launch
    h1 = svc.submit(cfg, seed=1)
    with pytest.raises(KeyboardInterrupt):
        svc.submit(cfg, seed=2)
    q = svc._queues[key]
    assert len(q) == 2 and len({r.rid for r in q}) == 2, \
        "requests re-queued more than once (or lost)"
    assert h1.status == "pending"
    svc.drain()
    assert h1.status == "completed"
    assert np.array_equal(h1.result().sent, ref.sent)
    assert not svc._handles


# ---- resilience under overlap (PR 6) ---------------------------------
def test_fault_in_batch_k_does_not_corrupt_staged_k_plus_1():
    """A poison fault detected while resolving batch k — AFTER batch
    k+1 (a different bucket) has already been staged and dispatched —
    must retry k in place without touching k+1: both buckets complete
    with bit-parity, only k pays retries.  Pinned at
    ``pipeline_depth=1`` — the PR 6 service-wide slot, where staging
    k+1 is what displaces and resolves k (at depth >= 2 the buckets
    ride independent rings and k resolves at flush instead;
    test_resilience.py::test_chaos_digest_depth2_two_buckets covers
    that plane)."""
    cfg_a = _dense_churn(n=16, ticks=22)
    cfg_b = _dense_churn(n=12, ticks=26)
    ref_a = Simulation(cfg_a).run(seed=1)
    ref_b = Simulation(cfg_b).run(seed=3)
    svc = FleetService(max_batch=2, pipeline=True, pipeline_depth=1,
                       injector=FaultInjector(schedule={1: "poison"}),
                       retry=_fast_retry())
    ha = [svc.submit(cfg_a, seed=s) for s in (1, 2)]   # batch k
    assert svc.in_flight == 2
    hb = [svc.submit(cfg_b, seed=s) for s in (3, 4)]   # batch k+1:
    # staging k+1 resolved k, caught the poison, and retried k while
    # k+1 executes — k terminal, k+1 in flight
    assert [h.status for h in ha] == ["completed", "completed"]
    assert all(h.metrics.retries == 1 for h in ha)
    assert svc.in_flight == 2
    svc.drain()
    assert [h.status for h in hb] == ["completed", "completed"]
    assert all(h.metrics.retries == 0 for h in hb)
    assert np.array_equal(ha[0].result().sent, ref_a.sent)
    assert np.array_equal(hb[0].result().sent, ref_b.sent)
    st = svc.stats()["failures"]
    assert st["poisoned_lanes"] == 1 and st["retries"] == 1
    assert not svc._handles


def test_fault_isolation_depth2_two_buckets():
    """PR 17: at depth 2 with TWO buckets riding independent rings, a
    poison fault in bucket A's batch (caught at A's resolve) must not
    corrupt bucket B's staged batch or shift B's attempt indices: A
    pays the retry (a NEW attempt index drawn after both launches), B
    resolves clean with retries == 0, and both buckets return
    bit-parity results."""
    cfg_a = _dense_churn(n=16, ticks=22)
    cfg_b = _dense_churn(n=12, ticks=26)
    ref_a = Simulation(cfg_a).run(seed=1)
    ref_b = Simulation(cfg_b).run(seed=3)
    svc = FleetService(max_batch=2, pipeline=True, pipeline_depth=2,
                       injector=FaultInjector(schedule={1: "poison"}),
                       retry=_fast_retry())
    ha = [svc.submit(cfg_a, seed=s) for s in (1, 2)]   # attempt 1
    hb = [svc.submit(cfg_b, seed=s) for s in (3, 4)]   # attempt 2
    # independent rings: BOTH batches are in flight — staging B did
    # not displace (or resolve, or poison-retry) A
    assert svc.in_flight == 4
    assert [h.status for h in ha + hb] == ["in_flight"] * 4
    st = svc.stats()
    assert st["pipeline_depth"] == 2
    assert len(st["in_flight_by_bucket"]) == 2
    svc.drain()
    # A's poison surfaced at its own resolve and retried there
    # (attempt 3); B's attempt index was drawn before the fault ever
    # surfaced, so its schedule position — and results — are untouched
    assert [h.status for h in ha] == ["completed", "completed"]
    assert all(h.metrics.retries == 1 for h in ha)
    assert [h.status for h in hb] == ["completed", "completed"]
    assert all(h.metrics.retries == 0 for h in hb)
    assert svc._attempts == 3
    assert np.array_equal(ha[0].result().sent, ref_a.sent)
    assert np.array_equal(hb[0].result().sent, ref_b.sent)
    fs = svc.stats()["failures"]
    assert fs["poisoned_lanes"] == 1 and fs["retries"] == 1
    assert not svc._handles


def test_chaos_digest_depth2_two_buckets():
    """PR 17: the chaos digest gate pinned at depth 2 with two active
    bucket shapes — the seeded fault schedule and per-request outcomes
    stay a pure function of the submit/flush sequence when independent
    buckets overlap in flight."""
    tpls = (overlay_templates(n=128, ticks=48)
            + overlay_templates(n=64, ticks=48))
    kw = dict(seeds_per_template=3, max_batch=4, fault_seed=11,
              fault_rate=0.3, device_loss_at=None, pipeline=True,
              pipeline_depth=2)
    m1, seq = chaos_replay(tpls, return_legs=True, **kw)
    m2 = chaos_replay(tpls, sequential=seq, **kw)
    assert m1["pipeline"] is True and m1["pipeline_depth"] == 2
    assert m1["faults"]["total"] > 0
    assert m1["schedule_digest"] == m2["schedule_digest"]
    assert m1["outcome_digest"] == m2["outcome_digest"]
    assert m1["completion_rate"] == m2["completion_rate"] == 1.0


def test_interrupted_flush_requeues_exactly_once_ring():
    """PR 17: the interrupted-flush contract generalized to the
    rings — with TWO buckets' batches in flight at depth 2, a
    non-Exception escape out of a third dispatch re-queues every
    unresolved request EXACTLY once (the popped batch via the
    backstop, both in-flight batches via the ring abort), and the
    next drain serves all of them with parity."""
    from gossip_protocol_tpu.service import bucket_key
    cfg_a = _dense_churn(n=16, ticks=22)
    cfg_b = _dense_churn(n=12, ticks=26)
    ref_a = Simulation(cfg_a).run(seed=1)
    ref_b = Simulation(cfg_b).run(seed=5)
    # pump_harvest=False: idle pumps between the submits must not
    # harvest batch A before the interrupt lands — the test needs both
    # rings occupied at the escape point
    svc = FleetService(max_batch=2, pipeline=True, pipeline_depth=2,
                       pump_harvest=False)
    key_a = bucket_key(cfg_a, "trace")
    ha = [svc.submit(cfg_a, seed=s) for s in (1, 2)]
    hb = [svc.submit(cfg_b, seed=s) for s in (5, 6)]
    assert svc.in_flight == 4
    sim = svc.cache.get(key_a, cfg_a)
    real_launch = sim.launch
    boom = {"armed": True}

    def interrupted_launch(*a, **kw):
        if boom.pop("armed", False):
            raise KeyboardInterrupt
        return real_launch(*a, **kw)

    sim.launch = interrupted_launch
    h3 = svc.submit(cfg_a, seed=3)
    with pytest.raises(KeyboardInterrupt):
        svc.submit(cfg_a, seed=4)      # fills bucket A -> dispatches
    # everything is back in its queue, exactly once, in rid order
    assert svc.in_flight == 0
    qa = svc._queues[key_a]
    qb = svc._queues[bucket_key(cfg_b, "trace")]
    assert len(qa) == 4 and len({r.rid for r in qa}) == 4
    assert [r.rid for r in qa] == sorted(r.rid for r in qa)
    assert len(qb) == 2 and len({r.rid for r in qb}) == 2
    assert all(h.status == "pending" for h in ha + hb + [h3])
    svc.drain()
    assert all(h.status == "completed" for h in ha + hb + [h3])
    assert np.array_equal(ha[0].result().sent, ref_a.sent)
    assert np.array_equal(hb[0].result().sent, ref_b.sent)
    assert not svc._handles


def test_chaos_replay_digest_stable_with_pipelining():
    """chaos_replay stays seed-replayable digest-for-digest with
    pipelining forced ON: launches, resolves, and retries all happen
    at fixed points of the submit/flush sequence, so the fault
    schedule and per-request outcomes are a pure function of submit
    order."""
    tpls = overlay_templates(n=128, ticks=48)
    kw = dict(seeds_per_template=3, max_batch=4, fault_seed=11,
              fault_rate=0.3, device_loss_at=None, pipeline=True)
    m1, seq = chaos_replay(tpls, return_legs=True, **kw)
    m2 = chaos_replay(tpls, sequential=seq, **kw)
    assert m1["pipeline"] is True
    assert m1["faults"]["total"] > 0
    assert m1["schedule_digest"] == m2["schedule_digest"]
    assert m1["outcome_digest"] == m2["outcome_digest"]
    assert m1["completion_rate"] == m2["completion_rate"] == 1.0


# ---- mesh degradation ------------------------------------------------
@pytest.mark.skipif(__import__("jax").device_count() < 2,
                    reason="needs 2 (virtual) devices")
def test_device_loss_shrinks_mesh_and_completes():
    """One injected device loss mid-stream: the service drops to a
    smaller mesh (2 -> single device), rebuilds through the mesh-keyed
    caches, and completes every request bit-identically."""
    from gossip_protocol_tpu.parallel.fleet_mesh import make_lane_mesh
    cfg = _dense_churn()
    ref = Simulation(cfg).run(seed=1)
    svc = FleetService(max_batch=2, mesh=make_lane_mesh(2),
                       injector=FaultInjector(device_loss_at=2),
                       retry=_fast_retry())
    assert svc.capacity == 4
    h1 = [svc.submit(cfg, seed=s) for s in (1, 2, 3, 4)]   # attempt 1 OK
    h2 = [svc.submit(cfg, seed=s) for s in (1, 5, 6, 7)]   # loss on 2
    assert all(h.status == "completed" for h in h1 + h2)
    assert svc.mesh is None and svc.n_devices == 1 and svc.capacity == 2
    st = svc.stats()
    assert st["failures"]["device_losses"] == 1
    assert st["failures"]["mesh_rebuilds"] == 1
    assert st["cache"]["mesh_rebinds"] == 1
    assert np.array_equal(h1[0].result().sent, ref.sent)
    assert np.array_equal(h2[0].result().sent, ref.sent)


def test_shrink_mesh_ladder():
    import jax
    from gossip_protocol_tpu.parallel.fleet_mesh import (make_lane_mesh,
                                                         mesh_descriptor,
                                                         shrink_mesh)
    assert shrink_mesh(None) is None
    if jax.device_count() < 4:
        pytest.skip("needs 4 (virtual) devices")
    m4 = make_lane_mesh(4)
    m3 = shrink_mesh(m4)
    assert m3.devices.size == 3
    assert mesh_descriptor(m3) != mesh_descriptor(m4)
    m2 = shrink_mesh(m3)
    assert m2.devices.size == 2
    assert shrink_mesh(m2) is None          # below 2: no mesh at all


# ---- the chaos-seeded parity sweep -----------------------------------
def _chaos_templates():
    dense = SimConfig(max_nnb=20, single_failure=False, drop_msg=False,
                      seed=0, total_ticks=26, fail_tick=20,
                      rejoin_after=4)
    drop = SimConfig(max_nnb=20, single_failure=True, drop_msg=True,
                     msg_drop_prob=0.1, seed=0, total_ticks=26,
                     fail_tick=10)
    return ([Template("dense-churn", dense), Template("dense-drop", drop)]
            + overlay_templates(n=128, ticks=48))


def test_chaos_seeded_parity_sweep_and_reproducibility():
    """The chaos gate at test scale: a mixed stream under a seeded
    ~30% fault schedule + one device loss completes 100% with parity
    (enforced inside chaos_replay), and the SAME seed reproduces the
    identical fault sequence and per-request outcomes."""
    tpls = _chaos_templates()
    m1, seq = chaos_replay(tpls, seeds_per_template=3, max_batch=4,
                           fault_seed=11, fault_rate=0.3,
                           return_legs=True)
    assert m1["requests"] == 15 and m1["completion_rate"] == 1.0
    assert m1["stranded"] == 0 and m1["failed"] == 0
    assert m1["faults"]["total"] >= 1
    m2 = chaos_replay(tpls, seeds_per_template=3, max_batch=4,
                      fault_seed=11, fault_rate=0.3, sequential=seq)
    assert m1["fault_events"] == m2["fault_events"]
    assert m1["schedule_digest"] == m2["schedule_digest"]
    assert m1["outcomes"] == m2["outcomes"]
    assert m1["outcome_digest"] == m2["outcome_digest"]
    # a different seed draws a different schedule
    m3 = chaos_replay(tpls, seeds_per_template=3, max_batch=4,
                      fault_seed=12, fault_rate=0.3, sequential=seq)
    assert m3["completion_rate"] == 1.0
    assert m3["fault_events"] != m1["fault_events"]


@pytest.mark.slow
def test_chaos_replay_acceptance():
    """The PR-5 acceptance gate: the full 204-request mixed replay
    under >=10% injected dispatch faults plus one mid-replay device
    loss completes 100% (0 stranded), every request bit-identical to
    its solo run, and the identical seed reproduces the identical
    fault sequence and per-request outcomes."""
    from gossip_protocol_tpu.service import grader_templates
    tpls = grader_templates() + overlay_templates(n=512, ticks=96)
    m1, seq = chaos_replay(tpls, seeds_per_template=34, max_batch=8,
                           fault_seed=20260804, fault_rate=0.12,
                           return_legs=True)
    assert m1["requests"] == 204
    assert m1["completion_rate"] == 1.0 and m1["stranded"] == 0
    assert m1["faults"]["total"] >= 0.10 * m1["dispatches"]
    assert m1["faults"]["device_loss"] == 1
    assert m1["latency_p95_s"] < 60.0
    m2 = chaos_replay(tpls, seeds_per_template=34, max_batch=8,
                      fault_seed=20260804, fault_rate=0.12,
                      sequential=seq)
    assert m1["fault_events"] == m2["fault_events"]
    assert m1["outcomes"] == m2["outcomes"]
