"""Churn/rejoin extension (SURVEY.md §5 — absent in the reference,
which never re-admits a failed node: no code path resets bFailed,
MP1Node.cpp:161-168 only clears state at shutdown).

A churned peer is wiped at its rejoin tick and re-enters through the
normal JOINREQ path.  Checks: full oracle parity with churn enabled,
rejoin events visible in the log stream, convergence back to complete
membership, and no permanent false removals.
"""

import numpy as np
import pytest

from gossip_protocol_tpu.core.sim import Simulation
from gossip_protocol_tpu.state import NEVER, make_schedule
from gossip_protocol_tpu.testing.dropsync import make_drop_masks
from gossip_protocol_tpu.testing.oracle import ReferenceOracle
from tests.conftest import scenario_cfg


@pytest.mark.parametrize("rejoin_after,drop", [
    (40, False),   # rejoin well after everyone removed the peer
    (10, False),   # rejoin while its stale entry still lingers
    (40, True),    # rejoin under 10% message drop
])
def test_churn_oracle_parity(rejoin_after, drop):
    cfg = scenario_cfg(
        "msgdropsinglefailure" if drop else "singlefailure",
        max_nnb=16, seed=2, fail_tick=30, rejoin_after=rejoin_after,
        total_ticks=160)
    res = Simulation(cfg).run()
    sched = make_schedule(cfg)
    drops = make_drop_masks(cfg, sched) if cfg.drop_msg else (None, None, None)
    o = ReferenceOracle(cfg, res.start_tick, res.fail_tick, *drops,
                        rejoin_tick=res.rejoin_tick).run()

    gv = res.grader_view()
    # joins compared as (tick, observer, subject) triples so a re-join
    # logged at the wrong tick (or swallowed) cannot hide behind the
    # pre-failure join of the same pair
    tick_adds = {(t, i, j) for t, i, j in zip(*np.nonzero(res.added))}
    assert {(t, i, j) for (t, i, j) in o.events.added} == tick_adds
    assert {(i, j) for (_, i, j) in o.events.added} == gv["joins"]
    oracle_removals = {}
    for (t, i, j) in o.events.removed:
        oracle_removals.setdefault((i, j), t)
    if not cfg.drop_msg:
        assert oracle_removals == gv["removal_ticks"]
        assert np.array_equal(o.sent, res.sent)
        assert np.array_equal(o.recv, res.recv)
    else:
        assert set(oracle_removals) == set(gv["removal_ticks"])
    assert np.array_equal(o.known_matrix(), np.asarray(res.final_state.known))


def test_churn_rejoin_converges():
    """After the victim rejoins: it is re-admitted (fresh join events),
    membership converges back to complete, and nothing is removed
    after the rejoin settles (no permanent false removals)."""
    cfg = scenario_cfg("singlefailure", max_nnb=16, seed=2, fail_tick=30,
                       rejoin_after=40, total_ticks=200)
    res = Simulation(cfg).run()
    victim = int(np.flatnonzero(res.fail_tick != NEVER)[0])
    rejoin_t = int(res.rejoin_tick[victim])
    assert rejoin_t == 70

    evs = res.events()
    # the rejoin logs a fresh nodeStart line
    assert any(e.observer == victim and e.tick == rejoin_t
               and "Trying to join" in e.text for e in evs)
    # every survivor removed the victim once (detection of the failure)
    # and re-admitted it after the rejoin
    n = cfg.n
    for obs in range(n):
        if obs == victim:
            continue
        rem = [e.tick for e in evs if e.observer == obs
               and f"Node {victim + 1}.0.0.0:0 removed" in e.text]
        readd = [e.tick for e in evs if e.observer == obs
                 and f"Node {victim + 1}.0.0.0:0 joined" in e.text
                 and e.tick > rejoin_t]
        assert rem == [cfg.fail_tick + cfg.t_remove + 1], (obs, rem)
        assert len(readd) == 1 and readd[0] <= rejoin_t + 4, (obs, readd)
    # no removals at all after the rejoin settles
    assert not [e for e in evs
                if "removed" in e.text and e.tick > rejoin_t + 25]
    # final membership is complete again
    known = np.asarray(res.final_state.known)
    assert (known.sum(1) == n - 1).all()
    assert bool(np.asarray(res.final_state.in_group).all())


def test_quick_rejoin_no_false_removal():
    """Rejoining before TREMOVE fires means survivors never drop the
    peer at all: its old entries get refreshed by the new incarnation's
    gossip, and the member list never shrinks."""
    cfg = scenario_cfg("singlefailure", max_nnb=16, seed=2, fail_tick=30,
                       rejoin_after=10, total_ticks=120)
    res = Simulation(cfg).run()
    gv = res.grader_view()
    assert not gv["removal_ticks"], gv["removal_ticks"]
    known = np.asarray(res.final_state.known)
    assert (known.sum(1) == cfg.n - 1).all()


def test_rejoin_after_zero_rejected():
    """rejoin_tick == fail_tick would collapse the failed window."""
    cfg = scenario_cfg("singlefailure", max_nnb=16, rejoin_after=0)
    with pytest.raises(ValueError, match="rejoin_after"):
        make_schedule(cfg)
