"""Ordered event stream reconstruction.

The dbg.log grammar is an external API — Grader.sh greps it
(Grader.sh:40-189) — so the framework reproduces it exactly from the
tick function's dense event masks.  Line *order* inside a tick follows
the reference driver: phase B walks nodes in reverse index order
(Application.cpp:138-163), each node logs its adds (checkMessages) before
its removes (nodeLoopOps), node 0 emits the ``@@time=`` heartbeat line
after its nodeLoop every 500 ticks (Application.cpp:156-160), and the
scripted failure lines come last, from ``fail()`` (Application.cpp:181-196).

Within one node's tick the reference's add order depends on EmulNet
queue order; we canonicalize to ascending subject id (the observed order
for the common paths) — Grader.sh sorts lines, so this is not
grader-visible.  Removes are emitted in descending subject order,
matching the reference's reverse list scan (MP1Node.cpp:339).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, Optional

import numpy as np

from .addressing import addr_str
from .config import INTRODUCER, SimConfig
from .state import NEVER


@dataclass
class LogEvent:
    """One dbg.log line: ``\\n <addr> [tick] <text>``  (Log.cpp:97-99)."""
    observer: Optional[int]  # peer index, or None for the blank-address quirk
    tick: int
    text: str


def event_stream(cfg: SimConfig, start_tick: np.ndarray, fail_tick: np.ndarray,
                 added: np.ndarray, removed: np.ndarray,
                 first_tick: int = 0,
                 include_boot: Optional[bool] = None,
                 rejoin_tick: Optional[np.ndarray] = None) -> Iterator[LogEvent]:
    """Yield the run's dbg.log events in reference order.

    Args:
      cfg:        scenario config.
      start_tick: i32[N] introduction ticks (Application.cpp:143).
      fail_tick:  i32[N] failure ticks (NEVER sentinel = never fails).
      added:      bool[T, N, N] — added[t, i, j]: observer i logged a
                  join for subject j during (absolute) tick
                  ``first_tick + t``.
      removed:    bool[T, N, N] — ditto for removals.
      first_tick: absolute tick of ``added[0]`` — nonzero when the run
                  segment was resumed from a checkpoint.
      include_boot: emit the per-node "APP" boot lines.  Default: for
                  non-empty segments starting at tick 0 — a fresh run
                  and a run resumed from a tick-0 checkpoint both get
                  them exactly once, while a zero-length segment or a
                  mid-run continuation never duplicates them.
      rejoin_tick: i32[N] churn-extension rejoin ticks (NEVER = stays
                  dead); a rejoining peer logs a fresh nodeStart line
                  and resumes observing from the next tick.
    """
    n = cfg.n
    t_total = added.shape[0]
    if rejoin_tick is None:
        rejoin_tick = np.full(n, NEVER, np.int32)

    # "APP" boot lines: one per node at construction time, forward order
    # (Application.cpp:59-69), stamped with tick 0.
    emit_boot = include_boot if include_boot is not None \
        else (first_tick == 0 and t_total > 0)
    if emit_boot:
        for i in range(n):
            yield LogEvent(i, 0, "APP")

    for t in range(first_tick, first_tick + t_total):
        for i in range(n - 1, -1, -1):
            if t == start_tick[i] or t == rejoin_tick[i]:
                # nodeStart logs (MP1Node.cpp:126-144); a churned
                # peer's rejoin is a fresh nodeStart
                if i == INTRODUCER:
                    yield LogEvent(i, t, "Starting up group...")
                else:
                    yield LogEvent(i, t, "Trying to join...")
            elif (t > start_tick[i] and t <= fail_tick[i]) \
                    or t > rejoin_tick[i]:
                for j in np.nonzero(added[t - first_tick, i])[0]:
                    yield LogEvent(
                        i, t, f"Node {addr_str(j)} joined at time {t}")
                for j in np.nonzero(removed[t - first_tick, i])[0][::-1]:
                    yield LogEvent(
                        i, t, f"Node {addr_str(j)} removed at time {t}")
                if i == 0 and t % 500 == 0:
                    yield LogEvent(i, t, f"@@time={t}")
        if t == cfg.fail_tick:
            # "Node failed" lines, logged with the *failed node's own*
            # address (Application.cpp:184,192).  Note the single- and
            # multi-failure format strings differ by spaces around '='.
            victims = np.nonzero(fail_tick == t)[0]
            for i in victims:
                if cfg.single_failure:
                    yield LogEvent(int(i), t, f"Node failed at time={t}")
                else:
                    yield LogEvent(int(i), t, f"Node failed at time = {t}")


def grader_view(events) -> dict:
    """Digest an event stream into the facts Grader.sh checks.

    Returns dict with:
      joins:    set of (observer, subject) pairs from "joined" lines
      removals: set of (observer, subject) pairs from "removed" lines
      removal_ticks: dict (observer, subject) -> first removal tick
      failed:   set of failed peer indices
    """
    joins, removals, failed = set(), set(), set()
    removal_ticks = {}
    from .addressing import parse_addr
    for ev in events:
        if "joined at time" in ev.text:
            subj = parse_addr(ev.text.split()[1])
            joins.add((ev.observer, subj))
        elif "removed at time" in ev.text:
            subj = parse_addr(ev.text.split()[1])
            removals.add((ev.observer, subj))
            removal_ticks.setdefault((ev.observer, subj), ev.tick)
        elif "Node failed at time" in ev.text:
            failed.add(ev.observer)
    return dict(joins=joins, removals=removals,
                removal_ticks=removal_ticks, failed=failed)
