"""Sharded overlay: the partial-view model over a device mesh.

Scale-out for the BASELINE 1M-peer config: the peer axis (and with it
the view tables and send flags) is sharded over a 1-D
``jax.sharding.Mesh`` axis; all (N,) vectors are replicated.  The XOR
partner exchange decomposes exactly along the shard split — for
``N = P * Nl`` (both powers of two) and mask ``m``:

    i ^ m  =  (s ^ m_hi) * Nl  +  (il ^ m_lo)

so the low bits stay the two local permutation matmuls and the high
bits become a **ppermute** whose pairing XORs the shard index.  The
mask is a traced per-tick value while ppermute pairings must be
static, so the comm dispatches through a ``lax.switch`` over the P
possible shard-XOR permutations (P is small).  Per tick the only
cross-device traffic is F of these ppermutes plus scalar psums — all
ICI-resident.

The sharded tick is the *same code* as the single-device tick
(models/overlay.py, parameterized by comm) and produces bit-identical
trajectories (tests/test_overlay_sharded.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import SimConfig
from .overlay import (OverlayMetrics, OverlaySchedule, OverlayState,
                      make_overlay_tick)

PEER_AXIS = "peers"


class RingOverlayComm:
    """Peer-axis-sharded execution inside ``shard_map``."""

    def __init__(self, axis_name: str, n_shards: int):
        assert n_shards & (n_shards - 1) == 0, \
            "shard count must be a power of two (XOR shard exchange)"
        self.axis = axis_name
        self.n_shards = n_shards

    def row_start(self, n: int):
        return lax.axis_index(self.axis).astype(jnp.int32) * (n // self.n_shards)

    def slice_rows(self, v):
        nl = v.shape[0] // self.n_shards
        start = lax.axis_index(self.axis) * nl
        return lax.dynamic_slice_in_dim(v, start, nl, axis=0)

    def xor_perm_shards(self, x, mask_hi):
        """Route the shard-index bits of the XOR exchange: shard s's
        block comes from shard ``s ^ mask_hi``.  The pairing must be
        static for ppermute, so switch over the P possibilities."""
        p = self.n_shards

        def case(m):
            if m == 0:
                return lambda y: y
            perm = [(s, s ^ m) for s in range(p)]   # (source, destination)
            return lambda y: lax.ppermute(y, self.axis, perm)

        branches = [case(m) for m in range(p)]
        return lax.switch(mask_hi, branches, x)

    def bcast_row0(self, x_local):
        contrib = jnp.where(lax.axis_index(self.axis) == 0,
                            x_local[0], jnp.zeros_like(x_local[0]))
        return lax.psum(contrib, self.axis)

    def on_first_shard(self):
        return lax.axis_index(self.axis) == 0

    def psum(self, v):
        return lax.psum(v, self.axis)


def make_overlay_mesh(n_devices=None, axis: str = PEER_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only "
                f"{len(devs)} devices are available")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def _state_specs(axis: str) -> OverlayState:
    mat = P(axis, None)
    rep = P()
    return OverlayState(tick=rep, ids=mat, hb=mat, ts=mat,
                        in_group=rep, own_hb=rep, send_flags=mat,
                        send_hist=mat, joinreq=rep, joinrep=rep)


def _sched_specs() -> OverlaySchedule:
    import dataclasses
    return OverlaySchedule(**{f.name: P() for f in
                              dataclasses.fields(OverlaySchedule)})


def _metric_specs() -> OverlayMetrics:
    import dataclasses
    return OverlayMetrics(**{f.name: P() for f in
                             dataclasses.fields(OverlayMetrics)})


_SHARDED_CACHE: dict = {}


def make_sharded_overlay_run(cfg: SimConfig, mesh: Mesh,
                             axis: str = PEER_AXIS,
                             use_pallas: bool | None = None):
    """Build ``run(state, sched) -> (final, metrics[T])`` with the
    scan-over-ticks inside ``shard_map`` over ``mesh``.

    ``use_pallas`` (None = auto: on for TPU) routes the per-shard
    (Nl, K) phase through the fused kernel with the comm ppermuting
    the exchange's shard bits — see make_overlay_tick."""
    n_shards = mesh.devices.size
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    key = (cfg.n, cfg.t_remove, cfg.total_ticks, cfg.overlay_view,
           cfg.fanout, cfg.topology, use_pallas,
           cfg.churn_rate > 0 or cfg.rejoin_after is not None, axis, mesh)
    if key in _SHARDED_CACHE:
        return _SHARDED_CACHE[key]

    comm = RingOverlayComm(axis, n_shards)
    tick = make_overlay_tick(cfg, comm=comm, use_pallas=use_pallas)

    def body(state: OverlayState, sched: OverlaySchedule):
        def step(carry, _):
            return tick(carry, sched)
        return jax.lax.scan(step, state, None, length=cfg.total_ticks)

    from ..compat.jaxapi import shard_map
    shmapped = shard_map(
        body, mesh=mesh,
        in_specs=(_state_specs(axis), _sched_specs()),
        out_specs=(_state_specs(axis), _metric_specs()),
        # The XLA path keeps full VMA checking.  The kernel path
        # cannot, and not because of our typing: the fused kernel's
        # operands are VMA-consistent (the scalar-prefetch vector is
        # shard-invariant by construction — the shard-varying
        # row_start rides a separate SMEM operand), but pallas's own
        # machinery slices kernel operands with replicated loop
        # indices (jax pallas hlo_interpreter dynamic_slice), which
        # trips the check for any shard-varying operand; jax's error
        # text itself prescribes check_vma=False as the workaround.
        check_vma=not use_pallas,
    )
    run = jax.jit(shmapped)
    _SHARDED_CACHE[key] = run
    return run


def shard_overlay_state(state: OverlayState, mesh: Mesh,
                        axis: str = PEER_AXIS) -> OverlayState:
    """Place a host/single-device OverlayState onto the mesh."""
    specs = _state_specs(axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), state, specs)
