"""Host harness for the multi-tick overlay megakernel.

Packs the :class:`~.overlay.OverlayState` pytree plus the
loop-invariant schedule columns into the megakernel's single
(N, 2K+16) VMEM plane, runs ``lax.scan`` over whole-SLOT_EPOCH
launches (ops/pallas/overlay_mega.py), and unpacks the result into the
same ``(final_state, OverlayMetrics[T])`` contract as
:func:`~.overlay.make_overlay_run` — the megakernel is a drop-in
scheduling optimization, bit-identical to the XLA tick
(tests/test_overlay_mega.py).

Why it exists: the per-tick formulation pays a fixed ~300-400 us
Pallas-launch plus ~500 us XLA-dispatch floor per tick, which caps the
simulator at ~1.1k ticks/s at N=4096 regardless of how little work a
tick does (VERDICT round-2 "2-3 ms/tick floor").  Running
``MEGA_TICKS`` ticks per launch amortizes the whole floor; see
ops/pallas/overlay_mega.py for the in-kernel design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import INTRODUCER, SimConfig
from ..ops.pallas.overlay_mega import (AUX_LANES, MEGA_TICKS, MET_ADDS,
                                       MET_FALSE_REMOVALS, MET_IN_GROUP,
                                       MET_RECV, MET_REMOVALS, MET_SENT,
                                       MET_VICTIM, MET_VIEW,
                                       mega_overlay_ticks)
from ..utils.hash32 import mix32
from .overlay import (_SALT_DEGREE, OverlayMetrics, OverlaySchedule,
                      OverlayState, _pack_th, exchange_mask, resolved_dims)

#: the envelope verified on hardware: N=4096 (K=48, F<=7) compiles
#: and runs within the raised scoped-vmem window.  N=8192 nominally
#: fits the same budget math but was never verified on-chip (the
#: verification run wedged the relay), so configs above 4096 take the
#: per-tick fused path instead of risking a runtime VMEM failure.
MEGA_N_LIMIT = 4096


def mega_supported(cfg: SimConfig) -> bool:
    """Whether the single-launch multi-tick kernel covers this config.

    ``f <= 7``: at exactly 8 exchange rounds the interpret-mode
    executable hits a pathological XLA:CPU slowdown (measured 355 s
    per tick vs 0.01 s at 7 rounds, same shapes).  The only F=8
    config is the power-law hub-degree cap, whose BASELINE shape
    (1M peers) is outside the megakernel envelope regardless; capped
    power-law runs (cfg.fanout <= 7) still take the mega path."""
    n = cfg.n
    k, f = resolved_dims(cfg)
    return (cfg.model == "overlay" and n & (n - 1) == 0 and 8 <= n
            and n <= MEGA_N_LIMIT and 2 * k + AUX_LANES <= 128 and f <= 7
            # the packed (ts+1)<<12 | hb+1 payload word caps runs at
            # 4094 ticks (make_overlay_tick asserts the same bound)
            and cfg.total_ticks <= 4094
            # the adversarial worlds (worlds.py) are not compiled into
            # the megakernel — world configs take the XLA tick.  The
            # latency plane is pinned explicitly on top of has_worlds:
            # its message-age state dimension (send_hist) is structural
            # — the packed plane has no lane for it — not merely a
            # routing choice
            and not cfg.has_worlds and not cfg.has_latency)


def _pack_state(cfg: SimConfig, state: OverlayState,
                sched: OverlaySchedule):
    """OverlayState + schedule columns -> the (N, 2K+16) plane."""
    n = cfg.n
    k, f = resolved_dims(cfg)
    i32 = jnp.int32
    rows = jnp.arange(n, dtype=i32)
    pw = jnp.where(state.ids >= 0, _pack_th(state.ts, state.hb), 0)
    du = mix32(sched.seed, rows.astype(jnp.uint32), np.uint32(_SALT_DEGREE))
    deg = 1 + (du[:, None] < sched.deg_thr[None, :]).sum(1).astype(i32)
    cols = [
        state.ids, pw,
        state.in_group.astype(i32)[:, None],
        state.own_hb[:, None],
        state.joinreq.astype(i32)[:, None],
        state.joinrep.astype(i32)[:, None],
        state.send_flags.astype(i32),
        jnp.zeros((n, 8 - f), i32),
        sched.start_of(rows)[:, None],
        sched.fail_of(rows)[:, None],
        sched.rejoin_of(rows)[:, None],
        deg[:, None],
    ]
    return jnp.concatenate(cols, axis=1)


def _unpack_state(cfg: SimConfig, plane, tick) -> OverlayState:
    n = cfg.n
    k, f = resolved_dims(cfg)
    a = 2 * k
    ids = plane[:, 0:k]
    pw = plane[:, k:2 * k]
    occ = ids >= 0
    return OverlayState(
        tick=tick.astype(jnp.int32),
        ids=ids,
        hb=jnp.where(occ, (pw & 0xFFF) - 1, 0),
        ts=jnp.where(occ, (pw >> 12) - 1, 0),
        in_group=plane[:, a + 0] > 0,
        own_hb=plane[:, a + 1],
        send_flags=plane[:, a + 4:a + 4 + f] > 0,
        # the mega envelope excludes the latency plane
        # (mega_supported), so the history word is identically zero
        send_hist=jnp.zeros((n, f), jnp.int32),
        joinreq=plane[:, a + 2] > 0,
        joinrep=plane[:, a + 3] > 0,
    )


def _sp_vector(cfg: SimConfig, sched: OverlaySchedule, t0, s_ticks: int,
               n: int, f: int):
    i32 = jnp.int32
    intro = jnp.int32(INTRODUCER)
    scalars = jnp.stack([
        t0.astype(i32) if hasattr(t0, "astype") else jnp.int32(t0),
        sched.seed.astype(i32), sched.victim_lo, sched.victim_hi,
        sched.fail_tick, sched.rejoin_after,
        sched.churn_thr.astype(i32), sched.churn_after,
        sched.drop_on.astype(i32), sched.drop_open, sched.drop_close,
        sched.drop_thr.astype(i32),
        sched.fail_of(intro), sched.rejoin_of(intro),
    ])
    ts = t0 + jnp.arange(s_ticks, dtype=i32)
    masks = jnp.stack([exchange_mask(sched.seed, ts - 1, fi, n)
                       for fi in range(f)], axis=1)       # (S, F)
    return jnp.concatenate([scalars, masks.reshape(-1)])


def make_mega_run(cfg: SimConfig, length: int):
    """``run(state, sched) -> (final, OverlayMetrics[length])`` via
    whole-SLOT_EPOCH megakernel launches (same contract as
    :func:`~.overlay.make_overlay_run`).

    On TPU the launches run inside one jitted ``lax.scan`` (this
    image's relay costs ~100 ms per eager dispatch).  On other
    backends each launch dispatches eagerly: inlining the
    interpret-mode kernel into an outer jitted scan makes the XLA:CPU
    compile blow up superlinearly (measured: minutes at F=8), while
    the standalone kernel compiles in seconds — and the launch
    sequence is identical either way."""
    assert mega_supported(cfg), "config outside the megakernel envelope"
    n = cfg.n
    k, f = resolved_dims(cfg)
    n_chunks, rem = divmod(length, MEGA_TICKS)
    kern_kw = dict(n=n, k=k, f_rounds=f, t_remove=cfg.t_remove,
                   churn_lo=cfg.total_ticks // 4,
                   churn_span=max(cfg.total_ticks // 2, 1),
                   can_rejoin=cfg.churn_rate > 0
                   or cfg.rejoin_after is not None,
                   powerlaw=cfg.topology == "powerlaw")

    def _metrics(met):
        return OverlayMetrics(
            in_group=met[:, MET_IN_GROUP],
            view_slots=met[:, MET_VIEW],
            adds=met[:, MET_ADDS],
            removals=met[:, MET_REMOVALS],
            false_removals=met[:, MET_FALSE_REMOVALS],
            victim_slots=met[:, MET_VICTIM],
            live_uncovered=jnp.full((length,), -1, jnp.int32),
            sent=met[:, MET_SENT],
            recv=met[:, MET_RECV],
        )

    def launch(plane, t, sched, s_ticks):
        """One megakernel launch of ``s_ticks`` ticks at clock ``t``."""
        sp = _sp_vector(cfg, sched, t, s_ticks, n, f)
        plane, met = mega_overlay_ticks(plane, sp, s_ticks=s_ticks,
                                        **kern_kw)
        return plane, t + s_ticks, met

    def assemble(cfg_plane_t, met_parts):
        plane, t = cfg_plane_t
        met = jnp.concatenate(met_parts, axis=0) if met_parts \
            else jnp.zeros((0, 128), jnp.int32)
        return _unpack_state(cfg, plane, t), _metrics(met)

    def run_body(state: OverlayState, sched: OverlaySchedule):
        plane = _pack_state(cfg, state, sched)
        t = state.tick
        met_parts = []
        if n_chunks:
            def step(carry, _):
                plane, t, met = launch(carry[0], carry[1], sched,
                                       MEGA_TICKS)
                return (plane, t), met
            (plane, t), met_main = jax.lax.scan(
                step, (plane, t), None, length=n_chunks)
            met_parts.append(met_main.reshape(n_chunks * MEGA_TICKS, 128))
        if rem:
            plane, t, met_rem = launch(plane, t, sched, rem)
            met_parts.append(met_rem)
        return assemble((plane, t), met_parts)

    if jax.default_backend() == "tpu":
        # the megakernel's whole-state-resident buffers + Mosaic stack
        # exceed the default 16 MB scoped-vmem window (measured ~34 MB
        # at N=4096, F=3); v5e has 128 MB of physical VMEM
        return jax.jit(run_body, compiler_options={
            "xla_tpu_scoped_vmem_limit_kib": "98304"})

    def run_eager(state: OverlayState, sched: OverlaySchedule):
        plane = _pack_state(cfg, state, sched)
        t = state.tick
        met_parts = []
        for _ in range(n_chunks):
            plane, t, met = launch(plane, t, sched, MEGA_TICKS)
            met_parts.append(met)
        if rem:
            plane, t, met = launch(plane, t, sched, rem)
            met_parts.append(met)
        return assemble((plane, t), met_parts)

    return run_eager
