"""Scenario catalog: named adversarial-failure families with
closed-form correctness oracles, and a fleet-scale sweep that grades
hundreds of seeded variants as ONE :class:`~..service.scheduler.FleetService`
run.

This is the protocol-level complement to the service-level chaos plane
(service/faults.py, PR 5): the chaos plane injects faults into the
SERVING machinery; this module injects failures into the SIMULATED
WORLD (worlds.py — partitions that heal, asymmetric per-link loss,
correlated failure waves, zombie peers gossiping stale tables,
flapping members, Byzantine liars forging freshness, per-link
delivery latency, and COMPOSED worlds layering several planes at
once) and grades the failure detector against what the protocol
provably owes under each.

Every family is a pure ``(family, seed) -> SimConfig`` mapping whose
windows are seed-independent config functions (seeds move WHICH nodes
are hit, never WHEN the world acts — worlds.py), so a whole sweep
buckets into one compiled program per family, its verdicts are pure
seed functions, and a failing variant replays from its
``(family, seed)`` pair alone (:func:`repro_command`).

Oracle philosophy: each family asserts only what the protocol
GUARANTEES in closed form — detection completeness at the exact
``fail + TREMOVE + 1`` horizon where the world is loss-free, zero
false removals of live members where silences stay under the
staleness horizon, re-convergence after a heal where a discovery path
exists — and the two models' honest differences are part of the
catalog: a dense full-view cluster split longer than TREMOVE is
PERMANENT (the reference protocol gossips only to known members — no
discovery path back), while the overlay re-converges (its XOR
exchange delivers by index, not by membership).

Round-2 oracle notes (docs/SCENARIOS.md has the full taxonomy):

* BYZ: the direct-sender-credit defense denies forged timestamp
  refresh, so the FIRST removal of a real victim stays on the exact
  honest horizon even with liars relaying boosted heartbeats; forged
  re-adds may cycle a purged id back in, but each cycle re-purges on
  schedule, so the end-state claim is a staleness bound, not absence.
* LATENCY: pure per-link delay does NOT admit a per-link tight
  window — heterogeneous link cadence lets post-death relays carry
  strictly-larger counters whose adoption refreshes timestamps — so
  the pure-latency family asserts the loose ``(0, 3*L]`` stretch.
  Composing BYZ on top removes exactly that refresh path, and the
  per-observer window TIGHTENS to ``(0, lat(victim, observer)]`` —
  the byz+latency family pins the sharper bound the defense buys.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Callable, Optional

import numpy as np

from .. import worlds
from ..config import INTRODUCER, SimConfig
from ..state import NEVER


@dataclasses.dataclass(frozen=True)
class Family:
    """One named scenario family: a config builder + its oracle."""

    name: str
    #: one-line statement of what the world does and what is owed
    claim: str
    build: Callable[[int], SimConfig]
    #: ``oracle(cfg, lane) -> [violation, ...]`` (empty = pass); the
    #: lane is a FleetSimulation lane / solo result (dense: events +
    #: final_state; overlay: metrics + final_state)
    oracle: Callable[[SimConfig, object], list]
    #: which adversarial world the family exercises (partition / asym /
    #: wave / zombie / flapping) — sweep reports count distinct worlds
    #: actually covered, not the catalog total
    world: str


# ---- shared oracle helpers -------------------------------------------

def _dense_events(lane):
    """{(observer, subject): first_removal_tick}, {(t, i, j) adds}."""
    removed = np.asarray(lane.removed)
    rem = {}
    for t, i, j in zip(*np.nonzero(removed)):
        rem.setdefault((int(i), int(j)), int(t))
    adds = {(int(t), int(i), int(j))
            for t, i, j in zip(*np.nonzero(np.asarray(lane.added)))}
    return rem, adds


def _dense_victims(cfg, lane):
    """Victim ids + per-victim fail tick from the lane's schedule."""
    fail = np.asarray(lane.fail_tick)
    vic = np.flatnonzero(fail != NEVER)
    return vic, fail


def _dense_detection_complete(cfg, lane, exact: bool) -> list:
    """Every victim removed from every live observer's view — at
    EXACTLY ``fail + t_remove + 1`` when the world is loss-free."""
    bad = []
    vic, fail = _dense_victims(cfg, lane)
    if vic.size == 0:
        return ["world never engaged: no victims scheduled"]
    rem, _ = _dense_events(lane)
    known = np.asarray(lane.final_state.known)
    live = np.ones(cfg.n, bool)
    live[vic] = False
    for v in vic:
        for i in np.flatnonzero(live):
            if known[i, v]:
                bad.append(f"victim {v} still in view of {i} at end")
            t_rm = rem.get((int(i), int(v)))
            horizon = int(fail[v]) + cfg.t_remove + 1
            if t_rm is None:
                if int(fail[v]) + cfg.t_remove + 1 <= cfg.total_ticks - 1:
                    bad.append(f"victim {v} never removed by {i}")
            elif exact and t_rm != horizon:
                bad.append(f"victim {v} removed by {i} at {t_rm}, "
                           f"expected exactly {horizon}")
            elif not exact and t_rm > horizon + 4:
                bad.append(f"victim {v} removed by {i} at {t_rm}, "
                           f"past horizon {horizon}+4")
    return bad


def _dense_no_false_removals(cfg, lane) -> list:
    """No removal event ever names a live (never-failed) subject."""
    vic, _ = _dense_victims(cfg, lane)
    rem, _ = _dense_events(lane)
    bad = [f"live member {j} removed by {i} at t={t}"
           for (i, j), t in rem.items() if j not in set(int(v) for v in vic)]
    return bad


def _dense_all_joined(cfg, lane) -> list:
    ig = np.asarray(lane.final_state.in_group)
    vic, fail = _dense_victims(cfg, lane)
    expect = np.ones(cfg.n, bool)
    expect[vic] = False
    missing = np.flatnonzero(expect & ~ig)
    return [f"nodes never joined: {missing.tolist()}"] if missing.size \
        else []


def _overlay_sched_arrays(cfg):
    import jax.numpy as jnp
    from .overlay import make_overlay_schedule
    sched = make_overlay_schedule(cfg)
    i = jnp.arange(cfg.n)
    return (np.asarray(sched.fail_of(i)), np.asarray(sched.rejoin_of(i)))


def _overlay_coverage(cfg, lane) -> list:
    """Union-coverage guarantees in their honest, 40-seed-checked
    form.  Coverage by the union of views is an EQUILIBRIUM property
    of the bounded-view overlay, not a per-tick invariant: a live
    member's entries can briefly fall out of every view between an
    eviction and its next advert (the re-advert tail — 1-3 tick blips
    in the ``live_uncovered`` series, so a point-in-time end check is
    a coin flip over which tick the run happens to stop on; seeds
    1026/1031 land the end tick on a blip).  What the protocol owes,
    and what is graded: every uncovered SPELL is transient — strictly
    shorter than ``t_remove`` (a live member uncovered that long would
    genuinely read as dead), and uncovered ticks are rare over the
    whole run.  The series is graded where it exists: solo runs track
    ``live_uncovered`` per tick, while fleet lanes deliberately report
    the -1 "not tracked" sentinel (the scatter behind the histogram
    serializes badly under batching — models/overlay.py), so inside
    the sweep only the final-state clause below applies and the spell
    bound is pinned by the solo repro path plus
    tests/test_worlds.py::test_overlay_coverage_spells_are_transient.
    The end-state clause is graded everywhere: no LIVE view still
    names a failed subject (failed holders' frozen tables are exempt:
    they stopped processing, so their stale victim entries are
    structural, not a detection failure)."""
    bad = []
    lu = np.asarray(lane.metrics.live_uncovered)
    nz = np.flatnonzero(lu > 0)
    if nz.size and not (lu < 0).any():
        spells = np.split(nz, np.flatnonzero(np.diff(nz) > 1) + 1)
        worst = max(len(s) for s in spells)
        if worst >= cfg.t_remove:
            bad.append(f"live members uncovered for {worst} consecutive "
                       f"ticks (>= t_remove={cfg.t_remove}): coverage "
                       "loss is not transient")
        if nz.size * 4 > lu.size:
            bad.append(f"live members uncovered on {nz.size}/{lu.size} "
                       "ticks: coverage is not the equilibrium")
    fail, rejoin = _overlay_sched_arrays(cfg)
    ids = np.asarray(lane.final_state.ids)
    t_end = int(np.asarray(lane.final_state.tick))
    failed = (t_end > fail) & (t_end <= rejoin)
    if cfg.flap_rate > 0:
        flap_at = worlds.make_flap_state(cfg)
        flap = np.array([flap_at(i, t_end)[0] for i in range(cfg.n)])
        failed = failed | flap
    live = np.asarray(lane.final_state.in_group) & ~failed
    vic = np.flatnonzero(failed)
    if vic.size:
        in_live = np.isin(ids[live], vic) & (ids[live] >= 0)
        if in_live.any():
            bad.append(f"{int(in_live.sum())} failed-subject entries "
                       "still in live views at end")
    return bad


def _overlay_no_false_removals(cfg, lane) -> list:
    fr = int(np.asarray(lane.metrics.false_removals).sum())
    return [f"{fr} false removals of live members"] if fr else []


# ---- the catalog ------------------------------------------------------

def _d(seed, **kw):
    base = dict(max_nnb=16, single_failure=True, drop_msg=False,
                total_ticks=120, fail_tick=40, seed=seed)
    base.update(kw)
    return SimConfig(**base)


def _o(seed, **kw):
    base = dict(model="overlay", max_nnb=64, single_failure=True,
                drop_msg=False, total_ticks=136, fail_tick=48,
                step_rate=8.0 / 64, seed=seed)
    base.update(kw)
    return SimConfig(**base)


def _partition_blip_oracle(cfg, lane):
    bad = _dense_all_joined(cfg, lane)
    rem, _ = _dense_events(lane)
    if rem:
        bad.append(f"sub-horizon partition caused {len(rem)} removals")
    known = np.asarray(lane.final_state.known)
    off = ~np.eye(cfg.n, dtype=bool)
    if not (known | ~off).all():
        bad.append("membership incomplete after the blip healed")
    return bad


def _partition_split_oracle(cfg, lane):
    bad = _dense_all_joined(cfg, lane)
    g = worlds.partition_groups_host(cfg)
    rem, _ = _dense_events(lane)
    cross = [(k, t) for k, t in rem.items() if g[k[0]] != g[k[1]]]
    same = [(k, t) for k, t in rem.items() if g[k[0]] == g[k[1]]]
    if not cross:
        bad.append("partition never bit: no cross-group removals")
    if same:
        bad.append(f"partition disturbed same-group liveness: {same[:3]}")
    known = np.asarray(lane.final_state.known)
    same_m = g[:, None] == g[None, :]
    off = ~np.eye(cfg.n, dtype=bool)
    if not (known | ~(same_m & off)).all():
        bad.append("same-group entries lost across the split")
    if known[~same_m].any():
        bad.append("cross-group entries survived a super-horizon split "
                   "(no discovery path exists — where did they come from?)")
    return bad


def _asym_oracle(cfg, lane):
    bad = _dense_all_joined(cfg, lane)
    bad += _dense_detection_complete(cfg, lane, exact=False)
    bad += _dense_no_false_removals(cfg, lane)
    return bad


def _wave_oracle(cfg, lane):
    bad = _dense_detection_complete(cfg, lane, exact=True)
    bad += _dense_no_false_removals(cfg, lane)
    return bad


def _zombie_oracle(cfg, lane):
    bad = _dense_detection_complete(cfg, lane, exact=True)
    bad += _dense_no_false_removals(cfg, lane)
    # the false-positive stress the world exists for: once an observer
    # removes the zombie, its stale table must not resurrect it
    rem, adds = _dense_events(lane)
    vic, _ = _dense_victims(cfg, lane)
    for v in vic:
        for (t, i, j) in adds:
            if j == int(v) and (i, j) in rem and t > rem[(i, j)]:
                bad.append(f"zombie {j} resurrected by {i} at t={t} "
                           f"(removed at {rem[(i, j)]})")
    return bad


def _flap_oracle(cfg, lane):
    bad = []
    if worlds.flap_mask_host(cfg).sum() < 1:
        bad.append("world never engaged: no flappers selected")
    bad += _dense_no_false_removals(cfg, lane)
    rem, _ = _dense_events(lane)
    if rem:
        # flap_down < t_remove: silences never cross the horizon
        bad.append(f"sub-horizon flapping caused {len(rem)} removals")
    bad += _dense_all_joined(cfg, lane)
    return bad


def _ov_partition_oracle(cfg, lane):
    # the overlay's partition TOLERANCE: a super-horizon split still
    # re-converges after the heal (delivery is by index)
    return _overlay_coverage(cfg, lane)


def _ov_wave_oracle(cfg, lane):
    bad = _overlay_coverage(cfg, lane)
    bad += _overlay_no_false_removals(cfg, lane)
    return bad


def _ov_zombie_oracle(cfg, lane):
    """Coverage (transient-spell form) + the failed-subject purge.
    Zero-false-removal-EVENTS is not claimed: the same re-advert tail
    that makes coverage an equilibrium property can push a quiet live
    member's entry past the staleness horizon in one view for a tick
    (seed 1034: two events at t=65, healed by the next advert, end
    state clean).  The spell bound in _overlay_coverage is the claim
    that such blips always heal."""
    return _overlay_coverage(cfg, lane)


def _ov_asym_oracle(cfg, lane):
    return _overlay_coverage(cfg, lane)


def _ov_flap_oracle(cfg, lane):
    bad = []
    if worlds.flap_mask_host(cfg).sum() < 1:
        bad.append("world never engaged: no flappers selected")
    bad += _overlay_coverage(cfg, lane)
    return bad


# ---- round-2 oracles: byz / latency / composed ------------------------

def _byz_staleness(cfg, lane) -> list:
    """No live view pins an entry past the staleness horizon at the
    end.  Forged re-adds may cycle a purged id back in, but the
    direct-credit defense guarantees every cycle re-purges on
    schedule — a stale pinned entry would mean forged freshness
    stuck, which is exactly what the defense forbids."""
    vic, _ = _dense_victims(cfg, lane)
    known = np.asarray(lane.final_state.known)
    ts = np.asarray(lane.final_state.ts)
    live = np.ones(cfg.n, bool)
    live[vic] = False
    stale = (known & (ts <= cfg.total_ticks - (cfg.t_remove + 1)))[live]
    return [f"{int(stale.sum())} stale entries pinned in live views "
            "at end"] if stale.any() else []


def _byz_first_removal_exact(cfg, lane) -> list:
    """Every live observer's FIRST removal of the real victim lands on
    the exact honest horizon ``fail + t_remove + 1``: liars relay
    boosted heartbeats for the corpse, but boosted counters earn no
    timestamp refresh (the defense), so detection is not delayed by a
    single tick.  Unlike :func:`_dense_detection_complete` this does
    NOT assert end-state absence — forged re-add/re-purge cycling is
    legal and graded by :func:`_byz_staleness` instead."""
    bad = []
    vic, fail = _dense_victims(cfg, lane)
    if vic.size == 0:
        return ["world never engaged: no victims scheduled"]
    rem, _ = _dense_events(lane)
    live = np.ones(cfg.n, bool)
    live[vic] = False
    for v in vic:
        horizon = int(fail[v]) + cfg.t_remove + 1
        for i in np.flatnonzero(live):
            t_rm = rem.get((int(i), int(v)))
            if t_rm is None:
                bad.append(f"victim {v} never removed by {i}")
            elif t_rm != horizon:
                bad.append(f"victim {v} first removed by {i} at "
                           f"{t_rm}, expected exactly {horizon}")
    return bad


def _byz_forge_oracle(cfg, lane):
    bad = _byz_first_removal_exact(cfg, lane)
    bad += _dense_no_false_removals(cfg, lane)
    bad += _byz_staleness(cfg, lane)
    bad += _dense_all_joined(cfg, lane)
    return bad


def _byz_ghost_oracle(cfg, lane):
    """No real failure: the only pressure is forged adds and boosted
    counters; what is owed is an untouched membership."""
    bad = []
    rem, _ = _dense_events(lane)
    if rem:
        bad.append(f"forgery alone caused {len(rem)} removals")
    bad += _dense_all_joined(cfg, lane)
    known = np.asarray(lane.final_state.known)
    off = ~np.eye(cfg.n, dtype=bool)
    if not (known | ~off).all():
        bad.append("membership incomplete under forged-add pressure")
    bad += _byz_staleness(cfg, lane)
    return bad


def _latency_loose_oracle(cfg, lane):
    """Pure per-link delay stretches detection by at most ``3 * L``
    ticks past the loss-free horizon and never manufactures a false
    removal.  The per-link tight window does NOT hold here (module
    docstring: relays refresh adoption timestamps); the byz+latency
    family pins the tight form."""
    bad = _dense_all_joined(cfg, lane)
    bad += _dense_no_false_removals(cfg, lane)
    vic, fail = _dense_victims(cfg, lane)
    if vic.size == 0:
        return ["world never engaged: no victims scheduled"]
    rem, _ = _dense_events(lane)
    known = np.asarray(lane.final_state.known)
    live = np.ones(cfg.n, bool)
    live[vic] = False
    lmax = 3 * cfg.link_latency
    for v in vic:
        base = int(fail[v]) + cfg.t_remove
        for i in np.flatnonzero(live):
            if known[i, v]:
                bad.append(f"victim {v} still in view of {i} at end")
            t_rm = rem.get((int(i), int(v)))
            if t_rm is None:
                if base + lmax <= cfg.total_ticks - 1:
                    bad.append(f"victim {v} never removed by {i}")
            elif not 1 <= t_rm - base <= lmax:
                bad.append(f"victim {v} removed by {i} at {t_rm}, "
                           f"outside ({base}, {base + lmax}]")
    return bad


def _byz_latency_tight_oracle(cfg, lane):
    """The composed sharpening: with liars present the defense stops
    ALL piggyback timestamp refresh, so the only freshness source is
    the victim's own direct sends and each observer's removal lands in
    the per-link window ``(fail + t_remove, fail + t_remove +
    lat(victim, observer)]`` — delay exactly the victim->observer link,
    never the relay topology."""
    bad = _dense_no_false_removals(cfg, lane)
    vic, fail = _dense_victims(cfg, lane)
    if vic.size == 0:
        return ["world never engaged: no victims scheduled"]
    rem, _ = _dense_events(lane)
    lat = worlds.link_latency_host(cfg)
    live = np.ones(cfg.n, bool)
    live[vic] = False
    for v in vic:
        base = int(fail[v]) + cfg.t_remove
        for i in np.flatnonzero(live):
            t_rm = rem.get((int(i), int(v)))
            hi = int(lat[int(v), int(i)])
            if t_rm is None:
                bad.append(f"victim {v} never removed by {i}")
            elif not 1 <= t_rm - base <= hi:
                bad.append(f"victim {v} removed by {i} at {t_rm}, "
                           f"outside ({base}, {base + hi}] "
                           f"(link delay {hi})")
    bad += _byz_staleness(cfg, lane)
    return bad


def _storm_oracle(cfg, lane):
    """The composition-grammar sentence ("a partition opens DURING a
    failure wave WHILE flappers flap") graded as completeness without
    a timing claim: the sub-horizon blip and flap add bounded
    interference, so every wave victim is still purged from every
    live view by the end, with zero false removals of STEADY members
    and everyone back in the group at the end.  Flappers are exempt
    from the false-removal claim: an up-edge whose JOINREQ lands
    inside the open partition is swallowed, leaving the flapper
    legitimately out of the group until its next up-edge — removing
    it meanwhile is correct detection of a member that really is
    absent, not a false positive (the all-joined check still pins
    the eventual recovery)."""
    bad = _dense_all_joined(cfg, lane)
    vic, fail = _dense_victims(cfg, lane)
    if vic.size == 0:
        return ["world never engaged: no victims scheduled"]
    vic_set = set(int(v) for v in vic)
    rem, _ = _dense_events(lane)
    flap_m = worlds.flap_mask_host(cfg)
    bad += [f"steady member {j} removed by {i} at t={t}"
            for (i, j), t in rem.items()
            if j not in vic_set and not flap_m[j]]
    known = np.asarray(lane.final_state.known)
    live = np.ones(cfg.n, bool)
    live[vic] = False
    for v in vic:
        for i in np.flatnonzero(live):
            if known[i, v]:
                bad.append(f"victim {v} still in view of {i} at end")
            # a flapper observer's rejoin WIPES its view, so the entry
            # can vanish without a removal event ever firing — for
            # flappers the end-state absence above is the whole claim
            if not flap_m[int(i)] and (int(i), int(v)) not in rem:
                bad.append(f"victim {v} never removed by {i}")
    return bad


def _composed_quiet_oracle(cfg, lane):
    """Composed sub-horizon worlds (blips, flaps, delays): none of the
    layered interference crosses the staleness horizon, so the
    detector owes total silence — zero removals, full membership."""
    bad = []
    rem, _ = _dense_events(lane)
    if rem:
        bad.append(f"sub-horizon composed world caused {len(rem)} "
                   "removals")
    bad += _dense_all_joined(cfg, lane)
    return bad


def _composed_asym_oracle(cfg, lane):
    """Zombie or wave composed with asymmetric loss: loose-horizon
    detection, no false removals, and (for the zombie) no
    resurrection by the stale table."""
    bad = _dense_detection_complete(cfg, lane, exact=False)
    bad += _dense_no_false_removals(cfg, lane)
    if cfg.zombie:
        rem, adds = _dense_events(lane)
        vic, _ = _dense_victims(cfg, lane)
        for v in vic:
            for (t, i, j) in adds:
                if j == int(v) and (i, j) in rem and t > rem[(i, j)]:
                    bad.append(f"zombie {j} resurrected by {i} at "
                               f"t={t} (removed at {rem[(i, j)]})")
    return bad


def _ov_failed_and_live(cfg, lane):
    fail, rejoin = _overlay_sched_arrays(cfg)
    t_end = int(np.asarray(lane.final_state.tick))
    failed = (t_end > fail) & (t_end <= rejoin)
    if cfg.flap_rate > 0:
        flap_at = worlds.make_flap_state(cfg)
        flap = np.array([flap_at(i, t_end)[0] for i in range(cfg.n)])
        failed = failed | flap
    return failed, np.asarray(lane.final_state.in_group) & ~failed


def _ov_victim_purged(cfg, lane) -> list:
    """No LIVE view still names a failed subject at the end."""
    failed, live = _ov_failed_and_live(cfg, lane)
    ids = np.asarray(lane.final_state.ids)
    vic = np.flatnonzero(failed)
    if vic.size:
        in_live = np.isin(ids[live], vic) & (ids[live] >= 0)
        if in_live.any():
            return [f"{int(in_live.sum())} failed-subject entries "
                    "still in live views at end"]
    return []


def _ov_all_joined(cfg, lane) -> list:
    failed, _ = _ov_failed_and_live(cfg, lane)
    ig = np.asarray(lane.final_state.in_group)
    missing = np.flatnonzero(~ig & ~failed)
    return [f"nodes never joined: {missing.tolist()}"] if missing.size \
        else []


def _ov_round2_oracle(cfg, lane):
    """The overlay's round-2 contract under delay and composed
    storms: failed subjects purged from live views, zero false
    removals, everyone (eventually) in the group.  Deliberately NOT
    asserted: live COVERAGE — under heterogeneous per-link delay (or
    a composed storm's slot pressure) a live remote whose links all
    delay looks stale and can legitimately lose every slot-priority
    contest, so coverage is a delay-free-world guarantee only (the
    round-1 families pin it there)."""
    bad = _ov_victim_purged(cfg, lane)
    bad += _overlay_no_false_removals(cfg, lane)
    bad += _ov_all_joined(cfg, lane)
    return bad


def _ov_byz_oracle(cfg, lane):
    """The overlay under liars claims LESS than the dense model: the
    shield attack genuinely works against bounded views — a liar
    re-advertising the corpse at the clamp ceiling every exchange can
    pin it past the staleness horizon (seeds exist where it persists
    to the end; slot-priority eviction usually, not always, decays
    it).  So victim purge is NOT owed here.  What the clamp defense
    does still owe: boosted counters freeze honest refresh for at most
    ``byz_boost`` ticks, under the staleness horizon, so liars can
    neither falsely remove an honest member nor keep anyone out of
    the group."""
    bad = _overlay_no_false_removals(cfg, lane)
    bad += _ov_all_joined(cfg, lane)
    return bad


#: the catalog: family name -> Family.  Dense families grade the
#: reference-faithful full-view protocol (exact horizons); overlay
#: families grade the bounded-partial-view scaling model (coverage
#: and purge guarantees).  Every one of the five round-1 worlds
#: appears in both models except the dense split/blip pair, which
#: together pin the partition world's two dense regimes; round 2 adds
#: the BYZ and LATENCY planes and the COMPOSED worlds (several planes
#: layered on one failure script — worlds.composition).
CATALOG: dict[str, Family] = {}


def _register(name, claim, build, oracle):
    world = name.split("_")[1]  # <model>_<world>[_<variant>]
    CATALOG[name] = Family(name=name, claim=claim, build=build,
                           oracle=oracle, world=world)


_register(
    "dense_partition_blip",
    "a partition shorter than TREMOVE heals with zero removals",
    lambda s: _d(s, partition_groups=2, partition_open_tick=30,
                 partition_close_tick=42, fail_tick=10_000),
    _partition_blip_oracle)
_register(
    "dense_partition_split",
    "a partition longer than TREMOVE splits the full-view cluster "
    "permanently (no discovery path), without touching same-group "
    "liveness",
    lambda s: _d(s, partition_groups=2, partition_open_tick=30,
                 partition_close_tick=70, total_ticks=160,
                 fail_tick=10_000),
    _partition_split_oracle)
_register(
    "dense_asym_drop",
    "per-link loss up to 2x the mean neither hides a real failure "
    "nor manufactures a false one",
    lambda s: _d(s, drop_msg=True, msg_drop_prob=0.12, asym_drop=True,
                 drop_open_tick=10, drop_close_tick=110),
    _asym_oracle)
_register(
    "dense_wave",
    "a correlated k-node wave is detected victim-by-victim at exactly "
    "fail + TREMOVE + 1",
    lambda s: _d(s, single_failure=False, wave_size=6, wave_tick=40,
                 wave_speed=2),
    _wave_oracle)
_register(
    "dense_zombie",
    "a zombie gossiping its frozen table is detected on the silent-"
    "failure horizon and never resurrected",
    lambda s: _d(s, zombie=True, total_ticks=140),
    _zombie_oracle)
_register(
    "dense_flapping",
    "flapping below the staleness horizon causes zero removals",
    lambda s: _d(s, flap_rate=0.4, flap_period=24, flap_down=6,
                 fail_tick=10_000, total_ticks=140),
    _flap_oracle)
_register(
    "overlay_partition_heal",
    "the overlay re-converges after a super-horizon partition "
    "(index-addressed delivery is the discovery path the dense model "
    "lacks)",
    lambda s: _o(s, partition_groups=2, partition_open_tick=30,
                 partition_close_tick=90, total_ticks=168,
                 fail_tick=10_000),
    _ov_partition_oracle)
_register(
    "overlay_asym_drop",
    "asymmetric per-link loss leaves live coverage intact and the "
    "victim purged",
    lambda s: _o(s, drop_msg=True, msg_drop_prob=0.1, asym_drop=True,
                 drop_open_tick=10, drop_close_tick=110),
    _ov_asym_oracle)
_register(
    "overlay_wave",
    "every wave victim is purged from every live view; live coverage "
    "holds",
    lambda s: _o(s, single_failure=False, wave_size=12, wave_tick=48,
                 wave_speed=2, total_ticks=168),
    _ov_wave_oracle)
_register(
    "overlay_zombie",
    "a zombie's frozen tables earn no liveness credit: purged on "
    "schedule, coverage the equilibrium (re-advert blips heal)",
    lambda s: _o(s, zombie=True, total_ticks=168),
    _ov_zombie_oracle)
_register(
    "overlay_flapping",
    "sub-horizon flapping: no false removals, coverage the "
    "equilibrium through the flap window",
    lambda s: _o(s, flap_rate=0.3, flap_period=24, flap_down=6,
                 fail_tick=10_000, total_ticks=168),
    _ov_flap_oracle)

# ---- round 2: byz / latency / composed worlds ------------------------

_register(
    "dense_byz_forge",
    "liars boosting the corpse's heartbeat cannot delay first removal "
    "past the exact honest horizon (direct-credit defense)",
    lambda s: _d(s, max_nnb=32, byz_rate=0.2, byz_boost=8),
    _byz_forge_oracle)
_register(
    "dense_byz_ghost",
    "sustained forged-add pressure with no real failure leaves "
    "membership untouched: zero removals, no stale pins",
    lambda s: _d(s, max_nnb=32, byz_rate=0.25, byz_boost=12,
                 fail_tick=10_000),
    _byz_ghost_oracle)
_register(
    "dense_latency",
    "per-link delay stretches detection at most 3*L past the "
    "loss-free horizon, with zero false removals",
    lambda s: _d(s, link_latency=4),
    _latency_loose_oracle)
_register(
    "dense_composed_byz_latency",
    "liars + per-link delay TIGHTEN the window: removal lands within "
    "exactly the victim->observer link delay (the defense removes the "
    "relay refresh that loosens pure latency)",
    lambda s: _d(s, max_nnb=32, byz_rate=0.2, byz_boost=8,
                 link_latency=4, total_ticks=140),
    _byz_latency_tight_oracle)
_register(
    "dense_composed_storm",
    "a partition opens DURING a failure wave WHILE flappers flap: "
    "every wave victim still purged everywhere, no steady member "
    "falsely removed, everyone back in the group",
    lambda s: _d(s, max_nnb=32, single_failure=False, wave_size=6,
                 wave_tick=60, wave_speed=2, partition_groups=2,
                 partition_open_tick=57, partition_close_tick=63,
                 flap_rate=0.2, flap_period=24, flap_down=6,
                 flap_open_tick=40, flap_close_tick=100,
                 total_ticks=160),
    _storm_oracle)
_register(
    "dense_composed_wave_asym",
    "a correlated wave under asymmetric per-link loss is detected on "
    "the loose horizon with zero false removals",
    lambda s: _d(s, single_failure=False, wave_size=6, wave_tick=40,
                 wave_speed=2, drop_msg=True, msg_drop_prob=0.12,
                 asym_drop=True, drop_open_tick=10,
                 drop_close_tick=110),
    _composed_asym_oracle)
_register(
    "dense_composed_zombie_asym",
    "a zombie's frozen table under asymmetric loss: loose-horizon "
    "detection, no resurrection, no false removals",
    lambda s: _d(s, zombie=True, drop_msg=True, msg_drop_prob=0.1,
                 asym_drop=True, drop_open_tick=10,
                 drop_close_tick=120, total_ticks=140),
    _composed_asym_oracle)
_register(
    "dense_composed_latency_flap",
    "flap-down plus worst-case link delay stays under the staleness "
    "horizon: composed interference owes total silence",
    lambda s: _d(s, link_latency=4, flap_rate=0.3, flap_period=24,
                 flap_down=6, fail_tick=10_000, total_ticks=140),
    _composed_quiet_oracle)
_register(
    "dense_composed_part_flap",
    "a sub-horizon blip composed with sub-horizon flapping: zero "
    "removals even where the silences abut",
    lambda s: _d(s, partition_groups=2, partition_open_tick=30,
                 partition_close_tick=38, flap_rate=0.3,
                 flap_period=24, flap_down=6, flap_open_tick=50,
                 flap_close_tick=110, fail_tick=10_000,
                 total_ticks=140),
    _composed_quiet_oracle)
_register(
    "overlay_byz_shield",
    "liars may shield the corpse in bounded views (the attack is "
    "real) but can neither falsely remove an honest member nor keep "
    "anyone out of the group",
    lambda s: _o(s, byz_rate=0.15, byz_boost=8, total_ticks=168),
    _ov_byz_oracle)
_register(
    "overlay_latency",
    "per-link delay through the XOR exchange: victim purged, zero "
    "false removals (coverage not owed — delayed links make a live "
    "member look stale to slot-priority eviction)",
    lambda s: _o(s, link_latency=4, total_ticks=168),
    _ov_round2_oracle)
_register(
    "overlay_composed_byz_latency",
    "liars over delayed links: the boost-freeze (byz_boost ticks) "
    "plus worst-case delay stays under the staleness horizon, so no "
    "honest member is falsely removed and the join plane is untouched",
    lambda s: _o(s, byz_rate=0.15, byz_boost=4, link_latency=3,
                 total_ticks=168),
    _ov_byz_oracle)
def _ov_zombie_asym_oracle(cfg, lane):
    """Composed zombie + asymmetric loss: the zombie's frozen tables
    earn no liveness credit (victim purged from live views) and the
    join plane holds.  Zero-false-removals is NOT claimed — like the
    round-1 asym family, sustained per-link loss can legitimately
    push an honest silence past the staleness horizon (SWIM's
    guarantee is probabilistic under loss)."""
    bad = _ov_victim_purged(cfg, lane)
    bad += _ov_all_joined(cfg, lane)
    return bad


_register(
    "overlay_composed_zombie_asym",
    "a zombie's frozen tables under asymmetric loss: no liveness "
    "credit — victim purged from live views, join plane untouched",
    lambda s: _o(s, zombie=True, drop_msg=True, msg_drop_prob=0.06,
                 asym_drop=True, drop_open_tick=10,
                 drop_close_tick=120, total_ticks=168),
    _ov_zombie_asym_oracle)
_register(
    "overlay_composed_gauntlet",
    "wave + sub-horizon blip + flappers on the overlay: coverage and "
    "purge survive the full composed storm",
    lambda s: _o(s, single_failure=False, wave_size=12, wave_tick=48,
                 wave_speed=2, partition_groups=2,
                 partition_open_tick=44, partition_close_tick=56,
                 flap_rate=0.2, flap_period=24, flap_down=6,
                 flap_open_tick=64, flap_close_tick=128,
                 total_ticks=192),
    _ov_round2_oracle)


def variants(families=None, seeds_per_family: int = 40,
             seed0: int = 1000) -> list:
    """The sweep's (family, seed) list, seed-major interleaved (like
    service/replay.build_trace: buckets fill concurrently)."""
    fams = [CATALOG[f] for f in (families or sorted(CATALOG))]
    return [(fam, seed0 + s) for s in range(seeds_per_family)
            for fam in fams]


def grade(family: Family, seed: int, lane) -> list:
    """One variant's oracle verdict: a list of violations (empty =
    pass)."""
    return family.oracle(family.build(seed), lane)


def _lane_digest(cfg: SimConfig, lane) -> str:
    h = hashlib.sha256()
    if cfg.model == "overlay":
        for f in ("ids", "hb", "ts", "in_group", "own_hb"):
            h.update(np.ascontiguousarray(
                np.asarray(getattr(lane.final_state, f))).tobytes())
    else:
        for f in ("known", "hb", "ts", "in_group"):
            h.update(np.ascontiguousarray(
                np.asarray(getattr(lane.final_state, f))).tobytes())
        h.update(np.ascontiguousarray(np.asarray(lane.removed)).tobytes())
    return h.hexdigest()[:16]


def repro_command(family: str, seed: int) -> str:
    """The exact single-variant repro a sweep failure prints."""
    return (f"PYTHONPATH=. python scripts/service_smoke.py scenario "
            f"--family {family} --seed {seed}")


def run_solo(family: str, seed: int):
    """One variant, no service — the repro path.  Returns
    ``(violations, lane_digest)``."""
    fam = CATALOG[family]
    cfg = fam.build(seed)
    from ..service.resilience import solo_execute
    lane = solo_execute(cfg, "trace")
    return grade(fam, seed, lane), _lane_digest(cfg, lane)


def sweep(families=None, seeds_per_family: int = 40, max_batch: int = 8,
          mesh=None, seed0: int = 1000, service=None,
          raise_on_fail: bool = True) -> dict:
    """Grade ``len(families) * seeds_per_family`` seeded scenario
    variants as ONE FleetService run.

    Gates enforced in-line: 100% of submitted variants reach a
    terminal completed state (a stranded or failed handle raises), and
    every variant's oracle verdict is recorded.  With the default
    catalog and ``seeds_per_family=40`` that is 1000 variants spanning
    all eight worlds (the five round-1 planes plus byz, latency, and
    the composed storms) on both models.  The returned ``verdict_digest`` /
    ``outcome_digest`` are pure functions of (families, seeds, mesh
    width): identical seeds must reproduce them digest-for-digest —
    the scenario replay gate (scripts/service_smoke.py scenarios,
    bench.py ``secondary.scenario_sweep``).

    On oracle failures the report names each failing variant with its
    violations AND the exact single-variant repro command.
    """
    from ..service.scheduler import FleetService
    var = variants(families, seeds_per_family, seed0)
    svc = service if service is not None else FleetService(
        max_batch=max_batch, mesh=mesh)
    done = set()
    for fam, _ in var:
        if fam.name not in done:
            done.add(fam.name)
            svc.warm(fam.build(seed0), "trace")
    t0 = time.perf_counter()
    handles = [(fam, seed, svc.submit(fam.build(seed), mode="trace"))
               for fam, seed in var]
    svc.drain()
    wall = time.perf_counter() - t0
    stranded = [h.request.rid for _, _, h in handles if not h.done]
    failed = [h.request.rid for _, _, h in handles if h.failed]
    if stranded or failed:
        errs = "; ".join(f"rid {h.request.rid}: {h.exception()!r}"
                         for _, _, h in handles if h.failed)[:500]
        raise RuntimeError(
            f"scenario sweep left {len(stranded)} stranded and "
            f"{len(failed)} failed handles of {len(handles)}: {errs}")
    rows = []
    fails = []
    per_family: dict[str, dict] = {}
    for fam, seed, h in handles:
        lane = h.result()
        cfg = fam.build(seed)
        violations = grade(fam, seed, lane)
        rows.append((fam.name, seed, tuple(violations),
                     _lane_digest(cfg, lane)))
        pf = per_family.setdefault(fam.name, {"pass": 0, "fail": 0})
        if violations:
            pf["fail"] += 1
            fails.append((fam.name, seed, violations))
        else:
            pf["pass"] += 1
    verdict_digest = hashlib.sha256(
        repr([(r[0], r[1], r[2]) for r in rows]).encode()).hexdigest()[:16]
    outcome_digest = hashlib.sha256(
        repr([(r[0], r[1], r[3]) for r in rows]).encode()).hexdigest()[:16]
    stats = svc.stats()
    report = {
        "variants": len(var),
        "families": len(done),
        "worlds": len({fam.world for fam, _ in var}),
        "passed": sum(pf["pass"] for pf in per_family.values()),
        "failed": sum(pf["fail"] for pf in per_family.values()),
        "pass_rate": round(sum(pf["pass"] for pf in per_family.values())
                           / max(len(var), 1), 4),
        "per_family": per_family,
        "verdict_digest": verdict_digest,
        "outcome_digest": outcome_digest,
        "wall_s": round(wall, 3),
        "devices": stats["devices"],
        "dispatches": stats["dispatches"],
        "mean_occupancy": stats["mean_occupancy"],
        "buckets": stats["cache"]["buckets"],
        "completed": stats["completed"],
        "terminal_rate": round(
            (len(handles) - len(stranded) - len(failed))
            / max(len(handles), 1), 4),
    }
    if fails and raise_on_fail:
        lines = [f"  {f}/{s}: {v[:2]}\n    repro: {repro_command(f, s)}"
                 for f, s, v in fails[:8]]
        raise RuntimeError(
            f"scenario sweep: {len(fails)}/{len(var)} variants failed "
            "their oracle:\n" + "\n".join(lines))
    report["failures"] = [
        {"family": f, "seed": s, "violations": list(v)[:4],
         "repro": repro_command(f, s)} for f, s, v in fails]
    return report
