"""Scenario catalog: named adversarial-failure families with
closed-form correctness oracles, and a fleet-scale sweep that grades
hundreds of seeded variants as ONE :class:`~..service.scheduler.FleetService`
run.

This is the protocol-level complement to the service-level chaos plane
(service/faults.py, PR 5): the chaos plane injects faults into the
SERVING machinery; this module injects failures into the SIMULATED
WORLD (worlds.py — partitions that heal, asymmetric per-link loss,
correlated failure waves, zombie peers gossiping stale tables,
flapping members) and grades the failure detector against what the
protocol provably owes under each.

Every family is a pure ``(family, seed) -> SimConfig`` mapping whose
windows are seed-independent config functions (seeds move WHICH nodes
are hit, never WHEN the world acts — worlds.py), so a whole sweep
buckets into one compiled program per family, its verdicts are pure
seed functions, and a failing variant replays from its
``(family, seed)`` pair alone (:func:`repro_command`).

Oracle philosophy: each family asserts only what the protocol
GUARANTEES in closed form — detection completeness at the exact
``fail + TREMOVE + 1`` horizon where the world is loss-free, zero
false removals of live members where silences stay under the
staleness horizon, re-convergence after a heal where a discovery path
exists — and the two models' honest differences are part of the
catalog: a dense full-view cluster split longer than TREMOVE is
PERMANENT (the reference protocol gossips only to known members — no
discovery path back), while the overlay re-converges (its XOR
exchange delivers by index, not by membership).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Callable, Optional

import numpy as np

from .. import worlds
from ..config import INTRODUCER, SimConfig
from ..state import NEVER


@dataclasses.dataclass(frozen=True)
class Family:
    """One named scenario family: a config builder + its oracle."""

    name: str
    #: one-line statement of what the world does and what is owed
    claim: str
    build: Callable[[int], SimConfig]
    #: ``oracle(cfg, lane) -> [violation, ...]`` (empty = pass); the
    #: lane is a FleetSimulation lane / solo result (dense: events +
    #: final_state; overlay: metrics + final_state)
    oracle: Callable[[SimConfig, object], list]
    #: which adversarial world the family exercises (partition / asym /
    #: wave / zombie / flapping) — sweep reports count distinct worlds
    #: actually covered, not the catalog total
    world: str


# ---- shared oracle helpers -------------------------------------------

def _dense_events(lane):
    """{(observer, subject): first_removal_tick}, {(t, i, j) adds}."""
    removed = np.asarray(lane.removed)
    rem = {}
    for t, i, j in zip(*np.nonzero(removed)):
        rem.setdefault((int(i), int(j)), int(t))
    adds = {(int(t), int(i), int(j))
            for t, i, j in zip(*np.nonzero(np.asarray(lane.added)))}
    return rem, adds


def _dense_victims(cfg, lane):
    """Victim ids + per-victim fail tick from the lane's schedule."""
    fail = np.asarray(lane.fail_tick)
    vic = np.flatnonzero(fail != NEVER)
    return vic, fail


def _dense_detection_complete(cfg, lane, exact: bool) -> list:
    """Every victim removed from every live observer's view — at
    EXACTLY ``fail + t_remove + 1`` when the world is loss-free."""
    bad = []
    vic, fail = _dense_victims(cfg, lane)
    if vic.size == 0:
        return ["world never engaged: no victims scheduled"]
    rem, _ = _dense_events(lane)
    known = np.asarray(lane.final_state.known)
    live = np.ones(cfg.n, bool)
    live[vic] = False
    for v in vic:
        for i in np.flatnonzero(live):
            if known[i, v]:
                bad.append(f"victim {v} still in view of {i} at end")
            t_rm = rem.get((int(i), int(v)))
            horizon = int(fail[v]) + cfg.t_remove + 1
            if t_rm is None:
                if int(fail[v]) + cfg.t_remove + 1 <= cfg.total_ticks - 1:
                    bad.append(f"victim {v} never removed by {i}")
            elif exact and t_rm != horizon:
                bad.append(f"victim {v} removed by {i} at {t_rm}, "
                           f"expected exactly {horizon}")
            elif not exact and t_rm > horizon + 4:
                bad.append(f"victim {v} removed by {i} at {t_rm}, "
                           f"past horizon {horizon}+4")
    return bad


def _dense_no_false_removals(cfg, lane) -> list:
    """No removal event ever names a live (never-failed) subject."""
    vic, _ = _dense_victims(cfg, lane)
    rem, _ = _dense_events(lane)
    bad = [f"live member {j} removed by {i} at t={t}"
           for (i, j), t in rem.items() if j not in set(int(v) for v in vic)]
    return bad


def _dense_all_joined(cfg, lane) -> list:
    ig = np.asarray(lane.final_state.in_group)
    vic, fail = _dense_victims(cfg, lane)
    expect = np.ones(cfg.n, bool)
    expect[vic] = False
    missing = np.flatnonzero(expect & ~ig)
    return [f"nodes never joined: {missing.tolist()}"] if missing.size \
        else []


def _overlay_sched_arrays(cfg):
    import jax.numpy as jnp
    from .overlay import make_overlay_schedule
    sched = make_overlay_schedule(cfg)
    i = jnp.arange(cfg.n)
    return (np.asarray(sched.fail_of(i)), np.asarray(sched.rejoin_of(i)))


def _overlay_coverage(cfg, lane) -> list:
    """Final-table guarantees, per the overlay's documented contract
    (models/overlay.py module docstring): every live member is covered
    by the UNION of views — all views, the same union
    ``OverlayResult.uncovered_members`` samples — and no LIVE view
    still names a failed subject (failed holders' frozen tables are
    exempt: they stopped processing, so their stale victim entries are
    structural, not a detection failure)."""
    bad = []
    fail, rejoin = _overlay_sched_arrays(cfg)
    ids = np.asarray(lane.final_state.ids)
    t_end = int(np.asarray(lane.final_state.tick))
    failed = (t_end > fail) & (t_end <= rejoin)
    if cfg.flap_rate > 0:
        flap_at = worlds.make_flap_state(cfg)
        flap = np.array([flap_at(i, t_end)[0] for i in range(cfg.n)])
        failed = failed | flap
    live = np.asarray(lane.final_state.in_group) & ~failed
    present = np.zeros(cfg.n, bool)
    present[ids[ids >= 0]] = True
    i = np.arange(cfg.n)
    unc = np.flatnonzero(live & ~present & (i != INTRODUCER))
    if unc.size:
        bad.append(f"live members uncovered at end: {unc.tolist()}")
    vic = np.flatnonzero(failed)
    if vic.size:
        in_live = np.isin(ids[live], vic) & (ids[live] >= 0)
        if in_live.any():
            bad.append(f"{int(in_live.sum())} failed-subject entries "
                       "still in live views at end")
    return bad


def _overlay_no_false_removals(cfg, lane) -> list:
    fr = int(np.asarray(lane.metrics.false_removals).sum())
    return [f"{fr} false removals of live members"] if fr else []


# ---- the catalog ------------------------------------------------------

def _d(seed, **kw):
    base = dict(max_nnb=16, single_failure=True, drop_msg=False,
                total_ticks=120, fail_tick=40, seed=seed)
    base.update(kw)
    return SimConfig(**base)


def _o(seed, **kw):
    base = dict(model="overlay", max_nnb=64, single_failure=True,
                drop_msg=False, total_ticks=136, fail_tick=48,
                step_rate=8.0 / 64, seed=seed)
    base.update(kw)
    return SimConfig(**base)


def _partition_blip_oracle(cfg, lane):
    bad = _dense_all_joined(cfg, lane)
    rem, _ = _dense_events(lane)
    if rem:
        bad.append(f"sub-horizon partition caused {len(rem)} removals")
    known = np.asarray(lane.final_state.known)
    off = ~np.eye(cfg.n, dtype=bool)
    if not (known | ~off).all():
        bad.append("membership incomplete after the blip healed")
    return bad


def _partition_split_oracle(cfg, lane):
    bad = _dense_all_joined(cfg, lane)
    g = worlds.partition_groups_host(cfg)
    rem, _ = _dense_events(lane)
    cross = [(k, t) for k, t in rem.items() if g[k[0]] != g[k[1]]]
    same = [(k, t) for k, t in rem.items() if g[k[0]] == g[k[1]]]
    if not cross:
        bad.append("partition never bit: no cross-group removals")
    if same:
        bad.append(f"partition disturbed same-group liveness: {same[:3]}")
    known = np.asarray(lane.final_state.known)
    same_m = g[:, None] == g[None, :]
    off = ~np.eye(cfg.n, dtype=bool)
    if not (known | ~(same_m & off)).all():
        bad.append("same-group entries lost across the split")
    if known[~same_m].any():
        bad.append("cross-group entries survived a super-horizon split "
                   "(no discovery path exists — where did they come from?)")
    return bad


def _asym_oracle(cfg, lane):
    bad = _dense_all_joined(cfg, lane)
    bad += _dense_detection_complete(cfg, lane, exact=False)
    bad += _dense_no_false_removals(cfg, lane)
    return bad


def _wave_oracle(cfg, lane):
    bad = _dense_detection_complete(cfg, lane, exact=True)
    bad += _dense_no_false_removals(cfg, lane)
    return bad


def _zombie_oracle(cfg, lane):
    bad = _dense_detection_complete(cfg, lane, exact=True)
    bad += _dense_no_false_removals(cfg, lane)
    # the false-positive stress the world exists for: once an observer
    # removes the zombie, its stale table must not resurrect it
    rem, adds = _dense_events(lane)
    vic, _ = _dense_victims(cfg, lane)
    for v in vic:
        for (t, i, j) in adds:
            if j == int(v) and (i, j) in rem and t > rem[(i, j)]:
                bad.append(f"zombie {j} resurrected by {i} at t={t} "
                           f"(removed at {rem[(i, j)]})")
    return bad


def _flap_oracle(cfg, lane):
    bad = []
    if worlds.flap_mask_host(cfg).sum() < 1:
        bad.append("world never engaged: no flappers selected")
    bad += _dense_no_false_removals(cfg, lane)
    rem, _ = _dense_events(lane)
    if rem:
        # flap_down < t_remove: silences never cross the horizon
        bad.append(f"sub-horizon flapping caused {len(rem)} removals")
    bad += _dense_all_joined(cfg, lane)
    return bad


def _ov_partition_oracle(cfg, lane):
    # the overlay's partition TOLERANCE: a super-horizon split still
    # re-converges after the heal (delivery is by index)
    return _overlay_coverage(cfg, lane)


def _ov_wave_oracle(cfg, lane):
    bad = _overlay_coverage(cfg, lane)
    bad += _overlay_no_false_removals(cfg, lane)
    return bad


def _ov_zombie_oracle(cfg, lane):
    bad = _overlay_coverage(cfg, lane)
    bad += _overlay_no_false_removals(cfg, lane)
    return bad


def _ov_asym_oracle(cfg, lane):
    return _overlay_coverage(cfg, lane)


def _ov_flap_oracle(cfg, lane):
    bad = []
    if worlds.flap_mask_host(cfg).sum() < 1:
        bad.append("world never engaged: no flappers selected")
    bad += _overlay_coverage(cfg, lane)
    return bad


#: the catalog: family name -> Family.  Dense families grade the
#: reference-faithful full-view protocol (exact horizons); overlay
#: families grade the bounded-partial-view scaling model (coverage
#: and purge guarantees).  Every one of the five worlds appears in
#: both models except the dense split/blip pair, which together pin
#: the partition world's two dense regimes.
CATALOG: dict[str, Family] = {}


def _register(name, claim, build, oracle):
    world = name.split("_")[1]  # <model>_<world>[_<variant>]
    CATALOG[name] = Family(name=name, claim=claim, build=build,
                           oracle=oracle, world=world)


_register(
    "dense_partition_blip",
    "a partition shorter than TREMOVE heals with zero removals",
    lambda s: _d(s, partition_groups=2, partition_open_tick=30,
                 partition_close_tick=42, fail_tick=10_000),
    _partition_blip_oracle)
_register(
    "dense_partition_split",
    "a partition longer than TREMOVE splits the full-view cluster "
    "permanently (no discovery path), without touching same-group "
    "liveness",
    lambda s: _d(s, partition_groups=2, partition_open_tick=30,
                 partition_close_tick=70, total_ticks=160,
                 fail_tick=10_000),
    _partition_split_oracle)
_register(
    "dense_asym_drop",
    "per-link loss up to 2x the mean neither hides a real failure "
    "nor manufactures a false one",
    lambda s: _d(s, drop_msg=True, msg_drop_prob=0.12, asym_drop=True,
                 drop_open_tick=10, drop_close_tick=110),
    _asym_oracle)
_register(
    "dense_wave",
    "a correlated k-node wave is detected victim-by-victim at exactly "
    "fail + TREMOVE + 1",
    lambda s: _d(s, single_failure=False, wave_size=6, wave_tick=40,
                 wave_speed=2),
    _wave_oracle)
_register(
    "dense_zombie",
    "a zombie gossiping its frozen table is detected on the silent-"
    "failure horizon and never resurrected",
    lambda s: _d(s, zombie=True, total_ticks=140),
    _zombie_oracle)
_register(
    "dense_flapping",
    "flapping below the staleness horizon causes zero removals",
    lambda s: _d(s, flap_rate=0.4, flap_period=24, flap_down=6,
                 fail_tick=10_000, total_ticks=140),
    _flap_oracle)
_register(
    "overlay_partition_heal",
    "the overlay re-converges after a super-horizon partition "
    "(index-addressed delivery is the discovery path the dense model "
    "lacks)",
    lambda s: _o(s, partition_groups=2, partition_open_tick=30,
                 partition_close_tick=90, total_ticks=168,
                 fail_tick=10_000),
    _ov_partition_oracle)
_register(
    "overlay_asym_drop",
    "asymmetric per-link loss leaves live coverage intact and the "
    "victim purged",
    lambda s: _o(s, drop_msg=True, msg_drop_prob=0.1, asym_drop=True,
                 drop_open_tick=10, drop_close_tick=110),
    _ov_asym_oracle)
_register(
    "overlay_wave",
    "every wave victim is purged from every live view; live coverage "
    "holds",
    lambda s: _o(s, single_failure=False, wave_size=12, wave_tick=48,
                 wave_speed=2, total_ticks=168),
    _ov_wave_oracle)
_register(
    "overlay_zombie",
    "a zombie's frozen tables earn no liveness credit: purged on "
    "schedule, coverage intact",
    lambda s: _o(s, zombie=True, total_ticks=168),
    _ov_zombie_oracle)
_register(
    "overlay_flapping",
    "sub-horizon flapping: no false removals, full coverage once the "
    "flap window closes",
    lambda s: _o(s, flap_rate=0.3, flap_period=24, flap_down=6,
                 fail_tick=10_000, total_ticks=168),
    _ov_flap_oracle)


def variants(families=None, seeds_per_family: int = 20,
             seed0: int = 1000) -> list:
    """The sweep's (family, seed) list, seed-major interleaved (like
    service/replay.build_trace: buckets fill concurrently)."""
    fams = [CATALOG[f] for f in (families or sorted(CATALOG))]
    return [(fam, seed0 + s) for s in range(seeds_per_family)
            for fam in fams]


def grade(family: Family, seed: int, lane) -> list:
    """One variant's oracle verdict: a list of violations (empty =
    pass)."""
    return family.oracle(family.build(seed), lane)


def _lane_digest(cfg: SimConfig, lane) -> str:
    h = hashlib.sha256()
    if cfg.model == "overlay":
        for f in ("ids", "hb", "ts", "in_group", "own_hb"):
            h.update(np.ascontiguousarray(
                np.asarray(getattr(lane.final_state, f))).tobytes())
    else:
        for f in ("known", "hb", "ts", "in_group"):
            h.update(np.ascontiguousarray(
                np.asarray(getattr(lane.final_state, f))).tobytes())
        h.update(np.ascontiguousarray(np.asarray(lane.removed)).tobytes())
    return h.hexdigest()[:16]


def repro_command(family: str, seed: int) -> str:
    """The exact single-variant repro a sweep failure prints."""
    return (f"PYTHONPATH=. python scripts/service_smoke.py scenario "
            f"--family {family} --seed {seed}")


def run_solo(family: str, seed: int):
    """One variant, no service — the repro path.  Returns
    ``(violations, lane_digest)``."""
    fam = CATALOG[family]
    cfg = fam.build(seed)
    from ..service.resilience import solo_execute
    lane = solo_execute(cfg, "trace")
    return grade(fam, seed, lane), _lane_digest(cfg, lane)


def sweep(families=None, seeds_per_family: int = 20, max_batch: int = 8,
          mesh=None, seed0: int = 1000, service=None,
          raise_on_fail: bool = True) -> dict:
    """Grade ``len(families) * seeds_per_family`` seeded scenario
    variants as ONE FleetService run.

    Gates enforced in-line: 100% of submitted variants reach a
    terminal completed state (a stranded or failed handle raises), and
    every variant's oracle verdict is recorded.  With the default
    catalog and ``seeds_per_family=20`` that is 220 variants spanning
    all five worlds on both models.  The returned ``verdict_digest`` /
    ``outcome_digest`` are pure functions of (families, seeds, mesh
    width): identical seeds must reproduce them digest-for-digest —
    the scenario replay gate (scripts/service_smoke.py scenarios,
    bench.py ``secondary.scenario_sweep``).

    On oracle failures the report names each failing variant with its
    violations AND the exact single-variant repro command.
    """
    from ..service.scheduler import FleetService
    var = variants(families, seeds_per_family, seed0)
    svc = service if service is not None else FleetService(
        max_batch=max_batch, mesh=mesh)
    done = set()
    for fam, _ in var:
        if fam.name not in done:
            done.add(fam.name)
            svc.warm(fam.build(seed0), "trace")
    t0 = time.perf_counter()
    handles = [(fam, seed, svc.submit(fam.build(seed), mode="trace"))
               for fam, seed in var]
    svc.drain()
    wall = time.perf_counter() - t0
    stranded = [h.request.rid for _, _, h in handles if not h.done]
    failed = [h.request.rid for _, _, h in handles if h.failed]
    if stranded or failed:
        errs = "; ".join(f"rid {h.request.rid}: {h.exception()!r}"
                         for _, _, h in handles if h.failed)[:500]
        raise RuntimeError(
            f"scenario sweep left {len(stranded)} stranded and "
            f"{len(failed)} failed handles of {len(handles)}: {errs}")
    rows = []
    fails = []
    per_family: dict[str, dict] = {}
    for fam, seed, h in handles:
        lane = h.result()
        cfg = fam.build(seed)
        violations = grade(fam, seed, lane)
        rows.append((fam.name, seed, tuple(violations),
                     _lane_digest(cfg, lane)))
        pf = per_family.setdefault(fam.name, {"pass": 0, "fail": 0})
        if violations:
            pf["fail"] += 1
            fails.append((fam.name, seed, violations))
        else:
            pf["pass"] += 1
    verdict_digest = hashlib.sha256(
        repr([(r[0], r[1], r[2]) for r in rows]).encode()).hexdigest()[:16]
    outcome_digest = hashlib.sha256(
        repr([(r[0], r[1], r[3]) for r in rows]).encode()).hexdigest()[:16]
    stats = svc.stats()
    report = {
        "variants": len(var),
        "families": len(done),
        "worlds": len({fam.world for fam, _ in var}),
        "passed": sum(pf["pass"] for pf in per_family.values()),
        "failed": sum(pf["fail"] for pf in per_family.values()),
        "pass_rate": round(sum(pf["pass"] for pf in per_family.values())
                           / max(len(var), 1), 4),
        "per_family": per_family,
        "verdict_digest": verdict_digest,
        "outcome_digest": outcome_digest,
        "wall_s": round(wall, 3),
        "devices": stats["devices"],
        "dispatches": stats["dispatches"],
        "mean_occupancy": stats["mean_occupancy"],
        "buckets": stats["cache"]["buckets"],
        "completed": stats["completed"],
        "terminal_rate": round(
            (len(handles) - len(stranded) - len(failed))
            / max(len(handles), 1), 4),
    }
    if fails and raise_on_fail:
        lines = [f"  {f}/{s}: {v[:2]}\n    repro: {repro_command(f, s)}"
                 for f, s, v in fails[:8]]
        raise RuntimeError(
            f"scenario sweep: {len(fails)}/{len(var)} variants failed "
            "their oracle:\n" + "\n".join(lines))
    report["failures"] = [
        {"family": f, "seed": s, "violations": list(v)[:4],
         "repro": repro_command(f, s)} for f, s, v in fails]
    return report
