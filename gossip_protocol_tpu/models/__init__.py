"""Model families.

* dense full-view (``core/tick.py``) — the reference-faithful protocol,
  O(N²) state, exact parity with the C++ reference's semantics.
* bounded partial-view overlay (``models/overlay.py``) — the large-N
  scaling model (BASELINE 65k/1M configs), O(N·K) state, dense-algebra
  tick (XOR exchange + hash-slot scatter-free merge).
"""

from .overlay import (OverlayMetrics, OverlayResult, OverlaySchedule,
                      OverlaySimulation, OverlayState, init_overlay_state,
                      make_overlay_run, make_overlay_schedule,
                      make_overlay_tick, resolved_dims)

__all__ = [
    "OverlayMetrics", "OverlayResult", "OverlaySchedule",
    "OverlaySimulation", "OverlayState", "init_overlay_state",
    "make_overlay_run", "make_overlay_schedule", "make_overlay_tick",
    "resolved_dims",
]
