"""Schedule-segmented planning for the grid-scale overlay kernel.

The protocol's epochs are all closed-form counter functions of the
config (models/overlay.py OverlaySchedule): the join ramp ends at
``start(N-1)``, churn/scripted failures and rejoins live in a bounded
tick window, and the drop window is ``(drop_open, drop_close]``.  The
grid megakernel (ops/pallas/overlay_grid.py) nevertheless paid the
full fixed per-step op budget — join scratch revolving, JOINREQ
aggregation, JOINREP winner extraction, ramp comparisons, churn-hash
wipes, drop masking — on **every** tick, and that kernel is
op-issue-bound, not bandwidth-bound (docs/PERF.md §1/§3).

This module derives, on host at trace time, the tick at which each
phase goes *provably dead* and splits a run into launch-aligned
segments tagged with four static liveness flags.  Each distinct flag
combination compiles one specialized grid-kernel variant; the
steady-state variant drops all four phases from the hot loop.  It is
the temporal analogue of the spatial prefix `core/dense_corner.py`
derives from the same closed-form schedule.

Flag semantics (each one OFF is a *guarantee* over every tick the
launch computes; the kernel elides the phase statically):

* ``ramp_live`` off: every peer's start tick precedes every tick of
  the launch — ``t > start(i)`` holds for all rows and no ``at_start``
  event can fire.  Dead from ``last_start + 1``.
* ``churn_live`` off: no row is inside its fail window and no row
  rejoins at any tick of the launch (``failed`` and ``rejoining`` are
  identically False, for the introducer too) — the per-row fail/rejoin
  hashes and the wipe-on-load disappear.  Dead outside
  ``[first_fail, last_rejoin]``; a no-rejoin scripted failure keeps it
  live from ``fail_tick`` onward (victims stay failed forever).
* ``join_live`` off: the joinreq/joinrep in-flight bits are provably
  zero at the launch's start and no join/rejoin event can set them
  during it — JOINREQ aggregation, the JOINREP broadcast merge, the
  introducer's winner extraction, and the broadcast-row revolve all
  disappear.  Flags drain within 3 ticks of the last possible
  ``starting`` event (set at T, answered at T+1, consumed or dropped
  by T+2 — a failed introducer *drops* pending JOINREQs, it does not
  hold them), so dead from ``max(last_start, last_rejoin) + 3``.
* ``drop_live`` off: the drop window does not intersect the launch —
  the three per-tick Bernoulli hash streams disappear.

Every bound is derived from the config alone (never from the seed):
the compiled run is cached per config and reseeded through the
schedule arrays, and seeds move *which* rows fail, never the windows.

Launch alignment matters for exactness: the in-kernel JOINREQ
aggregate lookahead computes tick ``t+1`` state only for ticks whose
successor is inside the same launch (the host recomputes the boot
aggregate at every launch boundary), so per-launch flags need only
cover the launch's own ticks.
"""

from __future__ import annotations

import dataclasses
from fractions import Fraction

from ..config import SimConfig

#: sentinel for "never happens within any representable run"
_INF = 1 << 30


@dataclasses.dataclass(frozen=True)
class PhaseFlags:
    """Static per-launch phase liveness (kernel specialization key)."""

    ramp_live: bool
    churn_live: bool
    join_live: bool
    drop_live: bool

    def as_kernel_kwargs(self) -> dict:
        return dataclasses.asdict(self)

    @property
    def tag(self) -> str:
        """Compact label, e.g. ``"ramp+join"`` or ``"steady"``."""
        parts = [name for name, on in (
            ("ramp", self.ramp_live), ("churn", self.churn_live),
            ("join", self.join_live), ("drop", self.drop_live)) if on]
        return "+".join(parts) if parts else "steady"


ALL_LIVE = PhaseFlags(True, True, True, True)


@dataclasses.dataclass(frozen=True)
class Segment:
    """A run of consecutive same-flag launches.

    ``start`` is the absolute tick of the segment's first tick and
    ``ticks`` its length; every segment is a whole number of
    ``grid_ticks`` launches except possibly the final one.
    """

    start: int
    ticks: int
    flags: PhaseFlags


@dataclasses.dataclass(frozen=True)
class PhaseWindows:
    """Inclusive tick windows in which each phase can be live."""

    last_start: int       # last tick with a scheduled nodeStart
    fail_lo: int          # first tick any fail window can open
    rejoin_hi: int        # last tick any row can be failed/rejoining
    #                       (_INF: no rejoin — failures are permanent)
    join_dead_from: int   # first tick with provably-zero join flags
    drop_lo: int          # first tick the drop window covers
    drop_hi: int          # last tick the drop window covers (-1: off)


def step_fraction(step_rate: float) -> tuple[int, int]:
    """(num, den) of the start-ramp rate (shared with the grid
    harness so the planner and the kernel agree on ``last_start``)."""
    frac = Fraction(step_rate).limit_denominator(1 << 15)
    return frac.numerator, max(frac.denominator, 1)


def phase_windows(cfg: SimConfig) -> PhaseWindows:
    """Seed-independent closed-form liveness windows of a config.

    The adversarial worlds (worlds.py) fold in here, so scenario
    configs flow through grid-kernel phase elision, checkpoint cuts,
    and the serving bucket keys unchanged: the correlated failure
    WAVE replaces the scripted fail tick with its radius-ramp window,
    FLAPPING members widen the churn and join windows to the flap
    window (every up-edge is a rejoin through the JOINREQ path), and
    the PARTITION window unions into the drop window (it rides the
    drop plane: sends can be blocked exactly while either is open).
    Seeds move which nodes are hit, never these windows — that
    invariance is what lets every lane of a fleet share one plan.

    The round-2 BYZ and LATENCY planes are windowless: liars lie for
    the whole run, and per-link delay shifts deliveries, not fail
    schedules or send gates (the join path stays one-tick, so
    ``join_dead_from`` holds under latency too).  They enter plan
    identity through ``worlds_key`` in :func:`plan_signature` rather
    than through any window here — which is exactly how the
    composition grammar (worlds.composition) stays closed: any plane
    subset folds to one window set plus the worlds-key tail.
    """
    n, total = cfg.n, cfg.total_ticks
    num, den = step_fraction(cfg.step_rate)
    last_start = (n - 1) * num // den
    if cfg.churn_rate > 0:
        # churn fail ticks are hashed into [lo, lo + span); rejoin
        # follows ``churn_after`` ticks later (make_overlay_schedule)
        fail_lo = total // 4
        fail_hi = fail_lo + max(total // 2, 1) - 1
        after = cfg.rejoin_after if cfg.rejoin_after is not None else 40
        rejoin_hi = fail_hi + after
    elif cfg.wave_size > 0:
        # the wave's radius ramp: first victim at wave_start, last at
        # wave_last_fail (worlds.py — config-only, the seeded
        # epicenter moves WHICH nodes, never the ticks)
        from .. import worlds
        fail_lo = worlds.wave_start(cfg)
        fail_hi = worlds.wave_last_fail(cfg)
        rejoin_hi = fail_hi + cfg.rejoin_after \
            if cfg.rejoin_after is not None else _INF
    else:
        fail_lo = fail_hi = cfg.fail_tick
        rejoin_hi = cfg.fail_tick + cfg.rejoin_after \
            if cfg.rejoin_after is not None else _INF
    join_events = [last_start]
    if rejoin_hi < _INF:
        join_events.append(rejoin_hi)
    if cfg.flap_rate > 0:
        # flapping members fail/rejoin inside [flap_open, flap_close];
        # the first possible down tick is anchor + 1 >= flap_open + 1
        from .. import worlds
        flap_lo, flap_hi = worlds.flap_window(cfg)
        fail_lo = min(fail_lo, flap_lo + 1)
        rejoin_hi = max(rejoin_hi, flap_hi)
        join_events.append(flap_hi)
    drop_lo = cfg.drop_open_tick + 1 if cfg.drop_msg else 0
    drop_hi = cfg.drop_close_tick if cfg.drop_msg else -1
    if cfg.partition_groups >= 2:
        # the partition rides the drop plane: union the two send-
        # blocking windows (conservative single interval)
        p_lo, p_hi = cfg.partition_open_tick + 1, cfg.partition_close_tick
        drop_lo, drop_hi = ((min(drop_lo, p_lo), max(drop_hi, p_hi))
                            if cfg.drop_msg else (p_lo, p_hi))
    return PhaseWindows(
        last_start=last_start,
        fail_lo=fail_lo,
        rejoin_hi=rejoin_hi,
        join_dead_from=max(join_events) + 3,
        drop_lo=drop_lo,
        drop_hi=drop_hi,
    )


def flags_at(win: PhaseWindows, t: int) -> PhaseFlags:
    """Phase liveness at one absolute tick (conservative)."""
    return PhaseFlags(
        ramp_live=t <= win.last_start,
        churn_live=win.fail_lo <= t <= win.rejoin_hi,
        join_live=t < win.join_dead_from,
        drop_live=win.drop_lo <= t <= win.drop_hi,
    )


def _launch_flags(win: PhaseWindows, t0: int, ticks: int) -> PhaseFlags:
    """OR of per-tick liveness over a launch window [t0, t0+ticks)."""
    f = [flags_at(win, t) for t in range(t0, t0 + ticks)]
    return PhaseFlags(
        ramp_live=any(x.ramp_live for x in f),
        churn_live=any(x.churn_live for x in f),
        join_live=any(x.join_live for x in f),
        drop_live=any(x.drop_live for x in f),
    )


def plan_segments(cfg: SimConfig, length: int, start_tick: int | None,
                  grid_ticks: int) -> list[Segment]:
    """Launch-aligned segment plan for ticks
    ``[start_tick, start_tick + length)``.

    ``start_tick=None`` means the caller cannot pin the run's absolute
    start tick at trace time; the plan degenerates to one all-live
    segment (bit-identical to the unsegmented kernel at any clock).
    Launch boundaries are exactly the unsegmented ones (whole
    ``grid_ticks`` chunks from the start, remainder last), so the
    segmented orchestration hands the double-buffered HBM plane across
    boundaries it was already crossing.
    """
    if length <= 0:
        return []
    if start_tick is None:
        return [Segment(start=-1, ticks=length, flags=ALL_LIVE)]
    win = phase_windows(cfg)
    segs: list[Segment] = []
    t = start_tick
    remaining = length
    while remaining > 0:
        s_ticks = min(grid_ticks, remaining)
        flags = _launch_flags(win, t, s_ticks)
        if segs and segs[-1].flags == flags \
                and segs[-1].ticks % grid_ticks == 0:
            segs[-1] = dataclasses.replace(
                segs[-1], ticks=segs[-1].ticks + s_ticks)
        else:
            segs.append(Segment(start=t, ticks=s_ticks, flags=flags))
        t += s_ticks
        remaining -= s_ticks
    # planner invariant the kernel relies on: a join-dead launch has
    # no starting events — the ramp is over and, when rejoin is
    # enabled at all (finite rejoin_hi), the rejoin window is too
    for seg in segs:
        assert seg.flags.join_live or not (
            seg.flags.ramp_live
            or (seg.flags.churn_live and win.rejoin_hi < _INF)), seg
    return segs


def describe_plan(plan: list[Segment]) -> str:
    """Compact human-readable plan, e.g.
    ``"ramp+join:48 + churn+join:144 + steady:96"``."""
    return " + ".join(f"{s.flags.tag}:{s.ticks}" for s in plan)


#: launch quantum the checkpoint planner aligns to — MUST equal the
#: grid kernel's ops/pallas/overlay_grid.GRID_TICKS (asserted by
#: tests/test_elastic.py; not imported here because this module is on
#: the light bucketing path and must not pull the Pallas stack in)
CHECKPOINT_GRID_TICKS = 16


def checkpoint_ticks(cfg: SimConfig,
                     grid_ticks: int = CHECKPOINT_GRID_TICKS
                     ) -> tuple[int, ...]:
    """The interior segment cuts of a config's tick-0 plan — the ONLY
    legal snapshot points for fleet checkpointing (core/fleet.py
    ``launch_leg``).

    A snapshot at a segment cut keeps phase elision static: the resumed
    run's plan from the cut is exactly the original plan's tail, so the
    grid path compiles the same specialized kernel variants it would
    have compiled uninterrupted (a mid-segment cut would split a
    segment and mint an extra variant).  The cuts are seed-independent
    (the plan is), so every lane of a fleet — and every seed of a
    service bucket — agrees on them by construction.
    """
    segs = plan_segments(cfg, cfg.total_ticks, 0, grid_ticks)
    return tuple(s.start for s in segs[1:])


def cut_for_budget(cfg: SimConfig, start: int, budget: int,
                   grid_ticks: int = CHECKPOINT_GRID_TICKS) -> int:
    """End tick of a resumable leg starting at ``start`` under a
    ``budget`` of ticks: the whole run when it fits the budget, else
    the LARGEST legal cut within ``start + budget`` (longest leg that
    respects the budget), else the smallest cut after ``start`` (the
    budget is finer than the plan — one oversized leg, documented in
    docs/SERVING.md "Elastic capacity"), else ``total_ticks``."""
    total = cfg.total_ticks
    if not 0 <= start < total:
        raise ValueError(f"leg start {start} outside [0, {total})")
    if total - start <= budget:
        return total
    cuts = [c for c in checkpoint_ticks(cfg, grid_ticks) if c > start]
    within = [c for c in cuts if c - start <= budget]
    if within:
        return within[-1]
    return cuts[0] if cuts else total


def plan_signature(cfg: SimConfig) -> tuple:
    """Hashable seed-independent digest of a config's segment plan.

    Two configs with equal signatures produce identical segment plans
    at every (start_tick, length, grid_ticks) — the signature is the
    closed-form phase windows themselves plus the horizon, which is
    everything :func:`plan_segments` reads.  Used as a compile-cache
    key component (core/tick.make_run, core/fleet.py) and as part of
    the serving layer's bucketing key (service/bucket.py): a config
    edit that only moves a phase boundary (say ``drop_open_tick``)
    changes the signature, so it can neither be served a stale
    compiled run nor be batched into a fleet whose kernels elided a
    phase it still needs.
    """
    win = phase_windows(cfg)
    return ("segplan", cfg.total_ticks, win.last_start, win.fail_lo,
            win.rejoin_hi, win.join_dead_from, win.drop_lo, win.drop_hi,
            # the adversarial worlds are part of plan identity beyond
            # their windows (zombie/asym change tick semantics with no
            # window of their own; flap/wave/partition knobs must not
            # collide across distinct configs whose unions coincide)
            cfg.worlds_key())


def quantize_tick(t: int, grid: int = CHECKPOINT_GRID_TICKS,
                  up: bool = False) -> int:
    """Snap a phase-window edge to the checkpoint grid: lo edges round
    DOWN (``up=False``), hi edges round UP — so a window built from
    quantized edges is always a SUPERSET of the exact window, which is
    what lets the canonical fleet path share one windowed cond across
    lanes and mask back to each lane's exact window
    (service/canonical.py).  Sentinels pass through unchanged (the
    ``_INF`` "never" horizon and negative "no window" edges)."""
    if t >= _INF or t < 0:
        return t
    return ((t + grid - 1) // grid) * grid if up else (t // grid) * grid


def quantized_plan_signature(cfg: SimConfig,
                             grid: int = CHECKPOINT_GRID_TICKS) -> tuple:
    """:func:`plan_signature` over the GRID-QUANTIZED plan: every
    phase-window edge snapped to the ``CHECKPOINT_GRID_TICKS`` grid
    (lo down, hi up) and the worlds tail reduced to the operand-vs-
    static split (worlds.canonical_world_key) — so near-identical
    schedules fall into one equivalence class and share one compiled
    fleet program, with the exact windows riding as Schedule data.
    This is a CANONICAL-path key only (service/canonical.py): the
    exact :func:`plan_signature` keeps guarding the solo run cache and
    the checkpoint-leg cut validation, neither of which the canonical
    path serves.  The ONLY window this key carries is the drop-draw
    window, quantized as a dedicated ``(open, close)`` pair: the
    class-shared ``drop_active`` cond plane is rebuilt from it alone.
    Every other phase edge — start ramp, fail/rejoin windows, the
    partition and flap windows — rides the batched Schedule as
    per-lane operands on the monolithic canonical path (it elides no
    phases and validates no cuts), so keying them would only split
    classes that compile to the same program; the exact
    :func:`plan_signature` still pins all of them wherever segment
    identity is real.
    """
    from .. import worlds
    drop_q = ((quantize_tick(cfg.drop_open_tick, grid),
               quantize_tick(cfg.drop_close_tick, grid, up=True))
              if cfg.drop_msg else None)
    return ("segplan-q", grid, cfg.total_ticks, drop_q,
            worlds.canonical_world_key(cfg, grid))
