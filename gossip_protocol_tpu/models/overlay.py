"""Bounded partial-view overlay: the large-N scaling model.

The reference's protocol is full-view: every node stores an entry for
every other node and gossips its entire list to everyone each tick
(MP1Node.cpp:350-361), which is O(N²) state and O(N³) merge work — and
it hard-caps at N<=10 (MP1Node.cpp:245) / N<=1000 (EmulNet.h:10).  The
dense model in ``core/tick.py`` removes the caps but keeps O(N²) state,
so BASELINE's 65k and 1M peer configs are unreachable by construction.
This module is the scaling answer: a **bounded partial-view** membership
protocol with O(N·K) state and O(N·F·K) work per tick.

Design: TPU-first, and specifically **gather/scatter/sort-free** — on
TPU those lower to serialized index loops (measured ~75M indices/s,
hundreds of ms per tick at 65k), so every phase here is dense algebra:

* **Dissemination = XOR partner exchange.**  At tick t every in-group
  node exchanges its payload with the F partners ``i ^ m_f(t)``, where
  the nonzero masks ``m_f(t)`` are counter-hashed fresh each tick —
  a new random F-regular graph per tick over the 2^b address space
  (the Erdős–Rényi-flavored fanout of the BASELINE configs), which
  mixes like an expander.  Applying ``x[i ^ m]`` to the whole payload
  matrix is two small permutation **matmuls** (the XOR factors
  bitwise across a HI×LO index split), exact in f32 and riding the
  MXU — no gather anywhere.  (The Pallas kernel does the same
  permutation for free: high mask bits in the block index map, low
  bits as an in-VMEM butterfly.)  Payloads carry the sender's whole
  K-slot view plus its self-entry, frozen at the send tick (= the
  carried state, the dense model's zero-copy trick).
* **View = epoch-slotted table, lane-aligned merges.**  An entry for
  peer ``j`` lives only in slot ``g_e(j) = mix32(e, j) % K``, where
  ``e = t // SLOT_EPOCH`` — the slot map is **shared by every node**
  and re-rolled every SLOT_EPOCH ticks.  Because sender and receiver
  tables are slotted identically within an epoch, merging an incoming
  view is a pure **lane-aligned (N, K) masked max** — no K×L
  slot-match product (the per-receiver-hash design this replaces paid
  an O(K·L) broadcast per partner; this one is O(K), ~8x less VPU
  work).  At each SLOT_EPOCH boundary every node re-slots its own
  table once (an O(K²) contention pass amortized over SLOT_EPOCH
  ticks, skipped on all other ticks via ``lax.cond``).
* **Contention is freshness-majorized.**  Slot collisions (ids with
  equal ``g_e``) contend; the winner is the largest packed uint32
  key ``(ts+1) << ID_BITS | id`` — the freshest observation wins
  outright, ties break on id.  The key is a pure function of the
  stored entry (no per-receiver or per-tick hash), which makes the
  whole merge pipeline single-uint-compare cheap — the VPU-bound
  in-kernel tick cost is dominated by per-entry key work, and this
  design removes all of it (round-5 redesign; the earlier
  epoch-rotated per-receiver tiebreak spent ~2x the vector ops for
  the same guarantees).  Coverage under deterministic contention is
  held **structurally** by the self-reseed: every live node stamps
  ``(id, own_hb, t-1)`` directly at its F partners each tick, and a
  tick-(t-1) observation carries the maximum timestamp any *relayed
  table entry* can have at tick t — so a direct self-entry outranks
  relayed rivals up to rare equal-ts ties (another direct entry, or a
  relayed JOINREQ entry stamped ts=t one tick earlier, colliding in
  the same slot with a larger id).  Every live member therefore keeps
  fresh holders at its (per-tick re-randomized) partners nearly every
  tick; the hard guarantee is the re-cover bound — the re-seeding
  plus the SLOT_EPOCH re-roll re-cover any transient hole within
  ``SLOT_EPOCH + 1`` ticks (tests/test_overlay.py::test_recover_bound;
  asserted at 65k scale by bench.py's boundary coverage walk).
* **Freshness is the priority.**  A live node stamps its own entry
  ``(id, own_hb, now)`` into every payload; the freshness-keyed merge
  propagates the freshest observation along exchange paths, so an
  entry's ``ts`` is the newest time anyone in the path cone saw the
  subject alive.  Failure detection is the reference's staleness rule
  (now - ts >= TREMOVE, MP1Node.cpp:339-348).
* **Schedules are closed-form.**  Start ramp, scripted failures,
  churn membership, churn fail/rejoin ticks, and drop decisions are
  all pure counter-hash functions of (seed, id, tick) — no (N,)
  schedule arrays to look up by id on device (an id-indexed lookup is
  a gather), and the numpy oracle (testing/overlay_oracle.py) replays
  them bit-exactly.

Accuracy semantics at scale: per-holder staleness removals are
*expected background churn* in a bounded partial view (an entry's
refresh is arrival-limited); the guarantees that matter are global —
every live member stays covered by the union of views, failed peers
are purged from every view within the detection horizon, and churned
peers re-enter through the normal JOINREQ path.  The reference-faithful
per-observer guarantees live in the dense model.

Deliberate divergences from the reference protocol (this is the
framework's scaling extension): receivers adopt the freshest (ts, hb)
observation instead of the increment-on-direct-gossip quirk
(MP1Node.cpp:236-239); views are bounded, so entries can be evicted by
slot contention; dissemination follows the XOR schedule rather than
"send to everyone I know"; messages carry a K-entry view, not the
unbounded full list.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import INTRODUCER, SimConfig
from ..state import NEVER
from ..utils.hash32 import mix32, threshold32
from ..worlds import (SALT_BYZ, SALT_FLAP, SALT_FLAP_PHASE, SALT_LINK,
                      SALT_PART, byz_threshold, flap_threshold,
                      flap_window, link_latency_of, partition_window,
                      wave_center, wave_start)

#: id field width in the packed priority key: ids < 2^20, and the XOR
#: exchange needs a power-of-two peer count, so the largest supported
#: group is N = 2^20 = 1,048,576 — the BASELINE 1M-peer config
#: exactly.  With the 12-bit ts+1 field (runs cap at 4094 ticks) the
#: key fills the uint32 exactly.
ID_BITS = 20
ID_MASK = (1 << ID_BITS) - 1

#: global slot map re-roll period (ticks).  Long enough to amortize the
#: O(K²) re-slot pass, short enough that a slot collision between two
#: live ids never persists past ~one TREMOVE horizon.
SLOT_EPOCH = 16

# salts for the independent counter-hash streams
_SALT_MASK = 1
_SALT_GOSSIP_DROP = 2
_SALT_JOINREQ_DROP = 3
_SALT_JOINREP_DROP = 4
_SALT_CHURN = 5
_SALT_CHURN_TICK = 6
_SALT_SLOT = 7
_SALT_DEGREE = 8


@struct.dataclass
class OverlayState:
    """World state: O(N·K) tables plus O(N·F) in-flight send flags."""

    tick: jax.Array        # i32 scalar
    ids: jax.Array         # i32[N, K] — entry subject id, -1 = empty slot
    hb: jax.Array          # i32[N, K] — heartbeat of the entry
    ts: jax.Array          # i32[N, K] — freshest observation time
    in_group: jax.Array    # bool[N]
    own_hb: jax.Array      # i32[N]
    send_flags: jax.Array  # bool[N, F] — node gossiped on exchange slot f
                           #   last tick (in-flight traffic marker)
    send_hist: jax.Array   # i32[N, F] — latency plane only: per-slot
                           #   send shift register (bit a = sent a+1
                           #   ticks ago; bit 0 mirrors send_flags; at
                           #   most 24 bits, so the word rides the f32
                           #   permutation matmuls exactly).  Inert
                           #   all-zero when cfg.link_latency == 0.
    joinreq: jax.Array     # bool[N] — JOINREQ to the introducer in flight
    joinrep: jax.Array     # bool[N] — JOINREP back to the joiner in flight


@struct.dataclass
class OverlaySchedule:
    """Closed-form schedule: scalars only, evaluated per (id, tick).

    ``fail_of``/``rejoin_of``/``start_of`` are pure functions usable on
    whole id arrays — the device never indexes a schedule table.
    With ``churn_thr > 0`` continuous churn replaces the scripted
    failure (the BASELINE 65k/20%-churn shape); otherwise the scripted
    single/multi failure interval applies.
    """

    seed: jax.Array         # u32 scalar
    step_num: jax.Array     # i32 — start ramp: start(i) = i*num//den
    step_den: jax.Array     # i32
    victim_lo: jax.Array    # i32 — scripted failure interval [lo, hi)
    victim_hi: jax.Array    # i32
    fail_tick: jax.Array    # i32 — scripted failure tick
    rejoin_after: jax.Array  # i32 — NEVER disables rejoin
    churn_thr: jax.Array    # u32 — churn membership threshold (0 = off)
    churn_lo: jax.Array     # i32 — churn fail ticks in [lo, lo+span)
    churn_span: jax.Array   # i32
    churn_after: jax.Array  # i32 — churn rejoin delay
    drop_on: jax.Array      # bool — drop window configured
    drop_open: jax.Array    # i32 — droppable sends: open < t <= close
    drop_close: jax.Array   # i32
    drop_thr: jax.Array     # u32 — per-message Bernoulli threshold
    deg_thr: jax.Array      # u32[F-1] — power-law out-degree CDF
                            #   thresholds (degree_thresholds)
    # --- adversarial failure worlds (worlds.py): every draw below is
    # --- a pure (seed, tick, node) counter hash, so lanes stay
    # --- bit-replayable and the numpy oracle replays them exactly ---
    part_groups: jax.Array  # u32 — partition group count (0 = off)
    part_open: jax.Array    # i32 — cross-group sends blocked:
    part_close: jax.Array   # i32   open < t <= close
    asym_on: jax.Array      # bool — per-link drop thresholds
    wave_size: jax.Array    # i32 — correlated wave victims (0 = off)
    wave_tick: jax.Array    # i32 — resolved wave start tick
    wave_speed: jax.Array   # i32 — radius step per this many ticks
    wave_center: jax.Array  # i32 — seeded epicenter
    wave_mod: jax.Array     # i32 — ring modulus (the peer count)
    zombie_on: jax.Array    # bool — window-failed peers keep gossiping
    flap_thr: jax.Array     # u32 — flapping-member threshold (0 = off)
    flap_period: jax.Array  # i32
    flap_down: jax.Array    # i32 — down ticks per period
    flap_open: jax.Array    # i32 — resolved window
    flap_close: jax.Array   # i32
    byz_thr: jax.Array      # u32 — Byzantine liar threshold (0 = off)
    byz_boost: jax.Array    # i32 — forged heartbeat inflation
    link_lat: jax.Array     # i32 — per-link latency bound L (0 = off);
                            #   link delays draw in [1, L+1] via
                            #   worlds.link_latency_of

    def start_of(self, i):
        return (i * self.step_num) // self.step_den

    def _churned(self, i):
        iu = i.astype(jnp.uint32) if hasattr(i, "astype") else np.uint32(i)
        sel = mix32(self.seed, iu, np.uint32(_SALT_CHURN)) < self.churn_thr
        return sel & (i != INTRODUCER)

    def fail_of(self, i):
        iu = i.astype(jnp.uint32) if hasattr(i, "astype") else np.uint32(i)
        churn_fail = self.churn_lo + (
            mix32(self.seed, iu, np.uint32(_SALT_CHURN_TICK))
            % self.churn_span.astype(jnp.uint32)).astype(jnp.int32)
        scripted = jnp.where((i >= self.victim_lo) & (i < self.victim_hi),
                             self.fail_tick, NEVER)
        # correlated wave: the wave_size nodes in the contiguous ring
        # block from the epicenter fail one radius step per wave_speed
        # ticks (replaces the scripted draw, like churn does)
        off = (i - self.wave_center) % jnp.maximum(self.wave_mod, 1)
        wave = jnp.where((off < self.wave_size) & (i != INTRODUCER),
                         self.wave_tick
                         + off // jnp.maximum(self.wave_speed, 1),
                         NEVER)
        base = jnp.where(self.wave_size > 0, wave, scripted)
        return jnp.where(self.churn_thr > 0,
                         jnp.where(self._churned(i), churn_fail, NEVER),
                         base)

    def rejoin_of(self, i):
        fail = self.fail_of(i)
        after = jnp.where(self.churn_thr > 0, self.churn_after,
                          self.rejoin_after)
        return jnp.where((fail != NEVER) & (after != NEVER),
                         fail + after, NEVER)

    def _flap(self, i, t):
        """(failed, rejoining) under the flap world: down for
        positions [1, flap_down] of each period from the node's hashed
        anchor, rejoining at position flap_down; only cycles completing
        before flap_close run (the window always ends clean)."""
        iu = i.astype(jnp.uint32) if hasattr(i, "astype") else np.uint32(i)
        sel = (mix32(self.seed, iu, np.uint32(SALT_FLAP))
               < self.flap_thr) & (i != INTRODUCER)
        per = jnp.maximum(self.flap_period, 1)
        anchor = self.flap_open + (
            mix32(self.seed, iu, np.uint32(SALT_FLAP_PHASE))
            % per.astype(jnp.uint32)).astype(jnp.int32)
        pos = t - anchor
        c = pos // per
        off = pos - c * per
        ok = sel & (pos >= 1) \
            & (anchor + c * per + self.flap_down <= self.flap_close)
        return (ok & (off >= 1) & (off <= self.flap_down),
                ok & (off == self.flap_down))

    def byz_of(self, i):
        """bool: node ``i`` is a seeded liar (byz plane; the introducer
        never lies — :func:`worlds.byz_mask_host` is the host twin)."""
        iu = i.astype(jnp.uint32) if hasattr(i, "astype") else np.uint32(i)
        sel = mix32(self.seed, iu, np.uint32(SALT_BYZ)) < self.byz_thr
        return sel & (i != INTRODUCER)

    def window_failed_at(self, i, t):
        """The WINDOW component of :meth:`failed_at` (scripted / churn
        / wave) — the failures the zombie world applies to."""
        return (t > self.fail_of(i)) & (t <= self.rejoin_of(i))

    def failed_at(self, i, t):
        f, _ = self._flap(i, t)
        return self.window_failed_at(i, t) | f

    def rejoining_at(self, i, t):
        _, r = self._flap(i, t)
        return (t == self.rejoin_of(i)) | r

    def drop_active(self, t):
        return self.drop_on & (t > self.drop_open) & (t <= self.drop_close)

    def part_active(self, t):
        """bool scalar: cross-group sends blocked at tick ``t``."""
        return (self.part_groups > 0) & (t > self.part_open) \
            & (t <= self.part_close)

    def group_of(self, i):
        """Hashed partition group of node ``i`` (0 when off)."""
        iu = i.astype(jnp.uint32) if hasattr(i, "astype") else np.uint32(i)
        return (mix32(self.seed, iu, np.uint32(SALT_PART))
                % jnp.maximum(self.part_groups, np.uint32(1))
                ).astype(jnp.int32)

    def link_thr(self, iu, ju):
        """u32 per-link drop threshold of link i -> j (asym world):
        ``H(seed, i*N+j) % 2*drop_thr`` — uniform in [0, 2*thr), mean
        ``drop_thr``; i*N+j wraps in uint32 at huge N, deliberately
        (it is a hash input and both backends wrap identically)."""
        two = self.drop_thr * np.uint32(2)
        return mix32(self.seed,
                     iu * self.wave_mod.astype(jnp.uint32) + ju,
                     np.uint32(SALT_LINK)) % jnp.maximum(two, np.uint32(1))


def make_overlay_schedule(cfg: SimConfig) -> OverlaySchedule:
    from ..utils.prng import fail_schedule_uniform

    # the shared step-rate fraction (models/segments.py): schedule,
    # grid harness, and segment planner must agree on it exactly
    from .segments import step_fraction
    n = cfg.n
    step_num, step_den = step_fraction(cfg.step_rate)
    if cfg.churn_rate > 0:
        # the churn window must not overlap the start ramp: a churned
        # peer whose fail tick precedes its start would be introduced
        # while failed (a posthumous join — reference-faithful in the
        # dense model, but it would suspend the overlay's victim-purge
        # guarantee).  Require the ramp to finish before churn opens.
        last_start = (n - 1) * step_num // step_den
        churn_lo = cfg.total_ticks // 4
        if last_start >= churn_lo:
            raise ValueError(
                f"start ramp ends at t={last_start} but churn opens at "
                f"t={churn_lo}; lower step_rate (e.g. {churn_lo / (2 * n)}) "
                "or lengthen the run")
    victim_lo, victim_hi = 0, 0
    if cfg.churn_rate <= 0:
        u = fail_schedule_uniform(cfg.seed)
        if cfg.single_failure:
            victim_lo = int(u * n) % n
            victim_hi = victim_lo + 1
        else:
            victim_lo = (int(u * n) % n) // 2
            victim_hi = victim_lo + n // 2
    # resolved adversarial-world windows (worlds.py — seed-independent
    # config functions, so they ride the segment planner / bucket keys)
    part_open, part_close = partition_window(cfg)
    flap_lo, flap_hi = flap_window(cfg)
    # numpy scalars, deliberately: a schedule build must dispatch ZERO
    # eager device ops.  Eager ``jnp`` scalar creation is a tiny XLA
    # program each; on the serving path a fleet program is often in
    # flight on the same device, and once the client's bounded
    # in-flight queue fills, the next tiny dispatch BLOCKS until the
    # big program finishes — which silently serialized the pipelined
    # scheduler's pack step behind the very execution it was supposed
    # to overlap (docs/PERF.md §11).  The values are identical; they
    # enter device code as jit inputs exactly as before.
    return OverlaySchedule(
        seed=np.uint32(cfg.seed & 0xFFFFFFFF),
        step_num=np.int32(step_num),
        step_den=np.int32(step_den),
        victim_lo=np.int32(victim_lo),
        victim_hi=np.int32(victim_hi),
        fail_tick=np.int32(cfg.fail_tick),
        rejoin_after=np.int32(cfg.rejoin_after
                              if cfg.rejoin_after is not None else NEVER),
        churn_thr=np.uint32(threshold32(cfg.churn_rate)
                            if cfg.churn_rate > 0 else 0),
        churn_lo=np.int32(cfg.total_ticks // 4),
        churn_span=np.int32(max(cfg.total_ticks // 2, 1)),
        churn_after=np.int32(cfg.rejoin_after
                             if cfg.rejoin_after is not None else 40),
        drop_on=np.bool_(bool(cfg.drop_msg)),
        drop_open=np.int32(cfg.drop_open_tick),
        drop_close=np.int32(cfg.drop_close_tick),
        drop_thr=np.uint32(threshold32(cfg.msg_drop_prob)),
        deg_thr=np.asarray(degree_thresholds(cfg, resolved_dims(cfg)[1])),
        part_groups=np.uint32(cfg.partition_groups
                              if cfg.partition_groups >= 2 else 0),
        part_open=np.int32(part_open),
        part_close=np.int32(part_close),
        asym_on=np.bool_(bool(cfg.asym_drop)),
        wave_size=np.int32(cfg.wave_size),
        wave_tick=np.int32(wave_start(cfg) if cfg.wave_size > 0 else 0),
        wave_speed=np.int32(max(cfg.wave_speed, 1)),
        wave_center=np.int32(wave_center(cfg) if cfg.wave_size > 0
                             else 0),
        wave_mod=np.int32(n),
        zombie_on=np.bool_(bool(cfg.zombie)),
        flap_thr=np.uint32(flap_threshold(cfg)),
        flap_period=np.int32(max(cfg.flap_period, 1)),
        flap_down=np.int32(cfg.flap_down),
        flap_open=np.int32(flap_lo),
        flap_close=np.int32(flap_hi if cfg.flap_rate > 0 else -1),
        byz_thr=np.uint32(byz_threshold(cfg)),
        byz_boost=np.int32(cfg.byz_boost),
        link_lat=np.int32(cfg.link_latency),
    )


@struct.dataclass
class OverlayMetrics:
    """Per-tick scalar counters (events at 65k+ cannot be dense masks)."""

    in_group: jax.Array       # i32 — nodes currently in the group
    view_slots: jax.Array     # i32 — total occupied view slots
    adds: jax.Array           # i32 — slots that changed to a new subject
    removals: jax.Array       # i32 — staleness removals this tick
    false_removals: jax.Array  # i32 — removals naming a live subject
    #   (expected background churn in a bounded partial view — see
    #   module docstring; the hard guarantee is live coverage)
    victim_slots: jax.Array   # i32 — slots still naming a failed subject
    live_uncovered: jax.Array  # i32 — live members in NO view (-1 when
    #   not tracked: the histogram needs a scatter, so it is computed
    #   only at small N; large-N coverage is checked on the final state)
    sent: jax.Array           # i32 — messages sent (after drop)
    recv: jax.Array           # i32 — messages consumed


#: track the live-coverage histogram on device only below this N
COVERAGE_N_LIMIT = 4096

#: re-slot pass row-block size (bounds the (B, K, K) contention
#: broadcast at SLOT_EPOCH boundaries)
REMAP_BLOCK = 1 << 13


def resolved_dims(cfg: SimConfig):
    """(K, F): view slots and exchange fanout.

    Auto sizing: K ~ 4*log2 N for view capacity (capped at 64).  Every
    message carries the sender's whole K-slot view (lane-aligned
    merges), so each exchange supplies ~1 candidate per occupied slot
    and the per-slot supply per tick is ~F·occupancy — F = 3 keeps
    slot refresh ahead of the TREMOVE horizon with margin for a 10%
    drop window (measured: zero false removals and zero coverage gaps
    at 65k/20%-churn and 4096/10%-drop; direct self-entries only need
    one of the F sends to land, P[all dropped] = 1e-3 at 10% drop).
    ``cfg.overlay_sample`` (the L-window of the earlier
    per-receiver-hash design) is accepted but ignored.
    """
    n = cfg.n
    b = int(math.ceil(math.log2(max(n, 4))))
    k = cfg.overlay_view if cfg.overlay_view > 0 \
        else min(64, max(16, 8 * ((b + 1) // 2)))
    if cfg.fanout > 0:
        f = cfg.fanout
    elif cfg.topology == "powerlaw":
        # F is the hub degree cap; the MEAN degree is sum k^-(a-1)/...,
        # ~1.9 at alpha=2.5 — leaves gossip rarely, hubs every round
        f = 8
    else:
        f = 3
    return k, f


def degree_thresholds(cfg: SimConfig, f: int):
    """uint32 CDF thresholds of the bounded Pareto out-degree draw.

    ``deg(i) = 1 + sum_{k=2..F} [mix32(seed, i, SALT_DEGREE) < thr_k]``
    with ``thr_k = round(2^32 * k^-(alpha-1))`` — so
    ``P[deg >= k] = k^-(alpha-1)`` (clipped to [1, F]).  Computed once
    on host in float64, compared in pure uint32 on device, replayed
    bit-exactly by the numpy oracle.  For topology="uniform" every
    threshold saturates and deg(i) = F for all i.
    """
    if cfg.topology == "uniform":
        return np.full(max(f - 1, 1), 0xFFFFFFFF, np.uint32)
    if cfg.topology != "powerlaw":
        raise ValueError(f"unknown overlay topology {cfg.topology!r}")
    a = float(cfg.powerlaw_alpha)
    if a <= 1.0:
        raise ValueError("powerlaw_alpha must be > 1")
    thr = [min(0xFFFFFFFF, int(round(4294967296.0 * k ** (-(a - 1.0)))))
           for k in range(2, f + 1)]
    return np.asarray(thr if thr else [0], np.uint32)


def _xor_factors(n: int):
    """Factor a power-of-two index space for the permutation matmuls.

    A two-way hi/lo split measures fastest on TPU (finer factorizations
    lower the FLOP count — sum(factors) vs 2*sqrt(N) — but the extra
    batched contractions cost more in relayouts than they save)."""
    b = n.bit_length() - 1
    hi = 1 << ((b + 1) // 2)
    return [hi, n // hi] if n > 1 else [1]


def init_overlay_state(cfg: SimConfig) -> OverlayState:
    n = cfg.n
    k, f = resolved_dims(cfg)
    return OverlayState(
        tick=jnp.int32(0),
        ids=jnp.full((n, k), -1, jnp.int32),
        hb=jnp.zeros((n, k), jnp.int32),
        ts=jnp.zeros((n, k), jnp.int32),
        in_group=jnp.zeros(n, bool),
        own_hb=jnp.zeros(n, jnp.int32),
        send_flags=jnp.zeros((n, f), bool),
        send_hist=jnp.zeros((n, f), jnp.int32),
        joinreq=jnp.zeros(n, bool),
        joinrep=jnp.zeros(n, bool),
    )


def exchange_mask(seed, t, fi, n):
    """Nonzero XOR mask of exchange slot ``fi`` at tick ``t`` (traced)."""
    tu = t.astype(jnp.uint32) if hasattr(t, "astype") else np.uint32(t)
    m = mix32(seed, tu, np.uint32(fi), np.uint32(_SALT_MASK))
    return (m % np.uint32(n - 1)).astype(jnp.int32) + 1


def _pack_th(ts, hb):
    """int32 pack of a winner's payload: (ts+1) << 12 | (hb+1).

    Both fields are < 4095 (runs are capped at 4094 ticks and
    heartbeats advance at most once per tick), so among equal
    priority-key candidates the max packed value is the lexicographic
    (ts, hb) maximum."""
    return ((ts + 1) << 12) | (hb + 1)


def _slot_of(seed, slot_epoch_u, ids, k):
    """Global slot of subject ``ids`` during a slot epoch.

    The map is shared by every node (NOT receiver-hashed) and re-rolled
    every SLOT_EPOCH ticks, so identically-slotted tables merge
    lane-aligned and any persistent slot collision is retired within
    one epoch.
    """
    return (mix32(seed, slot_epoch_u, ids.astype(jnp.uint32),
                  np.uint32(_SALT_SLOT)) % k).astype(jnp.int32)


def _pack_key(ids, ts):
    """uint32 slot-priority key: freshness-majorized.

    ``(ts+1) << ID_BITS | id`` — the freshest observation wins a slot
    outright; equal timestamps break on the larger id (deterministic,
    receiver-independent).  A pure function of the stored entry with
    no per-tick hashing: the merge pipeline reduces to single uint32
    compares, which is what makes the in-kernel tick cheap (module
    docstring).  0 is the empty key (real entries have ts >= 0, so
    their keys are >= 1 << ID_BITS).

    Direct observations need no boost field: a subject's own
    self-entry (the partner / introducer-reply entry, age 1) or its
    JOINREQ (age 0) carries the maximum timestamp any relayed table
    entry can have at merge time — relayed tables were frozen one tick
    earlier — so direct entries outrank relayed rivals except for rare
    equal-ts ties (see the module docstring), which is what keeps
    every live member covered under deterministic contention up to the
    SLOT_EPOCH + 1 re-cover bound.
    """
    return ((ts + 1).astype(jnp.uint32) << ID_BITS) \
        | ids.astype(jnp.uint32)


class LocalOverlayComm:
    """Single-device execution: all rows local, collectives trivial."""

    n_shards = 1

    def row_start(self, n: int):
        return 0

    def slice_rows(self, v):
        """Replicated [N, ...] -> local row block (identity here)."""
        return v

    def xor_perm_shards(self, x, mask_hi):
        """Cross-shard part of the XOR exchange (no-op on one shard)."""
        return x

    def bcast_row0(self, x_local):
        """Global row 0 of a row-sharded array, visible everywhere."""
        return x_local[0]

    def on_first_shard(self):
        return True

    def psum(self, v):
        return v


def make_overlay_tick(cfg: SimConfig, comm=None,
                      use_pallas: bool | None = None,
                      with_coverage: bool | None = None):
    """Build ``tick(state, sched) -> (state', OverlayMetrics)``.

    With the default :class:`LocalOverlayComm` this is a single-device
    program.  With a :class:`~.overlay_sharded.RingOverlayComm` inside
    ``shard_map`` the tables/send_flags are row-sharded and the XOR
    exchange's shard-index bits become a ``ppermute``; all (N,) vectors
    stay replicated.  Both paths are bit-identical
    (tests/test_overlay_sharded.py).

    ``use_pallas`` routes the exchange+merge hot phase through the
    fused Pallas kernel (ops/pallas/overlay_exchange.py; None = auto:
    on for TPU backends) on both the single-device and sharded paths —
    under ``shard_map`` the comm ppermutes each round's payload plane
    by the mask's shard bits and the kernel handles the shard-local
    bits.  The kernel is bit-identical to the XLA phases
    (tests/test_overlay_pallas.py, tests/test_overlay_sharded.py) and
    measured faster on v5e (per tick: ~3.4ms vs ~4.3ms at 65k, ~57ms
    vs ~106ms at 1M — scripts/profile_tick.py, 200-tick scans).

    ``with_coverage`` overrides the per-tick ``live_uncovered``
    histogram (None = auto: tracked for N <= COVERAGE_N_LIMIT).  The
    fleet path passes False — the scatter behind the histogram
    serializes badly under batching (it was ~40% of a CPU tick at
    N=2048) — and reports the same -1 "not tracked" sentinel the mega
    and grid kernels already use; coverage stays verifiable host-side
    on the final state (:meth:`OverlayResult.final_coverage`).
    """
    comm = comm or LocalOverlayComm()
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    powerlaw = cfg.topology == "powerlaw"
    # adversarial failure worlds (worlds.py) — static tick branches,
    # like powerlaw/can_rejoin: the compiled program is world-specific
    # (cfg.worlds_key() rides every run/bucket cache key)
    part = cfg.partition_groups >= 2
    asym = cfg.asym_drop
    zomb = cfg.zombie
    flap = cfg.flap_rate > 0
    # round-2 planes (worlds.py).  byz: liar senders ship forged relay
    # freshness and boosted counters, and never purge (the shield
    # attack); honest receivers clamp relayed freshness to the honest
    # maximum t-2 — a no-op for honest traffic.  latency: each link
    # delays delivery by a seeded [1, L+1]-tick lag read off the
    # sender's send-history shift register.
    byz = cfg.byz_rate > 0
    latency = cfg.link_latency > 0
    # flap up-edges are rejoin events (fresh-nodeStart wipes), so the
    # flap world compiles the churn/rejoin path in
    can_rejoin = cfg.churn_rate > 0 or cfg.rejoin_after is not None \
        or flap
    n = cfg.n
    k, f = resolved_dims(cfg)
    # shapes outside the fused kernel's envelope (k >= N_COUNTERS
    # metric lanes, >= 8 locally-held rows) fall back to the
    # bit-identical XLA phases instead of tripping kernel asserts.
    # The kernel is comm-generic: under shard_map the comm routes the
    # exchange's shard-index bits (ppermute per round) and the kernel
    # handles the shard-local bits (round-2 verdict task — the v4-8
    # path previously inherited the ~2x-slower XLA tick).
    from ..ops.pallas.overlay_exchange import N_COUNTERS
    t_remove = cfg.t_remove
    assert n & (n - 1) == 0, "overlay peer count must be a power of two " \
        "(XOR partner exchange)"
    assert n <= (1 << ID_BITS), \
        f"overlay supports N <= {1 << ID_BITS}"
    assert cfg.total_ticks <= 4094, \
        "the packed (ts, hb) winner payload caps runs at 4094 ticks " \
        "(the reference caps at MAX_TIME 3600, EmulNet.h:11)"
    p = comm.n_shards
    nl = n // p
    assert nl * p == n and nl & (nl - 1) == 0, \
        "shard count must divide the peer count (both powers of two)"
    # the fused kernel does not compile the adversarial worlds (its
    # detection/metrics scalars know only the churn/scripted windows,
    # and zombie/partition change merge/send semantics) — world
    # configs take the bit-identical XLA phases
    use_kernel = bool(use_pallas) and k >= N_COUNTERS and nl >= 8 \
        and not cfg.has_worlds
    factors = _xor_factors(nl)
    if with_coverage is None:
        with_coverage = n <= COVERAGE_N_LIMIT

    rows = jnp.arange(n, dtype=jnp.int32)        # global, replicated
    intro_onehot = rows == INTRODUCER
    kk = jnp.arange(k, dtype=jnp.int32)
    iotas = [jnp.arange(s, dtype=jnp.int32) for s in factors]

    _AX = "abcdef"

    def local_xor_perm(x, mask_lo):
        """x[il ^ mask_lo] over the local rows — one permutation matmul
        per index factor (_xor_factors), written as transpose-free
        einsums so each factor is a single MXU contraction.

        Exactness matters: the TPU default truncates matmul inputs to
        bf16, which rounds ids >= 2^16 (65535 -> 65536) and corrupts
        the tables.  HIGHEST is required: HIGH (bf16x3) nominally
        carries 24 mantissa bits but was measured NOT exact at 2^20-1
        ids on this hardware (caught by the final_coverage corruption
        guard at the 1M config)."""
        nf = len(factors)
        y = x.reshape(tuple(factors) + (x.shape[-1],))
        axes = _AX[:nf] + "D"
        rem = mask_lo
        for j in range(nf - 1, -1, -1):
            s = factors[j]
            mj = rem % s
            rem = rem // s
            pj = (iotas[j][:, None] == (iotas[j][None, :] ^ mj)) \
                .astype(jnp.float32)
            out_axes = axes.replace(_AX[j], "x")
            y = jnp.einsum(f"x{_AX[j]},{axes}->{out_axes}", pj, y,
                           precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)
        return y.reshape(x.shape)

    def xor_perm(x, mask):
        """x[i ^ mask] over global rows: local bits via matmuls, shard
        bits via the comm (a ppermute on a mesh)."""
        y = local_xor_perm(x, mask % nl)
        return comm.xor_perm_shards(y, mask // nl)

    def tick(state: OverlayState, sched: OverlaySchedule):
        t = state.tick
        tu = t.astype(jnp.uint32)
        seed = sched.seed
        # replicated (N,) schedule vectors
        start = sched.start_of(rows)
        fail = sched.fail_of(rows)
        rejoin = sched.rejoin_of(rows)
        # the scripted/churn/wave fail WINDOW, kept separate from the
        # flap overlay: the zombie world applies to window failures
        # only (a flap down-phase is ordinary silence)
        failed_win = (t > fail) & (t <= rejoin)
        failed = failed_win
        rejoining = (t == rejoin) if can_rejoin \
            else jnp.zeros_like(start, bool)
        if flap:
            fl_f, fl_r = sched._flap(rows, t)
            failed = failed | fl_f
            rejoining = rejoining | fl_r
        proc = (t > start) & ~failed

        # local row block
        row_start = comm.row_start(n)
        rows_g = rows[:nl] + row_start               # global ids of local rows
        rows_u = rows_g.astype(jnp.uint32)
        proc_l = comm.slice_rows(proc)
        keep_l = comm.slice_rows(~rejoining)

        # ---- churn wipe (same semantics as core/tick.py) -----------
        # statically compiled out when no config path can rejoin — at
        # 1M peers the wipe's (N, K) selects are measurable dead work
        if can_rejoin:
            keep = ~rejoining
            ids0 = jnp.where(keep_l[:, None], state.ids, -1)
            hb0 = state.hb * keep_l[:, None]
            ts0 = state.ts * keep_l[:, None]
            in_group0 = state.in_group & keep
            own_hb0 = state.own_hb * keep
        else:
            ids0, hb0, ts0 = state.ids, state.hb, state.ts
            in_group0, own_hb0 = state.in_group, state.own_hb
        own_hb0_l = comm.slice_rows(own_hb0)
        if latency:
            # a rejoin is a fresh nodeStart: the node's in-flight
            # stream dies with the wipe (the dense model's buffer
            # instead lets pre-fail traffic deliver late — each model
            # documents its own buffer semantics)
            hist0 = state.send_hist * keep_l[:, None] if can_rejoin \
                else state.send_hist

        # ---- payload of the send tick t-1 --------------------------
        # the sender's whole K-slot view + its self-entry, all from
        # carried state = frozen at the end of tick t-1 (whose table
        # layout epoch is t // SLOT_EPOCH — the re-slot pass runs at
        # the END of a boundary tick, so sender and receiver tables
        # are always identically slotted within a tick)
        slot_ep = (t // SLOT_EPOCH).astype(jnp.uint32)
        # Entries travel as two words per slot — the subject id and the
        # packed (ts, hb) payload word (exactly the merge's `p` value),
        # which halves the permutation width vs separate hb/ts planes.
        p0 = jnp.where(ids0 >= 0, _pack_th(ts0, hb0), 0)

        # ---- vector decisions (pure functions of carried state) ----
        jrep = state.joinrep & proc
        jrep_l = comm.slice_rows(jrep)
        jreq = state.joinreq & proc[INTRODUCER]
        in_group = in_group0 | jrep
        starting = (t == start) | rejoining
        in_group = in_group | (starting & intro_onehot)
        ops = proc & in_group
        own_hb = own_hb0 + ops.astype(jnp.int32)
        ops_l = comm.slice_rows(ops)
        rows_gu_all = rows.astype(jnp.uint32)

        # JOINREQ per-slot aggregates at the introducer: requester
        # entries (j, hb=1, ts=t) reduced to (K,) maxima by a dense
        # (K, N) one-hot max (addMember, MP1Node.cpp:265-280)
        q_slot = _slot_of(seed, slot_ep, rows, k)
        q_key = jnp.where(jreq & ~intro_onehot,
                          _pack_key(rows, jnp.broadcast_to(t, (n,))), 0)
        q_match = q_slot[None, :] == kk[:, None]             # (K, N)
        q_kf = (q_match * q_key[None, :]).max(1)             # (K,)
        q_sel = q_match & (q_key[None, :] == q_kf[:, None]) & (q_kf > 0)[:, None]
        q_pf = jnp.where(q_sel.any(1), _pack_th(t, 1), 0)    # all (t, hb=1)

        joins_recv = jrep.sum().astype(jnp.int32) \
            + jreq.sum().astype(jnp.int32)

        # the partner self-entry's age is exactly 1 tick, so its
        # freshness gate is static in t_remove
        self_entry_fresh = t_remove > 1

        if use_kernel:
            # ---- the whole (Nl, K) phase in one Pallas launch ------
            # (ops/pallas/overlay_exchange.py): accumulator init +
            # proc gating + F exchange rounds + JOINREP/JOINREQ +
            # winner extraction + detection + per-row metric counts.
            # Under shard_map the comm ppermutes each round's whole
            # payload plane by the mask's shard bits; the kernel
            # applies the shard-local bits and receives global row
            # identity via row_start.
            from ..ops.pallas.overlay_exchange import fused_overlay_tick
            masks = jnp.stack([exchange_mask(seed, t - 1, fi, n)
                               for fi in range(f)])
            i32 = jnp.int32
            bits_l = (proc_l.astype(i32) | (ops_l.astype(i32) << 1)
                      | (jrep_l.astype(i32) << 2))
            idsaux = jnp.concatenate([
                ids0, own_hb0_l[:, None], bits_l[:, None],
                state.send_flags.astype(i32)], 1)      # (Nl, K+2+F)
            bc = comm.bcast_row0(jnp.concatenate(
                [ids0, p0, own_hb0_l[:, None]], 1))    # (2K+1,) introducer
            zk = jnp.zeros((k,), i32)
            intro = jnp.stack([
                bc[:k], bc[k:2 * k],
                jnp.zeros((k,), i32).at[0].set(bc[2 * k]),
                q_kf.astype(i32), q_pf,
                zk, zk, zk])                           # (8, K)
            scalars = jnp.stack([
                t, seed.astype(i32), sched.victim_lo, sched.victim_hi,
                sched.fail_tick, sched.rejoin_after,
                sched.churn_thr.astype(i32), sched.churn_after])
            if p == 1:
                aux_rounds = pw_rounds = None
                masks_local = None
                vma = ()
            else:
                vma = (comm.axis,)
                aux_rounds = jnp.stack(
                    [comm.xor_perm_shards(idsaux, masks[fi] // nl)
                     for fi in range(f)])
                pw_rounds = jnp.stack(
                    [comm.xor_perm_shards(p0, masks[fi] // nl)
                     for fi in range(f)])
                masks_local = masks % nl
            ids2, hb2, ts2, ctr = fused_overlay_tick(
                idsaux, p0, intro, masks, scalars,
                k=k, t_remove=t_remove,
                churn_lo=cfg.total_ticks // 4,
                churn_span=max(cfg.total_ticks // 2, 1),
                masks_local=masks_local,
                row_start=jnp.int32(0) + row_start,
                aux_rounds=aux_rounds, pw_rounds=pw_rounds, vma=vma)
            recv_cnt = comm.psum(ctr[:, 0].sum()) + joins_recv
            removals = comm.psum(ctr[:, 1].sum())
            false_removals = comm.psum(ctr[:, 2].sum())
            victims_cnt = comm.psum(ctr[:, 3].sum())
            adds_cnt = comm.psum(ctr[:, 4].sum())
            view_cnt = comm.psum(ctr[:, 5].sum())
            ids_pre = ids2      # pre-re-roll table (kernel output is
            #                     pre-remap; the re-roll runs below)
        else:
            payload = jnp.concatenate([
                ids0.astype(jnp.float32),
                p0.astype(jnp.float32),   # < 2^24, f32-exact
                own_hb0_l.astype(jnp.float32)[:, None],
            ], 1)   # (Nl, 2K+1); the per-round in-flight flag is appended below

            # ---- merge phase: lane-aligned (Nl, K) max per partner -
            # Incoming tables are slotted by the same global map, so
            # the merge is a plain per-lane lexicographic
            # (key, payload) max — no slot-match product.  The
            # winner's (ts, hb) travel as one packed int32
            # ((ts+1) << 12 | hb+1; both < 4095 because runs are
            # capped at 4094 ticks); among equal-priority-key
            # candidates the lexicographic (ts, hb) max wins, which
            # the oracle mirrors.
            cur_key = jnp.where(ids0 >= 0, _pack_key(ids0, ts0), 0)
            keymax = cur_key
            p_acc = p0
            # zero derived from a shard-local value so the exchange
            # scan's carry is shard-varying from the start (shard_map
            # VMA typing)
            recv_cnt = (proc_l.sum() * 0).astype(jnp.int32)

            def lex_merge(keymax, p_acc, key_c, p_c):
                better = (key_c > keymax) \
                    | ((key_c == keymax) & (p_c > p_acc))
                return (jnp.where(better, key_c, keymax),
                        jnp.where(better, p_c, p_acc))

            def table_merge(keymax, p_acc, c_id, c_ts, c_p, valid):
                """Merge an identically-slotted (Nl, K) view.

                ``c_p`` is the already-packed (ts, hb) payload word —
                the wire format and the merge tiebreak coincide."""
                key = jnp.where(valid, _pack_key(c_id, c_ts),
                                jnp.uint32(0))
                return lex_merge(keymax, p_acc, key,
                                 jnp.where(valid, c_p, 0))

            def entry_merge(keymax, p_acc, subj, e_ts, e_hb, ok):
                """Merge one DIRECT (subject, ts, hb) entry per row."""
                sl = _slot_of(seed, slot_ep, subj, k)
                key = jnp.where(ok, _pack_key(subj, e_ts),
                                jnp.uint32(0))
                p = jnp.where(ok, _pack_th(e_ts, e_hb), 0)
                match = sl[:, None] == kk[None, :]
                return lex_merge(
                    keymax, p_acc,
                    jnp.where(match, key[:, None], jnp.uint32(0)),
                    jnp.where(match, p[:, None], 0))

            # rounds are structurally identical, so scan over the mask
            # axis instead of unrolling — XLA's CPU pipeline was
            # observed to hang compiling >= 8 unrolled rounds, and the
            # scan keeps compile size constant in F
            masks = jnp.stack([exchange_mask(seed, t - 1, fi, n)
                               for fi in range(f)])

            def exchange_round(carry, mf):
                keymax, p_acc, recv_cnt = carry
                mask, flag_col = mf
                q = xor_perm(
                    jnp.concatenate([payload, flag_col[:, None]], 1), mask)
                partner = rows_g ^ mask
                in_ids = q[:, :k].astype(jnp.int32)
                in_p = q[:, k:2 * k].astype(jnp.int32)
                in_ts = (in_p >> 12) - 1
                own_p = q[:, 2 * k].astype(jnp.int32)
                if latency:
                    # latency plane: the round delivers the message the
                    # partner sent lat(p, r) ticks ago on this exchange
                    # slot — bit lat-1 of its send-history word (the
                    # pairing mask is evaluated at delivery time, the
                    # sent bit and the self-entry's observation date at
                    # the true send tick).  Payloads stay content-
                    # current, like the dense plane.
                    lat_pr = link_latency_of(
                        seed, partner.astype(jnp.uint32), rows_u,
                        n, cfg.link_latency)
                    hist_w = q[:, 2 * k + 1].astype(jnp.int32)
                    sent_flag = ((hist_w >> (lat_pr - 1)) & 1) > 0
                    self_ts = t - lat_pr
                else:
                    sent_flag = q[:, 2 * k + 1] > 0.5
                    self_ts = jnp.broadcast_to(t - 1, (nl,))
                ok = sent_flag & proc_l
                if byz:
                    # defense first: relayed freshness is clamped to
                    # the honest maximum t-2 (stored tables never carry
                    # a newer stamp — a no-op for honest traffic).  The
                    # forgery then claims exactly that maximum on every
                    # liar entry with boosted counters: the liar's own
                    # diagonal slot is the inflate-your-own-heartbeat
                    # attack, its retained victim entries (no purge
                    # below) the shield attack.
                    liar_p = sched.byz_of(partner)
                    in_hb = jnp.where(in_ids >= 0, (in_p & 0xFFF) - 1, 0)
                    in_ts = jnp.minimum(in_ts, t - 2)
                    in_ts = jnp.where(liar_p[:, None], t - 2, in_ts)
                    in_hb = jnp.where(
                        liar_p[:, None],
                        jnp.minimum(in_hb + sched.byz_boost, 4093),
                        in_hb)
                    in_p = jnp.where(in_ids >= 0,
                                     _pack_th(in_ts, in_hb), 0)
                    own_p = jnp.where(liar_p, own_p + sched.byz_boost,
                                      own_p)
                valid = ok[:, None] & (in_ids >= 0) \
                    & (t - in_ts < t_remove) & (in_ids != rows_g[:, None])
                recv_cnt += ok.sum().astype(jnp.int32)
                keymax, p_acc = table_merge(
                    keymax, p_acc, in_ids, in_ts, in_p, valid)
                if self_entry_fresh:
                    cred = ok
                    if zomb:
                        # zombie world: a message from a window-failed
                        # sender carries a FROZEN heartbeat — its
                        # liveness claim is dated at the fail tick, not
                        # the send tick, so it earns no direct
                        # self-entry; its stale table rows still merged
                        # above under the ordinary freshness gates.
                        # Under latency the claim is dated at the TRUE
                        # send tick t - lat (config validation keeps
                        # every lat below the t_remove horizon).
                        cred = ok & ~sched.window_failed_at(partner,
                                                            self_ts)
                    keymax, p_acc = entry_merge(
                        keymax, p_acc, partner, self_ts, own_p, cred)
                return (keymax, p_acc, recv_cnt), None

            flight = hist0.astype(jnp.float32) if latency \
                else state.send_flags.astype(jnp.float32)
            (keymax, p_acc, recv_cnt), _ = jax.lax.scan(
                exchange_round, (keymax, p_acc, recv_cnt),
                (masks, flight.T))
            recv_cnt = comm.psum(recv_cnt)

            # ---- JOINREP (introducer's payload broadcast) ----------
            bc = comm.bcast_row0(payload)            # (2K+1,) introducer
            b_ids = jnp.broadcast_to(bc[:k].astype(jnp.int32), (nl, k))
            b_p = jnp.broadcast_to(bc[k:2 * k].astype(jnp.int32), (nl, k))
            b_ts = (b_p >> 12) - 1
            j_valid = jrep_l[:, None] & (b_ids >= 0) \
                & (t - b_ts < t_remove) & (b_ids != rows_g[:, None])
            keymax, p_acc = table_merge(keymax, p_acc, b_ids, b_ts, b_p,
                                        j_valid)
            if self_entry_fresh:
                intro_vec = jnp.broadcast_to(jnp.int32(INTRODUCER), (nl,))
                j_ok = jrep_l & (intro_vec != rows_g)
                if zomb:
                    j_ok = j_ok & ~sched.window_failed_at(
                        jnp.int32(INTRODUCER), t - 1)
                keymax, p_acc = entry_merge(
                    keymax, p_acc, intro_vec,
                    jnp.broadcast_to(t - 1, (nl,)),
                    jnp.broadcast_to(bc[2 * k].astype(jnp.int32), (nl,)),
                    j_ok)

            # ---- JOINREQ aggregates into (the shard holding) row 0 -
            on0 = comm.on_first_shard()
            row0_new = jnp.where(on0, jnp.maximum(keymax[0], q_kf),
                                 keymax[0])
            same0 = on0 & (q_kf == row0_new)
            was0 = keymax[0] == row0_new
            p0_row = jnp.where(same0,
                               jnp.maximum(q_pf,
                                           jnp.where(was0, p_acc[0], 0)),
                               p_acc[0])
            keymax = keymax.at[0].set(row0_new)
            p_acc = p_acc.at[0].set(p0_row)
            recv_cnt += joins_recv

            ids1 = jnp.where(keymax > 0,
                             (keymax & ID_MASK).astype(jnp.int32), -1)
            ts1 = jnp.where(keymax > 0, (p_acc >> 12) - 1, 0)
            hb1 = jnp.where(keymax > 0, (p_acc & 0xFFF) - 1, 0)

            # ---- detection (nodeLoopOps analog) --------------------
            stale = (ids1 >= 0) & (t - ts1 >= t_remove) & ops_l[:, None]
            if byz:
                # liars never purge: retained dead entries keep being
                # re-advertised at forged freshness — the shield attack
                # (an honest dense receiver defeats it via direct-only
                # credit; the unauthenticated overlay documents it as a
                # real limit, bounded only by slot-priority eviction)
                stale = stale & ~comm.slice_rows(sched.byz_of(rows))[:, None]
            subj = jnp.clip(ids1, 0)
            subj_fail = sched.fail_of(subj)
            subj_failed = (t > subj_fail) & (t <= sched.rejoin_of(subj))
            if flap:
                # a flap-down subject's removal is a TRUE positive
                subj_failed = subj_failed | sched._flap(subj, t)[0]
            removals = comm.psum(stale.sum().astype(jnp.int32))
            false_removals = comm.psum(
                (stale & ~subj_failed).sum().astype(jnp.int32))
            ids2 = jnp.where(stale, -1, ids1)
            hb2 = jnp.where(stale, 0, hb1)
            ts2 = jnp.where(stale, 0, ts1)
            ids_pre = ids2      # pre-re-roll table for aligned metrics
            victims_cnt = comm.psum(
                ((ids_pre >= 0) & subj_failed & ~stale)
                .sum().astype(jnp.int32))
            adds_cnt = comm.psum(
                ((ids1 != ids0) & (ids1 >= 0)).sum().astype(jnp.int32))
            view_cnt = comm.psum((ids_pre >= 0).sum().astype(jnp.int32))

        # ---- nodeStart / rejoin sends (replicated vector math) -----
        joinreq_new = starting & ~intro_onehot
        active = sched.drop_active(t)
        if asym:
            # asymmetric per-link drop (worlds.py): the JOINREQ row
            # uses each sender's link to the introducer, the JOINREP
            # row the introducer's link to each receiver — same single
            # windowed draw, per-link threshold
            qthr = sched.link_thr(rows_gu_all, np.uint32(INTRODUCER))
            pthr = sched.link_thr(np.uint32(INTRODUCER), rows_gu_all)
        else:
            qthr = pthr = sched.drop_thr
        qdrop = mix32(seed, tu, rows_gu_all, np.uint32(_SALT_JOINREQ_DROP)) \
            < qthr
        pdrop = mix32(seed, tu, rows_gu_all, np.uint32(_SALT_JOINREP_DROP)) \
            < pthr
        joinreq_sent = joinreq_new & ~(active & qdrop)
        joinrep_sent = jreq & ~(active & pdrop)      # introducer's replies
        if part:
            # the partition world gates sends exactly like a drop
            # decision: while the window is open, cross-group JOINREQ/
            # JOINREP traffic is blocked at send time (a deterministic
            # mask, no PRNG draw)
            pa = sched.part_active(t)
            grp = sched.group_of(rows)
            cross_intro = grp != grp[INTRODUCER]
            joinreq_sent = joinreq_sent & ~(pa & cross_intro)
            joinrep_sent = joinrep_sent & ~(pa & cross_intro)

        # ---- slot-map re-roll at the SLOT_EPOCH boundary -----------
        # Every node re-slots its surviving entries into the next
        # epoch's global map in one (Nl, K, K) contention pass —
        # collisions resolved by the same lexicographic (key, payload)
        # rule as any merge.  Runs on 1/SLOT_EPOCH of ticks
        # (lax.cond); row-blocked so the broadcast stays bounded at
        # large N.  Applies to every row (layout is global, not
        # protocol activity), so per-tick table metrics above describe
        # the pre-re-roll table on boundary ticks.
        next_ep = ((t + 1) // SLOT_EPOCH).astype(jnp.uint32)

        def reslot(tabs):
            idsv, hbv, tsv = tabs
            tgt = _slot_of(seed, next_ep, idsv, k)           # (Nl, K)
            key = jnp.where(idsv >= 0, _pack_key(idsv, tsv),
                            jnp.uint32(0))
            p = jnp.where(idsv >= 0, _pack_th(tsv, hbv), 0)

            def block(args):
                tgt_b, key_b, p_b = args
                match = tgt_b[:, None, :] == kk[None, :, None]  # (B, K, K)
                kf = (match * key_b[:, None, :]).max(2)
                sel = match & (key_b[:, None, :] == kf[:, :, None]) \
                    & (kf > 0)[:, :, None]
                pf = jnp.where(sel, p_b[:, None, :], 0).max(2)
                return kf, pf

            nb = max(1, nl // REMAP_BLOCK)
            if nb == 1:
                kf, pf = block((tgt, key, p))
            else:
                shp = lambda x: x.reshape((nb, nl // nb, k))
                kf, pf = jax.lax.map(block, (shp(tgt), shp(key), shp(p)))
                kf = kf.reshape(nl, k)
                pf = pf.reshape(nl, k)
            return (jnp.where(kf > 0, (kf & ID_MASK).astype(jnp.int32),
                              -1),
                    jnp.where(kf > 0, (pf & 0xFFF) - 1, 0),
                    jnp.where(kf > 0, (pf >> 12) - 1, 0))

        ids2, hb2, ts2 = jax.lax.cond(
            next_ep != slot_ep, reslot, lambda tabs: tabs, (ids2, hb2, ts2))

        # ---- dissemination: set the in-flight flags ----------------
        fis = jnp.arange(f, dtype=jnp.uint32)
        if part or asym:
            # the partner of local row i on exchange slot fi of the
            # NEXT tick's delivery is i ^ mask(t, fi) — known at send
            # time, so both link-dependent worlds gate here
            masks_nxt = jnp.stack([exchange_mask(seed, t, fi, n)
                                   for fi in range(f)])
            partners = rows_g[:, None] ^ masks_nxt[None, :]   # (Nl, F)
        if asym:
            gthr = sched.link_thr(rows_u[:, None],
                                  partners.astype(jnp.uint32))
        else:
            gthr = sched.drop_thr
        gdrop = mix32(seed, tu, rows_u[:, None], fis[None, :],
                      np.uint32(_SALT_GOSSIP_DROP)) < gthr
        send_src = ops_l
        if zomb:
            # zombie world: window-failed in-group peers keep gossiping
            # their FROZEN tables (their rows merged nothing and were
            # skipped by detection while failed, so the payload is
            # exactly the table at their fail tick)
            send_src = ops_l | comm.slice_rows(failed_win & in_group0)
        send_flags = send_src[:, None] & ~(active & gdrop)
        if part:
            send_flags = send_flags \
                & ~(pa & (comm.slice_rows(grp)[:, None]
                          != sched.group_of(partners)))
        if powerlaw:
            # scale-free out-degrees: node i gossips only on its first
            # deg(i) rounds (a static seeded node property; hubs cover
            # all F rounds, leaves one).  Statically compiled out for
            # the uniform topology.
            du = mix32(seed, rows_u, np.uint32(_SALT_DEGREE))
            deg = 1 + (du[:, None] < sched.deg_thr[None, :]) \
                .sum(1).astype(jnp.int32)
            send_flags = send_flags \
                & (fis.astype(jnp.int32)[None, :] < deg[:, None])
        sent = comm.psum(send_flags.sum().astype(jnp.int32)) \
            + joinreq_sent.sum().astype(jnp.int32) \
            + joinrep_sent.sum().astype(jnp.int32)

        if latency:
            # shift the send history: bit 0 = sent this tick (mirrors
            # send_flags), bit a = sent a ticks before that; the word
            # is capped at the largest drawable delay L+1 (<= 24 bits)
            send_hist = ((hist0 << 1) | send_flags.astype(jnp.int32)) \
                & ((1 << (cfg.link_latency + 1)) - 1)
        else:
            send_hist = state.send_hist

        live_hold = ~proc & ~failed
        joinreq_next = joinreq_sent | (state.joinreq
                                       & ~proc[INTRODUCER] & ~failed[INTRODUCER])
        joinrep_next = joinrep_sent | (state.joinrep & live_hold)

        live_member = in_group & ~failed & ~intro_onehot
        if with_coverage:
            covered = comm.psum(
                jnp.zeros(n, jnp.int32).at[jnp.clip(ids_pre, 0).reshape(-1)]
                .max((ids_pre >= 0).reshape(-1).astype(jnp.int32))) > 0
            live_uncovered = (live_member & ~covered).sum().astype(jnp.int32)
        else:
            live_uncovered = jnp.int32(-1)

        metrics = OverlayMetrics(
            in_group=in_group.sum().astype(jnp.int32),
            view_slots=view_cnt,
            adds=adds_cnt,
            removals=removals,
            false_removals=false_removals,
            victim_slots=victims_cnt,
            live_uncovered=live_uncovered,
            sent=sent,
            recv=recv_cnt,
        )
        new_state = OverlayState(
            tick=t + 1,
            ids=ids2, hb=hb2, ts=ts2,
            in_group=in_group, own_hb=own_hb,
            send_flags=send_flags, send_hist=send_hist,
            joinreq=joinreq_next, joinrep=joinrep_next,
        )
        return new_state, metrics

    return tick


def covered_histogram(ids, n: int, chunk: int = 1 << 15):
    """bool[N]: which subject ids appear in at least one view slot.

    Scatter-free (SURVEY: gather/scatter serialize at ~75M indices/s on
    this TPU): the presence histogram is computed as a blocked int8
    one-hot matmul — split each id j into (j >> 8, j & 255) and count
    entries per (hi, lo) bin pair with an int8 MXU contraction (exact:
    i8 x i8 accumulates in i32).  O(N*K*(N/256 + 256)) int8 work, ~2 GB
    of one-hot traffic at N=65536 — cheap enough to sample at launch
    boundaries during validation, far cheaper than the 4.2M-index
    scatter it replaces.  Intended for N <= ~2^17; the 1M config keeps
    final-snapshot validation (bench.py)."""
    assert n & (n - 1) == 0 and n >= 256, n
    c = 256
    r = n // c
    e = ids.reshape(-1)
    pad = (-e.shape[0]) % chunk
    if pad:
        e = jnp.concatenate([e, jnp.full((pad,), -1, e.dtype)])
    valid = e >= 0
    ei = jnp.where(valid, e, 0)
    hs = (ei >> 8).reshape(-1, chunk)
    ls = (ei & 255).reshape(-1, chunk)
    vs = valid.reshape(-1, chunk)
    iota_r = jnp.arange(r, dtype=jnp.int32)[None, :]
    iota_c = jnp.arange(c, dtype=jnp.int32)[None, :]

    def step(acc, args):
        h, l, v = args
        oh_h = ((h[:, None] == iota_r) & v[:, None]).astype(jnp.int8)
        oh_l = (l[:, None] == iota_c).astype(jnp.int8)
        acc = acc + jax.lax.dot_general(
            oh_h, oh_l, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        return acc, None

    acc, _ = jax.lax.scan(step, jnp.zeros((r, c), jnp.int32),
                          (hs, ls, vs))
    return (acc > 0).reshape(n)


_OVERLAY_RUN_CACHE: dict = {}


def make_overlay_run(cfg: SimConfig, length: int | None = None,
                     use_pallas: bool | None = None,
                     start_tick: int | None = None):
    """``lax.scan`` over ``length`` ticks (default: the whole run):
    ``run(state, sched) -> (final, metrics[length])``.  The schedule is
    closed-form in the absolute clock carried in the state, so a
    shorter scan resumes mid-run bit-identically.

    With ``use_pallas`` (auto on TPU) and a config inside the
    megakernel envelope (models/overlay_mega.py), the run executes
    MEGA_TICKS whole ticks per Pallas launch with state resident in
    VMEM — bit-identical to the per-tick path, but without the
    per-launch dispatch floor.  Its one observable difference:
    per-tick ``live_uncovered`` is the "not tracked" sentinel -1
    (coverage is still validated on the final state host-side).

    ``start_tick`` pins the run's absolute start tick at trace time;
    it only affects the grid path, which then compiles
    schedule-segmented kernel variants (models/segments.py) —
    bit-identical to the unsegmented run but with dead protocol
    phases statically elided per segment.  Leave it ``None`` when the
    caller resumes from arbitrary clocks."""
    length = cfg.total_ticks if length is None else length
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    from .overlay_grid import grid_supported, make_grid_run
    from .overlay_mega import make_mega_run, mega_supported
    mega = bool(use_pallas) and mega_supported(cfg)
    # above the VMEM-megakernel envelope the grid-scale multi-tick
    # kernel takes over (HBM-resident double-buffered state, TPU only:
    # the eager interpret-mode launch sequence is for tests)
    grid = (bool(use_pallas) and not mega and grid_supported(cfg)
            and jax.default_backend() == "tpu")
    key = (cfg.n, cfg.t_remove, length, resolved_dims(cfg), use_pallas,
           cfg.topology, cfg.total_ticks, mega, grid,
           cfg.churn_rate > 0 or cfg.rejoin_after is not None,
           # the grid kernel bakes churn-vs-scripted statically
           cfg.churn_rate > 0,
           # the adversarial worlds are static tick branches
           # (zombie/asym/partition/flap), so they are program identity
           cfg.worlds_key(),
           # the segment plan is a function of the pinned start tick
           start_tick if grid else None,
           cfg.step_rate if grid else None,
           (cfg.drop_msg, cfg.drop_open_tick, cfg.drop_close_tick,
            cfg.fail_tick, cfg.rejoin_after) if grid else None)
    if key in _OVERLAY_RUN_CACHE:
        return _OVERLAY_RUN_CACHE[key]
    if mega:
        run = make_mega_run(cfg, length)
        _OVERLAY_RUN_CACHE[key] = run
        return run
    if grid:
        run = make_grid_run(cfg, length, start_tick=start_tick)
        _OVERLAY_RUN_CACHE[key] = run
        return run
    tick = make_overlay_tick(cfg, use_pallas=use_pallas)

    @jax.jit
    def run(state: OverlayState, sched: OverlaySchedule):
        def step(carry, _):
            return tick(carry, sched)
        return jax.lax.scan(step, state, None, length=length)

    _OVERLAY_RUN_CACHE[key] = run
    return run


_OVERLAY_FLEET_CACHE: dict = {}

#: vmap axes of a stacked overlay fleet: every lane carries its own
#: arrays but the CLOCK is shared (``tick=None``), exactly like
#: core/fleet.WORLD_AXES — the lane-mesh path
#: (parallel/fleet_mesh.py) derives its replicated-vs-sharded
#: PartitionSpecs from this tree, so the two stay in lockstep by
#: construction.
OVERLAY_FLEET_STATE_AXES = OverlayState(tick=None, ids=0, hb=0, ts=0,
                                        in_group=0, own_hb=0,
                                        send_flags=0, send_hist=0,
                                        joinreq=0, joinrep=0)


def make_overlay_fleet_run(cfg: SimConfig, batch: int,
                           length: int | None = None,
                           use_pallas: bool | None = None,
                           start_tick: int = 0):
    """One compiled program over ``batch`` stacked overlay lanes.

    ``run(states, scheds) -> (finals, OverlayMetrics[batch, length])``:
    ``states`` is a stacked :class:`OverlayState` whose ``tick`` is a
    SHARED scalar (every lane starts at the same clock and ticks in
    lockstep — that keeps the SLOT_EPOCH re-slot ``lax.cond`` a real
    cond under ``vmap`` instead of degrading to a both-branches
    select), and ``scheds`` a stacked :class:`OverlaySchedule` (every
    field batched; distinct seeds live here).

    Routing (core/fleet.py is the orchestrator):

    * TPU + grid-supported config: the batched grid kernel — an
      explicit leading batch grid dimension
      (:func:`~.overlay_grid.make_grid_fleet_run`), never
      ``jax.vmap``-of-``pallas_call``.
    * everywhere else: the XLA tick under ``jax.vmap`` inside one
      jitted ``lax.scan`` with the stacked carry donated
      (``donate_argnums``) — one dispatch per scan step for the whole
      fleet.  Built with ``with_coverage=False``: per-tick
      ``live_uncovered`` reports the -1 sentinel (exactly like the
      mega/grid kernels; see :func:`make_overlay_tick`).

    Per lane the trajectory is bit-identical to a sequential
    :func:`make_overlay_run` of the lane's schedule
    (tests/test_fleet.py); only the ``live_uncovered`` metric differs.

    ``start_tick`` pins the absolute clock the scan starts at — the
    checkpoint/resume leg path (core/fleet.py ``launch_leg``) passes
    its cut so the GRID path plans (and clock-guards) the segment-
    specialized kernels from the right tick; leg boundaries are
    segment cuts, so the resumed plan is the tick-0 plan's tail and
    phase elision stays static.  The XLA vmap path reads the clock
    from the carried state and ignores it (any start is exact there).
    """
    length = cfg.total_ticks if length is None else length
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    from .overlay_grid import grid_supported, make_grid_fleet_run
    grid = (bool(use_pallas) and grid_supported(cfg)
            and jax.default_backend() == "tpu")
    # start_tick only shapes the grid build (segment plan + clock
    # guard); keying it unconditionally would mint redundant XLA-path
    # entries for every cut
    key = (cfg.replace(seed=0), batch, length, grid,
           start_tick if grid else 0)
    if key in _OVERLAY_FLEET_CACHE:
        return _OVERLAY_FLEET_CACHE[key]
    # a miss is a whole-run build: keep core.tick.run_build_count the
    # single process-wide odometer (the serving layer's one-build-per-
    # bucket contract is a delta on it)
    from ..core.tick import note_build
    note_build()
    if grid:
        run = make_grid_fleet_run(cfg, length, batch,
                                  start_tick=start_tick)
        _OVERLAY_FLEET_CACHE[key] = run
        return run
    tick = make_overlay_tick(cfg, use_pallas=False, with_coverage=False)
    state_axes = OVERLAY_FLEET_STATE_AXES
    vtick = jax.vmap(tick, in_axes=(state_axes, 0),
                     out_axes=(state_axes, 0))

    @partial(jax.jit, donate_argnums=(0,))
    def run(states: OverlayState, scheds: OverlaySchedule):
        def step(carry, _):
            return vtick(carry, scheds)
        finals, mets = jax.lax.scan(step, states, None, length=length)
        # scan stacks ticks leading: (T, B) -> the (B, T) fleet contract
        return finals, jax.tree.map(lambda m: m.T, mets)

    _OVERLAY_FLEET_CACHE[key] = run
    return run


def _overlay_expect(host):
    n, k = np.asarray(host["ids"]).shape
    f = np.asarray(host["send_flags"]).shape[1]
    return {"tick": (), "ids": (n, k), "hb": (n, k), "ts": (n, k),
            "in_group": (n,), "own_hb": (n,), "send_flags": (n, f),
            "send_hist": (n, f), "joinreq": (n,), "joinrep": (n,)}


def overlay_state_to_host(state: OverlayState) -> dict:
    """Device state -> plain numpy dict (checkpointing)."""
    from ..state import struct_to_host
    return struct_to_host(state)


def overlay_state_from_host(host: dict) -> OverlayState:
    """Inverse of :func:`overlay_state_to_host`, schema-checked."""
    from ..state import struct_from_host
    return struct_from_host(host, OverlayState, _overlay_expect)


def save_overlay_checkpoint(state: OverlayState, path: str) -> None:
    """Write a mid-run checkpoint; the path is used verbatim."""
    from ..state import save_struct_checkpoint
    save_struct_checkpoint(state, path)


def load_overlay_checkpoint(path: str) -> OverlayState:
    from ..state import load_struct_checkpoint
    return load_struct_checkpoint(path, OverlayState, _overlay_expect)


@dataclasses.dataclass
class OverlayResult:
    cfg: SimConfig
    sched: OverlaySchedule
    final_state: OverlayState
    metrics: OverlayMetrics      # numpy arrays, each [T]
    wall_seconds: float

    @property
    def ticks_run(self) -> int:
        """Ticks executed in this (possibly partial) segment."""
        return int(np.asarray(self.metrics.in_group).shape[0])

    @property
    def node_ticks_per_second(self) -> float:
        """Work rate; 0.0 for degenerate segments (same guard as
        ``SimResult.ticks_per_second``: a zero-length resumed segment
        pairs 0 ticks with a ~0 — possibly sub-resolution — wall)."""
        if self.ticks_run == 0 or self.wall_seconds <= 0.0:
            return 0.0
        return self.cfg.n * self.ticks_run / self.wall_seconds

    def uncovered_members(self):
        """ids of live members present in NO view of the final tables
        (host-side; the large-N stand-in for the per-tick coverage
        histogram).  Evaluated at the state's own clock, so partial
        segments are judged against the schedule at their stopping
        point."""
        ids = np.asarray(self.final_state.ids)
        n = self.cfg.n
        t_end = int(np.asarray(self.final_state.tick))
        if ids.max() >= n:
            raise AssertionError(
                f"corrupt view table: id {ids.max()} >= N={n}")
        present = np.zeros(n, bool)
        present[ids[ids >= 0]] = True
        i = np.arange(n)
        fail = np.asarray(self.sched.fail_of(jnp.asarray(i)))
        rejoin = np.asarray(self.sched.rejoin_of(jnp.asarray(i)))
        failed = (t_end > fail) & (t_end <= rejoin)
        # flapping members (worlds.py): a node in a down phase at the
        # final tick is not live (no-op when the flap world is off)
        fl_f, _ = self.sched._flap(jnp.asarray(i), jnp.int32(t_end))
        failed = failed | np.asarray(fl_f)
        in_group = np.asarray(self.final_state.in_group)
        live = in_group & ~failed & (i != INTRODUCER)
        return np.flatnonzero(live & ~present)

    def final_coverage(self):
        """(live_uncovered_count, victim_entries_left) from the final
        tables; see :meth:`uncovered_members`."""
        ids = np.asarray(self.final_state.ids)
        t_end = int(np.asarray(self.final_state.tick))
        i = np.arange(self.cfg.n)
        fail = np.asarray(self.sched.fail_of(jnp.asarray(i)))
        rejoin = np.asarray(self.sched.rejoin_of(jnp.asarray(i)))
        flat = ids[ids >= 0]
        victim_left = int(((t_end > fail[flat]) & (t_end <= rejoin[flat])).sum())
        return int(self.uncovered_members().size), victim_left


class OverlaySimulation:
    """Orchestrator for cfg.model == "overlay" runs (metrics mode)."""

    def __init__(self, cfg: SimConfig, use_pallas: bool | None = None):
        if cfg.model != "overlay":
            raise ValueError("OverlaySimulation requires cfg.model='overlay'")
        self.cfg = cfg
        self.use_pallas = use_pallas
        # pre-build/cache the whole-run function (fresh runs start at
        # tick 0, which is what run() requests for non-resumed runs)
        make_overlay_run(cfg, use_pallas=use_pallas, start_tick=0)

    def run(self, profile_dir=None, resume_from: OverlayState | None = None,
            ticks: int | None = None):
        """Run the configured scenario.

        ``resume_from`` continues a (possibly checkpointed) state —
        the clock and in-flight flags live in the state and the
        schedule is closed-form in the absolute clock, so the
        continuation is bit-identical to an uninterrupted run.
        ``ticks`` stops the segment early (to checkpoint mid-run).
        ``profile_dir`` wraps the run in ``jax.profiler.trace``
        (SURVEY.md §5 tracing hook).
        """
        import time
        if profile_dir is not None:
            with jax.profiler.trace(profile_dir):
                return self.run(resume_from=resume_from, ticks=ticks)
        cfg = self.cfg
        sched = make_overlay_schedule(cfg)
        state = init_overlay_state(cfg) if resume_from is None else resume_from
        first = int(np.asarray(state.tick))
        if first > cfg.total_ticks:
            raise ValueError(
                f"resume_from is at tick {first}, past total_ticks="
                f"{cfg.total_ticks}")
        if ticks is not None and ticks < 0:
            raise ValueError(f"ticks must be >= 0, got {ticks}")
        t_end = cfg.total_ticks if ticks is None \
            else min(cfg.total_ticks, first + ticks)
        # the start tick is concrete here, so the grid path can route
        # through the segment planner (schedule-specialized variants)
        run = make_overlay_run(cfg, t_end - first,
                               use_pallas=self.use_pallas,
                               start_tick=first)
        t0 = time.perf_counter()
        final, metrics = run(state, sched)
        jax.block_until_ready(final)
        if int(np.asarray(final.tick)) != t_end:
            raise RuntimeError("overlay run did not complete")
        wall = time.perf_counter() - t0
        return OverlayResult(cfg=cfg, sched=sched, final_state=final,
                             metrics=jax.tree.map(np.asarray, metrics),
                             wall_seconds=wall)
