"""Bounded partial-view overlay: the large-N scaling model.

The reference's protocol is full-view: every node stores an entry for
every other node and gossips its entire list to everyone each tick
(MP1Node.cpp:350-361), which is O(N²) state and O(N³) merge work — and
it hard-caps at N<=10 (MP1Node.cpp:245) / N<=1000 (EmulNet.h:10).  The
dense model in ``core/tick.py`` removes the caps but keeps O(N²) state,
so BASELINE's 65k and 1M peer configs are unreachable by construction.
This module is the scaling answer: a **bounded partial-view** membership
protocol with O(N·K) state and O(N·F·L) work per tick.

Design: TPU-first, and specifically **gather/scatter/sort-free** — on
TPU those lower to serialized index loops (measured ~75M indices/s,
hundreds of ms per tick at 65k), so every phase here is dense algebra:

* **Dissemination = XOR partner exchange.**  At tick t every in-group
  node exchanges its payload with the F partners ``i ^ m_f(t)``, where
  the nonzero masks ``m_f(t)`` are counter-hashed fresh each tick —
  a new random F-regular graph per tick over the 2^b address space
  (the Erdős–Rényi-flavored fanout of the BASELINE configs), which
  mixes like an expander.  Applying ``x[i ^ m]`` to the whole payload
  matrix is two small permutation **matmuls** (the XOR factors
  bitwise across a HI×LO index split), exact in f32 and riding the
  MXU — no gather anywhere.  Payloads carry a rotating L-slot window
  of the sender's view plus its self-entry, frozen at the send tick
  (= the carried state, the dense model's zero-copy trick).
* **View = per-receiver hash-slotted table.**  Node ``r`` can hold an
  entry for peer ``j`` only in slot ``h(r, j) = mix32(r, j) % K``
  (utils/hash32.py).  Collisions contend; the winner of a slot is the
  entry with the largest packed uint32 key — freshness band first,
  then an **epoch-rotated per-receiver tiebreak** — evaluated as a
  dense (N, K, L+1) masked max per partner (K and L are small static
  constants, so the "scatter" is a masked reduction).  The rotation is
  load-bearing: a sticky max-(ts, id) key freezes view composition,
  freshness waves stop reaching peripheral holders, and live entries
  age out as false removals.  With rotation, views continuously
  reshuffle (the TPU-shaped analog of Cyclon-style view exchange).
* **Freshness is the priority.**  A live node stamps its own entry
  ``(id, own_hb, now)`` into every payload; the banded max-merge
  propagates the freshest observation along exchange paths, so an
  entry's ``ts`` is the newest time anyone in the path cone saw the
  subject alive.  Failure detection is the reference's staleness rule
  (now - ts >= TREMOVE, MP1Node.cpp:339-348).
* **Schedules are closed-form.**  Start ramp, scripted failures,
  churn membership, churn fail/rejoin ticks, and drop decisions are
  all pure counter-hash functions of (seed, id, tick) — no (N,)
  schedule arrays to look up by id on device (an id-indexed lookup is
  a gather), and the numpy oracle (testing/overlay_oracle.py) replays
  them bit-exactly.

Accuracy semantics at scale: per-holder staleness removals are
*expected background churn* in a bounded partial view (an entry's
refresh is arrival-limited); the guarantees that matter are global —
every live member stays covered by the union of views, failed peers
are purged from every view within the detection horizon, and churned
peers re-enter through the normal JOINREQ path.  The reference-faithful
per-observer guarantees live in the dense model.

Deliberate divergences from the reference protocol (this is the
framework's scaling extension): receivers adopt the freshest (ts, hb)
observation instead of the increment-on-direct-gossip quirk
(MP1Node.cpp:236-239); views are bounded, so entries can be evicted by
slot contention; dissemination follows the XOR schedule rather than
"send to everyone I know"; payloads are sampled windows, not full
lists.
"""

from __future__ import annotations

import dataclasses
import math
from fractions import Fraction

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..config import INTRODUCER, SimConfig
from ..state import NEVER
from ..utils.hash32 import mix32, threshold32

#: id field width in the packed priority key: ids + 1 <= 2^21 - 1, and
#: the XOR exchange needs a power-of-two peer count, so the largest
#: supported group is N = 2^20 = 1,048,576 — the BASELINE 1M-peer
#: config exactly.
ID_BITS = 21
ID_MASK = (1 << ID_BITS) - 1

#: freshness band width (ticks) and tiebreak rotation period
BAND = 4
EPOCH = 4
_TIE_BITS = 8

# salts for the independent counter-hash streams
_SALT_MASK = 1
_SALT_GOSSIP_DROP = 2
_SALT_JOINREQ_DROP = 3
_SALT_JOINREP_DROP = 4
_SALT_CHURN = 5
_SALT_CHURN_TICK = 6


@struct.dataclass
class OverlayState:
    """World state: O(N·K) tables plus O(N·F) in-flight send flags."""

    tick: jax.Array        # i32 scalar
    ids: jax.Array         # i32[N, K] — entry subject id, -1 = empty slot
    hb: jax.Array          # i32[N, K] — heartbeat of the entry
    ts: jax.Array          # i32[N, K] — freshest observation time
    in_group: jax.Array    # bool[N]
    own_hb: jax.Array      # i32[N]
    send_flags: jax.Array  # bool[N, F] — node gossiped on exchange slot f
                           #   last tick (in-flight traffic marker)
    joinreq: jax.Array     # bool[N] — JOINREQ to the introducer in flight
    joinrep: jax.Array     # bool[N] — JOINREP back to the joiner in flight


@struct.dataclass
class OverlaySchedule:
    """Closed-form schedule: scalars only, evaluated per (id, tick).

    ``fail_of``/``rejoin_of``/``start_of`` are pure functions usable on
    whole id arrays — the device never indexes a schedule table.
    With ``churn_thr > 0`` continuous churn replaces the scripted
    failure (the BASELINE 65k/20%-churn shape); otherwise the scripted
    single/multi failure interval applies.
    """

    seed: jax.Array         # u32 scalar
    step_num: jax.Array     # i32 — start ramp: start(i) = i*num//den
    step_den: jax.Array     # i32
    victim_lo: jax.Array    # i32 — scripted failure interval [lo, hi)
    victim_hi: jax.Array    # i32
    fail_tick: jax.Array    # i32 — scripted failure tick
    rejoin_after: jax.Array  # i32 — NEVER disables rejoin
    churn_thr: jax.Array    # u32 — churn membership threshold (0 = off)
    churn_lo: jax.Array     # i32 — churn fail ticks in [lo, lo+span)
    churn_span: jax.Array   # i32
    churn_after: jax.Array  # i32 — churn rejoin delay
    drop_on: jax.Array      # bool — drop window configured
    drop_open: jax.Array    # i32 — droppable sends: open < t <= close
    drop_close: jax.Array   # i32
    drop_thr: jax.Array     # u32 — per-message Bernoulli threshold

    def start_of(self, i):
        return (i * self.step_num) // self.step_den

    def _churned(self, i):
        iu = i.astype(jnp.uint32) if hasattr(i, "astype") else np.uint32(i)
        sel = mix32(self.seed, iu, np.uint32(_SALT_CHURN)) < self.churn_thr
        return sel & (i != INTRODUCER)

    def fail_of(self, i):
        iu = i.astype(jnp.uint32) if hasattr(i, "astype") else np.uint32(i)
        churn_fail = self.churn_lo + (
            mix32(self.seed, iu, np.uint32(_SALT_CHURN_TICK))
            % self.churn_span.astype(jnp.uint32)).astype(jnp.int32)
        scripted = jnp.where((i >= self.victim_lo) & (i < self.victim_hi),
                             self.fail_tick, NEVER)
        return jnp.where(self.churn_thr > 0,
                         jnp.where(self._churned(i), churn_fail, NEVER),
                         scripted)

    def rejoin_of(self, i):
        fail = self.fail_of(i)
        after = jnp.where(self.churn_thr > 0, self.churn_after,
                          self.rejoin_after)
        return jnp.where((fail != NEVER) & (after != NEVER),
                         fail + after, NEVER)

    def drop_active(self, t):
        return self.drop_on & (t > self.drop_open) & (t <= self.drop_close)


def make_overlay_schedule(cfg: SimConfig) -> OverlaySchedule:
    from ..utils.prng import fail_schedule_uniform

    n = cfg.n
    frac = Fraction(cfg.step_rate).limit_denominator(1 << 15)
    if cfg.churn_rate > 0:
        # the churn window must not overlap the start ramp: a churned
        # peer whose fail tick precedes its start would be introduced
        # while failed (a posthumous join — reference-faithful in the
        # dense model, but it would suspend the overlay's victim-purge
        # guarantee).  Require the ramp to finish before churn opens.
        last_start = (n - 1) * frac.numerator // max(frac.denominator, 1)
        churn_lo = cfg.total_ticks // 4
        if last_start >= churn_lo:
            raise ValueError(
                f"start ramp ends at t={last_start} but churn opens at "
                f"t={churn_lo}; lower step_rate (e.g. {churn_lo / (2 * n)}) "
                "or lengthen the run")
    victim_lo, victim_hi = 0, 0
    if cfg.churn_rate <= 0:
        u = fail_schedule_uniform(cfg.seed)
        if cfg.single_failure:
            victim_lo = int(u * n) % n
            victim_hi = victim_lo + 1
        else:
            victim_lo = (int(u * n) % n) // 2
            victim_hi = victim_lo + n // 2
    return OverlaySchedule(
        seed=jnp.uint32(cfg.seed & 0xFFFFFFFF),
        step_num=jnp.int32(frac.numerator),
        step_den=jnp.int32(max(frac.denominator, 1)),
        victim_lo=jnp.int32(victim_lo),
        victim_hi=jnp.int32(victim_hi),
        fail_tick=jnp.int32(cfg.fail_tick),
        rejoin_after=jnp.int32(cfg.rejoin_after
                               if cfg.rejoin_after is not None else NEVER),
        churn_thr=jnp.uint32(threshold32(cfg.churn_rate)
                             if cfg.churn_rate > 0 else 0),
        churn_lo=jnp.int32(cfg.total_ticks // 4),
        churn_span=jnp.int32(max(cfg.total_ticks // 2, 1)),
        churn_after=jnp.int32(cfg.rejoin_after
                              if cfg.rejoin_after is not None else 40),
        drop_on=jnp.asarray(bool(cfg.drop_msg)),
        drop_open=jnp.int32(cfg.drop_open_tick),
        drop_close=jnp.int32(cfg.drop_close_tick),
        drop_thr=jnp.uint32(threshold32(cfg.msg_drop_prob)),
    )


@struct.dataclass
class OverlayMetrics:
    """Per-tick scalar counters (events at 65k+ cannot be dense masks)."""

    in_group: jax.Array       # i32 — nodes currently in the group
    view_slots: jax.Array     # i32 — total occupied view slots
    adds: jax.Array           # i32 — slots that changed to a new subject
    removals: jax.Array       # i32 — staleness removals this tick
    false_removals: jax.Array  # i32 — removals naming a live subject
    #   (expected background churn in a bounded partial view — see
    #   module docstring; the hard guarantee is live coverage)
    victim_slots: jax.Array   # i32 — slots still naming a failed subject
    live_uncovered: jax.Array  # i32 — live members in NO view (-1 when
    #   not tracked: the histogram needs a scatter, so it is computed
    #   only at small N; large-N coverage is checked on the final state)
    sent: jax.Array           # i32 — messages sent (after drop)
    recv: jax.Array           # i32 — messages consumed


#: track the live-coverage histogram on device only below this N
COVERAGE_N_LIMIT = 4096

#: merge pass row-block size (bounds the (B, K, L+1) broadcast
#: intermediates; see merge_candidates)
MERGE_BLOCK = 1 << 16


def resolved_dims(cfg: SimConfig):
    """(K, L, F): view slots, payload window, exchange fanout.

    Auto sizing: K ~ 4*log2 N for connectivity (capped at 64), payload
    window L = K/2, and fanout chosen so the per-slot candidate supply
    F*(L+1)/K is ~3.2 per tick — enough that slot refresh/eviction
    outpaces the TREMOVE horizon even in the hash-popularity tail and
    under a 10% drop window (empirically: supply 3.2 keeps the
    false-removal rate ~1e-5/entry-tick at 65k; supply ~2 reaches
    ~2e-4, still an order under the test bound).
    """
    n = cfg.n
    b = int(math.ceil(math.log2(max(n, 4))))
    k = cfg.overlay_view if cfg.overlay_view > 0 \
        else min(64, max(16, 8 * ((b + 1) // 2)))
    l = min(cfg.overlay_sample, k) if cfg.overlay_sample > 0 \
        else min(k, max(4, k // 2))
    f = cfg.fanout if cfg.fanout > 0 \
        else max(3, -(-16 * k // (5 * (l + 1))))
    return k, l, f


def _xor_factors(n: int):
    """Factor a power-of-two index space for the permutation matmuls.

    A two-way hi/lo split measures fastest on TPU (finer factorizations
    lower the FLOP count — sum(factors) vs 2*sqrt(N) — but the extra
    batched contractions cost more in relayouts than they save)."""
    b = n.bit_length() - 1
    hi = 1 << ((b + 1) // 2)
    return [hi, n // hi] if n > 1 else [1]


def init_overlay_state(cfg: SimConfig) -> OverlayState:
    n = cfg.n
    k, l, f = resolved_dims(cfg)
    return OverlayState(
        tick=jnp.int32(0),
        ids=jnp.full((n, k), -1, jnp.int32),
        hb=jnp.zeros((n, k), jnp.int32),
        ts=jnp.zeros((n, k), jnp.int32),
        in_group=jnp.zeros(n, bool),
        own_hb=jnp.zeros(n, jnp.int32),
        send_flags=jnp.zeros((n, f), bool),
        joinreq=jnp.zeros(n, bool),
        joinrep=jnp.zeros(n, bool),
    )


def exchange_mask(seed, t, fi, n):
    """Nonzero XOR mask of exchange slot ``fi`` at tick ``t`` (traced)."""
    tu = t.astype(jnp.uint32) if hasattr(t, "astype") else np.uint32(t)
    m = mix32(seed, tu, np.uint32(fi), np.uint32(_SALT_MASK))
    return (m % np.uint32(n - 1)).astype(jnp.int32) + 1


def _pack_th(ts, hb):
    """int32 pack of a winner's payload: (ts+1) << 12 | (hb+1).

    Both fields are < 4095 (runs are capped at 4094 ticks and
    heartbeats advance at most once per tick), so among equal
    priority-key candidates the max packed value is the lexicographic
    (ts, hb) maximum."""
    return ((ts + 1) << 12) | (hb + 1)


def _pack_key(seed, t, rows_u, ids, ts):
    """uint32 slot-priority key: freshness band | rotated tie | id+1.

    band (3b, bits 29-31): fresher BAND-quantized age wins outright.
    tie (_TIE_BITS=8b, bits 21-28): mix32(seed, epoch, receiver, id) —
               re-rolled every EPOCH ticks, per receiver, so slot
               winners rotate.
    id+1 (ID_BITS=21b, bits 0-20): deterministic final tiebreak;
               nonzero (0 = empty).
    """
    age = jnp.clip(t - ts, 0, 8 * BAND - 1)
    band = (jnp.uint32(7) - (age // BAND).astype(jnp.uint32)) \
        << (ID_BITS + _TIE_BITS)
    epoch = (t // EPOCH).astype(jnp.uint32)
    # the tie is the hash's top _TIE_BITS placed at bit ID_BITS — mask
    # then one right shift, NOT (h >> 24) << 21: that shift pair
    # miscompiles under Mosaic in the fused kernel's context (observed
    # on v5e: small tie values land as 0), and the masked form is
    # bit-identical algebra
    tie_mask = jnp.uint32(((1 << _TIE_BITS) - 1) << (32 - _TIE_BITS))
    tie = (mix32(seed, epoch, rows_u, ids.astype(jnp.uint32))
           & tie_mask) >> (32 - _TIE_BITS - ID_BITS)
    return band | tie | (ids + 1).astype(jnp.uint32)


class LocalOverlayComm:
    """Single-device execution: all rows local, collectives trivial."""

    n_shards = 1

    def row_start(self, n: int):
        return 0

    def slice_rows(self, v):
        """Replicated [N, ...] -> local row block (identity here)."""
        return v

    def xor_perm_shards(self, x, mask_hi):
        """Cross-shard part of the XOR exchange (no-op on one shard)."""
        return x

    def bcast_row0(self, x_local):
        """Global row 0 of a row-sharded array, visible everywhere."""
        return x_local[0]

    def on_first_shard(self):
        return True

    def psum(self, v):
        return v


def make_overlay_tick(cfg: SimConfig, comm=None,
                      use_pallas: bool | None = None):
    """Build ``tick(state, sched) -> (state', OverlayMetrics)``.

    With the default :class:`LocalOverlayComm` this is a single-device
    program.  With a :class:`~.overlay_sharded.RingOverlayComm` inside
    ``shard_map`` the tables/send_flags are row-sharded and the XOR
    exchange's shard-index bits become a ``ppermute``; all (N,) vectors
    stay replicated.  Both paths are bit-identical
    (tests/test_overlay_sharded.py).

    ``use_pallas`` routes the exchange+merge hot phase through the
    fused Pallas kernel (ops/pallas/overlay_exchange.py — single-device
    path only).  The kernel is bit-identical to the XLA phases
    (tests/test_overlay_pallas.py).  Default is currently OFF: with the
    per-receiver slot hash both paths are VPU-bound on the same
    (K, L+1) slot-match product, and the kernel's narrow per-candidate
    ops measure slower than XLA's broadcast formulation (65k: 20ms vs
    6.7ms/tick) — it becomes the fast path once the merge is
    lane-aligned (epoch-slotted views).
    """
    comm = comm or LocalOverlayComm()
    if use_pallas is None:
        use_pallas = False
    use_kernel = bool(use_pallas) and isinstance(comm, LocalOverlayComm)
    n = cfg.n
    k, l, f = resolved_dims(cfg)
    t_remove = cfg.t_remove
    assert n & (n - 1) == 0, "overlay peer count must be a power of two " \
        "(XOR partner exchange)"
    assert n + 1 < (1 << ID_BITS), \
        f"overlay supports N <= {1 << (ID_BITS - 1)}"
    assert cfg.total_ticks <= 4094, \
        "the packed (ts, hb) winner payload caps runs at 4094 ticks " \
        "(the reference caps at MAX_TIME 3600, EmulNet.h:11)"
    p = comm.n_shards
    nl = n // p
    assert nl * p == n and nl & (nl - 1) == 0, \
        "shard count must divide the peer count (both powers of two)"
    factors = _xor_factors(nl)
    with_coverage = n <= COVERAGE_N_LIMIT

    rows = jnp.arange(n, dtype=jnp.int32)        # global, replicated
    intro_onehot = rows == INTRODUCER
    kk = jnp.arange(k, dtype=jnp.int32)
    iotas = [jnp.arange(s, dtype=jnp.int32) for s in factors]

    _AX = "abcdef"

    def local_xor_perm(x, mask_lo):
        """x[il ^ mask_lo] over the local rows — one permutation matmul
        per index factor (_xor_factors), written as transpose-free
        einsums so each factor is a single MXU contraction.

        Exactness matters: the TPU default truncates matmul inputs to
        bf16, which rounds ids >= 2^16 (65535 -> 65536) and corrupts
        the tables.  HIGHEST is required: HIGH (bf16x3) nominally
        carries 24 mantissa bits but was measured NOT exact at 2^20-1
        ids on this hardware (caught by the final_coverage corruption
        guard at the 1M config)."""
        nf = len(factors)
        y = x.reshape(tuple(factors) + (x.shape[-1],))
        axes = _AX[:nf] + "D"
        rem = mask_lo
        for j in range(nf - 1, -1, -1):
            s = factors[j]
            mj = rem % s
            rem = rem // s
            pj = (iotas[j][:, None] == (iotas[j][None, :] ^ mj)) \
                .astype(jnp.float32)
            out_axes = axes.replace(_AX[j], "x")
            y = jnp.einsum(f"x{_AX[j]},{axes}->{out_axes}", pj, y,
                           precision=jax.lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)
        return y.reshape(x.shape)

    def xor_perm(x, mask):
        """x[i ^ mask] over global rows: local bits via matmuls, shard
        bits via the comm (a ppermute on a mesh)."""
        y = local_xor_perm(x, mask % nl)
        return comm.xor_perm_shards(y, mask // nl)

    def tick(state: OverlayState, sched: OverlaySchedule):
        t = state.tick
        tu = t.astype(jnp.uint32)
        seed = sched.seed
        # replicated (N,) schedule vectors
        start = sched.start_of(rows)
        fail = sched.fail_of(rows)
        rejoin = sched.rejoin_of(rows)
        failed = (t > fail) & (t <= rejoin)
        proc = (t > start) & ~failed
        rejoining = t == rejoin

        # local row block
        row_start = comm.row_start(n)
        rows_g = rows[:nl] + row_start               # global ids of local rows
        rows_u = rows_g.astype(jnp.uint32)
        proc_l = comm.slice_rows(proc)
        keep_l = comm.slice_rows(~rejoining)

        # ---- churn wipe (same semantics as core/tick.py) -----------
        keep = ~rejoining
        ids0 = jnp.where(keep_l[:, None], state.ids, -1)
        hb0 = state.hb * keep_l[:, None]
        ts0 = state.ts * keep_l[:, None]
        in_group0 = state.in_group & keep
        own_hb0 = state.own_hb * keep
        own_hb0_l = comm.slice_rows(own_hb0)

        # ---- payload of the send tick t-1 --------------------------
        # rotating L-slot window (covers the view every K/L ticks) +
        # the sender's self-entry; all from carried state = frozen at
        # the end of tick t-1
        off = (((t - 1) * l) % k + k) % k
        idsw = jnp.roll(ids0, -off, axis=1)[:, :l]
        hbw = jnp.roll(hb0, -off, axis=1)[:, :l]
        tsw = jnp.roll(ts0, -off, axis=1)[:, :l]
        if use_kernel:
            # integer payload for the Pallas kernel: the butterfly
            # moves rows without arithmetic, so no float casts (and no
            # matmul-precision hazard) anywhere.  All F per-round send
            # flags ride along as trailing columns.
            payload = jnp.concatenate([
                idsw, hbw, tsw, own_hb0_l[:, None],
                state.send_flags.astype(jnp.int32),
            ], 1)   # (Nl, 3L+1+F)
        else:
            payload = jnp.concatenate([
                idsw.astype(jnp.float32),
                hbw.astype(jnp.float32),
                tsw.astype(jnp.float32),
                own_hb0_l.astype(jnp.float32)[:, None],
            ], 1)   # (Nl, 3L+1); the per-round in-flight flag is appended below

        # ---- merge phase: one dense (Nl, K, L+1) pass per partner --
        # The winner's (ts, hb) travel as one packed int32
        # ((ts+1) << 12 | hb+1; both < 4095 because runs are capped at
        # 4094 ticks) so recovering them costs a single masked max —
        # among equal-priority-key candidates the lexicographic
        # (ts, hb) max wins, which the oracle mirrors.
        cur_key = jnp.where(ids0 >= 0,
                            _pack_key(seed, t, rows_u[:, None], ids0, ts0),
                            0)
        keymax = cur_key
        p_acc = jnp.where(ids0 >= 0, _pack_th(ts0, hb0), 0)
        recv_cnt = jnp.zeros((), jnp.int32)

        def merge_block(rows_u_b, keymax, p_acc, c_id, c_ts, c_hb,
                        valid):
            slot = (mix32(seed, rows_u_b[:, None],
                          c_id.astype(jnp.uint32)) % k).astype(jnp.int32)
            key = jnp.where(valid,
                            _pack_key(seed, t, rows_u_b[:, None], c_id, c_ts),
                            0)
            p_cand = jnp.where(valid, _pack_th(c_ts, c_hb), 0)
            match = slot[:, None, :] == kk[None, :, None]   # (B, K, L+1)
            kf = (match * key[:, None, :]).max(2)
            sel = match & (key[:, None, :] == kf[:, :, None]) \
                & (kf > 0)[:, :, None]
            pf = jnp.where(sel, p_cand[:, None, :], 0).max(2)
            new_max = jnp.maximum(keymax, kf)
            same = kf == new_max
            was = keymax == new_max
            p_acc = jnp.where(
                same, jnp.maximum(pf, jnp.where(was, p_acc, 0)), p_acc)
            return new_max, p_acc

        # Row-block the (rows, K, L+1) broadcast intermediates: at 1M
        # peers a full-width pass is ~9 GB of transient, so process
        # MERGE_BLOCK rows at a time (lax.map keeps peak memory at one
        # block while still emitting full-width outputs).
        n_blocks = max(1, nl // MERGE_BLOCK)
        blk = nl // n_blocks

        def merge_candidates(carry, c_id, c_ts, c_hb, valid):
            keymax, p_acc = carry
            if n_blocks == 1:
                return merge_block(rows_u, keymax, p_acc,
                                   c_id, c_ts, c_hb, valid)
            shp = lambda x: x.reshape((n_blocks, blk) + x.shape[1:])
            out = jax.lax.map(
                lambda xs: merge_block(*xs),
                (shp(rows_u), shp(keymax), shp(p_acc),
                 shp(c_id), shp(c_ts), shp(c_hb), shp(valid)))
            return tuple(x.reshape((nl,) + x.shape[2:]) for x in out)

        if use_kernel:
            from ..ops.pallas.overlay_exchange import fused_exchange_merge
            masks = jnp.stack([exchange_mask(seed, t - 1, fi, n)
                               for fi in range(f)])
            kmax_k, pacc_k, recv_row = fused_exchange_merge(
                payload, cur_key, p_acc, masks, t, seed,
                k=k, l=l, t_remove=t_remove)
            # the kernel merges every row; discard non-processing
            # receivers' accumulators (bit-equal to gating `valid`)
            keymax = jnp.where(proc_l[:, None], kmax_k, keymax)
            p_acc = jnp.where(proc_l[:, None], pacc_k, p_acc)
            recv_cnt = (recv_row * proc_l.astype(jnp.int32)).sum()
        else:
            for fi in range(f):
                mask = exchange_mask(seed, t - 1, fi, n)
                flag_col = state.send_flags[:, fi].astype(jnp.float32)[:, None]
                q = xor_perm(
                    jnp.concatenate([payload, flag_col], 1), mask)
                partner = rows_g ^ mask
                c_id = jnp.concatenate(
                    [q[:, :l].astype(jnp.int32), partner[:, None]], 1)
                c_hb = jnp.concatenate(
                    [q[:, l:2 * l].astype(jnp.int32),
                     q[:, 3 * l].astype(jnp.int32)[:, None]], 1)
                c_ts = jnp.concatenate(
                    [q[:, 2 * l:3 * l].astype(jnp.int32),
                     jnp.broadcast_to(t - 1, (nl, 1))], 1)
                sent_flag = q[:, 3 * l + 1] > 0.5
                valid = sent_flag[:, None] & proc_l[:, None] & (c_id >= 0) \
                    & (t - c_ts < t_remove) & (c_id != rows_g[:, None])
                recv_cnt += (sent_flag & proc_l).sum().astype(jnp.int32)
                keymax, p_acc = merge_candidates(
                    (keymax, p_acc), c_id, c_ts, c_hb, valid)
        recv_cnt = comm.psum(recv_cnt)

        # ---- JOINREP consumption (introducer's payload broadcast) --
        jrep = state.joinrep & proc
        jrep_l = comm.slice_rows(jrep)
        bc = comm.bcast_row0(payload)                # (3L+1,) introducer row
        j_id = jnp.concatenate([bc[:l].astype(jnp.int32),
                                jnp.array([INTRODUCER], jnp.int32)])
        j_hb = jnp.concatenate([bc[l:2 * l].astype(jnp.int32),
                                bc[3 * l].astype(jnp.int32)[None]])
        j_ts = jnp.concatenate([bc[2 * l:3 * l].astype(jnp.int32),
                                (t - 1)[None]])
        jc_id = jnp.broadcast_to(j_id, (nl, l + 1))
        jc_ts = jnp.broadcast_to(j_ts, (nl, l + 1))
        jc_hb = jnp.broadcast_to(j_hb, (nl, l + 1))
        j_valid = jrep_l[:, None] & (jc_id >= 0) & (t - jc_ts < t_remove) \
            & (jc_id != rows_g[:, None])
        keymax, p_acc = merge_candidates(
            (keymax, p_acc), jc_id, jc_ts, jc_hb, j_valid)
        in_group = in_group0 | jrep

        # ---- JOINREQ at the introducer -----------------------------
        # requester entries (j, hb=1, ts=t) merged into (the shard
        # holding) row 0 as a dense (K, N) masked max (addMember,
        # MP1Node.cpp:265-280)
        jreq = state.joinreq & proc[INTRODUCER]
        rows_gu_all = rows.astype(jnp.uint32)
        q_slot = (mix32(seed, jnp.uint32(INTRODUCER), rows_gu_all) % k) \
            .astype(jnp.int32)
        q_key = jnp.where(jreq & ~intro_onehot,
                          _pack_key(seed, t, jnp.uint32(INTRODUCER), rows,
                                    jnp.broadcast_to(t, (n,))), 0)
        q_match = q_slot[None, :] == kk[:, None]             # (K, N)
        q_kf = (q_match * q_key[None, :]).max(1)             # (K,)
        q_sel = q_match & (q_key[None, :] == q_kf[:, None]) & (q_kf > 0)[:, None]
        q_pf = jnp.where(q_sel.any(1), _pack_th(t, 1), 0)    # all (t, hb=1)
        on0 = comm.on_first_shard()
        row0_new = jnp.where(on0, jnp.maximum(keymax[0], q_kf), keymax[0])
        same0 = on0 & (q_kf == row0_new)
        was0 = keymax[0] == row0_new
        p0_row = jnp.where(same0,
                           jnp.maximum(q_pf, jnp.where(was0, p_acc[0], 0)),
                           p_acc[0])
        keymax = keymax.at[0].set(row0_new)
        p_acc = p_acc.at[0].set(p0_row)
        recv_cnt += jrep.sum().astype(jnp.int32) + jreq.sum().astype(jnp.int32)

        ids1 = jnp.where(keymax > 0,
                         (keymax & ID_MASK).astype(jnp.int32) - 1, -1)
        ts1 = jnp.where(keymax > 0, (p_acc >> 12) - 1, 0)
        hb1 = jnp.where(keymax > 0, (p_acc & 0xFFF) - 1, 0)

        # ---- nodeStart / rejoin (replicated vector math) -----------
        starting = (t == start) | rejoining
        in_group = in_group | (starting & intro_onehot)
        joinreq_new = starting & ~intro_onehot
        active = sched.drop_active(t)
        qdrop = mix32(seed, tu, rows_gu_all, np.uint32(_SALT_JOINREQ_DROP)) \
            < sched.drop_thr
        pdrop = mix32(seed, tu, rows_gu_all, np.uint32(_SALT_JOINREP_DROP)) \
            < sched.drop_thr
        joinreq_sent = joinreq_new & ~(active & qdrop)
        joinrep_sent = jreq & ~(active & pdrop)      # introducer's replies

        # ---- detection (nodeLoopOps analog) ------------------------
        ops = proc & in_group
        own_hb = own_hb0 + ops.astype(jnp.int32)
        ops_l = comm.slice_rows(ops)
        stale = (ids1 >= 0) & (t - ts1 >= t_remove) & ops_l[:, None]
        subj = jnp.clip(ids1, 0)
        subj_fail = sched.fail_of(subj)
        subj_failed = (t > subj_fail) & (t <= sched.rejoin_of(subj))
        removals = comm.psum(stale.sum().astype(jnp.int32))
        false_removals = comm.psum(
            (stale & ~subj_failed).sum().astype(jnp.int32))
        ids2 = jnp.where(stale, -1, ids1)
        hb2 = jnp.where(stale, 0, hb1)
        ts2 = jnp.where(stale, 0, ts1)

        # ---- dissemination: set the in-flight flags ----------------
        fis = jnp.arange(f, dtype=jnp.uint32)
        gdrop = mix32(seed, tu, rows_u[:, None], fis[None, :],
                      np.uint32(_SALT_GOSSIP_DROP)) < sched.drop_thr
        send_flags = ops_l[:, None] & ~(active & gdrop)
        sent = comm.psum(send_flags.sum().astype(jnp.int32)) \
            + joinreq_sent.sum().astype(jnp.int32) \
            + joinrep_sent.sum().astype(jnp.int32)

        live_hold = ~proc & ~failed
        joinreq_next = joinreq_sent | (state.joinreq
                                       & ~proc[INTRODUCER] & ~failed[INTRODUCER])
        joinrep_next = joinrep_sent | (state.joinrep & live_hold)

        live_member = in_group & ~failed & ~intro_onehot
        if with_coverage:
            covered = comm.psum(
                jnp.zeros(n, jnp.int32).at[jnp.clip(ids2, 0).reshape(-1)]
                .max((ids2 >= 0).reshape(-1).astype(jnp.int32))) > 0
            live_uncovered = (live_member & ~covered).sum().astype(jnp.int32)
        else:
            live_uncovered = jnp.int32(-1)

        metrics = OverlayMetrics(
            in_group=in_group.sum().astype(jnp.int32),
            view_slots=comm.psum((ids2 >= 0).sum().astype(jnp.int32)),
            adds=comm.psum(
                ((ids1 != ids0) & (ids1 >= 0)).sum().astype(jnp.int32)),
            removals=removals,
            false_removals=false_removals,
            victim_slots=comm.psum(
                ((ids2 >= 0) & subj_failed & ~stale).sum().astype(jnp.int32)),
            live_uncovered=live_uncovered,
            sent=sent,
            recv=recv_cnt,
        )
        new_state = OverlayState(
            tick=t + 1,
            ids=ids2, hb=hb2, ts=ts2,
            in_group=in_group, own_hb=own_hb,
            send_flags=send_flags,
            joinreq=joinreq_next, joinrep=joinrep_next,
        )
        return new_state, metrics

    return tick


_OVERLAY_RUN_CACHE: dict = {}


def make_overlay_run(cfg: SimConfig, length: int | None = None,
                     use_pallas: bool | None = None):
    """``lax.scan`` over ``length`` ticks (default: the whole run):
    ``run(state, sched) -> (final, metrics[length])``.  The schedule is
    closed-form in the absolute clock carried in the state, so a
    shorter scan resumes mid-run bit-identically."""
    length = cfg.total_ticks if length is None else length
    if use_pallas is None:
        use_pallas = False
    key = (cfg.n, cfg.t_remove, length, resolved_dims(cfg), use_pallas)
    if key in _OVERLAY_RUN_CACHE:
        return _OVERLAY_RUN_CACHE[key]
    tick = make_overlay_tick(cfg, use_pallas=use_pallas)

    @jax.jit
    def run(state: OverlayState, sched: OverlaySchedule):
        def step(carry, _):
            return tick(carry, sched)
        return jax.lax.scan(step, state, None, length=length)

    _OVERLAY_RUN_CACHE[key] = run
    return run


def _overlay_expect(host):
    n, k = np.asarray(host["ids"]).shape
    f = np.asarray(host["send_flags"]).shape[1]
    return {"tick": (), "ids": (n, k), "hb": (n, k), "ts": (n, k),
            "in_group": (n,), "own_hb": (n,), "send_flags": (n, f),
            "joinreq": (n,), "joinrep": (n,)}


def overlay_state_to_host(state: OverlayState) -> dict:
    """Device state -> plain numpy dict (checkpointing)."""
    from ..state import struct_to_host
    return struct_to_host(state)


def overlay_state_from_host(host: dict) -> OverlayState:
    """Inverse of :func:`overlay_state_to_host`, schema-checked."""
    from ..state import struct_from_host
    return struct_from_host(host, OverlayState, _overlay_expect)


def save_overlay_checkpoint(state: OverlayState, path: str) -> None:
    """Write a mid-run checkpoint; the path is used verbatim."""
    from ..state import save_struct_checkpoint
    save_struct_checkpoint(state, path)


def load_overlay_checkpoint(path: str) -> OverlayState:
    from ..state import load_struct_checkpoint
    return load_struct_checkpoint(path, OverlayState, _overlay_expect)


@dataclasses.dataclass
class OverlayResult:
    cfg: SimConfig
    sched: OverlaySchedule
    final_state: OverlayState
    metrics: OverlayMetrics      # numpy arrays, each [T]
    wall_seconds: float

    @property
    def ticks_run(self) -> int:
        """Ticks executed in this (possibly partial) segment."""
        return int(np.asarray(self.metrics.in_group).shape[0])

    @property
    def node_ticks_per_second(self) -> float:
        return self.cfg.n * self.ticks_run / self.wall_seconds

    def final_coverage(self):
        """(live_uncovered_count, victim_entries_left) from the final
        tables, computed on host — the large-N stand-in for the
        per-tick coverage histogram.  Evaluated at the state's own
        clock, so partial segments are judged against the schedule at
        their stopping point."""
        ids = np.asarray(self.final_state.ids)
        n = self.cfg.n
        t_end = int(np.asarray(self.final_state.tick))
        if ids.max() >= n:
            raise AssertionError(
                f"corrupt view table: id {ids.max()} >= N={n}")
        present = np.zeros(n, bool)
        present[ids[ids >= 0]] = True
        i = np.arange(n)
        fail = np.asarray(self.sched.fail_of(jnp.asarray(i)))
        rejoin = np.asarray(self.sched.rejoin_of(jnp.asarray(i)))
        failed = (t_end > fail) & (t_end <= rejoin)
        in_group = np.asarray(self.final_state.in_group)
        live = in_group & ~failed & (i != INTRODUCER)
        flat = ids[ids >= 0]
        victim_left = int(((t_end > fail[flat]) & (t_end <= rejoin[flat])).sum())
        return int((live & ~present).sum()), victim_left


class OverlaySimulation:
    """Orchestrator for cfg.model == "overlay" runs (metrics mode)."""

    def __init__(self, cfg: SimConfig, use_pallas: bool | None = None):
        if cfg.model != "overlay":
            raise ValueError("OverlaySimulation requires cfg.model='overlay'")
        self.cfg = cfg
        self.use_pallas = use_pallas
        make_overlay_run(cfg, use_pallas=use_pallas)   # pre-build/cache

    def run(self, profile_dir=None, resume_from: OverlayState | None = None,
            ticks: int | None = None):
        """Run the configured scenario.

        ``resume_from`` continues a (possibly checkpointed) state —
        the clock and in-flight flags live in the state and the
        schedule is closed-form in the absolute clock, so the
        continuation is bit-identical to an uninterrupted run.
        ``ticks`` stops the segment early (to checkpoint mid-run).
        ``profile_dir`` wraps the run in ``jax.profiler.trace``
        (SURVEY.md §5 tracing hook).
        """
        import time
        if profile_dir is not None:
            with jax.profiler.trace(profile_dir):
                return self.run(resume_from=resume_from, ticks=ticks)
        cfg = self.cfg
        sched = make_overlay_schedule(cfg)
        state = init_overlay_state(cfg) if resume_from is None else resume_from
        first = int(np.asarray(state.tick))
        if first > cfg.total_ticks:
            raise ValueError(
                f"resume_from is at tick {first}, past total_ticks="
                f"{cfg.total_ticks}")
        if ticks is not None and ticks < 0:
            raise ValueError(f"ticks must be >= 0, got {ticks}")
        t_end = cfg.total_ticks if ticks is None \
            else min(cfg.total_ticks, first + ticks)
        run = make_overlay_run(cfg, t_end - first, use_pallas=self.use_pallas)
        t0 = time.perf_counter()
        final, metrics = run(state, sched)
        jax.block_until_ready(final)
        if int(np.asarray(final.tick)) != t_end:
            raise RuntimeError("overlay run did not complete")
        wall = time.perf_counter() - t0
        return OverlayResult(cfg=cfg, sched=sched, final_state=final,
                             metrics=jax.tree.map(np.asarray, metrics),
                             wall_seconds=wall)
