"""Host harness for the grid-scale multi-tick overlay megakernel.

Packs the :class:`~.overlay.OverlayState` pytree into the kernel's
single (N, 2K) plane (ids | payload words with the aux bytes riding
the spare high bytes — ops/pallas/overlay_grid.py), runs ``lax.scan``
over whole-``GRID_TICKS`` launches, and unpacks the result into the
same ``(final_state, OverlayMetrics[T])`` contract as
:func:`~.overlay.make_overlay_run` — a drop-in scheduling optimization
for N above the VMEM megakernel envelope, bit-identical to the XLA
tick (tests/test_overlay_grid.py).

Why it exists: above ``MEGA_N_LIMIT`` the per-tick formulation pays a
fixed ~300-450 us Pallas launch plus an ~0.5-11.7 ms tail of per-tick
XLA vector phases every tick (docs/PERF.md) — the fixed cost the
reference's per-tick hot loop does not have
(/root/reference/Application.cpp:99-163).  Running ``GRID_TICKS``
whole ticks per launch with double-buffered HBM state amortizes the
launch floor and eliminates the XLA tail entirely.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import INTRODUCER, SimConfig
from ..ops.pallas.overlay_grid import (GRID_BLOCK_ROWS, GRID_TICKS,
                                       MET_ADDS, MET_FALSE_REMOVALS,
                                       MET_IN_GROUP, MET_RECV,
                                       MET_REMOVALS, MET_SENT, MET_VICTIM,
                                       MET_VIEW, grid_overlay_ticks,
                                       pack_aux_lanes, unpack_aux_lanes)
from .overlay import (SLOT_EPOCH, OverlayMetrics, OverlaySchedule,
                      OverlayState, _pack_key, _pack_th, _slot_of,
                      exchange_mask, resolved_dims)


def _step_frac(cfg: SimConfig):
    # the one shared definition (models/segments.py): the planner's
    # last_start and the kernel's runtime step_num/step_den ramp MUST
    # come from the same fraction or phase elision goes bit-wrong
    from .segments import step_fraction
    return step_fraction(cfg.step_rate)


def grid_supported(cfg: SimConfig) -> bool:
    """Whether the grid-scale multi-tick kernel covers this config.

    The envelope is structural, not VMEM-bound: only row blocks live
    on-chip, so any power-of-two N >= 8 with a 2K <= 128-lane packed
    plane qualifies.  ``step_num * (N-1) < 2^31`` guards the kernel's
    division-free start-ramp comparisons (module docstring)."""
    from .overlay import ID_BITS
    n = cfg.n
    k, f = resolved_dims(cfg)
    num, _ = _step_frac(cfg)
    return (cfg.model == "overlay" and n & (n - 1) == 0 and n >= 8
            and n <= (1 << ID_BITS)      # id field of the packed key
            and 2 * k <= 128 and k >= 8 and f <= 8
            and cfg.total_ticks <= 4094
            and num * (n - 1) < 2 ** 31
            # the adversarial worlds (worlds.py) are not compiled into
            # the grid kernel — world configs take the XLA tick.  The
            # latency plane is pinned explicitly on top of has_worlds:
            # its message-age state dimension (send_hist) is structural
            # — the packed plane has no lane for it — not merely a
            # routing choice
            and not cfg.has_worlds and not cfg.has_latency)


def _grid_kern_kwargs(cfg: SimConfig, k: int, f: int, b: int) -> dict:
    """The static kernel kwargs a config bakes in — ONE definition
    shared by the single-lane and fleet harnesses, so the two can
    never drift apart (their per-lane bit-parity is a test contract)."""
    return dict(n=cfg.n, k=k, f_rounds=f, b=b, t_remove=cfg.t_remove,
                churn_lo=cfg.total_ticks // 4,
                churn_span=max(cfg.total_ticks // 2, 1),
                can_rejoin=cfg.churn_rate > 0
                or cfg.rejoin_after is not None,
                churn_mode=cfg.churn_rate > 0,
                powerlaw=cfg.topology == "powerlaw")


def _clock_guard(start_tick: int | None, tick, what: str) -> None:
    """Refuse a pinned segment plan at an unverifiable or wrong clock
    (shared by the single-lane and fleet grid runs)."""
    if start_tick is None:
        return
    if isinstance(tick, jax.core.Tracer):
        # a pinned plan applied at an unverifiable clock would elide
        # phases on the wrong absolute ticks — refuse rather than
        # silently compute a bit-wrong trajectory
        raise ValueError(
            f"segmented {what} cannot verify its pinned start tick "
            f"({start_tick}) under a traced state; call it outside "
            "jit, or build with start_tick=None for the clock-agnostic "
            "unsegmented variant")
    if int(tick) != start_tick:
        raise ValueError(
            f"segmented {what} was planned for start tick {start_tick} "
            f"but the state is at tick {int(tick)}; build the run with "
            "the matching start_tick (or None for the unsegmented "
            "variant)")


def pack_grid_plane(cfg: SimConfig, state: OverlayState):
    """OverlayState -> the packed (N, PLANE_W) plane."""
    from ..ops.pallas.overlay_grid import PLANE_W
    n = cfg.n
    k, f = resolved_dims(cfg)
    i32 = jnp.int32
    pw = jnp.where(state.ids >= 0, _pack_th(state.ts, state.hb), 0)
    fis = jnp.arange(f, dtype=i32)[None, :]
    sf_bits = (state.send_flags.astype(i32) << fis).sum(1, keepdims=True)
    pw = pack_aux_lanes(pw, state.own_hb[:, None],
                        state.in_group.astype(i32)[:, None],
                        state.joinreq.astype(i32)[:, None],
                        state.joinrep.astype(i32)[:, None], sf_bits)
    cols = [state.ids, pw]
    if 2 * k < PLANE_W:
        cols.append(jnp.zeros((n, PLANE_W - 2 * k), i32))
    return jnp.concatenate(cols, axis=1)


def unpack_grid_plane(cfg: SimConfig, plane, tick) -> OverlayState:
    k, f = resolved_dims(cfg)
    ids = plane[:, 0:k]
    pw, own_hb, a1, sf = unpack_aux_lanes(plane[:, k:2 * k])
    occ = ids >= 0
    fis = jnp.arange(f, dtype=jnp.int32)[None, :]
    return OverlayState(
        tick=tick.astype(jnp.int32),
        ids=ids,
        hb=jnp.where(occ, (pw & 0xFFF) - 1, 0),
        ts=jnp.where(occ, (pw >> 12) - 1, 0),
        in_group=(a1[:, 0] & 0x10) > 0,
        own_hb=own_hb[:, 0],
        send_flags=((sf >> fis) & 1) > 0,
        # the grid envelope excludes the latency plane (grid_supported)
        send_hist=jnp.zeros((ids.shape[0], f), jnp.int32),
        joinreq=(a1[:, 0] & 0x20) > 0,
        joinrep=(a1[:, 0] & 0x40) > 0,
    )


def _boot_rows(cfg: SimConfig, sched: OverlaySchedule, plane, t0):
    """The (8, 2K) boot block: row 0 the introducer's plane row, row 1
    the start tick's JOINREQ per-slot aggregate (computed once per
    launch in XLA; later ticks' aggregates accumulate in-kernel)."""
    n = cfg.n
    k, _ = resolved_dims(cfg)
    rows = jnp.arange(n, dtype=jnp.int32)
    a1 = (plane[:, k + 1] >> 24) & 0xFF
    joinreq = (a1 & 0x20) > 0
    intro = jnp.int32(INTRODUCER)
    fail0 = sched.fail_of(intro)
    rejoin0 = sched.rejoin_of(intro)
    proc0 = (t0 > 0) & ~((t0 > fail0) & (t0 <= rejoin0))
    jreq = joinreq & proc0
    slot_ep = (t0 // SLOT_EPOCH).astype(jnp.uint32)
    q_slot = _slot_of(sched.seed, slot_ep, rows, k)
    q_key = jnp.where(jreq & (rows != INTRODUCER),
                      _pack_key(rows, jnp.broadcast_to(t0, (n,))),
                      jnp.uint32(0))
    kk = jnp.arange(k, dtype=jnp.int32)
    q_kf = jnp.where(q_slot[None, :] == kk[:, None],
                     q_key[None, :], jnp.uint32(0)).max(1)
    from ..ops.pallas.overlay_grid import PLANE_W
    boot = jnp.zeros((8, PLANE_W), jnp.int32)
    boot = boot.at[0].set(plane[INTRODUCER])
    boot = boot.at[1, 0:k].set(q_kf.astype(jnp.int32))
    return boot


def _sp_vector(sched: OverlaySchedule, t0, s_ticks: int, n: int, f: int):
    i32 = jnp.int32
    intro = jnp.int32(INTRODUCER)
    scalars = jnp.stack([
        t0.astype(i32) if hasattr(t0, "astype") else jnp.int32(t0),
        sched.seed.astype(i32), sched.victim_lo, sched.victim_hi,
        sched.fail_tick, sched.rejoin_after,
        sched.churn_thr.astype(i32), sched.churn_after,
        sched.drop_on.astype(i32), sched.drop_open, sched.drop_close,
        sched.drop_thr.astype(i32),
        sched.fail_of(intro), sched.rejoin_of(intro),
        sched.step_num, sched.step_den,
    ])
    deg = jnp.asarray(sched.deg_thr).astype(i32)[:f - 1]
    ts = t0 + jnp.arange(s_ticks, dtype=i32)
    masks = jnp.stack([exchange_mask(sched.seed, ts - 1, fi, n)
                       for fi in range(f)], axis=1)        # (S, F)
    return jnp.concatenate([scalars, deg, masks.reshape(-1)])


def make_grid_run(cfg: SimConfig, length: int,
                  block_rows: int = GRID_BLOCK_ROWS,
                  start_tick: int | None = None,
                  grid_ticks: int = GRID_TICKS):
    """``run(state, sched) -> (final, OverlayMetrics[length])`` via
    whole-``grid_ticks`` grid-kernel launches (same contract as
    :func:`~.overlay.make_overlay_run`).

    ``start_tick`` pins the run's absolute start tick at trace time
    and unlocks **schedule-segmented** execution (models/segments.py):
    the run splits at the closed-form phase boundaries and each
    segment executes a kernel variant with the dead phases statically
    removed — bit-identical to the all-live kernel, verified by
    tests/test_segments.py.  ``start_tick=None`` (callers that resume
    from arbitrary clocks, e.g. bench.py's coverage walk) compiles the
    single all-live variant, valid at any clock.  When a start tick is
    pinned, the returned run raises if called with a state whose
    (concrete) clock differs — the segment flags would describe the
    wrong absolute ticks.

    On TPU the launches run inside one jitted ``lax.scan`` per
    same-flag segment; on other backends each launch dispatches
    eagerly (inlining interpret-mode kernels into a jitted scan blows
    up the XLA:CPU compile — see overlay_mega.make_mega_run)."""
    from .segments import plan_segments
    assert grid_supported(cfg), "config outside the grid-kernel envelope"
    n = cfg.n
    k, f = resolved_dims(cfg)
    b = min(block_rows, n)
    plan = plan_segments(cfg, length, start_tick, grid_ticks)
    kern_kw = _grid_kern_kwargs(cfg, k, f, b)

    def _metrics(met):
        return OverlayMetrics(
            in_group=met[:, MET_IN_GROUP],
            view_slots=met[:, MET_VIEW],
            adds=met[:, MET_ADDS],
            removals=met[:, MET_REMOVALS],
            false_removals=met[:, MET_FALSE_REMOVALS],
            victim_slots=met[:, MET_VICTIM],
            live_uncovered=jnp.full((length,), -1, jnp.int32),
            sent=met[:, MET_SENT],
            recv=met[:, MET_RECV],
        )

    def launch(plane, t, sched, s_ticks: int, flags):
        init = jnp.concatenate([plane, _boot_rows(cfg, sched, plane, t)],
                               axis=0)
        sp = _sp_vector(sched, t, s_ticks, n, f)
        plane2, met = grid_overlay_ticks(init, sp, s_ticks=s_ticks,
                                         **kern_kw,
                                         **flags.as_kernel_kwargs())
        return plane2[s_ticks % 2], t + s_ticks, met

    def assemble(plane, t, met_parts):
        met = jnp.concatenate(met_parts, axis=0) if met_parts \
            else jnp.zeros((0, 128), jnp.int32)
        return unpack_grid_plane(cfg, plane, t), _metrics(met)

    def _check_clock(state: OverlayState):
        _clock_guard(start_tick, state.tick, "grid run")

    def run_body(state: OverlayState, sched: OverlaySchedule):
        plane = pack_grid_plane(cfg, state)
        t = state.tick
        met_parts = []
        for seg in plan:
            n_chunks, rem = divmod(seg.ticks, grid_ticks)
            if n_chunks:
                def step(carry, _, _flags=seg.flags):
                    plane, t, met = launch(carry[0], carry[1], sched,
                                           grid_ticks, _flags)
                    return (plane, t), met
                (plane, t), met_main = jax.lax.scan(
                    step, (plane, t), None, length=n_chunks)
                met_parts.append(
                    met_main.reshape(n_chunks * grid_ticks, 128))
            if rem:
                plane, t, met_rem = launch(plane, t, sched, rem,
                                           seg.flags)
                met_parts.append(met_rem)
        return assemble(plane, t, met_parts)

    if jax.default_backend() == "tpu":
        # the ANY-space double-buffered plane is XLA-placed: at mid N
        # (e.g. 8192 -> 8 MB) XLA puts it in VMEM, which overflows the
        # default 16 MB scoped window together with the kernel's row
        # blocks; v5e has 128 MB of physical VMEM (at large N XLA
        # falls back to HBM on its own)
        run_tpu = jax.jit(run_body, compiler_options={
            "xla_tpu_scoped_vmem_limit_kib": "98304"})

        def run_checked(state: OverlayState, sched: OverlaySchedule):
            _check_clock(state)
            return run_tpu(state, sched)

        return run_checked

    def run_eager(state: OverlayState, sched: OverlaySchedule):
        _check_clock(state)
        plane = pack_grid_plane(cfg, state)
        t = state.tick
        met_parts = []
        for seg in plan:
            n_chunks, rem = divmod(seg.ticks, grid_ticks)
            for _ in range(n_chunks):
                plane, t, met = launch(plane, t, sched, grid_ticks,
                                       seg.flags)
                met_parts.append(met)
            if rem:
                plane, t, met = launch(plane, t, sched, rem, seg.flags)
                met_parts.append(met)
        return assemble(plane, t, met_parts)

    return run_eager


#: vmap axes for a stacked fleet state: every lane carries its own
#: arrays but the CLOCK is shared (lanes tick in lockstep), so ``tick``
#: stays an unbatched scalar
FLEET_STATE_AXES = OverlayState(
    tick=None, ids=0, hb=0, ts=0, in_group=0, own_hb=0,
    send_flags=0, send_hist=0, joinreq=0, joinrep=0)


def make_grid_fleet_run(cfg: SimConfig, length: int, batch: int,
                        block_rows: int = GRID_BLOCK_ROWS,
                        start_tick: int | None = 0,
                        grid_ticks: int = GRID_TICKS):
    """Fleet-batched grid run: ONE kernel launch steps ``batch``
    independent simulations (distinct seeds, same config shape) via the
    leading batch grid dimension (ops/pallas/overlay_grid.py) — never
    ``jax.vmap``-of-``pallas_call``, which would destroy the kernel's
    manual DMA structure.

    ``run(states, scheds) -> (finals, OverlayMetrics[batch, length])``
    where ``states`` is a stacked :class:`OverlayState` (``tick`` a
    shared scalar, arrays with a leading (B,) axis) and ``scheds`` a
    stacked :class:`OverlaySchedule` (every field batched).  The
    schedule-segment plan is shared by all lanes: plans are derived
    from the config alone, never the seed (models/segments.py), so one
    variant sequence serves the whole fleet.  Bit-identical per lane to
    ``make_grid_run`` of the lane's schedule (tests/test_fleet.py)."""
    from .segments import plan_segments
    assert grid_supported(cfg), "config outside the grid-kernel envelope"
    assert batch >= 1
    n = cfg.n
    k, f = resolved_dims(cfg)
    b = min(block_rows, n)
    plan = plan_segments(cfg, length, start_tick, grid_ticks)
    kern_kw = _grid_kern_kwargs(cfg, k, f, b)

    def _metrics(met):
        return OverlayMetrics(
            in_group=met[:, :, MET_IN_GROUP],
            view_slots=met[:, :, MET_VIEW],
            adds=met[:, :, MET_ADDS],
            removals=met[:, :, MET_REMOVALS],
            false_removals=met[:, :, MET_FALSE_REMOVALS],
            victim_slots=met[:, :, MET_VICTIM],
            live_uncovered=jnp.full((batch, length), -1, jnp.int32),
            sent=met[:, :, MET_SENT],
            recv=met[:, :, MET_RECV],
        )

    def launch(planes, t, scheds, s_ticks: int, flags):
        boots = jax.vmap(
            lambda sc, p: _boot_rows(cfg, sc, p, t))(scheds, planes)
        init = jnp.concatenate([planes, boots], axis=1)
        sp = jax.vmap(
            lambda sc: _sp_vector(sc, t, s_ticks, n, f))(scheds)
        plane2, met = grid_overlay_ticks(init, sp, s_ticks=s_ticks,
                                         batch=batch, **kern_kw,
                                         **flags.as_kernel_kwargs())
        return plane2[:, s_ticks % 2], t + s_ticks, met

    def assemble(planes, t, met_parts):
        met = jnp.concatenate(met_parts, axis=1) if met_parts \
            else jnp.zeros((batch, 0, 128), jnp.int32)
        finals = jax.vmap(lambda p: unpack_grid_plane(cfg, p, t),
                          out_axes=FLEET_STATE_AXES)(planes)
        return finals, _metrics(met)

    def _check_clock(states: OverlayState):
        _clock_guard(start_tick, states.tick, "grid fleet run")

    def _pack(states: OverlayState):
        return jax.vmap(lambda st: pack_grid_plane(cfg, st),
                        in_axes=(FLEET_STATE_AXES,))(states)

    def run_body(states: OverlayState, scheds: OverlaySchedule):
        planes = _pack(states)
        t = states.tick
        met_parts = []
        for seg in plan:
            n_chunks, rem = divmod(seg.ticks, grid_ticks)
            if n_chunks:
                def step(carry, _, _flags=seg.flags):
                    planes, t, met = launch(carry[0], carry[1], scheds,
                                            grid_ticks, _flags)
                    return (planes, t), met
                (planes, t), met_main = jax.lax.scan(
                    step, (planes, t), None, length=n_chunks)
                # (n_chunks, B, grid_ticks, 128) -> (B, ticks, 128)
                met_parts.append(
                    met_main.swapaxes(0, 1)
                    .reshape(batch, n_chunks * grid_ticks, 128))
            if rem:
                planes, t, met_rem = launch(planes, t, scheds, rem,
                                            seg.flags)
                met_parts.append(met_rem)
        return assemble(planes, t, met_parts)

    if jax.default_backend() == "tpu":
        run_tpu = jax.jit(run_body, donate_argnums=(0,),
                          compiler_options={
                              "xla_tpu_scoped_vmem_limit_kib": "98304"})

        def run_checked(states, scheds):
            _check_clock(states)
            return run_tpu(states, scheds)

        return run_checked

    def run_eager(states, scheds):
        # eager per-launch dispatch off-TPU, like make_grid_run's
        # eager path: inlining interpret-mode kernels into a jitted
        # scan blows up the XLA:CPU compile (overlay_mega.make_mega_run)
        _check_clock(states)
        planes = _pack(states)
        t = states.tick
        met_parts = []
        for seg in plan:
            n_chunks, rem = divmod(seg.ticks, grid_ticks)
            for _ in range(n_chunks):
                planes, t, met = launch(planes, t, scheds, grid_ticks,
                                        seg.flags)
                met_parts.append(met)
            if rem:
                planes, t, met = launch(planes, t, scheds, rem,
                                        seg.flags)
                met_parts.append(met)
        return assemble(planes, t, met_parts)

    return run_eager
