"""Scalar oracle: an exact per-node re-implementation of the reference
protocol, used only for differential testing of the vectorized tick.

This mirrors the reference's observable semantics message-by-message —
including the EmulNet buffer's append order, the reverse-scan swap-pop
consumption order (EmulNet.cpp:151-160), the driver's forward recv /
reverse nodeLoop phases (Application.cpp:121-163), and the canonical
handler effects (MP1Node.cpp:219-362) — so the batched TPU tick can be
checked step-for-step against it on identical drop decisions.

It is deliberately *not* TPU code and deliberately slow (O(N^2) Python
per tick); its only job is to be obviously correct.  The reference's
id<10 merge cap (MP1Node.cpp:245) is intentionally NOT reproduced — it
is a scale bug, invisible at N<=10 except for one-tick-later adds of the
last peer, and the framework must scale past it (SURVEY.md §2.2 quirk 2).

Drop decisions are injected as precomputed masks so oracle and TPU runs
share the exact same randomness.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

import numpy as np

from ..config import INTRODUCER, SimConfig
from ..state import NEVER

JOINREQ, JOINREP, GOSSIP = 0, 1, 2


@dataclass
class Entry:
    """MemberListEntry (Member.h:62-81): id is our 0-based peer index."""
    peer: int
    hb: int
    ts: int


@dataclass
class Msg:
    kind: int
    src: int
    dst: int
    payload: list  # copy of sender's member list at send time


@dataclass
class OracleEvents:
    added: list = field(default_factory=list)    # (tick, observer, subject)
    removed: list = field(default_factory=list)  # (tick, observer, subject)


class ReferenceOracle:
    """Step-by-step scalar simulation with reference-identical ordering."""

    def __init__(self, cfg: SimConfig, start_tick, fail_tick,
                 gossip_drop=None, joinreq_drop=None, joinrep_drop=None,
                 rejoin_tick=None, flap_state=None):
        self.cfg = cfg
        n = cfg.n
        self.n = n
        self.start_tick = np.asarray(start_tick)
        self.fail_tick = np.asarray(fail_tick)
        self.rejoin_tick = (np.full(n, NEVER, np.int32)
                            if rejoin_tick is None else np.asarray(rejoin_tick))
        # drop masks indexed [t, ...]; None = no drops
        self.gossip_drop = gossip_drop
        self.joinreq_drop = joinreq_drop
        self.joinrep_drop = joinrep_drop
        # adversarial worlds (worlds.py): zombie rides cfg.zombie; the
        # flap world injects ``flap_state(i, t) -> (failed, rejoining)``
        # (worlds.make_flap_state) — periodic down phases on top of the
        # window schedule, with every up-edge a fresh-nodeStart rejoin
        self.zombie = bool(cfg.zombie)
        self.flap_state = flap_state

        self.t = 0
        self.in_group = np.zeros(n, bool)
        self.own_hb = np.zeros(n, np.int64)
        self.lists: list[list[Entry]] = [[] for _ in range(n)]
        self.queues: list[list[Msg]] = [[] for _ in range(n)]
        self.buffer: list[Msg] = []
        self.sent = np.zeros((n, cfg.total_ticks), np.int32)
        self.recv = np.zeros((n, cfg.total_ticks), np.int32)
        self.events = OracleEvents()

    # --- helpers ----------------------------------------------------
    def window_failed(self, i, t=None) -> bool:
        """The scripted/churn/wave fail-WINDOW component alone — the
        failures the zombie world applies to."""
        t = self.t if t is None else t
        return t > self.fail_tick[i] and t <= self.rejoin_tick[i]

    def failed(self, i) -> bool:
        """Churn extension: failed only inside (fail, rejoin]; flapping
        members add their periodic down phases on top."""
        if self.window_failed(i):
            return True
        return self.flap_state is not None \
            and self.flap_state(i, self.t)[0]

    def flap_rejoining(self, i) -> bool:
        return self.flap_state is not None \
            and self.flap_state(i, self.t)[1]

    def find(self, i, peer):
        for e in self.lists[i]:
            if e.peer == peer:
                return e
        return None

    def send(self, msg: Msg, dropped: bool):
        """ENsend (EmulNet.cpp:87-118): drop or append + account."""
        if len(self.buffer) >= self.cfg.en_buff_size or dropped:
            return
        self.buffer.append(msg)
        self.sent[msg.src, self.t] += 1

    def recv_loop(self, i):
        """ENrecv (EmulNet.cpp:144-177): reverse scan with swap-pop."""
        k = len(self.buffer) - 1
        while k >= 0:
            if k < len(self.buffer) and self.buffer[k].dst == i:
                msg = self.buffer[k]
                self.buffer[k] = self.buffer[-1]
                self.buffer.pop()
                self.queues[i].append(msg)
                self.recv[i, self.t] += 1
            k -= 1

    def add_member(self, i, peer, hb, ts):
        """addMember with dedup + join log (MP1Node.cpp:265-301)."""
        if peer == i or self.find(i, peer) is not None:
            return
        self.lists[i].append(Entry(peer, hb, ts))
        self.events.added.append((self.t, i, peer))

    # --- protocol handlers -----------------------------------------
    def handle(self, i, msg: Msg):
        """recvCallBack (MP1Node.cpp:219-260)."""
        t = self.t
        if msg.kind == JOINREQ:
            self.add_member(i, msg.src, 1, t)
            rep = Msg(JOINREP, i, msg.src, [dataclasses.replace(e) for e in self.lists[i]])
            dropped = bool(self.joinrep_drop[t, msg.src]) if self.joinrep_drop is not None else False
            self.send(rep, dropped)
        elif msg.kind == JOINREP:
            self.add_member(i, msg.src, 1, t)
            self.in_group[i] = True
        elif msg.kind == GOSSIP:
            # zombie world: a message from a window-failed sender
            # carries a FROZEN heartbeat — an old observation, not
            # proof of life — so the direct-sender credit is skipped;
            # its stale payload still merges by the ordinary rules
            if not (self.zombie and self.window_failed(msg.src, t - 1)):
                e = self.find(i, msg.src)
                if e is not None:
                    e.hb += 1
                    e.ts = t
                else:
                    self.add_member(i, msg.src, 1, t)
            for inc in msg.payload:
                node = self.find(i, inc.peer)
                if node is not None:
                    if inc.hb > node.hb:
                        node.hb = inc.hb
                        node.ts = t
                elif inc.peer != i and t - inc.ts < self.cfg.t_remove:
                    self.add_member(i, inc.peer, inc.hb, inc.ts)

    def node_loop_ops(self, i):
        """nodeLoopOps (MP1Node.cpp:335-362)."""
        t = self.t
        self.own_hb[i] += 1
        for k in range(len(self.lists[i]) - 1, -1, -1):
            e = self.lists[i][k]
            if t - e.ts >= self.cfg.t_remove:
                self.events.removed.append((t, i, e.peer))
                del self.lists[i][k]
        for e in list(self.lists[i]):
            g = Msg(GOSSIP, i, e.peer,
                    [dataclasses.replace(x) for x in self.lists[i]])
            dropped = bool(self.gossip_drop[t, i, e.peer]) if self.gossip_drop is not None else False
            self.send(g, dropped)

    # --- driver -----------------------------------------------------
    def step(self):
        """One global tick: mp1Run phases A+B (Application.cpp:121-163)."""
        t = self.t
        n = self.n
        # Churn extension: a rejoined peer comes back to an EMPTY
        # inbox, so traffic addressed to a currently-failed peer that
        # is scheduled to rejoin is dropped (the batched tick drops all
        # traffic to failed receivers).  Messages to permanently-failed
        # peers are left to rot exactly like the reference's buffer
        # (EmulNet.cpp:151) — removing them would perturb the swap-pop
        # consumption order for everyone else without any observable
        # protocol effect.
        if (self.rejoin_tick != NEVER).any() or self.flap_state is not None:
            self.buffer = [
                m for m in self.buffer
                if not ((self.window_failed(m.dst)
                         and self.rejoin_tick[m.dst] != NEVER)
                        or (self.flap_state is not None
                            and self.flap_state(m.dst, self.t)[0]))]
        # phase A: forward order receive
        for i in range(n):
            if t > self.start_tick[i] and not self.failed(i):
                self.recv_loop(i)
        # phase B: reverse order introduce / nodeLoop
        for i in range(n - 1, -1, -1):
            if t == self.start_tick[i] or t == self.rejoin_tick[i] \
                    or self.flap_rejoining(i):
                # nodeStart (MP1Node.cpp:67-154); a churned peer's
                # rejoin — and every flap up-edge — re-initializes
                # like initThisNode first
                if t == self.rejoin_tick[i] or self.flap_rejoining(i):
                    self.lists[i] = []
                    self.queues[i] = []
                    self.in_group[i] = False
                    self.own_hb[i] = 0
                if i == INTRODUCER:
                    self.in_group[i] = True
                else:
                    req = Msg(JOINREQ, i, INTRODUCER, [])
                    dropped = bool(self.joinreq_drop[t, i]) if self.joinreq_drop is not None else False
                    self.send(req, dropped)
            elif t > self.start_tick[i] and not self.failed(i):
                # nodeLoop (MP1Node.cpp:176-193)
                q = self.queues[i]
                self.queues[i] = []
                for msg in q:
                    self.handle(i, msg)
                if self.in_group[i]:
                    self.node_loop_ops(i)
            elif self.zombie and self.window_failed(i) and self.in_group[i]:
                # zombie world: a window-failed in-group peer keeps
                # gossiping its FROZEN table — no inbox drain, no
                # heartbeat increment, no removal scan, just the
                # full-list sends with the list frozen at its fail tick
                for e in list(self.lists[i]):
                    g = Msg(GOSSIP, i, e.peer,
                            [dataclasses.replace(x) for x in self.lists[i]])
                    dropped = bool(self.gossip_drop[t, i, e.peer]) \
                        if self.gossip_drop is not None else False
                    self.send(g, dropped)
        self.t += 1

    def run(self, ticks=None):
        for _ in range(ticks if ticks is not None else self.cfg.total_ticks):
            self.step()
        return self

    # --- inspection -------------------------------------------------
    def known_matrix(self) -> np.ndarray:
        m = np.zeros((self.n, self.n), bool)
        for i, lst in enumerate(self.lists):
            for e in lst:
                m[i, e.peer] = True
        return m

    def table(self, what: str) -> np.ndarray:
        m = np.zeros((self.n, self.n), np.int64)
        for i, lst in enumerate(self.lists):
            for e in lst:
                m[i, e.peer] = getattr(e, what)
        return m
