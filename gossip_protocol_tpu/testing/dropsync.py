"""Shared drop-decision precomputation for differential tests.

Replays the tick function's exact PRNG usage (ops/drop.py
``tick_drop_masks``: one per-tick ``fold_in`` + one (N+2, N) uniform
draw covering gossip rows, JOINREQ, and JOINREP in that order) so the
scalar oracle can consume the very same drop decisions the vectorized
simulation will draw on device.
"""

from __future__ import annotations

import jax
import numpy as np

from ..config import INTRODUCER, SimConfig
from ..state import Schedule


def make_drop_masks(cfg: SimConfig, sched: Schedule):
    """Returns (gossip_drop[T,N,N], joinreq_drop[T,N], joinrep_drop[T,N])
    boolean numpy arrays: True = that send would be dropped.

    Covers the adversarial worlds that ride the drop plane (worlds.py)
    exactly as the tick applies them: the asym world swaps the uniform
    threshold for the per-link matrix inside the same windowed draw,
    and the partition world ORs its deterministic cross-group mask in
    outside the window cond — so an oracle consuming these masks sees
    the byte-identical decisions."""
    n, t_total = cfg.n, cfg.total_ticks
    base = jax.random.PRNGKey(cfg.seed)
    active = np.asarray(sched.drop_active)
    lp = np.asarray(sched.link_prob)
    if lp.size:
        # the tick's concatenated threshold rows: gossip links, then
        # JOINREQ i -> introducer, then JOINREP introducer -> j
        thr = np.concatenate([lp, lp[:, INTRODUCER][None, :],
                              lp[INTRODUCER][None, :]], 0)
    else:
        thr = float(sched.drop_prob)

    g = np.zeros((t_total, n, n), bool)
    q = np.zeros((t_total, n), bool)
    r = np.zeros((t_total, n), bool)
    draw = jax.jit(lambda k: jax.random.uniform(k, (n + 2, n)) < thr)
    for t in range(t_total):
        if not active[t]:
            continue
        drop = np.asarray(draw(jax.random.fold_in(base, t)))
        g[t], q[t], r[t] = drop[:n], drop[n], drop[n + 1]
    if bool(sched.part_on):
        grp = np.asarray(sched.part_group)
        cross = grp[:, None] != grp[None, :]
        po, pc = int(sched.part_open), int(sched.part_close)
        for t in range(t_total):
            if po < t <= pc:
                g[t] |= cross
                q[t] |= cross[:, INTRODUCER]
                r[t] |= cross[INTRODUCER]
    return g, q, r
