"""Shared drop-decision precomputation for differential tests.

Replays the tick function's exact PRNG usage (core/tick.py: per-tick
``fold_in`` + 3-way split, gossip/joinreq/joinrep masks in that order)
so the scalar oracle can consume the very same drop decisions the
vectorized simulation will draw on device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SimConfig
from ..state import Schedule


def make_drop_masks(cfg: SimConfig, sched: Schedule):
    """Returns (gossip_drop[T,N,N], joinreq_drop[T,N], joinrep_drop[T,N])
    boolean numpy arrays: True = that send would be dropped."""
    n, t_total = cfg.n, cfg.total_ticks
    base = jax.random.PRNGKey(cfg.seed)
    active = np.asarray(sched.drop_active)
    p = float(sched.drop_prob)

    g = np.zeros((t_total, n, n), bool)
    q = np.zeros((t_total, n), bool)
    r = np.zeros((t_total, n), bool)
    rows = jnp.arange(n, dtype=jnp.int32)
    row_uniform = jax.jit(jax.vmap(
        lambda k, row: jax.random.uniform(jax.random.fold_in(k, row), (n,)),
        in_axes=(None, 0)))
    for t in range(t_total):
        if not active[t]:
            continue
        kg, kq, kp = jax.random.split(jax.random.fold_in(base, t), 3)
        g[t] = np.asarray(row_uniform(kg, rows) < p)
        q[t] = np.asarray(jax.random.uniform(kq, (n,)) < p)
        r[t] = np.asarray(jax.random.uniform(kp, (n,)) < p)
    return g, q, r
