"""Scalar oracle for the overlay model: a plain-numpy, loop-based
re-implementation of models/overlay.py's tick semantics, used only for
differential testing at small N.

Because the overlay derives *all* of its randomness and schedules from
pure counter hashing (utils/hash32.py) — XOR exchange masks, the
epoch-rotated global slot map, rotated tiebreaks, drop decisions,
churn membership — this oracle replays the exact device behavior with
no replay harness, and the comparison is bit-exact on the full state
trajectory (tests/test_overlay.py).  It is deliberately slow and
explicit; its only job is to be obviously correct.
"""

from __future__ import annotations

import numpy as np

from ..config import INTRODUCER, SimConfig
from ..models.overlay import (ID_BITS, SLOT_EPOCH, _SALT_CHURN,
                              _SALT_CHURN_TICK, _SALT_DEGREE,
                              _SALT_GOSSIP_DROP, _SALT_JOINREP_DROP,
                              _SALT_JOINREQ_DROP, _SALT_MASK, _SALT_SLOT,
                              _pack_th, degree_thresholds, resolved_dims)
from ..state import NEVER
from ..utils.hash32 import mix32, threshold32
from .. import worlds

U = np.uint32


class OverlayOracle:
    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.k, self.f = resolved_dims(cfg)
        n = cfg.n
        self.n = n
        self.seed = U(cfg.seed & 0xFFFFFFFF)
        self.drop_thr = threshold32(cfg.msg_drop_prob)
        self.churn_thr = threshold32(cfg.churn_rate) if cfg.churn_rate > 0 else 0
        self.deg_thr = degree_thresholds(cfg, self.f)

        from fractions import Fraction
        frac = Fraction(cfg.step_rate).limit_denominator(1 << 15)
        self.step_num, self.step_den = frac.numerator, max(frac.denominator, 1)
        self.victim_lo = self.victim_hi = 0
        if cfg.churn_rate <= 0:
            from ..utils.prng import fail_schedule_uniform
            u = fail_schedule_uniform(cfg.seed)
            if cfg.single_failure:
                self.victim_lo = int(u * n) % n
                self.victim_hi = self.victim_lo + 1
            else:
                self.victim_lo = (int(u * n) % n) // 2
                self.victim_hi = self.victim_lo + n // 2
        self.rejoin_after = (cfg.rejoin_after if cfg.rejoin_after is not None
                             else NEVER)
        self.churn_lo = cfg.total_ticks // 4
        self.churn_span = max(cfg.total_ticks // 2, 1)
        self.churn_after = (cfg.rejoin_after if cfg.rejoin_after is not None
                            else 40)

        # --- adversarial failure worlds (worlds.py) -----------------
        self.part_groups = worlds.partition_groups_host(cfg)
        self.part_on = cfg.partition_groups >= 2
        self.part_open, self.part_close = worlds.partition_window(cfg)
        self.asym = bool(cfg.asym_drop)
        self.wave_fail = (worlds.wave_fail_ticks(cfg)
                          if cfg.wave_size > 0 else None)
        self.zombie = bool(cfg.zombie)
        self.flap = cfg.flap_rate > 0
        self.flap_mask = worlds.flap_mask_host(cfg)
        self.flap_anchor = worlds.flap_anchor_host(cfg)
        self.flap_per = max(cfg.flap_period, 1)
        self.flap_down = cfg.flap_down
        _, self.flap_hi = worlds.flap_window(cfg)

        self.t = 0
        self.ids = np.full((n, self.k), -1, np.int32)
        self.hb = np.zeros((n, self.k), np.int32)
        self.ts = np.zeros((n, self.k), np.int32)
        self.in_group = np.zeros(n, bool)
        self.own_hb = np.zeros(n, np.int32)
        self.send_flags = np.zeros((n, self.f), bool)
        self.joinreq = np.zeros(n, bool)
        self.joinrep = np.zeros(n, bool)

    # --- closed-form schedule ---------------------------------------
    def start_of(self, i):
        return i * self.step_num // self.step_den

    def fail_of(self, i):
        if self.churn_thr > 0:
            if i == INTRODUCER or not (
                    int(mix32(self.seed, U(i), U(_SALT_CHURN))) < self.churn_thr):
                return NEVER
            return self.churn_lo + int(
                mix32(self.seed, U(i), U(_SALT_CHURN_TICK))) % self.churn_span
        if self.wave_fail is not None:
            # correlated failure wave: seeded epicenter + radius ramp
            # replaces the scripted draw (worlds.py)
            return int(self.wave_fail[i])
        return (self.cfg.fail_tick
                if self.victim_lo <= i < self.victim_hi else NEVER)

    def rejoin_of(self, i):
        fail = self.fail_of(i)
        after = self.churn_after if self.churn_thr > 0 else self.rejoin_after
        return fail + after if (fail != NEVER and after != NEVER) else NEVER

    def flap_state(self, i, t):
        """(failed, rejoining) under the flap world (worlds.py
        flap_state_host semantics, from the precomputed arrays)."""
        if not self.flap or not bool(self.flap_mask[i]):
            return False, False
        anchor = int(self.flap_anchor[i])
        pos = t - anchor
        if pos < 1:
            return False, False
        c = pos // self.flap_per
        off = pos - c * self.flap_per
        if anchor + c * self.flap_per + self.flap_down > self.flap_hi:
            return False, False
        return (1 <= off <= self.flap_down), off == self.flap_down

    def window_failed(self, i, t):
        """The scripted/churn/wave fail-window component alone — the
        failures the zombie world applies to."""
        return self.fail_of(i) < t <= self.rejoin_of(i)

    def failed(self, i, t):
        return self.window_failed(i, t) or self.flap_state(i, t)[0]

    def rejoining(self, i, t):
        return self.rejoin_of(i) == t or self.flap_state(i, t)[1]

    def drop_active(self, t):
        return (self.cfg.drop_msg
                and self.cfg.drop_open_tick < t <= self.cfg.drop_close_tick)

    def part_active(self, t):
        return self.part_on and self.part_open < t <= self.part_close

    def cross_group(self, i, j):
        return self.part_on and \
            int(self.part_groups[i]) != int(self.part_groups[j])

    def link_thr(self, i, j):
        """Per-link drop threshold of link i -> j (asym world): mean
        ``drop_thr``, uniform in [0, 2*thr) — the i*N+j hash input
        wraps in uint32 exactly like the device path."""
        two = (U(self.drop_thr) * U(2)) & U(0xFFFFFFFF)
        h = int(mix32(self.seed, U(i) * U(self.n) + U(j), U(worlds.SALT_LINK)))
        return h % max(int(two), 1)

    # --- protocol pieces --------------------------------------------
    def slot(self, epoch, j):
        """Global slot of subject ``j`` during slot epoch ``epoch``."""
        return int(mix32(self.seed, U(epoch), U(np.uint32(j)),
                         U(_SALT_SLOT)) % self.k)

    def key(self, t, r, j, ts):
        """Freshness-majorized slot key (models/overlay.py _pack_key):
        (ts+1) << ID_BITS | id — receiver-independent; ``t``/``r``
        kept in the signature for call-site symmetry."""
        return ((ts + 1) << ID_BITS) | j

    def key_direct(self, t, j, ts):
        """A direct self-entry / JOINREQ carries the same key; its
        merge-time-maximal ts is the structural boost."""
        return self.key(t, 0, j, ts)

    def mask(self, t, fi):
        return int(mix32(self.seed, U(np.uint32(t & 0xFFFFFFFF)), U(fi),
                         U(_SALT_MASK)) % U(self.n - 1)) + 1

    # --- one tick ---------------------------------------------------
    def step(self):
        t = self.t
        n, k, f = self.n, self.k, self.f
        T = self.cfg.t_remove
        epoch = t // SLOT_EPOCH          # layout of all tables this tick
        proc = np.array([t > self.start_of(i) and not self.failed(i, t)
                         for i in range(n)])
        rejoining = np.array([self.rejoining(i, t) for i in range(n)])

        # churn wipe
        for i in np.flatnonzero(rejoining):
            self.ids[i] = -1
            self.hb[i] = 0
            self.ts[i] = 0
            self.in_group[i] = False
            self.own_hb[i] = 0

        # candidates per receiver: (slot, subject, hb, ts) — incoming
        # tables are slotted by the same global map, so a table entry's
        # slot is its own position; the partner self-entry hashes in
        cands = [[] for _ in range(n)]
        recv = 0
        for fi in range(f):
            m = self.mask(t - 1, fi)
            for r in range(n):
                p = r ^ m
                if not (self.send_flags[p, fi] and proc[r]):
                    continue
                recv += 1
                for q in range(k):
                    if self.ids[p, q] >= 0:
                        cands[r].append((q, int(self.ids[p, q]),
                                         int(self.hb[p, q]),
                                         int(self.ts[p, q]), False))
                if self.zombie and self.window_failed(p, t - 1):
                    # zombie world: a window-failed sender's message
                    # carries a FROZEN heartbeat — no direct self-entry
                    # credit; its stale table rows merged above
                    continue
                cands[r].append((self.slot(epoch, p), p,
                                 int(self.own_hb[p]), t - 1, True))

        # JOINREP consumption
        jrep = self.joinrep & proc
        for r in np.flatnonzero(jrep):
            for q in range(k):
                if self.ids[INTRODUCER, q] >= 0:
                    cands[r].append((q, int(self.ids[INTRODUCER, q]),
                                     int(self.hb[INTRODUCER, q]),
                                     int(self.ts[INTRODUCER, q]), False))
            if not (self.zombie and self.window_failed(INTRODUCER, t - 1)):
                cands[r].append((self.slot(epoch, INTRODUCER), INTRODUCER,
                                 int(self.own_hb[INTRODUCER]), t - 1, True))
            recv += 1
        in_group = self.in_group | jrep

        # JOINREQ at the introducer
        jreq = self.joinreq & proc[INTRODUCER]
        recv += int(jreq.sum())
        for j in np.flatnonzero(jreq):
            if j != INTRODUCER:
                cands[INTRODUCER].append((self.slot(epoch, int(j)),
                                          int(j), 1, t, True))

        # merge: per-slot max of the packed priority key; among equal
        # keys the winner payload is the max packed _pack_th(ts, hb)
        # — the lexicographic (ts, hb) maximum, as on device
        def pack_th(ts, hb):
            return int(_pack_th(ts, hb))

        new_ids = self.ids.copy()
        new_hb = self.hb.copy()
        new_ts = self.ts.copy()
        for r in range(n):
            best = {}
            for (sl, j, hb, ts, direct) in cands[r]:
                if not (t - ts < T) or j == r or j < 0:
                    continue
                kkey = (self.key_direct(t, j, ts) if direct
                        else self.key(t, r, j, ts))
                p = pack_th(ts, hb)
                cur = best.get(sl)
                if cur is None or kkey > cur[0]:
                    best[sl] = [kkey, p]
                elif kkey == cur[0]:
                    cur[1] = max(cur[1], p)
            for sl, (kkey, p) in best.items():
                if self.ids[r, sl] >= 0:
                    ckey = self.key(t, r, int(self.ids[r, sl]),
                                    int(self.ts[r, sl]))
                    if ckey > kkey:
                        continue
                    if ckey == kkey:
                        p = max(p, pack_th(int(self.ts[r, sl]),
                                           int(self.hb[r, sl])))
                new_ids[r, sl] = kkey & ((1 << ID_BITS) - 1)
                new_ts[r, sl] = (p >> 12) - 1
                new_hb[r, sl] = (p & 0xFFF) - 1

        # nodeStart / rejoin
        starting = np.array([self.start_of(i) == t for i in range(n)]) | rejoining
        in_group = in_group | (starting & (np.arange(n) == INTRODUCER))
        active = self.drop_active(t)
        part = self.part_active(t)
        joinreq_sent = np.zeros(n, bool)
        for i in np.flatnonzero(starting):
            if i != INTRODUCER:
                thr = self.link_thr(i, INTRODUCER) if self.asym \
                    else self.drop_thr
                drop = active and int(mix32(self.seed, U(t), U(i),
                                            U(_SALT_JOINREQ_DROP))) < thr
                if part and self.cross_group(i, INTRODUCER):
                    drop = True
                joinreq_sent[i] = not drop
        joinrep_sent = np.zeros(n, bool)
        for j in np.flatnonzero(jreq):
            thr = self.link_thr(INTRODUCER, j) if self.asym \
                else self.drop_thr
            drop = active and int(mix32(self.seed, U(t), U(j),
                                        U(_SALT_JOINREP_DROP))) < thr
            if part and self.cross_group(INTRODUCER, j):
                drop = True
            joinrep_sent[j] = not drop

        # detection
        ops = proc & in_group
        self.own_hb = self.own_hb + ops.astype(np.int32)
        removals = 0
        for r in np.flatnonzero(ops):
            for sl in range(k):
                if new_ids[r, sl] >= 0 and t - new_ts[r, sl] >= T:
                    removals += 1
                    new_ids[r, sl] = -1
                    new_hb[r, sl] = 0
                    new_ts[r, sl] = 0

        # slot-map re-roll at the SLOT_EPOCH boundary (every row —
        # layout is global, not protocol activity); contention resolved
        # by the same lexicographic (key, payload) rule
        if (t + 1) // SLOT_EPOCH != epoch:
            nxt = (t + 1) // SLOT_EPOCH
            rm_ids = np.full_like(new_ids, -1)
            rm_hb = np.zeros_like(new_hb)
            rm_ts = np.zeros_like(new_ts)
            for r in range(n):
                best = {}
                for q in range(k):
                    j = int(new_ids[r, q])
                    if j < 0:
                        continue
                    sl = self.slot(nxt, j)
                    kkey = self.key(t, r, j, int(new_ts[r, q]))
                    p = pack_th(int(new_ts[r, q]), int(new_hb[r, q]))
                    cur = best.get(sl)
                    if cur is None or kkey > cur[0]:
                        best[sl] = [kkey, p]
                    elif kkey == cur[0]:
                        cur[1] = max(cur[1], p)
                for sl, (kkey, p) in best.items():
                    rm_ids[r, sl] = kkey & ((1 << ID_BITS) - 1)
                    rm_ts[r, sl] = (p >> 12) - 1
                    rm_hb[r, sl] = (p & 0xFFF) - 1
            new_ids, new_hb, new_ts = rm_ids, rm_hb, rm_ts

        # dissemination: in-flight flags for the next tick.  Zombie
        # world: window-failed in-group peers keep gossiping their
        # frozen tables (self.in_group is still the pre-update vector
        # here — a window-failed peer cannot have joined this tick)
        new_flags = np.zeros((n, f), bool)
        sent = int(joinreq_sent.sum()) + int(joinrep_sent.sum())
        send_rows = set(np.flatnonzero(ops))
        if self.zombie:
            send_rows |= {i for i in range(n)
                          if self.window_failed(i, t) and self.in_group[i]}
        for r in sorted(send_rows):
            deg = f
            if self.cfg.topology == "powerlaw":
                du = int(mix32(self.seed, U(r), U(_SALT_DEGREE)))
                deg = 1 + sum(1 for thr in self.deg_thr if du < int(thr))
            for fi in range(deg):
                partner = r ^ self.mask(t, fi)
                thr = self.link_thr(r, partner) if self.asym \
                    else self.drop_thr
                gdrop = active and int(mix32(self.seed, U(t), U(r), U(fi),
                                             U(_SALT_GOSSIP_DROP))) < thr
                if part and self.cross_group(r, partner):
                    gdrop = True
                if not gdrop:
                    new_flags[r, fi] = True
                    sent += 1

        live_hold = ~proc & ~np.array([self.failed(i, t) for i in range(n)])
        self.joinreq = joinreq_sent | (self.joinreq & (not proc[INTRODUCER])
                                       & (not self.failed(INTRODUCER, t)))
        self.joinrep = joinrep_sent | (self.joinrep & live_hold)

        self.ids, self.hb, self.ts = new_ids, new_hb, new_ts
        self.in_group = in_group
        self.send_flags = new_flags
        self.t += 1
        return dict(sent=sent, recv=recv, removals=removals)
