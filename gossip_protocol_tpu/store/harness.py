"""Kill-and-restart acceptance harness (the PR 12 gate).

Proves the durability subsystem end to end: a mixed replay stream
served WITH a run directory is killed mid-run (``os._exit`` — no
atexit, no flush, the honest crash model), then a FRESH process
recovers the run directory and finishes the stream.  The gate:

* every request reaches a terminal state exactly once across the two
  processes (pre-kill completions come from the journal's outcome
  records, post-recovery completions from live handles);
* ``restarted_lanes == 0`` — no checkpointed work was ever re-run
  from tick 0, even across the death;
* the per-request result content digests
  (service/replay.result_digest) are identical to an uninterrupted
  baseline run — bit-parity by the replay harness's own standard.

Two kill topologies share all the gating logic: ``child=True`` runs
the doomed serve in a subprocess (``python -m
gossip_protocol_tpu.store.harness serve ...``) so recovery is
genuinely cross-process — the acceptance/bench configuration; the
in-process variant abandons the doomed service object instead (fast,
used by the kill-at-every-cut tests, tests/test_durability.py).
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import tempfile

#: the doomed child's exit code — distinguishable from a crash (1),
#: a usage error (2), and a clean finish (0, which the gate REJECTS:
#: the kill must land mid-run)
KILL_EXIT = 47

_REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _templates(n_overlay: int, t_overlay: int):
    from ..service.replay import grader_templates, overlay_templates
    return grader_templates() + overlay_templates(n=n_overlay,
                                                  ticks=t_overlay)


def _warm_service(svc, trace) -> None:
    done = set()
    for tpl, _ in trace:
        if tpl.name not in done:
            done.add(tpl.name)
            svc.warm(tpl.cfg, tpl.mode)


def _drive(svc, kill_after=None, on_kill=None) -> bool:
    """Drive a service to completion one bucket-flush at a time,
    checking the kill threshold between flushes; returns False when
    the kill fired (True: ran to completion below the threshold)."""
    def _tripped() -> bool:
        if kill_after is not None and svc._dispatch_count >= kill_after:
            if on_kill is not None:
                on_kill()
            return True
        return False

    if _tripped():
        return False
    while True:
        progressed = False
        for key in list(svc._queues):
            if not svc._queues.get(key):
                continue
            svc.flush(key)
            progressed = True
            if _tripped():
                return False
        if svc.in_flight:
            svc.resolve_inflight()
            progressed = True
            if _tripped():
                return False
        if not progressed:
            return True


def _serve(run_dir: str, seeds_per_template: int, n_overlay: int,
           t_overlay: int, max_batch: int, checkpoint_every: int,
           kill_after, on_kill=None) -> bool:
    """The doomed serve: submit the standard mixed stream against a
    run directory and drive it until done or killed."""
    from ..service.replay import build_trace
    from ..service.scheduler import FleetService
    trace = build_trace(_templates(n_overlay, t_overlay),
                        seeds_per_template)
    svc = FleetService(max_batch=max_batch,
                       checkpoint_every=checkpoint_every,
                       run_dir=run_dir)
    _warm_service(svc, trace)
    # The crash window opens only once every submit is ACKNOWLEDGED
    # (journaled): full buckets auto-flush during this loop, so the
    # dispatch count can pass kill_after mid-submission, but dying
    # here would lose un-journaled requests — those are a
    # client-resubmit story, not a durability gate.  _drive's entry
    # check fires at the first flush boundary at/after kill_after.
    for tpl, seed in trace:
        svc.submit(tpl.cfg, seed=seed, mode=tpl.mode)
    return _drive(svc, kill_after=kill_after, on_kill=on_kill)


def run_killed_serve(run_dir: str, seeds_per_template: int,
                     n_overlay: int, t_overlay: int, max_batch: int,
                     checkpoint_every: int, kill_after: int,
                     timeout_s: float = 1800.0):
    """Run the doomed serve in a SUBPROCESS (the genuine crash model);
    returns the CompletedProcess.  The child forces the CPU backend
    and the 8-virtual-device topology exactly like the smoke
    harness."""
    env = os.environ.copy()
    env.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "gossip_protocol_tpu.store.harness",
           "serve", run_dir, str(seeds_per_template), str(n_overlay),
           str(t_overlay), str(max_batch), str(checkpoint_every),
           str(kill_after)]
    return subprocess.run(cmd, env=env, capture_output=True,
                          text=True, timeout=timeout_s, cwd=_REPO)


def _digest_of(per_rid: dict) -> str:
    """One run-level digest over the per-rid content digests."""
    h = hashlib.sha256()
    for rid in sorted(per_rid):
        h.update(f"{rid}:{per_rid[rid]};".encode())
    return h.hexdigest()[:16]


def kill_restart_replay(seeds_per_template: int = 34,
                        n_overlay: int = 512, t_overlay: int = 96,
                        max_batch: int = 8, checkpoint_every: int = 48,
                        kill_frac: float = 0.5, run_dir=None,
                        baseline=None, child: bool = True):
    """One kill-and-restart pass over the standard mixed stream;
    returns ``(metrics, baseline)`` — pass ``baseline`` back in to
    amortize the uninterrupted reference run across a sweep.

    Raises on ANY gate violation: a child that finished instead of
    dying, an incomplete or double-counted request set, a non-zero
    ``restarted_lanes``, or a single digest mismatch.
    """
    from ..service.replay import (build_trace, result_digest,
                                  run_service, warm)
    from ..service.scheduler import FleetService
    from .journal import read_journal

    trace = build_trace(_templates(n_overlay, t_overlay),
                        seeds_per_template)
    if baseline is None:
        # the uninterrupted reference: same stream, same batching,
        # same checkpoint cadence, NO store — rids are submission
        # order in both runs, so digests compare rid-for-rid
        svc0 = FleetService(max_batch=max_batch,
                            checkpoint_every=checkpoint_every)
        warm(trace, svc0)
        results, svc0, wall = run_service(trace, service=svc0)
        baseline = {
            "digests": {i: result_digest(r)
                        for i, r in enumerate(results)},
            "dispatches": svc0._dispatch_count,
            "wall_s": wall,
        }
    kill_after = max(1, int(baseline["dispatches"] * kill_frac))
    if run_dir is None:
        run_dir = tempfile.mkdtemp(prefix="gossip-run-")

    if child:
        cp = run_killed_serve(run_dir, seeds_per_template, n_overlay,
                              t_overlay, max_batch, checkpoint_every,
                              kill_after)
        if cp.returncode != KILL_EXIT:
            raise RuntimeError(
                f"doomed child exited {cp.returncode}, expected "
                f"{KILL_EXIT} (killed mid-run); stderr tail:\n"
                + "\n".join(cp.stderr.splitlines()[-15:]))
    else:
        finished = _serve(run_dir, seeds_per_template, n_overlay,
                          t_overlay, max_batch, checkpoint_every,
                          kill_after)
        if finished:
            raise RuntimeError(
                f"in-process serve finished below kill_after="
                f"{kill_after}; pick a smaller kill_frac")

    # pre-kill terminal outcomes come from the dead process's journal
    pre = {}
    for rec in read_journal(run_dir):
        if rec.get("rec") == "outcome":
            if rec["status"] == "failed":
                raise RuntimeError(
                    f"rid {rec['rid']} FAILED before the kill "
                    f"({rec.get('error')}) — the gate stream has no "
                    f"failure plane; this is a bug")
            pre[rec["rid"]] = rec.get("digest")

    svc, handles = FleetService.recover(run_dir)
    if not _drive(svc):
        raise RuntimeError("recovered service stalled")
    post = {rid: result_digest(h.result())
            for rid, h in handles.items()}

    overlap = set(pre) & set(post)
    if overlap:
        raise RuntimeError(
            f"{len(overlap)} requests terminal in BOTH processes "
            f"(e.g. rid {sorted(overlap)[0]}) — double service")
    got = {**pre, **post}
    want = set(range(len(trace)))
    if set(got) != want:
        missing = sorted(want - set(got))[:5]
        extra = sorted(set(got) - want)[:5]
        raise RuntimeError(
            f"completion gate: {len(got)}/{len(trace)} terminal "
            f"(missing {missing}, extra {extra})")
    restarted = svc.stats()["elastic"]["restarted_lanes"]
    if restarted != 0:
        raise RuntimeError(
            f"restarted_lanes == {restarted} across the death "
            f"(gate requires 0)")
    bad = [rid for rid in sorted(got)
           if got[rid] != baseline["digests"][rid]]
    if bad:
        raise RuntimeError(
            f"{len(bad)} digest mismatches vs the uninterrupted "
            f"baseline (first: rid {bad[0]})")

    stats = svc.stats()
    metrics = {
        "requests": len(trace),
        "completed": len(got),
        "completion_rate": len(got) / len(trace),
        "completed_before_kill": len(pre),
        "recovered_requests": len(post),
        "restarted_lanes": restarted,
        "digest_match": True,
        "outcome_digest": _digest_of(got),
        "baseline_digest": _digest_of(baseline["digests"]),
        "kill_after_dispatches": kill_after,
        "baseline_dispatches": baseline["dispatches"],
        "checkpoint_every": checkpoint_every,
        "max_batch": max_batch,
        "cross_process": bool(child),
        "durability": stats["durability"],
        "run_dir": run_dir,
    }
    return metrics, baseline


def main(argv) -> int:
    """``python -m gossip_protocol_tpu.store.harness serve <run_dir>
    <seeds> <n> <t> <max_batch> <checkpoint_every> <kill_after>`` —
    the doomed child of :func:`run_killed_serve`."""
    if len(argv) != 8 or argv[0] != "serve":
        print(main.__doc__, file=sys.stderr)
        return 2
    run_dir = argv[1]
    seeds, n, t, mb, ce, kill_after = (int(a) for a in argv[2:8])
    finished = _serve(run_dir, seeds, n, t, mb, ce, kill_after,
                      on_kill=lambda: os._exit(KILL_EXIT))
    return 0 if finished else 1  # 1: unreachable (on_kill exits)


if __name__ == "__main__":
    # the env guard mirrors scripts/: the doomed child must see the
    # CPU backend + 8 virtual devices BEFORE jax is imported (the
    # parent sets these; this is the belt to its suspenders)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()
    raise SystemExit(main(sys.argv[1:]))
