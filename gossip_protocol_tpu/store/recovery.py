"""Crash-restart recovery: rebuild a fleet service from its journal.

``recover_service(run_dir)`` (surfaced as
``FleetService.recover(run_dir)``) replays the write-ahead journal of
a dead process on a fresh one:

1. **Service parameters** come from the journal's first ``meta``
   record (batching, pad policy, checkpoint cadence) — overridable by
   keyword, and wall-clock policies (deadlines, ``max_wait_s``) are
   never persisted, so the recovered service starts with none.
2. **Every non-terminal request is re-admitted** under its ORIGINAL
   rid (a submit record with no outcome record), queued but not
   pumped — the caller decides when dispatching resumes (``drain``,
   ``flush``, or per-handle ``result()``).
3. **Each re-admitted request resumes from its newest loadable
   spilled cut**: cut records are scanned newest-first and the first
   digest that fetches AND validates becomes the request's
   ``resume`` proxy (its bucket is the matching resume sub-bucket).
   A request whose every recorded cut is missing or corrupt falls
   back to tick 0 and — because checkpointed work was genuinely
   lost — counts ``restarted_lanes``; a request that never reached a
   cut re-admits from tick 0 without counting (no checkpoint ever
   existed).  The kill-and-restart gate therefore asserts
   ``restarted_lanes == 0`` end to end.
4. **The program cache is re-warmed** per distinct (bucket, mode)
   before the caller's first flush, so recovery pays compilation
   up front exactly like a fresh service's ``warm()``.

Requests that completed BEFORE the death are NOT re-run: their
outcome records carry result content digests
(service/replay.result_digest), which is how the acceptance harness
(store/harness.py) proves whole-run bit-parity across the kill.
"""

from __future__ import annotations

from ..config import SimConfig
from .journal import read_journal
from .spill import CheckpointValidationError

#: meta-record service parameters recovery forwards to the fresh
#: FleetService (everything else is either wall-clock policy or
#: caller-supplied)
_META_PARAMS = ("max_batch", "pad_policy", "pipeline",
                "pipeline_depth",
                "checkpoint_every", "checkpoint_every_s")


def recover_service(run_dir: str, mesh=None, store=None, warm=True,
                    **service_kw):
    """Rebuild a service (and its pending work) from ``run_dir``.

    Returns ``(service, handles)`` where ``handles`` maps each
    re-admitted rid to a live :class:`~..service.types.RequestHandle`.
    Nothing is dispatched yet — drive the service (``drain()`` /
    ``result()``) to resume the run.
    """
    from ..service.scheduler import FleetService
    from . import RunStore

    records = read_journal(run_dir)
    meta = next((r for r in records if r.get("rec") == "meta"), None)
    if meta is None:
        raise ValueError(
            f"journal under {run_dir} has no meta record — not a "
            f"fleet-service run directory")
    params = {k: v for k, v in meta.get("service", {}).items()
              if k in _META_PARAMS}
    params.update(service_kw)
    if store is None:
        store = RunStore(run_dir)
    svc = FleetService(mesh=mesh, store=store, **params)

    submits = {}
    terminal = set()
    cuts = {}
    for r in records:
        kind = r.get("rec")
        if kind == "submit":
            submits[r["rid"]] = r
        elif kind == "outcome":
            terminal.add(r["rid"])
        elif kind == "cut":
            cuts.setdefault(r["rid"], []).append(r)

    handles = {}
    resumed = 0
    for rid in sorted(submits):
        if rid in terminal:
            continue
        sub = submits[rid]
        cfg = SimConfig.from_dict(sub["cfg"])
        resume = None
        for cut in reversed(cuts.get(rid, ())):
            try:
                ck = store.checkpoints.fetch(cut["digest"])
            except (CheckpointValidationError, FileNotFoundError):
                continue  # fall back to the next-older cut
            if ck.cfg != cfg or int(ck.tick) != int(cut["tick"]):
                # the address resolves to a DIFFERENT lane's snapshot
                # (journal/spill drift) — as unusable as a corrupt one
                continue
            resume = store.checkpoints.ref(ck)
            break
        if resume is None and cuts.get(rid):
            # checkpointed work existed and none of it was loadable:
            # this lane genuinely restarts from tick 0
            svc._elastic["restarted_lanes"] += 1
        handles[rid] = svc._readmit(
            rid, cfg, sub["mode"], priority=sub.get("priority",
                                                    "default"),
            tenant=sub.get("tenant"), resume=resume)
        resumed += resume is not None
    store.recoveries += 1
    store.recovered_requests += len(handles)

    if warm and handles:
        warmed = set()
        for rid in sorted(handles):
            req = handles[rid].request
            base = FleetService._base_key(req.bucket)
            if (base, req.mode) in warmed:
                continue
            warmed.add((base, req.mode))
            svc.warm(req.cfg, req.mode)
    store.journal.recover_mark(resumed, len(handles),
                               warmed_buckets=len(svc.cache.keys()))
    return svc, handles
