"""Write-ahead journal: the append-only record of one serving run.

One JSONL file (``<run_dir>/journal.jsonl``), one record per line,
flushed per append so a process death loses at most the line being
written (the tolerant reader below skips a torn tail).  Everything
the service does that matters for recovery is journaled:

* ``meta``    — service parameters at construction (batching, pad
  policy, checkpoint cadence).  Wall-clock policies (``max_wait_s``,
  deadlines) are deliberately NOT persisted: they are meaningless
  across a process death and must be re-chosen by the recovering
  caller.
* ``submit``  — one per admitted request: rid, the full config
  (``SimConfig.to_dict``), mode, priority class, tenant.
* ``cut``     — one per checkpointed lane per leg: rid, the cut's
  absolute clock, legs so far, and the snapshot's content address in
  the spill tier (store/spill.py).
* ``fault``   — every fault the injector actually fired (attempt
  index + kind).  The fault plane is already a pure function of
  ``(seed, attempt index)`` (service/faults.py), so this is
  observability, not state — recovery never replays faults.
* ``outcome`` — one per terminal request: status plus a content
  digest of the delivered result (service/replay.result_digest), so
  a recovered run can prove bit-parity for requests that completed
  BEFORE the death without their results surviving it.
* ``recover`` — appended by each recovery pass: how many requests
  were re-admitted and how many resumed from a spilled cut.

No timestamps anywhere: the journal is a pure record of decisions,
identical for identical request streams, which keeps it diffable and
keeps recovery deterministic.
"""

from __future__ import annotations

import json
import os
from typing import Optional


class Journal:
    """Append-only JSONL writer over one run directory's journal."""

    FILENAME = "journal.jsonl"

    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, self.FILENAME)
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        #: records appended by THIS process (an append-only file can
        #: carry records from the run that died; those are the
        #: reader's business, not this counter's)
        self.records_appended = 0

    def _append(self, rec: dict) -> None:
        self._f.write(json.dumps(rec, sort_keys=True,
                                 separators=(",", ":")) + "\n")
        # flush to the OS so the record survives os._exit / SIGKILL
        # of this process (page-cache durability — the crash model
        # here is process death, not power loss)
        self._f.flush()
        self.records_appended += 1

    def meta(self, service: dict) -> None:
        self._append({"rec": "meta", "version": 1, "service": service})

    def submit(self, req) -> None:
        self._append({"rec": "submit", "rid": req.rid,
                      "cfg": req.cfg.to_dict(), "mode": req.mode,
                      "priority": req.priority, "tenant": req.tenant})

    def cut(self, rid: int, tick: int, legs: int, digest: str) -> None:
        self._append({"rec": "cut", "rid": rid, "tick": int(tick),
                      "legs": int(legs), "digest": digest})

    def fault(self, idx: int, kind: str) -> None:
        self._append({"rec": "fault", "idx": int(idx), "kind": kind})

    def outcome(self, rid: int, status: str, result=None,
                error: Optional[str] = None) -> None:
        rec = {"rec": "outcome", "rid": rid, "status": status}
        if result is not None:
            from ..service.replay import result_digest
            rec["digest"] = result_digest(result)
        if error is not None:
            rec["error"] = error
        self._append(rec)

    def recover_mark(self, resumed: int, readmitted: int,
                     warmed_buckets: int = 0) -> None:
        self._append({"rec": "recover", "resumed": int(resumed),
                      "readmitted": int(readmitted),
                      "warmed_buckets": int(warmed_buckets)})

    def close(self) -> None:
        self._f.close()


def read_journal(path: str) -> list:
    """All records of a run's journal, in append order.

    ``path`` may be the journal file or its run directory.  A torn
    final line (the process died mid-append) is skipped; a torn line
    anywhere ELSE is corruption and raises — silently dropping
    interior records would un-admit requests.
    """
    if os.path.isdir(path):
        path = os.path.join(path, Journal.FILENAME)
    records = []
    with open(path, "r", encoding="utf-8") as f:
        lines = f.readlines()
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break  # torn tail: the append the death interrupted
            raise ValueError(
                f"corrupt journal record at {path}:{i + 1} (not the "
                f"final line — this is file corruption, not a torn "
                f"append): {line[:80]!r}")
    return records
