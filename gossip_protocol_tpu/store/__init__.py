"""Durability subsystem: spill tier + write-ahead journal + recovery.

PR 12.  Everything the fleet service needs to survive a process
death: :class:`RunStore` bundles one run directory's two durable
artifacts — the content-addressed checkpoint spill tier
(store/spill.py) and the append-only journal (store/journal.py) —
and ``FleetService(run_dir=...)`` writes through both as it serves.
``FleetService.recover(run_dir)`` (store/recovery.py) then rebuilds
a fresh service from the journal alone, resuming every non-terminal
request from its last spilled cut with zero restarted lanes.

Run directory layout::

    <run_dir>/journal.jsonl        append-only decision record
    <run_dir>/spill/<digest>.npz   one file per checkpoint cut

Host numpy + file IO only — no jnp anywhere in this package
(analysis/purity_lint.py enforces it).
"""

from __future__ import annotations

import os

from .journal import Journal, read_journal
from .spill import (CheckpointStore, CheckpointValidationError,
                    SpilledCheckpoint, inspect_spill, verify_spill)


class RunStore:
    """One serving run's durable state: journal + checkpoint store.

    The scheduler's single durability handle (``FleetService.store``):
    ``put`` journals a cut and admits its snapshot to the spill tier,
    ``materialize`` turns a queued request's lightweight proxy back
    into a dispatchable snapshot, and ``stats`` is what
    ``FleetService.stats()["durability"]`` reports.
    """

    def __init__(self, run_dir: str, max_ram_snapshots: int = 64,
                 policy: str = "eager"):
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self.journal = Journal(run_dir)
        self.checkpoints = CheckpointStore(
            os.path.join(run_dir, "spill"),
            max_ram_snapshots=max_ram_snapshots, policy=policy)
        self.recoveries = 0
        self.recovered_requests = 0

    def put(self, rid: int, ck) -> SpilledCheckpoint:
        """Durably record one checkpoint cut: spill the snapshot
        (write-through under the default eager policy), journal the
        cut, return the proxy the request queues with."""
        ref = self.checkpoints.ref(ck)
        self.journal.cut(rid, ref.tick, ref.legs, ref.digest)
        return ref

    def materialize(self, ck):
        return self.checkpoints.materialize(ck)

    def stats(self) -> dict:
        out = dict(self.checkpoints.stats())
        out["journal_records"] = self.journal.records_appended
        out["recoveries"] = self.recoveries
        out["recovered_requests"] = self.recovered_requests
        out["run_dir"] = self.run_dir
        return out


from .recovery import recover_service  # noqa: E402  (needs RunStore)

__all__ = [
    "RunStore", "Journal", "read_journal", "CheckpointStore",
    "CheckpointValidationError", "SpilledCheckpoint", "inspect_spill",
    "verify_spill", "recover_service",
]
