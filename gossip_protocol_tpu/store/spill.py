"""Checkpoint spill tier: content-addressed npz storage + RAM LRU.

One file per snapshot under ``<run_dir>/spill/<digest>.npz``, keyed
by the snapshot's existing content address
(``core.fleet.LaneCheckpoint.digest`` — clock + config + carry
bytes; the full config, so same-state lanes of different scenario
variants never share an address).  The
layout is the flattened ``(meta, arrays)`` pair of
``core.fleet.checkpoint_arrays``: a ``__header__`` JSON blob (config,
clock, legs, chunk field order, digest, and a sha over every array)
plus one npz entry per state field and per chunk leaf.

Three properties the serving layer leans on:

* **Atomic writes.**  Every spill lands via tmp + ``os.replace``: a
  kill mid-write leaves a dead ``*.tmp.<pid>`` file, never a torn
  ``<digest>.npz`` — recovery either sees a complete spill or none.
* **Validated loads.**  A fetch re-reads the header sha over the raw
  arrays, rebuilds the snapshot, re-derives its digest, and runs
  ``service.resilience.validate_checkpoint`` — a corrupt or
  mislabeled file raises :class:`CheckpointValidationError` carrying
  a single-command repro (``service_smoke.py inspect``) instead of
  re-entering a fleet.
* **Spill-before-evict.**  The in-RAM snapshot map is a bounded LRU;
  under the default eager policy every ``put`` is write-through (the
  durability contract for crash recovery), and under ``lazy`` the
  spill happens at eviction time — either way no snapshot is ever
  dropped from RAM without a bit-identical copy on disk first.

Everything here is host numpy + file IO — no jnp anywhere
(analysis/purity_lint.py registers this module's paths under the
``host-staging-is-numpy`` rule).
"""

from __future__ import annotations

import hashlib
import json
import os
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

#: spill policies: ``eager`` = write-through on every put (the
#: durability contract — crash recovery needs every cut on disk);
#: ``lazy`` = spill only when the RAM LRU evicts (bounded memory for
#: in-process long runs without the disk traffic)
SPILL_POLICIES = ("eager", "lazy")


class CheckpointValidationError(RuntimeError):
    """A spilled snapshot failed validation on load (corrupt bytes,
    digest mismatch, or an invalid rebuilt checkpoint)."""


def _arrays_sha(arrays: dict) -> str:
    """Content sha over every array (name + shape/dtype + bytes, in
    sorted-name order) — the corruption check ``verify_spill`` runs
    on the raw file, before any checkpoint is rebuilt."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def checkpoint_digest_from_arrays(meta: dict, arrays: dict) -> str:
    """``LaneCheckpoint.digest`` recomputed from the FLAT spill form
    (clock + full config + carry bytes — chunks are covered by the
    file sha).

    Mirrors core/fleet.py ``LaneCheckpoint.digest`` byte for byte
    (pinned by tests/test_durability.py) so the pure-numpy inspect
    path can verify a spill without importing jax.  The config dict
    survives the JSON round trip value-exactly (every ``SimConfig``
    field is a scalar), so sorting its items reproduces the live
    digest's fold.
    """
    h = hashlib.sha256()
    h.update(repr((int(meta["tick"]), meta["mode"])).encode())
    h.update(repr(sorted(meta["cfg"].items())).encode())
    state = sorted(k for k in arrays if k.startswith("state/"))
    for key in state:
        h.update(key.split("/", 1)[1].encode())
        h.update(np.ascontiguousarray(arrays[key]).tobytes())
    return h.hexdigest()[:16]


def save_spill(path: str, meta: dict, arrays: dict) -> int:
    """Atomically write one flattened snapshot; returns bytes written.

    The header gains a ``sha`` over the arrays; the write goes to
    ``<path>.tmp.<pid>`` and lands via ``os.replace`` so a kill at
    any instant leaves either the complete file or none.
    """
    meta = dict(meta)
    meta["sha"] = _arrays_sha(arrays)
    header = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, __header__=header, **arrays)
        size = os.path.getsize(tmp)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return size


def read_spill(path: str):
    """``(meta, arrays)`` of one spill file — pure numpy, no
    validation (that is :func:`verify_spill`'s job)."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(bytes(z["__header__"]).decode())
        arrays = {k: z[k] for k in z.files if k != "__header__"}
    return meta, arrays


def verify_spill(path: str):
    """Read + verify one spill file; returns ``(meta, arrays)``.

    Checks, in order: readable npz with a header; header sha matches
    the raw arrays (corruption); header digest matches the digest
    recomputed from the carry (mislabeling / content-address drift).
    Pure numpy — ``service_smoke.py inspect`` runs this without jax.
    """
    try:
        meta, arrays = read_spill(path)
    except Exception as e:  # zipfile/json/np errors: all "unreadable"
        raise CheckpointValidationError(
            f"unreadable spill file {path}: {type(e).__name__}: {e}")
    sha = _arrays_sha(arrays)
    if sha != meta.get("sha"):
        raise CheckpointValidationError(
            f"spill file {path} is corrupt: array sha {sha} != "
            f"recorded {meta.get('sha')}")
    digest = checkpoint_digest_from_arrays(meta, arrays)
    if digest != meta.get("digest"):
        raise CheckpointValidationError(
            f"spill file {path} is mislabeled: carry digest {digest} "
            f"!= recorded {meta.get('digest')}")
    return meta, arrays


def inspect_spill(run_dir: str, digest: str) -> dict:
    """One-command verdict on a single spilled snapshot (the repro
    printed by every :class:`CheckpointValidationError`)."""
    path = os.path.join(run_dir, "spill", f"{digest}.npz")
    if not os.path.exists(path):
        return {"digest": digest, "path": path, "ok": False,
                "why": "missing"}
    try:
        meta, arrays = verify_spill(path)
    except CheckpointValidationError as e:
        return {"digest": digest, "path": path, "ok": False,
                "why": str(e)}
    if meta["digest"] != digest:
        return {"digest": digest, "path": path, "ok": False,
                "why": f"file is addressed {digest} but holds "
                       f"{meta['digest']}"}
    return {"digest": digest, "path": path, "ok": True, "why": "",
            "tick": meta["tick"], "legs": meta["legs"],
            "model": meta["model"], "mode": meta["mode"],
            "n_chunks": meta["n_chunks"],
            "bytes": os.path.getsize(path)}


@dataclass
class SpilledCheckpoint:
    """Lightweight stand-in for a stored :class:`LaneCheckpoint`.

    Carries exactly the scalar fields the scheduler reads between
    dispatches (clock, legs, mesh provenance) plus the content
    address; the carry and chunks stay in the store's RAM LRU or on
    disk until a dispatch actually needs them (``load``).  This is
    what makes the RAM bound REAL: a queued request holding a full
    snapshot on ``req.resume`` would defeat any store-side eviction.
    """

    digest: str
    cfg: object
    mode: str
    tick: int
    legs: int
    wall_seconds: float
    mesh_desc: object = None
    _store: "CheckpointStore" = field(default=None, repr=False)

    @property
    def done(self) -> bool:
        return self.tick >= self.cfg.total_ticks

    def load(self):
        """The full snapshot — a RAM hit or a validated disk reload."""
        return self._store.fetch(self.digest)


class CheckpointStore:
    """Content-addressed snapshot store: bounded RAM LRU over a spill
    directory, with the spill-before-evict guarantee."""

    def __init__(self, spill_dir: str, max_ram_snapshots: int = 64,
                 policy: str = "eager"):
        if policy not in SPILL_POLICIES:
            raise ValueError(f"policy must be one of {SPILL_POLICIES}, "
                             f"got {policy!r}")
        if max_ram_snapshots < 1:
            raise ValueError(f"max_ram_snapshots must be >= 1, got "
                             f"{max_ram_snapshots}")
        self.spill_dir = spill_dir
        os.makedirs(spill_dir, exist_ok=True)
        self.max_ram_snapshots = max_ram_snapshots
        self.policy = policy
        self._ram: OrderedDict = OrderedDict()
        self.spills = 0            # npz files written
        self.spill_bytes = 0       # bytes written to the spill tier
        self.evicted_snapshots = 0  # RAM copies dropped by the LRU
        self.ram_hits = 0
        self.reloads = 0           # validated disk loads
        self.validation_failures = 0

    def _path(self, digest: str) -> str:
        return os.path.join(self.spill_dir, f"{digest}.npz")

    def _spill(self, digest: str, ck) -> None:
        path = self._path(digest)
        if os.path.exists(path):
            return  # content-addressed: same digest, same bytes
        from ..core.fleet import checkpoint_arrays
        meta, arrays = checkpoint_arrays(ck)
        self.spill_bytes += save_spill(path, meta, arrays)
        self.spills += 1

    def ref(self, ck) -> SpilledCheckpoint:
        """Admit a live snapshot to the RAM LRU (evicting under the
        bound, spilling first) and return its lightweight proxy."""
        digest = ck.digest()
        if digest in self._ram:
            self._ram.move_to_end(digest)
        else:
            self._ram[digest] = ck
        if self.policy == "eager":
            self._spill(digest, ck)
        while len(self._ram) > self.max_ram_snapshots:
            old_digest, old_ck = self._ram.popitem(last=False)
            self._spill(old_digest, old_ck)  # spill-before-evict
            self.evicted_snapshots += 1
        return SpilledCheckpoint(
            digest=digest, cfg=ck.cfg, mode=ck.mode, tick=int(ck.tick),
            legs=int(ck.legs), wall_seconds=float(ck.wall_seconds),
            mesh_desc=ck.mesh_desc, _store=self)

    def fetch(self, digest: str):
        """The full snapshot behind a content address.

        RAM hit when the LRU still holds it; otherwise a validated
        disk reload (sha + digest + ``validate_checkpoint``) that
        re-enters the LRU.  Raises :class:`CheckpointValidationError`
        (with the inspect repro) on any validation failure and
        ``FileNotFoundError`` when the address was never spilled.
        """
        ck = self._ram.get(digest)
        if ck is not None:
            self.ram_hits += 1
            self._ram.move_to_end(digest)
            return ck
        path = self._path(digest)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no spilled snapshot {digest} under {self.spill_dir} "
                f"(lazy-policy runs only spill on eviction; crash "
                f"recovery requires policy='eager')")
        try:
            meta, arrays = verify_spill(path)
            from ..core.fleet import checkpoint_from_arrays
            ck = checkpoint_from_arrays(meta, arrays)
            if ck.digest() != digest:
                raise CheckpointValidationError(
                    f"rebuilt snapshot digest {ck.digest()} != "
                    f"address {digest}")
            from types import SimpleNamespace
            from ..service.resilience import validate_checkpoint
            why = validate_checkpoint(
                SimpleNamespace(cfg=ck.cfg, rid=-1), ck)
            if why is not None:
                raise CheckpointValidationError(
                    f"rebuilt snapshot {digest} failed "
                    f"validate_checkpoint: {why}")
        except CheckpointValidationError as e:
            self.validation_failures += 1
            run_dir = os.path.dirname(self.spill_dir) or "."
            raise CheckpointValidationError(
                f"{e}\n  repro: PYTHONPATH=. python scripts/"
                f"service_smoke.py inspect {run_dir} {digest}") from e
        self.reloads += 1
        self._ram[digest] = ck
        while len(self._ram) > self.max_ram_snapshots:
            old_digest, old_ck = self._ram.popitem(last=False)
            self._spill(old_digest, old_ck)
            self.evicted_snapshots += 1
        return ck

    def materialize(self, ck):
        """A real :class:`LaneCheckpoint` for dispatch: proxies are
        fetched (RAM or disk), live snapshots pass through."""
        if isinstance(ck, SpilledCheckpoint):
            return self.fetch(ck.digest)
        return ck

    def stats(self) -> dict:
        return {"spills": self.spills, "spill_bytes": self.spill_bytes,
                "evicted_snapshots": self.evicted_snapshots,
                "ram_snapshots": len(self._ram),
                "max_ram_snapshots": self.max_ram_snapshots,
                "ram_hits": self.ram_hits, "reloads": self.reloads,
                "validation_failures": self.validation_failures,
                "policy": self.policy}
