"""32-bit counter-based hashing usable inside jitted TPU code.

The overlay model (models/overlay.py) derives all of its per-tick
randomness — per-receiver slot assignment, gossip target draws, drop
decisions — from this pure integer hash instead of stateful PRNG keys.
That keeps the hot path at one fused integer expression per draw, and
because the function is a plain uint32 computation it runs bit-identically
under numpy, so the scalar oracle (testing/overlay_oracle.py) replays the
exact device randomness without any replay harness.

The mixer is the murmur3 fmix32 finalizer over a Weyl-sequence
accumulation of the keys (public-domain constants), a 32-bit sibling of
the splitmix64 construction in utils/prng.py / native/bus.cc.
"""

from __future__ import annotations

import numpy as np

# 0-d arrays, not numpy scalars: unsigned wraparound is the point of
# the construction, and numpy warns on scalar (but not array) overflow
_GOLD = tuple(np.asarray(g, np.uint32) for g in
              (0x9E3779B1, 0x85EBCA6B, 0xC2B2AE35, 0x27D4EB2F, 0x165667B1))
_ONE = np.asarray(1, np.uint32)
_M1 = np.asarray(0x7FEB352D, np.uint32)
_M2 = np.asarray(0x846CA68B, np.uint32)


def _u32(v):
    if isinstance(v, (int, np.integer)) or (isinstance(v, np.generic)):
        return np.asarray(v, np.uint32)
    return v


def mix32(seed, *keys):
    """uint32 hash of up to five integer keys (arrays broadcast).

    Works on jax arrays and numpy arrays alike: every operation is
    uint32 (wrapping) arithmetic, with the constants pre-typed as
    numpy uint32 scalars so neither backend widens or overflows.
    Array inputs must already be uint32.
    """
    with np.errstate(over="ignore"):   # unsigned wraparound is intended
        x = _u32(seed)
        for k, g in zip(keys, _GOLD):
            x = x + (_u32(k) + _ONE) * g
        x = (x ^ (x >> 16)) * _M1
        x = (x ^ (x >> 15)) * _M2
        x = x ^ (x >> 16)
    return x


def threshold32(prob: float) -> int:
    """uint32 threshold so that ``mix32(...) < threshold32(p)`` is a
    Bernoulli(p) draw.  Integer comparison keeps device (float32) and
    oracle (float64) behavior bit-identical — no float round-off at the
    decision boundary."""
    return min(0xFFFFFFFF, max(0, int(round(prob * 4294967296.0))))
