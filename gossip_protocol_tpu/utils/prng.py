"""Counter-based PRNG shared with the native runtime.

``hash_uniform`` is the bit-exact Python twin of ``gossip::HashUniform``
(native/bus.cc): key material mixed with odd constants, then the
splitmix64 finalizer (public-domain Stafford/Steele mixing constants),
mapped to [0, 1) through the 53-bit double mantissa.  Both backends
derive scenario randomness (failure-victim selection, standalone drop
decisions) from this function, so the same seed produces the same
schedule whether the run executes on the JAX engine or the C++ engine —
unlike the reference, whose ``srand(time(NULL))`` (Application.cpp:50)
makes runs irreproducible even on one backend.

The device-side drop masks still come from ``jax.random`` (threefry)
inside the jitted tick — this module seeds *host-side* schedule
decisions only.
"""

from __future__ import annotations

_M64 = (1 << 64) - 1


def hash_uniform(seed: int, a: int, b: int, c: int, d: int) -> float:
    """Uniform double in [0, 1), a pure function of the five keys."""
    x = seed & _M64
    x = (x + 0x9E3779B97F4A7C15 * (a + 1)) & _M64
    x = (x + 0xBF58476D1CE4E5B9 * (b + 1)) & _M64
    x = (x + 0x94D049BB133111EB * (c + 1)) & _M64
    x = (x + 0xD6E8FEB86659FD93 * (d + 1)) & _M64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _M64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _M64
    x ^= x >> 31
    return (x >> 11) * (2.0 ** -53)


#: Salt for the failure-schedule draw (native/engine.cc uses the same).
FAIL_SALT = 7


def fail_schedule_uniform(seed: int) -> float:
    """The single uniform draw both backends use to pick failure victims."""
    return hash_uniform(seed, 0, 0, 0, FAIL_SALT)
