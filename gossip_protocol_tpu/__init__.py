"""gossip_protocol_tpu — a TPU-native gossip membership-protocol framework.

A from-scratch JAX/XLA re-design of the capabilities of the C++ reference
``Bobbyyang1314/Gossip_Protocol`` (the classic MP1 membership protocol:
introducer-based join, all-pairs heartbeat gossip, TREMOVE staleness
failure detection, scripted fault/drop injection, grep-able dbg.log).

Instead of stepping N node objects over an in-memory message buffer, the
entire world is a handful of device arrays and one tick is one jitted
pure function (see ``core/tick.py``); a full run is a ``lax.scan``.  The
reference's .conf format, CLI shape, and log grammars are preserved so
its grading harness passes unmodified; peer count scales far past the
reference's hard N<=10 cap via sharding (``parallel/``) and bounded
partial-view overlays (``models/overlay.py``).
"""

from .config import (INTRODUCER, MSG_DROP_SINGLE_FAILURE, MULTI_FAILURE,
                     SINGLE_FAILURE, SimConfig)
from .state import (Schedule, WorldState, init_state, load_checkpoint,
                    make_schedule, save_checkpoint, state_from_host,
                    state_to_host)

__version__ = "0.2.0"

__all__ = [
    "SimConfig", "INTRODUCER",
    "SINGLE_FAILURE", "MULTI_FAILURE", "MSG_DROP_SINGLE_FAILURE",
    "WorldState", "Schedule", "init_state", "make_schedule",
    "state_to_host", "state_from_host", "save_checkpoint", "load_checkpoint",
    "Simulation", "run_scenario", "OverlaySimulation",
]


def __getattr__(name):
    # lazy imports so `import gossip_protocol_tpu` stays light
    if name in ("Simulation", "run_scenario"):
        from .core import sim
        return getattr(sim, name)
    if name == "OverlaySimulation":
        from .models.overlay import OverlaySimulation
        return OverlaySimulation
    raise AttributeError(name)
