"""Reference-grammar log writers: dbg.log, stats.log, msgcount.log.

These files are the reference's observability surface and external API:

* ``dbg.log``    — event log, grep-asserted by Grader.sh.  First line is
  the hex char-sum of the magic string "CS425" (= 0x131, Log.cpp:79-88);
  every event is ``\\n <addr> [tick] <text>`` (Log.cpp:97-99) where
  ``<addr>`` is the dotted byte form with a trailing space (Log.cpp:73).
  Quirk reproduced under ``bug_compat``: the reference's static address
  buffer is not filled on the very first LOG call (the if/else at
  Log.cpp:56-73 skips the sprintf), so the first line's address is blank.
* ``stats.log``  — created empty (no #STATSLOG# producers exist,
  Log.cpp:90-95).
* ``msgcount.log`` — per-node, per-tick (sent, recv) matrix in the exact
  ENcleanup format (EmulNet.cpp:184-220), including the 10-per-line
  wrapping and the bizarre node-67 "special" row.

This module is the grammar's single source of truth on the Python side;
the native runtime carries an independent implementation of the same
grammar (``native/logsink.cc``) used by the C++ engine.
tests/test_native.py asserts msgcount.log byte-compatibility between
the two, and dbg.log compatibility at the event-set and grader level
(within-tick line order can legitimately differ between the engines'
canonical orders, so dbg.log is not byte-compared).
"""

from __future__ import annotations

import os
from typing import Iterable

import numpy as np

from .addressing import addr_str
from .events import LogEvent

MAGIC_NUMBER = "CS425"  # Log.h:19
DBG_LOG = "dbg.log"
STATS_LOG = "stats.log"
MSGCOUNT_LOG = "msgcount.log"


def magic_line() -> str:
    """Hex char-sum of the magic string: "131" (Log.cpp:80-86)."""
    return "%x" % sum(ord(c) for c in MAGIC_NUMBER)


def format_events(events: Iterable[LogEvent], bug_compat: bool = True) -> str:
    """Render an event stream to the dbg.log byte grammar."""
    parts = [magic_line(), "\n"]
    first = True
    for ev in events:
        addr = "" if (first and bug_compat) else addr_str(ev.observer) + " "
        parts.append(f"\n {addr}[{ev.tick}] {ev.text}")
        first = False
    return "".join(parts)


def write_dbg_log(events: Iterable[LogEvent], outdir: str = ".",
                  bug_compat: bool = True) -> str:
    path = os.path.join(outdir, DBG_LOG)
    text = format_events(events, bug_compat)
    with open(path, "w") as f:
        f.write(text)
    # stats.log is opened alongside dbg.log and stays empty (Log.cpp:66-67)
    open(os.path.join(outdir, STATS_LOG), "w").close()
    return path


def format_msgcount(sent: np.ndarray, recv: np.ndarray) -> str:
    """Render the (N, T) counters in ENcleanup's format (EmulNet.cpp:195-216).

    ``sent``/``recv`` are indexed by 0-based peer; rows print as 1-based
    node ids.  T is the final clock value (loop bound at exit).
    """
    n, t_total = sent.shape
    out = []
    for i in range(n):
        node_id = i + 1
        out.append("node %3d " % node_id)
        sent_total = recv_total = 0
        for j in range(t_total):
            sent_total += int(sent[i, j])
            recv_total += int(recv[i, j])
            if node_id != 67:
                out.append(" (%4d, %4d)" % (sent[i, j], recv[i, j]))
                if j % 10 == 9:
                    out.append("\n         ")
            else:
                out.append("special %4d %4d %4d\n" % (j, sent[i, j], recv[i, j]))
        out.append("\n")
        out.append("node %3d sent_total %6u  recv_total %6u\n\n"
                   % (node_id, sent_total, recv_total))
    return "".join(out)


def write_msgcount_log(sent: np.ndarray, recv: np.ndarray,
                       outdir: str = ".") -> str:
    path = os.path.join(outdir, MSGCOUNT_LOG)
    with open(path, "w") as f:
        f.write(format_msgcount(sent, recv))
    return path
