"""Simulation orchestrator.

TPU-native replacement for the reference ``Application`` driver
(Application.cpp:90-163): instead of a host loop that steps N C++
objects, the whole run is one (or a few, when chunked) ``lax.scan`` XLA
programs over the tick function, with event masks streamed back to host
only as often as the caller needs them.

Modes:
* trace mode (``run()``)  — stacked per-tick event masks come back to
  host; feeds the dbg.log writer and the grader checks.  Chunked over
  ticks so event staging memory stays bounded at large N.
* bench mode (``run_bench()``) — no event masks, counters only; the
  entire 700-tick run stays on device and is timed end-to-end.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import SimConfig
from ..events import LogEvent, event_stream, grader_view
from ..state import Schedule, WorldState, init_state, make_schedule
from .tick import make_run, make_tick


@partial(jax.jit, static_argnames=("cap",))
def _pack_sparse(added, removed, cap: int):
    """Device-side sparse encoding of two (C, N, N) bool event masks.

    The relay/PCIe transfer of dense per-tick masks dominates
    trace-mode wall time (366 MB for a 700-tick N=512 run); real event
    masks are sparse and clustered, so: bit-pack the subject axis into
    uint32 words (a dense reduce, cheap), then extract only the
    nonzero words of the two packed arrays together (flatnonzero over
    32x fewer elements; gather/scatter serialize on this TPU, so
    shrinking the nonzero problem is the whole trick).  Only the 32x-
    smaller PACKED arrays are concatenated — never the raw masks, so
    peak staging memory stays the two masks themselves.  Returns
    (idx, vals, nz_words); if nz_words > cap the caller falls back to
    the dense transfer (correctness never depends on the cap).
    """
    c, n, _ = added.shape
    nw = (n + 31) // 32
    pad = nw * 32 - n

    def packbits(m):
        if pad:
            m = jnp.pad(m, ((0, 0), (0, 0), (0, pad)))
        return (m.reshape(c, n, nw, 32).astype(jnp.uint32)
                << jnp.arange(32, dtype=jnp.uint32)) \
            .sum(-1, dtype=jnp.uint32).reshape(-1)

    flat = jnp.concatenate([packbits(added), packbits(removed)])
    nzw = (flat != 0).sum()
    idx = jnp.flatnonzero(flat, size=cap, fill_value=0)
    return idx.astype(jnp.int32), flat[idx], nzw


def _finish_masks_host(added, removed, idx, vals, nzw, cap: int):
    """Host half of the sparse mask transfer: consume the compaction
    outputs of :func:`_pack_sparse` (already computed on device — the
    fleet's pipelined launch dispatches the compaction right after
    the run program, core/fleet.py) and unpack to numpy.  Falls back
    to the dense transfer of the original masks when the realized
    nonzero count overflowed the sparse budget."""
    c, n, _ = added.shape
    nzw = int(nzw)
    if nzw > cap:                       # denser than the sparse budget
        return np.asarray(added), np.asarray(removed)
    sl = 1 << max(10, (max(nzw, 1) - 1).bit_length())
    sl = min(sl, cap)
    pair = np.asarray(jnp.stack([idx[:sl], vals[:sl].astype(jnp.int32)]))
    nw = (n + 31) // 32
    words = np.zeros((2 * c * n * nw,), np.uint32)
    words[pair[0, :nzw]] = pair[1, :nzw].astype(np.uint32)
    bits = np.unpackbits(words.view(np.uint8).reshape(-1, 4), axis=1,
                         bitorder="little")
    both_h = bits.reshape(2 * c, n, nw * 32)[:, :, :n].astype(bool)
    return both_h[:c], both_h[c:]


def _masks_to_host(added, removed, cap: int):
    """Two (C, N, N) device bool masks -> host numpy, sparse when
    possible (one compaction pass over both — fewer relay dispatches).

    Only a power-of-two bucket around the realized nonzero count
    crosses the relay, not the whole cap-sized buffer: the transfer
    is the wall-time bound here (~7 MB/s through this image's relay,
    docs/PERF.md), and real event streams fill a few percent of the
    cap.  Bucketing keeps the slice shapes (and so the compiled
    transfer programs) to a handful."""
    c, n, _ = added.shape
    if c == 0 or n < 2:
        return np.asarray(added), np.asarray(removed)
    idx, vals, nzw = _pack_sparse(added, removed, cap=cap)
    return _finish_masks_host(added, removed, idx, vals, nzw, cap)


@dataclass
class SimResult:
    """Host-side digest of a finished run (or resumed run segment)."""

    cfg: SimConfig
    start_tick: np.ndarray   # i32[N]
    fail_tick: np.ndarray    # i32[N]
    rejoin_tick: np.ndarray  # i32[N] (NEVER = no churn rejoin)
    added: Optional[np.ndarray]    # bool[T, N, N] (trace mode only)
    removed: Optional[np.ndarray]  # bool[T, N, N]
    sent: np.ndarray         # i32[N, T]
    recv: np.ndarray         # i32[N, T]
    final_state: WorldState
    wall_seconds: float
    first_tick: int = 0      # absolute tick of added[0] (0 unless resumed)
    resumed: bool = False    # True for a continuation segment (no boot lines)
    #: width at which the run drew its drop stream (None: full width).
    #: Bench runs routed through the active corner draw at width
    #: A < N, so their sent/recv counters are a different — equally
    #: seeded — realization of the drop process than a trace run of
    #: the same seed; compare counters across modes only when this
    #: equals cfg.n (core/dense_corner.py bench_stream_width).
    counter_stream_width: Optional[int] = None

    def events(self) -> list[LogEvent]:
        assert self.added is not None, "events need a trace-mode run"
        # boot-line emission is decided by event_stream's default rule:
        # non-empty segments starting at tick 0 (covers resumption from
        # a tick-0 checkpoint without duplicating mid-run continuations)
        return list(event_stream(self.cfg, self.start_tick, self.fail_tick,
                                 self.added, self.removed,
                                 first_tick=self.first_tick,
                                 rejoin_tick=self.rejoin_tick))

    def grader_view(self) -> dict:
        return grader_view(self.events())

    def write_logs(self, outdir: str = ".") -> None:
        from ..logging_compat import write_dbg_log, write_msgcount_log
        write_dbg_log(self.events(), outdir)
        write_msgcount_log(self.sent, self.recv, outdir)

    # --- convenience metrics ---------------------------------------
    @property
    def ticks_run(self) -> int:
        """Ticks actually executed in this (possibly partial) segment."""
        return self.sent.shape[1]

    @property
    def ticks_per_second(self) -> float:
        """Tick throughput of this segment; 0.0 for degenerate segments.

        A zero-length resumed segment (already at/after its end tick)
        finishes in ~0 wall seconds with 0 ticks run — 0/0 here — and
        a sub-resolution clock can report ``wall_seconds == 0.0``
        outright, so guard both rather than raise ZeroDivisionError.
        """
        if self.ticks_run == 0 or self.wall_seconds <= 0.0:
            return 0.0
        return self.ticks_run / self.wall_seconds

    @property
    def node_ticks_per_second(self) -> float:
        return self.ticks_per_second * self.cfg.n


class Simulation:
    """Compile once per (config shape), run many times."""

    def __init__(self, cfg: SimConfig, block_size: int = 128,
                 chunk_ticks: Optional[int] = None,
                 use_pallas: Optional[bool] = None):
        self.cfg = cfg
        self.block_size = block_size
        self.use_pallas = use_pallas
        # Default chunking bounds the DEVICE-staged event masks (~1 GB
        # of HBM); the host side receives a sparse encoding
        # (_pack_sparse), so host staging no longer constrains chunks.
        if chunk_ticks is None:
            per_tick = 2 * cfg.n * cfg.n  # two bool masks
            chunk_ticks = max(1, min(cfg.total_ticks, (1 << 30) // max(per_tick, 1)))
        self.chunk_ticks = chunk_ticks
        self._trace_runs = {}
        self._bench_run = None

    def _trace_run_fn(self, length: int):
        if length not in self._trace_runs:
            cfg = self.cfg.replace(total_ticks=length)
            self._trace_runs[length] = make_run(cfg, self.block_size,
                                                with_events=True,
                                                use_pallas=self.use_pallas)
        return self._trace_runs[length]

    def _bench_run_fn(self):
        """The bench-path compiled run, cached by config SHAPE alone.

        The cache key is explicit: ``self.cfg`` with whatever seed it
        carries — never the per-call seed — because everything
        seed-dependent flows through the Schedule arrays and the
        initial PRNG key, not the compiled program (``make_run``'s own
        cache key contains no seed either).  One build therefore
        serves every ``run_bench(seed=...)`` call; regression-pinned
        by tests/test_fleet.py::test_run_bench_no_rebuild via
        ``core.tick.run_build_count``.  The key does, however, carry
        the segment-plan signature (models/segments.plan_signature):
        a config edit that only moves a phase boundary — a shifted
        drop window, a later fail tick — compiles fresh instead of
        being served the old boundaries' program
        (tests/test_service.py::
        test_run_bench_cache_key_includes_plan_signature).
        """
        if self._bench_run is None:
            self._bench_run = make_run(self.cfg, self.block_size,
                                       with_events=False,
                                       use_pallas=self.use_pallas)
        return self._bench_run

    def run(self, seed: Optional[int] = None,
            resume_from: Optional[WorldState] = None,
            ticks: Optional[int] = None,
            profile_dir: Optional[str] = None) -> SimResult:
        """Trace-mode run: full event masks for logging/grading.

        ``resume_from`` continues a previous (possibly checkpointed)
        state — the clock, in-flight traffic, and PRNG key are all part
        of the state, so the continuation is bit-identical to an
        uninterrupted run (the reference cannot do this at all: it
        always runs 0..700, Application.cpp:99).  ``ticks`` stops the
        segment early (e.g. to checkpoint mid-run); the default runs
        through ``cfg.total_ticks``.

        ``profile_dir`` wraps the run in ``jax.profiler.trace`` and
        writes a TensorBoard-loadable profile there — the framework's
        answer to the reference's (absent) tracer, SURVEY.md §5.
        """
        if profile_dir is not None:
            with jax.profiler.trace(profile_dir):
                return self.run(seed=seed, resume_from=resume_from,
                                ticks=ticks)
        if seed is not None and resume_from is not None:
            raise ValueError(
                "seed and resume_from are mutually exclusive: a reseeded "
                "schedule would not be the one that produced the resumed "
                "state")
        cfg = self.cfg if seed is None else self.cfg.replace(seed=seed)
        sched = make_schedule(cfg)
        state = init_state(cfg) if resume_from is None else resume_from
        first = int(np.asarray(state.tick))
        t_end = cfg.total_ticks if ticks is None \
            else min(cfg.total_ticks, first + ticks)
        added, removed, sent, recv = [], [], [], []
        t0 = time.perf_counter()
        done = first
        while done < t_end:
            length = min(self.chunk_ticks, t_end - done)
            run = self._trace_run_fn(length)
            state, ev = run(state, sched)
            # sparse device->host event staging (an 8x+ transfer cut
            # guaranteed by the word cap; typically far more)
            nw = (cfg.n + 31) // 32
            # cap-sized idx/vals buffers are what actually crosses the
            # relay: words//16 keeps that small while real event
            # densities stay far below it (overflow falls back dense)
            cap = max(1 << 14, (2 * length * cfg.n * nw) // 16)
            a_h, r_h = _masks_to_host(ev.added, ev.removed, cap)
            added.append(a_h)
            removed.append(r_h)
            # one stacked transfer; i16 halves the bytes and is exact
            # (per-tick counters are bounded by ~2N, EmulNet semantics)
            if cfg.n <= 8192:
                sr = np.asarray(jnp.stack([ev.sent, ev.recv])
                                .astype(jnp.int16)).astype(np.int32)
            else:
                sr = np.asarray(jnp.stack([ev.sent, ev.recv]))
            sent.append(sr[0])
            recv.append(sr[1])
            done += length
        wall = time.perf_counter() - t0
        if not added:   # zero-length segment (already at/after t_end)
            added = [np.zeros((0, cfg.n, cfg.n), bool)]
            removed = [np.zeros((0, cfg.n, cfg.n), bool)]
            sent = [np.zeros((0, cfg.n), np.int32)]
            recv = [np.zeros((0, cfg.n), np.int32)]
        return SimResult(
            cfg=cfg,
            start_tick=np.asarray(sched.start_tick),
            fail_tick=np.asarray(sched.fail_tick),
            rejoin_tick=np.asarray(sched.rejoin_tick),
            added=np.concatenate(added, 0),
            removed=np.concatenate(removed, 0),
            sent=np.concatenate(sent, 0).T.copy(),
            recv=np.concatenate(recv, 0).T.copy(),
            final_state=state,
            wall_seconds=wall,
            first_tick=first,
            resumed=resume_from is not None,
        )

    def run_bench(self, seed: Optional[int] = None, warmup: bool = True) -> SimResult:
        """Bench-mode run: whole simulation on device, timed end-to-end.

        Always starts from ``init_state`` (tick 0) — the active-corner
        routing derives its width from the whole-run horizon and its
        run function rejects any other clock.  For drop configs whose
        ``active_bound < N`` the corner draws the drop stream at the
        corner width, so the returned sent/recv counters are NOT
        bit-comparable to a trace-mode ``run()`` of the same seed
        (statistically equivalent realizations of the same process);
        ``SimResult.counter_stream_width`` records the width drawn.
        """
        from .dense_corner import bench_stream_width
        cfg = self.cfg if seed is None else self.cfg.replace(seed=seed)
        sched = make_schedule(cfg)
        run = self._bench_run_fn()
        if warmup:  # compile outside the timed region
            s, e = run(init_state(cfg), sched)
            jax.block_until_ready(s)
        state = init_state(cfg)
        t0 = time.perf_counter()
        state, ev = run(state, sched)
        jax.block_until_ready(state)
        # Force a device->host readback inside the timed region: on
        # relayed/tunneled accelerators block_until_ready can return on
        # dispatch acknowledgement, and a wall-clock without a data
        # dependency under-reports.  (Not an assert: must survive -O.)
        if int(np.asarray(state.tick)) != cfg.total_ticks:
            raise RuntimeError("bench run did not complete all ticks")
        wall = time.perf_counter() - t0
        return SimResult(
            cfg=cfg,
            start_tick=np.asarray(sched.start_tick),
            fail_tick=np.asarray(sched.fail_tick),
            rejoin_tick=np.asarray(sched.rejoin_tick),
            added=None, removed=None,
            sent=np.asarray(ev.sent).T.copy(),
            recv=np.asarray(ev.recv).T.copy(),
            final_state=state,
            wall_seconds=wall,
            counter_stream_width=bench_stream_width(cfg),
        )


def run_scenario(cfg: SimConfig, outdir: Optional[str] = None,
                 **sim_kw) -> SimResult:
    """One-call helper: simulate and (optionally) write the three logs."""
    result = Simulation(cfg, **sim_kw).run()
    if outdir is not None:
        result.write_logs(outdir)
    return result
