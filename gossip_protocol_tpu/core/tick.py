"""One simulation tick as a single pure function.

The reference's driver runs, per global time step (Application.cpp:99-163):

  phase A — every started, live node drains its network inbox
            (``recvLoop``, Application.cpp:125-135);
  phase B — in reverse node order, nodes are introduced
            (``nodeStart``) or run ``nodeLoop`` = process queued
            messages, then periodic ops (Application.cpp:138-163);
  then    — scripted fault injection (``fail``, Application.cpp:173-202).

Because every message sent during tick *t* sits in the EmulNet buffer
until the receivers' phase A of tick *t+1* (all sends happen in phase B,
all receives in phase A), **no node observes another node's tick-t
actions within tick t** — the reference's sequential reverse-order loop
is only a logging order, not a data dependency.  The whole tick is
therefore expressible as batched, order-free tensor algebra over the
peer axis, which is what this module does.  One divergence is accepted
and documented: within a single receiver's tick, the reference processes
queued messages in EmulNet buffer order; we apply a canonical order
(all piggyback merges, then all direct-sender updates, then join
messages — matching the observed queue order gossip-before-JOINREP /
gossip-before-JOINREQ, EmulNet.cpp:151-160).  The only reachable
difference is a small offset on heartbeat counters seeded during the
join phase: an entry created one merge-order step apart ends up +/-1,
and because later merges adopt only strictly larger values the offset
persists, and two independently-seeded offsets can stack along a
gossip path (observed max 2, drop scenarios only).  It is not
observable in any logged event, removal time, or live-row timestamp
(asserted by tests/test_parity.py against the message-level oracle).

Fault injection runs *after* the protocol phases (Application.cpp:99-104),
so a node failed "at tick 100" still gossips during tick 100 and its
flag is observed from tick 101 on — that, plus the one-tick delivery
delay, is why the measured removal lands at fail + TREMOVE + 1 = t=121
(BASELINE.md).

The body is written once against the ``Comm`` interface
(parallel/comm.py): with :class:`LocalComm` it is a single-device XLA
program; inside ``shard_map`` with :class:`RingComm` the same code runs
with the peer axis sharded across a device mesh — (N,) vectors
replicated, (N, N) tables row-sharded, one ``all_to_all`` delivery
transpose and a ``ppermute`` ring merge per tick.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ..config import INTRODUCER, SimConfig
from ..ops.detect import staleness_mask
from ..ops.drop import tick_drop_masks
from ..parallel.comm import LocalComm
from ..state import Schedule, WorldState


@struct.dataclass
class TickEvents:
    """Grader-visible events produced by one tick, as dense masks.

    The dbg.log writer (events.py) turns these into the reference's
    exact log grammar; Grader.sh-style checks consume only these.
    """

    added: jax.Array    # bool[rows, N] — observer i added subject j this tick
                        #   (logNodeAdd, Log.cpp:116-120)
    removed: jax.Array  # bool[rows, N] — observer i removed subject j
                        #   (logNodeRemove, Log.cpp:127-131)
    sent: jax.Array     # i32[rows] — successful sends this tick (EmulNet.cpp:111)
    recv: jax.Array     # i32[rows] — messages consumed this tick (EmulNet.cpp:172)


def make_tick(cfg: SimConfig, block_size: int = 128, comm=None,
              use_pallas: bool | None = None, with_events: bool = True,
              n_active: int | None = None,
              lane_drop_window: bool = False):
    """Build the tick function for a config (shapes are static).

    Returned signature: ``tick(state, sched) -> (state', TickEvents)``.
    With a :class:`RingComm`, call it inside ``shard_map`` with (N, N)
    arrays sharded ``P(axis, None)`` and everything else replicated.
    ``use_pallas`` routes the matrix phases through Pallas (None =
    auto: on for TPU backends); on the single-device path this uses
    the fully-fused tick kernel (ops/pallas/tickfused.py) — merge,
    membership update, detection, and dissemination in one launch —
    while the sharded ring path uses the composable merge kernel.
    ``use_pallas`` is ignored when an explicit ``comm`` is passed.

    ``n_active`` pins the drop-stream width: the Bernoulli lattice is
    drawn at ``n_active`` peers and embedded into the (N, N) masks
    (zeros outside — no send ever leaves the active corner, see
    core/dense_corner.py).  The corner-reduced run draws at its own
    width natively; passing the same ``n_active`` here makes the
    full-width path consume the byte-identical stream, which is what
    the corner differential tests rely on.  Default: N.

    ``lane_drop_window`` re-applies each lane's EXACT drop window from
    the ``Schedule.drop_open``/``drop_close`` scalars on top of the
    windowed draw.  The canonical fleet path (service/canonical.py)
    shares one QUANTIZED superset window as ``drop_active`` across
    lanes whose exact windows differ — the draw itself depends only on
    (rng, t, n_active), so masking it back to the exact window yields
    the solo run's masks bit-for-bit while the shared cond predicate
    stays unbatched (cond-stays-cond, analysis/jaxpr_audit.py).
    """
    comm = comm or LocalComm(use_pallas)
    n = cfg.n
    na = n if n_active is None else n_active
    assert na <= n
    t_remove = cfg.t_remove
    # flap up-edges are rejoin events (fresh-nodeStart wipes), so the
    # flap world compiles the churn path in
    churn = cfg.rejoin_after is not None or cfg.flap_rate > 0
    # adversarial worlds (worlds.py): partition and asym-drop ride the
    # drop plane (mask-level, so the fused TPU path gets them for
    # free); zombie changes dissemination and the direct-sender credit,
    # which the fused kernel does not compile — gated below
    partition = cfg.partition_groups >= 2
    asym = cfg.asym_drop
    zombie = cfg.zombie
    # round-2 planes (worlds.py): byz forges the merge payload planes
    # and changes the timestamp rules (the direct-only-credit defense);
    # latency adds the message-age dimension — neither is compiled by
    # the fused kernel
    byz = cfg.byz_rate > 0
    latency = cfg.link_latency > 0
    assert n % comm.n_shards == 0, "peer count must divide the mesh axis"
    # the fused epilogue kernel needs its tile divisibility (row tile
    # 64, sublane-aligned — mirrors the asserts in fused_tick_update)
    # and bounded VMEM: its row tiles span the full peer axis, so the
    # kernel raises its scoped-VMEM window itself (the old n <= 2048
    # envelope was the default 16 MB window; n = 8192 would put a
    # single (TR=64, N) tile set near 50 MB, untested).  Everything
    # else falls back to the composable ops (which still use the MXU
    # merge when use_pallas is on).
    _tr = min(64, n)
    fused = (isinstance(comm, LocalComm) and comm.use_pallas
             and n <= 4096 and n % _tr == 0 and _tr % 8 == 0
             and not zombie and not byz and not latency)

    def tick(state: WorldState, sched: Schedule):
        t = state.tick
        row_ids = comm.row_ids(n)                        # global ids of local rows
        col_ids = jnp.arange(n, dtype=jnp.int32)
        self_mask = row_ids[:, None] == col_ids[None, :]  # local rows' diag
        is_intro_row = row_ids == INTRODUCER
        intro_onehot = col_ids == INTRODUCER

        failed = sched.failed_at(t)
        # recvLoop/nodeLoop gate: strictly after the start tick and not
        # failed (Application.cpp:130,153).
        proc = (t > sched.start_tick) & ~failed

        # ---- churn extension: wipe rejoining peers -----------------
        # A peer scheduled to rejoin at tick t is re-initialized like a
        # fresh nodeStart (initThisNode, MP1Node.cpp:95-113): empty
        # member list, heartbeat 0, out of group.  It is still failed
        # while processing tick t (failed_at: fail < t <= rejoin, and
        # make_schedule enforces rejoin > fail), so it neither consumes
        # traffic nor gossips this tick, and no other peer reads its
        # payload rows (in-flight traffic from a failed peer was
        # already dropped) — the wipe is safe anywhere in the tick.
        # Statically compiled out for no-churn configs.
        if churn:
            rejoining = sched.rejoining_at(t)
            keep_rows = ~rejoining[row_ids]
            st_known = state.known & keep_rows[:, None]
            st_hb = state.hb * keep_rows[:, None]
            st_ts = state.ts * keep_rows[:, None]
            st_in_group = state.in_group & ~rejoining
            st_own_hb = state.own_hb * ~rejoining
        else:
            rejoining = jnp.zeros_like(sched.start_tick, bool)
            st_known, st_hb, st_ts = state.known, state.hb, state.ts
            st_in_group, st_own_hb = state.in_group, state.own_hb

        # ---- phase A: consume in-flight traffic --------------------
        if latency:
            # per-link delay (worlds.py latency plane): a message sent
            # at t0 carries age t - t0 - 1 in gossip_age; it becomes
            # deliverable once it has been in flight lat(s, r) ticks.
            # Undelivered messages keep aging in place (at most one in
            # flight per link — a busy link skips the new send below),
            # and traffic to failed receivers rots like the
            # non-latency path's buffer rule.
            lat_l = comm.slice_rows(sched.link_lat)      # [rows=s, r]
            age1 = state.gossip_age + 1                  # ticks since send
            deliver = state.gossip & (age1 >= lat_l) & proc[None, :]
            held = state.gossip & ~deliver & ~failed[None, :]
        else:
            deliver = state.gossip & proc[None, :]       # [rows=s, r] consumed now
        jreq = state.joinreq & proc[INTRODUCER]          # requests the introducer processes
        jrep = state.joinrep & proc                      # JOINREPs joiners process
        recv_from = comm.transpose(deliver)              # [rows=r, s]

        # ---- nodeStart + per-tick vector decisions -----------------
        # (hoisted before the matrix phases — pure dataflow, and the
        # fused kernel consumes them).  The driver's introduction
        # branch does NOT check bFailed (only recvLoop and nodeLoop do,
        # Application.cpp:130,153), so a peer whose start tick falls
        # after its fail tick still sends its JOINREQ: the introducer
        # admits it, gossips its (forever-silent) entry, and everyone
        # removes it TREMOVE ticks later.  A churned peer's rejoin is
        # the same path (a fresh nodeStart).
        starting = (t == sched.start_tick) | rejoining
        joinreq_new = starting & ~intro_onehot           # JOINREQ send
        in_group = st_in_group | jrep
        in_group = in_group | (starting & intro_onehot)  # "Starting up group..."
        # nodeLoopOps gate: started, live, in-group (MP1Node.cpp:185-190;
        # in_group may have been set this very tick, MP1Node.cpp:182-190)
        ops = proc & in_group
        own_hb = st_own_hb + ops.astype(jnp.int32)       # MP1Node.cpp:337
        ops_rows = ops[row_ids]

        # ENsend drop injection (EmulNet.cpp:90-94); the asym world
        # swaps the uniform threshold for the per-link matrix inside
        # the same windowed draw
        gdrop_all, qdrop, pdrop = tick_drop_masks(
            state.rng, t, na, sched.drop_active[t], sched.drop_prob,
            link_prob=sched.link_prob[:na, :na] if asym else None)
        if lane_drop_window:
            # canonical fleets share a quantized superset window as
            # drop_active; mask the draw back to this lane's exact
            # window (scalar gate, so ticks outside it drop nothing —
            # exactly the solo run's all-False cond branch)
            lane_open = (t > sched.drop_open) & (t <= sched.drop_close)
            gdrop_all = gdrop_all & lane_open
            qdrop = qdrop & lane_open
            pdrop = pdrop & lane_open
        if na < n:
            # embed the active-corner stream; pairs outside the corner
            # never carry a send, so their mask bits are dead
            gdrop_all = jnp.zeros((n, n), bool).at[:na, :na].set(gdrop_all)
            qdrop = jnp.zeros((n,), bool).at[:na].set(qdrop)
            pdrop = jnp.zeros((n,), bool).at[:na].set(pdrop)
        if partition:
            # the partition world rides the drop plane: cross-group
            # sends are "dropped" at send time while the window is
            # open — a deterministic mask OR'd outside the drop cond,
            # so the windowed PRNG draw stays a real cond
            pa = sched.part_active_at(t)
            cross = sched.part_group[:, None] != sched.part_group[None, :]
            gdrop_all = gdrop_all | (pa & cross)
            qdrop = qdrop | (pa & cross[:, INTRODUCER])
            pdrop = pdrop | (pa & cross[INTRODUCER, :])
        gdrop = comm.slice_rows(gdrop_all)               # local sender rows
        joinreq_sent = joinreq_new & ~qdrop
        rep_out = jreq
        joinrep_sent = rep_out & ~pdrop
        live_hold = ~proc & ~failed

        if fused:
            # merge maxima by MXU level decomposition (via the comm's
            # merge dispatch, so fused and composable paths always run
            # the same merge), then one Pallas pass for membership
            # update + detection + dissemination
            # (ops/pallas/tickfused.py)
            from ..ops.pallas.tickfused import fused_tick_update
            m_all, m_fresh, t_fresh, _ = comm.merge_reduce(
                recv_from, st_known, st_hb, st_ts, t,
                t_remove=t_remove, block_size=block_size)
            known, hb, ts, gossip_next, gsent_row, added_m, removed_m = \
                fused_tick_update(
                    m_all, m_fresh, t_fresh, recv_from,
                    st_known, st_hb, st_ts, state.gossip, gdrop,
                    ops, jrep, jreq, live_hold, t, t_remove=t_remove,
                    with_events=with_events)
            joinreq_next = joinreq_sent | (state.joinreq
                                           & ~proc[INTRODUCER]
                                           & ~failed[INTRODUCER])
            joinrep_next = joinrep_sent | (state.joinrep & live_hold)
            rep_total = joinrep_sent.sum().astype(jnp.int32)
            req_total = jreq.sum().astype(jnp.int32)
            sent = gsent_row + joinreq_sent.astype(jnp.int32) \
                + jnp.where(is_intro_row, rep_total, 0)
            recv = recv_from.sum(1).astype(jnp.int32) \
                + jrep.astype(jnp.int32) \
                + jnp.where(is_intro_row, req_total, 0)
            zero_ev = jnp.zeros((), bool)
            events = TickEvents(
                added=added_m if with_events else zero_ev,
                removed=removed_m if with_events else zero_ev,
                sent=sent, recv=recv)
            new_state = WorldState(
                tick=t + 1, in_group=in_group, own_hb=own_hb,
                known=known, hb=hb, ts=ts, gossip=gossip_next,
                gossip_age=state.gossip_age,
                joinreq=joinreq_next, joinrep=joinrep_next, rng=state.rng)
            return new_state, events

        # ---- checkMessages: GOSSIP piggyback merge -----------------
        # (MP1Node.cpp:244-256; add path MP1Node.cpp:282-301)
        if byz:
            # Byzantine forgery plane (worlds.py): liar senders present
            # a FORGED view to the merge — their whole heartbeat row
            # boosted (the diagonal cell is the classic inflate-your-
            # own-counter attack), ghost/fake target entries
            # advertised, everything stamped at forged freshness t-1.
            # Only the transmitted planes are forged: the liar's true
            # local table, detection, and direct-credit behaviour are
            # untouched, and a targeted false accusation has no
            # transport at all — the strictly-larger-heartbeat merge
            # can only raise counters, never retract them.
            liar_rows = sched.byz_mask[row_ids]          # local sender rows
            tgt_rows = comm.slice_rows(sched.byz_target)
            f_known = st_known | tgt_rows
            f_hb = jnp.where(liar_rows[:, None],
                             st_hb + sched.byz_boost, st_hb)
            f_ts = jnp.where(liar_rows[:, None], t - 1, st_ts)
        else:
            f_known, f_hb, f_ts = st_known, st_hb, st_ts
        m_hb_all, m_hb_fresh, m_ts_fresh, any_fresh = comm.merge_reduce(
            recv_from, f_known, f_hb, f_ts, t,
            t_remove=t_remove, block_size=block_size)

        exists = st_known
        # merge into existing entries: adopt a strictly larger heartbeat
        # and refresh the timestamp (MP1Node.cpp:248-251)
        inc = exists & (m_hb_all > st_hb)
        hb = jnp.where(inc, m_hb_all, st_hb)
        if byz:
            # Defense: relayed counters are NOT liveness evidence once
            # forgery is in play — a merged-up heartbeat earns no
            # timestamp refresh; only direct-sender credit (below)
            # proves liveness.  In the dense full-view model every live
            # pair exchanges a direct message every tick, so honest
            # freshness never depends on the piggyback refresh and
            # detection horizons are unchanged.
            ts = st_ts
        else:
            ts = jnp.where(inc, t, st_ts)
        # add unknown entries if some contribution is fresh
        # (freshness gate at receive time, MP1Node.cpp:294); never self
        # (MP1Node.cpp:290-293).  The entry value mirrors "copy the
        # fresh entry, then later messages may merge it up, stamping
        # the local clock" under the canonical order.
        padd = ~exists & any_fresh & ~self_mask
        hb = jnp.where(padd, m_hb_all, hb)
        if byz:
            # forged adds start their staleness clock at arrival: an
            # entry no liar keeps re-advertising is purged within
            # t_remove + 1 ticks of its last advertisement
            ts = jnp.where(padd, t, ts)
        else:
            ts = jnp.where(padd, jnp.where(m_hb_all > m_hb_fresh, t, m_ts_fresh), ts)

        # ---- checkMessages: GOSSIP direct-sender handling ----------
        # A known sender's heartbeat is *incremented* locally (not
        # adopted) and its timestamp refreshed; an unknown sender is
        # added with heartbeat 1 (MP1Node.cpp:236-242, 265-280).
        # Zombie world: direct-sender credit models "a message from
        # you proves you are alive" — a zombie's message carries a
        # FROZEN heartbeat, which proves nothing, so senders that were
        # window-failed at the send tick (t-1) earn no credit and are
        # never added; their stale piggyback tables still merge by the
        # ordinary strictly-larger-heartbeat rule above.
        known_pb = exists | padd
        dcred = recv_from
        if zombie and latency:
            # with per-link delay the liveness claim is dated at the
            # message's TRUE send tick t - age (per link), not t - 1:
            # evaluate the fail window per (sender, receiver) cell on
            # the sender-major layout, then transpose into receiver rows
            sent_t = t - age1                            # [rows=s, r]
            zbad = (sent_t > sched.fail_tick[row_ids][:, None]) \
                & (sent_t <= sched.rejoin_tick[row_ids][:, None])
            dcred = dcred & ~comm.transpose(zbad)
        elif zombie:
            dcred = dcred & ~sched.window_failed_at(t - 1)[None, :]
        dinc = dcred & known_pb
        hb = jnp.where(dinc, hb + 1, hb)
        ts = jnp.where(dinc, t, ts)
        dadd = dcred & ~known_pb & ~self_mask
        hb = jnp.where(dadd, 1, hb)
        ts = jnp.where(dadd, t, ts)
        known = exists | padd | dadd

        # ---- checkMessages: JOINREQ at the introducer --------------
        # add the requester (dedup'd) and send back a JOINREP
        # (MP1Node.cpp:221-230)
        intro_row = comm.or_across(jnp.any(known & is_intro_row[:, None], 0))
        qadd = jreq & ~intro_row & ~intro_onehot         # [N], replicated
        q_cell = is_intro_row[:, None] & qadd[None, :]   # local cells to write
        known = known | q_cell
        hb = jnp.where(q_cell, 1, hb)
        ts = jnp.where(q_cell, t, ts)

        # ---- checkMessages: JOINREP at the joiner ------------------
        # add the introducer (dedup'd — usually already added via its
        # gossip, processed earlier in queue order) and enter the group
        # (MP1Node.cpp:231-233)
        radd_rows = jrep[row_ids] & ~known[:, INTRODUCER]
        r_cell = radd_rows[:, None] & intro_onehot[None, :]
        known = known | r_cell
        hb = jnp.where(r_cell, 1, hb)
        ts = jnp.where(r_cell, t, ts)

        known_after_adds = known

        # ---- nodeLoopOps: detection, dissemination -----------------
        stale = staleness_mask(ops_rows, known, ts, t, t_remove)
        known = known & ~stale

        # full-list gossip to every remaining member (MP1Node.cpp:350-361);
        # zombies keep sending their frozen tables (their rows merged
        # nothing and skipped detection above, so ``known`` is exactly
        # the table frozen at their fail tick)
        send_rows = ops_rows
        if zombie:
            # in_group is frozen for a failed peer (only a rejoin wipe
            # clears it), so this is "was in the group when it failed"
            # — a peer that failed before ever joining stays silent,
            # like the reference's in-group-gated gossip loop
            send_rows = send_rows \
                | (sched.window_failed_at(t) & in_group)[row_ids]
        send = send_rows[:, None] & known
        gossip_sent = send & ~gdrop

        # unconsumed traffic stays in flight (the EmulNet buffer holds
        # messages until the receiver's next recvLoop) — except traffic
        # to failed receivers, which in the reference rots in the buffer
        # forever (failed nodes never call recvLoop again,
        # Application.cpp:130, MP1Node.cpp:42-44) and is dropped here.
        if latency:
            # at most one message in flight per link: a busy link
            # (held traffic) skips this tick's send entirely, so the
            # effective gossip cadence on a lat-tick link is one
            # message every lat ticks.  Payloads are delivery-delayed
            # but content-current (the bool plane carries "a message is
            # in flight"; its payload is the sender's row at delivery-
            # check time) — the age plane exists to date delivery and
            # zombie credit, not to freeze content.
            gossip_sent = gossip_sent & ~held
            gossip_next = gossip_sent | held
            gossip_age = jnp.where(held, age1, 0)
        else:
            gossip_next = gossip_sent | (state.gossip & live_hold[None, :])
            gossip_age = state.gossip_age

        joinreq_next = joinreq_sent | (state.joinreq
                                       & ~proc[INTRODUCER] & ~failed[INTRODUCER])
        joinrep_next = joinrep_sent | (state.joinrep & live_hold)

        # ---- accounting (EmulNet.cpp:111,172) ----------------------
        # row-local (each device counts for its own peers; logically [N])
        rep_total = joinrep_sent.sum().astype(jnp.int32)
        req_total = jreq.sum().astype(jnp.int32)
        sent = gossip_sent.sum(1).astype(jnp.int32) \
            + joinreq_sent[row_ids].astype(jnp.int32) \
            + jnp.where(is_intro_row, rep_total, 0)
        recv = recv_from.sum(1).astype(jnp.int32) \
            + jrep[row_ids].astype(jnp.int32) \
            + jnp.where(is_intro_row, req_total, 0)

        events = TickEvents(
            added=known_after_adds & ~exists,
            removed=stale,
            sent=sent,
            recv=recv,
        )
        new_state = WorldState(
            tick=t + 1,
            in_group=in_group,
            own_hb=own_hb,
            known=known,
            hb=hb,
            ts=ts,
            gossip=gossip_next,
            gossip_age=gossip_age,
            joinreq=joinreq_next,
            joinrep=joinrep_next,
            rng=state.rng,
        )
        return new_state, events

    return tick


#: Compiled whole-run functions, shared across Simulation instances.
#: Everything config-dependent that isn't in the cache key flows in
#: through the Schedule arrays, so reuse is sound.
_RUN_CACHE: dict = {}

#: how many run functions have been BUILT (cache misses).  A second
#: ``Simulation.run_bench(seed=...)`` must not move this counter — the
#: cache key is config shape only, seeds flow through the Schedule
#: arrays (regression: tests/test_fleet.py::test_run_bench_no_rebuild).
_BUILD_COUNT = 0


def run_build_count() -> int:
    """Number of whole-run functions built so far (cache misses).

    Counts every process-wide whole-run construction: :func:`make_run`
    misses here, fleet-program misses in core/fleet.py and
    models/overlay.make_overlay_fleet_run (via :func:`note_build`).
    The serving layer (service/) keys its compiled-program cache on
    the same shape signatures, so "a 20-request mixed trace builds at
    most once per distinct bucket key" is a delta on this counter
    (tests/test_service.py)."""
    return _BUILD_COUNT


def note_build() -> None:
    """Record a whole-run build performed outside :func:`make_run`.

    Called by the fleet-program caches (core/fleet.py,
    models/overlay.py) on a cache miss so :func:`run_build_count`
    stays the single process-wide build odometer."""
    global _BUILD_COUNT
    _BUILD_COUNT += 1


def make_run(cfg: SimConfig, block_size: int = 128, with_events: bool = True,
             use_pallas: bool | None = None):
    """Whole-run function: ``lax.scan`` of the tick over all T ticks.

    Returns a jitted ``run(state, sched) -> (final_state, stacked_events)``.
    With ``with_events=False`` only the send/recv counters are stacked
    (benchmark mode — avoids materializing T*(N,N) masks).
    """
    global _BUILD_COUNT
    comm = LocalComm(use_pallas)
    from ..models.segments import plan_signature
    from .dense_corner import active_bound, make_corner_run
    from .dense_mega import dense_mega_supported, make_dense_mega_run
    mega = comm.use_pallas and dense_mega_supported(cfg, with_events)
    a = active_bound(cfg)
    # corner precedence over full-width mega is deliberate: the corner
    # saves (N/A)^3 of the work and rides the megakernel internally
    # whenever the corner width fits its envelope
    corner = (not with_events) and 0 < a < cfg.n
    # the segment-plan signature (closed-form phase windows) is part of
    # the key so a config edit that only moves a phase boundary — a
    # shifted drop window, a later fail tick — can never be served a
    # compiled run built for the old boundaries.  Today every dense
    # path reads those boundaries from the Schedule arrays (data, not
    # code), so the extra key bits cost at most a redundant build; any
    # future path that bakes a window statically (the overlay grid
    # kernel already does) is covered by construction.
    key = (cfg.n, cfg.t_remove, cfg.total_ticks, block_size, with_events,
           comm.use_pallas, mega, cfg.rejoin_after is not None,
           a if corner else cfg.n, plan_signature(cfg),
           # the adversarial worlds are static branches in the tick
           # (zombie/asym/partition/flap), so they are program identity
           cfg.worlds_key())
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]
    _BUILD_COUNT += 1
    if corner:
        # bench mode at a config whose schedule never starts peers
        # >= A: run on the static active corner (dense_corner.py) —
        # (N/A)^3 less matmul work.  Bit-identical to a full-width run
        # consuming the same width-A drop stream (tests pin this via
        # make_tick(n_active=A)); the full-width paths below draw at
        # width N, so for a drop config with A < N the corner is a
        # different — equally seeded — realization of the same
        # Bernoulli process.  See dense_corner.py for why the corner
        # cannot be chunked: A is derived from the whole-run horizon.
        run = make_corner_run(cfg, a, block_size, use_pallas=use_pallas)
        _RUN_CACHE[key] = run
        return run
    if mega:
        # TPU: DENSE_MEGA_TICKS whole ticks per Pallas launch, state
        # resident in VMEM — bit-identical to the per-tick path
        # (tests/test_dense_mega.py).  Trace mode emits the
        # added/removed masks from the kernel itself, so the graded
        # run clears the same per-launch floor as the bench run.
        run = make_dense_mega_run(cfg, with_events=with_events)
        _RUN_CACHE[key] = run
        return run
    # NOTE: this path deliberately draws the drop stream at full width
    # even when active_bound < N — Simulation.run() compiles it for
    # chunk lengths (cfg.total_ticks is a CHUNK here, not the run
    # horizon), so a chunk-derived active bound would be wrong for
    # later chunks' absolute ticks.  Width-A streams belong to the
    # corner path alone, which always spans the whole run.
    tick = make_tick(cfg, block_size, comm=comm, with_events=with_events)

    @jax.jit
    def run(state: WorldState, sched: Schedule):
        def step(carry, _):
            carry, ev = tick(carry, sched)
            if not with_events:
                ev = TickEvents(added=jnp.zeros((), bool),
                                removed=jnp.zeros((), bool),
                                sent=ev.sent, recv=ev.recv)
            return carry, ev
        return jax.lax.scan(step, state, None, length=cfg.total_ticks)

    _RUN_CACHE[key] = run
    return run
